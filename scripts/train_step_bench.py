"""Step-time decomposition bench: compute / exposed-comm / bubble, A/B'd.

Measures the training step's communication exposure with the ZeRO overlap
on vs off (``parallel/overlap.py``) and writes ``BENCH_step.json``:

- **overlap A/B**: the serial-placement step (one monolithic param gather,
  one post-backward scatter sweep) vs the bucketed in-scan placement, same
  math — gradients verified BITWISE between the arms in-process before any
  timing is trusted (``parity.bitwise``);
- **decomposition**: ``exposed_comm_ms = step_ms - compute_ms`` against a
  single-device run doing the same PER-DEVICE work (identical local batch,
  no collectives). On this repo's 2-core CPU container the 8 virtual
  devices oversubscribe the cores, which inflates both arms' "comm" share
  identically — the off/on RATIO keeps meaning there while the absolute
  fractions do not transfer (same honesty discipline as
  BENCH_ckpt_integrity.json);
- **projection**: where the bench runs off-TPU, an assumption-labeled
  model of the north-star config on v5e ICI (bytes/bandwidth vs
  FLOPs/peak, per layer): serial placement exposes the FULL gather+scatter
  time; overlapped placement exposes only the first gather, the last
  scatter, and any per-layer comm that outruns per-layer compute. The
  assumptions ride in the artifact so the number can be re-derived;
- **bubble**: the analytic ``pipeline.bubble_fraction`` table for
  gpipe/1f1b/interleaved at representative (P, M, V), plus a MEASURED tiny
  pipe run when the backend can execute the pipe engine (this image's jax
  0.4.37 cannot — the error is recorded verbatim rather than hidden);
- **attention microbench** (ROADMAP 5(a) satellite): per-op flash-vs-XLA
  fwd+bwd timings — the Pallas kernel is TPU-only, so on CPU the flash
  column records why it did not run instead of a fake number.

NOTE on platform: this image pre-imports jax, so JAX_PLATFORMS in the
environment is ignored (see bench.py) — the script pins the backend via
``jax.config`` from BENCH_PLATFORM (default cpu). On a TPU box run
``BENCH_PLATFORM=tpu python scripts/train_step_bench.py``.

Usage: python scripts/train_step_bench.py [--out BENCH_step.json]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time
from datetime import datetime, timezone
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

# must precede backend init: the CPU arm needs an 8-device virtual mesh
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)

import jax  # noqa: E402

jax.config.update("jax_platforms", os.environ.get("BENCH_PLATFORM", "cpu"))

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

# north-star projection assumptions (stated, not hidden — the projection is
# only as honest as these numbers, so they ride in the artifact)
V5E_ICI_GBPS = 400.0  # aggregate per-chip ICI bandwidth, GB/s
V5E_PEAK_FLOPS = 197e12
ASSUMED_MFU = 0.5  # matmul efficiency during the compute the comm hides under


def _bench_model():
    from zero_transformer_tpu.config import ModelConfig

    # mid-sized: big enough that a step is tens of ms on this box and the
    # per-layer buckets are real (8 layers), small enough to compile fast
    return ModelConfig(
        name="stepbench", vocab_size=1024, d_model=128, n_heads=4, n_layers=8,
        max_seq_len=128, dropout=0.0, compute_dtype="float32",
    )


def _timed_steps(step, state, batch, rng, reps: int, inner: int):
    """(best mean ms/step over ``reps`` windows of ``inner`` steps, state).
    Sync via a scalar fetch (see bench.py: block_until_ready is not a
    reliable barrier on every backend in this image)."""
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        for _ in range(inner):
            state, metrics = step(state, batch, rng)
        float(metrics["loss"])
        best = min(best, (time.perf_counter() - t0) / inner * 1e3)
    return best, state


def measure_overlap_ab(args) -> dict:
    from zero_transformer_tpu.config import MeshConfig, OptimizerConfig
    from zero_transformer_tpu.models import Transformer
    from zero_transformer_tpu.parallel.mesh import make_mesh
    from zero_transformer_tpu.parallel.zero import (
        init_train_state, make_plan, make_train_step,
    )
    from zero_transformer_tpu.training.optimizer import make_optimizer, make_schedule

    cfg = _bench_model()
    opt = OptimizerConfig(warmup_steps=10, total_steps=1000)
    mesh = make_mesh(MeshConfig(zero_stage=args.zero_stage))
    n_dev = jax.device_count()
    model = Transformer(cfg)
    tx = make_optimizer(opt)
    B, T, accum = args.batch, args.seq, args.accum
    plan = make_plan(model, tx, mesh, (B, T), args.zero_stage)
    batch = jax.random.randint(
        jax.random.PRNGKey(1), (accum, B, T), 0, cfg.vocab_size, jnp.int32
    )
    rng = jax.random.PRNGKey(2)

    def build(overlap):
        return make_train_step(
            model, tx, mesh, plan, args.zero_stage, make_schedule(opt),
            tx_factory=lambda nf, zc=None: make_optimizer(
                opt, make_schedule(opt), nf, zero_collectives=zc
            ),
            overlap_comm=overlap,
        )

    def fresh():
        return init_train_state(
            model, tx, jax.random.PRNGKey(0), mesh, (B, T), plan
        )

    # ---- bitwise parity first: a fast wrong step must not win the A/B
    s_on, s_off = fresh(), fresh()
    step_on, step_off = build(True), build(False)
    for i in range(2):
        s_on, m_on = step_on(s_on, batch, rng)
        s_off, m_off = step_off(s_off, batch, rng)
    bitwise = float(m_on["loss"]) == float(m_off["loss"]) and all(
        np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(jax.tree.leaves(s_on.params), jax.tree.leaves(s_off.params))
    )

    arms = {}
    for name, step in (("overlap_off", step_off), ("overlap_on", step_on)):
        state = fresh()
        state, metrics = step(state, batch, rng)  # compile + warm
        float(metrics["loss"])
        ms, state = _timed_steps(step, state, batch, rng, args.reps, args.steps)
        arms[name] = {"step_ms": round(ms, 3)}

    # ---- compute baseline: 1 device, SAME per-device work, no collectives
    mesh1 = make_mesh(MeshConfig(data=1), devices=jax.devices()[:1])
    local_B = max(B // n_dev, 1)
    plan1 = make_plan(model, tx, mesh1, (local_B, T), 1)
    step1 = make_train_step(model, tx, mesh1, plan1, 1, make_schedule(opt))
    state1 = init_train_state(
        model, tx, jax.random.PRNGKey(0), mesh1, (local_B, T), plan1
    )
    batch1 = batch[:, :local_B]
    state1, m1 = step1(state1, batch1, rng)
    float(m1["loss"])
    compute_ms, _ = _timed_steps(step1, state1, batch1, rng, args.reps, args.steps)

    for arm in arms.values():
        exposed = max(0.0, arm["step_ms"] - compute_ms)
        arm["exposed_comm_ms"] = round(exposed, 3)
        arm["exposed_comm_frac"] = round(exposed / arm["step_ms"], 4)

    off, on = arms["overlap_off"], arms["overlap_on"]
    measured_reduction = (
        round(off["exposed_comm_ms"] / on["exposed_comm_ms"], 2)
        if on["exposed_comm_ms"] > 0
        else None
    )
    return {
        "mesh": {"data": n_dev},
        "zero_stage": args.zero_stage,
        "accum": accum,
        "batch": B,
        "seq": T,
        "model_dims": {
            "d_model": cfg.d_model, "n_layers": cfg.n_layers,
            "vocab": cfg.vocab_size,
        },
        "overlap_off": off,
        "overlap_on": on,
        "single_device_compute_ms": round(compute_ms, 3),
        "measured_reduction": measured_reduction,
        "parity": {"bitwise": bool(bitwise), "steps": 2},
    }


def projection_v5e_north_star() -> dict:
    """Assumption-labeled exposed-comm projection for the 1.3B north-star
    config on one v5e ICI domain of 8 chips, ZeRO stage 3 (FSDP), serial
    vs overlapped placement. Every input is a field so the arithmetic can
    be audited from the artifact alone."""
    from zero_transformer_tpu.config import model_config

    cfg = model_config("1_3b")
    n_dev = 8
    tokens_per_step = 64 * 1024  # the 64k-tokens/step bench discipline
    embed = cfg.vocab_size * cfg.d_model
    layer_params = (cfg.num_params - embed) / cfg.n_layers
    bytes_per_param = 4  # f32 master params (what the ZeRO step moves)

    # ring all-gather of one layer's params across 8 chips: each chip
    # receives (N-1)/N of the full layer
    layer_bytes = layer_params * bytes_per_param
    t_gather_layer = layer_bytes * (n_dev - 1) / n_dev / (V5E_ICI_GBPS * 1e9)
    t_scatter_layer = t_gather_layer  # reduce-scatter moves the same bytes
    t_compute_layer = (
        6.0 * layer_params * tokens_per_step / (V5E_PEAK_FLOPS * ASSUMED_MFU)
    ) / n_dev

    L = cfg.n_layers
    serial_exposed = L * (t_gather_layer + t_scatter_layer)
    # overlapped: the first gather and the last scatter have no compute to
    # hide under; every other per-layer collective overlaps its neighbor
    # layer's compute and is exposed only past that compute's duration
    per_layer_exposed = max(0.0, t_gather_layer - t_compute_layer) + max(
        0.0, t_scatter_layer - t_compute_layer
    )
    overlap_exposed = t_gather_layer + t_scatter_layer + (L - 1) * per_layer_exposed
    step_compute = L * t_compute_layer
    return {
        "platform": "tpu_v5e_projected",
        "model": "1_3b",
        "n_devices": n_dev,
        "tokens_per_step": tokens_per_step,
        "assumptions": {
            "ici_gbps": V5E_ICI_GBPS,
            "peak_flops": V5E_PEAK_FLOPS,
            "mfu_during_overlap": ASSUMED_MFU,
            "bytes_per_param": bytes_per_param,
        },
        "per_layer_ms": {
            "gather": round(t_gather_layer * 1e3, 3),
            "scatter": round(t_scatter_layer * 1e3, 3),
            "compute": round(t_compute_layer * 1e3, 3),
        },
        "serial_exposed_comm_frac": round(
            serial_exposed / (step_compute + serial_exposed), 4
        ),
        "overlap_exposed_comm_frac": round(
            overlap_exposed / (step_compute + overlap_exposed), 4
        ),
        "reduction": round(serial_exposed / max(overlap_exposed, 1e-12), 1),
        "method": (
            "ring-collective bytes/bandwidth vs per-layer matmul FLOPs/peak; "
            "serial placement exposes all L gathers + L scatters, overlapped "
            "placement exposes the first gather, the last scatter, and any "
            "per-layer comm exceeding one layer's compute"
        ),
    }


def bubble_table(args) -> dict:
    from zero_transformer_tpu.parallel.pipeline import bubble_fraction

    analytic = []
    for sched, P_, M, V in (
        ("gpipe", 4, 16, 1),
        ("1f1b", 4, 16, 1),
        ("interleaved", 4, 16, 2),
        ("interleaved", 4, 16, 4),
        ("gpipe", 8, 16, 1),
        ("interleaved", 8, 16, 2),
        ("interleaved", 8, 16, 4),
    ):
        analytic.append({
            "pp_schedule": sched, "pipe": P_, "micro": M, "interleave": V,
            "bubble_frac": round(bubble_fraction(sched, P_, M, V), 4),
        })

    measured = {}
    for sched, V in (("gpipe", 1), ("interleaved", 2)):
        try:
            measured[sched] = _measure_pipe(sched, V, args)
        except Exception as e:  # noqa: BLE001 — record, never hide
            measured[sched] = {
                "error": f"{type(e).__name__}: {str(e)[:300]}"
            }
    return {"analytic": analytic, "measured": measured}


def _measure_pipe(sched: str, interleave: int, args) -> dict:
    from zero_transformer_tpu.config import MeshConfig, ModelConfig, OptimizerConfig
    from zero_transformer_tpu.models import Transformer
    from zero_transformer_tpu.parallel.mesh import make_mesh
    from zero_transformer_tpu.parallel.zero import (
        init_train_state, make_plan, make_train_step,
    )
    from zero_transformer_tpu.training.optimizer import make_optimizer, make_schedule

    cfg = ModelConfig(
        name="ppbench", vocab_size=512, d_model=64, n_heads=4, n_layers=4,
        max_seq_len=64, dropout=0.0, compute_dtype="float32",
    )
    opt = OptimizerConfig(warmup_steps=10, total_steps=1000)
    mesh = make_mesh(MeshConfig(pipe=2, data=jax.device_count() // 2))
    model = Transformer(cfg)
    tx = make_optimizer(opt)
    plan = make_plan(model, tx, mesh, (4, 32), 1, pp_schedule=sched)
    state = init_train_state(model, tx, jax.random.PRNGKey(0), mesh, (4, 32), plan)
    step = make_train_step(
        model, tx, mesh, plan, 1, make_schedule(opt), pp_schedule=sched,
        pp_interleave=interleave,
    )
    batch = jax.random.randint(
        jax.random.PRNGKey(1), (4, 4, 32), 0, cfg.vocab_size, jnp.int32
    )
    rng = jax.random.PRNGKey(2)
    state, metrics = step(state, batch, rng)
    float(metrics["loss"])
    ms, _ = _timed_steps(step, state, batch, rng, args.reps, args.steps)
    return {"step_ms": round(ms, 3), "pipe": 2, "micro": 4,
            "interleave": interleave}


def attention_interpret_parity() -> dict:
    """Interpret-mode numerics parity (PR 11): the Pallas kernels run as
    jax ops on THIS box (no TPU needed) and are pinned against the XLA
    reference — the correctness half of the per-op A/B that used to be
    recorded only as a why-absent reason off-TPU. ONE shared
    implementation (``ops.pallas.parity``) with bench.py's flash child, so
    the two artifacts can never assert different parity contracts. Timed
    numbers stay TPU-only; these are parity evidence with honest
    provenance."""
    from zero_transformer_tpu.ops.pallas.parity import interpret_parity_report

    return interpret_parity_report()


def mfu_projection_v5e() -> dict:
    """Assumption-labeled v5e MFU projection for flash-by-default on the
    1.3B north-star config. Baseline: the MEASURED 0.528 MFU
    (BENCH_measured.json, on-chip). The XLA attention materializes the
    [B, H, T, T] f32 score/weight tensors and round-trips them through HBM
    several times per layer per step (write scores, softmax read+write,
    out-matmul read, and the mirror passes in backward); the flash kernel
    keeps that traffic in VMEM. The projection removes exactly that HBM
    time from the measured step and re-derives MFU — every input is a
    field so the arithmetic can be audited from the artifact alone."""
    from zero_transformer_tpu.config import model_config

    cfg = model_config("1_3b")
    measured_mfu = 0.5281  # BENCH_measured.json (1_3b, on-chip v5e)
    n_chips = 8
    tokens_per_step = 64 * 1024
    hbm_gbps = 819.0  # v5e HBM bandwidth per chip
    score_passes = 6  # fwd: write + softmax rw + read; bwd: mirror passes
    T = cfg.max_seq_len
    B_chip = tokens_per_step // T // n_chips
    n_params = cfg.num_params
    useful_flops = 6.0 * n_params * tokens_per_step
    step_s = useful_flops / (n_chips * V5E_PEAK_FLOPS * measured_mfu)
    score_bytes_chip = (
        B_chip * cfg.n_heads * T * T * 4 * score_passes * cfg.n_layers
    )
    saved_s = score_bytes_chip / (hbm_gbps * 1e9)
    projected = measured_mfu * step_s / max(step_s - saved_s, 1e-9)
    return {
        "platform": "tpu_v5e_projected",
        "model": "1_3b",
        "baseline_mfu_measured": measured_mfu,
        "assumptions": {
            "n_chips": n_chips,
            "tokens_per_step": tokens_per_step,
            "peak_flops": V5E_PEAK_FLOPS,
            "hbm_gbps": hbm_gbps,
            "score_hbm_passes": score_passes,
            "n_params": int(n_params),
        },
        "step_s_at_measured_mfu": round(step_s, 4),
        "score_traffic_s_per_step": round(saved_s, 4),
        "projected_mfu": round(projected, 4),
        "target": 0.60,
        "method": (
            "remove the XLA path's [B,H,T,T] f32 score/weight HBM round "
            "trips (bytes/bandwidth) from the measured step time and "
            "re-derive MFU = useful_flops / (peak * new_step_time); "
            "flash keeps those tensors blockwise in VMEM"
        ),
    }


def attention_microbench(args) -> dict:
    """Per-op flash-vs-XLA attention, fwd+bwd (ROADMAP 5(a)): the kernel is
    Pallas/TPU — off TPU the flash column says WHY it is absent (timed
    numbers must be on-chip) while ``interpret_parity`` carries the
    correctness half on any box."""
    from zero_transformer_tpu.ops import flash_attention as fa
    from zero_transformer_tpu.ops.attention import xla_attention

    points = []
    for B, T in ((4, 128), (2, 256)):
        H, D = 4, 64
        q, k, v = (
            jax.random.normal(jax.random.PRNGKey(i), (B, T, H, D), jnp.float32)
            for i in range(3)
        )

        def bench(fn):
            lossf = lambda q, k, v: jnp.sum(fn(q, k, v).astype(jnp.float32))
            step = jax.jit(jax.grad(lossf, argnums=(0, 1, 2)))
            out = step(q, k, v)
            float(jnp.sum(out[0]))
            t0 = time.perf_counter()
            for _ in range(args.reps * 2):
                out = step(q, k, v)
            float(jnp.sum(out[0]))
            return (time.perf_counter() - t0) / (args.reps * 2) * 1e3

        xla_ms = bench(
            lambda q, k, v: xla_attention(q, k, v, causal=True, alibi=True)
        )
        point = {"shape": [B, T, H, D], "xla_ms": round(xla_ms, 3)}
        if fa.supported(q, k, v, causal=True, alibi=True):
            flash_ms = bench(
                lambda q, k, v: fa.flash_attention(q, k, v, causal=True, alibi=True)
            )
            point["flash_ms"] = round(flash_ms, 3)
            point["speedup"] = round(xla_ms / flash_ms, 2)
        else:
            point["flash_unsupported_reason"] = (
                f"pallas TPU kernel; backend={jax.default_backend()}"
            )
        points.append(point)
    return {
        "points": points,
        "impl_default": (
            "auto (flash + paged kernels on TPU or under "
            "ZT_PALLAS_INTERPRET=1; xla elsewhere)"
        ),
        "interpret_parity": attention_interpret_parity(),
    }


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--out", default="BENCH_step.json")
    p.add_argument("--zero-stage", type=int, default=2)
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--seq", type=int, default=128)
    p.add_argument("--accum", type=int, default=2)
    p.add_argument("--reps", type=int, default=3)
    p.add_argument("--steps", type=int, default=4, help="steps per timing window")
    args = p.parse_args()

    ab = measure_overlap_ab(args)
    platform = jax.default_backend()
    # always computed: on TPU it is the fallback headline when the
    # overlapped arm's exposed comm measures 0 (measured_reduction None —
    # "fully hidden" has no finite ratio), and off-TPU it IS the headline
    projection = projection_v5e_north_star()

    # headline value: the exposed-comm reduction — measured on TPU, the
    # labeled projection elsewhere (a 2-core CPU's collective "time" is
    # memcpy + core oversubscription and does not transfer)
    if platform == "tpu" and ab["measured_reduction"]:
        value, provenance = ab["measured_reduction"], "measured"
    else:
        value, provenance = projection["reduction"], "projected_v5e"

    artifact = {
        "metric": "train_step_exposed_comm_reduction",
        "value": value,
        "unit": "x",
        "provenance": provenance,
        "platform": platform,
        "device_kind": jax.devices()[0].device_kind,
        **ab,
        "projection": projection,
        "mfu_projection": mfu_projection_v5e(),
        "bubble": bubble_table(args),
        "attention_microbench": attention_microbench(args),
        "note": (
            "CPU-box caveat: the 8 'devices' are host threads on 2 shared "
            "cores, so the measured exposed-comm fractions are dominated by "
            "core oversubscription and do NOT transfer to TPU; the off/on "
            "arms share that inflation, and the bitwise parity + projection "
            "carry the honest claim (same methodology as "
            "BENCH_ckpt_integrity.json)"
        ) if platform != "tpu" else "measured on-chip",
        "best_of": args.reps,
        "measured_at_utc": datetime.now(timezone.utc).strftime(
            "%Y-%m-%dT%H:%M:%SZ"
        ),
    }
    Path(args.out).write_text(json.dumps(artifact) + "\n")
    print(json.dumps(artifact))


if __name__ == "__main__":
    main()
