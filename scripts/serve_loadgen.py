#!/usr/bin/env python
"""Load generator for the continuous-batching serving engine.

Drives ``serving.ServingEngine`` directly (no HTTP hop — this measures the
scheduler + fused decode step, not socket overhead) in either mode:

- **closed-loop** (default): N concurrent clients, each submitting its next
  request the moment the previous one finishes — the saturation measurement;
- **open-loop**: requests arrive at a fixed ``--rate`` regardless of
  completions — the latency-under-load measurement (closed-loop hides
  queueing delay by self-throttling).

Workloads: the default mix varies prompt lengths across prefill buckets;
``--shared-prefix`` instead models N personas behind one common system
prompt (the prefix spans >= 2 prefill chunks), so the engine's chunk-aligned
prefix cache gets real hits and the artifact can attribute TTFT to hit vs
miss admissions. Chunked prefill is ON by default (``--prefill-chunk``;
0 restores the legacy one-shot prefill) and the artifact splits ITL into
all-ticks vs pure-decode ticks (``itl_ms`` vs ``itl_ms_decode_only``) so
prefill interference is measurable, not inferred.

Every request's token stream is checked byte-for-byte against single-request
``generate()`` with the same seed (``--no-verify`` to skip): the engine's
request-isolation invariant, measured under real contention. The run emits a
``BENCH_serve.json`` artifact (one JSON doc, also printed as the final
stdout line) with TTFT/ITL percentiles, tokens/s, and occupancy evidence,
plus a Perfetto span-trace artifact (``<out>.trace.json`` — the measured
engine's request lifecycle trees and per-tick phase timeline). ``--obs-ab``
additionally measures span-tracing overhead (tracing OFF vs ON, best-of-N
per arm) into the ``obs_overhead`` field, which the bench guard holds to
<= 2% on decode tok/s.

CPU-runnable end to end with the ``test`` zoo model and random-init params —
the orchestration layer is what is being measured, so no checkpoint needed:

    JAX_PLATFORMS=cpu python scripts/serve_loadgen.py --requests 8 --slots 2
"""
from __future__ import annotations

import argparse
import json
import random
import sys
import threading
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))


def parse_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--model", default="test", help="model zoo name")
    p.add_argument("--slots", type=int, default=2)
    p.add_argument("--requests", type=int, default=8)
    p.add_argument("--concurrency", type=int, default=8,
                   help="closed-loop client count (capped at --requests)")
    p.add_argument("--mode", choices=("closed", "open"), default="closed")
    p.add_argument("--rate", type=float, default=16.0,
                   help="open-loop arrival rate, requests/s")
    p.add_argument("--max-new-tokens", type=int, default=16)
    p.add_argument("--cache-len", type=int, default=None)
    p.add_argument("--prefill-chunk", type=int, default=8,
                   help="chunked-prefill budget (tokens per tick) for the "
                        "measured engine; 0 = legacy one-shot prefill")
    p.add_argument("--prefix-cache", type=int, default=64, metavar="CHUNKS",
                   help="prefix-cache capacity in chunk entries (0 = off; "
                        "forced off when --prefill-chunk is 0)")
    p.add_argument("--shared-prefix", action="store_true",
                   help="shared-prefix workload: every request = one common "
                        "system prompt (>= 2 chunks long) + a short persona "
                        "tail, so prefix-cache hits and the TTFT hit/miss "
                        "split are measured on realistic traffic")
    p.add_argument("--kv-layout", choices=("slab", "paged"), default="paged",
                   help="KV cache layout for the measured engine (paged = "
                        "block-table page pool, the serving default)")
    p.add_argument("--page-size", type=int, default=4,
                   help="tokens per KV page (paged); must divide "
                        "--prefill-chunk")
    p.add_argument("--page-pool-tokens", type=int, default=0,
                   help="page-pool capacity in tokens (0 = slab-equivalent "
                        "slots x cache_len)")
    p.add_argument("--spec-k", type=int, default=0,
                   help="speculative serving draft length (0 = off). The "
                        "run also drives a spec-OFF control engine first "
                        "and embeds it as no_speculation for the A/B")
    p.add_argument("--greedy", action="store_true",
                   help="greedy sampling: with --spec-k the engine output "
                        "is bit-identical to plain decode, so the parity "
                        "verification stays byte-exact")
    p.add_argument("--fused-tail-ab", action="store_true",
                   help="also drive a DEFUSED-tail control engine "
                        "(sampling as its own dispatch after the forward, "
                        "fused_tail=False, speculation off) and embed it "
                        "as no_fused_tail — the fused-vs-split sampling "
                        "A/B the kernel lane prices")
    p.add_argument("--no-fused-tail", action="store_true",
                   help="run the MEASURED engine with the defused tail "
                        "(A/B control; byte-identical output, disables "
                        "--spec-k)")
    p.add_argument("--capacity-sweep", action="store_true",
                   help="capacity mode: ramp concurrent streams at mixed "
                        "prompt lengths against a slab engine and a paged "
                        "engine at EQUAL KV memory budget, and emit "
                        "BENCH_serve_capacity.json (slab-vs-paged "
                        "concurrent-stream A/B) instead of the standard "
                        "artifact")
    p.add_argument("--capacity-streams", type=int, default=24,
                   help="streams offered during --capacity-sweep")
    p.add_argument("--capacity-slots", type=int, default=16,
                   help="decode slots for the PAGED engine in the sweep "
                        "(its concurrency ceiling; the slab engine's slot "
                        "count is fixed by the memory budget)")
    p.add_argument("--long-prompt-flood", action="store_true",
                   help="disaggregation A/B (-> BENCH_disagg.json): a "
                        "long-prompt flood against a MIXED 2-replica fleet "
                        "vs a PREFILL+DECODE disaggregated fleet (real "
                        "engines behind the real router); records flood "
                        "TTFT and the background streams' decode-only ITL "
                        "per arm, plus the no-flood ITL baseline")
    p.add_argument("--sawtooth", action="store_true",
                   help="autoscale tracking segment (-> BENCH_disagg.json): "
                        "a sawtooth load against a stub fleet with the "
                        "router's autoscaler spawning/retiring replicas; "
                        "proof is tracking with dropped_streams == 0")
    p.add_argument("--flood-background", type=int, default=2,
                   help="decode-heavy background streams per flood arm")
    p.add_argument("--flood-requests", type=int, default=3,
                   help="long-prompt flood arrivals per arm")
    p.add_argument("--tenant-flood", action="store_true",
                   help="tenant-isolation A/B (-> BENCH_tenant.json): a "
                        "gold tenant's steady trickle alone vs the same "
                        "trickle while a hostile tenant floods the 2-replica "
                        "QoS fleet with batch work; proof is the gold p99 "
                        "ratio within --tenant-isolation-factor, zero "
                        "dropped streams, and every flood rejection "
                        "retryable with a Retry-After")
    p.add_argument("--tenant-gold-requests", type=int, default=8,
                   help="gold trickle length per tenant-flood arm")
    p.add_argument("--tenant-flood-clients", type=int, default=4,
                   help="hostile batch-tenant client threads")
    p.add_argument("--tenant-batch-rate", type=float, default=20.0,
                   help="batch-class token-bucket refill rate (tokens/s) "
                        "for the tenant-flood fleet")
    p.add_argument("--tenant-batch-burst", type=float, default=40.0,
                   help="batch-class token-bucket burst for the "
                        "tenant-flood fleet")
    p.add_argument("--tenant-isolation-factor", type=float, default=5.0,
                   help="max allowed gold e2e-p99 ratio, flood arm vs "
                        "baseline arm (CPU-noise headroom included)")
    p.add_argument("--router", action="store_true",
                   help="fleet-router mode: spawn N in-process PACED stub "
                        "replicas (fixed inter-token interval — models "
                        "device-bound decode whose rate does not depend on "
                        "this box's CPU) behind a real RouterServer and "
                        "measure what the ROUTER contributes: aggregate "
                        "relayed tok/s scaling replicas 1 -> N, prefix-"
                        "affinity hit rate, mid-stream failover, and a "
                        "rolling fleet reload with dropped_streams == 0. "
                        "Emits BENCH_router.json instead of the standard "
                        "artifact")
    p.add_argument("--router-replicas", type=int, default=4,
                   help="largest fleet size in the scaling sweep (the sweep "
                        "runs 1, 2, ... doubling up to this)")
    p.add_argument("--router-clients", type=int, default=0,
                   help="closed-loop client count (0 = replica slots x the "
                        "largest fleet, so the biggest fleet is exactly "
                        "saturated and smaller ones queue)")
    p.add_argument("--router-requests", type=int, default=3,
                   help="requests per client per sweep point (each client "
                        "reuses its own chunk-aligned prefix, so request "
                        "2..N of a client should ride prefix affinity)")
    p.add_argument("--router-max-new", type=int, default=48,
                   help="tokens generated per router-mode request")
    p.add_argument("--router-itl-ms", type=float, default=10.0,
                   help="stub replica inter-token interval (the paced "
                        "'device' speed the router must keep up with; "
                        "long enough that per-request admission overhead "
                        "amortizes and scheduler-oversleep noise on a "
                        "shared box stays small vs the pace)")
    p.add_argument("--router-repeats", type=int, default=3,
                   help="repeats per sweep point, best-of (CPU-neighbor "
                        "noise only ever slows a run down — the best run "
                        "is the router's real cost, the BENCHMARKS.md "
                        "best-of-N discipline); correctness must hold in "
                        "EVERY repeat")
    p.add_argument("--router-slots", type=int, default=2,
                   help="concurrent decode slots per stub replica")
    p.add_argument("--max-queue", type=int, default=1024,
                   help="admission-queue depth (large: the loadgen measures "
                        "latency under queueing, not reject behavior)")
    p.add_argument("--seed", type=int, default=0, help="base request seed")
    p.add_argument("--workload", default=None, metavar="SPEC_JSON",
                   help="load the FULL workload (prompt lengths, arrival "
                        "pattern, seeds, shared-prefix mix) from a committed "
                        "spec file (configs/workloads/*.json) so tuning "
                        "trials and bench runs replay byte-identical "
                        "workloads across arms; the resolved spec's hash is "
                        "embedded in the artifact (workload_hash)")
    p.add_argument("--prompt-seed", type=int, default=1234,
                   help="RNG seed for the deterministic prompt mix")
    p.add_argument("--prompt-len-min", type=int, default=2,
                   help="shortest prompt in the mixed workload")
    p.add_argument("--prompt-len-max", type=int, default=8,
                   help="longest prompt in the mixed workload (clamped to "
                        "what the cache budget allows)")
    p.add_argument("--no-verify", action="store_true",
                   help="skip the per-request generate() parity check")
    p.add_argument("--obs-ab", action="store_true",
                   help="measure tracing overhead: run the workload with "
                        "span tracing OFF and ON (--obs-ab-repeats each, "
                        "best-of), and embed the A/B as obs_overhead in the "
                        "artifact — scripts/serve_bench_guard.py fails a "
                        "committed overhead_frac > 2%%")
    p.add_argument("--obs-ab-repeats", type=int, default=3,
                   help="repeats per tracing arm in the --obs-ab A/B "
                        "(best-of-N de-noises the 2%% bar on shared boxes)")
    p.add_argument("--trace-out", default=None,
                   help="Perfetto/Chrome-trace artifact path for the "
                        "measured run's span ring (default: <--out> with "
                        "a .trace.json suffix)")
    p.add_argument("--chaos", action="store_true",
                   help="inject serving faults into the measured run (a "
                        "decode-tick fault window + a NaN-logit window): "
                        "faulted requests must fail RETRYABLY, untouched "
                        "requests must still match generate() byte-for-byte")
    p.add_argument("--chaos-tick", type=int, default=6,
                   help="tick index of the injected decode fault")
    p.add_argument("--chaos-nan-tick", type=int, default=10,
                   help="tick index of the injected NaN-logit window (slot 0)")
    p.add_argument("--drain-deadline", type=float, default=30.0,
                   help="graceful-drain budget at end of run (the measured "
                        "drain latency lands in the artifact)")
    p.add_argument("--out", default=str(REPO / "BENCH_serve.json"))
    return p.parse_args(argv)


# the workload-defining fields a --workload spec file may pin (anything
# else in the file is an error — a typo must not silently change traffic)
WORKLOAD_KEYS = (
    "model", "requests", "concurrency", "mode", "rate", "max_new_tokens",
    "cache_len", "seed", "prompt_seed", "prompt_len_min", "prompt_len_max",
    "shared_prefix", "greedy",
)


def resolve_workload(args):
    """Apply a --workload spec file onto args (the file is the frozen
    source of truth for every traffic-defining field it names), then
    return ``(name, spec, hash)`` for the RESOLVED workload — the spec
    actually replayed, hashed so two artifacts claiming the same workload
    can be checked byte-for-byte. Runs for every mode so the hash is
    always available; the spec file itself is only meaningful for the
    standard (engine-driving) scenario."""
    name = "inline"
    if args.workload:
        raw = json.loads(Path(args.workload).read_text())
        name = raw.pop("name", Path(args.workload).stem)
        unknown = set(raw) - set(WORKLOAD_KEYS)
        if unknown:
            raise SystemExit(
                f"workload spec {args.workload}: unknown keys "
                f"{sorted(unknown)} (allowed: {sorted(WORKLOAD_KEYS)})"
            )
        for key, value in raw.items():
            setattr(args, key, value)
    spec = {k: getattr(args, k) for k in WORKLOAD_KEYS}
    if args.shared_prefix:
        # shared-prefix prompt construction derives the prefix length from
        # the prefill chunk (make_requests), so for THAT workload the chunk
        # is traffic-defining and must be part of the hashed identity —
        # two different chunk sizes are two different request streams
        spec["prefill_chunk_traffic"] = args.prefill_chunk
    from zero_transformer_tpu.analysis.autotune import workload_hash

    return name, spec, workload_hash(spec)


def make_requests(args, vocab_size: int, cache_len: int):
    """Deterministic request mix: varied prompt lengths so admissions cross
    prefill buckets, seeds offset from --seed. With --shared-prefix, every
    prompt is one common system prefix (>= 2 prefill chunks when the cache
    budget allows) + a short unique persona tail. Every input comes from
    args, so a --workload spec replays byte-identically across arms."""
    rng = random.Random(args.prompt_seed)
    out = []
    if args.shared_prefix:
        chunk = max(1, args.prefill_chunk)
        # the prefix must leave room for the tail and the generation:
        # prefix + tail + max_new - 1 <= cache_len
        budget = cache_len - args.max_new_tokens - 4 + 1
        prefix_len = max(chunk + 1, min(2 * chunk, budget))
        prefix = [rng.randint(1, vocab_size - 1) for _ in range(prefix_len)]
        for i in range(args.requests):
            tail = [rng.randint(1, vocab_size - 1) for _ in range(rng.randint(2, 4))]
            out.append((prefix + tail, args.seed + i))
        return out
    max_prompt = max(2, min(args.prompt_len_max, cache_len - args.max_new_tokens))
    min_prompt = max(1, min(args.prompt_len_min, max_prompt))
    for i in range(args.requests):
        length = rng.randint(min_prompt, max_prompt)
        prompt = [rng.randint(1, vocab_size - 1) for _ in range(length)]
        out.append((prompt, args.seed + i))
    return out


def build(args):
    import jax
    import jax.numpy as jnp

    from zero_transformer_tpu.config import model_config
    from zero_transformer_tpu.inference.sampling import SamplingConfig
    from zero_transformer_tpu.models import Transformer
    from zero_transformer_tpu.serving import ServingEngine

    cfg = model_config(args.model, dropout=0.0)
    params = Transformer(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    sampling = SamplingConfig(temperature=0.9, top_k=20, greedy=args.greedy)
    cache_len = args.cache_len or cfg.max_seq_len
    kv_layout = args.kv_layout if args.prefill_chunk else "slab"

    def engine(chaos=None, prefix_cache=None, spec_k=None, slots=None,
               layout=None, pool_tokens=None, trace=True, fused_tail=None):
        chunks = prefix_cache if prefix_cache is not None else args.prefix_cache
        lay = layout or kv_layout
        fused = (
            fused_tail if fused_tail is not None
            else not getattr(args, "no_fused_tail", False)
        )
        draft = args.spec_k if spec_k is None else spec_k
        if not fused:
            draft = 0  # the defused control covers the plain decode path
        return ServingEngine(
            cfg, params, n_slots=slots or args.slots, cache_len=cache_len,
            sampling=sampling, max_queue=args.max_queue, chaos=chaos,
            prefill_chunk=args.prefill_chunk,
            prefix_cache_chunks=chunks if args.prefill_chunk else 0,
            kv_layout=lay,
            page_size=args.page_size,
            page_pool_tokens=(
                (pool_tokens if pool_tokens is not None else args.page_pool_tokens)
                if lay == "paged" else 0
            ),
            draft_k=draft,
            fused_tail=fused,
            trace=trace,
        )

    return cfg, params, sampling, cache_len, engine


def chaos_plan(args):
    """Deterministic serving fault plan for --chaos: one decode-tick fault
    (fails whatever is in a slot on that tick, retryably) and one NaN-logit
    window on slot 0 (the per-tick guard must retire ONLY that slot)."""
    from zero_transformer_tpu.serving import ServeFault, ServingChaosMonkey

    return ServingChaosMonkey([
        ServeFault("tick_fault", step=args.chaos_tick, duration=1),
        ServeFault("nan_logits", step=args.chaos_nan_tick, duration=1,
                   slots=[0]),
    ])


def reference_outputs(cfg, params, sampling, cache_len, requests, max_new):
    import jax
    import jax.numpy as jnp

    from zero_transformer_tpu.inference.generate import decode_model, generate

    model = decode_model(cfg, cache_len)
    refs = []
    for prompt, seed in requests:
        toks = generate(
            model, params, jnp.asarray([prompt], jnp.int32), max_new,
            jax.random.PRNGKey(seed), sampling,
        )
        refs.append(jax.device_get(toks)[0].tolist())
    return refs


def prefill_p50(handles, pred=lambda h: True):
    """p50 of admission -> first token, in ms. The prefill+first-decode
    component the ENGINE controls: under a closed loop, FULL TTFT is
    dominated by queue wait (a prefix-cache hit that queued behind cold
    requests looks slower on TTFT while prefilling 4x faster), so
    attribution splits on this instead."""
    samples = sorted(
        h.first_token_at - h.admitted_at
        for h in handles
        if h is not None
        and h.first_token_at is not None
        and h.admitted_at is not None
        and pred(h)
    )
    if not samples:
        return 0.0
    return round(samples[(len(samples) - 1) // 2] * 1e3, 3)


def run_load(engine, requests, args):
    """Submit + drain all requests; returns (handles, wall_seconds)."""
    handles: list = [None] * len(requests)
    stop = threading.Event()
    scheduler = threading.Thread(target=engine.run, args=(stop,), daemon=True)
    started = time.monotonic()
    scheduler.start()
    try:
        if args.mode == "open":
            interval = 1.0 / args.rate if args.rate > 0 else 0.0
            for i, (prompt, seed) in enumerate(requests):
                handles[i] = engine.submit(
                    prompt, max_new_tokens=args.max_new_tokens, seed=seed
                )
                time.sleep(interval)
            for h in handles:
                h.result(timeout=600)
        else:
            nxt = iter(range(len(requests)))
            lock = threading.Lock()

            def client():
                while True:
                    with lock:
                        i = next(nxt, None)
                    if i is None:
                        return
                    prompt, seed = requests[i]
                    handle = engine.submit(
                        prompt, max_new_tokens=args.max_new_tokens, seed=seed
                    )
                    handles[i] = handle
                    for _ in handle.stream(timeout=600):
                        pass  # drain the SSE-style per-token stream

            workers = [
                threading.Thread(target=client, daemon=True)
                for _ in range(min(args.concurrency, len(requests)))
            ]
            for w in workers:
                w.start()
            for w in workers:
                w.join(timeout=600)
    finally:
        # end-of-run graceful drain (instead of a bare stop): measures the
        # drain latency the artifact reports, and proves the lifecycle
        # reaches STOPPED with nothing in flight
        engine.begin_drain(deadline_s=args.drain_deadline)
        scheduler.join(timeout=args.drain_deadline + 30)
        stop.set()  # fallback: a wedged drain still stops the loop
        scheduler.join(timeout=30)
    return handles, time.monotonic() - started


def run_capacity_sweep(args, cfg, cache_len, make_engine) -> dict:
    """Slab-vs-paged concurrent-stream capacity at EQUAL KV memory budget.

    The budget is what the slab reserves: ``slots x cache_len`` positions.
    The paged engine gets a page pool of exactly that many positions (plus
    its block tables — int32 noise) and ``--capacity-slots`` decode rows,
    then both engines are offered the same ``--capacity-streams`` mixed-
    length streams. The slab's concurrency is pinned at its slot count
    whatever the sequence lengths; the paged engine admits as many streams
    as their ACTUAL worst-case footprints fit (reservation-checked, so
    nothing preempts mid-decode) — peak occupancy IS the measured capacity,
    and admission beyond it waits in the queue (the reject/OOM boundary).
    Emits BENCH_serve_capacity.json.
    """
    import jax

    budget_tokens = args.slots * cache_len
    rng = random.Random(4321)
    max_prompt = max(2, min(8, cache_len - args.max_new_tokens))
    streams = [
        (
            [rng.randint(1, cfg.vocab_size - 1) for _ in range(rng.randint(2, max_prompt))],
            args.seed + i,
        )
        for i in range(args.capacity_streams)
    ]

    def drive(engine):
        handles = [
            engine.submit(p, max_new_tokens=args.max_new_tokens, seed=s)
            for p, s in streams
        ]
        engine.run_until_idle()
        snap = engine.metrics_snapshot()
        ok = sum(1 for h in handles if h.status == "done")
        return handles, snap, ok

    # warmup both program families (compiles happen off the measured path)
    for layout, slots, pool in (
        ("slab", args.slots, None),
        ("paged", args.capacity_slots, budget_tokens),
    ):
        w = make_engine(layout=layout, slots=slots, pool_tokens=pool, spec_k=0)
        for p, s in streams[: slots + 1]:
            w.submit(p, max_new_tokens=args.max_new_tokens, seed=s)
        w.run_until_idle()

    slab = make_engine(layout="slab", slots=args.slots, spec_k=0)
    _, slab_snap, slab_ok = drive(slab)
    paged = make_engine(
        layout="paged", slots=args.capacity_slots, pool_tokens=budget_tokens,
        spec_k=0,
    )
    _, paged_snap, paged_ok = drive(paged)

    ratio = (
        paged_snap["peak_occupancy"] / slab_snap["peak_occupancy"]
        if slab_snap["peak_occupancy"]
        else 0.0
    )
    artifact = {
        "metric": "serve_capacity_streams_ratio",
        "value": round(ratio, 3),
        "unit": "paged_streams / slab_streams @ equal KV budget",
        "model": args.model,
        "kv_budget_tokens": budget_tokens,
        "page_size": args.page_size,
        "prefill_chunk": args.prefill_chunk,
        "max_new_tokens": args.max_new_tokens,
        "streams_offered": args.capacity_streams,
        "slab": {
            "slots": args.slots,
            "capacity_streams": slab_snap["peak_occupancy"],
            "completed": slab_ok,
        },
        "paged": {
            "slots": args.capacity_slots,
            "capacity_streams": paged_snap["peak_occupancy"],
            "completed": paged_ok,
            "page_pool_util": round(
                paged_snap["page_pool_peak"]
                / max(1, paged.slots.pool.n_pages - 1),
                4,
            ),
            "page_faults": paged_snap["page_faults"],
            "preemptions": paged_snap["preemptions"],
        },
        "platform": {
            "backend": jax.default_backend(),
            "device": getattr(jax.devices()[0], "device_kind", "unknown"),
        },
        "measured_at_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    Path(args.out).write_text(json.dumps(artifact, indent=2) + "\n")
    print(json.dumps(artifact))
    if slab_ok != len(streams) or paged_ok != len(streams):
        raise SystemExit(
            f"CAPACITY SWEEP FAILED: slab completed {slab_ok}, paged "
            f"completed {paged_ok} of {len(streams)} (a capacity claim over "
            "dropped streams is not a capacity claim)"
        )
    return artifact


# ------------------------------------------------------- fleet router bench


def _platform_block() -> dict:
    import jax

    return {
        "backend": jax.default_backend(),
        "device": getattr(jax.devices()[0], "device_kind", "unknown"),
    }


def _sse_collect(port: int, body: dict, timeout: float = 120.0,
                 headers: dict = None):
    """Minimal SSE client against the router: returns (token_ids, done_event)
    for streams, or (tokens, doc) for JSON rejections."""
    import http.client

    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request(
            "POST", "/generate", json.dumps(body),
            {"Content-Type": "application/json", **(headers or {})},
        )
        resp = conn.getresponse()
        if "text/event-stream" not in resp.getheader("Content-Type", ""):
            return [], json.loads(resp.read() or b"{}")
        ids, done = [], None
        while True:
            line = resp.readline()
            if not line:
                break
            if not line.startswith(b"data: "):
                continue
            event = json.loads(line[6:])
            if event.get("done"):
                done = event
                break
            if "token" in event:
                ids.append(int(event["token"]))
        return ids, done
    finally:
        conn.close()


def _drive_router_fleet(router, prompts, n_requests, max_new, expect_base):
    """Closed loop: one thread per prompt family, ``n_requests`` streams
    each (same family prefix, varying tail). Returns (wall_s, tokens_ok,
    streams_done, mismatches, hung)."""
    results: list = []
    lock = threading.Lock()

    def client(prefix):
        for j in range(n_requests):
            prompt = prefix + [101 + j]
            ids, done = _sse_collect(
                router.port, {"tokens": prompt, "max_new_tokens": max_new}
            )
            first = expect_base + len(prompt)
            ok = (
                done is not None
                and done.get("status") == "done"
                and ids == list(range(first, first + max_new))
            )
            with lock:
                results.append((len(ids), done, ok))

    threads = [
        threading.Thread(target=client, args=(p,), daemon=True)
        for p in prompts
    ]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=600)
    wall = time.monotonic() - t0
    hung = sum(1 for t in threads if t.is_alive())
    expected = len(prompts) * n_requests
    done_n = sum(1 for _, done, _ in results if done and done.get("done"))
    mismatches = sum(1 for _, _, ok in results if not ok)
    tokens = sum(n for n, _, _ in results)
    return wall, tokens, done_n, mismatches + (expected - len(results)), hung


def run_router_bench(args) -> dict:
    """The fleet-scaling measurement (ISSUE 9). Replicas are PACED stubs
    (``scripts/serve_router.py`` StubReplica): each emits deterministic
    token ids at a fixed inter-token interval with a bounded slot count —
    a model of a device-bound replica whose decode rate does not depend on
    this box's CPU. What IS measured on this box is the part that runs on a
    router box in production: the relay loop, the routing policy, failover,
    and the rolling reload. Three segments:

    - **scaling sweep**: the same closed-loop client pool against fleets of
      1, 2, ... --router-replicas; aggregate relayed tok/s should track the
      fleet's aggregate pace near-linearly (the guard's >= 3x at 1 -> 4 bar)
      with every stream token-exact vs the stubs' arithmetic sequence;
    - **failover**: one replica armed to die mid-stream; the client stream
      must resume on the survivor and stay token-exact end to end;
    - **rolling reload**: a 3-replica fleet reloaded one replica at a time
      under live streams; ``dropped_streams`` must stay 0.
    """
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "serve_router", REPO / "scripts" / "serve_router.py"
    )
    serve_router = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(serve_router)
    from zero_transformer_tpu.serving.router import RouterServer

    itl_s = args.router_itl_ms / 1e3
    slots = args.router_slots
    chunk = 4
    counts = [1]
    while counts[-1] * 2 <= args.router_replicas:
        counts.append(counts[-1] * 2)
    clients = args.router_clients or slots * counts[-1]
    max_new = args.router_max_new
    # one fixed chunk-aligned prefix per client: requests 2..N of a client
    # should ride prefix affinity back to the replica that served request 1
    prefixes = [[10 + i] * (2 * chunk) for i in range(clients)]
    dropped_total = 0
    failures: list = []

    def fleet(n, router_kw=None, **kw):
        stubs = [
            serve_router.StubReplica(itl_s=itl_s, slots=slots, **kw).start()
            for _ in range(n)
        ]
        router = RouterServer(
            [s.url for s in stubs], probe_interval=0.05, chunk_tokens=chunk,
            max_attempts=4, stream_timeout=60.0, **(router_kw or {}),
        )
        router.start()
        if not router.wait_ready(10.0):
            raise SystemExit("ROUTER BENCH FAILED: fleet never became ready")
        return stubs, router

    def teardown(stubs, router):
        nonlocal dropped_total
        dropped_total += router.stats["dropped_streams"]
        router.stop()
        for s in stubs:
            s.stop()

    # ---- segment 1: scaling sweep (best-of --router-repeats per point:
    # neighbor contention only slows a run down, so the best repeat is the
    # router's real relay cost; correctness must hold in EVERY repeat)
    scaling = []
    routing = None
    repeats = max(1, args.router_repeats)
    for n in counts:
        best = None
        for rep_i in range(repeats):
            stubs, router = fleet(n)
            wall, tokens, done_n, mismatches, hung = _drive_router_fleet(
                router, prefixes, args.router_requests, max_new,
                expect_base=1000,
            )
            snap = router.metrics_snapshot()
            expected = clients * args.router_requests
            if hung or done_n != expected or mismatches:
                failures.append(
                    f"scaling@{n} repeat {rep_i}: {hung} hung, "
                    f"{done_n}/{expected} done, "
                    f"{mismatches} token-sequence mismatches"
                )
            per_replica = {
                rid: round(info["tokens_relayed"] / wall, 1)
                for rid, info in snap["replicas"].items()
            }
            point = {
                "replicas": n,
                "aggregate_tok_s": round(tokens / wall, 1),
                "per_replica_tok_s": sorted(
                    per_replica.values(), reverse=True
                ),
                "wall_s": round(wall, 3),
                "streams": done_n,
                "repeats": repeats,
                "affinity_hit_rate": round(snap["affinity_hit_rate"], 4),
                "failovers": snap["failovers"],
            }
            teardown(stubs, router)
            if best is None or point["aggregate_tok_s"] > best[0]["aggregate_tok_s"]:
                best = (point, snap)
        scaling.append(best[0])
        if n == counts[-1]:
            snap = best[1]
            routing = {
                "affinity_hits": snap["affinity_hits"],
                "affinity_misses": snap["affinity_misses"],
                "hit_rate": round(snap["affinity_hit_rate"], 4),
            }

    # ---- segment 1.5: fleet observability plane (ISSUE 15) — an
    # unsaturated 2-replica fleet with the SLO engine on: every stream's
    # merged fleet trace must stitch (>=95% coverage, zero orphans, hops
    # ordered after clock correction), the terminal ledgers must be
    # schema-complete, and the healthy run's SLO verdict must be ok
    from zero_transformer_tpu.obs.fleet import FLEET_OBS_REQUIRED_KEYS
    from zero_transformer_tpu.obs.slo import Objective

    trace_path = (
        args.out[:-5] if args.out.endswith(".json") else args.out
    ) + ".trace.json"
    obs_objectives = [
        # correctness-shaped objectives for the verdict: latency SLOs on a
        # deliberately saturated CPU-box sweep would grade queue wait, not
        # the router (tests/test_fleet_obs.py exercises the latency path)
        Objective(name="availability", metric="availability", target=0.999,
                  short_window_s=5.0, long_window_s=60.0),
        Objective(name="dropped_streams", metric="dropped_streams",
                  kind="zero", target=0.999999, short_window_s=5.0,
                  long_window_s=60.0, fast_burn=1.0),
    ]
    stubs, router = fleet(2, router_kw={
        "slo": obs_objectives, "metrics_scrape_interval": 0.1,
        "slo_eval_interval": 0.1,
    })
    fleet_trace = {"file": Path(trace_path).name}
    slo_block: dict = {}
    ledger_block: dict = {}
    try:
        wall, tokens, done_n, mismatches, hung = _drive_router_fleet(
            router, prefixes[: min(4, len(prefixes))], 1, max_new,
            expect_base=1000,
        )
        if hung or mismatches:
            failures.append(
                f"fleet-obs segment: {hung} hung, {mismatches} mismatches"
            )
        router.scrape_fleet_metrics()
        router.evaluate_slo()
        stitch = router.verify_run_traces()
        router.export_merged_trace(trace_path)
        fleet_trace.update({
            k: stitch[k]
            for k in ("requests", "coverage_min", "orphans", "hops_ordered")
        })
        slo_block = router.slo.snapshot()
        ledger_block = router.tenants.totals()
        if stitch["coverage_min"] < 0.95:
            failures.append(
                f"stitched coverage {stitch['coverage_min']} < 0.95"
            )
        if stitch["orphans"] or not stitch["hops_ordered"]:
            failures.append(f"stitched trace failed verification: {stitch}")
        if slo_block.get("verdict") != "ok":
            failures.append(
                f"healthy fleet-obs segment SLO verdict: "
                f"{slo_block.get('verdict')}"
            )
        missing_led = FLEET_OBS_REQUIRED_KEYS["ledger"] - set(ledger_block)
        if missing_led:
            failures.append(f"aggregate ledger missing {sorted(missing_led)}")
        if not ledger_block.get("tokens_relayed"):
            failures.append("aggregate ledger relayed no tokens")
    finally:
        teardown(stubs, router)

    # ---- segment 2: mid-stream failover on a survivor, token-exact
    victim = serve_router.StubReplica(
        itl_s=itl_s, slots=slots, die_after_tokens=3
    ).start()
    survivor = serve_router.StubReplica(itl_s=itl_s, slots=slots).start()
    router = RouterServer(
        [victim.url, survivor.url], probe_interval=0.05, chunk_tokens=chunk,
        max_attempts=4, stream_timeout=60.0,
    )
    router.start()
    failover = {"failovers": 0, "resumed_streams": 0, "token_exact": False}
    try:
        if not router.wait_ready(10.0):
            raise SystemExit("ROUTER BENCH FAILED: failover fleet not ready")
        prompt = [3] * (2 * chunk)
        router.affinity.record(prompt, f"127.0.0.1:{victim.port}")
        ids, done = _sse_collect(
            router.port, {"tokens": prompt, "max_new_tokens": 12}
        )
        first = 1000 + len(prompt)
        failover = {
            "failovers": router.stats["failovers"],
            "resumed_streams": router.stats["resumed_streams"],
            "token_exact": bool(
                done is not None
                and done.get("status") == "done"
                and ids == list(range(first, first + 12))
            ),
        }
        if not (victim.died and failover["token_exact"]
                and failover["resumed_streams"] == 1):
            failures.append(f"failover: {failover}, victim.died={victim.died}")
    finally:
        dropped_total += router.stats["dropped_streams"]
        router.stop()
        victim.stop()
        survivor.stop()

    # ---- segment 3: rolling reload under live streams, zero drops
    stubs, router = fleet(3)
    reload_result = {"ok": False, "steps": 0, "dropped_streams": -1}
    try:
        done_flags: list = []

        def bg_client(i):
            ids, done = _sse_collect(
                router.port,
                {"tokens": [70 + i] * chunk, "max_new_tokens": max_new},
            )
            done_flags.append(bool(done and done.get("status") == "done"))

        bg = [
            threading.Thread(target=bg_client, args=(i,), daemon=True)
            for i in range(4)
        ]
        for t in bg:
            t.start()
        time.sleep(4 * itl_s)  # streams mid-generation
        ok, steps = router.rolling_reload(drain_timeout_s=60.0,
                                          ready_timeout_s=60.0)
        for t in bg:
            t.join(timeout=120)
        hung = sum(1 for t in bg if t.is_alive())
        reload_result = {
            "ok": bool(ok and not hung and all(done_flags)
                       and len(done_flags) == 4),
            "steps": sum(1 for s in steps if s.get("ok")),
            "dropped_streams": router.stats["dropped_streams"],
        }
        if not reload_result["ok"] or reload_result["dropped_streams"]:
            failures.append(f"rolling_reload: {reload_result}, steps={steps}")
    finally:
        teardown(stubs, router)

    base = scaling[0]["aggregate_tok_s"]
    peak = scaling[-1]["aggregate_tok_s"]
    artifact = {
        "metric": "router_scaling_tok_s",
        "value": round(peak / base, 3) if base else 0.0,
        "unit": f"aggregate tok/s ratio, {counts[-1]} replicas vs 1",
        "replica_model": "paced_stub",
        "replica_itl_ms": args.router_itl_ms,
        "replica_slots": slots,
        "clients": clients,
        "requests_per_client": args.router_requests,
        "max_new_tokens": max_new,
        "scaling": scaling,
        "aggregate_tok_s": peak,
        "routing": routing,
        "failover": failover,
        "rolling_reload": reload_result,
        "dropped_streams": dropped_total,
        # fleet observability plane (ISSUE 15): the merged fleet trace's
        # programmatic verification, the SLO verdict over the run, and the
        # aggregate cost ledger (serve_bench_guard fails a violated verdict
        # on matching hardware)
        "fleet_trace": fleet_trace,
        "slo": slo_block,
        "ledger": ledger_block,
        "platform": _platform_block(),
        "measured_at_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    Path(args.out).write_text(json.dumps(artifact, indent=2) + "\n")
    print(json.dumps(artifact))
    if failures or dropped_total:
        raise SystemExit(
            "ROUTER BENCH FAILED: "
            + "; ".join(failures or [f"{dropped_total} dropped streams"])
        )
    return artifact


# --------------------------------------------- disaggregated fleet (ISSUE 12)


def _pcts(values, qs=(50, 99)):
    import math

    if not values:
        return {f"p{q}": 0.0 for q in qs}
    ordered = sorted(values)
    out = {}
    for q in qs:
        rank = max(
            0, min(len(ordered) - 1, math.ceil(q / 100 * len(ordered)) - 1)
        )
        out[f"p{q}"] = round(ordered[rank], 3)
    return out


def _sse_timed(port: int, body: dict, timeout: float = 600.0,
               headers: dict = None):
    """SSE client recording each token's ARRIVAL time: returns
    (ids, stamps, done_event)."""
    import http.client

    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request(
            "POST", "/generate", json.dumps(body),
            {"Content-Type": "application/json", **(headers or {})},
        )
        resp = conn.getresponse()
        if "text/event-stream" not in (resp.getheader("Content-Type") or ""):
            return [], [], json.loads(resp.read() or b"{}")
        ids, stamps, done = [], [], None
        while True:
            line = resp.readline()
            if not line:
                break
            if not line.startswith(b"data: "):
                continue
            event = json.loads(line[6:])
            if event.get("done"):
                done = event
                break
            if "token" in event:
                ids.append(int(event["token"]))
                stamps.append(time.monotonic())
        return ids, stamps, done
    finally:
        conn.close()


class _IdTokenizer:
    eos_token_id = None

    def encode(self, text):
        return [1 + (b % 250) for b in text.encode()]

    def decode(self, ids, **kw):
        return "".join(f"<{t}>" for t in ids)

    def convert_ids_to_tokens(self, ids):
        return [f"<{t}>" for t in ids]

    def convert_tokens_to_string(self, toks):
        return "".join(toks)


def _run_flood_arm(cfg, params, sampling, cache_len, args, roles, label):
    """One fleet arm of the long-prompt-flood A/B: build the fleet (REAL
    engines + servers + router), measure (a) the no-flood decode-only ITL
    baseline, then (b) background ITL + flood TTFT with the flood live.
    Client-side clocks: the numbers are what a caller would see."""
    from zero_transformer_tpu.serving import (
        RouterServer,
        ServingEngine,
        ServingServer,
    )

    servers = []
    for role in roles:
        engine = ServingEngine(
            cfg, params, n_slots=args.slots, cache_len=cache_len,
            sampling=sampling, prefill_chunk=args.prefill_chunk,
            prefix_cache_chunks=0, kv_layout="paged",
            page_size=args.page_size, role=role,
        )
        server = ServingServer(engine, _IdTokenizer(), port=0)
        server.start()
        servers.append(server)
    router = RouterServer(
        [f"127.0.0.1:{s.port}" for s in servers],
        probe_interval=0.05, chunk_tokens=args.prefill_chunk,
        stream_timeout=600.0, max_attempts=4,
    )
    router.start()
    try:
        if not router.wait_ready(60):
            raise SystemExit(f"DISAGG BENCH FAILED: {label} fleet not ready")
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and any(
            r.role != roles[i]
            for i, r in enumerate(router.registry.replicas.values())
        ):
            time.sleep(0.05)
        # warm every compile family outside the measured window
        bg_prompt = [7, 11, 13, 17, 19, 23]
        long_len = 3 * args.prefill_chunk + 2
        _sse_timed(router.port, {"tokens": bg_prompt, "max_new_tokens": 2})
        _sse_timed(router.port, {
            "tokens": [(29 + i) % 250 + 1 for i in range(long_len)],
            "max_new_tokens": 2,
        })

        bg_new = args.max_new_tokens * 2
        lock = threading.Lock()

        def background(i, sink):
            prompt = bg_prompt + [31 + i]
            ids, stamps, done = _sse_timed(router.port, {
                "tokens": prompt, "max_new_tokens": bg_new, "seed": i,
            })
            with lock:
                sink.append((prompt, bg_new, i, ids, stamps, done))

        # ---- no-flood baseline: background streams alone
        base_runs: list = []
        threads = [
            threading.Thread(target=background, args=(i, base_runs), daemon=True)
            for i in range(args.flood_background)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=600)
        base_gaps = [
            (b - a) * 1e3
            for _, _, _, _, stamps, _ in base_runs
            for a, b in zip(stamps, stamps[1:])
        ]

        # ---- flood phase: background + long-prompt arrivals
        bg_runs: list = []
        flood_runs: list = []
        threads = [
            threading.Thread(
                target=background, args=(100 + i, bg_runs), daemon=True
            )
            for i in range(args.flood_background)
        ]
        for t in threads:
            t.start()
        time.sleep(0.05)

        def flood(i):
            prompt = [(37 + i + j) % 250 + 1 for j in range(long_len)]
            t0 = time.monotonic()
            ids, stamps, done = _sse_timed(router.port, {
                "tokens": prompt, "max_new_tokens": 4, "seed": 0,
            })
            ttft = (stamps[0] - t0) * 1e3 if stamps else float("inf")
            with lock:
                flood_runs.append((prompt, 4, ttft, ids, done))

        fthreads = [
            threading.Thread(target=flood, args=(i,), daemon=True)
            for i in range(args.flood_requests)
        ]
        for t in fthreads:
            t.start()
        for t in fthreads + threads:
            t.join(timeout=600)
        hung = sum(1 for t in fthreads + threads if t.is_alive())
        flood_gaps = [
            (b - a) * 1e3
            for _, _, _, _, stamps, _ in bg_runs
            for a, b in zip(stamps, stamps[1:])
        ]
        all_done = all(
            done is not None and done.get("status") == "done"
            for _, _, _, _, _, done in base_runs + bg_runs
        ) and all(
            done is not None and done.get("status") == "done"
            for _, _, _, _, done in flood_runs
        )
        streams = [
            (prompt, max_new, 0, ids)
            for prompt, max_new, _, ids, _ in flood_runs
        ] + [
            (prompt, max_new, seed, ids)
            for prompt, max_new, seed, ids, _, _ in base_runs + bg_runs
        ]
        return {
            "roles": list(roles),
            "itl_ms_decode_bg_no_flood": _pcts(base_gaps),
            "itl_ms_decode_bg_flood": _pcts(flood_gaps),
            "ttft_ms_flood": _pcts([t for _, _, t, _, _ in flood_runs]),
            "streams_done": all_done,
            "hung": hung,
            "dropped_streams": router.stats["dropped_streams"],
            "disagg_dispatches": router.stats["disagg_dispatches"],
            "resume_replayed_tokens": router.stats["resume_replayed_tokens"],
        }, streams
    finally:
        router.stop()
        for s in servers:
            s.stop()


def _run_sawtooth_segment(args) -> dict:
    """Autoscale tracking: stub replicas (paced, device-speed-independent)
    behind the router's autoscaler; a burst phase must scale the fleet up
    and an idle phase must scale it back down, with zero dropped streams."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "serve_router", REPO / "scripts" / "serve_router.py"
    )
    serve_router = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(serve_router)
    from zero_transformer_tpu.serving import RouterServer

    live = []

    class _Scaler:
        def spawn(self):
            stub = serve_router.StubReplica(itl_s=0.004, slots=1).start()
            live.append(stub)
            return f"127.0.0.1:{stub.port}"

        def retire(self, url):
            port = int(url.rsplit(":", 1)[1])
            for stub in live:
                if stub.port == port:
                    stub.stop()

    seed_stub = serve_router.StubReplica(itl_s=0.004, slots=1).start()
    live.append(seed_stub)
    router = RouterServer(
        [f"127.0.0.1:{seed_stub.port}"],
        probe_interval=0.05, chunk_tokens=4, stream_timeout=120.0,
        scaler=_Scaler(), autoscale_interval=0.15, scale_patience=2,
        scale_up_queue=1.0, scale_down_active=0, min_replicas=1,
        max_replicas=3, scale_drain_timeout_s=10.0,
    )
    router.start()
    trace = []
    stop_sampling = threading.Event()

    def sample():
        t0 = time.monotonic()
        while not stop_sampling.wait(0.1):
            trace.append([
                round(time.monotonic() - t0, 2),
                len(router.registry.routable()),
                sum(r.queue_depth for r in router.registry.routable()),
            ])

    sampler = threading.Thread(target=sample, daemon=True)
    sampler.start()
    try:
        if not router.wait_ready(30):
            raise SystemExit("DISAGG BENCH FAILED: sawtooth fleet not ready")
        results: list = []
        lock = threading.Lock()

        def client(i):
            ids, done = _sse_collect(router.port, {
                "tokens": [10 + i] * 4, "max_new_tokens": 24,
            }, timeout=300)
            with lock:
                results.append((ids, done))

        # tooth 1: a burst well past one stub's capacity
        burst = [
            threading.Thread(target=client, args=(i,), daemon=True)
            for i in range(6)
        ]
        for t in burst:
            t.start()
        for t in burst:
            t.join(timeout=300)
        # trough: idle until the autoscaler retires the extra capacity
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and len(router.registry) > 1:
            time.sleep(0.1)
        # tooth 2: prove the shrunk fleet still tracks a second burst
        burst2 = [
            threading.Thread(target=client, args=(20 + i,), daemon=True)
            for i in range(6)
        ]
        for t in burst2:
            t.start()
        for t in burst2:
            t.join(timeout=300)
        stop_sampling.set()
        sampler.join(timeout=5)
        hung = sum(1 for t in burst + burst2 if t.is_alive())
        done_n = sum(
            1 for _, done in results
            if done is not None and done.get("status") == "done"
        )
        return {
            "streams": len(burst) + len(burst2),
            "streams_done": done_n,
            "hung": hung,
            "dropped_streams": router.stats["dropped_streams"],
            "autoscale_ups": router.stats["autoscale_ups"],
            "autoscale_downs": router.stats["autoscale_downs"],
            "autoscale_aborts": router.stats["autoscale_aborts"],
            "max_replicas_seen": max((n for _, n, _ in trace), default=1),
            "min_replicas_seen": min((n for _, n, _ in trace), default=1),
            "replica_trace": trace,
        }
    finally:
        stop_sampling.set()
        router.stop()
        for stub in live:
            stub.stop()


def run_disagg_bench(args) -> dict:
    """BENCH_disagg.json: the disaggregation A/B (mixed fleet control vs
    prefill/decode split under a long-prompt flood) and the sawtooth
    autoscale segment. Correctness is hard-enforced at write time: every
    stream done, token-exact vs ``generate()`` (greedy), zero drops, zero
    replayed tokens on the disaggregated arm."""
    args.greedy = True  # token-exactness is part of the artifact's claim
    cfg, params, sampling, cache_len, _ = build(args)
    artifact: dict = {
        "bench": "serve_disagg",
        "metric": "disagg_flood_and_autoscale",
        "platform": _platform_block(),
        "config": {
            "model": args.model, "slots": args.slots,
            "prefill_chunk": args.prefill_chunk,
            "page_size": args.page_size,
            "background_streams": args.flood_background,
            "flood_requests": args.flood_requests,
        },
    }
    failures = []
    if args.long_prompt_flood:
        mixed, mixed_streams = _run_flood_arm(
            cfg, params, sampling, cache_len, args,
            ("mixed", "mixed"), "mixed",
        )
        disagg, dis_streams = _run_flood_arm(
            cfg, params, sampling, cache_len, args,
            ("prefill", "decode"), "disagg",
        )
        # token-exactness vs generate() — the phase split must be
        # INVISIBLE in the bytes (greedy): every stream of BOTH arms
        refs: dict = {}

        def ref(prompt, max_new, seed):
            key = (tuple(prompt), max_new, seed)
            if key not in refs:
                refs[key] = reference_outputs(
                    cfg, params, sampling, cache_len,
                    [(list(prompt), seed)], max_new,
                )[0]
            return refs[key]

        token_exact = all(
            arm["streams_done"] and not arm["hung"]
            for arm in (mixed, disagg)
        ) and all(
            ids == ref(prompt, max_new, seed)
            for prompt, max_new, seed, ids in mixed_streams + dis_streams
        )
        # the headline: how much did the flood stretch the background
        # streams' decode ITL in each arm? (1.0 = perfectly isolated)
        for arm in (mixed, disagg):
            base = arm["itl_ms_decode_bg_no_flood"]["p50"] or 1e-9
            arm["itl_bg_p50_degradation"] = round(
                arm["itl_ms_decode_bg_flood"]["p50"] / base, 3
            )
        artifact["flood"] = {
            "mixed": mixed,
            "disagg": disagg,
            "token_exact": token_exact,
            "dropped_streams": (
                mixed["dropped_streams"] + disagg["dropped_streams"]
            ),
        }
        if not token_exact:
            failures.append("flood arm had hung/failed streams")
        if mixed["dropped_streams"] or disagg["dropped_streams"]:
            failures.append("flood arm dropped streams")
        if not disagg["disagg_dispatches"]:
            failures.append("disagg arm never split a request")
        if disagg["resume_replayed_tokens"]:
            failures.append("disagg arm replayed tokens")
    if args.sawtooth:
        saw = _run_sawtooth_segment(args)
        artifact["sawtooth"] = saw
        if saw["dropped_streams"]:
            failures.append("sawtooth dropped streams")
        if saw["hung"] or saw["streams_done"] != saw["streams"]:
            failures.append("sawtooth streams did not all finish")
        if not saw["autoscale_ups"] or not saw["autoscale_downs"]:
            failures.append("autoscaler never acted (no up or no down)")
    out = Path(args.out)
    out.write_text(json.dumps(artifact, indent=2) + "\n")
    print(json.dumps(artifact))
    if failures:
        raise SystemExit("DISAGG BENCH FAILED: " + "; ".join(failures))
    return artifact


# ------------------------------------------------ tenant isolation (ISSUE 18)


def _json_post(port: int, body: dict, headers: dict = None,
               timeout: float = 60.0):
    """Non-stream POST returning (status, json_doc, response_headers)."""
    import http.client

    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request(
            "POST", "/generate", json.dumps(body),
            {"Content-Type": "application/json", **(headers or {})},
        )
        resp = conn.getresponse()
        return (
            resp.status,
            json.loads(resp.read() or b"{}"),
            dict(resp.getheaders()),
        )
    finally:
        conn.close()


def _run_tenant_arm(cfg, params, sampling, cache_len, args, flood, label):
    """One arm of the tenant-isolation A/B: a real 2-replica QoS fleet
    (gold slot+page floors, a tight batch token bucket) behind the real
    router. The gold tenant runs a sequential streaming trickle with
    client-side clocks; the flood arm adds hostile batch-tenant threads
    hammering the fleet for the whole trickle window."""
    from zero_transformer_tpu.serving import (
        RouterServer,
        ServingEngine,
        ServingServer,
    )

    qos = {
        "classes": {
            "gold": {"slot_floor": 1, "page_floor_frac": 0.25},
            "batch": {"rate": args.tenant_batch_rate,
                      "burst": args.tenant_batch_burst},
        }
    }
    servers = []
    for _ in range(2):
        engine = ServingEngine(
            cfg, params, n_slots=args.slots, cache_len=cache_len,
            sampling=sampling, prefill_chunk=args.prefill_chunk,
            prefix_cache_chunks=0, kv_layout="paged",
            page_size=args.page_size, qos=qos,
        )
        server = ServingServer(engine, _IdTokenizer(), port=0)
        server.start()
        servers.append(server)
    doc = json.loads((REPO / "configs" / "slo_default.json").read_text())
    doc["qos"]["classes"]["batch"].update(
        rate=args.tenant_batch_rate, burst=args.tenant_batch_burst
    )
    router = RouterServer(
        [f"127.0.0.1:{s.port}" for s in servers],
        probe_interval=0.05, max_attempts=2, stream_timeout=600.0, slo=doc,
    )
    router.start()
    try:
        if not router.wait_ready(60):
            raise SystemExit(f"TENANT BENCH FAILED: {label} fleet not ready")
        # warm the compile families outside the measured trickle
        _sse_timed(
            router.port, {"tokens": [5, 7], "max_new_tokens": 2},
            headers={"X-Tenant-Key": "warm", "X-QoS-Class": "gold"},
        )

        stop = threading.Event()
        flood_codes: list = []
        lock = threading.Lock()

        def hostile():
            while not stop.is_set():
                try:
                    code, doc_, hdrs = _json_post(
                        router.port,
                        {"tokens": [9, 9, 9],
                         "max_new_tokens": args.max_new_tokens,
                         "seed": 0, "stream": False},
                        headers={"X-Tenant-Key": "flooder",
                                 "X-QoS-Class": "batch"},
                    )
                    with lock:
                        flood_codes.append((code, doc_, hdrs))
                except OSError:
                    pass

        threads = []
        if flood:
            threads = [
                threading.Thread(target=hostile, daemon=True)
                for _ in range(args.tenant_flood_clients)
            ]
            for t in threads:
                t.start()
            time.sleep(0.05)

        gold_runs = []
        for i in range(args.tenant_gold_requests):
            prompt = [3, 5, 7 + i]
            t0 = time.monotonic()
            ids, stamps, done = _sse_timed(
                router.port,
                {"tokens": prompt, "max_new_tokens": args.max_new_tokens,
                 "seed": i},
                headers={"X-Tenant-Key": "vip", "X-QoS-Class": "gold"},
            )
            e2e = (time.monotonic() - t0) * 1e3
            ttft = (stamps[0] - t0) * 1e3 if stamps else float("inf")
            gold_runs.append((prompt, i, ids, done, e2e, ttft))
        stop.set()
        for t in threads:
            t.join(30)

        rejected = [(c, d, h) for c, d, h in flood_codes if c != 200]
        bad_rejections = [
            (c, d) for c, d, h in rejected
            if c not in (429, 503)
            or float(h.get("Retry-After", 0)) < 1
            or not d.get("retryable", True)
        ]
        engine_stats = [s.engine.stats for s in servers]
        arm = {
            "label": label,
            "gold_e2e_ms": _pcts([run[4] for run in gold_runs]),
            "gold_ttft_ms": _pcts([run[5] for run in gold_runs]),
            "gold_done": sum(
                1 for run in gold_runs
                if run[3] is not None and run[3].get("status") == "done"
            ),
            "gold_offered": len(gold_runs),
            "flood_attempts": len(flood_codes),
            "flood_ok": sum(1 for c, _, _ in flood_codes if c == 200),
            "flood_rejected": len(rejected),
            "flood_bad_rejections": len(bad_rejections),
            "dropped_streams": router.stats["dropped_streams"],
            "isolation_counters": {
                "router_rejected_quota": router.stats["rejected_quota"],
                "engine_rejected_quota": sum(
                    st["rejected_quota"] for st in engine_stats
                ),
                "shed_lower_class": sum(
                    st["shed_lower_class"] for st in engine_stats
                ),
                "preempted_for_class": sum(
                    st["preempted_for_class"] for st in engine_stats
                ),
                "rejected_queue_full": sum(
                    st["rejected_queue_full"] for st in engine_stats
                ),
            },
        }
        streams = [
            (prompt, args.max_new_tokens, seed, ids)
            for prompt, seed, ids, done, _, _ in gold_runs
            if done is not None and done.get("status") == "done"
        ]
        return arm, streams
    finally:
        router.stop()
        for s in servers:
            s.stop()


def run_tenant_flood_bench(args) -> dict:
    """BENCH_tenant.json: the tenant-isolation proof (ISSUE 18). Two arms
    over the same 2-replica QoS fleet: the gold tenant's trickle alone,
    then the same trickle under a hostile batch-tenant flood. Correctness
    is hard-enforced at write time (every gold stream done and token-exact
    vs ``generate()``, zero dropped streams, every flood rejection
    retryable with a Retry-After); the headline is the gold e2e-p99 ratio
    between the arms."""
    args.greedy = True  # token-exactness is part of the artifact's claim
    cfg, params, sampling, cache_len, _ = build(args)
    base, base_streams = _run_tenant_arm(
        cfg, params, sampling, cache_len, args, flood=False, label="baseline"
    )
    flood, flood_streams = _run_tenant_arm(
        cfg, params, sampling, cache_len, args, flood=True, label="flood"
    )
    refs: dict = {}

    def ref(prompt, max_new, seed):
        key = (tuple(prompt), max_new, seed)
        if key not in refs:
            refs[key] = reference_outputs(
                cfg, params, sampling, cache_len,
                [(list(prompt), seed)], max_new,
            )[0]
        return refs[key]

    token_exact = all(
        ids == ref(prompt, max_new, seed)
        for prompt, max_new, seed, ids in base_streams + flood_streams
    )
    base_p99 = base["gold_e2e_ms"]["p99"] or 1e-9
    ratio = round(flood["gold_e2e_ms"]["p99"] / base_p99, 3)
    artifact = {
        "bench": "serve_tenant",
        "metric": "tenant_isolation",
        "value": ratio,
        "unit": "gold e2e p99 ratio, flood arm vs baseline (1.0 = isolated)",
        "isolation_factor_limit": args.tenant_isolation_factor,
        "config": {
            "model": args.model, "slots": args.slots,
            "prefill_chunk": args.prefill_chunk,
            "page_size": args.page_size,
            "max_new_tokens": args.max_new_tokens,
            "gold_requests": args.tenant_gold_requests,
            "flood_clients": args.tenant_flood_clients,
            "batch_rate": args.tenant_batch_rate,
            "batch_burst": args.tenant_batch_burst,
        },
        "baseline": base,
        "flood": flood,
        "token_exact": token_exact,
        "dropped_streams": base["dropped_streams"] + flood["dropped_streams"],
        "platform": _platform_block(),
        "measured_at_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    failures = []
    if (base["gold_done"] != base["gold_offered"]
            or flood["gold_done"] != flood["gold_offered"]):
        failures.append("gold streams did not all complete")
    if not token_exact:
        failures.append("gold streams not token-exact vs generate()")
    if artifact["dropped_streams"]:
        failures.append("dropped streams in a tenant arm")
    if not flood["flood_rejected"]:
        failures.append("flood never hit a limit -- not a flood")
    if flood["flood_bad_rejections"]:
        failures.append(
            "flood rejections without retryable semantics (non-429/503 or "
            "missing Retry-After)"
        )
    if sum(flood["isolation_counters"].values()) == 0:
        failures.append("isolation machinery never engaged")
    if ratio > args.tenant_isolation_factor:
        failures.append(
            f"gold p99 ratio {ratio} exceeds the pinned isolation factor "
            f"{args.tenant_isolation_factor}"
        )
    Path(args.out).write_text(json.dumps(artifact, indent=2) + "\n")
    print(json.dumps(artifact))
    if failures:
        raise SystemExit("TENANT BENCH FAILED: " + "; ".join(failures))
    return artifact


def main(argv=None) -> dict:
    args = parse_args(argv)
    # some images pre-import jax with a platform baked into jax.config,
    # where the JAX_PLATFORMS env var alone is a silent no-op — re-assert
    # it through the config so "CPU run" means CPU
    import os

    if os.environ.get("JAX_PLATFORMS"):
        import jax

        try:
            jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
        except RuntimeError:
            pass  # backend already initialized (e.g. under pytest)
    if args.workload and (
        args.router or args.long_prompt_flood or args.sawtooth
        or args.capacity_sweep or args.tenant_flood
    ):
        raise SystemExit(
            "--workload pins the standard engine-driving workload; the "
            "router/disagg/capacity scenarios generate their own traffic"
        )
    wl_name, wl_spec, wl_hash = resolve_workload(args)
    if args.router:
        if args.out == str(REPO / "BENCH_serve.json"):  # untouched default
            args.out = str(REPO / "BENCH_router.json")
        return run_router_bench(args)
    if args.long_prompt_flood or args.sawtooth:
        if args.out == str(REPO / "BENCH_serve.json"):  # untouched default
            args.out = str(REPO / "BENCH_disagg.json")
        return run_disagg_bench(args)
    if args.tenant_flood:
        if args.out == str(REPO / "BENCH_serve.json"):  # untouched default
            args.out = str(REPO / "BENCH_tenant.json")
        return run_tenant_flood_bench(args)
    cfg, params, sampling, cache_len, make_engine = build(args)
    if args.capacity_sweep:
        if args.out == str(REPO / "BENCH_serve.json"):  # untouched default
            args.out = str(REPO / "BENCH_serve_capacity.json")
        return run_capacity_sweep(args, cfg, cache_len, make_engine)
    requests = make_requests(args, cfg.vocab_size, cache_len)

    if args.spec_k and args.no_fused_tail:
        # mirror serve.py's loud handling of the same flag combination: the
        # defused control covers the plain decode path only. Zeroing
        # args.spec_k HERE (not just in the engine closure) also stops the
        # spec warmup arms and the no_speculation control, which would
        # otherwise compare the measured engine against itself
        print(
            "serve_loadgen: --no-fused-tail (the fused-tail A/B control) "
            "covers the plain decode path only; speculation DISABLED for "
            "this run",
            file=sys.stderr,
        )
        args.spec_k = 0

    if args.spec_k and not args.greedy and not args.no_verify:
        # stochastic speculation preserves the DISTRIBUTION (rejection
        # rule), not the per-seed trajectory — byte-parity vs generate()
        # only holds for greedy, so the check would report false garbling
        print(
            "serve_loadgen: --spec-k with stochastic sampling is "
            "distribution-preserving, not trajectory-preserving; skipping "
            "the byte-parity check (use --greedy for exact verification)",
            file=sys.stderr,
        )
        args.no_verify = True

    refs = None
    if not args.no_verify:
        refs = reference_outputs(
            cfg, params, sampling, cache_len, requests, args.max_new_tokens
        )

    # warmup engine: pay prefill-bucket + fused-step compiles outside the
    # measured run (jit caches are shared across engines — the model and
    # sampling statics compare structurally equal). With --spec-k both
    # program families get warmed: the spec-OFF control below must not pay
    # the plain step's compile inside ITS measured window
    warm_specs = (args.spec_k, 0) if args.spec_k else (args.spec_k,)
    warm_arms = [(k, True) for k in warm_specs]
    if args.fused_tail_ab or args.no_fused_tail:
        # the defused control's two programs (standalone sample + forward-
        # only) must be warm before ITS measured window too
        warm_arms.append((0, False))
    for k, fused in warm_arms:
        warm = make_engine(spec_k=k, fused_tail=fused)
        for prompt, seed in requests[: min(len(requests), args.slots + 1)]:
            warm.submit(prompt, max_new_tokens=args.max_new_tokens, seed=seed)
        warm.run_until_idle()

    # cache-OFF control for the shared-prefix A/B, run BEFORE the measured
    # engine (not after): everything downstream of the warmup is equally
    # warm for both, so the comparison isolates the prefix cache instead of
    # which run went second
    no_cache = None
    if args.shared_prefix:
        control = make_engine(prefix_cache=0)
        control_handles, control_wall = run_load(control, requests, args)
        csnap = control.metrics_snapshot()
        no_cache = {
            "ttft_ms_p50": round(csnap["ttft_ms_p50"], 3),
            "prefill_ms_p50": prefill_p50(control_handles),
            "decode_tok_s": round(
                sum(len(h.tokens) for h in control_handles if h is not None)
                / control_wall,
                3,
            ),
        }

    # spec-OFF control for the speculation A/B, same ordering discipline as
    # the prefix-cache control: it runs BEFORE the measured engine so both
    # are equally warm and the delta isolates the verify step itself
    no_spec = None
    if args.spec_k:
        control = make_engine(spec_k=0)
        control_handles, control_wall = run_load(control, requests, args)
        csnap = control.metrics_snapshot()
        no_spec = {
            "decode_tok_s": round(
                sum(len(h.tokens) for h in control_handles if h is not None)
                / control_wall,
                3,
            ),
            "itl_ms_p50": round(csnap["itl_ms_p50"], 3),
        }

    # DEFUSED-tail control for the fused-sampling A/B (same ordering
    # discipline: runs before the measured engine so both are equally warm
    # and the delta isolates the extra dispatch + [S]-token round trip of
    # the split tail). Speculation off in the control — its comparison
    # partner is no_speculation (the fused plain-decode arm), not the
    # spec-on headline.
    no_fused = None
    if args.fused_tail_ab:
        control = make_engine(spec_k=0, fused_tail=False)
        control_handles, control_wall = run_load(control, requests, args)
        csnap = control.metrics_snapshot()
        no_fused = {
            "decode_tok_s": round(
                sum(len(h.tokens) for h in control_handles if h is not None)
                / control_wall,
                3,
            ),
            "itl_ms_p50": round(csnap["itl_ms_p50"], 3),
            "itl_ms_decode_only_p99": round(csnap["itl_decode_ms_p99"], 3),
        }

    # tracing-overhead A/B: alternate OFF/ON arms on the same workload and
    # take each arm's best run — the stable statistic on a noisy shared box
    # (the guard holds the committed overhead to <=2%, far below run-to-run
    # noise of a single sample). Runs BEFORE the measured engine, same
    # warm-everything discipline as the other controls.
    obs_ab = None
    if args.obs_ab:
        best = {"off": 0.0, "on": 0.0}
        for _ in range(max(1, args.obs_ab_repeats)):
            for arm in ("off", "on"):
                e = make_engine(trace=(arm == "on"))
                hs, w = run_load(e, requests, args)
                toks = sum(len(h.tokens) for h in hs if h is not None)
                best[arm] = max(best[arm], toks / w)
        overhead = (
            max(0.0, (best["off"] - best["on"]) / best["off"])
            if best["off"] else 0.0
        )
        obs_ab = {
            "decode_tok_s_trace_off": round(best["off"], 3),
            "decode_tok_s_trace_on": round(best["on"], 3),
            "overhead_frac": round(overhead, 4),
            "repeats": max(1, args.obs_ab_repeats),
        }

    engine = make_engine(chaos_plan(args) if args.chaos else None)
    handles, wall = run_load(engine, requests, args)
    # one Perfetto trace artifact per run: the measured engine's span ring
    # (request lifecycle trees + per-tick engine phases), loadable at
    # ui.perfetto.dev — docs/OBSERVABILITY.md shows how to read it
    trace_path = args.trace_out or (
        args.out[:-5] if args.out.endswith(".json") else args.out
    ) + ".trace.json"
    engine.tracer.write_chrome_trace(trace_path)

    terminal = ("done", "cancelled", "expired", "rejected", "failed")
    # dropped = HUNG (no terminal event) — the acceptance bar's "no in-flight
    # request hangs". Chaos-faulted requests fail retryably; they are errors,
    # not drops.
    dropped = sum(1 for h in handles if h is None or h.status not in terminal)
    errors = sum(1 for h in handles if h is not None and h.status == "failed")
    # non-chaos runs demand every request COMPLETE; chaos runs only demand
    # terminal states (faulted requests fail retryably by design)
    incomplete = sum(1 for h in handles if h is None or h.status != "done")
    mismatches = 0
    if refs is not None:
        # byte-identical contract, measured over requests a fault did NOT
        # touch: every completed request must match single-request
        # generate() even when its neighbors were faulted mid-run
        mismatches = sum(
            1
            for h, ref in zip(handles, refs)
            if h is not None and h.status == "done" and h.tokens != ref
        )
    tokens_out = sum(len(h.tokens) for h in handles if h is not None)
    snap = engine.metrics_snapshot()
    shed = snap["shed_infeasible"] + snap["rejected_draining"]

    import jax

    prefix_total = snap["prefix_hits"] + snap["prefix_misses"]
    artifact = {
        "metric": f"serve_tokens_per_sec_{args.model}",
        "value": round(tokens_out / wall, 3),
        "unit": "tokens/s",
        "model": args.model,
        "mode": args.mode,
        "workload": "shared_prefix" if args.shared_prefix else "mixed",
        # the frozen traffic spec this run replayed (--workload file or the
        # CLI-derived inline spec) — TUNE artifacts carry the same hash, so
        # "tuned under this workload" is checkable, not asserted
        "workload_spec": wl_name,
        "workload_hash": wl_hash,
        "slots": args.slots,
        "requests": args.requests,
        "concurrency": min(args.concurrency, args.requests),
        "max_new_tokens": args.max_new_tokens,
        "wall_s": round(wall, 3),
        # decode_tok_s is the regression guard's key (scripts/
        # serve_bench_guard.py); kept alongside the legacy "value" alias
        "decode_tok_s": round(tokens_out / wall, 3),
        "prefill_chunk": engine.prefill_chunk,
        "prefix_cache": {
            "hits": snap["prefix_hits"],
            "misses": snap["prefix_misses"],
            "hit_rate": round(snap["prefix_hits"] / prefix_total, 4)
            if prefix_total
            else 0.0,
        },
        "prefill_ms_hit_p50": prefill_p50(handles, lambda h: h.prefix_hit_tokens > 0),
        "prefill_ms_miss_p50": prefill_p50(handles, lambda h: h.prefix_hit_tokens == 0),
        "no_prefix_cache": no_cache,
        # paged-KV + speculation evidence (ISSUE 6): layout, pool pressure,
        # and the draft-and-verify acceptance economics, plus the spec-OFF
        # control for the same workload
        "kv_layout": engine.kv_layout,
        "page_size": engine.page_size if engine.kv_layout == "paged" else 0,
        "page_faults": snap["page_faults"],
        "pages_reclaimed": snap["pages_reclaimed"],
        "preemptions": snap["preemptions"],
        "page_pool_util": round(
            snap["page_pool_peak"]
            / max(1, engine.slots.pool.n_pages - 1), 4
        )
        if engine.kv_layout == "paged"
        else 0.0,
        "cow_copies": snap["cow_copies"],
        "draft_k": engine.draft_k,
        "acceptance_rate": round(snap["acceptance_rate"], 4),
        "spec_ticks": snap["spec_ticks"],
        "no_speculation": no_spec,
        # fused-sampling-tail evidence (PR 11): is the measured engine's
        # sampling inside the single decode program, and the defused
        # control (None unless --fused-tail-ab measured it)
        "fused_tail": bool(engine.fused_tail),
        "kernel_paged_attention": bool(snap["kernel_paged_attention"]),
        "no_fused_tail": no_fused,
        # observability evidence (ISSUE 7): the tracing-cost A/B (None
        # unless --obs-ab measured it) and the Perfetto span artifact every
        # run saves next to the JSON
        "obs_overhead": obs_ab,
        "trace_file": Path(trace_path).name,
        "obs_spans": len(engine.tracer),
        "platform": {
            "backend": jax.default_backend(),
            "device": getattr(jax.devices()[0], "device_kind", "unknown"),
        },
        "ttft_ms": {q: round(snap[f"ttft_ms_{q}"], 3) for q in ("p50", "p90", "p99")},
        "itl_ms": {q: round(snap[f"itl_ms_{q}"], 3) for q in ("p50", "p90", "p99")},
        "itl_ms_decode_only": {
            q: round(snap[f"itl_decode_ms_{q}"], 3) for q in ("p50", "p90", "p99")
        },
        "peak_occupancy": snap["peak_occupancy"],
        "peak_queue_depth": snap["peak_queue_depth"],
        "completed": snap["completed"],
        "rejected": snap["rejected_queue_full"] + snap["rejected_invalid"],
        "dropped": dropped,
        "verified": refs is not None,
        "mismatches": mismatches,
        "chaos": bool(args.chaos),
        "errors": errors,
        "error_rate": round(errors / max(1, args.requests), 4),
        "shed": shed,
        "shed_rate": round(shed / max(1, args.requests), 4),
        "drain_latency_s": round(engine.drain_latency_s or 0.0, 4),
        "tick_faults": snap["tick_faults"],
        "poisoned_slots": snap["poisoned_slots"],
        "breaker_trips": snap["breaker_trips"],
        "final_state": snap["state"],
        "measured_at_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    Path(args.out).write_text(json.dumps(artifact, indent=2) + "\n")
    print(json.dumps(artifact))
    if dropped or mismatches or (incomplete and not args.chaos):
        raise SystemExit(
            f"LOAD RUN FAILED: {dropped} dropped (hung), {incomplete} "
            f"incomplete, {mismatches} garbled (vs generate() baseline) of "
            f"{args.requests}"
        )
    if args.chaos and artifact["final_state"] != "stopped":
        raise SystemExit(
            f"CHAOS RUN FAILED: engine did not drain (state "
            f"{artifact['final_state']})"
        )
    return artifact


if __name__ == "__main__":
    main()
