#!/usr/bin/env python
"""Training-fleet coordinator: control plane + worker supervisor.

Starts the :class:`FleetCoordinator` HTTP control plane, spawns N worker
processes (scripts/train_fleet_worker.py), and supervises them: a worker
that exits is respawned with ``--resume`` after a jittered exponential
backoff (the same ``backoff_delay`` the in-process Supervisor uses —
simultaneous respawns after a correlated fault would otherwise stampede
the join endpoint). Worker death DETECTION is not this loop's job: the
coordinator's heartbeat sweeper cordons silent workers and re-layouts the
shard assignment among survivors; this loop only brings capacity back.

The coordinator process itself performs no jax computation, so it stays
responsive while workers grind through compiles.

Artifacts (all optional flags):
  --bench-out    BENCH_fleet_train.json (re-layout downtime, replayed steps)
  --trace-out    fleet-stitched Perfetto trace for --trace-step
  --status-out   full coordinator status (loss history, relayouts, events)
  --losses-out   loss history alone — feed a later run's --control-losses
  --control-losses  reference loss history; sets bitwise_rejoin in bench

Examples:
  python scripts/train_coordinator.py --workers 3 --steps 12
  python scripts/train_coordinator.py --workers 3 --steps 20 \
      --chaos w1=sigkill@7 --respawn 2 --bench-out BENCH_fleet_train.json
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from zero_transformer_tpu.obs.fleet import write_trace  # noqa: E402
from zero_transformer_tpu.resilience.supervisor import backoff_delay  # noqa: E402
from zero_transformer_tpu.training.fleet import (  # noqa: E402
    CoordinatorServer,
    FleetCoordinator,
)

WORKER_SCRIPT = os.path.join(_REPO, "scripts", "train_fleet_worker.py")


def parse_chaos(specs):
    """``wid=kind@step[:dur]`` -> {wid: [spec, ...]} (validated lazily by
    the worker's own parser, which owns the Fault grammar)."""
    out = {}
    for s in specs:
        wid, sep, spec = s.partition("=")
        if not sep:
            raise SystemExit(f"bad --chaos {s!r} (want wid=kind@step[:dur])")
        out.setdefault(wid, []).append(spec)
    return out


class WorkerProc:
    """One supervised worker slot: the process handle plus respawn state."""

    def __init__(self, wid, chaos_specs, log_path=None):
        self.wid = wid
        self.chaos_specs = chaos_specs
        self.log_path = log_path
        self.proc = None
        self.attempts = 0  # spawns so far
        self.next_spawn_t = 0.0  # monotonic gate for backoff
        self.exits = []

    def spawn(self, url, args, resume):
        cmd = [
            sys.executable, WORKER_SCRIPT,
            "--coordinator", url,
            "--id", self.wid,
            "--hb-interval", str(args.hb_interval),
        ]
        if args.ckpt_dir:
            cmd += ["--ckpt-dir", args.ckpt_dir]
        if resume:
            cmd += ["--resume"]
        # chaos only on the first life: a respawned worker must not re-kill
        # itself at the same step counter and livelock the run
        if self.attempts == 0:
            for spec in self.chaos_specs:
                cmd += ["--chaos", spec]
        if self.log_path:
            out = open(self.log_path, "ab")
        else:
            out = subprocess.DEVNULL
        self.proc = subprocess.Popen(
            cmd, stdout=out, stderr=subprocess.STDOUT, cwd=_REPO
        )
        if self.log_path:
            out.close()  # child holds its own fd
        self.attempts += 1
        return self.proc


def kill_all(slots):
    # SIGKILL, not SIGTERM: a SIGSTOPped worker never delivers SIGTERM
    for s in slots:
        if s.proc is not None and s.proc.poll() is None:
            try:
                s.proc.send_signal(signal.SIGKILL)
            except OSError:
                pass  # already reaped
    for s in slots:
        if s.proc is not None:
            try:
                s.proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                print(f"coordinator: worker {s.wid} unkillable?", file=sys.stderr)


def losses_bitwise_equal(ours, reference):
    if len(ours) != len(reference):
        return False
    return all(
        s == rs and l == rl for (s, l), (rs, rl) in zip(ours, reference)
    )


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter
    )
    ap.add_argument("--workers", type=int, default=3)
    ap.add_argument("--shards", type=int, default=4)
    ap.add_argument("--steps", type=int, default=12)
    ap.add_argument("--per-shard-batch", type=int, default=2)
    ap.add_argument("--seq-len", type=int, default=16)
    ap.add_argument("--vocab", type=int, default=64)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--lr", type=float, default=1e-3)
    ap.add_argument("--snapshot-every", type=int, default=5)
    ap.add_argument("--min-workers", type=int, default=1)
    ap.add_argument("--ckpt-dir", default=None)
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--hb-timeout", type=float, default=0.75)
    ap.add_argument("--hb-interval", type=float, default=0.2)
    ap.add_argument("--eject-threshold", type=int, default=3)
    ap.add_argument(
        "--chaos", action="append", default=[], metavar="WID=KIND@STEP[:DUR]",
        help="inject a fault into one worker (repeatable)",
    )
    ap.add_argument(
        "--respawn", type=int, default=0, metavar="N",
        help="respawn a dead worker up to N times (with jittered backoff)",
    )
    ap.add_argument("--backoff-base", type=float, default=0.05)
    ap.add_argument("--backoff-max", type=float, default=1.0)
    ap.add_argument("--backoff-jitter", type=float, default=0.1)
    ap.add_argument(
        "--no-spawn", action="store_true",
        help="serve only; workers are started externally (prints COORD_URL=)",
    )
    ap.add_argument("--timeout", type=float, default=300.0)
    ap.add_argument("--worker-logs", default=None, metavar="DIR")
    ap.add_argument("--bench-out", default=None)
    ap.add_argument("--trace-out", default=None)
    ap.add_argument("--trace-step", type=int, default=None)
    ap.add_argument("--status-out", default=None)
    ap.add_argument("--losses-out", default=None)
    ap.add_argument("--control-losses", default=None)
    args = ap.parse_args(argv)

    chaos_by_wid = parse_chaos(args.chaos)
    coord = FleetCoordinator(
        n_shards=args.shards,
        per_shard_batch=args.per_shard_batch,
        seq_len=args.seq_len,
        vocab=args.vocab,
        seed=args.seed,
        total_steps=args.steps,
        snapshot_every=args.snapshot_every,
        min_workers=args.min_workers,
        lr=args.lr,
        ckpt_dir=args.ckpt_dir,
        hb_timeout_s=args.hb_timeout,
        eject_threshold=args.eject_threshold,
    )
    server = CoordinatorServer(coord, port=args.port).start()
    print(f"COORD_URL={server.url}", flush=True)

    if args.worker_logs:
        os.makedirs(args.worker_logs, exist_ok=True)

    slots = []
    if not args.no_spawn:
        for i in range(args.workers):
            wid = f"w{i}"
            log = (
                os.path.join(args.worker_logs, f"{wid}.log")
                if args.worker_logs else None
            )
            slot = WorkerProc(wid, chaos_by_wid.get(wid, ()), log_path=log)
            slot.spawn(server.url, args, resume=False)
            slots.append(slot)

    deadline = time.monotonic() + args.timeout
    timed_out = False
    try:
        while not coord.done.wait(0.1):
            now = time.monotonic()
            if now > deadline:
                timed_out = True
                print("coordinator: wall-clock timeout", file=sys.stderr)
                coord.stop()
                break
            for s in slots:
                if s.proc is not None and s.proc.poll() is not None:
                    rc = s.proc.returncode
                    s.exits.append(rc)
                    s.proc = None
                    respawns_used = s.attempts - 1
                    if respawns_used < args.respawn and not coord.stopping:
                        delay = backoff_delay(
                            args.backoff_base, args.backoff_max,
                            respawns_used + 1, jitter=args.backoff_jitter,
                        )
                        s.next_spawn_t = now + delay
                        print(
                            f"coordinator: {s.wid} exited rc={rc}; "
                            f"respawn in {delay:.3f}s",
                            flush=True,
                        )
                    else:
                        s.next_spawn_t = float("inf")
                elif s.proc is None and now >= s.next_spawn_t:
                    s.spawn(server.url, args, resume=bool(args.ckpt_dir))
                    print(
                        f"coordinator: respawned {s.wid} "
                        f"(attempt {s.attempts})",
                        flush=True,
                    )
    finally:
        # give cleanly-finishing workers a moment to see "stop" and exit,
        # then reap the rest (hung/stopped ones included) with SIGKILL
        settle = time.monotonic() + 3.0
        while time.monotonic() < settle and any(
            s.proc is not None and s.proc.poll() is None for s in slots
        ):
            time.sleep(0.05)
        kill_all(slots)
        server.close()

    status = coord.status()
    losses = status["loss_history"]
    bitwise = None
    if args.control_losses:
        with open(args.control_losses) as f:
            bitwise = losses_bitwise_equal(losses, json.load(f))
        print(f"BITWISE_REJOIN={bitwise}", flush=True)

    if args.losses_out:
        with open(args.losses_out, "w") as f:
            json.dump(losses, f)
    if args.status_out:
        with open(args.status_out, "w") as f:
            json.dump(status, f, indent=1)
    if args.bench_out:
        doc = coord.bench(chaos=args.chaos, bitwise_rejoin=bitwise)
        with open(args.bench_out, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
        print(
            f"BENCH relayouts={len(doc['relayouts'])} "
            f"replayed_steps={doc['replayed_steps']} "
            f"downtime_s={doc['relayout_downtime_s']:.3f}",
            flush=True,
        )
    if args.trace_out:
        step = args.trace_step
        if step is None:
            step = status["committed"]
        write_trace(args.trace_out, coord.trace_doc(step))
        print(f"TRACE step={step} -> {args.trace_out}", flush=True)

    done = status["committed"] + 1
    print(f"COORD_OK steps={done} relayouts={len(status['relayouts'])}", flush=True)
    if timed_out:
        return 2
    return 0 if done >= args.steps else 1


if __name__ == "__main__":
    sys.exit(main())
