#!/usr/bin/env python
"""Stub-fleet stitched-trace smoke (``make obs``, PR 15).

Spins up a router over 2 paced stub replicas (stdlib-only — no jax, no
model), drives a handful of streams, and then verifies the fleet
observability plane end to end, programmatically:

- ONE merged Perfetto trace per request (router relay spans + each stub's
  request tree on its own process track, clock-offset corrected): >= 95%
  wall-latency coverage, zero orphan spans, hop ordering intact;
- the router's /metrics exposes ``fleet_*`` rollups whose per-role sums
  equal the per-replica scrapes they fold;
- ``/slo`` answers with the declared objectives' burn rates and an ``ok``
  verdict on this healthy run;
- every terminal event carried a complete cost ledger (schema-pinned).

Writes the merged trace artifact to ``--out`` (default
``/tmp/_fleet_obs_smoke.trace.json``) and exits nonzero on any failure.
"""
from __future__ import annotations

import argparse
import importlib.util
import json
import sys
import threading
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from zero_transformer_tpu.obs.fleet import (  # noqa: E402
    FLEET_OBS_REQUIRED_KEYS,
    parse_exposition,
    request_ids_in,
    verify_stitched,
)
from zero_transformer_tpu.serving.router import RouterServer  # noqa: E402


def _load_stubs():
    spec = importlib.util.spec_from_file_location(
        "serve_router", REPO / "scripts" / "serve_router.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _sse(port: int, body: dict):
    import http.client

    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
    try:
        conn.request("POST", "/generate", json.dumps(body),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        done = None
        while True:
            line = resp.readline()
            if not line:
                break
            if not line.startswith(b"data: "):
                continue
            event = json.loads(line[6:])
            if event.get("done"):
                done = event
                break
        return done
    finally:
        conn.close()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--out", default="/tmp/_fleet_obs_smoke.trace.json")
    p.add_argument("--streams", type=int, default=4)
    p.add_argument("--itl-ms", type=float, default=5.0)
    args = p.parse_args(argv)

    serve_router = _load_stubs()
    stubs = [
        serve_router.StubReplica(itl_s=args.itl_ms / 1e3, slots=2).start()
        for _ in range(2)
    ]
    router = RouterServer(
        [s.url for s in stubs], probe_interval=0.05, chunk_tokens=4,
        metrics_scrape_interval=0.1, slo_eval_interval=0.1,
    )
    router.start()
    failures: list = []
    try:
        if not router.wait_ready(10.0):
            raise SystemExit("FLEET OBS SMOKE FAILED: fleet never ready")

        dones: list = []
        lock = threading.Lock()

        def client(i):
            done = _sse(router.port, {
                "tokens": [5 + i] * 4, "max_new_tokens": 8,
            })
            with lock:
                dones.append(done)

        threads = [
            threading.Thread(target=client, args=(i,), daemon=True)
            for i in range(args.streams)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        if len(dones) != args.streams or any(
            d is None or d.get("status") != "done" for d in dones
        ):
            failures.append(f"streams did not all finish: {dones}")

        # --- ledger schema on every terminal event
        for d in dones:
            missing = FLEET_OBS_REQUIRED_KEYS["ledger"] - set(
                (d or {}).get("ledger") or {}
            )
            if missing:
                failures.append(f"ledger missing keys: {sorted(missing)}")
                break

        # --- merged trace, one route root per stream, verified
        doc = router.merged_trace()
        rids = request_ids_in(doc)
        if len(rids) != args.streams:
            failures.append(
                f"expected {args.streams} stitched requests, got {len(rids)}"
            )
        worst = 1.0
        for rid in rids:
            check = verify_stitched(doc, rid, slack_s=0.05)
            worst = min(worst, check["coverage"])
            if check["orphans"] or not check["hops_ordered"]:
                failures.append(f"stitch check failed for {rid}: {check}")
        if worst < 0.95:
            failures.append(f"stitched coverage {worst:.3f} < 0.95")
        Path(args.out).write_text(json.dumps(doc) + "\n")

        # --- fleet rollups: per-role sums equal the per-replica scrapes
        router.scrape_fleet_metrics()
        import http.client

        conn = http.client.HTTPConnection("127.0.0.1", router.port, timeout=10)
        conn.request("GET", "/metrics?format=prometheus")
        text = conn.getresponse().read().decode()
        conn.close()
        fams = parse_exposition(text)
        fleet_tokens = sum(
            v for labels, v in fams.get(
                "fleet_serve_tokens_out_total", {"samples": []}
            )["samples"] if "replica" not in labels
        )
        stub_tokens = sum(s.tokens_emitted for s in stubs)
        if fleet_tokens != stub_tokens:
            failures.append(
                f"fleet rollup {fleet_tokens} != per-replica sum {stub_tokens}"
            )

        # --- /slo verdict on a healthy run
        router.evaluate_slo()
        conn = http.client.HTTPConnection("127.0.0.1", router.port, timeout=10)
        conn.request("GET", "/slo")
        slo = json.loads(conn.getresponse().read())
        conn.close()
        missing = FLEET_OBS_REQUIRED_KEYS["slo"] - set(slo)
        if missing:
            failures.append(f"/slo missing keys: {sorted(missing)}")
        if slo.get("verdict") != "ok":
            failures.append(f"healthy run's SLO verdict: {slo.get('verdict')}")
        if router.stats["dropped_streams"]:
            failures.append("dropped streams during the smoke")
    finally:
        router.stop()
        for s in stubs:
            s.stop()
    if failures:
        print("FLEET OBS SMOKE FAILED: " + "; ".join(failures))
        return 1
    print(
        f"fleet obs smoke ok: {args.streams} streams stitched "
        f"(min coverage {worst:.3f}), rollups pinned, SLO verdict ok -> "
        f"{args.out}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
