#!/usr/bin/env bash
# TPU VM bring-up: run once on every host of a pod slice.
# Reference analogue: prepareTPUVM.sh (jax[tpu] install + deps).
#
#   gcloud compute tpus tpu-vm ssh $TPU_NAME --zone $ZONE --worker=all \
#     --command="bash -s" < scripts/setup_tpu_vm.sh
set -euo pipefail

python3 -m pip install -U pip
# TPU jax wheel rides libtpu from the special index
python3 -m pip install -U "jax[tpu]" \
  -f https://storage.googleapis.com/jax-releases/libtpu_releases.html
# deps inlined (mirrors requirements.txt): under the piped invocation above
# the repo is not on the remote host yet, so no file paths can be read
python3 -m pip install flax optax orbax-checkpoint chex einops numpy pyyaml pytest
# optional extras used when configured (wandb logging, gs:// data/ckpts,
# HF-streaming source, tokenizer for serve/eval-on-text)
python3 -m pip install wandb gcsfs datasets transformers || true

python3 - <<'PY'
import jax
print(f"devices={jax.device_count()} local={jax.local_device_count()} "
      f"process={jax.process_index()}/{jax.process_count()}")
PY
