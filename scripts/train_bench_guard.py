#!/usr/bin/env python
"""Train-step-bench regression guard: fresh BENCH_step.json vs committed.

``make train-bench`` snapshots the committed artifact before the run, then
calls this with (baseline, fresh). Checks, in the style of
``serve_bench_guard.py``:

- **parity is platform-independent**: the fresh artifact's overlap-on /
  overlap-off gradient parity must be BITWISE — a fast wrong step must
  never pass the lane, anywhere;
- on MATCHING hardware (platform + device kind):
  - ``overlap_on.step_ms`` regressing > tolerance fails;
  - the headline exposed-comm ``value`` (reduction, ×) shrinking past the
    tolerance fails when both artifacts carry the same provenance
    (measured vs projected numbers are never compared to each other).

Skips exit 0 with a reason — the guard catches real regressions on
comparable runs, not noise on incomparable ones.

Usage: train_bench_guard.py <baseline.json> <fresh.json> [--tolerance 0.15]
"""
from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent))
import bench_common  # noqa: E402  (shared skip-or-grade logic, ISSUE 14)

TOLERANCE = 0.15


def compare(baseline: dict, fresh: dict, tolerance: float = TOLERANCE):
    """Returns (ok, messages). ok=True covers both pass and skip."""
    msgs = []
    ok = True

    parity = fresh.get("parity", {})
    if not parity.get("bitwise"):
        return False, [
            "REGRESSION: overlap-on/off gradient parity is no longer bitwise "
            f"(parity={parity}) — the overlapped step changed the math, not "
            "just the collective placement"
        ]
    msgs.append(f"ok: parity bitwise over {parity.get('steps')} step(s)")

    hw_ok, hw_reason = bench_common.hardware_gate(
        baseline, fresh, fields=("platform", "device_kind"),
        what="timing not comparable",
    )
    if not hw_ok:
        return ok, msgs + [hw_reason]

    base_ms = baseline.get("overlap_on", {}).get("step_ms", 0)
    fresh_ms = fresh.get("overlap_on", {}).get("step_ms", 0)
    if not fresh_ms:
        # a missing/zero measurement is a broken artifact, not a pass —
        # the parity gate above proved the run got far enough to measure
        return False, msgs + [
            f"REGRESSION: fresh artifact has no overlap_on.step_ms "
            f"({fresh.get('overlap_on')!r}) — bench did not complete"
        ]
    if base_ms and fresh_ms > base_ms * (1 + tolerance):
        ok = False
        msgs.append(
            f"REGRESSION: overlap_on.step_ms {fresh_ms:.1f} > "
            f"{(1 + tolerance) * 100:.0f}% of baseline {base_ms:.1f}"
        )
    else:
        msgs.append(
            f"ok: overlap_on.step_ms {fresh_ms:.1f} (baseline {base_ms:.1f})"
        )

    prov_ok, prov_reason = bench_common.provenance_gate(baseline, fresh)
    if prov_ok:
        base_red = baseline.get("value", 0)
        fresh_red = fresh.get("value", 0)
        if base_red and fresh_red < base_red * (1 - tolerance):
            ok = False
            msgs.append(
                f"REGRESSION: exposed-comm reduction {fresh_red:.2f}x < "
                f"{(1 - tolerance) * 100:.0f}% of baseline {base_red:.2f}x "
                f"({fresh.get('provenance')})"
            )
        else:
            msgs.append(
                f"ok: exposed-comm reduction {fresh_red:.2f}x "
                f"(baseline {base_red:.2f}x, {fresh.get('provenance')})"
            )
    else:
        msgs.append(prov_reason)
    return ok, msgs


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("baseline", help="committed BENCH_step.json snapshot")
    p.add_argument("fresh", help="artifact from the run under test")
    p.add_argument("--tolerance", type=float, default=TOLERANCE)
    args = p.parse_args(argv)
    baseline = json.loads(Path(args.baseline).read_text())
    fresh = json.loads(Path(args.fresh).read_text())
    ok, msgs = compare(baseline, fresh, args.tolerance)
    for m in msgs:
        print(f"train-bench-guard: {m}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
