#!/usr/bin/env python
"""Fleet-router entrypoint + the fleet test/bench harness processes.

Three modes in one script so the fleet pieces ship together:

- **router** (default): front N already-running replicas::

      python scripts/serve_router.py \\
          --replica http://127.0.0.1:8001 --replica http://127.0.0.1:8002

  Endpoints: ``POST /generate`` (prefix-aware routed, mid-stream failover),
  ``GET /healthz`` (fleet view), ``GET /metrics`` (JSON / Prometheus),
  ``POST /admin/reload`` (rolling fleet reload — drains one replica at a
  time through the router, reloads it via the replica's own
  ``/admin/reload``, waits READY, proceeds; ``dropped_streams == 0``).

- **--replica-worker**: a real single-replica serving process on the CPU
  ``test`` zoo model with random-init params (the fleet chaos tests SIGKILL
  these — the orchestration layer is what is under test, no checkpoint
  needed). Prints ``REPLICA_PORT=<n>`` once listening so a parent that
  passed ``--port 0`` can discover the bound port.

- **--stub**: a *paced* stub replica — answers the same HTTP surface
  (``/generate`` SSE, ``/healthz`` with the router's admission inputs,
  ``/admin/reload``) but "decodes" by emitting deterministic token ids at a
  fixed inter-token interval with a bounded slot count. This models a
  device-bound replica whose decode rate does not depend on this box's CPU:
  the loadgen's router-scaling sweep drives it to measure whether the
  ROUTER (relay + routing policy, the part that runs on this box) keeps up
  with N replicas' aggregate token rate. Token ids continue an arithmetic
  sequence in prompt length, so a resumed stream provably continues exactly
  where the dead replica stopped.
"""
from __future__ import annotations

import argparse
import json
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))


# --------------------------------------------------------------------- stub


class StubReplica:
    """Paced fake replica speaking the replica HTTP surface (stdlib-only,
    no jax import). Deterministic by construction:

    - ``/generate`` emits ``max_new_tokens`` SSE token events, one every
      ``itl_s`` seconds, ids ``token_base + prompt_len, token_base +
      prompt_len + 1, ...`` — a resumed request (prompt + generated-so-far)
      continues the same arithmetic sequence, so stream-continuity is
      assertable to the token.
    - ``slots`` bounds concurrent generations with a semaphore; excess
      requests wait (reported as ``queue_depth`` in ``/healthz``), which is
      what makes the router's least-loaded policy measurable.
    - ``die_after_tokens=k`` arms a one-shot mid-stream death: the FIRST
      stream to reach k emitted tokens is cut without a done event (the
      exact wire signature of a SIGKILLed replica).
    """

    def __init__(self, port: int = 0, itl_s: float = 0.002, slots: int = 2,
                 die_after_tokens: int | None = None,
                 fail_5xx_requests: int = 0,
                 backpressure_retry_after: float = 0.0,
                 reload_delay_s: float = 0.0, token_base: int = 1000):
        self.itl_s = itl_s
        self.n_slots = slots
        self.token_base = token_base
        self.reload_delay_s = reload_delay_s
        self._sem = threading.Semaphore(slots)
        self._lock = threading.Lock()
        self._die_after = die_after_tokens
        # fleet-obs surface (PR 15), stdlib-only like the rest of the stub:
        # a bounded per-request span list (the router's /admin/spans pull),
        # and fixed-bucket TTFT samples for the /metrics exposition the
        # router's aggregator folds
        self._spans: list = []  # dicts: track/name/t0/t1/attrs
        self._span_cap = 4096
        self._ttft_buckets = (0.005, 0.025, 0.1, 0.5, 2.0)
        self._ttft_counts = [0] * (len(self._ttft_buckets) + 1)
        self._ttft_sum = 0.0
        self._ttft_n = 0
        # pre-stream server errors: the first N /generate requests answer
        # 500 before any SSE bytes (a crashed handler, not a dead process)
        self._fail_5xx = fail_5xx_requests
        # when > 0: every /generate answers 503 + a Retry-After HEADER (the
        # replica wire format — the body has no retry_after field)
        self._backpressure_ra = backpressure_retry_after
        self.died = False
        self.state = "ready"
        self.requests = 0
        self.tokens_emitted = 0
        self.reloads = 0
        self.active = 0
        self.waiting = 0
        self.seen_request_ids: list = []
        self.seen_bodies: list = []
        self._born = time.monotonic()
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # noqa: A003
                pass

            def _json(self, code, obj, headers=None):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for name, value in (headers or {}).items():
                    self.send_header(name, value)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802
                path, _, query = self.path.partition("?")
                if path == "/admin/spans":
                    rid = ""
                    for part in query.split("&"):
                        if part.startswith("request_id="):
                            rid = part[len("request_id="):]
                    with outer._lock:
                        spans = [
                            s for s in outer._spans
                            if not rid or s["track"] == rid
                        ]
                    self._json(200, {
                        "request_id": rid,
                        "clock_monotonic": time.monotonic(),
                        "role": "mixed",
                        "spans": spans,
                        "spans_dropped": 0,
                    })
                    return
                if path == "/metrics":
                    body = outer._metrics_text().encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type",
                        "text/plain; version=0.0.4; charset=utf-8",
                    )
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if path != "/healthz":
                    self._json(404, {"error": "no route"})
                    return
                ok = outer.state == "ready"
                self._json(200 if ok else 503, {
                    "status": "ok" if ok else outer.state,
                    "state": outer.state,
                    "clock_monotonic": time.monotonic(),
                    "uptime_s": round(time.monotonic() - outer._born, 3),
                    "reloads": outer.reloads,
                    "breaker_open": False,
                    "slots": outer.n_slots,
                    "active": outer.active,
                    "prefilling": 0,
                    "queued": outer.waiting,
                    "itl_ewma_ms": outer.itl_s * 1e3,
                    "queue_depth": outer.waiting,
                    "active_slots": outer.active,
                    "free_pages": max(0, outer.n_slots - outer.active),
                })

            def do_POST(self):  # noqa: N802
                length = int(self.headers.get("Content-Length", 0))
                try:
                    req = json.loads(self.rfile.read(length) or b"{}")
                except ValueError:
                    self._json(400, {"error": "malformed JSON"})
                    return
                if self.path == "/admin/reload":
                    if outer.reload_delay_s:
                        time.sleep(outer.reload_delay_s)
                    with outer._lock:
                        outer.reloads += 1
                    self._json(200, {"reloaded": True,
                                     "reloads": outer.reloads,
                                     "state": outer.state})
                    return
                if self.path != "/generate":
                    self._json(404, {"error": "no route"})
                    return
                outer._generate(self, req)

        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def start(self) -> "StubReplica":
        self._thread.start()
        return self

    def stop(self) -> None:
        """Stop accepting connections (the 'process gone' signature for
        connect-level failover tests: subsequent connects are refused)."""
        self.state = "stopped"
        self._httpd.shutdown()
        self._httpd.server_close()

    def _add_span(self, track, name, t0, t1, attrs=None) -> None:
        with self._lock:
            if len(self._spans) >= self._span_cap:
                del self._spans[: self._span_cap // 4]
            self._spans.append({
                "track": str(track), "name": name, "t0": t0, "t1": t1,
                "attrs": attrs,
            })

    def _observe_ttft(self, ttft_s: float) -> None:
        with self._lock:
            i = len(self._ttft_buckets)
            for j, bound in enumerate(self._ttft_buckets):
                if ttft_s <= bound:
                    i = j
                    break
            self._ttft_counts[i] += 1
            self._ttft_sum += ttft_s
            self._ttft_n += 1

    def _metrics_text(self) -> str:
        """Minimal 0.0.4 exposition so the router's fleet aggregator (and
        its latency SLO objectives) have real families to fold — the same
        names the real replica exports."""
        with self._lock:
            counts = list(self._ttft_counts)
            total, s = self._ttft_n, self._ttft_sum
            tokens = self.tokens_emitted
            requests = self.requests
            active = self.active
            queued = self.waiting
        lines = [
            "# HELP serve_tokens_out_total Tokens emitted to clients",
            "# TYPE serve_tokens_out_total counter",
            f"serve_tokens_out_total {tokens}",
            "# HELP serve_submitted_total Requests submitted",
            "# TYPE serve_submitted_total counter",
            f"serve_submitted_total {requests}",
            "# HELP serve_queue_depth Requests waiting for a slot",
            "# TYPE serve_queue_depth gauge",
            f"serve_queue_depth {queued}",
            "# HELP serve_slot_occupancy Slots actively decoding",
            "# TYPE serve_slot_occupancy gauge",
            f"serve_slot_occupancy {active}",
            "# HELP serve_ttft_seconds Submit-to-first-token latency",
            "# TYPE serve_ttft_seconds histogram",
        ]
        cum = 0
        for bound, c in zip(self._ttft_buckets, counts):
            cum += c
            lines.append(f'serve_ttft_seconds_bucket{{le="{bound}"}} {cum}')
        lines.append(f'serve_ttft_seconds_bucket{{le="+Inf"}} {total}')
        lines.append(f"serve_ttft_seconds_sum {s:.6f}")
        lines.append(f"serve_ttft_seconds_count {total}")
        return "\n".join(lines) + "\n"

    def _generate(self, handler, req: dict) -> None:
        rid = handler.headers.get("X-Request-Id") or req.get("request_id")
        try:
            hop = int(handler.headers.get("X-Trace-Hop", ""))
        except (TypeError, ValueError):
            hop = None
        t_req = time.monotonic()
        with self._lock:
            self.requests += 1
            self.seen_request_ids.append(rid)
            self.seen_bodies.append(req)
            if self._fail_5xx > 0:
                self._fail_5xx -= 1
                handler._json(500, {"error": "injected server error",
                                    "request_id": rid})
                return
            if self._backpressure_ra > 0:
                handler._json(
                    503, {"error": "draining", "request_id": rid},
                    headers={"Retry-After": str(int(self._backpressure_ra))},
                )
                return
            self.waiting += 1
        self._sem.acquire()
        t_acq = time.monotonic()
        with self._lock:
            self.waiting -= 1
            self.active += 1

        def ledger(n_tokens: int, now: float) -> dict:
            return {
                "decode_ticks": n_tokens, "tokens_out": n_tokens,
                "prefill_chunks": 1, "migrations": 0,
                "queue_ms": round((t_acq - t_req) * 1e3, 3),
                "prefill_ms": 0.0,
                "decode_ms": round((now - t_acq) * 1e3, 3),
            }

        def emit_spans(now: float, n_tokens: int, outcome: str) -> None:
            if rid:
                attrs = {"outcome": outcome, "tokens": n_tokens}
                if hop is not None:
                    attrs["hop"] = hop
                self._add_span(rid, "request", t_req, now, attrs)
                self._add_span(rid, "queue", t_req, t_acq)
                self._add_span(rid, "decode", t_acq, now)

        try:
            prompt = req.get("tokens") or [0] * len(str(req.get("prompt", "x")))
            max_new = int(req.get("max_new_tokens", 8))
            first = self.token_base + len(prompt)
            ids = list(range(first, first + max_new))
            stream = req.get("stream", True)
            if not stream:
                with self._lock:
                    self.tokens_emitted += len(ids)
                now = time.monotonic()
                self._observe_ttft(t_acq - t_req + self.itl_s)
                emit_spans(now, len(ids), "done")
                handler._json(200, {
                    "status": "done", "tokens": ids,
                    "text": "".join(f"<{t}>" for t in ids),
                    "request_id": rid,
                    "ledger": ledger(len(ids), now),
                })
                return
            handler.send_response(200)
            handler.send_header("Content-Type", "text/event-stream")
            handler.end_headers()
            sent = []
            first_at = None
            for t in ids:
                time.sleep(self.itl_s)
                with self._lock:
                    armed = (
                        self._die_after is not None
                        and len(sent) >= self._die_after
                    )
                    if armed:
                        self._die_after = None
                        self.died = True
                if armed:
                    # mid-stream death: cut the connection with no done
                    # event — exactly what a SIGKILL looks like on the wire
                    try:
                        handler.connection.close()
                    except OSError:
                        pass
                    return
                event = {"token": t, "text": f"<{t}>"}
                try:
                    handler.wfile.write(
                        b"data: " + json.dumps(event).encode() + b"\n\n"
                    )
                    handler.wfile.flush()
                except (BrokenPipeError, ConnectionResetError, OSError):
                    return  # client (router) went away; stop decoding
                if first_at is None:
                    first_at = time.monotonic()
                    self._observe_ttft(first_at - t_req)
                sent.append(t)
                with self._lock:
                    self.tokens_emitted += 1
            with self._lock:
                # die_after_tokens == max_new_tokens: the death lands in
                # the gap between the LAST token and the done event
                armed = (
                    self._die_after is not None
                    and len(sent) >= self._die_after
                )
                if armed:
                    self._die_after = None
                    self.died = True
            if armed:
                try:
                    handler.connection.close()
                except OSError:
                    pass
                return
            now = time.monotonic()
            emit_spans(now, len(sent), "done")
            done = {"done": True, "status": "done",
                    "text": "".join(f"<{t}>" for t in sent),
                    "retryable": False, "request_id": rid,
                    "ledger": ledger(len(sent), now)}
            try:
                handler.wfile.write(
                    b"data: " + json.dumps(done).encode() + b"\n\n"
                )
                handler.wfile.flush()
            except (BrokenPipeError, ConnectionResetError, OSError):
                pass
        finally:
            with self._lock:
                self.active -= 1
            self._sem.release()


# ----------------------------------------------------------- replica worker


def run_replica_worker(args) -> None:
    """A real single-replica serving process on the test zoo model —
    the SIGKILL target of the fleet chaos tests."""
    import os

    if os.environ.get("JAX_PLATFORMS"):
        import jax

        try:
            jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
        except RuntimeError:
            pass
    import jax
    import jax.numpy as jnp

    from zero_transformer_tpu.config import model_config
    from zero_transformer_tpu.inference.sampling import SamplingConfig
    from zero_transformer_tpu.models import Transformer
    from zero_transformer_tpu.serving import ServingEngine, ServingServer

    cfg = model_config(args.model, dropout=0.0, compute_dtype="float32")
    params = Transformer(cfg).init(
        jax.random.PRNGKey(args.init_seed), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    sampling = SamplingConfig(
        temperature=args.temperature, top_k=args.top_k, greedy=args.greedy
    )
    engine = ServingEngine(
        cfg, params, n_slots=args.slots,
        cache_len=args.cache_len or cfg.max_seq_len, sampling=sampling,
        prefill_chunk=args.prefill_chunk,
        prefix_cache_chunks=args.prefix_cache if args.prefill_chunk else 0,
        kv_layout="paged" if args.prefill_chunk else "slab",
        page_size=args.page_size,
        role=args.role,
    )

    class _TokenTokenizer:
        eos_token_id = None

        def encode(self, text):
            return [1 + (b % (cfg.vocab_size - 1)) for b in text.encode()]

        def decode(self, ids, **kw):
            return "".join(f"<{t}>" for t in ids)

        def convert_ids_to_tokens(self, ids):
            return [f"<{t}>" for t in ids]

        def convert_tokens_to_string(self, toks):
            return "".join(toks)

    server = ServingServer(engine, _TokenTokenizer(), port=args.port)
    server.install_signal_handlers(drain_deadline_s=args.drain_deadline)
    server.start_scheduler()
    # the parent (test harness) reads this line to learn the bound port
    print(f"REPLICA_PORT={server.port}", flush=True)
    server._httpd.serve_forever()


def run_stub(args) -> None:
    stub = StubReplica(
        port=args.port, itl_s=args.itl_ms / 1e3, slots=args.slots,
        die_after_tokens=args.die_after if args.die_after >= 0 else None,
    ).start()
    print(f"STUB_PORT={stub.port}", flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        stub.stop()


# ------------------------------------------------------------------- router


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--replica", action="append", default=[],
                   help="replica base URL (repeatable): http://host:port")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8080)
    p.add_argument("--probe-interval", type=float, default=0.25,
                   help="seconds between /healthz probes per replica")
    p.add_argument("--probe-timeout", type=float, default=1.0)
    p.add_argument("--eject-threshold", type=int, default=3,
                   help="consecutive probe failures before ejection")
    p.add_argument("--backoff-base", type=float, default=0.5,
                   help="first re-probe backoff after ejection (doubles up "
                        "to --backoff-max)")
    p.add_argument("--backoff-max", type=float, default=8.0)
    p.add_argument("--chunk-tokens", type=int, default=8,
                   help="prefix-affinity granularity; match the replicas' "
                        "--prefill-chunk so affinity aligns with their "
                        "prefix caches")
    p.add_argument("--max-attempts", type=int, default=3,
                   help="replica dispatch attempts per request (failover "
                        "budget)")
    p.add_argument("--connect-timeout", type=float, default=2.0)
    p.add_argument("--stream-timeout", type=float, default=30.0,
                   help="max seconds between SSE events before the replica "
                        "is considered dead mid-stream")
    p.add_argument("--admin-token", default=None)
    p.add_argument("--obs-dir", default=None,
                   help="flight-recorder dumps (replica ejections) + traces")
    p.add_argument("--slo", default=None, metavar="SPEC_JSON",
                   help="SLO objectives config (JSON list — see "
                        "configs/slo_default.json); 'off' disables the SLO "
                        "engine; default: the built-in objectives")
    p.add_argument("--metrics-scrape-interval", type=float, default=1.0,
                   help="seconds between per-replica /metrics scrapes "
                        "folded into the router's fleet_* rollups "
                        "(0 disables aggregation + SLO evaluation)")
    p.add_argument("--disaggregate", default="auto",
                   choices=("auto", "off"),
                   help="split requests prefill/decode by phase whenever the "
                        "fleet advertises both roles on /healthz (auto), or "
                        "force the classic single-replica path (off)")
    p.add_argument("--no-migrate-drain", action="store_true",
                   help="rolling reload: wait out in-flight generations "
                        "instead of migrating them (the pre-PR12 behavior)")
    p.add_argument("--role", default="mixed",
                   choices=("mixed", "prefill", "decode"),
                   help="replica-worker mode: the engine role "
                        "(see serve --role)")
    # harness modes (testing / benching):
    p.add_argument("--replica-worker", action="store_true",
                   help=argparse.SUPPRESS)
    p.add_argument("--stub", action="store_true", help=argparse.SUPPRESS)
    p.add_argument("--model", default="test", help=argparse.SUPPRESS)
    p.add_argument("--slots", type=int, default=2, help=argparse.SUPPRESS)
    p.add_argument("--cache-len", type=int, default=None,
                   help=argparse.SUPPRESS)
    p.add_argument("--prefill-chunk", type=int, default=8,
                   help=argparse.SUPPRESS)
    p.add_argument("--prefix-cache", type=int, default=64,
                   help=argparse.SUPPRESS)
    p.add_argument("--page-size", type=int, default=4, help=argparse.SUPPRESS)
    p.add_argument("--temperature", type=float, default=0.9,
                   help=argparse.SUPPRESS)
    p.add_argument("--top-k", type=int, default=20, help=argparse.SUPPRESS)
    p.add_argument("--greedy", action="store_true", help=argparse.SUPPRESS)
    p.add_argument("--init-seed", type=int, default=0, help=argparse.SUPPRESS)
    p.add_argument("--drain-deadline", type=float, default=10.0,
                   help=argparse.SUPPRESS)
    p.add_argument("--itl-ms", type=float, default=2.0, help=argparse.SUPPRESS)
    p.add_argument("--die-after", type=int, default=-1,
                   help=argparse.SUPPRESS)
    args = p.parse_args(argv)

    if args.replica_worker:
        run_replica_worker(args)
        return
    if args.stub:
        run_stub(args)
        return
    if not args.replica:
        p.error("router mode needs at least one --replica URL")
    from zero_transformer_tpu.serving.router import run_router

    slo = None  # None -> the built-in default objectives
    if args.slo == "off":
        slo = ()
    elif args.slo:
        slo = json.loads(Path(args.slo).read_text())

    run_router(
        args.replica, host=args.host, port=args.port,
        probe_interval=args.probe_interval, probe_timeout=args.probe_timeout,
        eject_threshold=args.eject_threshold,
        backoff_base_s=args.backoff_base, backoff_max_s=args.backoff_max,
        chunk_tokens=args.chunk_tokens, max_attempts=args.max_attempts,
        connect_timeout=args.connect_timeout,
        stream_timeout=args.stream_timeout, admin_token=args.admin_token,
        obs_dir=args.obs_dir,
        disaggregate=args.disaggregate,
        migrate_drain=not args.no_migrate_drain,
        slo=slo,
        metrics_scrape_interval=args.metrics_scrape_interval,
    )


if __name__ == "__main__":
    main()
