#!/usr/bin/env python
"""Shared bench-artifact provenance logic: the platform-match /
skip-or-grade rules both regression guards apply, in ONE place.

Extracted from ``serve_bench_guard.py`` and ``train_bench_guard.py``
(ISSUE 14): the two copies of "grade perf only on matching hardware, skip
loudly otherwise" had drifted across the router/disagg compare functions.
The autotuner reuses the same gate for its committed TUNE artifacts:
``train.py --tuned`` / ``serve.py --tuned`` refuse an artifact whose
platform/model/workload does not match the current run, exactly the
BENCH honesty discipline.

Pure stdlib (argparse-free, jax imported lazily only by
``platform_block``) so the guards stay cheap to exec.
"""
from __future__ import annotations

import json
from pathlib import Path
from typing import Any, Dict, Optional, Sequence, Tuple


def load_artifact(path) -> Dict[str, Any]:
    return json.loads(Path(path).read_text())


def platform_block() -> Dict[str, Any]:
    """The current process' platform block, in the shape every TUNE
    artifact embeds. Includes ``device_count``: a knob ranking measured on
    8 virtual devices is NOT the same platform as 1 real device, and the
    --tuned gate must be able to tell (dict equality covers it). Imports
    jax lazily — guard scripts comparing two JSON files never pay backend
    init."""
    import jax

    return {
        "backend": jax.default_backend(),
        "device": getattr(jax.devices()[0], "device_kind", "unknown"),
        "device_count": jax.device_count(),
    }


def hardware_gate(
    baseline: Dict[str, Any],
    fresh: Dict[str, Any],
    fields: Sequence[str] = ("platform",),
    what: str = "not comparable",
) -> Tuple[bool, Optional[str]]:
    """Skip-or-grade on hardware identity: (True, None) when every
    ``fields`` entry is present in both artifacts and equal; otherwise
    (False, "SKIP: ..."). A skip is a PASS for a guard — its job is
    catching real regressions on comparable runs, not adding noise on
    incomparable ones."""
    base_hw = tuple(baseline.get(f) for f in fields)
    fresh_hw = tuple(fresh.get(f) for f in fields)
    # `not v` (not just None): an empty platform block is as unknown as a
    # missing one — two empty blocks comparing equal must not grade perf
    if any(not v for v in base_hw + fresh_hw):
        return False, (
            f"SKIP: baseline or fresh artifact lacks {'/'.join(fields)}"
        )
    if base_hw != fresh_hw:
        b = base_hw[0] if len(fields) == 1 else base_hw
        f = fresh_hw[0] if len(fields) == 1 else fresh_hw
        return False, (
            f"SKIP: hardware mismatch (baseline {b} vs fresh {f}); {what}"
        )
    return True, None


def correctness_gate(baseline: Dict[str, Any], fresh: Dict[str, Any]) -> bool:
    """The grade decision for artifacts whose CORRECTNESS fields grade on
    any hardware while their perf numbers are baseline-gated (router,
    disagg): perf grades only when the baseline carries the same metric
    AND an identical platform block. This is the logic that had drifted
    between the two copies."""
    return (
        baseline.get("metric") == fresh.get("metric")
        and bool(baseline.get("platform"))
        and baseline.get("platform") == fresh.get("platform")
    )


def provenance_gate(
    baseline: Dict[str, Any], fresh: Dict[str, Any]
) -> Tuple[bool, Optional[str]]:
    """Measured and projected numbers are never compared to each other."""
    if baseline.get("provenance") == fresh.get("provenance"):
        return True, None
    return False, (
        f"SKIP reduction: provenance changed "
        f"({baseline.get('provenance')} -> {fresh.get('provenance')})"
    )


def load_tuned(
    path,
    platform: Optional[Dict[str, str]] = None,
    model: Optional[str] = None,
    workload_hash: Optional[str] = None,
    target: Optional[str] = None,
) -> Tuple[Optional[Dict[str, Any]], list]:
    """Read + gate a TUNE artifact in one step — the shared flow behind
    ``train.py --tuned`` and ``serve.py --tuned`` (one implementation, so
    the two surfaces cannot drift on what "matching" means). Returns
    (artifact, []) when it applies, (None, reasons) when it must be
    refused — including an unreadable file, which is a refusal, not a
    crash."""
    try:
        artifact = load_artifact(path)
    except (OSError, ValueError) as e:
        return None, [f"unreadable: {e}"]
    ok, reasons = check_tuned(
        artifact, platform=platform, model=model,
        workload_hash=workload_hash, target=target,
    )
    return (artifact, []) if ok else (None, reasons)


def check_tuned(
    artifact: Dict[str, Any],
    platform: Optional[Dict[str, str]] = None,
    model: Optional[str] = None,
    workload_hash: Optional[str] = None,
    target: Optional[str] = None,
) -> Tuple[bool, list]:
    """Gate a TUNE_<target>.json artifact against the CURRENT run: the
    tuned defaults only apply where they were measured. Returns
    (ok, reasons); every mismatch is listed so the refusal names exactly
    what disagrees (platform, model, workload, target)."""
    reasons = []
    if not isinstance(artifact, dict) or "winner" not in artifact:
        return False, ["artifact has no winner block (not a TUNE artifact?)"]
    art_platform = artifact.get("platform")
    if not art_platform:
        reasons.append("artifact lacks a platform block")
    elif platform is not None and art_platform != platform:
        reasons.append(
            f"platform mismatch: tuned on {art_platform}, running on "
            f"{platform}"
        )
    if target is not None and artifact.get("target") != target:
        reasons.append(
            f"target mismatch: artifact tunes {artifact.get('target')!r}, "
            f"this is a {target!r} run"
        )
    if model is not None and artifact.get("model") != model:
        reasons.append(
            f"model mismatch: tuned for {artifact.get('model')!r}, "
            f"running {model!r}"
        )
    if (
        workload_hash is not None
        and artifact.get("workload_hash") != workload_hash
    ):
        reasons.append(
            f"workload mismatch: tuned under workload "
            f"{artifact.get('workload_hash')!r}, this run replays "
            f"{workload_hash!r}"
        )
    return (not reasons), reasons
