"""End-to-end quality loop: prepare -> train -> eval -> serve, zero egress.

The reference's headline evidence is its published model-quality table
(reference ``README.md:53-57``: LAMBADA PPL/ACC + Pile BPB per model), which
required exporting to PyTorch and running lm-eval-harness on a GPU. This
script demonstrates the same capability IN-TREE at no-download scale:

1. gather a real-text corpus from the image (repo + reference markdown,
   package READMEs/licenses/doc trees) — natural English, deduplicated;
2. ``data.prepare`` it into tar shards with the built-in byte tokenizer
   (vocab 256, NUL document separator -> packed-sequence masking);
3. pretrain the ``byte_25m`` config (``configs/train_e2e_bytes.yaml``),
   recording train/val loss to ``metrics.jsonl``;
4. export msgpack params and score held-out text with the in-tree
   evalharness: byte perplexity, bits-per-byte, and a LAMBADA-style
   last-word completion task built from held-out paragraphs;
5. generate a sample from the checkpoint through ``serve.py`` (byte
   tokenizer, greedy).

Artifacts land in ``--out`` (default ``runs/e2e``): ``metrics.jsonl``,
``eval.json``, ``sample.txt``. Modes: ``--mode smoke`` (CPU, ~2 min, proves
the loop); ``--mode full`` (the real run — on the TPU chip this is ~10 min).

Usage::

  python scripts/e2e_quality.py --mode smoke
  python scripts/e2e_quality.py --mode full
"""
from __future__ import annotations

import argparse
import glob
import gzip
import hashlib
import json
import os
import re
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent

# Ordered prose-first: the byte cap truncates from the END, so natural
# English survives in full and code fills the remainder (the reference's
# own training set was a pile + code mix, its data/index names say so).
TEXT_SOURCES = [
    "/root/repo/*.md",
    "/root/repo/docs/*.md",
    "/root/reference/*.md",
    "/root/reference/**/*.md",
    "/opt/venv/lib/python3.12/site-packages/**/README*",
    "/opt/venv/lib/python3.12/site-packages/**/*.rst",
    "/opt/venv/lib/python3.12/site-packages/**/LICENSE*",
    "/usr/share/doc/**/*.txt",
    "/usr/share/doc/**/copyright",
    "/usr/share/doc/**/changelog*",  # mostly .gz; gather decompresses
    "/usr/local/lib/python3.12/*.py",  # stdlib source = the code mix
    "/usr/local/lib/python3.12/[a-z]*/*.py",
    # site-packages source (numpy/jax/flax/...) last: the cap bounds it
    "/opt/venv/lib/python3.12/site-packages/[a-z]*/**/*.py",
]


def gather_corpus(out_dir: Path, cap_bytes: int, heldout_frac: float = 0.05):
    """Collect real text files into train/heldout doc lists (dedup by hash)."""
    seen: set = set()
    docs: list[str] = []
    total = 0

    def iter_paths():
        # glob lazily per pattern: once the cap is met, later (large, code)
        # patterns are never even walked — smoke mode stops at the prose
        for pattern in TEXT_SOURCES:
            if total >= cap_bytes:
                return
            yield from sorted(glob.glob(pattern, recursive=True))

    for p in iter_paths():
        if total >= cap_bytes:
            break
        try:
            raw = Path(p).read_bytes()
            if p.endswith(".gz"):
                raw = gzip.decompress(raw)
            text = raw.decode("utf-8", errors="strict")
        except Exception:
            continue  # binary / non-utf8 / unreadable: not corpus material
        if len(text) < 512:
            continue
        if "\x00" in text:
            continue  # NUL is the document separator; must not occur in-doc
        h = hashlib.sha256(text.encode()).hexdigest()
        if h in seen:  # identical LICENSE files appear dozens of times
            continue
        seen.add(h)
        docs.append(text)
        total += len(text)
    if total < 1 << 20:
        raise SystemExit(f"only {total} bytes of corpus text found — need >=1MB")
    # deterministic split by doc hash (stable across runs/machines)
    train, heldout = [], []
    for d in docs:
        frac = int(hashlib.sha256(d.encode()).hexdigest()[:8], 16) / 0xFFFFFFFF
        (heldout if frac < heldout_frac else train).append(d)
    out_dir.mkdir(parents=True, exist_ok=True)
    for name, split in (("train", train), ("heldout", heldout)):
        with open(out_dir / f"{name}.jsonl", "w") as f:
            for d in split:
                f.write(json.dumps({"text": d}) + "\n")
    print(f"corpus: {len(train)} train docs, {len(heldout)} heldout docs, "
          f"{total/1e6:.1f} MB", flush=True)
    return train, heldout


def build_eval_files(heldout: list[str], data_dir: Path, max_ppl_bytes: int,
                     max_lambada: int, ctx: int = 512):
    """Pre-tokenized (byte) eval JSONLs for the in-tree evalharness."""
    # ppl / bpb: one big token stream from held-out docs
    stream = "\n\n".join(heldout)[:max_ppl_bytes]
    tokens = list(stream.encode("utf-8"))
    with open(data_dir / "heldout_ppl.jsonl", "w") as f:
        f.write(json.dumps({"tokens": tokens, "num_bytes": len(tokens)}) + "\n")

    # LAMBADA-style last-word completion: context = paragraph minus final
    # word, target = " " + final word (the reference task's shape,
    # reference README.md:53-57, at byte granularity)
    n = 0
    with open(data_dir / "heldout_lastword.jsonl", "w") as f:
        for doc in heldout:
            for para in doc.split("\n\n"):
                para = para.strip()
                words = para.split()
                if not (12 <= len(words) <= 80) or len(para) > 1200:
                    continue
                last = words[-1]
                if not re.fullmatch(r"[A-Za-z][A-Za-z'\-]{2,}[.:,;]?", last):
                    continue  # target must be a real word, as in LAMBADA
                context = para[: len(para) - len(last) - 1]
                target = " " + last
                f.write(json.dumps({
                    "context": list(context.encode()),
                    "target": list(target.encode()),
                }) + "\n")
                n += 1
                if n >= max_lambada:
                    break
            if n >= max_lambada:
                break
    # PIQA/Winogrande-style choice task (the reference's other published
    # metric shape, reference README.md:53-57): pick the paragraph's TRUE
    # second half among distractor continuations taken from other
    # paragraphs. Gold position round-robins over the example index.
    paras = [
        p.strip() for doc in heldout for p in doc.split("\n\n")
        if 200 <= len(p.strip()) <= 900
    ]
    if len(paras) < 4:
        raise SystemExit(
            f"only {len(paras)} usable paragraphs — too few for the choice task"
        )
    cap = max(32, ctx // 2 - 8)  # scoring.py needs continuation BYTES < seq_len

    def second_half(s: str) -> tuple[str, str]:
        """Split at a whitespace boundary near the middle: a mid-word cut
        would let spelling alone identify the gold continuation."""
        cut = s.find(" ", len(s) // 2)
        cut = cut if cut != -1 else len(s) // 2
        return s[:cut], s[cut:]

    def cap_b(s: str) -> str:
        # cap in BYTES, not characters — multi-byte UTF-8 would otherwise
        # overflow the scoring window
        return s.encode()[:cap].decode("utf-8", errors="ignore")

    n_choice = 0
    with open(data_dir / "heldout_choice.jsonl", "w") as f:
        for i, para in enumerate(paras):
            context, true_cont = second_half(para)
            cands = [
                cap_b(true_cont),
                cap_b(second_half(paras[(i + 1) % len(paras)])[1]),
                cap_b(second_half(paras[(i + 2) % len(paras)])[1]),
            ]
            gold = i % 3  # round-robin gold position by example index
            cands[0], cands[gold] = cands[gold], cands[0]
            f.write(json.dumps({
                "context": list(context.encode()),
                "choices": [list(c.encode()) for c in cands],
                "gold": gold,
                "choice_bytes": [len(c.encode()) for c in cands],
            }) + "\n")
            n_choice += 1
            if n_choice >= (20 if len(paras) < 100 else 200):
                break
    print(f"eval files: {len(tokens)} ppl bytes, {n} last-word examples, "
          f"{n_choice} choice examples", flush=True)
    if n == 0:
        raise SystemExit("no last-word examples extracted")


def run(cmd: list[str], **kw) -> subprocess.CompletedProcess:
    print("+", " ".join(str(c) for c in cmd), flush=True)
    return subprocess.run([str(c) for c in cmd], check=True, **kw)


def run_cli(module: str, argv: list, force_cpu: bool, **kw):
    """Invoke an in-tree CLI's ``main(argv)`` in a subprocess.

    NOT ``python -m``: in this image jax is pre-imported at interpreter
    startup with the (tunneled TPU) axon platform baked in, and the
    JAX_PLATFORMS env var is read then and ignored later — the only way to
    pin CPU is ``jax.config.update`` before any backend initializes, which
    needs a ``-c`` stub. A wedged tunnel otherwise hangs every subprocess."""
    argv = [str(a) for a in argv]
    code = (
        "import jax\n"
        + ("jax.config.update('jax_platforms','cpu')\n" if force_cpu else "")
        + f"from {module} import main\nmain({argv!r})\n"
    )
    print(f"+ [{module}]", " ".join(argv), flush=True)
    return subprocess.run([sys.executable, "-c", code], check=True, **kw)


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--mode", choices=("smoke", "full"), default="smoke")
    ap.add_argument("--out", default="runs/e2e")
    ap.add_argument("--force-cpu", action="store_true",
                    help="pin the cpu platform (smoke defaults to this)")
    ap.add_argument("--on-chip", action="store_true",
                    help="smoke mode: run train/eval on the default (TPU) "
                         "backend instead of smoke's CPU pin — a ~3-minute "
                         "on-chip proof of the whole loop for windows too "
                         "short for the full byte_25m run")
    ap.add_argument("--steps", type=int, default=None,
                    help="override training.total_steps (full mode: right-size "
                         "the on-chip run to the available window)")
    ap.add_argument("--model", default=None,
                    help="full mode: zoo name overriding byte_25m for BOTH "
                         "train and eval (byte_2m = the CPU-scale sibling)")
    ap.add_argument("--extra-set", nargs="*", default=[], metavar="KEY=V",
                    help="extra train.py --set overrides appended LAST "
                         "(e.g. training.batch_size=4 for a CPU budget)")
    args = ap.parse_args()

    out = Path(args.out)
    data_dir = out / "data"
    smoke = args.mode == "smoke"
    if args.on_chip and not smoke:
        raise SystemExit(
            "--on-chip is a smoke-mode option (full mode already runs on the "
            "default backend); drop --mode full or drop --on-chip"
        )
    cap = 2 << 20 if smoke else 64 << 20

    # fresh run state: metrics.jsonl is an append-mode sink and orbax
    # refuses to overwrite existing steps — a rerun over a stale --out
    # would concatenate trajectories / fail the save
    import shutil

    shutil.rmtree(out / "ckpt", ignore_errors=True)

    ctx = 128 if smoke else 512
    train, heldout = gather_corpus(data_dir, cap_bytes=cap)
    build_eval_files(
        heldout, data_dir,
        max_ppl_bytes=(50_000 if smoke else 400_000),
        max_lambada=(40 if smoke else 400),
        ctx=ctx,
    )

    # --- prepare: tar shards + index for train AND a small val split
    for split, inp in (("train", data_dir / "train.jsonl"),
                       ("val", data_dir / "heldout.jsonl")):
        run_cli("zero_transformer_tpu.data.prepare",
                ["--input", inp, "--tokenizer", "bytes",
                 "--max-context", ctx, "--format", "tar", "--doc-sep", 0,
                 "--rows-per-shard", 512, "--out", data_dir / split],
                force_cpu=True, cwd=REPO)

    # --- train (the train.py CLI surface, exactly as a user would)
    overrides = [
        "--set", f"checkpoint.directory={out}/ckpt",
        "--set", f"data.train_path={data_dir}/train.index",
        "--set", f"data.validation_path={data_dir}/val.index",
    ]
    if smoke:
        overrides += [
            "--set", "model.size=test",
            "--set", "model.doc_sep_token=0",
            "--set", "model.max_seq_len=128",
            "--set", f"training.train_context={ctx}",
            "--set", f"data.max_context={ctx}",
            "--set", "training.batch_size=8",
            "--set", "training.total_steps=60",
            "--set", "training.evaluation_frequency=20",
            "--set", "training.maximum_evaluation_steps=4",
            "--set", "training.log_frequency=10",
            "--set", "optimizer.warmup_steps=10",
            "--set", "checkpoint.save_frequency=60",
        ]
    if args.steps is not None:
        if args.steps < 10:
            raise SystemExit("--steps must be >= 10 (warmup+decay need room)")
        # LAST so it wins in either mode (train.py --set: last occurrence
        # takes effect). warmup must shrink with the run or the cosine
        # schedule gets decay_steps <= 0 (config warmup is 200); eval
        # frequency must shrink too or short runs record no validation loss
        overrides += [
            "--set", f"training.total_steps={args.steps}",
            "--set", f"checkpoint.save_frequency={args.steps}",
            "--set", f"optimizer.warmup_steps={max(1, min(200, args.steps // 10))}",
            "--set", f"training.evaluation_frequency={max(10, args.steps // 10)}",
        ]
    if args.model:
        if smoke:
            raise SystemExit(
                "--model is a full-mode option (smoke always runs the 'test' "
                "zoo model); drop --mode smoke or drop --model"
            )
        overrides += ["--set", f"model.size={args.model}"]
    for kv in args.extra_set:
        overrides += ["--set", kv]
    env = dict(os.environ)
    # --on-chip lifts smoke's CPU pin (train + eval on the default backend);
    # an explicit --force-cpu still wins
    pin_cpu = (smoke and not args.on_chip) or args.force_cpu
    code = (
        "import jax\n"
        + ("jax.config.update('jax_platforms','cpu')\n" if pin_cpu else "")
        + "import sys; import train\n"
        "sys.argv = ['train.py', '--cfg', 'configs/train_e2e_bytes.yaml'] + "
        f"{overrides!r}\n"
        "train.main()\n"
    )
    run([sys.executable, "-c", code], cwd=REPO, env=env)

    # --- export msgpack from the checkpoint (host-side work; always CPU)
    params = out / "params.msgpack"
    run_cli("zero_transformer_tpu.export",
            ["extract", "--checkpoint-dir", out / "ckpt", "--out", params],
            force_cpu=True, cwd=REPO)

    # --- eval: byte ppl, bits-per-byte, last-word accuracy
    model_name = "test" if smoke else (args.model or "byte_25m")
    force_cpu = pin_cpu
    results = {}
    eval_common = ["--model", model_name, "--params", params,
                   "--seq-len", ctx,
                   "--dtype", "float32" if smoke else "bfloat16"]
    for task, data in (("bpb", "heldout_ppl.jsonl"),
                       ("lambada", "heldout_lastword.jsonl"),
                       ("choice", "heldout_choice.jsonl")):
        proc = run_cli("zero_transformer_tpu.evalharness.cli",
                       eval_common + ["--task", task, "--data", data_dir / data],
                       force_cpu=force_cpu,
                       cwd=REPO, capture_output=True, text=True)
        lines = [l for l in proc.stdout.splitlines() if l.strip().startswith("{")]
        if not lines:
            raise SystemExit(
                f"evalharness {task} printed no JSON line.\n"
                f"stdout:\n{proc.stdout[-2000:]}\nstderr:\n{proc.stderr[-2000:]}"
            )
        results[task] = json.loads(lines[-1])
        print(task, "->", lines[-1], flush=True)
    (out / "eval.json").write_text(json.dumps(results, indent=2))

    # --- serve: one greedy sample through the real CLI
    new_tokens = 48 if smoke else 256
    prompt = "The license terms of this "
    proc = run_cli("zero_transformer_tpu.serve",
                   ["--model", model_name, "--params", params,
                    "--tokenizer", "bytes", "--greedy",
                    # ALiBi extrapolates, but the KV cache is fixed-shape:
                    # size it for prompt + continuation explicitly (the
                    # smoke model's max_seq_len would be too small)
                    "--cache-len", len(prompt) + new_tokens + 8,
                    "--max-new-tokens", new_tokens,
                    "--prompt", prompt],
                   force_cpu=force_cpu,
                   cwd=REPO, capture_output=True, text=True)
    (out / "sample.txt").write_text(proc.stdout)
    print("sample:", proc.stdout[-300:], flush=True)
    print(f"E2E {args.mode} loop complete -> {out}", flush=True)


if __name__ == "__main__":
    main()
