"""Poll the TPU tunnel by repeatedly running bench.py until a genuine on-chip
measurement lands, then promote it to BENCH_measured.json.

The axon TPU tunnel in this image wedges at backend init for hours at a time
(observed rounds 1-4) and clears on its own. bench.py already handles a wedged
tunnel gracefully (per-child timeouts, cached-artifact fallback), so the
cheapest robust watcher is simply: run the full ladder, inspect the artifact,
retry later if the tunnel was down.

Usage: python scripts/tpu_watch.py [--interval 900] [--max-attempts 0]
Writes each attempt to runs/bench_attempt_<n>.json (+ .log for stderr) and, on
success, rewrites BENCH_measured.json with fresh provenance so both the driver
bench and any later wedged round can ride it.
"""
from __future__ import annotations

import argparse
import datetime
import json
import os
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_once(attempt: int) -> dict | None:
    """One full bench.py ladder run; returns the parsed artifact or None."""
    out_path = os.path.join(ROOT, "runs", f"bench_attempt_{attempt}.json")
    log_path = os.path.join(ROOT, "runs", f"bench_attempt_{attempt}.log")
    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    # bench.py spawns each scenario as its own subprocess: run the whole
    # tree in a new session so the backstop kill reaps the grandchildren
    # too — an orphaned scenario child would keep the TPU tunnel held,
    # recreating the very wedge this watcher exists to outlast
    with open(log_path, "w") as log:
        popen = subprocess.Popen(
            [sys.executable, os.path.join(ROOT, "bench.py")],
            cwd=ROOT, stdout=subprocess.PIPE, stderr=log, text=True,
            start_new_session=True,
        )
        try:
            stdout, _ = popen.communicate(
                timeout=3 * 3600  # the ladder self-limits; this is a backstop
            )
        except subprocess.TimeoutExpired as e:
            import signal

            os.killpg(popen.pid, signal.SIGKILL)
            # TimeoutExpired.stdout is BYTES even under text=True (CPython
            # joins the raw chunks, gh-87597)
            stdout = e.stdout or b""
            if isinstance(stdout, bytes):
                stdout = stdout.decode(errors="replace")
            popen.wait()
            print("attempt hit the 3h backstop timeout; killed the "
                  "bench process group", flush=True)
    with open(log_path, "a") as log:  # keep raw stdout diagnosable even if
        log.write("\n--- stdout ---\n" + (stdout or ""))  # the parse fails
    for line in reversed((stdout or "").strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                art = json.loads(line)
            except json.JSONDecodeError:
                return None
            with open(out_path, "w") as f:
                json.dump(art, f, indent=1)
            return art
    return None


def is_live_tpu(art: dict) -> bool:
    metric = str(art.get("metric", ""))
    if metric.endswith("_cached") or "cpu_fallback" in metric:
        return False
    scen = (art.get("extra") or {}).get("scenarios") or {}
    return any(r.get("ok") and r.get("platform") == "tpu" for r in scen.values())


def promote(art: dict) -> None:
    """Write BENCH_measured.json from the headline scenario of a live run."""
    art = dict(art)
    art["measured_at_utc"] = datetime.datetime.now(datetime.timezone.utc).isoformat()
    path = os.path.join(ROOT, "BENCH_measured.json")
    with open(path, "w") as f:
        json.dump(art, f, indent=1)
    print(f"promoted live TPU measurement to {path}", flush=True)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--interval", type=float, default=900.0,
                    help="seconds to sleep between failed attempts")
    ap.add_argument("--max-attempts", type=int, default=0,
                    help="0 = retry forever")
    args = ap.parse_args()

    attempt = 0
    while True:
        attempt += 1
        stamp = datetime.datetime.now().strftime("%H:%M:%S")
        print(f"[{stamp}] bench attempt {attempt} starting", flush=True)
        art = run_once(attempt)  # handles the backstop timeout internally
        if art is not None and is_live_tpu(art):
            promote(art)
            print("TPU LIVE — watcher done", flush=True)
            return
        errs = ((art or {}).get("extra") or {}).get("errors") or []
        print(f"no live TPU measurement (errors: {errs[:2]})", flush=True)
        if args.max_attempts and attempt >= args.max_attempts:
            print("max attempts reached; giving up", flush=True)
            return
        time.sleep(args.interval)


if __name__ == "__main__":
    main()
