#!/usr/bin/env python
"""Serving-bench regression guard: fresh BENCH_serve.json vs the committed
baseline.

``make serve-bench`` snapshots the committed artifact before the load run,
then calls this with (baseline, fresh). The guard FAILS LOUDLY (exit 1)
when, on matching hardware, either headline metric regresses past the
tolerance:

- ``decode_tok_s`` (aggregate decode throughput) drops > 15%
- ``itl_ms.p99`` (tail inter-token latency) grows > 15%
- ``itl_ms_decode_only.p99`` (pure-decode tail — the fused sampling tail /
  paged-kernel home metric) grows > 15%
- the fresh artifact's measured span-tracing overhead (``obs_overhead``,
  from the loadgen's --obs-ab tracing-on/off A/B on this same run's
  hardware) exceeds 2% of decode tok/s — observability must stay
  effectively free on the hot path

"Matching hardware" is judged from the artifact's ``platform`` block (jax
backend + device kind): a TPU box must not be graded against a CPU
baseline, and a baseline from before the platform field existed can only be
skipped. Skips exit 0 with a reason — the guard's job is catching real
regressions on comparable runs, not adding noise on incomparable ones.

Usage: serve_bench_guard.py <baseline.json> <fresh.json> [--tolerance 0.15]
"""
from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent))
import bench_common  # noqa: E402  (shared skip-or-grade logic, ISSUE 14)

TOLERANCE = 0.15
# span tracing must cost <= this fraction of decode tok/s (ISSUE 7): the
# A/B inside one artifact ran both arms on the same box minutes apart, so
# unlike the baseline comparison there is no hardware-mismatch skip
OBS_OVERHEAD_MAX = 0.02
# the fleet router's near-linear-scaling bar (ISSUE 9): aggregate relayed
# tok/s at the largest fleet must be >= this multiple of the 1-replica run
ROUTER_SCALING_MIN = 3.0


def compare_capacity(baseline: dict, fresh: dict, tolerance: float = TOLERANCE):
    """BENCH_serve_capacity.json pair: the paged/slab concurrent-stream
    ratio at equal KV budget must not shrink past the tolerance."""
    msgs = []
    base_ratio = baseline.get("value", 0)
    fresh_ratio = fresh.get("value", 0)
    if base_ratio and fresh_ratio < base_ratio * (1 - tolerance):
        return False, [
            f"REGRESSION: capacity ratio {fresh_ratio:.2f} < "
            f"{(1 - tolerance) * 100:.0f}% of baseline {base_ratio:.2f}"
        ]
    msgs.append(f"ok: capacity ratio {fresh_ratio:.2f} (baseline {base_ratio:.2f})")
    return True, msgs


def compare_router(
    baseline: dict, fresh: dict, tolerance: float = TOLERANCE,
    grade_scaling: bool = True,
):
    """BENCH_router.json pair. Correctness fields (zero dropped streams, a
    token-exact resumed failover, a clean rolling reload) grade on ANY
    hardware — a dropped stream is a dropped stream wherever it ran; they
    were already hard-enforced by the loadgen at artifact-write time and
    are re-checked so a hand-edited or stale artifact cannot sneak past.
    The scaling ratio (the absolute near-linear bar + the baseline
    tolerance) only grades on matching hardware, like every other perf
    number in this guard."""
    msgs = []
    ok = True
    if fresh.get("dropped_streams", -1) != 0:
        ok = False
        msgs.append(
            f"FAIL: router artifact has dropped_streams="
            f"{fresh.get('dropped_streams')} (must be 0)"
        )
    failover = fresh.get("failover") or {}
    if not failover.get("token_exact"):
        ok = False
        msgs.append("FAIL: router failover segment was not token-exact")
    reload_block = fresh.get("rolling_reload") or {}
    if not reload_block.get("ok") or reload_block.get("dropped_streams"):
        ok = False
        msgs.append(f"FAIL: rolling reload {reload_block}")
    # stitched-trace verification (ISSUE 15) is correctness: a merged trace
    # with orphan spans or <95% coverage is a broken observability plane on
    # any hardware (absent block = pre-PR15 artifact, skipped not failed)
    trace_block = fresh.get("fleet_trace")
    if trace_block is not None:
        if trace_block.get("coverage_min", 0) < 0.95:
            ok = False
            msgs.append(
                f"FAIL: stitched-trace coverage "
                f"{trace_block.get('coverage_min')} < 0.95"
            )
        if trace_block.get("orphans") or not trace_block.get("hops_ordered"):
            ok = False
            msgs.append(f"FAIL: stitched trace {trace_block}")
    if not grade_scaling:
        msgs.append(
            "SKIP: hardware mismatch vs baseline; router scaling ratio "
            "not graded (correctness fields were)"
        )
        return ok, msgs
    # the SLO verdict (ISSUE 15) grades with the perf numbers: on foreign
    # hardware a "violated" verdict may be the box, not the router — but on
    # matching hardware the declared objectives are part of the bar
    slo = fresh.get("slo") or {}
    if slo.get("verdict") == "violated":
        ok = False
        msgs.append(
            f"REGRESSION: SLO verdict violated — "
            f"{ {name: o.get('state') for name, o in (slo.get('objectives') or {}).items() if o.get('state') != 'ok'} }"
        )
    elif slo:
        msgs.append(f"ok: SLO verdict {slo.get('verdict')}")
    ratio = fresh.get("value", 0)
    if ratio < ROUTER_SCALING_MIN:
        ok = False
        msgs.append(
            f"REGRESSION: router scaling ratio {ratio:.2f} < the "
            f"near-linear bar {ROUTER_SCALING_MIN:.1f}"
        )
    else:
        msgs.append(
            f"ok: router scaling ratio {ratio:.2f} "
            f"(bar {ROUTER_SCALING_MIN:.1f})"
        )
    base_ratio = baseline.get("value", 0)
    if base_ratio and ratio < base_ratio * (1 - tolerance):
        ok = False
        msgs.append(
            f"REGRESSION: router scaling ratio {ratio:.2f} < "
            f"{(1 - tolerance) * 100:.0f}% of baseline {base_ratio:.2f}"
        )
    return ok, msgs


def compare_disagg(
    baseline: dict, fresh: dict, tolerance: float = TOLERANCE,
    grade_perf: bool = True,
):
    """BENCH_disagg.json pair (ISSUE 12). Correctness grades on ANY
    hardware: every stream token-exact and finished, zero dropped streams,
    the disaggregated arm actually split requests with ZERO replayed
    tokens, and the sawtooth segment scaled up AND back down without
    drops. The within-artifact A/B (the disaggregated arm must isolate
    background decode from the flood at least as well as the mixed-fleet
    control) also grades everywhere — both arms ran minutes apart on the
    same box, like the obs-overhead A/B. Only the cross-run degradation
    ratio vs the committed baseline is hardware-gated."""
    msgs = []
    ok = True
    flood = fresh.get("flood") or {}
    if flood:
        if not flood.get("token_exact"):
            ok = False
            msgs.append("FAIL: flood arm streams were not token-exact")
        if flood.get("dropped_streams", -1) != 0:
            ok = False
            msgs.append(
                f"FAIL: flood dropped_streams="
                f"{flood.get('dropped_streams')} (must be 0)"
            )
        disagg = flood.get("disagg") or {}
        mixed = flood.get("mixed") or {}
        if not disagg.get("disagg_dispatches"):
            ok = False
            msgs.append("FAIL: disagg arm never split a request by phase")
        if disagg.get("resume_replayed_tokens", -1) != 0:
            ok = False
            msgs.append(
                "FAIL: disagg arm replayed "
                f"{disagg.get('resume_replayed_tokens')} tokens (must be 0)"
            )
        d_deg = disagg.get("itl_bg_p50_degradation", 0)
        m_deg = mixed.get("itl_bg_p50_degradation", 0)
        on_cpu = (fresh.get("platform") or {}).get("backend") == "cpu"
        if d_deg and m_deg and on_cpu:
            # CPU-honesty (the BENCH_ckpt_integrity / train_bench
            # discipline): on a shared-core CPU box both "replicas"
            # compete for the same cores, so the flood steals cycles from
            # the decode replica whatever process it lives in — phase
            # isolation is a DEVICE-parallelism claim and measuring it
            # here is scheduler noise (observed flipping run to run).
            # Correctness still graded above; ratios recorded, not graded.
            msgs.append(
                f"SKIP: cpu backend — isolation ratio recorded "
                f"(disagg {d_deg:.2f}x vs mixed {m_deg:.2f}x) but not "
                "graded; replicas share the same cores here"
            )
        elif d_deg and m_deg:
            budget = max(m_deg * (1 + tolerance), 1.5)
            if d_deg > budget:
                ok = False
                msgs.append(
                    f"REGRESSION: disagg ITL degradation {d_deg:.2f}x under "
                    f"flood exceeds the mixed control's {m_deg:.2f}x "
                    f"(budget {budget:.2f}x) — disaggregation stopped "
                    "isolating decode"
                )
            else:
                msgs.append(
                    f"ok: flood stretches background decode ITL p50 "
                    f"{d_deg:.2f}x disaggregated vs {m_deg:.2f}x mixed"
                )
    saw = fresh.get("sawtooth") or {}
    if saw:
        if saw.get("dropped_streams", -1) != 0 or saw.get("hung"):
            ok = False
            msgs.append(f"FAIL: sawtooth dropped/hung streams: {saw}")
        if saw.get("streams_done") != saw.get("streams"):
            ok = False
            msgs.append(
                f"FAIL: sawtooth finished {saw.get('streams_done')} of "
                f"{saw.get('streams')} streams"
            )
        if not saw.get("autoscale_ups") or not saw.get("autoscale_downs"):
            ok = False
            msgs.append(
                "FAIL: autoscaler never tracked the sawtooth "
                f"(ups={saw.get('autoscale_ups')}, "
                f"downs={saw.get('autoscale_downs')})"
            )
        else:
            msgs.append(
                f"ok: sawtooth tracked (ups={saw['autoscale_ups']}, "
                f"downs={saw['autoscale_downs']}, dropped 0)"
            )
    if not grade_perf:
        msgs.append(
            "SKIP: hardware mismatch vs baseline; cross-run degradation "
            "not graded (correctness + within-artifact A/B were)"
        )
        return ok, msgs
    base_deg = (
        (baseline.get("flood") or {}).get("disagg") or {}
    ).get("itl_bg_p50_degradation", 0)
    fresh_deg = (
        (fresh.get("flood") or {}).get("disagg") or {}
    ).get("itl_bg_p50_degradation", 0)
    if (fresh.get("platform") or {}).get("backend") == "cpu":
        base_deg = 0  # same shared-core honesty as the within-artifact A/B
    if base_deg and fresh_deg and fresh_deg > base_deg * (1 + tolerance):
        ok = False
        msgs.append(
            f"REGRESSION: disagg ITL degradation {fresh_deg:.2f}x > "
            f"{(1 + tolerance) * 100:.0f}% of baseline {base_deg:.2f}x"
        )
    elif base_deg and fresh_deg:
        msgs.append(
            f"ok: disagg ITL degradation {fresh_deg:.2f}x "
            f"(baseline {base_deg:.2f}x)"
        )
    return ok, msgs


def compare_tenant(
    baseline: dict, fresh: dict, tolerance: float = TOLERANCE,
    grade_perf: bool = True,
):
    """BENCH_tenant.json pair (ISSUE 18). Correctness grades on ANY
    hardware: every gold stream done and token-exact, zero dropped
    streams, the flood actually throttled, every rejection retryable with
    a Retry-After, and the isolation machinery engaged. The gold p99
    ratio is a device-parallelism claim: on a shared-core CPU box the
    flood steals cycles from the gold replica whatever the admission
    plane does, so the ratio is recorded, not graded (same CPU-honesty
    discipline as the disagg isolation A/B); on an accelerator it grades
    against the artifact's own pinned factor and the committed baseline."""
    msgs = []
    ok = True
    for arm_name in ("baseline", "flood"):
        arm = fresh.get(arm_name) or {}
        if arm.get("gold_done") != arm.get("gold_offered"):
            ok = False
            msgs.append(
                f"FAIL: {arm_name} arm finished {arm.get('gold_done')} of "
                f"{arm.get('gold_offered')} gold streams"
            )
    if not fresh.get("token_exact"):
        ok = False
        msgs.append("FAIL: gold streams were not token-exact")
    if fresh.get("dropped_streams", -1) != 0:
        ok = False
        msgs.append(
            f"FAIL: dropped_streams={fresh.get('dropped_streams')} "
            "(must be 0)"
        )
    flood = fresh.get("flood") or {}
    if not flood.get("flood_rejected"):
        ok = False
        msgs.append("FAIL: the flood was never throttled — not a flood")
    if flood.get("flood_bad_rejections"):
        ok = False
        msgs.append(
            f"FAIL: {flood.get('flood_bad_rejections')} flood rejections "
            "without retryable semantics (non-429/503 or missing "
            "Retry-After)"
        )
    if sum((flood.get("isolation_counters") or {}).values()) == 0:
        ok = False
        msgs.append("FAIL: isolation machinery never engaged under flood")
    ratio = fresh.get("value", 0)
    limit = fresh.get("isolation_factor_limit", 0)
    on_cpu = (fresh.get("platform") or {}).get("backend") == "cpu"
    if on_cpu:
        msgs.append(
            f"SKIP: cpu backend — gold p99 ratio recorded ({ratio:.2f}x) "
            "but not graded; the flood shares the gold replica's cores here"
        )
        return ok, msgs
    if not grade_perf:
        msgs.append(
            "SKIP: hardware mismatch vs baseline; gold p99 ratio not "
            "graded (correctness fields were)"
        )
        return ok, msgs
    if limit and ratio > limit:
        ok = False
        msgs.append(
            f"REGRESSION: gold p99 ratio {ratio:.2f}x exceeds the pinned "
            f"isolation factor {limit:.2f}x"
        )
    base_ratio = baseline.get("value", 0)
    if base_ratio and ratio > base_ratio * (1 + tolerance):
        ok = False
        msgs.append(
            f"REGRESSION: gold p99 ratio {ratio:.2f}x > "
            f"{(1 + tolerance) * 100:.0f}% of baseline {base_ratio:.2f}x"
        )
    elif ok:
        msgs.append(
            f"ok: gold p99 ratio {ratio:.2f}x "
            f"(limit {limit:.2f}x, baseline {base_ratio:.2f}x)"
        )
    return ok, msgs


def compare(baseline: dict, fresh: dict, tolerance: float = TOLERANCE):
    """Returns (ok, messages). ok=True covers both pass and skip."""
    msgs = []
    # the tenant-isolation artifact dispatches before the generic platform
    # gate: its correctness fields grade everywhere, its latency ratio is
    # accelerator-only (CPU-honesty) and hardware-gated vs the baseline
    if str(fresh.get("metric", "")) == "tenant_isolation":
        grade = bench_common.correctness_gate(baseline, fresh)
        return compare_tenant(
            baseline if grade else {}, fresh, tolerance, grade_perf=grade
        )
    # the disagg artifact dispatches before the generic platform gate too:
    # its correctness fields + within-artifact A/B grade everywhere; the
    # perf grade decision is the ONE shared rule (bench_common, ISSUE 14 —
    # the router/disagg copies of this predicate had drifted)
    if str(fresh.get("metric", "")) == "disagg_flood_and_autoscale":
        grade = bench_common.correctness_gate(baseline, fresh)
        return compare_disagg(
            baseline if grade else {}, fresh, tolerance, grade_perf=grade
        )
    # the router artifact dispatches before the generic platform gate: its
    # correctness fields must grade everywhere, only its scaling perf is
    # hardware-gated
    if str(fresh.get("metric", "")) == "router_scaling_tok_s":
        grade = bench_common.correctness_gate(baseline, fresh)
        return compare_router(
            baseline if grade else {}, fresh, tolerance, grade_scaling=grade
        )
    hw_ok, hw_reason = bench_common.hardware_gate(baseline, fresh)
    if not hw_ok:
        return True, [hw_reason]
    if baseline.get("metric") != fresh.get("metric"):
        return True, ["SKIP: different metrics; not comparable"]
    if str(baseline.get("metric", "")).startswith("serve_capacity"):
        return compare_capacity(baseline, fresh, tolerance)
    if baseline.get("workload", "mixed") != fresh.get("workload", "mixed"):
        return True, ["SKIP: different workloads; not comparable"]

    ok = True
    base_tps = baseline.get("decode_tok_s", baseline.get("value", 0))
    fresh_tps = fresh.get("decode_tok_s", fresh.get("value", 0))
    if base_tps and fresh_tps < base_tps * (1 - tolerance):
        ok = False
        msgs.append(
            f"REGRESSION: decode_tok_s {fresh_tps:.1f} < "
            f"{(1 - tolerance) * 100:.0f}% of baseline {base_tps:.1f}"
        )
    else:
        msgs.append(f"ok: decode_tok_s {fresh_tps:.1f} (baseline {base_tps:.1f})")

    base_p99 = baseline.get("itl_ms", {}).get("p99", 0)
    fresh_p99 = fresh.get("itl_ms", {}).get("p99", 0)
    if base_p99 and fresh_p99 > base_p99 * (1 + tolerance):
        ok = False
        msgs.append(
            f"REGRESSION: itl_ms.p99 {fresh_p99:.3f} ms > "
            f"{(1 + tolerance) * 100:.0f}% of baseline {base_p99:.3f} ms"
        )
    else:
        msgs.append(f"ok: itl_ms.p99 {fresh_p99:.3f} ms (baseline {base_p99:.3f} ms)")

    # decode-only ITL tail (PR 11): the fused sampling tail's home metric —
    # ticks with no prefill work are pure decode, so a regression here is a
    # kernel/tail regression, not admission-mix noise
    base_d99 = (baseline.get("itl_ms_decode_only") or {}).get("p99", 0)
    fresh_d99 = (fresh.get("itl_ms_decode_only") or {}).get("p99", 0)
    if base_d99 and fresh_d99 > base_d99 * (1 + tolerance):
        ok = False
        msgs.append(
            f"REGRESSION: itl_ms_decode_only.p99 {fresh_d99:.3f} ms > "
            f"{(1 + tolerance) * 100:.0f}% of baseline {base_d99:.3f} ms"
        )
    elif base_d99:
        msgs.append(
            f"ok: itl_ms_decode_only.p99 {fresh_d99:.3f} ms "
            f"(baseline {base_d99:.3f} ms)"
        )

    obs = fresh.get("obs_overhead")
    if obs and obs.get("overhead_frac", 0) > OBS_OVERHEAD_MAX:
        ok = False
        msgs.append(
            f"REGRESSION: span-tracing overhead "
            f"{obs['overhead_frac'] * 100:.1f}% of decode tok/s > "
            f"{OBS_OVERHEAD_MAX * 100:.0f}% budget "
            f"(on {obs.get('decode_tok_s_trace_off', 0):.1f} tok/s traced off)"
        )
    elif obs:
        msgs.append(
            f"ok: span-tracing overhead {obs['overhead_frac'] * 100:.1f}% "
            f"(budget {OBS_OVERHEAD_MAX * 100:.0f}%)"
        )
    return ok, msgs


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("baseline", help="committed BENCH_serve.json snapshot")
    p.add_argument("fresh", help="artifact from the run under test")
    p.add_argument("--tolerance", type=float, default=TOLERANCE)
    args = p.parse_args(argv)
    baseline = json.loads(Path(args.baseline).read_text())
    fresh = json.loads(Path(args.fresh).read_text())
    ok, msgs = compare(baseline, fresh, args.tolerance)
    for m in msgs:
        print(f"serve-bench-guard: {m}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
