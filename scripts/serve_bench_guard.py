#!/usr/bin/env python
"""Serving-bench regression guard: fresh BENCH_serve.json vs the committed
baseline.

``make serve-bench`` snapshots the committed artifact before the load run,
then calls this with (baseline, fresh). The guard FAILS LOUDLY (exit 1)
when, on matching hardware, either headline metric regresses past the
tolerance:

- ``decode_tok_s`` (aggregate decode throughput) drops > 15%
- ``itl_ms.p99`` (tail inter-token latency) grows > 15%
- ``itl_ms_decode_only.p99`` (pure-decode tail — the fused sampling tail /
  paged-kernel home metric) grows > 15%
- the fresh artifact's measured span-tracing overhead (``obs_overhead``,
  from the loadgen's --obs-ab tracing-on/off A/B on this same run's
  hardware) exceeds 2% of decode tok/s — observability must stay
  effectively free on the hot path

"Matching hardware" is judged from the artifact's ``platform`` block (jax
backend + device kind): a TPU box must not be graded against a CPU
baseline, and a baseline from before the platform field existed can only be
skipped. Skips exit 0 with a reason — the guard's job is catching real
regressions on comparable runs, not adding noise on incomparable ones.

Usage: serve_bench_guard.py <baseline.json> <fresh.json> [--tolerance 0.15]
"""
from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

TOLERANCE = 0.15
# span tracing must cost <= this fraction of decode tok/s (ISSUE 7): the
# A/B inside one artifact ran both arms on the same box minutes apart, so
# unlike the baseline comparison there is no hardware-mismatch skip
OBS_OVERHEAD_MAX = 0.02
# the fleet router's near-linear-scaling bar (ISSUE 9): aggregate relayed
# tok/s at the largest fleet must be >= this multiple of the 1-replica run
ROUTER_SCALING_MIN = 3.0


def compare_capacity(baseline: dict, fresh: dict, tolerance: float = TOLERANCE):
    """BENCH_serve_capacity.json pair: the paged/slab concurrent-stream
    ratio at equal KV budget must not shrink past the tolerance."""
    msgs = []
    base_ratio = baseline.get("value", 0)
    fresh_ratio = fresh.get("value", 0)
    if base_ratio and fresh_ratio < base_ratio * (1 - tolerance):
        return False, [
            f"REGRESSION: capacity ratio {fresh_ratio:.2f} < "
            f"{(1 - tolerance) * 100:.0f}% of baseline {base_ratio:.2f}"
        ]
    msgs.append(f"ok: capacity ratio {fresh_ratio:.2f} (baseline {base_ratio:.2f})")
    return True, msgs


def compare_router(
    baseline: dict, fresh: dict, tolerance: float = TOLERANCE,
    grade_scaling: bool = True,
):
    """BENCH_router.json pair. Correctness fields (zero dropped streams, a
    token-exact resumed failover, a clean rolling reload) grade on ANY
    hardware — a dropped stream is a dropped stream wherever it ran; they
    were already hard-enforced by the loadgen at artifact-write time and
    are re-checked so a hand-edited or stale artifact cannot sneak past.
    The scaling ratio (the absolute near-linear bar + the baseline
    tolerance) only grades on matching hardware, like every other perf
    number in this guard."""
    msgs = []
    ok = True
    if fresh.get("dropped_streams", -1) != 0:
        ok = False
        msgs.append(
            f"FAIL: router artifact has dropped_streams="
            f"{fresh.get('dropped_streams')} (must be 0)"
        )
    failover = fresh.get("failover") or {}
    if not failover.get("token_exact"):
        ok = False
        msgs.append("FAIL: router failover segment was not token-exact")
    reload_block = fresh.get("rolling_reload") or {}
    if not reload_block.get("ok") or reload_block.get("dropped_streams"):
        ok = False
        msgs.append(f"FAIL: rolling reload {reload_block}")
    if not grade_scaling:
        msgs.append(
            "SKIP: hardware mismatch vs baseline; router scaling ratio "
            "not graded (correctness fields were)"
        )
        return ok, msgs
    ratio = fresh.get("value", 0)
    if ratio < ROUTER_SCALING_MIN:
        ok = False
        msgs.append(
            f"REGRESSION: router scaling ratio {ratio:.2f} < the "
            f"near-linear bar {ROUTER_SCALING_MIN:.1f}"
        )
    else:
        msgs.append(
            f"ok: router scaling ratio {ratio:.2f} "
            f"(bar {ROUTER_SCALING_MIN:.1f})"
        )
    base_ratio = baseline.get("value", 0)
    if base_ratio and ratio < base_ratio * (1 - tolerance):
        ok = False
        msgs.append(
            f"REGRESSION: router scaling ratio {ratio:.2f} < "
            f"{(1 - tolerance) * 100:.0f}% of baseline {base_ratio:.2f}"
        )
    return ok, msgs


def compare(baseline: dict, fresh: dict, tolerance: float = TOLERANCE):
    """Returns (ok, messages). ok=True covers both pass and skip."""
    msgs = []
    # the router artifact dispatches before the generic platform gate: its
    # correctness fields must grade everywhere, only its scaling perf is
    # hardware-gated
    if str(fresh.get("metric", "")) == "router_scaling_tok_s":
        grade = (
            baseline.get("metric") == fresh.get("metric")
            and bool(baseline.get("platform"))
            and baseline.get("platform") == fresh.get("platform")
        )
        return compare_router(
            baseline if grade else {}, fresh, tolerance, grade_scaling=grade
        )
    base_platform = baseline.get("platform")
    fresh_platform = fresh.get("platform")
    if not base_platform or not fresh_platform:
        return True, ["SKIP: baseline or fresh artifact lacks a platform block"]
    if base_platform != fresh_platform:
        return True, [
            f"SKIP: hardware mismatch (baseline {base_platform} vs "
            f"fresh {fresh_platform}); not comparable"
        ]
    if baseline.get("metric") != fresh.get("metric"):
        return True, ["SKIP: different metrics; not comparable"]
    if str(baseline.get("metric", "")).startswith("serve_capacity"):
        return compare_capacity(baseline, fresh, tolerance)
    if baseline.get("workload", "mixed") != fresh.get("workload", "mixed"):
        return True, ["SKIP: different workloads; not comparable"]

    ok = True
    base_tps = baseline.get("decode_tok_s", baseline.get("value", 0))
    fresh_tps = fresh.get("decode_tok_s", fresh.get("value", 0))
    if base_tps and fresh_tps < base_tps * (1 - tolerance):
        ok = False
        msgs.append(
            f"REGRESSION: decode_tok_s {fresh_tps:.1f} < "
            f"{(1 - tolerance) * 100:.0f}% of baseline {base_tps:.1f}"
        )
    else:
        msgs.append(f"ok: decode_tok_s {fresh_tps:.1f} (baseline {base_tps:.1f})")

    base_p99 = baseline.get("itl_ms", {}).get("p99", 0)
    fresh_p99 = fresh.get("itl_ms", {}).get("p99", 0)
    if base_p99 and fresh_p99 > base_p99 * (1 + tolerance):
        ok = False
        msgs.append(
            f"REGRESSION: itl_ms.p99 {fresh_p99:.3f} ms > "
            f"{(1 + tolerance) * 100:.0f}% of baseline {base_p99:.3f} ms"
        )
    else:
        msgs.append(f"ok: itl_ms.p99 {fresh_p99:.3f} ms (baseline {base_p99:.3f} ms)")

    # decode-only ITL tail (PR 11): the fused sampling tail's home metric —
    # ticks with no prefill work are pure decode, so a regression here is a
    # kernel/tail regression, not admission-mix noise
    base_d99 = (baseline.get("itl_ms_decode_only") or {}).get("p99", 0)
    fresh_d99 = (fresh.get("itl_ms_decode_only") or {}).get("p99", 0)
    if base_d99 and fresh_d99 > base_d99 * (1 + tolerance):
        ok = False
        msgs.append(
            f"REGRESSION: itl_ms_decode_only.p99 {fresh_d99:.3f} ms > "
            f"{(1 + tolerance) * 100:.0f}% of baseline {base_d99:.3f} ms"
        )
    elif base_d99:
        msgs.append(
            f"ok: itl_ms_decode_only.p99 {fresh_d99:.3f} ms "
            f"(baseline {base_d99:.3f} ms)"
        )

    obs = fresh.get("obs_overhead")
    if obs and obs.get("overhead_frac", 0) > OBS_OVERHEAD_MAX:
        ok = False
        msgs.append(
            f"REGRESSION: span-tracing overhead "
            f"{obs['overhead_frac'] * 100:.1f}% of decode tok/s > "
            f"{OBS_OVERHEAD_MAX * 100:.0f}% budget "
            f"(on {obs.get('decode_tok_s_trace_off', 0):.1f} tok/s traced off)"
        )
    elif obs:
        msgs.append(
            f"ok: span-tracing overhead {obs['overhead_frac'] * 100:.1f}% "
            f"(budget {OBS_OVERHEAD_MAX * 100:.0f}%)"
        )
    return ok, msgs


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("baseline", help="committed BENCH_serve.json snapshot")
    p.add_argument("fresh", help="artifact from the run under test")
    p.add_argument("--tolerance", type=float, default=TOLERANCE)
    args = p.parse_args(argv)
    baseline = json.loads(Path(args.baseline).read_text())
    fresh = json.loads(Path(args.fresh).read_text())
    ok, msgs = compare(baseline, fresh, args.tolerance)
    for m in msgs:
        print(f"serve-bench-guard: {m}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
