#!/bin/bash
# Retry the full on-chip e2e quality run until its artifacts land.
#
# Same philosophy as scripts/tpu_watch.py (the bench-ladder watcher): this
# image's TPU tunnel wedges at backend init for stretches and clears on its
# own, so the cheapest robust automation is run → inspect → retry. Each
# attempt is backstop-killed (a wedged backend-init otherwise blocks
# forever) and success is judged by the artifacts, not the exit code:
# sample.txt is written LAST by e2e_quality.py, so its presence (plus
# eval.json) means the whole prepare→train→eval→serve chain completed.
#
# Usage: bash scripts/e2e_watch.sh [OUT_DIR] [ATTEMPTS] [ATTEMPT_TIMEOUT_S]
set -u
OUT=${1:-docs/e2e/full_tpu}
ATTEMPTS=${2:-20}
TMO=${3:-2400}
cd "$(dirname "$0")/.."
mkdir -p runs
# a stale artifact from a previous run must not count as this run's success
rm -f "$OUT/eval.json" "$OUT/sample.txt"
for i in $(seq 1 "$ATTEMPTS"); do
  echo "[$(date +%H:%M:%S)] e2e attempt $i -> $OUT" | tee -a runs/e2e_watch.log
  timeout -k 30 "$TMO" python scripts/e2e_quality.py --mode full --out "$OUT" \
    > "runs/e2e_full_tpu_$i.log" 2>&1
  rc=$?
  echo "[$(date +%H:%M:%S)] attempt $i rc=$rc (runs/e2e_full_tpu_$i.log)" | tee -a runs/e2e_watch.log
  if [ -f "$OUT/eval.json" ] && [ -f "$OUT/sample.txt" ]; then
    echo "E2E DONE: $OUT" | tee -a runs/e2e_watch.log
    exit 0
  fi
  sleep 300
done
echo "e2e watcher: out of attempts" | tee -a runs/e2e_watch.log
exit 1
