#!/bin/bash
# Retry the on-chip e2e quality run until its artifacts land.
#
# Same philosophy as scripts/tpu_watch.py (the bench-ladder watcher): this
# image's TPU tunnel wedges at backend init for stretches and clears on its
# own. Two hardenings beyond run→retry:
#   1. PROBE-GATED: a 120s jax.devices() probe decides whether the tunnel
#      is worth an attempt — a wedged backend-init otherwise burns ~25 min
#      of the cycle before failing.
#   2. SMOKE BANKING: on the first live probe, the ~4-minute smoke-size
#      on-chip loop (e2e_quality.py --mode smoke --on-chip) runs before the
#      ~13-minute full byte_25m run, so even a window too short for the
#      full run leaves a committed-grade on-chip artifact.
# Success is judged by the artifacts, not exit codes: sample.txt is written
# LAST by e2e_quality.py, so eval.json + sample.txt means the whole
# prepare→train→eval→serve chain completed.
# Runs land under runs/ (scratch, gitignored) and are PROMOTED into the
# git-tracked docs/e2e/ dirs only on success — a failed or interrupted cycle
# must never delete the last committed good artifact.
#
# Usage: bash scripts/e2e_watch.sh [OUT_DIR] [CYCLES] [FULL_TIMEOUT_S]
set -u
OUT=${1:-runs/e2e/full_tpu}
CYCLES=${2:-60}
TMO=${3:-2400}
SMOKE_OUT=${SMOKE_OUT:-runs/e2e/smoke_tpu_live}
PUBLISH_FULL=${PUBLISH_FULL:-docs/e2e/full_tpu}
PUBLISH_SMOKE=${PUBLISH_SMOKE:-docs/e2e/smoke_tpu_live}
cd "$(dirname "$0")/.."
mkdir -p runs
# a stale artifact from a previous SCRATCH run must not count as this run's
# success (the published docs/e2e/ copies are left untouched)
rm -f "$OUT/eval.json" "$OUT/sample.txt" "$SMOKE_OUT/eval.json" "$SMOKE_OUT/sample.txt"
publish() { # publish SRC_DIR DEST_DIR: copy a completed run's artifacts
  # top-level files only: the run's scratch data/ and ckpt/ dirs stay in
  # runs/ (they were gitignored even under docs/e2e/)
  mkdir -p "$2" && find "$1" -maxdepth 1 -type f -exec cp {} "$2"/ \; && \
    echo "[$(date +%H:%M:%S)] published $1 -> $2" | tee -a runs/e2e_watch.log
}
probe() {
  timeout -k 10 120 python - <<'EOF' >/dev/null 2>&1
import jax
assert jax.devices()[0].platform != "cpu"
EOF
}
for i in $(seq 1 "$CYCLES"); do
  if probe; then
    echo "[$(date +%H:%M:%S)] probe LIVE (cycle $i)" | tee -a runs/e2e_watch.log
    if [ ! -f "$SMOKE_OUT/eval.json" ] || [ ! -f "$SMOKE_OUT/sample.txt" ]; then
      timeout -k 30 900 python scripts/e2e_quality.py --mode smoke --on-chip \
        --out "$SMOKE_OUT" > "runs/e2e_smoke_tpu_$i.log" 2>&1
      echo "[$(date +%H:%M:%S)] smoke-on-chip rc=$?" | tee -a runs/e2e_watch.log
      if [ -f "$SMOKE_OUT/eval.json" ] && [ -f "$SMOKE_OUT/sample.txt" ]; then
        publish "$SMOKE_OUT" "$PUBLISH_SMOKE"  # bank the smoke artifact now
      fi
    fi
    timeout -k 30 "$TMO" python scripts/e2e_quality.py --mode full --out "$OUT" \
      > "runs/e2e_full_tpu_$i.log" 2>&1
    echo "[$(date +%H:%M:%S)] full rc=$? (runs/e2e_full_tpu_$i.log)" | tee -a runs/e2e_watch.log
    if [ -f "$OUT/eval.json" ] && [ -f "$OUT/sample.txt" ]; then
      publish "$OUT" "$PUBLISH_FULL"
      echo "E2E DONE: $OUT" | tee -a runs/e2e_watch.log
      exit 0
    fi
  else
    echo "[$(date +%H:%M:%S)] probe wedged (cycle $i)" | tee -a runs/e2e_watch.log
  fi
  sleep 240
done
echo "e2e watcher: out of cycles" | tee -a runs/e2e_watch.log
exit 1
