"""Measure the save-tick overhead of checkpoint integrity manifests.

The integrity manifest (checkpoint.py: per-leaf uint32 bit-sum digests,
computed on device in one jit call) rides every save; its budget is <5% of
the save tick. This script measures it honestly on a mid-sized state —
digesting is bandwidth-bound, so a toy state would flatter the ratio while
a real one is dominated by orbax's array serialization — and writes the
one-line JSON artifact ``BENCH_ckpt_integrity.json``:

    {"digest_ms": ..., "save_ms": ..., "overhead_frac": ...,
     "state_mb": ..., "leaves": ..., "best_of": ..., "platform": ...,
     "measured_at_utc": ...}

Measured against the PRODUCTION checkpoint configuration (async_save=True):
a save tick spans save() -> commit, and the digest — computed before
staging — extends that span by digest_ms, so
``overhead_frac = digest_ms / save_ms`` is exactly the tick extension the
manifest costs. ``save_block_ms`` additionally reports the train-loop-
blocking portion (staging + digest) for operators budgeting the loop
stall.

Platform caveat, stated rather than hidden: this image's CPU container has
2 shared cores and a page-cache-speed local filesystem — the digest
(compute-bound) is maximally penalized and the write (storage-bound)
maximally flattered, so the measured CPU ratio is an upper bound that does
NOT transfer to the deployment platform. On a TPU pod the same digest is a
bandwidth-bound on-device reduction (hundreds of GB/s against a
multi-GB/s GCS write), putting the true overhead well under 1%. The
committed artifact therefore carries ``digest_gbps`` so the budget test
(tests/test_bench_artifact.py::test_ckpt_integrity_artifact_budget) can
pin <5% on accelerator-measured artifacts and a bandwidth-sanity backstop
on CPU ones.

Usage: JAX_PLATFORMS=cpu python scripts/ckpt_overhead_bench.py [--out PATH]
"""
from __future__ import annotations

import argparse
import json
import shutil
import sys
import tempfile
import time
from datetime import datetime, timezone
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--out", default="BENCH_ckpt_integrity.json")
    parser.add_argument("--best-of", type=int, default=3)
    args = parser.parse_args()

    import jax
    import jax.numpy as jnp

    from zero_transformer_tpu import checkpoint as ckpt_lib
    from zero_transformer_tpu.config import (
        MeshConfig,
        ModelConfig,
        OptimizerConfig,
    )
    from zero_transformer_tpu.models.gpt import Transformer
    from zero_transformer_tpu.parallel.mesh import make_mesh
    from zero_transformer_tpu.parallel.zero import init_train_state, make_plan
    from zero_transformer_tpu.training.optimizer import make_optimizer

    # mid-sized bench config: ~6M params -> ~70 MB of f32 state with adam's
    # two moments (big enough that orbax is writing real bytes, small
    # enough to run in seconds on the CPU image)
    cfg = ModelConfig(
        vocab_size=2048, d_model=256, n_heads=8, n_layers=8,
        max_seq_len=128, dropout=0.0,
    )
    mesh = make_mesh(MeshConfig())
    model = Transformer(cfg)
    tx = make_optimizer(OptimizerConfig(warmup_steps=10, total_steps=100))
    shape = (8, 128)
    plan = make_plan(model, tx, mesh, shape, zero_stage=1)

    def fresh_state(seed):
        # a FRESH state per round: jax caches an array's host conversion
        # (_npy_value) after the first digest, which would flatter every
        # later round — real saves always digest never-before-seen buffers
        return init_train_state(
            model, tx, jax.random.PRNGKey(seed), mesh, shape, plan
        )

    state = fresh_state(0)
    state_bytes = sum(
        l.size * jnp.dtype(l.dtype).itemsize for l in jax.tree.leaves(state)
    )
    n_leaves = len(jax.tree.leaves(state))

    # warm the digest path (jit compile / thread-pool spin-up paid once)
    ckpt_lib.tree_digests(state)

    digest_ms = []
    save_ms = []
    block_ms = []
    root = Path(tempfile.mkdtemp(prefix="ckpt_overhead_"))
    try:
        for i in range(args.best_of):
            state = fresh_state(i + 1)
            jax.block_until_ready(state)
            step_root = root / f"round{i}"
            mgr = ckpt_lib.CheckpointManager(
                step_root, keep=1, save_frequency=1, async_save=True,
                integrity=True,
            )
            t0 = time.perf_counter()
            assert mgr.save(1, state, force=True)
            block_ms.append((time.perf_counter() - t0) * 1e3)
            mgr.wait()
            save_ms.append((time.perf_counter() - t0) * 1e3)
            digest_ms.append(mgr.last_digest_ms)
            mgr.close()
    finally:
        shutil.rmtree(root, ignore_errors=True)

    best_save = min(save_ms)
    best_digest = min(digest_ms)
    artifact = {
        "digest_ms": round(best_digest, 3),
        "save_ms": round(best_save, 3),
        "save_block_ms": round(min(block_ms), 3),
        "overhead_frac": round(best_digest / best_save, 5),
        "digest_gbps": round(state_bytes / 1e9 / (best_digest / 1e3), 3),
        "state_mb": round(state_bytes / 1e6, 1),
        "leaves": n_leaves,
        "best_of": args.best_of,
        "platform": jax.default_backend(),
        "measured_at_utc": datetime.now(timezone.utc).strftime(
            "%Y-%m-%dT%H:%M:%SZ"
        ),
    }
    Path(args.out).write_text(json.dumps(artifact) + "\n")
    print(json.dumps(artifact))


if __name__ == "__main__":
    main()
