#!/usr/bin/env python
"""Automap-style system autotuner: analytic pruning + measured search over
the training and serving knob spaces, per (model, hardware, workload).

The thesis (Automap, arXiv 2112.02958; ROADMAP item 5): the repo already
has everything a search needs — deterministic bench harnesses as the cost
model, config validation + ``spec_check`` as the validity oracle, bitwise
parity suites as the correctness gate — so hand-picked defaults should not
be load-bearing. Per run:

1. **enumerate** the declared ``KnobSpace`` (``analysis/autotune.py``) —
   every knob registered with its domain, its ``Config`` field, and which
   bench grades it;
2. **analytically pre-prune**: config-validation refusals (the exact
   ``ValueError`` a real run raises), redundancy dedup (inert-knob
   duplicates), the ``analysis.memory`` stash/gather-buffer budget, and
   workload/backend feasibility — every pruned point recorded with its
   reason, so the trace is auditable;
3. **measured trials** through the existing harnesses (the
   ``serve_loadgen`` engine workload replay for serve, a
   ``train_step_bench``-style timed step for train) under a fixed seed
   and a frozen workload spec (``configs/workloads/*.json``), with
   successive halving so cheap short trials gate expensive long ones;
4. emit a committed, provenance-labeled ``TUNE_<target>.json`` (winner
   config, full search trace, platform block, workload hash) that
   ``train.py --tuned`` / ``serve.py --tuned`` load as defaults — and
   refuse loudly when platform/model/workload do not match.

Honesty discipline (the BENCH_ckpt_integrity/BENCH_step rules): every
number in the artifact was measured on THIS box and says so in the
platform block; the winner-vs-hand-defaults ratio is a within-run A/B
(same workload, same seed, minutes apart), and ``--reruns 2`` certifies
that the same (seed, space, workload) reproduces the same winner and
search-trace fingerprint before the artifact is written.

    JAX_PLATFORMS=cpu python scripts/autotune.py --target serve --reruns 2
    JAX_PLATFORMS=cpu python scripts/autotune.py --target train --reruns 2
    python scripts/autotune.py --target serve --smoke   # make tune-smoke
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))
sys.path.insert(0, str(Path(__file__).resolve().parent))

# TRAIN trials want the 8-device virtual mesh (the arrangement
# train_step_bench and the tier-1 suite use); SERVE trials must run the
# real single-device topology `serve.py` serves on — tuning serving knobs
# under a different device count than production would poison every
# dispatch-overhead-sensitive ranking, and the platform block records
# device_count so the --tuned gate can tell the difference. The env var
# must be set before this process first initializes a backend, hence the
# argv peek (argparse has not run yet at import time).
_argv = sys.argv[1:]
_IS_TRAIN_TARGET = "--target=train" in _argv or any(
    a == "train" and i > 0 and _argv[i - 1] == "--target"
    for i, a in enumerate(_argv)
)
if _IS_TRAIN_TARGET:
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    )

import bench_common  # noqa: E402

# train workload spec: a file may pin any subset; the rest comes from these
# defaults, and the artifact hashes the fully RESOLVED spec (the same rule
# serve_loadgen.resolve_workload applies to the serve spec, so a partial
# file can never produce a hash that silently matches nothing)
TRAIN_WORKLOAD_DEFAULTS = {
    "model": "test", "batch": 8, "seq": 32, "steps_final": 3, "seed": 0,
}


def parse_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--target", choices=("train", "serve"), required=True)
    p.add_argument("--workload", default=None, metavar="SPEC_JSON",
                   help="frozen workload spec (default: "
                        "configs/workloads/tune_<target>.json)")
    p.add_argument("--out", default=None,
                   help="artifact path (default: TUNE_<target>.json)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--repeats", type=int, default=2,
                   help="best-of repeats per timed window at the final "
                        "rung (the BENCHMARKS.md best-of-N discipline)")
    p.add_argument("--reruns", type=int, default=1,
                   help="2 = run the whole search twice and certify the "
                        "same winner + trace fingerprint (the determinism "
                        "field of the artifact)")
    p.add_argument("--keep-frac", type=float, default=0.5,
                   help="fraction of arms promoted per halving rung")
    p.add_argument("--tie-frac", type=float, default=0.02,
                   help="relative noise floor for ranking: arms scoring "
                        "within this fraction of the rung's best are a "
                        "statistical tie and resolve deterministically by "
                        "arm index (0 = raw scores)")
    p.add_argument("--hbm-budget-gb", type=float, default=16.0,
                   help="per-device analytic memory budget for the train "
                        "pruner (the 16 GB chip discipline)")
    p.add_argument("--no-prune-pipe", action="store_true",
                   help="keep pipe>1 points in the measured set (default: "
                        "analytic backend_capability prune — this image's "
                        "jax cannot execute the pipe engine, see "
                        "BENCH_step.json bubble.measured)")
    p.add_argument("--smoke", action="store_true",
                   help="tiny space + single rung: the make tune-smoke "
                        "lane (schema + determinism mechanics, not a "
                        "committed tuning run)")
    p.add_argument("--list", action="store_true",
                   help="print the space + prune summary and exit (no "
                        "measured trials)")
    return p.parse_args(argv)


def _load_loadgen():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "serve_loadgen", REPO / "scripts" / "serve_loadgen.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ------------------------------------------------------------- serve target


class ServeHarness:
    """Measured serve trials: one frozen workload replayed through a real
    ``ServingEngine`` per candidate point (the serve_loadgen harness,
    minus the artifact plumbing). Greedy workload -> every final arm is
    byte-verified against single-request ``generate()``."""

    def __init__(self, args, wl_spec):
        import jax
        import jax.numpy as jnp

        from zero_transformer_tpu.config import model_config
        from zero_transformer_tpu.inference.sampling import SamplingConfig
        from zero_transformer_tpu.models import Transformer

        self.loadgen = _load_loadgen()
        # one loadgen args namespace carries the workload for request
        # generation and the run_load client loop
        self.wl_args = self.loadgen.parse_args(["--out", "/dev/null"])
        for key, value in wl_spec.items():
            setattr(self.wl_args, key, value)
        self.cfg = model_config(wl_spec["model"], dropout=0.0)
        self.params = Transformer(self.cfg).init(
            jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
        )["params"]
        self.sampling = SamplingConfig(
            temperature=0.9, top_k=20, greedy=bool(wl_spec["greedy"])
        )
        self.cache_len = wl_spec["cache_len"] or self.cfg.max_seq_len
        self.requests = self.loadgen.make_requests(
            self.wl_args, self.cfg.vocab_size, self.cache_len
        )
        self.repeats = max(1, args.repeats)
        self._warm: set = set()
        self._refs = None

    def engine(self, knobs, trace=False):
        from zero_transformer_tpu.config import ServingConfig
        from zero_transformer_tpu.serving import ServingEngine

        paged = knobs["kv_layout"] == "paged"
        # prefix cache at its ServingConfig hand default: trials measure
        # the configuration `serve.py --tuned` actually DEPLOYS (the cache
        # interacts with layout and chunking; a no-cache winner would be
        # optimal for an engine nobody runs)
        prefix_chunks = (
            ServingConfig().prefix_cache_chunks
            if knobs["prefill_chunk"] else 0
        )
        return ServingEngine(
            self.cfg, self.params, n_slots=self.wl_args.slots,
            cache_len=self.cache_len, sampling=self.sampling,
            max_queue=self.wl_args.max_queue,
            prefill_chunk=knobs["prefill_chunk"],
            prefix_cache_chunks=prefix_chunks,
            kv_layout=knobs["kv_layout"],
            page_size=knobs["page_size"],
            page_pool_tokens=knobs["page_pool_tokens"] if paged else 0,
            draft_k=knobs["draft_k"],
            fused_tail=knobs["fused_tail"],
            trace=trace,
        )

    def measure(self, knobs, budget, repeats=1, verify=False):
        key = json.dumps(knobs, sort_keys=True)
        requests = self.requests[:budget]
        if key not in self._warm:
            # pay every compile outside the measured window (jit caches
            # are shared across engines: same statics, same programs)
            warm = self.engine(knobs)
            for prompt, seed in requests[: self.wl_args.slots + 1]:
                warm.submit(
                    prompt, max_new_tokens=self.wl_args.max_new_tokens,
                    seed=seed,
                )
            warm.run_until_idle()
            self._warm.add(key)
        best = None
        handles = None
        for _ in range(repeats):
            eng = self.engine(knobs)
            hs, wall = self.loadgen.run_load(eng, requests, self.wl_args)
            toks = sum(len(h.tokens) for h in hs if h is not None)
            snap = eng.metrics_snapshot()
            incomplete = sum(
                1 for h in hs if h is None or h.status != "done"
            )
            if incomplete:
                return {
                    "ok": False,
                    "error": f"{incomplete} of {len(requests)} requests "
                             "did not complete",
                }
            point = {
                "decode_tok_s": round(toks / wall, 3),
                "itl_ms_p50": round(snap["itl_ms_p50"], 3),
                "itl_ms_p99": round(snap["itl_ms_p99"], 3),
                "wall_s": round(wall, 3),
                "requests": len(requests),
            }
            if best is None or point["decode_tok_s"] > best["decode_tok_s"]:
                best, handles = point, hs
        if verify:
            if self._refs is None:
                self._refs = self.loadgen.reference_outputs(
                    self.cfg, self.params, self.sampling, self.cache_len,
                    self.requests, self.wl_args.max_new_tokens,
                )
            mismatches = sum(
                1 for h, ref in zip(handles, self._refs[:budget])
                if h.tokens != ref
            )
            best["verified"] = True
            best["mismatches"] = mismatches
            if mismatches:
                return {
                    "ok": False, "metrics": best,
                    "error": f"{mismatches} trajectories diverged from "
                             "single-request generate() — correctness "
                             "gate failed",
                }
        # lower score is better; tok/s is the headline, maximize it
        return {"ok": True, "score": -best["decode_tok_s"], "metrics": best}

    def budgets(self, smoke):
        n = len(self.requests)
        if smoke:
            return [n]
        return [max(2, n // 2), n]


# ------------------------------------------------------------- train target


class TrainHarness:
    """Measured train trials: a timed real train step per candidate point
    (the train_step_bench harness pattern). ``make_plan`` runs
    ``spec_check`` on every candidate BEFORE compile — an invalid plan
    raises here, it never executes."""

    def __init__(self, args, wl_spec):
        self.wl = wl_spec
        self.repeats = max(1, args.repeats)
        self._built: dict = {}

    def _build(self, knobs):
        import jax
        import jax.numpy as jnp

        from zero_transformer_tpu.config import (
            MeshConfig,
            OptimizerConfig,
            model_config,
        )
        from zero_transformer_tpu.models import Transformer
        from zero_transformer_tpu.parallel.mesh import make_mesh
        from zero_transformer_tpu.parallel.zero import (
            init_train_state,
            make_plan,
            make_train_step,
        )
        from zero_transformer_tpu.training.optimizer import (
            make_optimizer,
            make_schedule,
        )

        cfg = model_config(
            self.wl["model"], dropout=0.0, compute_dtype="float32",
            remat=knobs["remat"], remat_policy=knobs["remat_policy"],
        )
        opt = OptimizerConfig(warmup_steps=10, total_steps=1000)
        mc = MeshConfig(
            zero_stage=knobs["zero_stage"], pipe=knobs["pipe"],
            pp_schedule=knobs["pp_schedule"],
            pp_interleave=knobs["pp_interleave"],
            overlap_comm=knobs["overlap_comm"],
        )
        mesh = make_mesh(mc)
        model = Transformer(cfg)
        tx = make_optimizer(opt)
        # accum MICROBATCHES the workload's FIXED global batch (B = global
        # / accum): every arm sees the same tokens per optimizer step and
        # the same mean gradient (fp reduction order aside), so accum is a
        # pure perf knob here — never a silent change to the optimization
        # trajectory a --tuned user would inherit
        T, accum = self.wl["seq"], knobs["accum"]
        B = self.wl["batch"] // accum
        plan = make_plan(  # spec_check fires in here, pre-compile
            model, tx, mesh, (B, T), knobs["zero_stage"],
            pp_schedule=knobs["pp_schedule"],
        )
        step = make_train_step(
            model, tx, mesh, plan, knobs["zero_stage"], make_schedule(opt),
            tx_factory=lambda nf, zc=None: make_optimizer(
                opt, make_schedule(opt), nf, zero_collectives=zc
            ),
            pp_schedule=knobs["pp_schedule"],
            pp_interleave=knobs["pp_interleave"],
            overlap_comm=knobs["overlap_comm"],
        )
        state = init_train_state(
            model, tx, jax.random.PRNGKey(0), mesh, (B, T), plan
        )
        batch = jax.random.randint(
            jax.random.PRNGKey(self.wl["seed"] + 1), (accum, B, T), 0,
            cfg.vocab_size, jnp.int32,
        )
        rng = jax.random.PRNGKey(self.wl["seed"] + 2)
        state, metrics = step(state, batch, rng)  # compile + warm
        loss = float(metrics["loss"])
        if loss != loss:  # NaN guard: a diverged trial must not win on speed
            raise RuntimeError(f"non-finite warmup loss {loss}")
        return {"step": step, "state": state, "batch": batch, "rng": rng,
                "tokens_per_step": self.wl["batch"] * T}

    def measure(self, knobs, budget_steps, repeats=1):
        key = json.dumps(knobs, sort_keys=True)
        try:
            if key not in self._built:
                self._built[key] = self._build(knobs)
        except Exception as e:  # noqa: BLE001 — recorded, never hidden
            self._built[key] = None
            return {"ok": False, "error": f"{type(e).__name__}: {e}"}
        built = self._built[key]
        if built is None:
            return {"ok": False, "error": "build failed in an earlier rung"}
        step, state = built["step"], built["state"]
        best_ms = float("inf")
        for _ in range(max(1, repeats)):
            t0 = time.perf_counter()
            for _ in range(budget_steps):
                state, metrics = step(state, built["batch"], built["rng"])
            float(metrics["loss"])  # sync barrier (bench.py discipline)
            best_ms = min(
                best_ms, (time.perf_counter() - t0) / budget_steps * 1e3
            )
        built["state"] = state
        tok_s = built["tokens_per_step"] / (best_ms / 1e3)
        metrics_out = {
            "step_ms": round(best_ms, 3),
            "tokens_per_step": built["tokens_per_step"],
            "tokens_per_s": round(tok_s, 1),
        }
        return {"ok": True, "score": -tok_s, "metrics": metrics_out}

    def budgets(self, smoke):
        if smoke:
            return [max(1, self.wl["steps_final"] - 1)]
        # rung 0 at 2 steps (a 1-step window is pure scheduler noise on a
        # shared box); the final rung runs the workload's full window
        return [2, self.wl["steps_final"]]


# ------------------------------------------------------------------ spaces


def build_space(target, smoke):
    from zero_transformer_tpu.analysis import autotune as at

    if not smoke:
        return at.train_space() if target == "train" else at.serve_space()
    # tiny smoke spaces: the mechanics (enumerate -> prune -> trial ->
    # artifact) on a 2-arm search that runs in seconds
    s = at.KnobSpace(target)
    if target == "train":
        s.register(at.Knob("overlap_comm", (False, True),
                           "mesh.overlap_comm", "train", "BENCH_step"))
        s.register(at.Knob("zero_stage", (1,), "mesh.zero_stage",
                           "train", "BENCH_step"))
        s.register(at.Knob("pipe", (1,), "mesh.pipe", "train", "BENCH_step"))
        s.register(at.Knob("pp_schedule", ("gpipe",), "mesh.pp_schedule",
                           "train", "BENCH_step"))
        s.register(at.Knob("pp_interleave", (1,), "mesh.pp_interleave",
                           "train", "BENCH_step"))
        s.register(at.Knob("accum", (1,),
                           "training.gradient_accumulation_steps",
                           "train", "BENCH_step"))
        s.register(at.Knob("remat", (False,), "model.remat",
                           "train", "BENCH_step"))
        s.register(at.Knob("remat_policy", ("none", "dots"),
                           "model.remat_policy", "train", "BENCH_step"))
    else:
        s.register(at.Knob("kv_layout", ("paged",), "serving.kv_layout",
                           "serve", "BENCH_serve"))
        s.register(at.Knob("prefill_chunk", (8,), "serving.prefill_chunk",
                           "serve", "BENCH_serve"))
        s.register(at.Knob("page_size", (4, 6), "serving.page_size",
                           "serve", "BENCH_serve"))
        s.register(at.Knob("page_pool_tokens", (0,),
                           "serving.page_pool_tokens", "serve",
                           "BENCH_serve"))
        s.register(at.Knob("draft_k", (0, 4), "serving.draft_k",
                           "serve", "BENCH_serve"))
        s.register(at.Knob("fused_tail", (True,), "serving.fused_tail",
                           "serve", "BENCH_serve"))
    return s


def build_validators(args, target, space, wl_spec, cache_len=None):
    from zero_transformer_tpu.analysis import autotune as at
    from zero_transformer_tpu.config import Config, apply_dotted_overrides

    base_cfg = Config()
    if target == "serve":
        # tuning engines run the prefix cache off (it is not a searched
        # knob); left at the shipped default it would mask the REAL refusal
        # for prefill_chunk=0 points behind its own coupling rule
        base_cfg = apply_dotted_overrides(
            base_cfg, {"serving.prefix_cache_chunks": 0}
        )
    validators = [at.config_validator(space, base_cfg)]
    if target == "train":
        validators.append(at.train_redundancy_validator())
        validators.append(("model_divisibility", _train_divisibility(wl_spec)))
        if not args.no_prune_pipe:
            validators.append(("backend_capability", _pipe_capability()))
        validators.append(at.train_memory_validator(
            space, base_cfg, int(args.hbm_budget_gb * (1 << 30)), 8
        ))
    else:
        validators.append(at.serve_redundancy_validator())
        # the harness' resolved cache_len (workload value or the model's
        # max_seq_len) — the pruner and the measured engines must agree on
        # the geometry or the feasibility rules prune/admit the wrong set
        validators.append(at.serve_feasibility_validator(cache_len))
    return validators


def _train_divisibility(wl_spec):
    from zero_transformer_tpu.config import model_config

    n_layers = model_config(wl_spec["model"]).n_layers

    def check(point):
        pipe, v = point.get("pipe", 1), point.get("pp_interleave", 1)
        accum = point.get("accum", 1)
        if wl_spec["batch"] % accum:
            return (
                f"accum={accum} does not divide the workload's global "
                f"batch={wl_spec['batch']} (accum microbatches a FIXED "
                "global batch — same tokens per optimizer step in every "
                "arm)"
            )
        if wl_spec["batch"] // accum < 1:
            return (
                f"accum={accum} leaves no sequences per microbatch at "
                f"global batch {wl_spec['batch']}"
            )
        if pipe > 1 and n_layers % pipe:
            return (
                f"n_layers={n_layers} not divisible by pipe={pipe} "
                "(layer sharding would be ragged; make_train_step refuses)"
            )
        if point.get("pp_schedule") == "interleaved":
            if n_layers % (pipe * v):
                return (
                    f"interleaved needs n_layers % (pipe*V) == 0 "
                    f"({n_layers} % {pipe * v} != 0)"
                )
            if point.get("accum", 1) % pipe:
                return (
                    f"interleaved needs accum % pipe == 0 "
                    f"({point.get('accum')} % {pipe} != 0)"
                )
        return None

    return check


def _pipe_capability():
    def check(point):
        if point.get("pipe", 1) > 1:
            return (
                "pipe>1: this image's jax cannot execute the pipe engine "
                "(the known old-jax-0.4.37 incompat recorded verbatim in "
                "BENCH_step.json bubble.measured); excluded from measured "
                "trials on this platform — pass --no-prune-pipe on a "
                "capable backend"
            )
        return None

    return check


def hand_defaults(target, space):
    """The hand-picked defaults as a point of the knob space: the Config()
    field values the repo ships — the baseline arm the winner must beat."""
    from zero_transformer_tpu.config import Config

    cfg = Config()
    point = {}
    for knob in space.knobs:
        section, _, field = knob.field.partition(".")
        point[knob.name] = getattr(getattr(cfg, section), field)
    return point


# -------------------------------------------------------------------- main


def run_search(
    args, target, wl_spec, wl_name, harness, measure_baseline=True, log=print
):
    """One full search pass: enumerate -> prune -> successive halving ->
    (winner, baseline, trace pieces). Deterministic mechanics; measured
    scores come from the harness."""
    from zero_transformer_tpu.analysis import autotune as at

    space = build_space(target, args.smoke)
    points = space.points()
    validators = build_validators(
        args, target, space, wl_spec,
        cache_len=getattr(harness, "cache_len", None),
    )
    survivors, pruned = at.prune_points(points, validators)
    log(
        f"autotune[{target}]: {len(points)} enumerated, {len(pruned)} "
        f"pruned analytically ({len(pruned) / len(points):.0%}), "
        f"{len(survivors)} measured candidates"
    )
    if args.list:
        for p in pruned:
            log(f"  PRUNE [{p.rule}] {p.knobs}: {p.reason}")
        for i, knobs in survivors:
            log(f"  TRIAL {i}: {knobs}")
        return None
    budgets = harness.budgets(args.smoke)
    arm_knobs = {i: knobs for i, knobs in survivors}

    def measure(arm, budget, rung):
        final = rung == len(budgets) - 1
        if target == "serve":
            return harness.measure(
                arm_knobs[arm], budget,
                repeats=harness.repeats if final else 1, verify=final,
            )
        return harness.measure(
            arm_knobs[arm], budget,
            repeats=harness.repeats if final else 1,
        )

    winner_arm, rungs = at.successive_halving(
        [i for i, _ in survivors], measure, budgets,
        keep_frac=args.keep_frac, tie_frac=args.tie_frac, log=log,
    )
    winner_knobs = arm_knobs[winner_arm]
    winner_final = next(
        t for t in rungs[-1]["trials"] if t["arm"] == winner_arm
    )
    # the baseline arm: hand defaults at the FULL budget, same repeats,
    # same verification — the within-run A/B the improvement claim rests
    # on. Only the first certification pass measures it (reruns certify
    # the WINNER; re-verifying the baseline would be discarded wall-clock)
    baseline_knobs = hand_defaults(target, space)
    baseline_metrics = None
    if measure_baseline:
        if target == "serve":
            base_res = harness.measure(
                baseline_knobs, budgets[-1], repeats=harness.repeats,
                verify=True,
            )
        else:
            base_res = harness.measure(
                baseline_knobs, budgets[-1], repeats=harness.repeats
            )
        if not base_res.get("ok"):
            raise SystemExit(
                f"AUTOTUNE FAILED: the hand-defaults baseline arm failed "
                f"({base_res.get('error')}) — nothing honest to compare "
                "against"
            )
        baseline_metrics = base_res["metrics"]
    fingerprint = at.trace_fingerprint(
        target, wl_spec["model"], at.workload_hash(wl_spec), args.seed,
        space.describe(), pruned, survivors, budgets,
    )
    return {
        "space": space,
        "points": points,
        "survivors": survivors,
        "pruned": pruned,
        "budgets": budgets,
        "rungs": rungs,
        "arm_knobs": arm_knobs,
        "winner_arm": winner_arm,
        "winner_knobs": winner_knobs,
        "winner_metrics": winner_final["metrics"],
        "baseline_knobs": baseline_knobs,
        "baseline_metrics": baseline_metrics,
        "fingerprint": fingerprint,
    }


def main(argv=None):
    args = parse_args(argv)
    # some images pre-import jax with a platform baked into jax.config,
    # where the JAX_PLATFORMS env var alone is a silent no-op (see
    # serve_loadgen.py) — re-assert it through the config
    if os.environ.get("JAX_PLATFORMS"):
        import jax

        try:
            jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
        except RuntimeError:
            pass  # backend already initialized (e.g. under pytest)
    from zero_transformer_tpu.analysis import autotune as at

    target = args.target
    wl_path = Path(
        args.workload or REPO / "configs" / "workloads" / f"tune_{target}.json"
    )
    if target == "train":
        raw = json.loads(wl_path.read_text())
        wl_name = raw.pop("name", wl_path.stem)
        unknown = set(raw) - set(TRAIN_WORKLOAD_DEFAULTS)
        if unknown:
            raise SystemExit(
                f"train workload spec {wl_path}: unknown keys "
                f"{sorted(unknown)}"
            )
        wl_spec = {**TRAIN_WORKLOAD_DEFAULTS, **raw}
        wl_hash = at.workload_hash(wl_spec)
    else:
        # resolve through serve_loadgen itself (file over CLI defaults), so
        # the hash is byte-identical to what a `serve_loadgen --workload`
        # BENCH run embeds — "tuned under this workload" stays checkable
        loadgen = _load_loadgen()
        args_ns = loadgen.parse_args(
            ["--workload", str(wl_path), "--out", "/dev/null"]
        )
        wl_name, wl_spec, wl_hash = loadgen.resolve_workload(args_ns)
    if target == "serve":
        harness = ServeHarness(args, wl_spec)
    else:
        harness = TrainHarness(args, wl_spec)

    passes = []
    for rerun in range(max(1, args.reruns)):
        result = run_search(
            args, target, wl_spec, wl_name, harness,
            measure_baseline=rerun == 0,
        )
        if result is None:  # --list
            return None
        passes.append(result)
        print(
            f"autotune[{target}] pass {rerun}: winner {result['winner_knobs']}"
            f" {result['winner_metrics']}"
        )
    first = passes[0]
    # Determinism certification. The trace STRUCTURE (enumeration, pruning,
    # survivors, budgets) must reproduce exactly — it is a pure function of
    # (seed, space, workload). The measured WINNER certifies as a class
    # property: argmax identity between two independent wall-clock runs is
    # not a certifiable claim on a shared box (two arms inside the noise
    # floor swap raw order freely), so every rerun must instead score the
    # committed winner within --tie-frac of ITS OWN best at the final rung
    # — the rerun reproduces the winner as a member of the top equivalence
    # class, or the artifact is refused.
    fingerprints_equal = all(
        p["fingerprint"] == first["fingerprint"] for p in passes
    )
    winner_arm = first["winner_arm"]
    winner_margins = []
    for p in passes:
        final = {t["arm"]: t for t in p["rungs"][-1]["trials"] if t["ok"]}
        if winner_arm not in final:
            raise SystemExit(
                f"AUTOTUNE FAILED: rerun dropped the committed winner arm "
                f"{winner_arm} from its final rung "
                f"(present: {sorted(final)}) — not reproducible"
            )
        best = min(t["score"] for t in final.values())
        margin = (final[winner_arm]["score"] - best) / abs(best)
        winner_margins.append(round(margin, 4))
    winner_stable = all(m <= args.tie_frac for m in winner_margins)
    if not winner_stable or not fingerprints_equal:
        raise SystemExit(
            "AUTOTUNE FAILED: a rerun scored the winner "
            f"{first['winner_knobs']} outside the {args.tie_frac} noise "
            f"floor of its own best (margins {winner_margins}, "
            f"fingerprints_equal={fingerprints_equal}) — raise --repeats "
            "or --tie-frac honestly, never commit an unreproducible winner"
        )

    space = first["space"]
    if target == "serve":
        metric, hib = "decode_tok_s", True
        base_v = first["baseline_metrics"]["decode_tok_s"]
        win_v = first["winner_metrics"]["decode_tok_s"]
        ratio = win_v / base_v if base_v else 0.0
        unit = "x vs hand defaults (decode_tok_s)"
    else:
        metric, hib = "tokens_per_s", True
        base_v = first["baseline_metrics"]["tokens_per_s"]
        win_v = first["winner_metrics"]["tokens_per_s"]
        ratio = win_v / base_v if base_v else 0.0
        unit = "x vs hand defaults (tokens/s)"

    def tuned_overrides(knobs):
        ov = space.overrides(knobs)
        if target == "train":
            # accum microbatches the workload's fixed global batch, so the
            # loadable overrides pin BOTH fields — a --tuned run reproduces
            # the measured geometry (and its optimizer trajectory), never a
            # silently multiplied batch
            ov["training.batch_size"] = (
                wl_spec["batch"] // max(1, knobs.get("accum", 1))
            )
        return ov

    rules_hist: dict = {}
    for p in first["pruned"]:
        rules_hist[p.rule] = rules_hist.get(p.rule, 0) + 1
    artifact = {
        "metric": f"autotune_{target}_improvement",
        "target": target,
        "value": round(ratio, 4),
        "unit": unit,
        "model": wl_spec["model"],
        "platform": bench_common.platform_block(),
        "workload": {"name": wl_name, "spec": wl_spec},
        "workload_hash": wl_hash,
        "seed": args.seed,
        "provenance": "measured",
        "space": space.describe(),
        "pruning": {
            "enumerated": len(first["points"]),
            "pruned": len(first["pruned"]),
            "survivors": len(first["survivors"]),
            "pruned_frac": round(
                len(first["pruned"]) / len(first["points"]), 4
            ),
            "rules": rules_hist,
            "points": [
                {"index": p.index, "knobs": p.knobs, "rule": p.rule,
                 "reason": p.reason}
                for p in first["pruned"]
            ],
        },
        "search": {
            "algorithm": "successive_halving",
            "keep_frac": args.keep_frac,
            "tie_frac": args.tie_frac,
            "budgets": list(first["budgets"]),
            "repeats": args.repeats,
            "arms": {
                str(i): knobs for i, knobs in first["arm_knobs"].items()
            },
            "rungs": first["rungs"],
        },
        "winner": {
            "knobs": first["winner_knobs"],
            "overrides": tuned_overrides(first["winner_knobs"]),
            "metrics": first["winner_metrics"],
        },
        "baseline": {
            "knobs": first["baseline_knobs"],
            "overrides": tuned_overrides(first["baseline_knobs"]),
            "metrics": first["baseline_metrics"],
        },
        "improvement": {
            "metric": metric,
            "higher_is_better": hib,
            "baseline": base_v,
            "winner": win_v,
            "ratio": round(ratio, 4),
        },
        "determinism": {
            "reruns": max(1, args.reruns),
            "winner_stable": winner_stable,
            "criterion": (
                f"every rerun scores the winner within tie_frac="
                f"{args.tie_frac} of its own final-rung best (argmax "
                "identity between independent wall-clock runs is not a "
                "certifiable claim; top-class membership is)"
            ),
            "winner_margins_frac": winner_margins,
            "fingerprints_equal": fingerprints_equal,
            "fingerprint": first["fingerprint"],
        },
        "measured_at_utc": time.strftime(
            "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
        ),
        "schema_version": at.TUNE_SCHEMA_VERSION,
    }
    out = Path(args.out or REPO / f"TUNE_{target}.json")
    out.write_text(json.dumps(artifact, indent=2) + "\n")
    print(json.dumps({k: artifact[k] for k in (
        "metric", "value", "unit", "model", "platform", "workload_hash",
        "winner", "determinism",
    )}))
    if ratio <= 1.0:
        print(
            f"autotune[{target}]: WARNING — the winner does not beat the "
            f"hand defaults on this box (ratio {ratio:.3f}); the artifact "
            "records it honestly, do not commit it as a win"
        )
    return artifact


if __name__ == "__main__":
    main()
