#!/usr/bin/env python
"""One training-fleet worker process (spawned by train_coordinator.py).

Joins the coordinator, bootstraps state (fresh init / peer state /
verified snapshot restore), then loops: compute owned shards, push grads,
apply the released fold. Prints ``LOSS step=N <loss>`` per applied step
and ``WORKER_OK`` on clean shutdown — the same contract as
tests/multihost_resume_worker.py, so test harnesses parse one format.

Chaos faults are injected per-process via ``--chaos kind@step[:duration]``
(e.g. ``--chaos sigkill@7``, ``--chaos slow_worker@3:0.4``): the process
being killed/frozen/partitioned is THIS one, which is the point.
"""
from __future__ import annotations

import argparse
import os
import sys

# backend config must precede the package import chain (config.py imports
# jax at module scope): one CPU device per worker — each worker is one DP
# rank; the multi-"host" topology is the process fleet itself
os.environ.setdefault("JAX_PLATFORMS", "cpu")

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
sys.path.insert(0, os.path.join(_REPO, "tests"))

import jax  # noqa: E402

# belt and braces: in images where jax is pre-imported at interpreter
# startup the env var above is too late, but no backend is initialized
# yet so the config update still lands (same move as tests/conftest.py)
jax.config.update("jax_platforms", "cpu")

try:
    # shared persistent compile cache (tests/_compile_cache.py): N workers
    # compile the SAME tiny program — without this, N identical XLA compiles
    import _compile_cache  # noqa: E402

    _compile_cache.configure(jax)
except ImportError as e:
    print(f"fleet-worker: no compile cache ({e}); cold compiles", file=sys.stderr)

from zero_transformer_tpu.resilience.chaos import ChaosMonkey, Fault  # noqa: E402
from zero_transformer_tpu.training.fleet import FleetWorker  # noqa: E402


def parse_fault(spec: str) -> Fault:
    """``kind@step[:duration]`` -> Fault (duration in seconds for the
    time-windowed kinds, defaulting to 1)."""
    kind, sep, rest = spec.partition("@")
    if not sep:
        raise ValueError(f"bad --chaos spec {spec!r} (want kind@step[:dur])")
    step_s, _, dur = rest.partition(":")
    return Fault(
        kind=kind, step=int(step_s), duration=float(dur) if dur else 1
    )


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--coordinator", required=True, help="coordinator base URL")
    ap.add_argument("--id", required=True, help="worker id (e.g. w0)")
    ap.add_argument("--ckpt-dir", default=None)
    ap.add_argument(
        "--resume", action="store_true",
        help="restore the newest verified snapshot before joining",
    )
    ap.add_argument(
        "--chaos", action="append", default=[],
        metavar="KIND@STEP[:DUR]", help="inject a process-level fault",
    )
    ap.add_argument("--hb-interval", type=float, default=0.2)
    args = ap.parse_args(argv)

    chaos = (
        ChaosMonkey([parse_fault(s) for s in args.chaos])
        if args.chaos else None
    )
    worker = FleetWorker(
        args.coordinator,
        args.id,
        ckpt_dir=args.ckpt_dir,
        resume=args.resume,
        chaos=chaos,
        hb_interval_s=args.hb_interval,
    )
    applied = worker.run()
    print(f"WORKER_OK applied={applied}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
