#!/usr/bin/env python
"""graftlint CLI: run the repo's invariant rules over the tree.

Usage:
    python scripts/graftlint.py [paths...]            # lint (default tree)
    python scripts/graftlint.py --audit               # + list suppressions
    python scripts/graftlint.py --rule donation-safety path/to/file.py
    python scripts/graftlint.py --json                # machine-readable

Exit status: 0 when every finding is suppressed-with-a-reason, 1 otherwise.
Loads the analyzer module directly by file path — no jax, no package
``__init__`` chain — so the whole-tree pass costs seconds (single AST walk
per file).
"""
from __future__ import annotations

import argparse
import importlib.util
import json
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def _load_static_rules():
    path = REPO / "zero_transformer_tpu" / "analysis" / "static_rules.py"
    spec = importlib.util.spec_from_file_location("graftlint_static", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod  # dataclasses resolve types via sys.modules
    spec.loader.exec_module(mod)
    return mod


DEFAULT_PATHS = [
    "zero_transformer_tpu",
    "scripts",
    "train.py",
    "bench.py",
]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("paths", nargs="*", default=None)
    ap.add_argument(
        "--rule",
        action="append",
        help="run only this rule (repeatable); default: all rules",
    )
    ap.add_argument(
        "--audit",
        action="store_true",
        help="list every suppression with its reason (the audit trail)",
    )
    ap.add_argument("--json", action="store_true", help="JSON output")
    args = ap.parse_args(argv)

    sr = _load_static_rules()
    unknown = [r for r in (args.rule or []) if r not in sr.ALL_RULES]
    if unknown:
        # a typo'd rule name must not run zero rules and report "clean"
        print(
            f"graftlint: unknown rule(s) {', '.join(unknown)} "
            f"(known: {', '.join(sr.ALL_RULES)})",
            file=sys.stderr,
        )
        return 2
    t0 = time.monotonic()
    paths = [REPO / p for p in (args.paths or DEFAULT_PATHS)]
    paths = [p for p in paths if p.exists()]
    mesh_axes = sr.refresh_mesh_axes(REPO)
    findings = sr.analyze_paths(paths, rules=args.rule, mesh_axes=mesh_axes)
    for f in findings:
        try:
            f.path = str(Path(f.path).relative_to(REPO))
        except ValueError:
            pass
    elapsed = time.monotonic() - t0

    active = [f for f in findings if not f.suppressed]
    suppressed = [f for f in findings if f.suppressed]

    if args.json:
        print(
            json.dumps(
                {
                    "elapsed_s": round(elapsed, 3),
                    "files": len(sr.iter_python_files(paths)),
                    "active": [vars(f) for f in active],
                    "suppressed": [vars(f) for f in suppressed],
                },
                indent=2,
            )
        )
        return 1 if active else 0

    for f in active:
        print(f.format())
    if args.audit:
        if suppressed:
            print(f"\n-- suppression audit ({len(suppressed)}) --")
        for f in suppressed:
            print(f"{f.path}:{f.line}: allow[{f.rule}] reason={f.reason}")
    n_files = len(sr.iter_python_files(paths))
    status = "clean" if not active else f"{len(active)} unsuppressed finding(s)"
    print(
        f"\ngraftlint: {n_files} files, {len(findings)} finding(s) "
        f"({len(suppressed)} suppressed) in {elapsed:.2f}s -- {status}"
    )
    return 1 if active else 0


if __name__ == "__main__":
    sys.exit(main())
