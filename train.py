"""Training entry point (reference: ``python main_zero.py``, ``main_zero.py:41-55``).

Usage:
    python train.py --cfg configs/train_125m.yaml [--resume] [--set key=value ...]

``--set`` overrides any dotted config field, e.g.
``--set training.total_steps=100 model.n_layers=4``.
"""
from __future__ import annotations

import argparse
import ast
import dataclasses
import logging

import jax


def parse_overrides(pairs):
    out = {}
    for pair in pairs or []:
        key, _, raw = pair.partition("=")
        try:
            out[key] = ast.literal_eval(raw)
        except (ValueError, SyntaxError):
            out[key] = raw
    return out


def apply_overrides(cfg, overrides: dict):
    # one implementation with the autotuner's candidate-point construction:
    # config.apply_dotted_overrides (model.size-first ordering included)
    from zero_transformer_tpu.config import apply_dotted_overrides

    return apply_dotted_overrides(cfg, overrides)


def _bench_common():
    """scripts/bench_common.py via the shared by-path loader (the platform
    gate the bench guards and both --tuned surfaces use)."""
    from zero_transformer_tpu.utils.modload import load_script

    return load_script("bench_common.py")


# tuned-override couples (see scripts/autotune.py tuned_overrides): these
# fields are only meaningful TOGETHER — accum microbatches the tuned
# workload's fixed global batch, so batch_size rides with it. A user
# override of either member drops the whole group, never leaving half a
# pair applied (a stranded tuned batch_size would silently change the
# global batch — exactly what the pairing exists to prevent).
_COUPLED_TUNED_FIELDS = (
    ("training.gradient_accumulation_steps", "training.batch_size"),
)


def apply_tuned(cfg, path, user_overrides, logger=None):
    """Load a TUNE_train.json autotuner artifact (scripts/autotune.py) as
    config defaults. The artifact only applies where it was measured: a
    platform/model/target mismatch is REFUSED with a loud warning and the
    hand defaults stand (the BENCH_ckpt_integrity/BENCH_step honesty
    discipline — never silently apply foreign tuning). Explicit --set
    overrides always win over tuned values."""
    import logging

    from zero_transformer_tpu.analysis.autotune import winner_overrides

    logger = logger or logging.getLogger("zero_transformer_tpu")
    bc = _bench_common()
    artifact, reasons = bc.load_tuned(
        path, platform=bc.platform_block(), model=cfg.model.name,
        target="train",
    )
    if artifact is None:
        logger.warning(
            "--tuned %s REFUSED (%s); falling back to hand defaults",
            path, "; ".join(reasons),
        )
        return cfg
    overrides = {
        k: v for k, v in winner_overrides(artifact).items()
        if k not in user_overrides
    }
    for group in _COUPLED_TUNED_FIELDS:
        if any(k in user_overrides for k in group):
            dropped = [k for k in group if overrides.pop(k, None) is not None]
            if dropped:
                logger.warning(
                    "--tuned %s: dropping coupled tuned fields %s — the "
                    "user overrode %s and these only hold as a pair "
                    "(fixed global batch)",
                    path, dropped,
                    [k for k in group if k in user_overrides],
                )
    logger.info(
        "--tuned %s: applying autotuned defaults %s (tuned on %s, "
        "workload %s, improvement %sx)",
        path, overrides, artifact.get("platform"),
        artifact.get("workload_hash"), artifact.get("value"),
    )
    return apply_overrides(cfg, overrides)


def main():
    parser = argparse.ArgumentParser(description="TPU-native ZeRO transformer trainer")
    parser.add_argument("--cfg", default="configs/train_test.yaml")
    parser.add_argument(
        "--resume",
        action="store_true",
        default=False,
        help="resume from the newest VERIFIED checkpoint (corrupt step dirs "
        "are quarantined with fallback to an older verified step). Elastic: "
        "resuming onto a DIFFERENT device/host count than the checkpoint "
        "was saved under reshards the ZeRO state natively and preserves the "
        "global-token trajectory; genuinely incompatible topologies fail "
        "with a precise error before compilation",
    )
    parser.add_argument(
        "--audit-frequency",
        type=int,
        default=None,
        metavar="N",
        help="cross-replica divergence audit every N steps (overrides "
        "resilience.audit_frequency): bit-exact agreement check of the "
        "DP-replicated state inside the compiled step — catches silent "
        "data corruption that desyncs one replica",
    )
    parser.add_argument(
        "--supervise",
        action="store_true",
        default=False,
        help="run under the in-process supervisor: bounded restarts with "
        "exponential backoff on retryable failures (loader/storage IO, "
        "hangs, preemption), resuming from the last good checkpoint each "
        "time; fatal config/shape errors still exit immediately. Budget and "
        "backoff come from the `resilience` config block",
    )
    parser.add_argument("--wandb", action="store_true", default=False)
    parser.add_argument("--max-steps", type=int, default=None)
    parser.add_argument(
        "--metrics-port",
        type=int,
        default=0,
        metavar="PORT",
        help="serve the training Prometheus registry at "
        "http://0.0.0.0:PORT/metrics (train_bubble_frac, "
        "train_exposed_comm_frac, ...); 0 disables. Pair with "
        "training.step_bench_artifact pointing at a BENCH_step.json "
        "measured on this platform to populate the exposed-comm gauge",
    )
    parser.add_argument(
        "--profile",
        type=int,
        default=None,
        metavar="N",
        help="capture a jax.profiler trace of N steps (after the compile step)",
    )
    parser.add_argument(
        "--profile-window",
        default=None,
        metavar="START:LEN",
        help="capture a jax.profiler trace of the step window "
        "[START, START+LEN) — an absolute-step twin of --profile for "
        "profiling steady state or a suspect step range mid-run (e.g. "
        "1000:20). Lands in training.profile_dir next to the "
        "flight-recorder dumps",
    )
    parser.add_argument(
        "--memory-analysis",
        action="store_true",
        default=False,
        help="AOT-compile the train step and print the compiled HBM "
        "breakdown (state/temps/peak), then exit — nothing is allocated "
        "or executed. The pre-flight for sizing a config to a 16 GB chip.",
    )
    parser.add_argument(
        "--debug-nans",
        action="store_true",
        default=False,
        help="jax_debug_nans: fail fast at the op that produced a NaN "
        "(numeric sanitizer; ~2x slower — debugging only)",
    )
    parser.add_argument(
        "--tuned",
        nargs="?",
        const="TUNE_train.json",
        default=None,
        metavar="TUNE_JSON",
        help="load autotuned defaults from a scripts/autotune.py artifact "
        "(default: TUNE_train.json). Applied only when the artifact's "
        "platform/model match this run — a mismatch is refused with a loud "
        "warning and the hand defaults stand. --set overrides always win",
    )
    # action="extend": repeated --set flags accumulate instead of the last
    # occurrence silently replacing earlier ones
    parser.add_argument(
        "--set", nargs="*", action="extend", default=None, metavar="KEY=VALUE"
    )
    args = parser.parse_args()
    if args.debug_nans:
        jax.config.update("jax_debug_nans", True)

    logging.basicConfig(level=logging.INFO)
    from zero_transformer_tpu.config import load_config
    from zero_transformer_tpu.parallel.bootstrap import maybe_initialize
    from zero_transformer_tpu.training.trainer import Trainer

    # multi-host: wire the DCN coordination service when coordinator env vars
    # are present (reference ran pods on the implicit runtime, main_zero.py:181-184)
    maybe_initialize()

    cfg = load_config(args.cfg)
    user_overrides = parse_overrides(args.set)
    if args.tuned:
        # a --set model.size zoo lookup applies BEFORE the tuned gate, so
        # the artifact's model is checked against the model actually being
        # trained — and the later full-override pass can no longer clobber
        # tuned model.* values with a whole-section replacement
        if "model.size" in user_overrides:
            cfg = apply_overrides(
                cfg, {"model.size": user_overrides.pop("model.size")}
            )
        cfg = apply_tuned(cfg, args.tuned, user_overrides)
    cfg = apply_overrides(cfg, user_overrides)
    if args.resume:
        cfg = dataclasses.replace(
            cfg, checkpoint=dataclasses.replace(cfg.checkpoint, resume=True)
        )
    if args.profile:
        cfg = dataclasses.replace(
            cfg, training=dataclasses.replace(cfg.training, profile_steps=args.profile)
        )
    if args.profile_window:
        from zero_transformer_tpu.obs import parse_profile_window

        p_start, p_len = parse_profile_window(args.profile_window)
        cfg = dataclasses.replace(
            cfg,
            training=dataclasses.replace(
                cfg.training, profile_start=p_start, profile_steps=p_len
            ),
        )
    if args.audit_frequency is not None:
        cfg = dataclasses.replace(
            cfg,
            resilience=dataclasses.replace(
                cfg.resilience, audit_frequency=args.audit_frequency
            ),
        )

    logging.info(
        "devices=%d processes=%d backend=%s",
        jax.device_count(),
        jax.process_count(),
        jax.default_backend(),
    )
    if args.memory_analysis:
        import json

        from zero_transformer_tpu.training.trainer import memory_analysis

        report = memory_analysis(cfg)
        gb = 1 << 30
        for k in sorted(report):
            v = report[k]
            logging.info(
                "memory-analysis %s = %s", k,
                f"{v / gb:.2f} GiB" if "_bytes" in k and isinstance(v, int) else v,
            )
        print(json.dumps(report), flush=True)
        return
    if args.supervise:
        from zero_transformer_tpu.resilience import Supervisor

        if args.metrics_port:
            # loud, not silent: the supervisor rebuilds the Trainer (and its
            # registry) on every restart, so a single exporter bound here
            # would scrape a dead registry after the first recovery
            logging.getLogger("zero_transformer_tpu").warning(
                "--metrics-port is not supported with --supervise "
                "(the trainer registry is rebuilt across restarts); "
                "no /metrics endpoint will be served"
            )
        Supervisor(cfg, use_wandb=args.wandb).run(max_steps=args.max_steps)
        return
    trainer = Trainer(cfg, use_wandb=args.wandb)
    exporter = None
    try:
        if args.metrics_port:
            # inside the try: a bind failure (port in use) must still close
            # the trainer's async checkpoint machinery on the way out
            from zero_transformer_tpu.obs import MetricsExporter

            exporter = MetricsExporter(trainer.registry, port=args.metrics_port)
        trainer.train(max_steps=args.max_steps)
    finally:
        if exporter is not None:
            exporter.close()
        trainer.close()


if __name__ == "__main__":
    main()
