# Developer entry points, mirroring CI (.github/workflows/ci.yml).
# Capability match: reference Makefile:1-6 (format + test targets).

PY ?= python

.PHONY: test test-full chaos elastic-chaos serve-chaos router-chaos disagg-chaos tenant-chaos chaos-fleet obs bench bench-watch serve-bench tenant-bench train-bench kernel-bench tune tune-smoke e2e-watch fmt fmt-check dryrun lint

# Invariant lint lane (ISSUE 10): graftlint's repo-specific AST rules +
# the suppression audit over the whole tree. Pure stdlib — no jax import,
# no backend init — so it costs seconds. Exit 1 on any unsuppressed
# finding; every suppression's reason is printed for review. The same
# gate runs in the quick lane as test_graftlint.py::test_tree_is_clean.
lint:
	$(PY) scripts/graftlint.py --audit

# Quick lane: everything but tests marked slow (multi-process jax.distributed,
# long training loops, heavy cross-stage numerics). This is what CI runs on
# every push; CI adds PYTEST_ARGS="-n auto" (pytest-xdist) for multi-core.
# tests/conftest.py keeps a persistent XLA compilation cache (override dir
# via JAX_TEST_COMPILATION_CACHE); warm-cache timing 2026-07-30: full suite
# 273 passed in 9m20 at -n 4 on a heavily loaded box (cold cache ran >2x
# that). CI persists the cache across runs via actions/cache.
test:
	$(PY) -m pytest tests/ -x -q -m "not slow" $(PYTEST_ARGS)

# Full lane: the whole suite, nightly in CI.
test-full:
	$(PY) -m pytest tests/ -x -q $(PYTEST_ARGS)

# Fault-injection lane: every chaos-marked scenario (supervised recovery
# from injected loader/checkpoint/hang/preemption faults). The deterministic
# fast resilience cases are UN-marked and already run in the quick lane.
chaos:
	$(PY) -m pytest tests/test_resilience.py -q -m chaos $(PYTEST_ARGS)

# Trustworthy-restore lane: the elastic + integrity chaos suite — corrupt
# (truncated / bit-flipped) checkpoints quarantined with fallback, replica
# desync caught by the cross-replica audit, plus the full checkpoint
# integrity and elastic-resume test files. The multi-process elastic test
# (save on 8 simulated devices, resume on 4, and 4 -> 8) is slow-marked and
# runs in the full lane: tests/test_multihost.py::test_elastic_resume_across_world_sizes.
elastic-chaos:
	$(PY) -m pytest tests/test_resilience.py -q -m chaos \
		-k "ckpt_corruption or replica" $(PYTEST_ARGS)
	$(PY) -m pytest tests/test_checkpoint.py tests/test_elastic.py -q $(PYTEST_ARGS)

# Serving fault-injection lane: the full chaos scenario over the HTTP
# server (decode faults + NaN-logit windows + mid-load SIGTERM -> graceful
# drain, untouched requests byte-identical). The fast deterministic serving
# resilience cases are un-marked and run in the quick lane.
serve-chaos:
	$(PY) -m pytest tests/test_serving_resilience.py -q -m chaos $(PYTEST_ARGS)

# Fleet-router fault-injection lane (ISSUE 9): 3 real subprocess replicas
# under live streaming load through the router — one SIGKILLed mid-stream
# (every in-flight stream must resume token-exact on a survivor or end with
# a retryable terminal event; the victim is ejected with a flight-recorder
# dump) — plus a rolling fleet reload under load with dropped_streams == 0.
# The fast deterministic router cases (registry state machine, routing
# policy, stub-fleet failover/reload over HTTP) are un-marked and run in
# the quick lane.
router-chaos:
	$(PY) -m pytest tests/test_router.py -q -m chaos $(PYTEST_ARGS)

# Training-fleet fault-injection lane (ISSUE 17): N real worker processes
# training under a supervising coordinator — one SIGKILLed mid-run (bounded
# replay <= snapshot interval, loss trajectory rejoins the unfaulted run
# bitwise), a heartbeat blackhole (declared dead, then rejoins), a SIGSTOP
# hang (survivors finish bitwise without it), a slow worker (detected as a
# straggler and shed), and a full-fleet kill (snapshot rewind, bounded
# replay). The fast deterministic fleet cases (shard assignment, fold
# algebra, registry edge cases, HTTP surface) are un-marked and run in the
# quick lane.
chaos-fleet:
	$(PY) -m pytest tests/test_fleet_train.py -q -m chaos $(PYTEST_ARGS)

# Disaggregated-fleet fault-injection lane (ISSUE 12): SIGKILL a
# prefill-role replica mid-long-prompt-flood (every stream finishes
# token-exact or ends retryably through the recompute fallback, zero
# drops, the fleet keeps serving without its prefill tier), and kill a
# migration's TARGET mid-transfer (the ship fails, the source degrades the
# stream retryably, the router's recompute fallback resumes it token-exact
# on a survivor). The fast deterministic disagg cases (page-span roundtrip,
# migration parity, autoscaler logic) are un-marked and run in the quick lane.
disagg-chaos:
	$(PY) -m pytest tests/test_serving_disagg.py -q -m chaos $(PYTEST_ARGS)

# Tenant-isolation fault-injection lane (ISSUE 18): the multi-tenant flood
# proof (one tenant floods a real 2-replica QoS fleet with batch work while
# a gold tenant's trickle must ALL complete with zero dropped streams and
# every flood rejection retryable with a Retry-After) plus the slow_client
# chaos case (a stalled SSE consumer hits its bounded emit buffer and ends
# retryably; the concurrent healthy stream stays byte-identical). The fast
# deterministic QoS cases (token buckets, DWRR fairness, brownout ladder,
# floors, preemption) are un-marked and run in the quick lane.
tenant-chaos:
	$(PY) -m pytest tests/test_qos.py -q -m chaos $(PYTEST_ARGS)

# Tenant-isolation bench (ISSUE 18): the gold-trickle A/B under a hostile
# batch flood on a real 2-replica QoS fleet -> BENCH_tenant.json (gold p99
# ratio graded on accelerators only — on a shared-core CPU box the flood
# steals the gold replica's cycles whatever the admission plane does;
# correctness graded everywhere). Schema pinned by tests/test_serve_bench.py.
tenant-bench:
	@cp BENCH_tenant.json /tmp/_serve_tenant_baseline.json 2>/dev/null || true
	JAX_PLATFORMS=cpu $(PY) scripts/serve_loadgen.py --tenant-flood
	@if [ -f /tmp/_serve_tenant_baseline.json ]; then \
		$(PY) scripts/serve_bench_guard.py /tmp/_serve_tenant_baseline.json BENCH_tenant.json; \
	else \
		echo "serve-bench-guard: no committed tenant baseline; skipping"; \
	fi

# Observability lane (ISSUE 7 + ISSUE 15): the obs test files (span-tree
# parity over every request outcome, Prometheus exposition conformance
# under live traffic, X-Request-Id round trip, flight-recorder dump on
# breaker-open, /admin/profile lifecycle, fleet stitching/aggregation/SLO/
# ledger) plus two smokes: a loadgen trace smoke (one small run must
# produce a Perfetto-loadable span trace with nonzero events) and the
# stub-fleet stitched-trace smoke (router + 2 stub replicas -> ONE merged
# fleet trace, programmatically verified: >=95% coverage, zero orphans,
# rollup sums pinned, /slo verdict ok).
obs:
	$(PY) -m pytest tests/test_obs.py tests/test_fleet_obs.py -q $(PYTEST_ARGS)
	JAX_PLATFORMS=cpu $(PY) scripts/serve_loadgen.py --requests 4 --slots 2 \
		--max-new-tokens 8 --cache-len 64 --out /tmp/_obs_smoke.json
	$(PY) -c "import json; t=json.load(open('/tmp/_obs_smoke.trace.json')); \
		n=len(t['traceEvents']); assert n, 'empty trace'; \
		print(f'obs trace smoke ok: {n} events')"
	$(PY) scripts/fleet_obs_smoke.py

# One-line JSON benchmark artifact (driver contract).
bench:
	$(PY) bench.py

# Continuous-batching serving bench: 8 concurrent clients against a 2-slot
# engine on the CPU test model (paged KV cache + chunked prefill by
# default), every response verified byte-identical to single-request
# generate(). Four scenarios:
#  - headline mixed-length run, SPECULATION ON (greedy so the byte-parity
#    check stays exact) with an embedded spec-OFF control (no_speculation)
#    -> BENCH_serve.json — the spec-on/spec-off pair;
#  - shared-prefix run (N personas x one system prompt; with paging a hit
#    is a page-refcount bump) -> BENCH_serve_prefix.json;
#  - capacity sweep: slab vs paged concurrent streams at EQUAL KV budget
#    -> BENCH_serve_capacity.json (the >=4x concurrency evidence);
#  - fleet-router scaling: paced stub replicas behind the real router,
#    aggregate relayed tok/s at 1/2/4 replicas + token-exact mid-stream
#    failover + rolling reload with zero drops -> BENCH_router.json (the
#    guard holds the >= 3x near-linear bar on matching hardware and the
#    correctness fields everywhere);
#  - disaggregation A/B + autoscale sawtooth (ISSUE 12): a long-prompt
#    flood against a mixed fleet vs a prefill/decode split fleet (real
#    engines, token-exact, zero replayed tokens), plus the autoscaler
#    tracking a sawtooth on stub replicas with zero drops
#    -> BENCH_disagg.json (isolation ratios graded on accelerators only —
#    on a shared-core CPU box both replicas compete for the same cores).
# A regression guard compares the fresh runs against the previously
# committed artifacts (>15% on decode_tok_s / itl p99 / capacity ratio /
# router scaling fails loudly on matching hardware, skips otherwise).
# Schema pinned by tests/test_serve_bench.py.
serve-bench:
	@cp BENCH_serve.json /tmp/_serve_baseline.json 2>/dev/null || true
	@cp BENCH_serve_capacity.json /tmp/_serve_cap_baseline.json 2>/dev/null || true
	@cp BENCH_router.json /tmp/_serve_router_baseline.json 2>/dev/null || true
	@cp BENCH_disagg.json /tmp/_serve_disagg_baseline.json 2>/dev/null || true
	JAX_PLATFORMS=cpu $(PY) scripts/serve_loadgen.py --requests 8 --slots 2 \
		--spec-k 4 --greedy --max-new-tokens 32 --cache-len 64 --obs-ab \
		--fused-tail-ab
	JAX_PLATFORMS=cpu $(PY) scripts/serve_loadgen.py --requests 8 --slots 2 \
		--shared-prefix --cache-len 64 --out BENCH_serve_prefix.json
	JAX_PLATFORMS=cpu $(PY) scripts/serve_loadgen.py --capacity-sweep \
		--cache-len 128 --max-new-tokens 8
	JAX_PLATFORMS=cpu $(PY) scripts/serve_loadgen.py --router
	JAX_PLATFORMS=cpu $(PY) scripts/serve_loadgen.py --long-prompt-flood \
		--sawtooth --cache-len 64 --max-new-tokens 12 --slots 2
	@if [ -f /tmp/_serve_baseline.json ]; then \
		$(PY) scripts/serve_bench_guard.py /tmp/_serve_baseline.json BENCH_serve.json; \
	else \
		echo "serve-bench-guard: no committed baseline; skipping"; \
	fi
	@if [ -f /tmp/_serve_cap_baseline.json ]; then \
		$(PY) scripts/serve_bench_guard.py /tmp/_serve_cap_baseline.json BENCH_serve_capacity.json; \
	else \
		echo "serve-bench-guard: no committed capacity baseline; skipping"; \
	fi
	@if [ -f /tmp/_serve_router_baseline.json ]; then \
		$(PY) scripts/serve_bench_guard.py /tmp/_serve_router_baseline.json BENCH_router.json; \
	else \
		echo "serve-bench-guard: no committed router baseline; skipping"; \
	fi
	@if [ -f /tmp/_serve_disagg_baseline.json ]; then \
		$(PY) scripts/serve_bench_guard.py /tmp/_serve_disagg_baseline.json BENCH_disagg.json; \
	else \
		echo "serve-bench-guard: no committed disagg baseline; skipping"; \
	fi

# Training step-time decomposition lane (ISSUE 8): overlap-on/off A/B with
# in-process BITWISE gradient parity, compute/exposed-comm split vs a
# single-device baseline, the analytic bubble table (gpipe/1f1b/interleaved),
# a measured tiny pipe run where the backend can execute it, the per-op
# flash-vs-XLA attention microbench, and the assumption-labeled v5e
# projection -> BENCH_step.json. The guard compares against the committed
# artifact (parity must stay bitwise everywhere; timing/reduction graded on
# matching hardware only). Schema pinned by tests/test_train_bench.py.
train-bench:
	@cp BENCH_step.json /tmp/_step_baseline.json 2>/dev/null || true
	$(PY) scripts/train_step_bench.py
	@if [ -f /tmp/_step_baseline.json ]; then \
		$(PY) scripts/train_bench_guard.py /tmp/_step_baseline.json BENCH_step.json; \
	else \
		echo "train-bench-guard: no committed baseline; skipping"; \
	fi

# Kernel lane (ISSUE 11): interpret-mode parity for the Pallas kernels on
# THIS box (flash train fwd+bwd and serving offset/mask shapes pinned
# few-ulp vs the XLA reference; the paged-attention decode kernel pinned
# BITWISE vs the gather-to-slab path it replaces, int8 scales included)
# plus the per-op microbench's CPU half (the parity block child_flash
# emits off-TPU — timed flash numbers stay TPU-only with honest
# provenance). docs/KERNELS.md documents the dispatch-gate decision table.
kernel-bench:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_paged_kernel.py \
		tests/test_flash_attention.py -q $(PYTEST_ARGS)
	JAX_PLATFORMS=cpu $(PY) -c "import bench, json; out = bench.child_flash(); \
		print(json.dumps(out)); assert out['ok'], 'kernel parity failed'"

# Autotuner lanes (ISSUE 14, docs/TUNING.md). `tune` runs the real
# per-(model, hardware, workload) searches and rewrites the committed
# TUNE_train.json / TUNE_serve.json (re-run on new hardware — the
# artifacts only ever apply under a matching platform block). `tune-smoke`
# is the CI lane: a tiny space, 2 measured trials, two full passes, and
# asserts the artifact schema plus determinism (same winner + same trace
# fingerprint across the passes — the --reruns 2 gate inside the script),
# mirroring the BENCH schema tests; the committed-artifact schema itself
# is pinned by tests/test_autotune.py (TUNE_REQUIRED_KEYS).
tune:
	JAX_PLATFORMS=cpu $(PY) scripts/autotune.py --target serve --reruns 2
	JAX_PLATFORMS=cpu $(PY) scripts/autotune.py --target train --reruns 2

tune-smoke:
	JAX_PLATFORMS=cpu $(PY) scripts/autotune.py --target serve --smoke \
		--reruns 2 --out /tmp/_tune_smoke.json
	$(PY) -c "import json; \
		from zero_transformer_tpu.analysis.autotune import TUNE_REQUIRED_KEYS; \
		art = json.load(open('/tmp/_tune_smoke.json')); \
		missing = TUNE_REQUIRED_KEYS - art.keys(); \
		assert not missing, f'smoke artifact missing {sorted(missing)}'; \
		det = art['determinism']; \
		assert det['winner_stable'] and det['fingerprints_equal'], det; \
		print(f\"tune-smoke ok: winner {art['winner']['knobs']} \" \
		      f\"({art['value']}x), fingerprint {det['fingerprint']}\")"

# Retry the bench ladder until a live on-chip measurement lands, then promote
# it to BENCH_measured.json (this image's TPU tunnel wedges for hours at a
# time and clears on its own; see scripts/tpu_watch.py).
bench-watch:
	$(PY) scripts/tpu_watch.py

# Same, for the on-chip e2e quality run (prepare -> train -> eval -> serve):
# retries until docs/e2e/full_tpu/eval.json lands.
e2e-watch:
	bash scripts/e2e_watch.sh

# Multi-chip sharding dry-run on an 8-device virtual CPU mesh.
dryrun:
	$(PY) -c "import __graft_entry__ as g; g.dryrun_multichip(8); print('dryrun ok')"

fmt:
	@$(PY) -c "import black" 2>/dev/null && $(PY) -m black zero_transformer_tpu tests train.py bench.py || echo "black not installed; skipping"
	@$(PY) -c "import isort" 2>/dev/null && $(PY) -m isort zero_transformer_tpu tests train.py bench.py || echo "isort not installed; skipping"

# Fails on misformatted code (or on a missing formatter) — safe to gate CI on.
fmt-check:
	$(PY) -m black --check zero_transformer_tpu tests train.py bench.py
