"""scripts/serve_loadgen.py: the BENCH_serve.json artifact contract.

Same philosophy as test_bench_artifact.py for the training bench: the
artifact is the driver-facing evidence of a load run, so its schema and its
invariants (no drops, no garbling, occupancy actually reached the slot
count) are pinned here — a real (small) load run on CPU with the ``test``
zoo model, not a mocked one.
"""
import importlib.util
import json
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent

REQUIRED_KEYS = {
    "metric", "value", "unit", "model", "mode", "slots", "requests",
    "max_new_tokens", "wall_s", "ttft_ms", "itl_ms", "peak_occupancy",
    "peak_queue_depth", "completed", "rejected", "dropped", "verified",
    "mismatches", "measured_at_utc",
    # resilience evidence (ISSUE 3): fault/shed/drain behavior is part of
    # the load-run contract, chaos or not
    "chaos", "errors", "error_rate", "shed", "shed_rate",
    "drain_latency_s", "tick_faults", "poisoned_slots", "breaker_trips",
    "final_state",
    # frozen-workload evidence (ISSUE 14): which spec this run replayed and
    # its hash — TUNE artifacts carry the same hash, so "tuned under this
    # workload" is checkable against the bench artifact
    "workload_spec", "workload_hash",
    # serving hot path evidence (ISSUE 4): chunked prefill, prefix caching,
    # per-phase latency attribution, and the regression guard's keys
    "workload", "decode_tok_s", "prefill_chunk", "prefix_cache",
    "itl_ms_decode_only", "prefill_ms_hit_p50", "prefill_ms_miss_p50",
    "no_prefix_cache", "platform",
    # paged KV + speculation evidence (ISSUE 6): layout, pool pressure, and
    # draft-and-verify acceptance economics with the spec-off control
    "kv_layout", "page_size", "page_faults", "pages_reclaimed",
    "preemptions", "page_pool_util", "cow_copies",
    "draft_k", "acceptance_rate", "spec_ticks", "no_speculation",
    # kernel-lane evidence (ISSUE 11): fused sampling tail + defused
    # control, and whether the paged-attention kernel traced into the
    # decode program on this run's backend
    "fused_tail", "kernel_paged_attention", "no_fused_tail",
    # observability evidence (ISSUE 7): tracing-cost A/B (populated by
    # --obs-ab, None otherwise) and the Perfetto span artifact every run
    # writes beside the JSON
    "obs_overhead", "trace_file", "obs_spans",
}

CAPACITY_REQUIRED_KEYS = {
    "metric", "value", "unit", "model", "kv_budget_tokens", "page_size",
    "prefill_chunk", "max_new_tokens", "streams_offered", "slab", "paged",
    "platform", "measured_at_utc",
}

ROUTER_REQUIRED_KEYS = {
    # fleet-router evidence (ISSUE 9): the replica-scaling sweep, routing
    # hit-rate, the token-exact mid-stream failover segment, and the
    # rolling-reload zero-drop proof
    "metric", "value", "unit", "replica_model", "replica_itl_ms",
    "replica_slots", "clients", "requests_per_client", "max_new_tokens",
    "scaling", "aggregate_tok_s", "routing", "failover", "rolling_reload",
    "dropped_streams", "platform", "measured_at_utc",
    # fleet observability plane (ISSUE 15): the merged-trace verification,
    # the SLO verdict over the run, and the aggregate cost ledger
    "fleet_trace", "slo", "ledger",
}

DISAGG_REQUIRED_KEYS = {"bench", "metric", "platform", "config", "flood",
                        "sawtooth"}
DISAGG_FLOOD_ARM_KEYS = {
    "roles", "itl_ms_decode_bg_no_flood", "itl_ms_decode_bg_flood",
    "ttft_ms_flood", "itl_bg_p50_degradation", "streams_done", "hung",
    "dropped_streams", "disagg_dispatches", "resume_replayed_tokens",
}
DISAGG_SAWTOOTH_KEYS = {
    "streams", "streams_done", "hung", "dropped_streams", "autoscale_ups",
    "autoscale_downs", "autoscale_aborts", "max_replicas_seen",
    "min_replicas_seen", "replica_trace",
}

TENANT_REQUIRED_KEYS = {
    # tenant-isolation evidence (ISSUE 18): the gold-trickle A/B under a
    # hostile batch flood, the retryable-rejection proof, and the
    # isolation counters that show WHICH mechanism absorbed the flood
    "bench", "metric", "value", "unit", "isolation_factor_limit", "config",
    "baseline", "flood", "token_exact", "dropped_streams", "platform",
    "measured_at_utc",
}
TENANT_ARM_KEYS = {
    "label", "gold_e2e_ms", "gold_ttft_ms", "gold_done", "gold_offered",
    "flood_attempts", "flood_ok", "flood_rejected", "flood_bad_rejections",
    "dropped_streams", "isolation_counters",
}


def _load():
    spec = importlib.util.spec_from_file_location(
        "serve_loadgen", REPO / "scripts" / "serve_loadgen.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_loadgen_artifact_schema_and_invariants(tmp_path):
    loadgen = _load()
    out = tmp_path / "BENCH_serve.json"
    artifact = loadgen.main([
        "--requests", "6", "--slots", "2", "--concurrency", "6",
        "--max-new-tokens", "8", "--out", str(out),
    ])

    on_disk = json.loads(out.read_text())
    assert on_disk == artifact  # stdout line and file artifact must agree

    missing = REQUIRED_KEYS - set(artifact)
    assert not missing, f"artifact missing keys: {sorted(missing)}"
    assert artifact["metric"] == "serve_tokens_per_sec_test"
    assert artifact["unit"] == "tokens/s"
    assert artifact["value"] > 0

    for block in ("ttft_ms", "itl_ms", "itl_ms_decode_only"):
        assert set(artifact[block]) == {"p50", "p90", "p99"}
        assert artifact[block]["p50"] <= artifact[block]["p99"]
    assert set(artifact["prefix_cache"]) == {"hits", "misses", "hit_rate"}
    assert set(artifact["platform"]) == {"backend", "device"}
    assert artifact["decode_tok_s"] == artifact["value"]
    assert artifact["workload"] == "mixed"
    assert artifact["prefill_chunk"] > 0  # chunked prefill is the default

    # the load-run correctness invariants the acceptance bar names
    assert artifact["completed"] == 6
    assert artifact["dropped"] == 0
    assert artifact["verified"] is True and artifact["mismatches"] == 0
    # 6 concurrent clients against 2 slots must saturate the engine
    assert artifact["peak_occupancy"] == 2
    assert artifact["peak_queue_depth"] >= 1
    # an undisturbed run ends with a clean graceful drain and zero faults
    assert artifact["chaos"] is False and artifact["errors"] == 0
    assert artifact["final_state"] == "stopped"
    assert artifact["drain_latency_s"] >= 0
    # paged KV is the loadgen default; speculation off in this run
    assert artifact["kv_layout"] == "paged" and artifact["page_size"] > 0
    assert artifact["preemptions"] == 0
    assert artifact["draft_k"] == 0 and artifact["no_speculation"] is None
    # fused tail is the default; the defused control needs --fused-tail-ab
    assert artifact["fused_tail"] is True
    assert artifact["no_fused_tail"] is None
    assert artifact["kernel_paged_attention"] in (True, False)
    # every run writes a Perfetto-loadable span trace next to the artifact
    assert artifact["obs_overhead"] is None  # --obs-ab not requested here
    assert artifact["obs_spans"] > 0
    trace = json.loads((out.parent / artifact["trace_file"]).read_text())
    assert trace["traceEvents"], "span trace artifact is empty"
    names = {e.get("name") for e in trace["traceEvents"]}
    assert {"request", "queue", "prefill", "decode"} <= names, names


def test_loadgen_speculative_run_verified_with_acceptance(tmp_path):
    """--spec-k + --greedy: every trajectory STILL byte-identical to
    (greedy) generate() — the verify step's exactness contract under real
    contention — with a nonzero acceptance rate and the spec-OFF control
    embedded for the A/B."""
    loadgen = _load()
    out = tmp_path / "BENCH_serve_spec.json"
    artifact = loadgen.main([
        "--requests", "6", "--slots", "2", "--concurrency", "6",
        "--max-new-tokens", "24", "--cache-len", "64",
        "--spec-k", "4", "--greedy", "--out", str(out),
    ])
    assert artifact["draft_k"] == 4
    assert artifact["verified"] is True and artifact["mismatches"] == 0
    assert artifact["completed"] == 6 and artifact["dropped"] == 0
    assert artifact["spec_ticks"] > 0
    assert artifact["acceptance_rate"] > 0
    assert artifact["no_speculation"] is not None
    assert artifact["no_speculation"]["decode_tok_s"] > 0


@pytest.mark.slow
def test_loadgen_fused_tail_ab(tmp_path):
    """--fused-tail-ab: the defused-tail control engine (sampling as its
    own dispatch) runs the same workload and embeds a no_fused_tail block;
    every measured trajectory still verifies byte-identical against
    generate() — the defused control changes dispatch count, never math.
    Slow lane: the A/B is an extra full load run (+ its defused warmup);
    tier-1 covers the schema keys (None without the flag) and the engine
    fused/defused byte-parity in tests/test_paged_kernel.py, and make
    serve-bench runs the real A/B into the committed BENCH_serve.json."""
    loadgen = _load()
    out = tmp_path / "BENCH_serve_ft.json"
    artifact = loadgen.main([
        "--requests", "6", "--slots", "2", "--concurrency", "6",
        "--max-new-tokens", "8", "--cache-len", "48",
        "--fused-tail-ab", "--out", str(out),
    ])
    assert artifact["fused_tail"] is True
    nf = artifact["no_fused_tail"]
    assert nf is not None
    assert nf["decode_tok_s"] > 0
    assert nf["itl_ms_decode_only_p99"] >= 0
    assert artifact["verified"] is True and artifact["mismatches"] == 0


@pytest.mark.slow
def test_loadgen_obs_ab_measures_tracing_overhead(tmp_path):
    """--obs-ab: the tracing-on/off A/B runs both arms and embeds a sane
    obs_overhead block (fractions in [0, 1], both arms nonzero). Slow lane:
    the A/B is two extra full load runs; tier-1 covers the obs_overhead
    schema key (None without --obs-ab) and the guard logic, and
    make serve-bench runs the real best-of-5 A/B into the committed
    BENCH_serve.json where the guard enforces the <=2% budget."""
    loadgen = _load()
    out = tmp_path / "BENCH_serve_obs.json"
    artifact = loadgen.main([
        "--requests", "4", "--slots", "2", "--concurrency", "4",
        "--max-new-tokens", "8", "--obs-ab", "--obs-ab-repeats", "1",
        "--out", str(out),
    ])
    ab = artifact["obs_overhead"]
    assert ab is not None
    assert ab["decode_tok_s_trace_off"] > 0
    assert ab["decode_tok_s_trace_on"] > 0
    assert 0.0 <= ab["overhead_frac"] <= 1.0
    assert ab["repeats"] == 1


def test_loadgen_capacity_sweep_artifact(tmp_path):
    """--capacity-sweep: slab vs paged concurrent streams at EQUAL KV
    budget. The schema is pinned and the paged engine must beat the slab
    by the ISSUE 6 bar (>=4x) with zero preemptions (reservation-backed
    admission means capacity pressure -> waiting, not eviction)."""
    loadgen = _load()
    out = tmp_path / "BENCH_serve_capacity.json"
    artifact = loadgen.main([
        "--capacity-sweep", "--cache-len", "128", "--max-new-tokens", "8",
        "--capacity-streams", "20", "--out", str(out),
    ])
    on_disk = json.loads(out.read_text())
    assert on_disk == artifact
    missing = CAPACITY_REQUIRED_KEYS - set(artifact)
    assert not missing, f"capacity artifact missing keys: {sorted(missing)}"
    assert artifact["metric"] == "serve_capacity_streams_ratio"
    assert artifact["slab"]["completed"] == 20
    assert artifact["paged"]["completed"] == 20
    assert artifact["slab"]["capacity_streams"] == artifact["slab"]["slots"]
    assert artifact["value"] >= 4.0, artifact
    assert artifact["paged"]["preemptions"] == 0
    assert 0 < artifact["paged"]["page_pool_util"] <= 1.0


def test_loadgen_chaos_run_fails_retryably_and_drains(tmp_path):
    """--chaos: the injected decode fault + NaN-logit window fail SOME
    requests (retryably), hang none, garble none of the survivors (every
    completed request stays byte-identical to generate()), and the engine
    still drains to STOPPED — the quick-lane slice of the serving chaos
    acceptance bar."""
    loadgen = _load()
    out = tmp_path / "BENCH_serve_chaos.json"
    artifact = loadgen.main([
        "--requests", "6", "--slots", "2", "--concurrency", "6",
        "--max-new-tokens", "8", "--chaos", "--out", str(out),
    ])
    assert artifact["chaos"] is True
    assert artifact["errors"] > 0  # the faults really fired
    assert artifact["tick_faults"] >= 1 and artifact["poisoned_slots"] >= 1
    assert artifact["dropped"] == 0  # no request hung: all reached terminal
    assert artifact["mismatches"] == 0  # survivors byte-identical
    assert artifact["completed"] + artifact["errors"] == 6
    assert artifact["final_state"] == "stopped"


def test_loadgen_shared_prefix_hits_and_parity(tmp_path):
    """--shared-prefix: the common system prompt really hits the prefix
    cache (hit_rate > 0), every trajectory STILL matches single-request
    generate() byte-for-byte (reused K/V spans are bit-identical by
    construction), and admissions that hit reach their first token FASTER
    than the cache-off control — the TTFT win, measured on the component
    the engine controls (admission -> first token; full TTFT under a
    closed loop is dominated by queue wait)."""
    loadgen = _load()
    out = tmp_path / "BENCH_serve_prefix.json"
    artifact = loadgen.main([
        "--requests", "6", "--slots", "2", "--concurrency", "6",
        "--max-new-tokens", "8", "--cache-len", "48", "--shared-prefix",
        "--out", str(out),
    ])
    assert artifact["workload"] == "shared_prefix"
    assert artifact["prefix_cache"]["hits"] > 0
    assert artifact["prefix_cache"]["hit_rate"] > 0
    assert artifact["verified"] is True and artifact["mismatches"] == 0
    assert artifact["completed"] == 6 and artifact["dropped"] == 0
    # both phases have samples: someone paid the cold prefix prefill
    # (2+ chunk ticks) and someone skipped straight to the novel chunk
    assert artifact["prefill_ms_miss_p50"] > 0
    assert artifact["prefill_ms_hit_p50"] > 0
    # the headline: a prefix hit prefills strictly less than the cache-off
    # control's cold prefill (same workload, same seeds, same box)
    assert artifact["no_prefix_cache"] is not None
    assert artifact["prefill_ms_hit_p50"] < artifact["no_prefix_cache"]["prefill_ms_p50"]


def test_loadgen_router_artifact(tmp_path):
    """--router: the fleet-scaling scenario over paced stub replicas. Small
    here (2-replica sweep, short streams) — tier-1 pins the artifact schema
    and the correctness invariants (every stream token-exact, the failover
    segment resumed exactly, rolling reload with zero drops); make
    serve-bench runs the full 1 -> 4 sweep into the committed
    BENCH_router.json where the guard holds the >= 3x near-linear bar."""
    loadgen = _load()
    out = tmp_path / "BENCH_router.json"
    artifact = loadgen.main([
        "--router", "--router-replicas", "2", "--router-requests", "2",
        "--router-max-new", "12", "--router-itl-ms", "2",
        "--router-repeats", "1", "--out", str(out),
    ])
    on_disk = json.loads(out.read_text())
    assert on_disk == artifact
    missing = ROUTER_REQUIRED_KEYS - set(artifact)
    assert not missing, f"router artifact missing keys: {sorted(missing)}"
    assert artifact["metric"] == "router_scaling_tok_s"
    assert artifact["value"] > 1.0  # 2 replicas must beat 1
    # sweep shape: 1 and 2 replicas, aggregate == sum of per-replica rates
    assert [p["replicas"] for p in artifact["scaling"]] == [1, 2]
    for point in artifact["scaling"]:
        assert point["streams"] == artifact["clients"] * 2
        assert len(point["per_replica_tok_s"]) == point["replicas"]
        assert point["aggregate_tok_s"] > 0
    # each client's 2nd request rides prefix affinity back to its replica
    assert artifact["routing"]["hit_rate"] == 0.5
    assert artifact["routing"]["affinity_hits"] > 0
    # the failover segment resumed mid-stream, token-exact, on the survivor
    assert artifact["failover"]["token_exact"] is True
    assert artifact["failover"]["resumed_streams"] == 1
    assert artifact["failover"]["failovers"] >= 1
    # rolling reload under live streams: one step per replica, zero drops
    assert artifact["rolling_reload"]["ok"] is True
    assert artifact["rolling_reload"]["steps"] == 3
    assert artifact["rolling_reload"]["dropped_streams"] == 0
    assert artifact["dropped_streams"] == 0
    assert set(artifact["platform"]) == {"backend", "device"}
    # fleet observability plane (ISSUE 15): the merged trace stitched and
    # verified, the SLO verdict ok on a healthy run, and the aggregate
    # ledger schema-complete (FLEET_OBS_REQUIRED_KEYS is the contract)
    from zero_transformer_tpu.obs.fleet import FLEET_OBS_REQUIRED_KEYS

    ft = artifact["fleet_trace"]
    assert ft["coverage_min"] >= 0.95 and ft["orphans"] == 0
    assert ft["hops_ordered"] is True and ft["requests"] >= 1
    trace_doc = json.loads((out.parent / ft["file"]).read_text())
    assert trace_doc["traceEvents"], "merged fleet trace is empty"
    assert FLEET_OBS_REQUIRED_KEYS["slo"] <= set(artifact["slo"])
    assert artifact["slo"]["verdict"] == "ok"
    missing = FLEET_OBS_REQUIRED_KEYS["ledger"] - set(artifact["ledger"])
    assert not missing, f"aggregate ledger missing {sorted(missing)}"
    assert artifact["ledger"]["tokens_relayed"] > 0


def test_committed_disagg_artifact_schema():
    """BENCH_disagg.json (ISSUE 12): schema + the correctness invariants
    the acceptance bar names — token-exact phase split with zero replayed
    tokens, zero dropped streams, and a sawtooth the autoscaler tracked."""
    path = REPO / "BENCH_disagg.json"
    assert path.exists(), "commit BENCH_disagg.json (make disagg-bench)"
    artifact = json.loads(path.read_text())
    missing = DISAGG_REQUIRED_KEYS - set(artifact)
    assert not missing, f"disagg artifact missing keys: {sorted(missing)}"
    assert artifact["metric"] == "disagg_flood_and_autoscale"
    flood = artifact["flood"]
    for arm in ("mixed", "disagg"):
        missing = DISAGG_FLOOD_ARM_KEYS - set(flood[arm])
        assert not missing, f"{arm} arm missing: {sorted(missing)}"
    assert flood["token_exact"] is True
    assert flood["dropped_streams"] == 0
    assert flood["disagg"]["disagg_dispatches"] > 0
    assert flood["disagg"]["resume_replayed_tokens"] == 0
    assert flood["mixed"]["disagg_dispatches"] == 0  # the control is pure
    saw = artifact["sawtooth"]
    missing = DISAGG_SAWTOOTH_KEYS - set(saw)
    assert not missing, f"sawtooth missing: {sorted(missing)}"
    assert saw["dropped_streams"] == 0 and saw["hung"] == 0
    assert saw["streams_done"] == saw["streams"]
    assert saw["autoscale_ups"] >= 1 and saw["autoscale_downs"] >= 1
    assert saw["max_replicas_seen"] > saw["min_replicas_seen"]
    assert set(artifact["platform"]) == {"backend", "device"}


def test_committed_tenant_artifact_schema():
    """BENCH_tenant.json (ISSUE 18): schema + the correctness invariants
    the acceptance bar names — every gold stream done and token-exact,
    zero dropped streams, a flood that was actually throttled with every
    rejection retryable, and an engaged isolation plane."""
    path = REPO / "BENCH_tenant.json"
    assert path.exists(), "commit BENCH_tenant.json (make tenant-bench)"
    artifact = json.loads(path.read_text())
    missing = TENANT_REQUIRED_KEYS - set(artifact)
    assert not missing, f"tenant artifact missing keys: {sorted(missing)}"
    assert artifact["metric"] == "tenant_isolation"
    for arm_name in ("baseline", "flood"):
        arm = artifact[arm_name]
        missing = TENANT_ARM_KEYS - set(arm)
        assert not missing, f"{arm_name} arm missing: {sorted(missing)}"
        assert arm["gold_done"] == arm["gold_offered"] > 0
        assert arm["dropped_streams"] == 0
        for pcts in (arm["gold_e2e_ms"], arm["gold_ttft_ms"]):
            assert set(pcts) == {"p50", "p99"}
    assert artifact["token_exact"] is True
    assert artifact["dropped_streams"] == 0
    # the control arm had no flood; the flood arm was really throttled
    assert artifact["baseline"]["flood_attempts"] == 0
    flood = artifact["flood"]
    assert flood["flood_rejected"] > 0
    assert flood["flood_bad_rejections"] == 0
    assert sum(flood["isolation_counters"].values()) > 0
    assert artifact["value"] > 0
    assert artifact["value"] <= artifact["isolation_factor_limit"]
    assert set(artifact["platform"]) == {"backend", "device"}


def test_loadgen_sawtooth_segment_live(tmp_path):
    """The autoscale segment end to end on stub replicas: the control loop
    must spawn under the burst, retire in the trough, and drop nothing.
    (The flood A/B runs real engines and lives in make disagg-bench; its
    committed artifact is schema-checked above.)"""
    loadgen = _load()
    out = tmp_path / "BENCH_disagg.json"
    artifact = loadgen.main(["--sawtooth", "--out", str(out)])
    on_disk = json.loads(out.read_text())
    assert on_disk == artifact
    saw = artifact["sawtooth"]
    assert saw["dropped_streams"] == 0
    assert saw["streams_done"] == saw["streams"]
    assert saw["autoscale_ups"] >= 1 and saw["autoscale_downs"] >= 1


def test_serve_bench_guard_disagg_logic():
    """Disagg-artifact guard branch: correctness + the within-artifact A/B
    grade on ANY hardware; only the cross-run ratio is platform-gated."""
    spec = importlib.util.spec_from_file_location(
        "serve_bench_guard", REPO / "scripts" / "serve_bench_guard.py"
    )
    guard = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(guard)

    def arm(deg, dispatches=0, replayed=0):
        return {
            "itl_bg_p50_degradation": deg,
            "disagg_dispatches": dispatches,
            "resume_replayed_tokens": replayed,
            "streams_done": True, "hung": 0, "dropped_streams": 0,
        }

    good = {
        "metric": "disagg_flood_and_autoscale",
        "platform": {"backend": "cpu", "device": "x"},
        "flood": {
            "token_exact": True, "dropped_streams": 0,
            "mixed": arm(1.8), "disagg": arm(1.1, dispatches=5),
        },
        "sawtooth": {
            "streams": 12, "streams_done": 12, "hung": 0,
            "dropped_streams": 0, "autoscale_ups": 2, "autoscale_downs": 1,
        },
    }
    ok, _ = guard.compare(good, json.loads(json.dumps(good)))
    assert ok
    # dropped streams fail on any hardware
    bad = json.loads(json.dumps(good))
    bad["flood"]["dropped_streams"] = 1
    ok, msgs = guard.compare(good, bad)
    assert not ok and any("dropped" in m for m in msgs)
    # replayed tokens on the disagg arm fail (the zero-recompute claim)
    bad = json.loads(json.dumps(good))
    bad["flood"]["disagg"]["resume_replayed_tokens"] = 40
    ok, msgs = guard.compare(good, bad)
    assert not ok and any("replayed" in m for m in msgs)
    # on a CPU box the isolation ratio is recorded but NOT graded (both
    # replicas share the same cores — scheduler noise, not isolation)
    noisy = json.loads(json.dumps(good))
    noisy["flood"]["disagg"]["itl_bg_p50_degradation"] = 9.0
    ok, msgs = guard.compare(good, noisy)
    assert ok and any("share the same cores" in m for m in msgs)
    # on an accelerator the within-artifact A/B grades — even when the
    # baseline came from foreign hardware
    tpu = json.loads(json.dumps(good))
    tpu["platform"] = {"backend": "tpu", "device": "v4"}
    bad = json.loads(json.dumps(tpu))
    bad["flood"]["disagg"]["itl_bg_p50_degradation"] = 9.0
    ok, msgs = guard.compare(good, bad)
    assert not ok and any("isolating" in m for m in msgs)
    # an idle autoscaler fails: the sawtooth exists to prove tracking
    bad = json.loads(json.dumps(good))
    bad["sawtooth"]["autoscale_downs"] = 0
    ok, msgs = guard.compare(good, bad)
    assert not ok and any("autoscaler" in m for m in msgs)
    # cross-run regression: graded on matching ACCELERATOR hardware...
    worse = json.loads(json.dumps(tpu))
    worse["flood"]["disagg"]["itl_bg_p50_degradation"] = 1.4
    ok, msgs = guard.compare(tpu, worse)
    assert not ok and any("baseline" in m for m in msgs)
    # ...and skipped across a hardware mismatch
    worse["platform"] = {"backend": "tpu", "device": "v5e"}
    ok, msgs = guard.compare(tpu, worse)
    assert ok and any("SKIP" in m for m in msgs)


def test_serve_bench_guard_tenant_logic():
    """Tenant-artifact guard branch: correctness fields fail on ANY
    hardware; the gold p99 ratio is CPU-honesty gated (recorded, not
    graded, on a shared-core box) and baseline-gated on accelerators."""
    spec = importlib.util.spec_from_file_location(
        "serve_bench_guard", REPO / "scripts" / "serve_bench_guard.py"
    )
    guard = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(guard)

    def arm(label, attempts=0, rejected=0, counters=0):
        return {
            "label": label, "gold_e2e_ms": {"p50": 30.0, "p99": 50.0},
            "gold_ttft_ms": {"p50": 10.0, "p99": 20.0},
            "gold_done": 8, "gold_offered": 8,
            "flood_attempts": attempts, "flood_ok": 0,
            "flood_rejected": rejected, "flood_bad_rejections": 0,
            "dropped_streams": 0,
            "isolation_counters": {"router_rejected_quota": counters},
        }

    good = {
        "metric": "tenant_isolation", "value": 2.0,
        "isolation_factor_limit": 5.0,
        "platform": {"backend": "cpu", "device": "x"},
        "baseline": arm("baseline"),
        "flood": arm("flood", attempts=100, rejected=90, counters=90),
        "token_exact": True, "dropped_streams": 0,
    }
    ok, msgs = guard.compare(good, json.loads(json.dumps(good)))
    assert ok and any("not graded" in m for m in msgs)
    # correctness fails on any hardware
    bad = json.loads(json.dumps(good))
    bad["flood"]["gold_done"] = 7
    ok, msgs = guard.compare(good, bad)
    assert not ok and any("gold streams" in m for m in msgs)
    bad = json.loads(json.dumps(good))
    bad["flood"]["flood_rejected"] = 0
    bad["flood"]["isolation_counters"] = {"router_rejected_quota": 0}
    ok, msgs = guard.compare(good, bad)
    assert not ok and any("never throttled" in m for m in msgs)
    bad = json.loads(json.dumps(good))
    bad["flood"]["flood_bad_rejections"] = 3
    ok, msgs = guard.compare(good, bad)
    assert not ok and any("retryable" in m for m in msgs)
    # on CPU an awful ratio is recorded, not graded (shared cores)
    noisy = json.loads(json.dumps(good))
    noisy["value"] = 40.0
    ok, msgs = guard.compare(good, noisy)
    assert ok and any("cpu backend" in m for m in msgs)
    # on an accelerator the pinned factor grades...
    tpu = json.loads(json.dumps(good))
    tpu["platform"] = {"backend": "tpu", "device": "v4"}
    bad = json.loads(json.dumps(tpu))
    bad["value"] = 9.0
    ok, msgs = guard.compare(tpu, bad)
    assert not ok and any("pinned isolation factor" in m for m in msgs)
    # ...so does the baseline tolerance on matching hardware...
    worse = json.loads(json.dumps(tpu))
    worse["value"] = 3.0
    ok, msgs = guard.compare(tpu, worse)
    assert not ok and any("baseline" in m for m in msgs)
    # ...and a hardware mismatch skips the ratio but kept correctness
    worse["platform"] = {"backend": "tpu", "device": "v5e"}
    ok, msgs = guard.compare(tpu, worse)
    assert ok and any("SKIP" in m for m in msgs)


def test_serve_bench_guard_router_logic():
    """Router-artifact guard branch: correctness fields fail on ANY
    hardware, the scaling bar only grades against a matching-platform
    baseline."""
    spec = importlib.util.spec_from_file_location(
        "serve_bench_guard", REPO / "scripts" / "serve_bench_guard.py"
    )
    guard = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(guard)

    good = {
        "metric": "router_scaling_tok_s", "value": 3.4,
        "dropped_streams": 0,
        "failover": {"token_exact": True, "resumed_streams": 1},
        "rolling_reload": {"ok": True, "steps": 3, "dropped_streams": 0},
        "platform": {"backend": "cpu", "device": "x"},
        "fleet_trace": {"coverage_min": 0.99, "orphans": 0,
                        "hops_ordered": True, "requests": 4},
        "slo": {"verdict": "ok", "objectives": {}},
    }
    ok, _ = guard.compare(good, dict(good))
    assert ok
    # an SLO verdict of violated fails on matching hardware (ISSUE 15)...
    bad_slo = {**good, "slo": {"verdict": "violated", "objectives": {
        "availability": {"state": "fast_burn"}}}}
    ok, msgs = guard.compare(good, bad_slo)
    assert not ok and any("SLO" in m for m in msgs)
    # ...but skips with the other perf grades across a hardware mismatch
    ok, msgs = guard.compare(
        good, {**bad_slo, "platform": {"backend": "tpu", "device": "v4"}}
    )
    assert ok and any("SKIP" in m for m in msgs)
    # a broken stitched trace is correctness — fails anywhere
    ok, msgs = guard.compare(good, {
        **good, "platform": {"backend": "tpu", "device": "v4"},
        "fleet_trace": {"coverage_min": 0.5, "orphans": 0,
                        "hops_ordered": True},
    })
    assert not ok and any("coverage" in m for m in msgs)
    ok, msgs = guard.compare(good, {
        **good,
        "fleet_trace": {"coverage_min": 0.99, "orphans": 2,
                        "hops_ordered": True},
    })
    assert not ok and any("stitched" in m for m in msgs)
    # pre-PR15 artifacts (no fleet_trace/slo blocks) still grade cleanly
    legacy = {k: v for k, v in good.items()
              if k not in ("fleet_trace", "slo")}
    ok, _ = guard.compare(legacy, dict(legacy))
    assert ok
    # below the absolute near-linear bar fails on matching hardware
    ok, msgs = guard.compare(good, {**good, "value": 2.4})
    assert not ok and any("near-linear" in m for m in msgs)
    # >15% below the committed baseline fails even above the bar
    ok, msgs = guard.compare({**good, "value": 3.9}, {**good, "value": 3.2})
    assert not ok and any("baseline" in m for m in msgs)
    # hardware mismatch: scaling SKIPS instead of failing...
    other_hw = {**good, "value": 2.4,
                "platform": {"backend": "tpu", "device": "v4"}}
    ok, msgs = guard.compare(good, other_hw)
    assert ok and any("SKIP" in m for m in msgs)
    # ...but dropped streams / a non-exact failover / a failed reload are
    # correctness, and fail everywhere
    ok, msgs = guard.compare(good, {**other_hw, "dropped_streams": 1})
    assert not ok and any("dropped_streams" in m for m in msgs)
    ok, msgs = guard.compare(
        good, {**good, "failover": {"token_exact": False}}
    )
    assert not ok and any("token-exact" in m for m in msgs)
    ok, msgs = guard.compare(
        good,
        {**good, "rolling_reload": {"ok": True, "steps": 3,
                                    "dropped_streams": 2}},
    )
    assert not ok and any("rolling reload" in m for m in msgs)
    # a throughput artifact as "baseline" (metric mismatch) has no
    # comparable scaling number: the grade skips, correctness still checked
    ok, msgs = guard.compare({"metric": "serve_tokens_per_sec_test",
                              "platform": good["platform"]}, good)
    assert ok
    ok, msgs = guard.compare(
        {"metric": "serve_tokens_per_sec_test"},
        {**good, "dropped_streams": 3},
    )
    assert not ok


def test_serve_bench_guard_logic():
    """The regression guard fails loudly on >15% regressions when the
    hardware matches and skips (never fails) when it does not."""
    spec = importlib.util.spec_from_file_location(
        "serve_bench_guard", REPO / "scripts" / "serve_bench_guard.py"
    )
    guard = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(guard)

    base = {
        "decode_tok_s": 600.0, "itl_ms": {"p99": 2.0},
        "platform": {"backend": "cpu", "device": "x"}, "workload": "mixed",
    }
    same = dict(base)
    ok, _ = guard.compare(base, same)
    assert ok
    slow = {**base, "decode_tok_s": 400.0}
    ok, msgs = guard.compare(base, slow)
    assert not ok and any("decode_tok_s" in m for m in msgs)
    tail = {**base, "itl_ms": {"p99": 5.0}}
    ok, msgs = guard.compare(base, tail)
    assert not ok and any("p99" in m for m in msgs)
    # within tolerance passes
    ok, _ = guard.compare(base, {**base, "decode_tok_s": 540.0,
                                 "itl_ms": {"p99": 2.2}})
    assert ok
    # decode-only ITL tail (the fused-tail/kernel home metric) is graded
    # too, and absent blocks (older baselines) are skipped, not failed
    both = {**base, "itl_ms_decode_only": {"p99": 1.0}}
    ok, msgs = guard.compare(both, {**both, "itl_ms_decode_only": {"p99": 1.5}})
    assert not ok and any("decode_only" in m for m in msgs)
    ok, _ = guard.compare(both, {**both, "itl_ms_decode_only": {"p99": 1.1}})
    assert ok
    ok, _ = guard.compare(base, both)
    assert ok
    # different hardware: a regression-shaped delta SKIPS instead of failing
    other_hw = {**slow, "platform": {"backend": "tpu", "device": "v4"}}
    ok, msgs = guard.compare(base, other_hw)
    assert ok and any("SKIP" in m for m in msgs)
    # pre-platform-field baselines can only skip
    ok, msgs = guard.compare({"decode_tok_s": 600.0, "itl_ms": {"p99": 2.0}}, slow)
    assert ok and any("SKIP" in m for m in msgs)
    # capacity artifacts compare on the paged/slab stream ratio
    cap = {
        "metric": "serve_capacity_streams_ratio", "value": 8.0,
        "platform": {"backend": "cpu", "device": "x"},
    }
    ok, _ = guard.compare(cap, dict(cap))
    assert ok
    ok, msgs = guard.compare(cap, {**cap, "value": 4.0})
    assert not ok and any("capacity" in m for m in msgs)
    ok, _ = guard.compare(cap, {**cap, "value": 7.5})  # within tolerance
    assert ok
    # mismatched metrics (capacity vs throughput artifact) skip, not fail
    ok, msgs = guard.compare(cap, base)
    assert ok and any("SKIP" in m for m in msgs)
    # span-tracing overhead budget: >2% in the fresh artifact's own A/B
    # fails on matching hardware; <=2% passes; absent (no --obs-ab) passes
    heavy = {**base, "obs_overhead": {
        "overhead_frac": 0.05, "decode_tok_s_trace_off": 600.0,
        "decode_tok_s_trace_on": 570.0, "repeats": 3}}
    ok, msgs = guard.compare(base, heavy)
    assert not ok and any("tracing overhead" in m for m in msgs)
    light = {**base, "obs_overhead": {
        "overhead_frac": 0.01, "decode_tok_s_trace_off": 600.0,
        "decode_tok_s_trace_on": 594.0, "repeats": 3}}
    ok, _ = guard.compare(base, light)
    assert ok
    # hardware mismatch still skips BEFORE the overhead check fires
    ok, msgs = guard.compare(base, {**heavy, "platform": {"backend": "tpu",
                                                          "device": "v4"}})
    assert ok and any("SKIP" in m for m in msgs)


def test_loadgen_request_mix_is_deterministic():
    """Two processes building the mix must agree (the parity check decodes
    the reference from the same (prompt, seed) pairs)."""
    loadgen = _load()
    args = loadgen.parse_args(["--requests", "5"])
    a = loadgen.make_requests(args, 256, 32)
    b = loadgen.make_requests(args, 256, 32)
    assert a == b
    assert len(a) == 5
    assert all(2 <= len(p) <= 8 for p, _ in a)
    seeds = [s for _, s in a]
    assert seeds == list(range(5))  # seed = base + index
