"""Shared resolution of the persistent XLA compile-cache directory.

Used by ``tests/conftest.py`` AND the standalone multihost workers so every
process — pytest, xdist workers, spawned ``jax.distributed`` subprocesses,
CI with its own ``JAX_TEST_COMPILATION_CACHE`` — lands in the same
host-fingerprinted directory.

The fingerprint subdirectory is applied UNCONDITIONALLY (env-provided bases
included): cached AOT entries are only valid for the CPU feature set they
were compiled with, and the cross-host reuse case is exactly the one where
the base comes from the environment (CI actions/cache restoring a previous
runner's directory; VM migrations under a fixed operator-set path).
Observed failure modes of a stale entry: SIGILL'd xdist workers, SIGABRT
mid-compile (2026-07-31, twice). An empty base disables caching entirely.
"""
from __future__ import annotations

import os


def cpu_fingerprint() -> str:
    try:
        import zlib  # crc32: no crypto, so FIPS-enabled hosts can't reject it

        with open("/proc/cpuinfo") as f:
            for line in f:
                # x86 spells it "flags", aarch64 "Features"
                if line.startswith(("flags", "Features")):
                    return f"{zlib.crc32(line.encode()):08x}"
    except OSError:
        pass
    return "nofp"


def resolve_cache_dir() -> str:
    """The fingerprinted cache directory, or "" when caching is disabled."""
    base = os.path.expanduser(
        os.environ.get(
            "JAX_TEST_COMPILATION_CACHE", "/tmp/zero_transformer_tpu_jax_cache"
        )
    )
    if not base:
        return ""
    return os.path.join(base, cpu_fingerprint())


def configure(jax_module) -> str:
    """Point jax's persistent compile cache at the resolved directory (no-op
    when disabled); returns the directory used."""
    cache_dir = resolve_cache_dir()
    if cache_dir:
        jax_module.config.update("jax_compilation_cache_dir", cache_dir)
        # default min compile-time threshold (1s) would skip most test
        # programs; cache everything — CPU test compiles of 2+ seconds are
        # the norm here
        jax_module.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax_module.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    return cache_dir
