"""Real 2-process distributed test (VERDICT round-1 gap: every "multi-host"
path ran single-process only).

Launches two OS processes, each with 4 virtual CPU devices, wired together by
``jax.distributed`` through the env-driven ``bootstrap.maybe_initialize``.
The worker (``multihost_worker.py``) covers striped loading,
``device_put_batch``, a cross-process ZeRO-2 train step, multi-process Orbax
save/restore, and pod_check. The reference validated all of this only
manually on live pods (reference ``src/utils/pod_test.py``, SURVEY §4).
"""
import os
import socket
import subprocess
import sys
from pathlib import Path

import pytest

WORKER = Path(__file__).parent / "multihost_worker.py"


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_training_and_checkpoint(tmp_path):
    port = _free_port()
    procs = []
    for pid in range(2):
        env = dict(os.environ)
        env.update(
            JAX_COORDINATOR_ADDRESS=f"localhost:{port}",
            JAX_NUM_PROCESSES="2",
            JAX_PROCESS_ID=str(pid),
            WORKER_CKPT_DIR=str(tmp_path / "ckpt"),
        )
        procs.append(
            subprocess.Popen(
                [sys.executable, str(WORKER)],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
            )
        )
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=420)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail("multihost workers timed out:\n" + "\n---\n".join(outs))
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} rc={p.returncode}:\n{out}"
        assert "WORKER_OK" in out, f"worker {i} did not finish:\n{out}"
