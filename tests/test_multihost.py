"""Real 2-process distributed test (VERDICT round-1 gap: every "multi-host"
path ran single-process only).

Launches two OS processes, each with 4 virtual CPU devices, wired together by
``jax.distributed`` through the env-driven ``bootstrap.maybe_initialize``.
The worker (``multihost_worker.py``) covers striped loading,
``device_put_batch``, a cross-process ZeRO-2 train step, multi-process Orbax
save/restore, and pod_check. The reference validated all of this only
manually on live pods (reference ``src/utils/pod_test.py``, SURVEY §4).
"""
import os
import socket
import subprocess
import sys
from pathlib import Path

import pytest

WORKER = Path(__file__).parent / "multihost_worker.py"


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


RESUME_WORKER = Path(__file__).parent / "multihost_resume_worker.py"


def _launch(worker: Path, n: int, env_common: dict) -> list:
    port = _free_port()
    procs = []
    for pid in range(n):
        env = dict(os.environ)
        env.update(
            JAX_COORDINATOR_ADDRESS=f"localhost:{port}",
            JAX_NUM_PROCESSES=str(n),
            JAX_PROCESS_ID=str(pid),
            **env_common,
        )
        procs.append(
            subprocess.Popen(
                [sys.executable, str(worker)],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
            )
        )
    return procs


def _reap(procs, timeout: float) -> list:
    """ONE shared deadline for the whole process group — a wedged collective
    hangs every worker, and per-process timeouts would serialize into
    n x timeout of wasted CI wall-clock."""
    import time

    deadline = time.monotonic() + timeout
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=max(0.0, deadline - time.monotonic()))
        except subprocess.TimeoutExpired:
            p.kill()
            out, _ = p.communicate()
            out += "\n[KILLED BY TEST HARNESS]"
        outs.append(out)
    return outs


def _losses(out: str) -> dict:
    return {
        int(l.split()[1].split("=")[1]): l.split()[2]
        for l in out.splitlines()
        if l.startswith("LOSS step=")
    }


def _phase(worker, n, env, check, attempts=2, clean_ckpt=True):
    """Run one multi-process phase; ONE retry when the failure is the
    known infra flake (gloo's fixed 30s context-init deadline trips when
    per-process compile skew exceeds it on a loaded box — observed with
    concurrent background training; not a repo bug).

    ``clean_ckpt``: wipe WORKER_CKPT_DIR before each attempt — orbax
    save(force=True) does NOT overwrite an existing step
    (StepAlreadyExistsError), so a writer phase's retry must not see
    attempt 1's step. MUST be False for the resume phase, which exists to
    READ that directory."""
    import shutil

    for a in range(attempts):
        if clean_ckpt and env.get("WORKER_CKPT_DIR"):
            shutil.rmtree(env["WORKER_CKPT_DIR"], ignore_errors=True)
        procs = _launch(worker, n, env)
        outs = _reap(procs, 420)
        err = check(procs, outs)
        if err is None:
            return outs
        infra = any(
            "Gloo context initialization failed" in o
            or "DEADLINE_EXCEEDED" in o
            for o in outs
        )
        if a + 1 < attempts and infra:
            continue
        pytest.fail(err)


@pytest.mark.slow
def test_four_process_kill_and_resume(tmp_path):
    """Crash recovery across REAL process boundaries (round-4 VERDICT next
    #8): a 4-process job checkpoints, loses a member to an abrupt host
    death, and a fresh 4-process job restores the sharded checkpoint +
    loader position and continues with EXACTLY the trajectory an
    uninterrupted run produces. The reference's only recovery was manual
    (``src/utils/pod_test.py``, ``main_zero.py:291-313``)."""

    def all_ok(procs, outs):
        for i, (p, out) in enumerate(zip(procs, outs)):
            # the ground truth must come from a fully-clean run, not a job
            # where a non-rank-0 worker died while rank 0 limped to step 4
            if p.returncode != 0 or "WORKER_OK" not in out:
                return f"worker {i} rc={p.returncode}:\n{out}"
        return None

    env = {"WORKER_CKPT_DIR": str(tmp_path / "straight_ckpt"),
           "WORKER_MODE": "straight"}
    outs = _phase(RESUME_WORKER, 4, env, all_ok)
    truth = _losses(outs[0])
    assert set(truth) == {1, 2, 3, 4}, outs[0]

    # phase 2: periodic save at step 2, then process 3's host "dies"
    def interrupted_ok(procs, outs):
        if procs[3].returncode != 9:
            return f"victim survived rc={procs[3].returncode}:\n{outs[3]}"
        for i in (0, 1, 2):
            if "SAVED step=2" not in outs[i]:
                return f"survivor {i} never saved:\n{outs[i]}"
            # a job with a dead member must NOT complete the next step...
            if "SURVIVOR_STEP_COMPLETED_UNEXPECTEDLY" in outs[i]:
                return outs[i]
            # ...and must exit through the worker's own watchdog/error
            # path (rc 7), not hang until the harness deadline kills it
            if procs[i].returncode != 7:
                return f"survivor {i} rc={procs[i].returncode}:\n{outs[i]}"
        return None

    env = {"WORKER_CKPT_DIR": str(tmp_path / "ckpt"),
           "WORKER_MODE": "interrupted"}
    _phase(RESUME_WORKER, 4, env, interrupted_ok)

    # phase 3: fresh job restores and continues
    def resume_ok(procs, outs):
        for i, out in enumerate(outs):
            if "WORKER_OK" not in out:
                return f"resume worker {i}:\n{out}"
        return None

    env["WORKER_MODE"] = "resume"
    outs = _phase(RESUME_WORKER, 4, env, resume_ok, clean_ckpt=False)
    resumed = _losses(outs[0])
    assert set(resumed) == {3, 4}, outs[0]
    # exact continuation: the interruption is invisible in the trajectory
    assert resumed[3] == truth[3] and resumed[4] == truth[4], (resumed, truth)


@pytest.mark.slow
def test_elastic_resume_across_world_sizes(tmp_path):
    """Elastic ZeRO resume across REAL process boundaries (slow lane — stays
    out of tier-1 by marker): a checkpoint saved by a 4-process / 8-device
    job resumes on a 2-process / 4-device job (and 4 devices -> 8), through
    the digest-verified restore path with the ZeRO plan rebuilt for the new
    world. The global batch stream is identical across topologies, so the
    post-resume losses must match a same-topology uninterrupted run to
    reduction-order ulps (the batch-boundary trajectory semantics pinned in
    tests/test_elastic.py, here across real process counts)."""
    import numpy as np

    def all_ok(procs, outs):
        for i, (p, out) in enumerate(zip(procs, outs)):
            if p.returncode != 0 or "WORKER_OK" not in out:
                return f"worker {i} rc={p.returncode}:\n{out}"
        return None

    # ground truth: uninterrupted 4-process (8-device) run, steps 1-4
    env = {"WORKER_CKPT_DIR": str(tmp_path / "truth_ckpt"),
           "WORKER_MODE": "straight"}
    outs = _phase(RESUME_WORKER, 4, env, all_ok)
    truth = {k: float(v) for k, v in _losses(outs[0]).items()}
    assert set(truth) == {1, 2, 3, 4}, outs[0]

    def elastic(n_save, n_resume, tag, atol):
        env = {"WORKER_CKPT_DIR": str(tmp_path / f"ckpt_{tag}"),
               "WORKER_MODE": "elastic_save"}
        outs = _phase(RESUME_WORKER, n_save, env, all_ok)
        assert "SAVED step=2" in outs[0], outs[0]
        env["WORKER_MODE"] = "elastic_resume"
        outs = _phase(RESUME_WORKER, n_resume, env, all_ok, clean_ckpt=False)
        assert "ELASTIC device count" in outs[0], outs[0]
        resumed = {k: float(v) for k, v in _losses(outs[0]).items()}
        assert set(resumed) == {3, 4}, outs[0]
        for s in (3, 4):
            assert np.isclose(resumed[s], truth[s], rtol=0, atol=atol), (
                tag, s, resumed, truth,
            )

    # 8 simulated devices -> 4: steps 1-2 ran on the SAME topology as the
    # truth run, so only the 2 post-resume steps accumulate ulp drift
    elastic(4, 2, "8to4", atol=2e-4)
    # 4 -> 8: steps 1-2 ALSO ran on a different topology than the truth run
    # (drift on both sides of the save), so the bound is looser
    elastic(2, 4, "4to8", atol=5e-4)


@pytest.mark.slow
def test_two_process_training_and_checkpoint(tmp_path):
    procs = _launch(WORKER, 2, {"WORKER_CKPT_DIR": str(tmp_path / "ckpt")})
    outs = _reap(procs, 420)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} rc={p.returncode}:\n{out}"
        assert "WORKER_OK" in out, f"worker {i} did not finish:\n{out}"
