"""Unified observability layer (ISSUE 7): span lifecycle parity, Prometheus
exposition conformance, X-Request-Id round trip, flight-recorder dumps, and
on-demand profiling.

The load-bearing invariants:

- every ADMITTED request's span tree is complete and well-nested — root
  ``request`` span covering contiguous ``queue``/``prefill``/``decode``
  children accounting for >=95% of its measured wall latency — for every
  terminal outcome (done, shed, expired, cancelled, tick-faulted);
- ``/metrics`` text exposition parses under the Prometheus 0.0.4 grammar
  while the engine is actively serving (histogram buckets cumulative,
  ``+Inf`` == count), and the scrape never perturbs in-flight requests;
- a breaker-open fires a flight-recorder dump whose ring contains the
  faulting ticks — the post-mortem exists without verbose logging;
- profile captures ride the admin lifecycle (202 accepted, 409 while
  draining).
"""
import http.client
import json
import re
import threading
import time

import jax
import jax.numpy as jnp
import pytest

from zero_transformer_tpu import obs
from zero_transformer_tpu.config import model_config
from zero_transformer_tpu.inference.sampling import SamplingConfig
from zero_transformer_tpu.models import Transformer
from zero_transformer_tpu.serving import (
    ServeFault,
    ServingChaosMonkey,
    ServingEngine,
    run_server,
)

CACHE_LEN = 32
SAMPLING = SamplingConfig(temperature=0.9, top_k=20)


@pytest.fixture(scope="module")
def cfg():
    return model_config("test", dropout=0.0, compute_dtype="float32")


@pytest.fixture(scope="module")
def params(cfg):
    model = Transformer(cfg)
    return model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))[
        "params"
    ]


def make_engine(cfg, params, **kw):
    kw.setdefault("n_slots", 2)
    kw.setdefault("cache_len", CACHE_LEN)
    kw.setdefault("sampling", SAMPLING)
    return ServingEngine(cfg, params, **kw)


class ByteTok:
    eos_token_id = None

    def encode(self, text):
        return [ord(c) % 250 + 1 for c in text] or [1]

    def decode(self, toks, **kw):
        return "".join(chr(97 + (t % 26)) for t in toks)


# ------------------------------------------------------------ metric types


def test_histogram_observe_quantile_monotone():
    h = obs.Histogram("h_seconds", "t", buckets=(0.001, 0.01, 0.1, 1.0))
    assert h.quantile(0.5) == 0.0  # empty
    for v in (0.0005, 0.002, 0.003, 0.05, 0.5, 3.0):
        h.observe(v)
    qs = [h.quantile(q) for q in (0.1, 0.5, 0.9, 0.99, 1.0)]
    assert qs == sorted(qs), qs  # monotone in q
    assert len(h) == 6 and h.count == 6
    assert h.sum == pytest.approx(3.5555)
    # overflow clamps at the top finite bound, never extrapolates
    assert h.quantile(1.0) == 1.0


def test_histogram_rejects_bad_buckets():
    with pytest.raises(ValueError):
        obs.Histogram("x", "t", buckets=())
    with pytest.raises(ValueError):
        obs.Histogram("x", "t", buckets=(0.1, 0.01))


def test_registry_get_or_create_and_type_conflict():
    reg = obs.Registry()
    c1 = reg.counter("reqs", "h")
    assert reg.counter("reqs", "h") is c1  # idempotent wiring
    with pytest.raises(ValueError):
        reg.gauge("reqs", "h")  # one name, two meanings = scrape bug
    with pytest.raises(ValueError):
        c1.inc(-1)  # counters only go up
    # the two func flavors share one class — the type check must still hold
    reg.counter_func("fn_metric", "h", lambda: 1)
    with pytest.raises(ValueError):
        reg.gauge_func("fn_metric", "h", lambda: 2)


def test_exposition_format_counters_gauges_histograms_labels():
    reg = obs.Registry()
    reg.counter("a_reqs", "count").inc(3)
    reg.gauge("b_depth", 'weird "help"\nline').set(2.5)
    reg.histogram("c_seconds", "lat", buckets=(0.1, 1.0)).observe(0.05)
    reg.gauge_func("d_hbm", "per device",
                   lambda: [({"device": "0"}, 1.0), ({"device": "1"}, 2.0)])
    text = reg.render()
    assert 'c_seconds_bucket{le="0.1"} 1' in text
    assert 'c_seconds_bucket{le="+Inf"} 1' in text
    assert "a_reqs_total 3" in text
    assert 'd_hbm{device="1"} 2' in text
    # HELP text escapes the newline so the line-oriented grammar survives
    assert '# HELP b_depth weird "help"\\nline' in text


EXPOSITION_LINE = re.compile(
    r"^(?:"
    r"# (?:HELP|TYPE) [a-zA-Z_:][a-zA-Z0-9_:]* .+"
    r"|"
    r'[a-zA-Z_:][a-zA-Z0-9_:]*(?:\{(?:[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*",?)+\})?'
    r" (?:NaN|[+-]Inf|[-+]?[0-9]*\.?[0-9]+(?:[eE][-+]?[0-9]+)?)"
    r")$"
)


def _assert_conformant(text: str) -> None:
    """Every line matches the 0.0.4 exposition grammar; every histogram's
    bucket counts are cumulative and ``+Inf`` equals ``_count``."""
    assert text.endswith("\n")
    buckets: dict = {}
    counts: dict = {}
    for line in text.splitlines():
        assert EXPOSITION_LINE.match(line), f"malformed exposition line: {line!r}"
        if "_bucket{" in line:
            name = line.split("_bucket{", 1)[0]
            le = re.search(r'le="([^"]+)"', line).group(1)
            buckets.setdefault(name, []).append((le, float(line.rsplit(" ", 1)[1])))
        elif re.match(r"^[a-zA-Z_:][a-zA-Z0-9_:]*_count \d", line):
            counts[line.split("_count ", 1)[0]] = float(line.rsplit(" ", 1)[1])
    assert buckets, "no histograms rendered"
    for name, series in buckets.items():
        values = [v for _, v in series]
        assert values == sorted(values), f"{name} buckets not cumulative"
        assert series[-1][0] == "+Inf"
        assert values[-1] == counts[name], f"{name} +Inf != _count"


def test_engine_prometheus_text_conformance(cfg, params):
    engine = make_engine(cfg, params)
    for i in range(3):
        engine.submit([1 + i, 2, 3], max_new_tokens=4, seed=i)
    engine.run_until_idle()
    text = engine.prometheus_text()
    _assert_conformant(text)
    assert "serve_completed_total 3" in text
    assert "serve_ttft_seconds_count 3" in text


# ------------------------------------------------------------- span tracing


def test_tracer_ring_bounds_and_drop_count():
    tr = obs.Tracer(capacity=4)
    for i in range(10):
        tr.add("s", "t", float(i), float(i) + 0.5)
    assert len(tr) == 4 and tr.dropped == 6
    doc = tr.chrome_trace()
    assert doc["otherData"]["dropped_spans"] == 6
    names = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert len(names) == 4
    disabled = obs.Tracer(enabled=False)
    disabled.add("s", "t", 0.0, 1.0)
    assert len(disabled) == 0


def test_tracer_jsonl_is_incremental(tmp_path):
    tr = obs.Tracer()
    tr.add("a", "t", 0.0, 1.0)
    path = tmp_path / "spans.jsonl"
    assert tr.write_jsonl(path) == 1
    assert tr.write_jsonl(path) == 0  # nothing new
    tr.add("b", "t", 1.0, 2.0, {"k": 1})
    assert tr.write_jsonl(path) == 1
    lines = [json.loads(l) for l in path.read_text().splitlines()]
    assert [l["name"] for l in lines] == ["a", "b"]
    assert lines[1]["attrs"] == {"k": 1}


def _assert_complete_tree(spans, handle, outcome):
    """The acceptance bar: a complete, well-nested span tree whose children
    account for >=95% of the request's measured wall latency."""
    tree = obs.span_tree(spans, handle.rid)
    assert tree, f"no span tree for {handle.rid} ({outcome})"
    root = tree["root"]
    r0, r1 = root[obs.spans.T0], root[obs.spans.T1]
    assert root[obs.spans.ATTRS]["outcome"] == outcome
    assert r0 == handle.submitted_at and r1 == handle.finished_at
    for child in tree["children"]:
        assert child[obs.spans.T0] >= r0 - 1e-9, "child escapes root (left)"
        assert child[obs.spans.T1] <= r1 + 1e-9, "child escapes root (right)"
    assert obs.coverage_fraction(tree) >= 0.95
    names = {c[obs.spans.NAME] for c in tree["children"]}
    assert "queue" in names


def test_span_tree_complete_for_done_cancel_expire(cfg, params):
    """finish / cancel / queue-expiry outcomes all leave complete trees."""
    engine = make_engine(cfg, params, n_slots=2, prefill_chunk=8)
    done = [engine.submit([1, 2, 3], max_new_tokens=4, seed=i) for i in range(2)]
    # a third request queued behind the two slots, cancelled before admission
    cancelled = engine.submit([4, 5], max_new_tokens=4, seed=9)
    cancelled.cancel()
    # and one whose deadline has already passed when the scheduler sees it
    expired = engine.submit([6, 7], max_new_tokens=4, seed=10, timeout=0.0)
    engine.run_until_idle()
    spans = engine.tracer.spans()
    for h in done:
        assert h.status == "done"
        _assert_complete_tree(spans, h, "done")
        names = {c[obs.spans.NAME]
                 for c in obs.span_tree(spans, h.rid)["children"]}
        assert {"queue", "prefill", "decode"} <= names
    assert cancelled.status == "cancelled"
    _assert_complete_tree(spans, cancelled, "cancelled")
    assert expired.status == "expired"
    _assert_complete_tree(spans, expired, "expired")


def test_span_tree_complete_for_shed_and_reject(cfg, params):
    """Admission-time terminal outcomes (deadline shed, invalid reject)
    still get a root + queue tree — correlation ids must resolve even for
    requests that never touched a slot."""
    engine = make_engine(cfg, params)
    # warm the ITL EWMA so the shedder has evidence
    for _ in range(8):
        engine._itl_ewma.update(0.05)
    shed = engine.submit([1, 2], max_new_tokens=20, timeout=0.001)
    assert shed.status == "rejected" and "shed" in shed.error
    invalid = engine.submit([], max_new_tokens=4)
    assert invalid.status == "rejected"
    spans = engine.tracer.spans()
    _assert_complete_tree(spans, shed, "rejected")
    _assert_complete_tree(spans, invalid, "rejected")


def test_span_tree_complete_for_tick_fault(cfg, params):
    """A supervised decode-tick fault fails its slots retryably — and their
    span trees still close, outcome=failed, fault attribution intact."""
    chaos = ServingChaosMonkey([
        ServeFault("tick_fault", step=1, duration=1),
    ])
    engine = make_engine(cfg, params, chaos=chaos, prefill_chunk=8)
    handles = [engine.submit([1 + i, 2], max_new_tokens=6, seed=i)
               for i in range(2)]
    engine.run_until_idle()
    statuses = sorted(h.status for h in handles)
    assert "failed" in statuses  # the fault really fired
    spans = engine.tracer.spans()
    for h in handles:
        _assert_complete_tree(spans, h, h.status)
    # the engine-track timeline recorded phases around the fault
    engine_names = {s[obs.spans.NAME] for s in engine.tracer.by_track("engine")}
    assert "tick" in engine_names and "decode_step" in engine_names


def test_perfetto_export_has_thread_metadata(cfg, params, tmp_path):
    engine = make_engine(cfg, params)
    engine.submit([1, 2, 3], max_new_tokens=4, seed=0)
    engine.run_until_idle()
    path = engine.export_trace(str(tmp_path / "t.trace.json"))
    doc = json.loads((tmp_path / "t.trace.json").read_text())
    assert path and doc["traceEvents"]
    metas = [e for e in doc["traceEvents"] if e["ph"] == "M"]
    tracks = {m["args"]["name"] for m in metas}
    assert "engine" in tracks
    xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert all(e["dur"] >= 0 for e in xs)


# ------------------------------------------------------- HTTP: ids + scrape


def test_request_id_roundtrip_http_sse(cfg, params):
    """Inbound X-Request-Id is honored end-to-end (header + SSE done event);
    without one, the engine generates an id at admission and returns it the
    same two ways — non-stream JSON responses carry it too."""
    engine = make_engine(cfg, params, prefill_chunk=8)
    server = run_server(engine, ByteTok(), port=0, background=True)
    try:
        conn = http.client.HTTPConnection("127.0.0.1", server.port, timeout=60)
        conn.request(
            "POST", "/generate",
            json.dumps({"prompt": "hello", "max_new_tokens": 4}),
            {"Content-Type": "application/json", "X-Request-Id": "corr-123"},
        )
        resp = conn.getresponse()
        assert resp.status == 200
        assert resp.getheader("X-Request-Id") == "corr-123"
        body = resp.read().decode()
        done = json.loads(body.strip().splitlines()[-1][len("data: "):])
        assert done["done"] is True and done["request_id"] == "corr-123"
        # generated id: header and body agree, and it resolves to a span tree
        conn.request(
            "POST", "/generate",
            json.dumps({"prompt": "yo", "max_new_tokens": 2, "stream": False}),
            {"Content-Type": "application/json"},
        )
        resp = conn.getresponse()
        rid = resp.getheader("X-Request-Id")
        doc = json.loads(resp.read())
        assert rid and doc["request_id"] == rid
        assert obs.span_tree(engine.tracer.spans(), rid)
        # hostile ids (body field — http.client refuses to SEND a bad
        # header, but a raw-socket client wouldn't): CR/LF and non-ASCII
        # must never reach the response header (response splitting /
        # UnicodeEncodeError in send_header)
        conn.request(
            "POST", "/generate",
            json.dumps({"prompt": "x", "max_new_tokens": 2, "stream": False,
                        "request_id": "evil\r\nSet-Cookie: pwned=1"}),
            {"Content-Type": "application/json"},
        )
        resp = conn.getresponse()
        rid = resp.getheader("X-Request-Id")
        resp.read()
        assert resp.getheader("Set-Cookie") is None
        assert "\r" not in rid and "\n" not in rid and " " not in rid
        conn.request(
            "POST", "/generate",
            json.dumps({"prompt": "x", "max_new_tokens": 2, "stream": False,
                        "request_id": "☃☃"}),  # sanitizes to empty
            {"Content-Type": "application/json"},
        )
        resp = conn.getresponse()
        rid = resp.getheader("X-Request-Id")
        assert rid and rid.isascii()  # fell back to a generated id
        assert json.loads(resp.read())["request_id"] == rid
        conn.close()
    finally:
        server.stop()


def test_metrics_scrape_conformant_while_serving(cfg, params):
    """Prometheus text scrape (content-negotiated) DURING live traffic:
    format conforms, JSON default stays, and the scraped requests finish
    normally — exposition never perturbs the tick loop."""
    engine = make_engine(cfg, params, prefill_chunk=8)
    server = run_server(engine, ByteTok(), port=0, background=True)
    try:
        results = []

        def client(i):
            c = http.client.HTTPConnection("127.0.0.1", server.port, timeout=60)
            c.request(
                "POST", "/generate",
                json.dumps({"prompt": "x" * (3 + i), "max_new_tokens": 12,
                            "stream": False}),
                {"Content-Type": "application/json"},
            )
            r = c.getresponse()
            results.append((r.status, json.loads(r.read())["status"]))
            c.close()

        workers = [threading.Thread(target=client, args=(i,)) for i in range(4)]
        for w in workers:
            w.start()
        texts = []
        scrape = http.client.HTTPConnection("127.0.0.1", server.port, timeout=60)
        while any(w.is_alive() for w in workers):
            scrape.request("GET", "/metrics",
                           headers={"Accept": "text/plain;version=0.0.4"})
            r = scrape.getresponse()
            assert "text/plain; version=0.0.4" in r.getheader("Content-Type")
            texts.append(r.read().decode())
            time.sleep(0.02)
        for w in workers:
            w.join(timeout=60)
        # also via ?format= and the JSON default
        scrape.request("GET", "/metrics?format=prometheus")
        r = scrape.getresponse()
        texts.append(r.read().decode())
        scrape.request("GET", "/metrics")
        r = scrape.getresponse()
        assert "application/json" in r.getheader("Content-Type")
        snap = json.loads(r.read())
        scrape.close()
        assert snap["completed"] == 4
        assert all(s == (200, "done") for s in results), results
        for text in texts[-3:]:
            _assert_conformant(text)
        assert "serve_completed_total 4" in texts[-1]
    finally:
        server.stop()


# ------------------------------------------------------------ flight recorder


@pytest.mark.chaos
def test_flight_recorder_dumps_on_breaker_open(cfg, params, tmp_path):
    """Three consecutive injected tick faults trip the breaker — the dump
    must appear in the obs dir with the faulting ticks and the breaker_trip
    event inside, without any verbose logging enabled."""
    chaos = ServingChaosMonkey([
        ServeFault("tick_fault", step=2, duration=3),
    ])
    engine = make_engine(
        cfg, params, chaos=chaos, prefill_chunk=8,
        breaker_threshold=3, obs_dir=str(tmp_path),
    )
    # enough offered load that every faulting tick has active slots — the
    # breaker counts CONSECUTIVE faulted ticks, and an idle tick between
    # faults would reset nothing yet never trip
    for i in range(8):
        engine.submit([1 + i, 2, 3], max_new_tokens=16, seed=i)
    engine.run_until_idle()
    assert engine.stats["breaker_trips"] >= 1
    dumps = [p for p in engine.flight.dumps if "breaker_open" in p]
    assert dumps, engine.flight.dumps
    doc = json.loads(open(dumps[0]).read())
    assert doc["reason"] == "breaker_open"
    fault_ticks = [t for t in doc["ticks"] if t.get("fault")]
    assert len(fault_ticks) >= 3, "faulting ticks missing from the ring"
    assert any(e["event"] == "breaker_trip" for e in doc["events"])
    assert any(e["event"] == "tick_fault" for e in doc["events"])
    assert doc.get("spans"), "span tail missing from the dump"


def test_flight_recorder_dumps_on_drain(cfg, params, tmp_path):
    engine = make_engine(cfg, params, obs_dir=str(tmp_path))
    engine.submit([1, 2], max_new_tokens=3, seed=0)
    stop = threading.Event()
    t = threading.Thread(target=engine.run, args=(stop,), daemon=True)
    t.start()
    time.sleep(0.2)
    engine.begin_drain(deadline_s=30)
    t.join(timeout=60)
    assert engine.lifecycle.state == "stopped"
    assert any("drain" in p for p in engine.flight.dumps)
    # the drain path also exports the Perfetto trace + span log
    assert (tmp_path / "trace_serve.json").exists()
    assert (tmp_path / "spans.jsonl").exists()


def test_flight_recorder_no_dir_is_silent_noop():
    fr = obs.FlightRecorder(directory=None)
    fr.tick({"tick": 1})
    fr.event("boom", detail="x")
    assert fr.dump("anything") is None
    assert len(fr.ticks()) == 1 and len(fr.events()) == 1


# ----------------------------------------------------------------- profiling


def test_parse_profile_window():
    assert obs.parse_profile_window("100:20") == (100, 20)
    for bad in ("x:y", "100", "0:5", "5:0", ":"):
        with pytest.raises(ValueError):
            obs.parse_profile_window(bad)


@pytest.mark.slow
def test_profile_capture_over_http_and_draining_409(cfg, params, tmp_path):
    """Slow lane (a real jax.profiler capture serializes xplane protos for
    ~20s on CPU): the full 202 -> capture -> on-disk artifact -> drain-409
    lifecycle. Tier-1 covers the staging/conflict/draining refusals in
    test_profile_request_refusals without touching the profiler."""
    engine = make_engine(cfg, params, obs_dir=str(tmp_path), prefill_chunk=8)
    server = run_server(engine, ByteTok(), port=0, background=True)
    try:
        conn = http.client.HTTPConnection("127.0.0.1", server.port, timeout=60)
        conn.request("POST", "/admin/profile", json.dumps({"ticks": 2}),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        doc = json.loads(resp.read())
        assert resp.status == 202 and doc["accepted"] and doc["ticks"] == 2
        # a second request while the first is pending/active conflicts
        conn.request("POST", "/admin/profile", json.dumps({"ticks": 2}),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        assert resp.status == 409
        resp.read()
        # traffic drives ticks; the capture must complete and land on disk
        conn.request(
            "POST", "/generate",
            json.dumps({"prompt": "abc", "max_new_tokens": 8, "stream": False}),
            {"Content-Type": "application/json"},
        )
        conn.getresponse().read()
        deadline = time.time() + 30
        while engine.profile_active and time.time() < deadline:
            time.sleep(0.05)
        assert engine.profiles_completed, "capture never finished"
        assert (tmp_path / "profiles").exists()
        # draining: new captures are rejected with 409
        engine.begin_drain(deadline_s=30)
        conn.request("POST", "/admin/profile", json.dumps({"ticks": 1}),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        assert resp.status == 409
        body = json.loads(resp.read())
        assert "drain" in body["error"]
        conn.close()
    finally:
        server.stop()


def test_profile_request_refusals(cfg, params, tmp_path):
    """The staging-side contract, without ever touching jax.profiler (the
    scheduler never runs, so the staged capture never starts): no obs dir
    -> refuse; concurrent capture -> refuse; draining -> refuse."""
    engine = make_engine(cfg, params)  # no obs_dir
    with pytest.raises(RuntimeError, match="obs"):
        engine.request_profile(2)
    staged = make_engine(cfg, params, obs_dir=str(tmp_path))
    info = staged.request_profile(3)
    assert info["ticks"] == 3 and "profiles" in info["path"]
    with pytest.raises(RuntimeError, match="in progress"):
        staged.request_profile(2)
    draining = make_engine(cfg, params, obs_dir=str(tmp_path / "d"))
    draining.begin_drain(deadline_s=1.0)
    with pytest.raises(RuntimeError, match="drain"):
        draining.request_profile(2)


# ------------------------------------------------------------- training side


def test_hbm_device_stats_shape():
    stats = obs.hbm_device_stats()
    if stats is None:  # CPU backend exposes no memory stats — the honest None
        assert obs.hbm_used_gb() is None
        return
    assert stats["max_gb"] == max(stats["per_device_gb"])
    assert stats["mean_gb"] == pytest.approx(
        sum(stats["per_device_gb"]) / len(stats["per_device_gb"])
    )


def test_trainer_emits_step_spans_and_trace(tmp_path, devices):
    """A tiny end-to-end train run records the per-phase step timeline
    (data_fetch / dispatch / device_sync / checkpoint_save) and exports the
    Perfetto trace + spans.jsonl beside metrics.jsonl on close."""
    from zero_transformer_tpu.config import (
        CheckpointConfig,
        Config,
        DataConfig,
        MeshConfig,
        ModelConfig,
        OptimizerConfig,
        TrainingConfig,
    )
    from zero_transformer_tpu.training.trainer import Trainer

    cfg = Config(
        model=ModelConfig(vocab_size=64, d_model=32, n_heads=2, n_layers=2,
                          max_seq_len=16, dropout=0.0),
        mesh=MeshConfig(zero_stage=1),
        optimizer=OptimizerConfig(peak_learning_rate=1e-2, warmup_steps=2,
                                  total_steps=10),
        training=TrainingConfig(batch_size=8, train_context=16, total_steps=10,
                                evaluation_frequency=0,
                                maximum_evaluation_steps=1,
                                log_frequency=5, seed=0),
        data=DataConfig(source="synthetic", max_context=16),
        checkpoint=CheckpointConfig(directory=str(tmp_path / "run"),
                                    save_frequency=5, async_save=False),
    )
    trainer = Trainer(cfg)
    trainer.train()
    trainer.close()
    names = {s[obs.spans.NAME] for s in trainer.tracer.by_track("train")}
    assert {"data_fetch", "dispatch", "device_sync", "checkpoint_save"} <= names
    run_dir = tmp_path / "run"
    assert (run_dir / "trace_train.json").exists()
    assert (run_dir / "spans.jsonl").exists()
    assert (run_dir / "metrics.jsonl").exists()  # the obs exports sit beside it
    doc = json.loads((run_dir / "trace_train.json").read_text())
    assert any(e.get("name") == "data_fetch" for e in doc["traceEvents"])
    # flight ring carried the log-point step summaries
    assert any(t[1].get("step") for t in trainer.flight.ticks())
