"""Every shipped YAML config must load, validate, and produce a buildable
plan (catches zoo/field drift that unit tests on inline configs miss)."""
import glob
import os

import jax
import pytest

from zero_transformer_tpu.config import load_config, load_model_zoo, model_config

CONFIG_DIR = os.path.join(os.path.dirname(__file__), "..", "configs")
TRAIN_CONFIGS = sorted(glob.glob(os.path.join(CONFIG_DIR, "train_*.yaml")))


def test_zoo_entries_all_valid():
    zoo = load_model_zoo(os.path.join(CONFIG_DIR, "models.yaml"))
    assert {"test", "125m", "580m", "1_3b", "llama3_8b", "moe_test"} <= set(zoo)
    for name in zoo:
        cfg = model_config(name)  # __post_init__ validates
        assert cfg.num_params > 0


@pytest.mark.parametrize(
    "path", TRAIN_CONFIGS, ids=[os.path.basename(p) for p in TRAIN_CONFIGS]
)
def test_train_config_loads_and_plans(path):
    cfg = load_config(path)
    assert cfg.training.total_steps > cfg.optimizer.warmup_steps
    # the batch geometry must be loadable (divisibility rules)
    split = cfg.data.max_context // cfg.training.train_context
    assert cfg.data.max_context % cfg.training.train_context == 0
    seqs = cfg.training.batch_size * max(cfg.training.gradient_accumulation_steps, 1)
    assert seqs % split == 0
    # the model must trace at the configured train shape (ALiBi extrapolates
    # past max_seq_len; learned positions would raise here)
    from zero_transformer_tpu.models import Transformer

    model = Transformer(cfg.model)
    # the input must be an eval_shape ARGUMENT (abstracted to a tracer), not
    # a closure: a closed-over ShapeDtypeStruct reaches the model raw, and
    # packed models compare tokens against doc_sep_token (`x == sep`)
    jax.eval_shape(
        lambda r, x: model.init(r, x),
        jax.random.PRNGKey(0),
        jax.ShapeDtypeStruct((1, cfg.training.train_context), jax.numpy.int32),
    )
