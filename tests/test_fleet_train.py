"""MPMD training fleet: fold determinism, elastic re-layout, chaos recovery.

Two tiers:

- Fast unit tests (tier-1): pure-numpy fold/shard/wire contracts, the
  FleetRegistry facade over serving's registry, and FleetCoordinator
  control-plane logic driven directly (no subprocesses, no jax compute —
  stub "workers" post hand-built numpy gradient docs).

- ``slow + chaos`` multi-process scenarios: real coordinator + N real
  worker processes (scripts/train_coordinator.py), faults injected with
  ChaosMonkey process-level kinds. The acceptance bar: SIGKILLing a
  worker costs bounded replay and the recovered run's loss trajectory is
  BITWISE identical to an unfaulted control run.
"""
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from zero_transformer_tpu.obs.fleet import detect_stragglers, verify_stitched
from zero_transformer_tpu.training.fleet import (
    FLEET_BENCH_REQUIRED_KEYS,
    CoordinatorServer,
    FleetCoordinator,
    FleetRegistry,
    assign_shards,
    decode_leaves,
    encode_leaves,
    fold_losses,
    fold_shard_leaves,
    http_json,
    scale_leaves,
    shard_batch,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
COORD_SCRIPT = os.path.join(REPO, "scripts", "train_coordinator.py")


# ---------------------------------------------------------------- fold contracts


def test_assign_shards_covers_all_shards_deterministically():
    a = assign_shards(["w2", "w0", "w1"], 7)
    assert sorted(s for ss in a.values() for s in ss) == list(range(7))
    # pure function of the (sorted) live set — order of discovery is noise
    assert a == assign_shards(["w0", "w1", "w2"], 7)
    # more workers than shards: the surplus worker is shardless, not failed
    b = assign_shards(["w0", "w1", "w2"], 2)
    assert b["w2"] == ()


def test_shard_batch_counter_addressed():
    a = shard_batch(seed=3, step=5, shard=1, per_shard=4, seq_len=8, vocab=50)
    b = shard_batch(seed=3, step=5, shard=1, per_shard=4, seq_len=8, vocab=50)
    assert a.dtype == np.int32 and a.shape == (4, 8)
    np.testing.assert_array_equal(a, b)  # replay regenerates identical data
    assert not np.array_equal(
        a, shard_batch(seed=3, step=5, shard=2, per_shard=4, seq_len=8, vocab=50)
    )
    assert not np.array_equal(
        a, shard_batch(seed=3, step=6, shard=1, per_shard=4, seq_len=8, vocab=50)
    )


def test_encode_decode_leaves_bitwise_roundtrip():
    rng = np.random.default_rng(0)
    leaves = [
        rng.standard_normal((3, 5)).astype(np.float32),
        rng.integers(0, 9, size=(7,), dtype=np.int32),
        np.float32(1e-30) * rng.standard_normal((2, 2, 2)).astype(np.float32),
    ]
    out = decode_leaves(encode_leaves(leaves))
    assert len(out) == len(leaves)
    for a, b in zip(leaves, out):
        assert a.dtype == b.dtype
        assert a.tobytes() == b.tobytes()  # bit-exact, not allclose


def test_fold_is_invariant_to_contribution_arrival_order():
    rng = np.random.default_rng(1)
    per_shard = {
        s: [rng.standard_normal((4, 3)).astype(np.float32)] for s in range(4)
    }
    folded1 = fold_shard_leaves({s: per_shard[s] for s in [0, 1, 2, 3]})
    folded2 = fold_shard_leaves({s: per_shard[s] for s in [3, 1, 0, 2]})
    assert folded1[0].tobytes() == folded2[0].tobytes()
    # fixed left-fold bracketing, spelled out
    expect = ((per_shard[0][0] + per_shard[1][0]) + per_shard[2][0]) + per_shard[3][0]
    assert folded1[0].tobytes() == expect.tobytes()
    scaled = scale_leaves(folded1, 4)
    assert scaled[0].dtype == np.float32
    assert scaled[0].tobytes() == (expect * np.float32(0.25)).tobytes()


def test_fold_losses_fixed_order():
    losses = {2: 0.3, 0: 0.1, 1: 0.2}
    a = fold_losses(losses, 3)
    b = fold_losses(dict(sorted(losses.items())), 3)
    assert a == b


# ---------------------------------------------------------------- registry facade


class FakeClock:
    def __init__(self):
        self.t = 100.0

    def __call__(self):
        return self.t


def test_fleet_registry_silence_ejects_after_threshold():
    clk = FakeClock()
    reg = FleetRegistry(clock=clk, hb_timeout_s=1.0, eject_threshold=3)
    reg.register("w0")
    reg.register("w1")
    assert reg.live() == ["w0", "w1"]
    for _ in range(3):
        clk.t += 1.5  # w1 goes silent; w0 keeps beating
        assert reg.heartbeat("w0", {})
        events = reg.sweep()
        if ("ejected", "w1") in events:
            break
    else:
        pytest.fail("w1 never ejected despite heartbeat silence")
    assert not reg.is_live("w1")
    assert reg.is_live("w0")


def test_fleet_registry_late_heartbeat_from_removed_worker_dropped():
    clk = FakeClock()
    reg = FleetRegistry(clock=clk, hb_timeout_s=1.0)
    reg.register("w0")
    reg.remove("w0")
    # the straggling heartbeat must NOT resurrect the row
    assert reg.heartbeat("w0", {}) is False
    assert reg.live() == []
    assert not reg.is_live("w0")


def test_fleet_registry_reregister_gets_fresh_row_not_stale_cordon():
    clk = FakeClock()
    reg = FleetRegistry(clock=clk, hb_timeout_s=1.0)
    reg.register("w0")
    reg.cordon("w0")
    assert not reg.is_live("w0")
    # SIGKILLed worker respawns under the same id: fresh row, no cordon
    reg.register("w0")
    assert reg.is_live("w0")


# ------------------------------------------------------------ coordinator logic


def _grad_doc(value, shape=(2, 2)):
    return encode_leaves([np.full(shape, value, dtype=np.float32)])


def _submit_all(coord, wid, step, values, timeout=2.0):
    """One worker posting every shard of ``step`` in a single call.

    NB: ``timeout`` is measured on the COORDINATOR's clock — tests driving
    a frozen FakeClock must pass 0 or the barrier wait never expires."""
    docs = {str(s): _grad_doc(v) for s, v in values.items()}
    losses = {str(s): float(s) * 0.1 for s in values}
    return coord.submit(wid, coord.epoch, step, docs, losses, timeout=timeout)


def test_fold_barrier_releases_mean_of_shards():
    coord = FleetCoordinator(n_shards=3, total_steps=None)
    coord.join("w0")
    out = _submit_all(coord, "w0", 0, {0: 1.0, 1: 2.0, 2: 6.0})
    assert out.get("ok"), out
    grads = decode_leaves(out["grads"])
    np.testing.assert_array_equal(
        grads[0], np.full((2, 2), 3.0, dtype=np.float32)
    )
    assert coord.committed == 0


def test_final_fold_is_delivered_before_stop():
    coord = FleetCoordinator(n_shards=2, total_steps=1)
    coord.join("w0")
    out = _submit_all(coord, "w0", 0, {0: 1.0, 1: 3.0})
    # the run-ending fold must still reach the submitter — a bare "stop"
    # here would strand the final optimizer step on the coordinator
    assert out.get("ok"), out
    assert coord.stopping and coord.done.is_set()
    assert _submit_all(coord, "w0", 1, {0: 1.0, 1: 1.0}).get("stop")


def test_join_after_stop_is_refused_with_stop():
    coord = FleetCoordinator(n_shards=1, total_steps=1)
    coord.join("w0")
    _submit_all(coord, "w0", 0, {0: 1.0})
    epochs_before = coord.epoch
    out = coord.join("w9")
    assert out.get("stop")
    assert out["assignment"] == {}
    assert coord.epoch == epochs_before  # no phantom relayout record


def test_relayout_keeps_partial_contribs_and_replays_only_missing_shards():
    clk = FakeClock()
    coord = FleetCoordinator(
        n_shards=3, min_workers=1, hb_timeout_s=1.0, eject_threshold=3,
        clock=clk,
    )
    coord.join("w0")
    coord.join("w1")
    assert coord.assignment == {"w0": (0, 2), "w1": (1,)}
    # w0 delivers its shards; w1's shard 1 never arrives
    out = _submit_all(coord, "w0", 0, {0: 1.0, 2: 5.0}, timeout=0)
    assert out.get("retry"), out
    assert sorted(coord.contribs) == [0, 2]
    # w1 goes silent -> ejected -> loss relayout
    for _ in range(4):
        clk.t += 1.5
        coord.registry.heartbeat("w0", {})
        coord.sweep()
        if not coord.registry.is_live("w1"):
            break
    assert coord.assignment == {"w0": (0, 1, 2)}
    rec = coord.relayouts[-1]
    assert rec.lost == ("w1",)
    assert rec.replayed_shards == 1  # NOT 3: partial contribs survived
    assert sorted(coord.contribs) == [0, 2]
    # survivor supplies only the missing shard under the new epoch
    out = coord.submit(
        "w0", coord.epoch, 0, {"1": _grad_doc(3.0)}, {"1": 0.1}, timeout=2.0
    )
    assert out.get("ok"), out
    np.testing.assert_array_equal(
        decode_leaves(out["grads"])[0], np.full((2, 2), 3.0, dtype=np.float32)
    )


def test_stale_epoch_submit_bounced_with_new_layout():
    coord = FleetCoordinator(n_shards=2)
    coord.join("w0")
    old_epoch = coord.epoch
    coord.join("w1")  # bumps the epoch
    out = coord.submit(
        "w0", old_epoch, 0, {"0": _grad_doc(1.0)}, {"0": 0.0}, timeout=2.0
    )
    assert out.get("relayout"), out
    assert out["epoch"] == coord.epoch
    assert "w1" in out["assignment"]


def test_submit_from_removed_worker_is_gone():
    coord = FleetCoordinator(n_shards=1)
    coord.join("w0")
    coord.registry.remove("w0")
    out = coord.submit("w0", coord.epoch, 0, {}, {}, timeout=0.1)
    assert out.get("gone")


def test_late_heartbeat_into_coordinator_dropped_with_event():
    coord = FleetCoordinator(n_shards=1)
    assert coord.heartbeat("ghost", {"step": 0}) is None  # HTTP layer: 410
    assert any(
        e["event"] == "late_heartbeat_dropped" and e["wid"] == "ghost"
        for e in coord.events
    )


def test_sole_survivor_snapshot_rewind_is_bounded():
    clk = FakeClock()
    coord = FleetCoordinator(
        n_shards=1, snapshot_every=3, hb_timeout_s=1.0, clock=clk
    )
    coord.join("w0")
    for s in range(5):
        assert _submit_all(coord, "w0", s, {0: float(s)}).get("ok")
    assert coord.committed == 4
    losses_before = list(coord.loss_history)
    for _ in range(4):  # whole fleet dies
        clk.t += 1.5
        coord.sweep()
    assert coord.registry.live() == []
    # respawned worker restored the step-3 snapshot; fold line rewinds to it
    out = coord.join("w0", version=3)
    assert coord.committed == 2
    rec = coord.relayouts[-1]
    assert rec.reason == "rewind:w0"
    assert rec.replayed_steps == 2
    assert rec.replayed_steps <= coord.snapshot_every  # the bounded-replay bar
    assert [e[0] for e in coord.loss_history] == [0, 1, 2]
    # replay re-produces the exact losses that were rewound away
    for s in (3, 4):
        out = _submit_all(coord, "w0", s, {0: float(s)})
        assert out.get("ok")
    assert coord.loss_history == losses_before


def _compute_spans(step0, n, dur, t0=1000.0):
    spans = []
    t = t0
    for i in range(n):
        spans.append(
            {"track": f"step-{step0 + i}", "name": "compute",
             "t0": t, "t1": t + dur, "attrs": {}}
        )
        t += dur + 0.001
    return spans


def test_detect_stragglers_median_robust():
    groups = [
        {"process": "w0", "offset_s": 0.0, "spans": _compute_spans(0, 5, 0.01)},
        {"process": "w1", "offset_s": 0.0, "spans": _compute_spans(0, 5, 0.012)},
        {"process": "w2", "offset_s": 0.0, "spans": _compute_spans(0, 5, 0.11)},
    ]
    rep = detect_stragglers(groups, factor=3.0, min_spans=4)
    assert rep["w2"]["straggler"] and rep["w2"]["ratio"] > 3.0
    assert not rep["w0"]["straggler"] and not rep["w1"]["straggler"]
    # a lone process has no fleet to lag behind
    assert not detect_stragglers(groups[:1], factor=3.0, min_spans=4)["w0"]["straggler"]
    # too few samples: no verdict
    few = [dict(g, spans=g["spans"][:2]) for g in groups]
    assert not detect_stragglers(few, factor=3.0, min_spans=4)["w2"]["straggler"]


def test_straggler_shed_moves_shard_to_fastest_worker():
    # three processes: with only two, the median baseline sits halfway
    # between fast and slow and fleet-relative detection (correctly) abstains
    coord = FleetCoordinator(
        n_shards=6, straggler_factor=3.0, straggler_min_spans=4
    )
    for w in ("w0", "w1", "w2"):
        coord.join(w)
    assert coord.assignment == {"w0": (0, 3), "w1": (1, 4), "w2": (2, 5)}
    coord.worker_spans["w0"] = _compute_spans(0, 6, 0.01)
    coord.worker_spans["w1"] = _compute_spans(0, 6, 0.2)
    coord.worker_spans["w2"] = _compute_spans(0, 6, 0.012)
    coord.sweep()
    assert any(e["event"] == "straggler_detected" for e in coord.events)
    assert coord.relayouts[-1].reason == "shed:w1->w0"
    assert len(coord.assignment["w1"]) == 1
    all_shards = sorted(s for ss in coord.assignment.values() for s in ss)
    assert all_shards == [0, 1, 2, 3, 4, 5]  # shed re-homes work, never drops it


def test_min_workers_start_gate_holds_first_fold():
    coord = FleetCoordinator(n_shards=2, min_workers=2)
    coord.join("w0")
    assert coord.assignment == {}  # gate closed: nobody owns shards yet
    coord.join("w1")
    assert set(coord.assignment) == {"w0", "w1"}


def test_bench_document_schema_and_json_safety():
    coord = FleetCoordinator(n_shards=1, total_steps=2)
    coord.join("w0")
    for s in range(2):
        _submit_all(coord, "w0", s, {0: 1.0})
    doc = coord.bench(chaos=["w0=sigkill@1"], bitwise_rejoin=True)
    assert set(FLEET_BENCH_REQUIRED_KEYS) <= set(doc)
    json.dumps(doc, allow_nan=False)  # NaN downtime must never leak out
    assert doc["steps"] == 2
    assert doc["bitwise_rejoin"] is True


def test_trace_doc_stitches_worker_and_coordinator_spans():
    coord = FleetCoordinator(n_shards=2)
    coord.join("w0")
    t0 = coord.clock()
    out = _submit_all(coord, "w0", 0, {0: 1.0, 1: 2.0})
    assert out.get("ok")
    # worker-side spans arrive via heartbeat drain
    coord.heartbeat(
        "w0",
        {"step": 1, "offset_s": 0.0, "spans": [
            {"track": "step-0", "name": "compute", "t0": t0, "t1": t0 + 0.001,
             "attrs": {"shard": 0}},
            {"track": "step-0", "name": "post", "t0": t0 + 0.001,
             "t1": coord.clock(), "attrs": {}},
        ]},
    )
    doc = coord.trace_doc(0)
    names = {
        e["name"] for e in doc["traceEvents"] if e.get("ph") == "X"
    }
    assert {"route", "compute", "post"} <= names
    rep = verify_stitched(doc, "step-0")
    assert rep["orphans"] == 0
    assert rep["spans"] >= 3


def test_http_control_plane_roundtrip():
    coord = FleetCoordinator(n_shards=1, total_steps=2)
    with CoordinatorServer(coord, sweep_interval_s=0.05) as srv:
        _, join = http_json(srv.url, "/join", {"wid": "w0", "offset_s": 0.0})
        assert join["bootstrap"] == "init"
        assert join["cfg"]["n_shards"] == 1
        status, _ = http_json(
            srv.url, "/heartbeat", {"wid": "ghost", "step": 0}
        )
        assert status == 410  # unknown worker must re-join, not be re-added
        for s in range(2):
            _, out = http_json(
                srv.url, "/grads",
                {"wid": "w0", "epoch": join["epoch"], "step": s,
                 "shards": {"0": _grad_doc(float(s + 1))},
                 "losses": {"0": 0.5}},
            )
            assert out.get("ok"), out
        _, st = http_json(srv.url, "/status")
        assert st["committed"] == 1 and st["stopping"]
        _, clk = http_json(srv.url, "/clock")
        assert "clock_monotonic" in clk


# ------------------------------------------------- committed chaos-proof artifact


def test_committed_fleet_bench_artifact_proves_bounded_replay():
    path = os.path.join(REPO, "BENCH_fleet_train.json")
    assert os.path.exists(path), "chaos-proof artifact missing"
    doc = json.load(open(path))
    assert set(FLEET_BENCH_REQUIRED_KEYS) <= set(doc)
    assert doc["bitwise_rejoin"] is True
    assert doc["workers"] >= 3
    assert any("sigkill" in c for c in doc["chaos"])
    # the acceptance bound: replay after a kill <= snapshot interval
    assert 1 <= doc["replayed_steps"] <= doc["snapshot_every"]
    assert doc["relayout_downtime_s"] >= 0.0
    assert any(r["lost"] for r in doc["relayouts"])


def test_committed_fleet_trace_is_stitched():
    path = os.path.join(REPO, "BENCH_fleet_train.trace.json")
    assert os.path.exists(path), "fleet trace artifact missing"
    doc = json.load(open(path))
    roots = [
        e for e in doc["traceEvents"]
        if e.get("ph") == "X" and e["name"] == "route"
    ]
    assert roots, "no global-step root span"
    track = roots[0]["cat"]
    rep = verify_stitched(doc, track)
    assert rep["orphans"] == 0
    assert rep["spans"] >= 4
    # more than one process contributed to the step's merged timeline
    pids = {
        e["pid"] for e in doc["traceEvents"]
        if e.get("ph") == "X" and e.get("cat") == track
    }
    assert len(pids) >= 2


# ------------------------------------------------------- multi-process scenarios


def _run_fleet(tmp, *, steps=10, workers=3, chaos=(), respawn=0,
               snapshot_every=3, control=None, extra=()):
    out = {
        "losses": os.path.join(tmp, "losses.json"),
        "status": os.path.join(tmp, "status.json"),
        "bench": os.path.join(tmp, "bench.json"),
        "logs": os.path.join(tmp, "logs"),
    }
    cmd = [
        sys.executable, COORD_SCRIPT,
        "--workers", str(workers), "--steps", str(steps),
        "--shards", "4", "--snapshot-every", str(snapshot_every),
        "--ckpt-dir", os.path.join(tmp, "ckpt"),
        "--worker-logs", out["logs"],
        "--losses-out", out["losses"],
        "--status-out", out["status"],
        "--bench-out", out["bench"],
        "--respawn", str(respawn),
        "--timeout", "150",
    ]
    if respawn:
        # first respawn must land AFTER the death sweep (hb_timeout 0.75s):
        # the scenario under test is detect -> re-layout -> re-admit, not a
        # replacement sneaking in before the fleet notices the loss
        cmd += ["--backoff-base", "1.5"]
    for c in chaos:
        cmd += ["--chaos", c]
    if control:
        cmd += ["--control-losses", control]
    cmd += list(extra)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        cmd, cwd=REPO, env=env, capture_output=True, text=True, timeout=240
    )
    return proc, out


def _worker_logs(paths):
    text = ""
    for name in sorted(os.listdir(paths["logs"])):
        text += open(os.path.join(paths["logs"], name)).read()
    return text


@pytest.fixture(scope="module")
def control_losses(tmp_path_factory):
    """One unfaulted 10-step run; every chaos scenario's bitwise reference."""
    tmp = str(tmp_path_factory.mktemp("fleet_control"))
    proc, paths = _run_fleet(tmp)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    losses = json.load(open(paths["losses"]))
    assert len(losses) == 10
    return paths["losses"]


@pytest.mark.slow
@pytest.mark.chaos
def test_sigkill_bounded_replay_bitwise_rejoin(tmp_path, control_losses):
    """THE acceptance scenario: SIGKILL one of three workers mid-run; the
    fleet re-layouts, replays at most the partial step, and the recovered
    loss trajectory rejoins the unfaulted control bitwise."""
    proc, paths = _run_fleet(
        str(tmp_path), chaos=["w1=sigkill@4"], respawn=2,
        control=control_losses,
        extra=["--trace-out", os.path.join(str(tmp_path), "trace.json"),
               "--trace-step", "7"],
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "BITWISE_REJOIN=True" in proc.stdout
    bench = json.load(open(paths["bench"]))
    assert bench["bitwise_rejoin"] is True
    assert 1 <= bench["replayed_steps"] <= bench["snapshot_every"]
    status = json.load(open(paths["status"]))
    assert any(r["lost"] == ["w1"] for r in status["relayouts"])
    doc = json.load(open(os.path.join(str(tmp_path), "trace.json")))
    rep = verify_stitched(doc, "step-7")
    assert rep["orphans"] == 0 and rep["spans"] >= 4


@pytest.mark.slow
@pytest.mark.chaos
def test_heartbeat_blackhole_declared_dead_then_rejoins(tmp_path, control_losses):
    # a warm-cache global step takes ~50ms, so an unpaced 10-step run ends
    # before heartbeat silence can cross the death threshold. The uniform
    # slow_worker sleep paces every worker equally: pure wall-clock, zero
    # effect on the math — the bitwise check against the unpaced control
    # run is itself evidence of that.
    pace = [f"w{i}=slow_worker@0:0.08" for i in range(3)]
    proc, paths = _run_fleet(
        str(tmp_path), chaos=pace + ["w2=hb_blackhole@3:2.5"],
        control=control_losses, extra=["--hb-timeout", "0.5"],
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    # partitioned, ejected, and re-admitted through a FRESH registry row —
    # the trajectory never notices
    assert "declared dead by coordinator" in _worker_logs(paths)
    assert "BITWISE_REJOIN=True" in proc.stdout
    status = json.load(open(paths["status"]))
    assert any("w2" in r["lost"] for r in status["relayouts"])


@pytest.mark.slow
@pytest.mark.chaos
def test_sigstop_hang_survivors_finish_bitwise(tmp_path, control_losses):
    proc, paths = _run_fleet(
        str(tmp_path), chaos=["w1=sigstop@3"], control=control_losses
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "BITWISE_REJOIN=True" in proc.stdout
    status = json.load(open(paths["status"]))
    assert any("w1" in r["lost"] for r in status["relayouts"])


@pytest.mark.slow
@pytest.mark.chaos
def test_slow_worker_detected_as_straggler(tmp_path):
    proc, paths = _run_fleet(
        str(tmp_path), steps=12, chaos=["w1=slow_worker@2:0.4"],
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    status = json.load(open(paths["status"]))
    assert "w1" in status["stragglers"], status["stragglers"]
    assert any(
        e["event"] == "straggler_detected" and e["wid"] == "w1"
        for e in status["events"]
    )
    # shedding moved load but never changed the math
    losses = json.load(open(paths["losses"]))
    assert len(losses) == 12


@pytest.mark.slow
@pytest.mark.chaos
def test_full_fleet_kill_snapshot_rewind_bounded(tmp_path, control_losses):
    """Sole worker SIGKILLed between snapshots: the respawn restores the
    latest verified snapshot, the coordinator rewinds the fold line to it,
    and replay is bounded by the snapshot interval. Worker count differs
    from the 3-worker control — the trajectory must not care."""
    proc, paths = _run_fleet(
        str(tmp_path), workers=1, chaos=["w0=sigkill@5"], respawn=2,
        control=control_losses,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "BITWISE_REJOIN=True" in proc.stdout
    status = json.load(open(paths["status"]))
    rewinds = [r for r in status["relayouts"] if r["reason"].startswith("rewind:")]
    assert rewinds, status["relayouts"]
    assert 1 <= rewinds[0]["replayed_steps"] <= 3
