"""Data pipeline tests: sources, striping, curriculum, resume, sharded put.

The reference has no data-pipeline tests at all (SURVEY §4); its semantics
(process striping ``main_zero.py:377-387``, curriculum reshape ``:425-428``,
islice resume skip ``:470-471``) are pinned here against the new pipeline.
"""
import numpy as np
import pytest

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from zero_transformer_tpu.config import Config, DataConfig, MeshConfig, ModelConfig, TrainingConfig
from zero_transformer_tpu.data import (
    DataLoader,
    MemmapSource,
    SyntheticSource,
    device_put_batch,
    make_loader,
)
from zero_transformer_tpu.data.sources import write_memmap
from zero_transformer_tpu.parallel.mesh import make_mesh


def take(it, n):
    return [next(it) for _ in range(n)]


class TestSyntheticSource:
    def test_deterministic(self):
        a = take(iter(SyntheticSource(100, 16, seed=1)), 5)
        b = take(iter(SyntheticSource(100, 16, seed=1)), 5)
        for x, y in zip(a, b):
            np.testing.assert_array_equal(x, y)
        assert a[0].dtype == np.int32 and a[0].shape == (16,)

    def test_seed_changes_stream(self):
        a = next(iter(SyntheticSource(100, 16, seed=1)))
        b = next(iter(SyntheticSource(100, 16, seed=2)))
        assert not np.array_equal(a, b)

    def test_seek_matches_discard(self):
        s1 = SyntheticSource(100, 16, seed=1)
        take(iter(s1), 7)
        s2 = SyntheticSource(100, 16, seed=1)
        s2.seek(7)
        np.testing.assert_array_equal(next(iter(s1)), next(iter(s2)))


class TestMemmapSource:
    @pytest.fixture
    def token_file(self, tmp_path):
        tokens = np.arange(16 * 8, dtype=np.uint16)  # 16 rows of 8
        return write_memmap(tokens, str(tmp_path / "toks.bin")), tokens

    def test_epoch_covers_all_rows_permuted(self, token_file):
        path, tokens = token_file
        src = MemmapSource(path, max_context=8, seed=3)
        rows = take(iter(src), 16)
        starts = sorted(int(r[0]) for r in rows)
        assert starts == [i * 8 for i in range(16)]  # every row exactly once
        assert [int(r[0]) for r in rows] != [i * 8 for i in range(16)]  # shuffled

    def test_epochs_differ(self, token_file):
        path, _ = token_file
        src = MemmapSource(path, max_context=8, seed=3)
        e0 = [int(r[0]) for r in take(iter(src), 16)]
        e1 = [int(r[0]) for r in take(iter(src), 16)]
        assert sorted(e0) == sorted(e1) and e0 != e1

    def test_seek_and_state_restore(self, token_file):
        path, _ = token_file
        src = MemmapSource(path, max_context=8, seed=3)
        take(iter(src), 20)  # into epoch 2
        expected = next(iter(src))

        s2 = MemmapSource(path, max_context=8, seed=3)
        s2.seek(20)
        np.testing.assert_array_equal(next(iter(s2)), expected)

        s3 = MemmapSource(path, max_context=8, seed=3)
        s3.restore(src.state())  # src consumed 21 rows now
        take(iter(src), 3)
        take(iter(s3), 3)
        np.testing.assert_array_equal(next(iter(s3)), next(iter(src)))

    def test_no_shuffle_is_sequential(self, token_file):
        path, _ = token_file
        src = MemmapSource(path, max_context=8, shuffle=False)
        rows = take(iter(src), 3)
        assert [int(r[0]) for r in rows] == [0, 8, 16]

    def test_rejects_too_small_file(self, tmp_path):
        p = str(tmp_path / "small.bin")
        np.arange(4, dtype=np.uint16).tofile(p)
        with pytest.raises(ValueError):
            MemmapSource(p, max_context=8)


class TestDataLoader:
    def test_shapes_and_curriculum(self):
        # rows at max_context=64 split into 2 sequences of train_context=32
        src = SyntheticSource(100, 64, seed=0)
        dl = DataLoader(src, batch_size=4, train_context=32, accum_steps=2,
                        process_index=0, process_count=1)
        batch = next(iter(dl))
        assert batch.shape == (2, 4, 32)
        # rows were consumed whole: first row's two halves appear in order
        row0 = next(iter(SyntheticSource(100, 64, seed=0)))
        flat = batch.reshape(-1, 32)
        np.testing.assert_array_equal(flat[0], row0[:32])
        np.testing.assert_array_equal(flat[1], row0[32:])

    def test_process_striping_disjoint_and_complete(self):
        def rows_for(pidx):
            src = SyntheticSource(100, 32, seed=0)
            dl = DataLoader(src, batch_size=4, train_context=32,
                            process_index=pidx, process_count=2)
            return np.concatenate(take(iter(dl), 2)).reshape(-1, 32)

        r0, r1 = rows_for(0), rows_for(1)
        global_rows = [r for r in take(iter(SyntheticSource(100, 32, seed=0)), 8)]
        # process 0 takes even global rows, process 1 odd — together all of them
        np.testing.assert_array_equal(np.concatenate([r0, r1]),
                                      np.stack(global_rows[0::2] + global_rows[1::2]))

    def test_skip_matches_discard(self):
        def fresh():
            return DataLoader(SyntheticSource(100, 32, seed=0), batch_size=4,
                              train_context=32, process_index=0, process_count=1)

        dl1 = fresh()
        it1 = iter(dl1)
        take(it1, 3)
        dl2 = fresh()
        dl2.skip(3)
        np.testing.assert_array_equal(next(it1), next(iter(dl2)))
        assert dl1.steps_consumed == dl2.steps_consumed

    def test_indivisible_batch_raises(self):
        with pytest.raises(ValueError):
            DataLoader(SyntheticSource(100, 64, seed=0), batch_size=3,
                       train_context=32, process_index=0, process_count=2)

    def test_prefetch_matches_sync(self):
        # identical stream with and without the background producer thread
        def batches(prefetch, n=6):
            dl = DataLoader(SyntheticSource(100, 32, seed=0), batch_size=4,
                            train_context=32, process_index=0, process_count=1,
                            prefetch=prefetch)
            return take(iter(dl), n)

        for a, b in zip(batches(0), batches(3)):
            np.testing.assert_array_equal(a, b)

    def test_prefetch_counts_only_yielded_steps(self):
        # steps_consumed must reflect batches YIELDED, not read ahead —
        # otherwise checkpoint resume state would drift by the queue depth
        dl = DataLoader(SyntheticSource(100, 32, seed=0), batch_size=4,
                        train_context=32, process_index=0, process_count=1,
                        prefetch=4)
        it = iter(dl)
        take(it, 3)
        assert dl.steps_consumed == 3

    def test_prefetch_reiteration_loses_no_batches(self):
        # abandoning a prefetching iterator mid-stream (the trainer's chunked
        # train(max_steps=k) pattern) must not skip the read-ahead batches:
        # a fresh iterator serves them before new source reads
        sync = DataLoader(SyntheticSource(100, 32, seed=0), batch_size=4,
                          train_context=32, process_index=0, process_count=1)
        want = take(iter(sync), 8)

        dl = DataLoader(SyntheticSource(100, 32, seed=0), batch_size=4,
                        train_context=32, process_index=0, process_count=1,
                        prefetch=3)
        got = take(iter(dl), 3)          # first iterator reads ahead ~3 more
        got += take(iter(dl), 5)         # second iterator must continue exactly
        for a, b in zip(want, got):
            np.testing.assert_array_equal(a, b)
        assert dl.steps_consumed == 8

    def test_prefetch_propagates_source_error(self):
        class BoomSource(SyntheticSource):
            def __iter__(self):
                yield self._row(0)
                raise RuntimeError("decode failed")

        dl = DataLoader(BoomSource(100, 32, seed=0), batch_size=1,
                        train_context=32, process_index=0, process_count=1,
                        prefetch=2)
        it = iter(dl)
        next(it)
        with pytest.raises(RuntimeError, match="decode failed"):
            next(it)

    def test_device_put_batch_sharded(self, devices):
        mesh = make_mesh(devices=devices)
        sharding = NamedSharding(mesh, P(None, "data", None))
        local = np.zeros((2, 8, 16), np.int32)
        arr = device_put_batch(local, sharding)
        assert arr.shape == (2, 8, 16)
        assert arr.sharding.is_equivalent_to(sharding, 3)


def test_make_loader_from_config():
    cfg = Config(
        model=ModelConfig(vocab_size=100),
        training=TrainingConfig(batch_size=4, train_context=32),
        data=DataConfig(source="synthetic", max_context=32),
    )
    train = make_loader(cfg, process_index=0, process_count=1)
    val = make_loader(cfg, validation=True, process_index=0, process_count=1)
    tb, vb = next(iter(train)), next(iter(val))
    assert tb.shape == (1, 4, 32) and vb.shape == (1, 4, 32)
    assert not np.array_equal(tb, vb)  # different seeds
