"""Model surgery + export CLI + pod check tests.

Counterpart of the reference's (untested) ``src/utils/extend_params.py`` and
``torch_compatability/extract_msgpack.py`` paths, plus the pod health check
(reference ``src/utils/pod_test.py``, manual-only there).
"""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from zero_transformer_tpu.config import ModelConfig
from zero_transformer_tpu.models import Transformer
from zero_transformer_tpu.utils import surgery

CFG = ModelConfig(
    name="t", vocab_size=64, d_model=32, n_heads=4, n_layers=2, max_seq_len=16,
    dropout=0.0, compute_dtype="float32", scan_layers=False,
)


def _params(cfg, seed=0):
    from zero_transformer_tpu.parallel.sharding import unbox

    model = Transformer(cfg)
    boxed = model.init(jax.random.PRNGKey(seed), jnp.zeros((1, 8), jnp.int32))["params"]
    return model, unbox(boxed)  # surgery operates on TrainState params (unboxed)


def test_stack_unstack_round_trip():
    _, params = _params(CFG)
    stacked = surgery.stack_blocks(params)
    assert surgery.is_stacked(stacked)
    assert surgery.num_layers(stacked) == 2
    back = surgery.unstack_blocks(stacked)
    jax.tree.map(np.testing.assert_array_equal, back, params)


def test_stacked_equals_scan_layout():
    """Stacking per-block params must produce the exact tree a scan_layers
    model initializes — the layout-conversion contract."""
    scan_cfg = dataclasses.replace(CFG, scan_layers=True)
    _, scan_params = _params(scan_cfg)
    _, loop_params = _params(CFG)
    stacked = surgery.stack_blocks(loop_params)
    assert jax.tree.structure(stacked) == jax.tree.structure(scan_params)
    for (pa, a), (pb, b) in zip(
        jax.tree_util.tree_flatten_with_path(stacked)[0],
        jax.tree_util.tree_flatten_with_path(scan_params)[0],
    ):
        assert a.shape == b.shape, (pa, a.shape, b.shape)


def test_extend_depth_per_block():
    _, params = _params(CFG)
    ext = surgery.extend_depth(params, 4)
    assert surgery.num_layers(ext) == 4
    # block i -> blocks 2i, 2i+1 (reference mapping, extend_params.py:46-49)
    for i in range(2):
        for j in range(2):
            jax.tree.map(
                np.testing.assert_array_equal,
                ext[f"block_{2 * i + j}"],
                params[f"block_{i}"],
            )
    # non-block params untouched
    jax.tree.map(np.testing.assert_array_equal, ext["wte"], params["wte"])

    # extended params run in the deeper model
    big_cfg = dataclasses.replace(CFG, n_layers=4)
    big = Transformer(big_cfg)
    out = big.apply({"params": ext}, jnp.zeros((1, 8), jnp.int32))
    assert out.shape == (1, 8, CFG.vocab_size)
    assert np.isfinite(np.asarray(out)).all()


def test_extend_depth_stacked():
    _, params = _params(CFG)
    stacked = surgery.stack_blocks(params)
    ext = surgery.extend_depth(stacked, 6)
    assert surgery.is_stacked(ext) and surgery.num_layers(ext) == 6
    # repeat semantics: rows [0,1,2] from donor row 0, rows [3,4,5] from row 1
    leaf = jax.tree.leaves(ext["blocks"])[0]
    donor_leaf = jax.tree.leaves(stacked["blocks"])[0]
    for i in range(2):
        for j in range(3):
            np.testing.assert_array_equal(leaf[3 * i + j], donor_leaf[i])


def test_extend_depth_rejects_non_multiple():
    _, params = _params(CFG)
    with pytest.raises(ValueError):
        surgery.extend_depth(params, 3)


def test_export_cli_round_trip(tmp_path):
    from flax.serialization import msgpack_serialize

    from zero_transformer_tpu.checkpoint import import_params_msgpack
    from zero_transformer_tpu.export import main as export_main

    _, params = _params(CFG)
    src = tmp_path / "donor.msgpack"
    src.write_bytes(msgpack_serialize(jax.tree.map(np.asarray, params)))

    out = tmp_path / "extended.msgpack"
    export_main(["extend", "--params", str(src), "--layers", "4", "--out", str(out)])
    ext = import_params_msgpack(out)
    assert surgery.num_layers(ext) == 4
    jax.tree.map(np.testing.assert_array_equal, ext["block_3"], params["block_1"])


def test_pod_check_healthy(devices):
    from zero_transformer_tpu.utils.pod_check import pod_check

    assert pod_check(timeout=120.0, verbose=False)
