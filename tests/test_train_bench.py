"""BENCH_step.json (scripts/train_step_bench.py) + its regression guard.

Same philosophy as test_serve_bench.py / test_bench_artifact.py: the
committed artifact is the driver-facing evidence for the step-time
decomposition claim (exposed-comm reduction from overlapped ZeRO comm), so
its schema and invariants are pinned here, and the guard's pass / fail /
skip semantics are unit-tested on synthetic artifacts — no jax, no timing,
fast lane.
"""
import copy
import importlib.util
import json
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent

REQUIRED_KEYS = {
    # headline: the exposed-comm reduction and where it came from
    "metric", "value", "unit", "provenance", "platform", "device_kind",
    # the measured A/B (both arms + the compute baseline they subtract)
    "mesh", "zero_stage", "accum", "batch", "seq", "model_dims",
    "overlap_off", "overlap_on", "single_device_compute_ms",
    "measured_reduction", "parity",
    # the assumption-labeled projection (null on TPU where it's measured)
    "projection",
    # kernel-lane MFU projection (ISSUE 11): flash-by-default vs the
    # measured 0.53 baseline, assumption-labeled, targeting >= 0.60
    "mfu_projection",
    # bubble table + attention microbench satellites
    "bubble", "attention_microbench",
    "note", "best_of", "measured_at_utc",
}

ARM_KEYS = {"step_ms", "exposed_comm_ms", "exposed_comm_frac"}


def _guard():
    spec = importlib.util.spec_from_file_location(
        "train_bench_guard", REPO / "scripts" / "train_bench_guard.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def artifact():
    path = REPO / "BENCH_step.json"
    assert path.exists(), "BENCH_step.json must be committed"
    return json.loads(path.read_text())


def test_step_artifact_schema(artifact):
    missing = REQUIRED_KEYS - artifact.keys()
    assert not missing, f"BENCH_step.json missing keys: {sorted(missing)}"
    for arm in ("overlap_off", "overlap_on"):
        assert ARM_KEYS <= artifact[arm].keys(), (arm, artifact[arm])
        assert artifact[arm]["step_ms"] > 0
        assert 0.0 <= artifact[arm]["exposed_comm_frac"] <= 1.0


def test_step_artifact_acceptance(artifact):
    """The ISSUE 8 acceptance claim: exposed-comm fraction reduced >= 2x on
    the measured platform, honest projection where TPU is unreachable —
    and the parity that makes the A/B meaningful is BITWISE."""
    assert artifact["parity"]["bitwise"] is True
    assert artifact["metric"] == "train_step_exposed_comm_reduction"
    assert artifact["provenance"] in ("measured", "projected_v5e")
    assert artifact["value"] >= 2.0, (
        f"exposed-comm reduction {artifact['value']}x < 2x "
        f"({artifact['provenance']})"
    )
    if artifact["provenance"] == "projected_v5e":
        # a projection must carry its inputs so it can be re-derived
        proj = artifact["projection"]
        assert proj["assumptions"].keys() >= {
            "ici_gbps", "peak_flops", "mfu_during_overlap", "bytes_per_param"
        }
        assert proj["serial_exposed_comm_frac"] >= (
            2.0 * proj["overlap_exposed_comm_frac"]
        )


def test_step_artifact_bubble_table(artifact):
    """The artifact's analytic bubble rows must agree with the ONE shared
    formula (pipeline.bubble_fraction) — the bench may never fork it."""
    from zero_transformer_tpu.parallel.pipeline import bubble_fraction

    rows = artifact["bubble"]["analytic"]
    assert rows, "empty bubble table"
    for row in rows:
        expected = bubble_fraction(
            row["pp_schedule"], row["pipe"], row["micro"], row["interleave"]
        )
        assert row["bubble_frac"] == pytest.approx(expected, abs=1e-4), row
    # a measured entry exists per schedule — a timing or the verbatim error
    for sched in ("gpipe", "interleaved"):
        entry = artifact["bubble"]["measured"][sched]
        assert "step_ms" in entry or "error" in entry, entry


def test_step_artifact_attention_points(artifact):
    points = artifact["attention_microbench"]["points"]
    assert points
    for p in points:
        assert p["xla_ms"] > 0
        # flash either ran (with speedup) or says why it could not
        assert ("flash_ms" in p) != ("flash_unsupported_reason" in p), p


def test_step_artifact_interpret_parity(artifact):
    """ISSUE 11: the committed artifact must carry the interpret-mode
    parity block — the Pallas kernels' numerics exercised ON THIS BOX
    (flash train fwd+bwd few-ulp, serving offsets+mask few-ulp, paged
    decode kernel BITWISE vs the gather path), honestly labeled so the
    timed TPU columns and the anywhere-parity evidence can't be
    conflated."""
    parity = artifact["attention_microbench"]["interpret_parity"]
    assert parity["provenance"] == "interpret_mode_parity"
    assert parity["ok"] is True
    names = {c["case"] for c in parity["cases"]}
    assert {"flash_train_fwd_bwd", "flash_serving_offsets_mask",
            "paged_decode_vs_gather"} <= names
    paged = next(c for c in parity["cases"] if c["case"] == "paged_decode_vs_gather")
    assert paged["bitwise"] is True


def test_step_artifact_mfu_projection(artifact):
    """ISSUE 11 acceptance: the assumption-labeled v5e MFU projection for
    flash-by-default must carry its inputs and clear the 0.60 target from
    the measured 0.53 baseline."""
    proj = artifact["mfu_projection"]
    assert proj["assumptions"].keys() >= {
        "n_chips", "tokens_per_step", "peak_flops", "hbm_gbps",
        "score_hbm_passes", "n_params",
    }
    assert 0.5 < proj["baseline_mfu_measured"] < 0.6
    assert proj["projected_mfu"] >= proj["target"] == 0.60
    # the projection must be re-derivable from its own fields
    assert proj["step_s_at_measured_mfu"] > proj["score_traffic_s_per_step"] > 0


# -- guard semantics on synthetic artifacts ----------------------------------


def _base_art():
    return {
        "platform": "cpu", "device_kind": "cpu", "provenance": "projected_v5e",
        "value": 24.0, "parity": {"bitwise": True, "steps": 2},
        "overlap_on": {"step_ms": 100.0},
    }


def test_guard_passes_on_identical():
    ok, msgs = _guard().compare(_base_art(), _base_art())
    assert ok, msgs


def test_guard_fails_on_parity_loss():
    fresh = _base_art()
    fresh["parity"] = {"bitwise": False, "steps": 2}
    ok, msgs = _guard().compare(_base_art(), fresh)
    assert not ok
    assert any("parity" in m for m in msgs)


def test_guard_fails_on_step_time_regression():
    fresh = _base_art()
    fresh["overlap_on"] = {"step_ms": 130.0}  # +30% > 15% tolerance
    ok, msgs = _guard().compare(_base_art(), fresh)
    assert not ok
    assert any("step_ms" in m for m in msgs)


def test_guard_fails_on_reduction_shrink():
    fresh = _base_art()
    fresh["value"] = 10.0  # 24x -> 10x
    ok, msgs = _guard().compare(_base_art(), fresh)
    assert not ok
    assert any("reduction" in m for m in msgs)


def test_guard_fails_on_missing_step_time():
    fresh = _base_art()
    fresh["overlap_on"] = {}
    ok, msgs = _guard().compare(_base_art(), fresh)
    assert not ok
    assert any("did not complete" in m for m in msgs)


def test_guard_skips_on_hardware_mismatch():
    fresh = _base_art()
    fresh["platform"], fresh["device_kind"] = "tpu", "TPU v5e"
    fresh["overlap_on"] = {"step_ms": 900.0}  # would fail if compared
    ok, msgs = _guard().compare(_base_art(), fresh)
    assert ok
    assert any("SKIP" in m for m in msgs)


def test_guard_skips_reduction_on_provenance_change():
    base = _base_art()
    fresh = copy.deepcopy(base)
    fresh["provenance"], fresh["value"] = "measured", 2.5
    ok, msgs = _guard().compare(base, fresh)
    assert ok
    assert any("provenance" in m for m in msgs)
