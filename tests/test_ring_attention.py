"""Ring attention vs the unsharded XLA path, on the 8-device CPU mesh.

Sequence/context parallelism the reference lacks entirely (SURVEY §2
checklist: SP/CP = none). Exactness is the contract: ring attention must
reproduce full attention bit-for-bit-ish (f32 tolerances) for every mesh
layout, including tensor-sharded heads (per-head ALiBi slopes sliced per
shard) and GQA.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from zero_transformer_tpu.config import MeshConfig, ModelConfig
from zero_transformer_tpu.models import Transformer
from zero_transformer_tpu.ops.attention import xla_attention
from zero_transformer_tpu.ops.ring_attention import ring_attention
from zero_transformer_tpu.parallel.mesh import make_mesh


def _qkv(B, T, H, KVH, D, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return (
        jax.random.normal(ks[0], (B, T, H, D)),
        jax.random.normal(ks[1], (B, T, KVH, D)),
        jax.random.normal(ks[2], (B, T, KVH, D)),
    )


@pytest.mark.parametrize(
    "mesh_cfg,H,KVH,alibi",
    [
        (MeshConfig(data=2, sequence=4), 4, 4, False),
        (MeshConfig(data=2, sequence=4), 4, 4, True),
        (MeshConfig(data=1, sequence=8), 4, 2, True),  # GQA
        (MeshConfig(data=2, tensor=2, sequence=2), 4, 4, True),  # TP-sharded heads
        (MeshConfig(data=2, tensor=2, sequence=2), 8, 2, False),  # TP + GQA
    ],
)
def test_ring_matches_full_attention(devices, mesh_cfg, H, KVH, alibi):
    mesh = make_mesh(mesh_cfg)
    B, T, D = 2, 32, 16
    q, k, v = _qkv(B, T, H, KVH, D)
    ref = xla_attention(q, k, v, causal=True, alibi=alibi)
    out = jax.jit(
        lambda q, k, v: ring_attention(q, k, v, mesh, causal=True, alibi=alibi)
    )(q, k, v)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_ring_gradients_match(devices):
    mesh = make_mesh(MeshConfig(data=2, sequence=4))
    B, T, H, D = 1, 32, 4, 16
    q, k, v = _qkv(B, T, H, H, D)
    g = jax.random.normal(jax.random.PRNGKey(7), (B, T, H, D))

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention(q, k, v, mesh, causal=True, alibi=True) * g)

    def loss_ref(q, k, v):
        return jnp.sum(xla_attention(q, k, v, causal=True, alibi=True) * g)

    gr = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    gx = jax.jit(jax.grad(loss_ref, argnums=(0, 1, 2)))(q, k, v)
    for name, a, b in zip("qkv", gr, gx):
        np.testing.assert_allclose(a, b, atol=5e-5, rtol=5e-4, err_msg=f"d{name}")


def test_ring_rejects_indivisible_seq(devices):
    mesh = make_mesh(MeshConfig(data=1, sequence=8))
    q, k, v = _qkv(1, 28, 4, 4, 16)
    with pytest.raises(ValueError):
        ring_attention(q, k, v, mesh)


@pytest.mark.parametrize("position", ["alibi", "rope"])
def test_model_with_sequence_parallel_matches_single(devices, position):
    """Full model forward under a sequence-parallel mesh == unsharded model."""
    cfg = ModelConfig(
        name="t", vocab_size=64, d_model=32, n_heads=4, n_layers=2,
        max_seq_len=32, dropout=0.0, compute_dtype="float32", position=position,
    )
    mesh = make_mesh(MeshConfig(data=2, sequence=4))
    plain = Transformer(cfg)
    ringed = Transformer(cfg, mesh=mesh)
    x = jnp.asarray(
        np.random.default_rng(0).integers(0, 64, (2, 32)), jnp.int32
    )
    params = plain.init(jax.random.PRNGKey(0), x)["params"]
    ref = plain.apply({"params": params}, x, labels=x)[1]
    out = jax.jit(lambda p, x: ringed.apply({"params": p}, x, labels=x)[1])(params, x)
    np.testing.assert_allclose(float(out), float(ref), rtol=1e-5)


def test_ring_with_remat_trains_llama_shapes(devices):
    """Ring attention composed with per-block rematerialization in a full
    ZeRO train step, at llama3-family shapes (GQA + RoPE + RMSNorm + SwiGLU,
    scaled down) on a data=2 x sequence=4 mesh — the configuration an 8k-32k
    context llama3 run would use (remat for HBM, CP for sequence). Guards
    that jax.checkpoint's rematerialized backward traverses the ring
    collectives correctly (loss decreases; grads stay finite)."""
    from zero_transformer_tpu.parallel import make_plan, init_train_state, make_train_step
    from zero_transformer_tpu.training.optimizer import make_optimizer, make_schedule
    from zero_transformer_tpu.config import OptimizerConfig

    cfg = ModelConfig(
        name="llama_ring_t", vocab_size=128, d_model=64, n_heads=4, n_kv_heads=2,
        n_layers=2, max_seq_len=32, dropout=0.0, position="rope", norm="rmsnorm",
        activation="swiglu", tie_embeddings=False, remat=True,
        compute_dtype="bfloat16",
    )
    opt = OptimizerConfig(peak_learning_rate=3e-3, warmup_steps=2, total_steps=40)
    mesh = make_mesh(MeshConfig(data=2, sequence=4))
    model = Transformer(cfg, mesh=mesh)
    tx = make_optimizer(opt)
    plan = make_plan(model, tx, mesh, (4, 32), zero_stage=1)
    state = init_train_state(model, tx, jax.random.PRNGKey(0), mesh, (4, 32), plan)
    step = make_train_step(model, tx, mesh, plan, 1, make_schedule(opt))

    batch = jnp.asarray(
        np.random.default_rng(0).integers(0, 128, (1, 4, 32)), jnp.int32
    )
    losses = []
    rng = jax.random.PRNGKey(1)
    for _ in range(15):
        state, metrics = step(state, batch, rng)
        losses.append(float(metrics["loss"]))
        assert np.isfinite(losses[-1]) and np.isfinite(float(metrics["grad_norm"]))
    assert losses[-1] < losses[0] - 0.5, f"no learning under ring+remat: {losses}"


# -- flash-backed ring (Pallas engine, interpret mode) ------------------------


@pytest.mark.parametrize(
    "mesh_cfg,H,KVH,alibi",
    [
        (MeshConfig(data=2, sequence=4), 4, 4, True),
        (MeshConfig(data=2, sequence=4), 4, 2, False),  # GQA
        (MeshConfig(data=1, tensor=2, sequence=4), 4, 4, True),  # TP slopes
    ],
)
def test_flash_ring_matches_full_attention(devices, mesh_cfg, H, KVH, alibi):
    mesh = make_mesh(mesh_cfg)
    B, T, D = 1, 512, 64
    q, k, v = _qkv(B, T, H, KVH, D)
    ref = xla_attention(q, k, v, causal=True, alibi=alibi)
    out = jax.jit(
        lambda q, k, v: ring_attention(
            q, k, v, mesh, causal=True, alibi=alibi, impl="flash", interpret=True
        )
    )(q, k, v)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize(
    "mesh_cfg,KVH,alibi",
    [
        (MeshConfig(data=2, sequence=4), 4, True),
        (MeshConfig(data=2, sequence=4), 2, False),  # GQA dk/dv group-sum
        (MeshConfig(data=1, tensor=2, sequence=4), 4, True),  # TP slopes in bwd
    ],
)
def test_flash_ring_gradients_match(devices, mesh_cfg, KVH, alibi):
    mesh = make_mesh(mesh_cfg)
    B, T, H, D = 2, 512, 4, 64
    q, k, v = _qkv(B, T, H, KVH, D)
    g = jax.random.normal(jax.random.PRNGKey(7), (B, T, H, D))

    def loss_ring(q, k, v):
        return jnp.sum(
            ring_attention(
                q, k, v, mesh, causal=True, alibi=alibi, impl="flash", interpret=True
            )
            * g
        )

    def loss_ref(q, k, v):
        return jnp.sum(xla_attention(q, k, v, causal=True, alibi=alibi) * g)

    gr = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    gx = jax.jit(jax.grad(loss_ref, argnums=(0, 1, 2)))(q, k, v)
    for name, a, b in zip("qkv", gr, gx):
        np.testing.assert_allclose(a, b, atol=1e-4, rtol=1e-3, err_msg=f"d{name}")


def test_flash_ring_requires_supported_shape(devices):
    mesh = make_mesh(MeshConfig(data=1, sequence=8))
    q, k, v = _qkv(1, 32, 4, 4, 16)  # t_local=4 too small for the kernel
    with pytest.raises(NotImplementedError):
        ring_attention(q, k, v, mesh, impl="flash", interpret=True)


def test_engine_ctx_nested_resolution(devices):
    """_engine_ctx: standalone = full behavior (every mentioned axis manual,
    specs untouched, concrete mesh); in a context whose abstract mesh marks
    axes Manual (the inside of the explicit ZeRO core), those axes are
    dropped from specs/axis set and the ambient ABSTRACT mesh is returned
    (a concrete all-Auto mesh is rejected there). This is the contract that
    lets the CP engines nest inside the explicit ZeRO core (r5)."""
    from jax.sharding import AbstractMesh, AxisType, PartitionSpec as P

    from zero_transformer_tpu.ops.ring_attention import _engine_ctx

    mesh = make_mesh(MeshConfig(data=4, sequence=2))
    qkv = P(("data",), "sequence", None, None)
    ids = P(("data",), "sequence")

    # standalone: unchanged
    mesh_arg, axes, (q2, i2) = _engine_ctx(mesh, (qkv, ids))
    assert mesh_arg is mesh
    assert axes == frozenset({"data", "sequence"})
    assert q2 == qkv and i2 == ids

    # nested: the ambient abstract mesh marks `data` Manual (exactly what
    # get_abstract_mesh() returns inside the core's partial-manual region)
    names = mesh.abstract_mesh.axis_names
    nested = AbstractMesh(
        tuple(mesh.shape[n] for n in names), names,
        axis_types=tuple(
            AxisType.Manual if n == "data" else AxisType.Auto for n in names
        ),
    )
    with jax.sharding.use_abstract_mesh(nested):
        mesh_arg, axes, (q2, i2) = _engine_ctx(mesh, (qkv, ids))
    assert mesh_arg is not mesh and mesh_arg.axis_types == nested.axis_types
    assert axes == frozenset({"sequence"})
    assert q2 == P(None, "sequence", None, None)
    assert i2 == P(None, "sequence")
