"""graftlint: the repo's invariant analyzer + runtime sanitizers.

Four layers under test:

- **static rules** against the fixture corpus (``tests/fixtures/graftlint/``):
  every rule has a minimal true-positive snippet and a clean twin;
- **suppression audit**: a reasoned ``allow`` suppresses and is listed, a
  reasonless one is itself a finding, a stale one is a finding;
- **tree cleanliness** (tier-1): the analyzer over ``zero_transformer_tpu/``
  and ``scripts/`` must report zero unsuppressed findings — regressions of
  any hard-won invariant fail the suite here;
- **spec checker + compile-family sanitizer**: hand-seeded bad
  ``ShardingPlan`` rejected with precise messages; labeled dispatch sites
  trip on signature-family overflow and stay within bounds over a real
  serving run.

The static-rule tests load ``analysis/static_rules.py`` directly by file
path — the lint lane must work (and stay fast) with no jax import.
"""
import ast
import importlib.util
import sys
import warnings
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
FIXTURES = Path(__file__).parent / "fixtures" / "graftlint"


def _load_static_rules():
    path = REPO / "zero_transformer_tpu" / "analysis" / "static_rules.py"
    spec = importlib.util.spec_from_file_location("graftlint_static_t", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return mod


SR = _load_static_rules()

# (rule, fixture stem): each *_bad.py must trigger exactly this rule and
# nothing else; each *_ok.py must be perfectly clean
RULE_FIXTURES = [
    ("donation-safety", "donation_safety"),
    ("host-sync-in-hot-path", "host_sync"),
    ("wall-clock-in-span-path", "wall_clock"),
    ("broad-except-in-supervised-seam", "broad_except"),
    ("lock-held-device-sync", "lock_sync"),
    ("sharding-spec", "sharding_spec"),
]


# ------------------------------------------------------------ rule fixtures


@pytest.mark.parametrize("rule,stem", RULE_FIXTURES)
def test_rule_true_positive(rule, stem):
    findings = SR.analyze_file(FIXTURES / f"{stem}_bad.py")
    assert findings, f"{stem}_bad.py must trigger {rule}"
    assert {f.rule for f in findings} == {rule}
    assert all(not f.suppressed for f in findings)


@pytest.mark.parametrize("rule,stem", RULE_FIXTURES)
def test_rule_true_negative(rule, stem):
    findings = SR.analyze_file(FIXTURES / f"{stem}_ok.py")
    assert findings == [], [f.format() for f in findings]


def test_duplicate_axis_in_partition_spec_flagged():
    src = (
        "from jax.sharding import PartitionSpec as P\n"
        'SPEC = P("data", "data")\n'
    )
    msgs = [f.message for f in SR.analyze_source(src)]
    assert any("twice" in m for m in msgs), msgs


def test_local_probe_mesh_axes_are_legal():
    """A module constructing its own Mesh may use those axis names (the
    pod_check 1-D probe-mesh pattern) without tripping sharding-spec."""
    src = (
        "from jax.sharding import Mesh, PartitionSpec as P\n"
        "def probe(devices):\n"
        '    mesh = Mesh(devices, ("all",))\n'
        '    return mesh, P("all")\n'
    )
    assert SR.analyze_source(src) == []


def test_donation_safety_flags_unsealed_return():
    """A function handing restored/device_put buffers to its CALLERS is
    flagged too — the donation may happen a module away."""
    src = (
        "import jax\n"
        "def load(params, shardings):\n"
        "    return jax.device_put(params, shardings)\n"
    )
    findings = SR.analyze_source(src)
    assert [f.rule for f in findings] == ["donation-safety"]


def test_donation_safety_reassignment_clears_taint():
    """Statement order matters: sealing the SAME name must clear it."""
    src = (
        "import jax\n"
        "from zero_transformer_tpu.utils.jax_compat import ensure_donatable\n"
        "def load(params, shardings):\n"
        "    params = jax.device_put(params, shardings)\n"
        "    params = ensure_donatable(params)\n"
        "    return params\n"
    )
    assert SR.analyze_source(src) == []


# ------------------------------------- control-plane except rule (path-scoped)
# No flat fixture pair for this rule: it fires only when the module PATH is
# in a control-plane location, so the fixtures are inline sources analyzed
# under explicit in-scope / out-of-scope paths.

IN_SCOPE = "zero_transformer_tpu/training/fleet.py"


def test_control_plane_bare_except_flagged():
    src = (
        "def sweep(self):\n"
        "    try:\n"
        "        self._relayout()\n"
        "    except:\n"
        "        pass\n"
    )
    findings = SR.analyze_source(src, path=IN_SCOPE)
    assert [f.rule for f in findings] == ["swallowed-except-in-control-plane"]
    assert "bare 'except:'" in findings[0].message


@pytest.mark.parametrize("exc", ["Exception", "BaseException"])
@pytest.mark.parametrize("body", ["pass", "continue", "..."])
def test_control_plane_swallow_only_broad_except_flagged(exc, body):
    src = (
        "def hb_loop(self):\n"
        "    while True:\n"
        "        try:\n"
        "            self.post()\n"
        f"        except {exc}:\n"
        f"            {body}\n"
    )
    findings = SR.analyze_source(src, path=IN_SCOPE)
    assert [f.rule for f in findings] == ["swallowed-except-in-control-plane"]
    assert "swallows the failure" in findings[0].message


def test_control_plane_observing_broad_except_clean():
    """Control loops legitimately outlive individual failures — a broad
    except that LOGS (or otherwise acts) is the sanctioned shape."""
    src = (
        "def hb_loop(self):\n"
        "    try:\n"
        "        self.post()\n"
        "    except Exception:\n"
        "        log.exception('heartbeat post failed; retrying')\n"
    )
    assert SR.analyze_source(src, path=IN_SCOPE) == []


def test_control_plane_narrow_except_pass_clean():
    """Swallowing a NAMED exception is a deliberate, reviewable choice —
    only the catch-everything shapes are flagged."""
    src = (
        "def poll(self):\n"
        "    try:\n"
        "        self.q.get_nowait()\n"
        "    except KeyError:\n"
        "        pass\n"
    )
    assert SR.analyze_source(src, path=IN_SCOPE) == []


@pytest.mark.parametrize(
    "path",
    [
        "zero_transformer_tpu/resilience/supervisor.py",
        "zero_transformer_tpu/training/fleet.py",
        "zero_transformer_tpu/serving/router.py",
        "scripts/train_coordinator.py",
        "scripts/train_fleet_worker.py",
        "scripts/serve_router.py",
    ],
)
def test_control_plane_scope_covers_all_declared_paths(path):
    src = "try:\n    go()\nexcept:\n    pass\n"
    findings = SR.analyze_source(src, path=path)
    assert [f.rule for f in findings] == ["swallowed-except-in-control-plane"]


def test_control_plane_rule_ignores_out_of_scope_paths():
    """Data-plane / model code is governed by the opt-in supervised-seam
    rule, not this one — the same source outside the scope list is clean."""
    src = "try:\n    go()\nexcept Exception:\n    pass\n"
    for path in (
        "zero_transformer_tpu/model/attention.py",
        "zero_transformer_tpu/training/loop.py",
        "tests/test_fleet_train.py",
    ):
        assert SR.analyze_source(src, path=path) == [], path


def test_control_plane_suppressible_with_reason():
    src = (
        "def drain(self):\n"
        "    try:\n"
        "        self.sock.close()\n"
        "    # graftlint: allow[swallowed-except-in-control-plane] reason=best-effort close on teardown\n"
        "    except Exception:\n"
        "        pass\n"
    )
    (f,) = SR.analyze_source(src, path=IN_SCOPE)
    assert f.suppressed and f.reason == "best-effort close on teardown"


# ------------------------------------------------------- suppression audit


def test_suppression_with_reason_silences_and_is_audited():
    src = (
        "import time\n"
        "def stamp():\n"
        "    # graftlint: allow[wall-clock-in-span-path] reason=unix stamp for humans\n"
        "    return time.time()\n"
    )
    (f,) = SR.analyze_source(src)
    assert f.suppressed and f.reason == "unix stamp for humans"


def test_suppression_without_reason_is_itself_a_finding():
    src = (
        "import time\n"
        "def stamp():\n"
        "    # graftlint: allow[wall-clock-in-span-path]\n"
        "    return time.time()\n"
    )
    rules = sorted(f.rule for f in SR.analyze_source(src))
    # the original finding stays ACTIVE and the naked allow is flagged
    assert rules == ["suppression-missing-reason", "wall-clock-in-span-path"]
    assert all(not f.suppressed for f in SR.analyze_source(src))


def test_stale_suppression_is_a_finding():
    src = (
        "def f():\n"
        "    # graftlint: allow[wall-clock-in-span-path] reason=nothing here anymore\n"
        "    return 1\n"
    )
    (f,) = SR.analyze_source(src)
    assert f.rule == "unused-suppression"
    assert "matched no finding" in f.message


def test_unknown_rule_in_allow_is_a_finding():
    src = (
        "def f():\n"
        "    # graftlint: allow[no-such-rule] reason=typo\n"
        "    return 1\n"
    )
    (f,) = SR.analyze_source(src)
    assert f.rule == "unused-suppression"
    assert "unknown rule" in f.message


def test_single_rule_run_does_not_stale_other_allows():
    """--rule invocations must not call another rule's allow stale."""
    src = (
        "import time\n"
        "def stamp():\n"
        "    # graftlint: allow[wall-clock-in-span-path] reason=unix stamp\n"
        "    return time.time()\n"
    )
    findings = SR.analyze_source(src, rules=["donation-safety"])
    assert findings == []


# ------------------------------------------------------- whole-tree lane


def test_tree_is_clean():
    """Tier-1 gate: zero unsuppressed findings over the whole tree. A
    failure here means a PR reintroduced one of the invariants each rule
    encodes — fix it or suppress WITH a reason that survives review."""
    paths = [
        REPO / "zero_transformer_tpu",
        REPO / "scripts",
        REPO / "train.py",
        REPO / "bench.py",
    ]
    axes = SR.refresh_mesh_axes(REPO)
    findings = SR.analyze_paths(
        [p for p in paths if p.exists()], mesh_axes=axes
    )
    active = [f for f in findings if not f.suppressed]
    assert not active, "\n".join(f.format() for f in active)


def test_mesh_axes_derive_from_mesh_py():
    """The CLI re-derives the axis universe from parallel/mesh.py's
    ``*_AXIS`` constants; the built-in fallback must agree so a renamed
    axis cannot silently stale the linter."""
    assert SR.refresh_mesh_axes(REPO) == SR.MESH_AXES


def test_checkpoint_restores_are_sealed():
    """Pin for ``static_rules._TAINT_LAST`` treating CheckpointManager
    restores as CLEAN sources: every restore entry point must seal its
    product through ``ensure_donatable`` before returning. If this fails,
    either re-seal checkpoint.py or move the method names back into the
    taint set."""
    tree = ast.parse(
        (REPO / "zero_transformer_tpu" / "checkpoint.py").read_text()
    )
    cm = next(
        n
        for n in tree.body
        if isinstance(n, ast.ClassDef) and n.name == "CheckpointManager"
    )
    for name in ("restore", "restore_verified", "restore_params"):
        fn = next(
            n
            for n in ast.walk(cm)
            if isinstance(n, ast.FunctionDef) and n.name == name
        )
        sealed = any(
            isinstance(call, ast.Call)
            and (
                getattr(call.func, "id", None) == "ensure_donatable"
                or getattr(call.func, "attr", None) == "ensure_donatable"
            )
            for ret in ast.walk(fn)
            if isinstance(ret, ast.Return) and ret.value is not None
            for call in ast.walk(ret.value)
        )
        assert sealed, (
            f"CheckpointManager.{name} no longer seals its product through "
            "ensure_donatable — donation-safety's taint exclusions are stale"
        )


# ------------------------------------------------------------ spec checker


def _mesh_2dev():
    import jax
    import numpy as np
    from jax.sharding import Mesh

    return Mesh(np.array(jax.devices()[:2]), ("data",))


def test_spec_checker_rejects_hand_seeded_bad_plan():
    """Acceptance case: unknown axis + indivisible ZeRO dim, one SpecError,
    both inconsistencies named precisely."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from zero_transformer_tpu.analysis import spec_check
    from zero_transformer_tpu.parallel.zero import ShardingPlan, TrainState

    mesh = _mesh_2dev()
    repl = NamedSharding(mesh, P())
    state = TrainState(
        step=repl,
        params={
            # raw PartitionSpec leaf: NamedSharding's own constructor
            # rejects unknown axes, but a spec table/config file can
            # carry one all the way to plan time — exactly what the
            # checker must catch before compile
            "w": P("bogus"),
            "v": NamedSharding(mesh, P("data")),
        },
        opt_state={},
    )
    abstract = TrainState(
        step=jax.ShapeDtypeStruct((), jnp.int32),
        params={
            "w": jax.ShapeDtypeStruct((4, 4), jnp.float32),
            # 3 is not divisible by data=2: the hand-seeded ragged shard
            "v": jax.ShapeDtypeStruct((3,), jnp.float32),
        },
        opt_state={},
    )
    plan = ShardingPlan(state=state, batch=repl, zero={}, logical=None)
    with pytest.raises(spec_check.SpecError) as ei:
        spec_check.check_plan(plan, mesh, abstract_state=abstract)
    msg = str(ei.value)
    assert "'bogus'" in msg and "not a mesh axis" in msg
    assert "not divisible" in msg and "size 3" in msg
    assert len(ei.value.errors) == 2


def test_spec_checker_flags_duplicate_axis():
    from jax.sharding import PartitionSpec as P

    from zero_transformer_tpu.analysis import spec_check

    errors = spec_check.check_entry_spec(
        P("data", "data"), _mesh_2dev(), "w"
    )
    assert len(errors) == 1 and "at most one dim" in errors[0]


def test_spec_checker_passes_good_plan():
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from zero_transformer_tpu.analysis import spec_check
    from zero_transformer_tpu.parallel.zero import ShardingPlan, TrainState

    mesh = _mesh_2dev()
    repl = NamedSharding(mesh, P())
    state = TrainState(
        step=repl,
        params={"w": NamedSharding(mesh, P("data"))},
        opt_state={},
    )
    abstract = TrainState(
        step=jax.ShapeDtypeStruct((), jnp.int32),
        params={"w": jax.ShapeDtypeStruct((4, 4), jnp.float32)},
        opt_state={},
    )
    plan = ShardingPlan(state=state, batch=repl, zero={}, logical=None)
    spec_check.check_plan(plan, mesh, abstract_state=abstract)  # no raise


def test_spec_checker_allow_uneven_scopes_divisibility():
    """The pipe axis may shard the stacked layer dim unevenly (GSPMD pads;
    the pipeline engine owns the "divisible" refusal) — ``allow_uneven``
    exempts exactly that axis while unknown/duplicate axes stay hard
    errors. Pins the make_plan contract test_pp_rejects_zero3_and_
    indivisible relies on: plan builds, make_train_step refuses."""
    from jax.sharding import PartitionSpec as P

    from zero_transformer_tpu.analysis import spec_check

    mesh = _mesh_2dev()
    ragged = spec_check.check_entry_spec(
        P("data"), mesh, "blocks", shape=(3, 8)
    )
    assert len(ragged) == 1 and "not divisible" in ragged[0]
    assert (
        spec_check.check_entry_spec(
            P("data"), mesh, "blocks", shape=(3, 8), allow_uneven=("data",)
        )
        == []
    )
    # the exemption is about raggedness ONLY: a bogus axis still fails
    assert spec_check.check_entry_spec(
        P("bogus"), mesh, "blocks", shape=(3, 8), allow_uneven=("bogus",)
    )


def test_spec_checker_mixed_axis_dim_stays_strict():
    """A dim sharded by an allowed-uneven axis AND a strict (ZeRO) axis is
    still checked at the full world: _add_zero_axis only adds the ZeRO
    axis when the whole product divides, so raggedness on a mixed dim
    means a hand-seeded or corrupted spec."""
    import jax
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    from zero_transformer_tpu.analysis import spec_check

    mesh = Mesh(
        np.array(jax.devices()[:4]).reshape(2, 2), ("tensor", "fsdp")
    )
    ragged = spec_check.check_entry_spec(
        P(("tensor", "fsdp")), mesh, "w", shape=(6,),
        allow_uneven=("tensor",),
    )
    assert len(ragged) == 1 and "not divisible" in ragged[0]
    # all axes allowed-uneven: exempt
    assert (
        spec_check.check_entry_spec(
            P(("tensor", "fsdp")), mesh, "w", shape=(6,),
            allow_uneven=("tensor", "fsdp"),
        )
        == []
    )


def test_make_plan_is_spec_checked(tmp_path):
    """make_plan routes every derived plan through check_plan — a poisoned
    rule table must fail at plan time with the precise message, not at
    first pjit dispatch."""
    import jax

    from zero_transformer_tpu.parallel import sharding as shd

    with pytest.raises(ValueError, match="unknown mesh axes"):
        shd.validate_rules({**shd.LOGICAL_RULES, "mlp": "tensorr"})


# ----------------------------------------------- compile-family sanitizer


class _Arr:
    """Duck-typed array stand-in: the sanitizer reads only shape/dtype."""

    def __init__(self, shape, dtype="float32", fill=0):
        self.shape = tuple(shape)
        self.dtype = dtype
        self.fill = fill  # value must NOT enter the signature


@pytest.fixture
def strict_sites():
    from zero_transformer_tpu.analysis import runtime as rt

    rt.set_strict(True)
    yield rt
    rt.set_strict(None)


def test_dispatch_site_trips_listing_offending_signatures(strict_sites):
    rt = strict_sites
    site = rt.bounded_dispatch("test.vary_shape", 1)
    site.observe(_Arr((2, 3)))
    site.observe(_Arr((2, 3), fill=7))  # same signature: values never count
    assert site.distinct == 1
    with pytest.raises(rt.CompileFamilyExceeded) as ei:
        site.observe(_Arr((2, 4)))  # the deliberately varied shape
    msg = str(ei.value)
    assert "test.vary_shape" in msg
    assert "(2, 3)" in msg and "(2, 4)" in msg  # every signature listed
    assert "NEW" in msg  # the fresh offender is marked


def test_dispatch_site_sees_through_dataclass_containers(strict_sites):
    """flax.struct-style dataclasses (TrainState) must be walked by field
    — collapsing them to their type would blind trainer.step to the very
    shapes that select the executable."""
    import dataclasses as dc

    rt = strict_sites

    @dc.dataclass
    class State:
        step: "_Arr"
        params: dict

    site = rt.bounded_dispatch("test.dataclass", 1)
    site.observe(State(_Arr(()), {"w": _Arr((4, 4))}))
    with pytest.raises(rt.CompileFamilyExceeded):
        site.observe(State(_Arr(()), {"w": _Arr((4, 8))}))


def test_dispatch_site_kwarg_values_enter_signature(strict_sites):
    """sorted(kwargs) would record key NAMES only — a per-call shape
    variation through a keyword argument must still trip the bound."""
    rt = strict_sites
    site = rt.bounded_dispatch("test.kwargs", 1)
    site.observe(x=_Arr((128,)))
    site.observe(x=_Arr((128,), fill=3))  # same signature
    with pytest.raises(rt.CompileFamilyExceeded):
        site.observe(x=_Arr((256,)))


def test_cli_rejects_unknown_rule_names():
    """A typo'd --rule must not run zero rules and exit 0 'clean'."""
    cli_path = REPO / "scripts" / "graftlint.py"
    spec = importlib.util.spec_from_file_location("graftlint_cli_t", cli_path)
    cli = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = cli
    spec.loader.exec_module(cli)
    assert cli.main(["--rule", "donation_safety"]) == 2  # underscore typo
    assert (
        cli.main(["--rule", "wall-clock-in-span-path", "zero_transformer_tpu/obs"])
        == 0
    )


def test_dispatch_site_statics_select_executables(strict_sites):
    rt = strict_sites
    site = rt.bounded_dispatch("test.vary_static", 1)
    site.observe(_Arr((2, 3)), 16)
    with pytest.raises(rt.CompileFamilyExceeded):
        site.observe(_Arr((2, 3)), 32)  # static arg value varies the family


def test_dispatch_site_warns_once_outside_strict():
    from zero_transformer_tpu.analysis import runtime as rt

    rt.set_strict(False)
    try:
        site = rt.bounded_dispatch("test.warn", 1)
        site.observe(_Arr((1,)))
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            site.observe(_Arr((2,)))
            site.observe(_Arr((3,)))
        assert len(w) == 1  # warned once, not per overflow
        assert site.violations == 2  # every overflow still counted
    finally:
        rt.set_strict(None)


def test_dispatch_site_wrap_instruments_callable(strict_sites):
    rt = strict_sites
    site = rt.bounded_dispatch("test.wrap", 1)
    fn = site.wrap(lambda x: x.shape)
    assert fn(_Arr((4, 4))) == (4, 4)
    with pytest.raises(rt.CompileFamilyExceeded):
        fn(_Arr((4, 5)))


def test_engine_dispatch_sites_stay_within_bounds(strict_sites):
    """Serving parity run under strict sanitizers: chunked prefill +
    decode over interleaved admissions must keep every instrumented site
    at ONE signature — the fixed-shape discipline, machine-checked."""
    import jax
    import jax.numpy as jnp

    from zero_transformer_tpu.config import model_config
    from zero_transformer_tpu.inference.sampling import SamplingConfig
    from zero_transformer_tpu.models import Transformer
    from zero_transformer_tpu.serving import ServingEngine

    cfg = model_config("test", dropout=0.0, compute_dtype="float32")
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))[
        "params"
    ]
    engine = ServingEngine(
        cfg,
        params,
        n_slots=2,
        cache_len=32,
        prefill_chunk=8,
        sampling=SamplingConfig(temperature=0.9, top_k=20),
    )
    first = [
        engine.submit([3, 7, 11], max_new_tokens=6, seed=0),
        engine.submit([5, 9], max_new_tokens=6, seed=1),
    ]
    for _ in range(3):
        engine.step()
    late = [engine.submit([2, 4, 6, 8], max_new_tokens=6, seed=2)]
    engine.run_until_idle()
    for h in first + late:
        assert h.status == "done"
    sites = {
        s.name: s.snapshot()
        for s in (engine._ds_decode, engine._ds_prefill, engine._ds_spec)
    }
    # a strict-mode trip would have raised mid-run; assert the positive too
    for name, snap in sites.items():
        assert snap["violations"] == 0, (name, snap)
        assert snap["distinct"] <= snap["max_entries"], (name, snap)
    assert sites["engine.decode_step"]["calls"] > 0
    assert sites["engine.decode_step"]["distinct"] == 1
    assert sites["engine.prefill_chunk"]["distinct"] == 1
    # a strict trip must ESCAPE the engine's supervised tick handler (not
    # be classified as a tick fault and fed to the breaker): reset the
    # decode site and poison it with a foreign signature so the next real
    # tick's (now-fresh) signature overflows the bound
    engine._ds_decode.reset()
    engine._ds_decode.signatures[("poison",)] = 1
    engine.submit([1, 2], max_new_tokens=2, seed=3)
    with pytest.raises(strict_sites.CompileFamilyExceeded):
        engine.run_until_idle()
    assert not engine._breaker.open
