"""Weight-only int8 serving (models/quant.py; `serve --quantize int8`).

The quantized model must compute (x @ q) * s where the full model with
dequantized weights computes x @ (q * s) — identical up to float
associativity — and every quantized leaf must be an int8 tensor so the
claimed HBM halving is real, not cosmetic.
"""
import dataclasses

import numpy as np
import pytest

import flax.linen as nn
import jax
import jax.numpy as jnp

from zero_transformer_tpu.config import ModelConfig, model_config
from zero_transformer_tpu.models.gpt import Transformer
from zero_transformer_tpu.models.quant import quantize_array, quantize_params

CFG = model_config("test", dropout=0.0, compute_dtype="float32",
                   param_dtype="float32")


def _dequantized(params_q, params_ref):
    """Rebuild full-precision params from the quantized tree: q * scale with
    the reference tree's structure (for the exactness cross-check)."""

    def walk(qt, rt):
        out = {}
        for k, v in rt.items():
            if isinstance(v, dict):
                out[k] = walk(qt[k], v)
            elif k == "embedding" and "embedding_q" in qt:
                out[k] = (
                    qt["embedding_q"].astype(np.float32)
                    * np.expand_dims(qt["scale"], -1)
                )
            elif k == "kernel" and "kernel_q" in qt:
                out[k] = (
                    qt["kernel_q"].astype(np.float32)
                    * np.expand_dims(qt["scale"], -2)
                )
            elif f"{k}_q" in qt:  # MoE expert weights (wi/wo/gate)
                scale = qt[f"{k}_scale"]
                out[k] = (
                    qt[f"{k}_q"].astype(np.float32)
                    * np.expand_dims(scale, -2)
                )
            else:
                out[k] = v
        return out

    return walk(params_q, params_ref)


def test_quantize_array_error_bound():
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.normal(size=(16, 32)).astype(np.float32))
    q, scale = quantize_array(w, axis=-2)
    assert q.dtype == jnp.int8 and scale.shape == (32,)
    err = np.abs(np.asarray(w) - np.asarray(q, np.float32) * np.asarray(scale))
    # round-to-nearest: error <= scale/2 per element, columnwise
    assert (err <= np.asarray(scale) / 2 + 1e-7).all()


@pytest.mark.parametrize("tie", [True, False])
def test_quant_forward_matches_dequantized_full(tie):
    cfg = dataclasses.replace(CFG, tie_embeddings=tie)
    qcfg = dataclasses.replace(cfg, param_quant="int8")
    x = jnp.asarray([[1, 5, 9, 2, 7, 3, 4, 8]], jnp.int32)
    params = nn.meta.unbox(Transformer(cfg).init(jax.random.PRNGKey(0), x)["params"])
    params_q = quantize_params(jax.tree.map(np.asarray, params))
    # structure must match what the quant model expects
    expect = nn.meta.unbox(jax.eval_shape(
        lambda: Transformer(qcfg).init(jax.random.PRNGKey(0), x)
    )["params"])
    assert jax.tree.structure(jax.tree.map(lambda l: 0, params_q)) == \
        jax.tree.structure(jax.tree.map(lambda l: 0, expect))
    for lq, le in zip(jax.tree.leaves(params_q), jax.tree.leaves(expect)):
        assert lq.shape == le.shape and lq.dtype == le.dtype, (lq.shape, le.shape, lq.dtype, le.dtype)

    out_q = Transformer(qcfg).apply({"params": params_q}, x)
    full = _dequantized(params_q, params)
    out_f = Transformer(cfg).apply({"params": full}, x)
    np.testing.assert_allclose(
        np.asarray(out_q), np.asarray(out_f), rtol=2e-4, atol=2e-4
    )


def test_quant_decode_generates():
    from zero_transformer_tpu.inference.generate import decode_model, generate
    from zero_transformer_tpu.inference.sampling import SamplingConfig

    cfg = dataclasses.replace(CFG, param_quant="int8")
    x = jnp.asarray([[1, 5, 9, 2]], jnp.int32)
    model = decode_model(cfg, cache_len=12)
    params = model.init(jax.random.PRNGKey(0), x)["params"]
    out = generate(model, params, x, 6, jax.random.PRNGKey(1),
                   SamplingConfig(greedy=True))
    out = np.asarray(out)
    assert out.shape == (1, 6)
    assert ((out >= 0) & (out < cfg.vocab_size)).all()


def test_quant_tree_is_half_the_bytes():
    x = jnp.asarray([[1, 2, 3, 4]], jnp.int32)
    params = nn.meta.unbox(Transformer(CFG).init(jax.random.PRNGKey(0), x)["params"])
    params_q = quantize_params(jax.tree.map(np.asarray, params))

    def nbytes(tree):
        return sum(l.size * l.dtype.itemsize for l in
                   map(np.asarray, jax.tree.leaves(tree)))

    # f32 source -> int8 + scales: ~0.25x (+ norm params untouched); the
    # bf16-serving ratio is 0.5x by the same leaf accounting
    assert nbytes(params_q) < 0.30 * nbytes(params)


def test_quant_moe_forward_matches_dequantized_full():
    """MoE expert tensors quantize too: per-(expert, out-channel) scales
    applied after each expert einsum must reproduce the dequantized-full
    model (same associativity argument as QuantDense)."""
    cfg = dataclasses.replace(
        CFG, n_experts=2, moe_top_k=1, activation="swiglu",
    )
    qcfg = dataclasses.replace(cfg, param_quant="int8")
    x = jnp.asarray([[1, 5, 9, 2, 7, 3, 4, 8]], jnp.int32)
    params = nn.meta.unbox(Transformer(cfg).init(jax.random.PRNGKey(0), x)["params"])
    params_q = quantize_params(jax.tree.map(np.asarray, params))
    expect = nn.meta.unbox(jax.eval_shape(
        lambda: Transformer(qcfg).init(jax.random.PRNGKey(0), x)
    )["params"])
    assert jax.tree.structure(jax.tree.map(lambda l: 0, params_q)) == \
        jax.tree.structure(jax.tree.map(lambda l: 0, expect))

    out_q = Transformer(qcfg).apply({"params": params_q}, x)
    out_f = Transformer(cfg).apply({"params": _dequantized(params_q, params)}, x)
    np.testing.assert_allclose(
        np.asarray(out_q), np.asarray(out_f), rtol=2e-4, atol=2e-4
    )


def test_quant_rejections():
    with pytest.raises(ValueError, match="param_quant"):
        ModelConfig(param_quant="int4")
    # loss paths are full-precision only
    qcfg = dataclasses.replace(CFG, param_quant="int8")
    x = jnp.asarray([[1, 2, 3, 4]], jnp.int32)
    with pytest.raises(NotImplementedError, match="inference"):
        Transformer(qcfg).init(jax.random.PRNGKey(0), x, x)
    # and the trainer refuses to build
    from zero_transformer_tpu.config import Config
    from zero_transformer_tpu.training.trainer import build_training

    with pytest.raises(ValueError, match="inference-only"):
        build_training(Config(model=qcfg))


def test_quant_tp2_decode_matches_single_device(devices):
    """Quantized serving composes with tensor parallelism: QuantDense /
    QuantEmbed carry the same logical axes as their bf16 twins, so
    shard_for_inference distributes the int8 leaves and TP=2 greedy decode
    must reproduce the single-device tokens exactly."""
    from zero_transformer_tpu.inference.generate import (
        decode_model,
        generate,
        serve_mesh,
        shard_for_inference,
    )
    from zero_transformer_tpu.inference.sampling import SamplingConfig

    cfg = dataclasses.replace(CFG, param_quant="int8")
    model = decode_model(cfg, 24)
    prompt = jnp.asarray(
        np.random.default_rng(3).integers(0, cfg.vocab_size, (2, 8)), jnp.int32
    )
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((2, 8), jnp.int32))["params"]
    greedy = SamplingConfig(greedy=True)
    out_single = generate(model, params, prompt, 8, jax.random.PRNGKey(1), greedy)

    mesh = serve_mesh(2)
    sharded = shard_for_inference(model, params, mesh)
    n_int8_sharded = sum(
        1 for l in jax.tree.leaves(sharded)
        if l.dtype == jnp.int8 and not l.sharding.is_fully_replicated
    )
    assert n_int8_sharded > 0, "no int8 kernel was tensor-sharded"
    out_tp = generate(model, sharded, prompt, 8, jax.random.PRNGKey(1), greedy,
                      mesh=mesh)
    np.testing.assert_array_equal(np.asarray(out_single), np.asarray(out_tp))


def test_quant_speculative_composes():
    """Prompt-lookup speculation runs the quant model unchanged (it only
    calls apply): greedy spec output must equal the quant plain loop's."""
    from zero_transformer_tpu.inference.generate import decode_model, generate
    from zero_transformer_tpu.inference.sampling import SamplingConfig
    from zero_transformer_tpu.inference.speculative import generate_speculative

    cfg = dataclasses.replace(CFG, param_quant="int8")
    piece = jnp.asarray([[1, 5, 9, 2] * 4], jnp.int32)  # periodic prompt
    model = decode_model(cfg, piece.shape[1] + 8 + 4)
    params = model.init(jax.random.PRNGKey(0), piece[:, :4])["params"]
    plain = generate(model, params, piece, 8, jax.random.PRNGKey(1),
                     SamplingConfig(greedy=True))
    spec = generate_speculative(model, params, piece, 8, draft_len=4)
    np.testing.assert_array_equal(np.asarray(plain), np.asarray(spec))


def test_export_quantize_cli_roundtrip(tmp_path):
    """`export quantize` writes a serving msgpack; quantize_params is
    idempotent on it (kernel_q/scale leaves match no conversion rule), so
    serve --quantize accepts both raw and pre-quantized artifacts."""
    from zero_transformer_tpu.checkpoint import (
        export_params_msgpack,
        import_params_msgpack,
    )
    from zero_transformer_tpu.export import main as export_main

    x = jnp.asarray([[1, 2, 3, 4]], jnp.int32)
    params = nn.meta.unbox(Transformer(CFG).init(jax.random.PRNGKey(0), x)["params"])
    src = tmp_path / "p.msgpack"
    dst = tmp_path / "q.msgpack"
    export_params_msgpack(jax.tree.map(np.asarray, params), src)
    export_main(["quantize", "--params", str(src), "--out", str(dst)])
    assert dst.stat().st_size < 0.35 * src.stat().st_size  # f32 -> int8+scales
    q = import_params_msgpack(dst)
    q2 = quantize_params(q)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        q, q2,
    )


def test_quant_llama8b_fits_one_v5e_chip():
    """The headline claim behind `serve --quantize int8`, as a test:
    llama3-8B's quantized serving footprint — int8 params + f32 scales +
    a bf16 4k-context KV cache — fits a 16 GB v5e chip with margin.
    Abstract shapes only (eval_shape); nothing materializes."""
    from zero_transformer_tpu.inference.generate import decode_model

    cfg = model_config(
        "llama3_8b", dropout=0.0, param_dtype="bfloat16",
        compute_dtype="bfloat16", param_quant="int8", kv_cache_dtype="int8",
    )
    B, cache_len = 1, 4096
    model = decode_model(cfg, cache_len)
    shapes = nn.meta.unbox(jax.eval_shape(
        lambda r: model.init(r, jnp.zeros((B, 8), jnp.int32)),
        jax.random.PRNGKey(0),
    ))

    def nbytes(tree):
        return sum(
            int(np.prod(l.shape)) * jnp.dtype(l.dtype).itemsize
            for l in jax.tree.leaves(tree)
        )

    param_b = nbytes(shapes["params"])
    cache_b = nbytes(shapes["cache"])
    total = param_b + cache_b
    # ~8B params -> ~8 GB int8 (+ scales); int8 KV at 4k ctx is small
    assert 7.5e9 < param_b < 9.5e9, param_b
    assert total < 12e9, (param_b, cache_b)  # 16 GB HBM minus headroom
    # and the bf16 UNquantized model provably does NOT fit — the contrast
    # that makes --quantize the enabling lever, not an optimization
    full = decode_model(
        model_config("llama3_8b", dropout=0.0, param_dtype="bfloat16",
                     compute_dtype="bfloat16"), cache_len
    )
    full_shapes = nn.meta.unbox(jax.eval_shape(
        lambda r: full.init(r, jnp.zeros((B, 8), jnp.int32)),
        jax.random.PRNGKey(0),
    ))
    assert nbytes(full_shapes["params"]) > 15e9


def test_quant_llama_family_matches_dequantized_full():
    """RoPE/GQA/RMSNorm/SwiGLU/untied (the Llama recipe) under int8: the
    rotation applies to activations after the quantized q/k projections and
    the untied head is a QuantDense, so the whole family must reproduce the
    dequantized-full model like the GPT family does."""
    cfg = model_config("llama3_test", dropout=0.0, compute_dtype="float32",
                       param_dtype="float32")
    qcfg = dataclasses.replace(cfg, param_quant="int8")
    x = jnp.asarray([[1, 5, 9, 2, 7, 3, 4, 8]], jnp.int32)
    params = nn.meta.unbox(Transformer(cfg).init(jax.random.PRNGKey(0), x)["params"])
    params_q = quantize_params(jax.tree.map(np.asarray, params))
    expect = nn.meta.unbox(jax.eval_shape(
        lambda: Transformer(qcfg).init(jax.random.PRNGKey(0), x)
    )["params"])
    assert jax.tree.structure(jax.tree.map(lambda l: 0, params_q)) == \
        jax.tree.structure(jax.tree.map(lambda l: 0, expect))

    out_q = Transformer(qcfg).apply({"params": params_q}, x)
    out_f = Transformer(cfg).apply({"params": _dequantized(params_q, params)}, x)
    np.testing.assert_allclose(
        np.asarray(out_q), np.asarray(out_f), rtol=2e-4, atol=2e-4
    )


def test_quantize_params_validates_against_quant_model():
    """With cfg, quantize_params cross-checks its by-name conversion
    against the quant model's eval_shape structure: a good conversion
    passes, a mangled tree fails AT CONVERSION with the offending paths
    named (the alternative was an opaque flax structure mismatch deep
    inside apply — ADVICE round 5)."""
    params = Transformer(CFG).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    params = nn.meta.unbox(params)
    host = jax.tree.map(np.asarray, params)

    # the honest conversion validates clean
    quantize_params(host, CFG)

    # a checkpoint with an unexpected leaf name sails through the by-name
    # walk unconverted — validation must name the stray path
    bad = dict(host)
    bad["blocks"] = dict(bad["blocks"])
    bad["blocks"]["stray_module"] = {"kernel_oddname": np.zeros((4, 4))}
    with pytest.raises(ValueError, match="stray_module"):
        quantize_params(bad, CFG)

    # a missing subtree must also fail with the path, not inside apply
    short = {k: v for k, v in host.items() if k != "ln_f"}
    with pytest.raises(ValueError, match="ln_f"):
        quantize_params(short, CFG)

    # without cfg: legacy behavior, no validation
    quantize_params(bad)


def test_serve_rejects_prequantized_artifact_without_flag(tmp_path):
    """Importing an already-int8 msgpack without --quantize int8 must fail
    fast with the remedy in the message, not as a flax structure mismatch
    (ADVICE round 5)."""
    from flax.serialization import msgpack_serialize

    from zero_transformer_tpu.serve import main

    params = Transformer(CFG).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    q = quantize_params(jax.tree.map(np.asarray, nn.meta.unbox(params)))
    path = tmp_path / "p_int8.msgpack"
    path.write_bytes(msgpack_serialize(q))

    with pytest.raises(SystemExit, match="already int8-quantized"):
        main(["--model", "test", "--params", str(path),
              "--prompt", "x", "--tokenizer", "bytes"])


# ------------------------------------------------ int8 weight SERVING (PR 11)


def test_quant_engine_parity_with_generate():
    """The continuous-batching engine runs the int8 weight model through
    the same fused decode/prefill programs as full precision: every greedy
    trajectory byte-identical to single-request generate() on the SAME
    quantized tree — int8 weights ride the fused step, not a side path."""
    from zero_transformer_tpu.inference.generate import decode_model, generate
    from zero_transformer_tpu.inference.sampling import SamplingConfig
    from zero_transformer_tpu.serving import ServingEngine

    qcfg = dataclasses.replace(CFG, param_quant="int8")
    params = nn.meta.unbox(
        Transformer(CFG).init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    )
    qparams = jax.tree.map(jnp.asarray, quantize_params(jax.tree.map(np.asarray, params), qcfg))
    model_q = decode_model(qcfg, 48)
    greedy = SamplingConfig(greedy=True)
    prompts = [[(3 + i + j) % 250 + 1 for j in range(n)]
               for i, n in enumerate((4, 9, 13))]
    refs = [
        jax.device_get(generate(
            model_q, qparams, jnp.asarray([p], jnp.int32), 8,
            jax.random.PRNGKey(i), greedy,
        ))[0].tolist()
        for i, p in enumerate(prompts)
    ]
    engine = ServingEngine(
        qcfg, qparams, n_slots=2, cache_len=48, sampling=greedy,
        prefill_chunk=8, kv_layout="paged", page_size=8,
    )
    handles = [engine.submit(p, max_new_tokens=8, seed=i)
               for i, p in enumerate(prompts)]
    engine.run_until_idle()
    assert all(h.status == "done" for h in handles)
    assert [h.tokens for h in handles] == refs


def test_quant_perplexity_budget():
    """The parity gate for int8 weight serving: per-channel int8 must cost
    at most a small perplexity premium over full precision on held-out
    tokens. On the test model the quantization noise is tiny relative to
    the CE floor; the 2% ceiling is the budget the serving flag advertises
    (a real checkpoint regenerates this on its own eval split)."""
    from zero_transformer_tpu.ops.losses import next_token_loss

    qcfg = dataclasses.replace(CFG, param_quant="int8")
    params = nn.meta.unbox(
        Transformer(CFG).init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    )
    qparams = quantize_params(jax.tree.map(np.asarray, params), qcfg)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, CFG.vocab_size, (4, 24)), jnp.int32
    )
    labels = jnp.roll(tokens, -1, axis=1)
    logits_fp = Transformer(CFG).apply({"params": params}, tokens)
    logits_q = Transformer(qcfg).apply({"params": qparams}, tokens)
    ppl_fp = float(jnp.exp(next_token_loss(logits_fp, labels)))
    ppl_q = float(jnp.exp(next_token_loss(logits_q, labels)))
    assert ppl_q <= ppl_fp * 1.02, (ppl_q, ppl_fp)
