"""Test env: force an 8-device virtual CPU mesh before jax backends initialize.

This gives every test real multi-device semantics (sharding, collectives,
resharding) without a pod — the distributed-testing tier the reference lacks
entirely (SURVEY.md §4: "Distributed testing: none automated").

NOTE: in this image jax is pre-imported at interpreter startup, so setting
JAX_PLATFORMS via os.environ here is too late — the value is already baked
into jax.config. jax.config.update still works because no backend has been
initialized yet; XLA_FLAGS is read at backend init so it can still be set.
"""
import os
import sys

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache: the suite's wall-clock is dominated by XLA
# CPU compiles of 8-device programs that are identical run-to-run (round-3
# VERDICT weak #6). Shared across workers and runs; xdist workers hit the
# same directory safely (orbax-style atomic renames inside jax's cache).
# KNOWN ENVIRONMENT FLAKE (r5): on virtualized boxes the host CPU feature
# set can differ from the one a cached AOT entry was compiled with (XLA
# warns 'could lead to execution errors such as SIGILL' on every load);
# observed as SIGILL'd xdist workers AND as SIGABRT mid-compile (2026-07-31,
# twice, same cache dir populated on a previous host). The default cache dir
# is therefore fingerprinted with the host's CPU feature flags: a VM
# migration lands in a fresh directory (cold first run, no stale-AOT
# crashes) instead of poisoning the suite.


def _cpu_fingerprint() -> str:
    try:
        import zlib  # crc32: no crypto, so FIPS-enabled hosts can't reject it

        with open("/proc/cpuinfo") as f:
            for line in f:
                # x86 spells it "flags", aarch64 "Features"
                if line.startswith(("flags", "Features")):
                    return f"{zlib.crc32(line.encode()):08x}"
    except OSError:
        pass
    return "nofp"


_cache_dir = os.path.expanduser(
    os.environ.get(
        "JAX_TEST_COMPILATION_CACHE",
        f"/tmp/zero_transformer_tpu_jax_cache_{_cpu_fingerprint()}",
    )
)
# subprocess-based tests (the multihost workers) inherit the SAME resolved
# directory through the environment — a worker on a stale un-fingerprinted
# dir would reintroduce the very crash this guard exists for
os.environ["JAX_TEST_COMPILATION_CACHE"] = _cache_dir
if _cache_dir:
    jax.config.update("jax_compilation_cache_dir", _cache_dir)
    # default min compile-time threshold (1s) would skip most test programs;
    # cache everything — CPU test compiles of 2+ seconds are the norm here
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs


@pytest.fixture(scope="session", autouse=True)
def _assert_cpu():
    assert jax.default_backend() == "cpu", jax.default_backend()


def pytest_configure(config):
    config.addinivalue_line("markers", "slow: long-running test (excluded from quick CI lane)")
