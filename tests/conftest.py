"""Test env: force an 8-device virtual CPU mesh before jax backends initialize.

This gives every test real multi-device semantics (sharding, collectives,
resharding) without a pod — the distributed-testing tier the reference lacks
entirely (SURVEY.md §4: "Distributed testing: none automated").

NOTE: in this image jax is pre-imported at interpreter startup, so setting
JAX_PLATFORMS via os.environ here is too late — the value is already baked
into jax.config. jax.config.update still works because no backend has been
initialized yet; XLA_FLAGS is read at backend init so it can still be set.
"""
import os
import sys

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache: the suite's wall-clock is dominated by XLA
# CPU compiles of 8-device programs that are identical run-to-run (round-3
# VERDICT weak #6). Shared across workers and runs; xdist workers hit the
# same directory safely (orbax-style atomic renames inside jax's cache).
# Resolution (base dir + host-CPU fingerprint subdir, see
# tests/_compile_cache.py for the stale-AOT crash history) is shared with
# the standalone multihost workers, which recompute it from the same env.
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import _compile_cache  # noqa: E402

_cache_dir = _compile_cache.configure(jax)

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs


@pytest.fixture(scope="session", autouse=True)
def _assert_cpu():
    assert jax.default_backend() == "cpu", jax.default_backend()


def pytest_configure(config):
    config.addinivalue_line("markers", "slow: long-running test (excluded from quick CI lane)")
    config.addinivalue_line(
        "markers",
        "chaos: fault-injection scenario (supervisor restarts, watchdog "
        "aborts, injected IO failures) — `make chaos` runs just these",
    )
