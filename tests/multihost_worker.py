"""Worker for the 2-process x 4-device multi-host test (test_multihost.py).

Runs as a real separate process: initializes jax.distributed through
``bootstrap.maybe_initialize`` (env-driven), then exercises every
multi-process code path the reference only ever ran on live pods
(reference ``main_zero.py:181-184,377-387,554-557``):

- global device census across processes,
- ``device_put_batch`` building a global array from process-local rows,
- a fused ZeRO train step (grad all-reduce crosses the process boundary),
- multi-process Orbax save + restore,
- the pod health check.

Prints ``WORKER_OK`` as its last line on success.
"""
import os
import sys

import jax

jax.config.update("jax_platforms", "cpu")
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=4"
)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from zero_transformer_tpu.parallel.bootstrap import maybe_initialize  # noqa: E402


def main():
    assert maybe_initialize(), "coordinator env vars must trigger initialization"
    assert jax.process_count() == 2, jax.process_count()
    assert jax.device_count() == 8, jax.device_count()
    assert jax.local_device_count() == 4, jax.local_device_count()

    import numpy as np
    import optax

    from zero_transformer_tpu import checkpoint as ckpt_lib
    from zero_transformer_tpu.config import MeshConfig, OptimizerConfig, model_config
    from zero_transformer_tpu.data import DataLoader, SyntheticSource, device_put_batch
    from zero_transformer_tpu.models.gpt import Transformer
    from zero_transformer_tpu.parallel.mesh import make_mesh
    from zero_transformer_tpu.parallel.zero import (
        init_train_state,
        make_plan,
        make_train_step,
    )
    from zero_transformer_tpu.training.optimizer import make_optimizer
    from zero_transformer_tpu.utils.pod_check import pod_check

    # health check crosses both processes
    assert pod_check(timeout=120.0), "pod_check failed"

    cfg = model_config("test", dropout=0.0)
    mesh = make_mesh(MeshConfig(zero_stage=2))
    model = Transformer(cfg)
    tx = make_optimizer(OptimizerConfig(warmup_steps=2, total_steps=10))

    batch_size, seq = 8, 32
    plan = make_plan(model, tx, mesh, (batch_size, seq), zero_stage=2)
    state = init_train_state(
        model, tx, jax.random.PRNGKey(0), mesh, (batch_size, seq), plan
    )
    step = make_train_step(model, tx, mesh, plan, zero_stage=2)

    # striped loader -> process-local rows -> global sharded batch
    loader = DataLoader(
        SyntheticSource(cfg.vocab_size, seq, seed=1),
        batch_size=batch_size,
        train_context=seq,
    )
    assert loader.process_count == 2
    from jax.sharding import NamedSharding, PartitionSpec as P

    batch_sharding = NamedSharding(mesh, P(None, *plan.batch.spec))
    rng = jax.random.PRNGKey(2)
    losses = []
    it = iter(loader)
    for _ in range(2):
        local = next(it)  # [1, local_batch, seq]
        batch = device_put_batch(local, batch_sharding)
        assert batch.shape == (1, batch_size, seq)
        state, metrics = step(state, batch, rng)
        losses.append(float(metrics["loss"]))
    assert all(l == l for l in losses), f"non-finite loss: {losses}"
    norm_before = float(optax.global_norm(state.params))

    # multi-process Orbax round trip (each host writes only its shards)
    ckpt_dir = os.environ["WORKER_CKPT_DIR"]
    mgr = ckpt_lib.CheckpointManager(ckpt_dir, keep=1, async_save=False)
    mgr.save(2, state, meta={"loader": loader.state()}, force=True)
    mgr.wait()

    abstract = jax.tree.map(
        lambda l, s: jax.ShapeDtypeStruct(l.shape, l.dtype, sharding=s),
        jax.eval_shape(lambda s: s, state),
        plan.state,
    )
    restored, meta = mgr.restore(abstract)
    assert int(restored.step) == 2
    assert meta["loader"]["steps_consumed"] == 2
    norm_after = float(optax.global_norm(restored.params))
    np.testing.assert_allclose(norm_after, norm_before, rtol=1e-6)
    mgr.close()

    # hybrid (DCN) mesh: 2 process granules x 4 devices -> the data axis
    # must be ordered granule-major (indices 0-3 one process, 4-7 the
    # other), i.e. only the outer half of the data axis crosses the slow
    # network — the layout dcn_data exists to guarantee
    hybrid = make_mesh(MeshConfig(data=8, dcn_data=2))
    dev_grid = hybrid.devices  # (pipe, data, fsdp, expert, tensor, sequence)
    assert dev_grid.shape == (1, 8, 1, 1, 1, 1), dev_grid.shape
    row = dev_grid[0, :, 0, 0, 0, 0]
    first = {d.process_index for d in row[:4]}
    second = {d.process_index for d in row[4:]}
    assert len(first) == 1 and len(second) == 1 and first != second, (
        f"hybrid data axis not granule-major: {[d.process_index for d in row]}"
    )
    # and it actually computes: a cross-granule reduction over the hybrid
    # mesh's sharded data axis
    from jax.sharding import NamedSharding, PartitionSpec as P

    ones = jax.device_put(
        np.ones((8,), np.float32), NamedSharding(hybrid, P("data"))
    )
    s = float(jax.jit(lambda x: x.sum())(ones))
    assert s == 8.0, s

    print(f"process {jax.process_index()}: losses={losses}", flush=True)
    print("WORKER_OK", flush=True)


if __name__ == "__main__":
    main()
