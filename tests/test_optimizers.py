"""Optimizer families: adamw (reference parity), adafactor, lion.

The reference hardcodes one AdamW chain (reference ``main_zero.py:160-168``);
here the family is a config knob and each member must actually train on the
8-device mesh with its optimizer state placed per the ZeRO plan.
"""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from zero_transformer_tpu.config import MeshConfig, ModelConfig, OptimizerConfig
from zero_transformer_tpu.models import Transformer
from zero_transformer_tpu.parallel import (
    make_mesh,
    make_plan,
    init_train_state,
    make_train_step,
)
from zero_transformer_tpu.training.optimizer import make_optimizer, make_schedule

CFG = ModelConfig(
    name="t", vocab_size=256, d_model=64, n_heads=4, n_layers=2, max_seq_len=32,
    dropout=0.0, compute_dtype="float32",
)


def _setup(opt_name, lr=1e-3):
    opt = OptimizerConfig(
        peak_learning_rate=lr, warmup_steps=4, total_steps=64, optimizer=opt_name
    )
    mesh = make_mesh(MeshConfig())
    model = Transformer(CFG)
    tx = make_optimizer(opt)
    plan = make_plan(model, tx, mesh, (2, 16), 1)
    state = init_train_state(model, tx, jax.random.PRNGKey(0), mesh, (2, 16), plan)
    step = make_train_step(model, tx, mesh, plan, 1, make_schedule(opt))
    return state, step


def _batch(seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.integers(0, 256, (1, 8, 16)), jnp.int32)


@pytest.mark.parametrize("opt_name,lr,drop", [
    ("adamw", 1e-3, 0.5),
    # adafactor scales updates by parameter norm (tiny at init on a tiny
    # model), so it moves slower here; the contract is monotone learning,
    # not a race
    ("adafactor", 3e-2, 0.08),
    ("lion", 3e-4, 0.5),
])
def test_all_families_train(devices, opt_name, lr, drop):
    state, step = _setup(opt_name, lr)
    rng = jax.random.PRNGKey(42)
    losses = []
    for _ in range(20):
        state, metrics = step(state, _batch(), rng)
        losses.append(float(metrics["loss"]))
        assert np.isfinite(losses[-1])
    best = min(losses)
    assert best < losses[0] - drop, f"{opt_name}: no learning: {losses}"


def test_adafactor_state_is_factored(devices):
    """Adafactor's whole point: second-moment state much smaller than the
    params (row/col factors instead of full mu+nu). optax only factors dims
    >= 128, so this uses d_model=128 — at the default test width the
    assertion would pass vacuously with nothing factored."""
    big = dataclasses.replace(CFG, d_model=128, n_heads=4)
    opt = OptimizerConfig(warmup_steps=4, total_steps=64, optimizer="adafactor")
    mesh = make_mesh(MeshConfig())
    model = Transformer(big)
    tx = make_optimizer(opt)
    plan = make_plan(model, tx, mesh, (2, 16), 1)
    state_af = init_train_state(
        model, tx, jax.random.PRNGKey(0), mesh, (2, 16), plan
    )
    n_params = sum(l.size for l in jax.tree.leaves(state_af.params))
    af = sum(l.size for l in jax.tree.leaves(state_af.opt_state))
    # factored: v_row+v_col (O(d+f)) instead of full v (O(d*f)) for the
    # big kernels -> total opt state well under one params' worth
    assert af < 0.6 * n_params, f"adafactor state {af} vs params {n_params}"


def test_adafactor_trains_at_zero2(tmp_path):
    """Adafactor x ZeRO-2 through the Trainer (pre-round-5 this combination
    was rejected; the explicit core now swaps in the shard-aware factored
    transforms via tx_factory). Loss must fall and stay finite — the
    trajectory-vs-stage-1 exactness lives in test_zero.py."""
    from zero_transformer_tpu.config import (
        CheckpointConfig, Config, DataConfig, TrainingConfig,
    )
    from zero_transformer_tpu.training.trainer import Trainer

    cfg = Config(
        model=dataclasses.replace(CFG, d_model=128),  # >=128 so factoring fires
        mesh=MeshConfig(zero_stage=2),
        optimizer=OptimizerConfig(peak_learning_rate=3e-2, warmup_steps=2,
                                  total_steps=20, optimizer="adafactor"),
        training=TrainingConfig(batch_size=8, train_context=16, total_steps=20,
                                evaluation_frequency=100, log_frequency=100),
        data=DataConfig(source="synthetic", max_context=16),
        checkpoint=CheckpointConfig(directory=str(tmp_path / "run"),
                                    save_frequency=100, async_save=False),
    )
    trainer = Trainer(cfg)
    state = trainer.init_state()
    first_eval = trainer.evaluate(state)["loss"]
    state = trainer.train()
    final_eval = trainer.evaluate(state)["loss"]
    trainer.close()
    assert np.isfinite(final_eval)
    assert final_eval < first_eval, (first_eval, final_eval)


def test_invalid_family_rejected():
    with pytest.raises(ValueError, match="invalid optimizer"):
        OptimizerConfig(optimizer="sgd")
