"""Paged-attention decode kernel parity (ops/pallas/paged_attention.py).

The kernel's contract is BIT-exactness against the gather-to-slab reference
it replaces: per (row, kv-head) it runs the exact op sequence of
``jnp.take(pool, table)`` + ``ops.attention.xla_attention``'s per-row
branch, so swapping the read path can never change a served token. These
tests pin that bit-for-bit across page sizes {8, 64}, ragged block tables,
trash-page rows, int8 KV scales, chunk-boundary offsets, and the
spec-verify window — then prove the ENGINE integration: a serving run with
the kernels enabled (interpret mode on this CPU image) emits byte-identical
streams to the gather engine, under strict-mode dispatch sanitizers at one
compile signature per site.
"""
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from zero_transformer_tpu.ops.attention import xla_attention
from zero_transformer_tpu.ops.pallas import paged_attention as pa

CACHE_LEN = 48


def _case(B, T, H, KVH, D, page, n_blocks, dtype, alibi, int8=False, seed=0,
          offsets=None, table=None):
    """Build (q, pools, table, offsets) and both attention paths."""
    n_pages = B * n_blocks + 4
    S = page * n_blocks
    ks = jax.random.split(jax.random.PRNGKey(seed), 8)
    q = jax.random.normal(ks[0], (B, T, H, D), dtype)
    if int8:
        k_pool = jax.random.randint(
            ks[1], (n_pages, page, KVH, D), -127, 128, jnp.int32
        ).astype(jnp.int8)
        v_pool = jax.random.randint(
            ks[2], (n_pages, page, KVH, D), -127, 128, jnp.int32
        ).astype(jnp.int8)
        k_sc = jax.random.uniform(ks[5], (n_pages, page, KVH, 1), jnp.float32, 1e-3, 2e-2)
        v_sc = jax.random.uniform(ks[6], (n_pages, page, KVH, 1), jnp.float32, 1e-3, 2e-2)
    else:
        k_pool = jax.random.normal(ks[1], (n_pages, page, KVH, D), dtype)
        v_pool = jax.random.normal(ks[2], (n_pages, page, KVH, D), dtype)
        k_sc = v_sc = None
    if table is None:
        table = jax.random.randint(ks[3], (B, n_blocks), 1, n_pages, jnp.int32)
    if offsets is None:
        offsets = jax.random.randint(ks[4], (B,), 0, S - T + 1, jnp.int32)
    offsets = jnp.asarray(offsets, jnp.int32)
    table = jnp.asarray(table, jnp.int32)

    def reference(q, kp, vp, tbl, off):
        """The gather-to-slab path the kernel replaces, verbatim."""
        if int8:
            g = (jnp.take(kp, tbl, axis=0).astype(jnp.float32)
                 * jnp.take(k_sc, tbl, axis=0)).astype(dtype).reshape(B, S, KVH, D)
            gv = (jnp.take(vp, tbl, axis=0).astype(jnp.float32)
                  * jnp.take(v_sc, tbl, axis=0)).astype(dtype).reshape(B, S, KVH, D)
        else:
            g = jnp.take(kp, tbl, axis=0).reshape(B, S, KVH, D)
            gv = jnp.take(vp, tbl, axis=0).reshape(B, S, KVH, D)
        kv_valid = (jnp.arange(S)[None, :] < (off[:, None] + T)).astype(jnp.int32)
        return xla_attention(
            q, g, gv, causal=T > 1, alibi=alibi, q_offset=off,
            segment_ids=kv_valid,
        )

    ref = jax.jit(reference)(q, k_pool, v_pool, table, offsets)
    out = jax.jit(
        lambda q, kp, vp, tbl, off: pa.paged_attention(
            q, kp, vp, tbl, off, causal=T > 1, alibi=alibi,
            k_scale=k_sc, v_scale=v_sc, interpret=True,
        )
    )(q, k_pool, v_pool, table, offsets)
    return np.asarray(ref), np.asarray(out)


@pytest.mark.parametrize("page,n_blocks", [(8, 6), (64, 2)])
@pytest.mark.parametrize("alibi", [True, False])
def test_bitwise_vs_gather_page_sizes(page, n_blocks, alibi):
    ref, out = _case(3, 1, 4, 2, 64, page, n_blocks, jnp.float32, alibi)
    assert np.array_equal(ref, out)


def test_bitwise_bf16_and_gqa():
    ref, out = _case(2, 1, 8, 2, 64, 8, 4, jnp.bfloat16, True)
    assert np.array_equal(ref, out)


def test_bitwise_mha_single_token():
    """MHA (G=1) single-token decode — the shape that exposed the per-head
    2-D-dot lowering divergence: XLA routes an M=1 gemv differently from
    the reference's batched einsum, so the kernel must keep the kv-head
    axis INSIDE the contraction. Pinned so a grid refactor can't silently
    reintroduce the per-head dot."""
    ref, out = _case(2, 1, 4, 4, 64, 16, 4, jnp.float32, True, seed=11)
    assert np.array_equal(ref, out)


def test_bitwise_spec_verify_window_causal():
    """T = 1 + draft_k: the spec-verify block attends causally within its
    window at each row's own offset."""
    ref, out = _case(2, 5, 4, 4, 64, 8, 4, jnp.float32, False)
    assert np.array_equal(ref, out)
    ref, out = _case(2, 4, 6, 6, 64, 8, 3, jnp.float32, True)
    assert np.array_equal(ref, out)


def test_bitwise_int8_kv_scales():
    """int8 pages dequantize in-register exactly like the gathered view:
    (int8 -> f32) * scale -> compute dtype, elementwise."""
    ref, out = _case(2, 1, 4, 2, 64, 8, 4, jnp.float32, True, int8=True)
    assert np.array_equal(ref, out)
    ref, out = _case(2, 3, 4, 2, 64, 8, 4, jnp.float32, True, int8=True, seed=7)
    assert np.array_equal(ref, out)


def test_bitwise_ragged_tables_and_trash_rows():
    """Rows at wildly different fills — including a fully-parked row whose
    zeroed table routes every read to the trash page — and offsets landing
    exactly ON and one-before page boundaries (the chunk-boundary cases)."""
    page, n_blocks = 8, 6
    B = 5
    # offsets: 0 (empty-ish), page-1, page (boundary), mid, full-1
    offsets = [0, page - 1, page, 3 * page + 5, page * n_blocks - 1]
    table = np.random.default_rng(0).integers(1, B * n_blocks + 3, (B, n_blocks))
    table[0, :] = 0  # parked row: trash page everywhere
    ref, out = _case(
        B, 1, 4, 2, 64, page, n_blocks, jnp.float32, True,
        offsets=offsets, table=table,
    )
    assert np.array_equal(ref, out)


def test_gate_decisions():
    """The ONE gate both the model trace and the engine gauge consult."""
    common = dict(T=1, D=64, page_size=16, dtype=jnp.float32)
    assert pa.supported("auto", interpret=True, **common)
    assert pa.supported("flash", interpret=True, **common)
    assert not pa.supported("xla", interpret=True, **common)
    # decode windows only
    assert not pa.supported(
        "auto", interpret=True, T=pa.MAX_DECODE_T + 1, D=64, page_size=16,
        dtype=jnp.float32,
    )
    # off-TPU without interpret: decline (the gather path is the fallback)
    if jax.default_backend() != "tpu":
        assert not pa.supported("auto", **common)
    # f16 never
    assert not pa.supported(
        "auto", interpret=True, T=1, D=64, page_size=16, dtype=jnp.float16
    )


# ---------------------------------------------------------------- engine e2e


def test_engine_kernel_parity_and_one_signature(monkeypatch):
    """Serving run with the Pallas kernels enabled (interpret mode): every
    stream byte-identical to the gather-path engine, decode AND spec-verify
    dispatch sites at ONE compile signature under strict-mode sanitizers,
    and the paged-kernel gauge honest about what traced."""
    from zero_transformer_tpu.analysis import runtime as rt
    from zero_transformer_tpu.config import model_config
    from zero_transformer_tpu.inference.sampling import SamplingConfig
    from zero_transformer_tpu.models import Transformer
    from zero_transformer_tpu.serving import ServingEngine

    cfg = model_config("test", dropout=0.0, compute_dtype="float32")
    params = Transformer(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    prompts = [
        [(3 + i + j) % 250 + 1 for j in range(n)]
        for i, n in enumerate((2, 7, 17))
    ]

    def run(greedy, draft_k):
        sampling = SamplingConfig(greedy=True) if greedy else SamplingConfig(
            temperature=0.9, top_k=20
        )
        engine = ServingEngine(
            cfg, params, n_slots=2, cache_len=CACHE_LEN, sampling=sampling,
            prefill_chunk=8, kv_layout="paged", page_size=8, draft_k=draft_k,
        )
        handles = [
            engine.submit(p, max_new_tokens=8, seed=i)
            for i, p in enumerate(prompts)
        ]
        engine.run_until_idle()
        assert all(h.status == "done" for h in handles)
        return [h.tokens for h in handles], engine

    monkeypatch.delenv("ZT_PALLAS_INTERPRET", raising=False)
    gather_plain, _ = run(greedy=False, draft_k=0)
    gather_spec, _ = run(greedy=True, draft_k=3)

    monkeypatch.setenv("ZT_PALLAS_INTERPRET", "1")
    rt.set_strict(True)
    try:
        kernel_plain, e1 = run(greedy=False, draft_k=0)
        kernel_spec, e2 = run(greedy=True, draft_k=3)
    finally:
        rt.set_strict(None)
    assert kernel_plain == gather_plain
    assert kernel_spec == gather_spec
    for engine in (e1, e2):
        snap = engine.metrics_snapshot()
        assert snap["kernel_paged_attention"] == 1
        assert snap["dispatch_paged_attention_signatures"] == 1
        assert snap["dispatch_paged_attention_violations"] == 0
        assert snap["dispatch_decode_step_violations"] == 0
        assert snap["dispatch_spec_verify_violations"] == 0


def test_engine_fused_tail_control_parity():
    """fused_tail=False (the A/B control: sampling as its own dispatch)
    emits byte-identical trajectories to the fused path, and its sample
    site stays at one signature."""
    from zero_transformer_tpu.config import model_config
    from zero_transformer_tpu.inference.sampling import SamplingConfig
    from zero_transformer_tpu.models import Transformer
    from zero_transformer_tpu.serving import ServingEngine

    cfg = model_config("test", dropout=0.0, compute_dtype="float32")
    params = Transformer(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    prompts = [[(5 + i + j) % 250 + 1 for j in range(n)]
               for i, n in enumerate((3, 9, 14))]

    def run(fused):
        engine = ServingEngine(
            cfg, params, n_slots=2, cache_len=CACHE_LEN,
            sampling=SamplingConfig(temperature=0.9, top_k=20),
            prefill_chunk=8, kv_layout="paged", page_size=8,
            fused_tail=fused,
        )
        handles = [
            engine.submit(p, max_new_tokens=8, seed=i)
            for i, p in enumerate(prompts)
        ]
        engine.run_until_idle()
        assert all(h.status == "done" for h in handles)
        return [h.tokens for h in handles], engine

    fused, ef = run(True)
    control, ec = run(False)
    assert fused == control
    assert ef.metrics_snapshot()["fused_tail"] == 1
    snap = ec.metrics_snapshot()
    assert snap["fused_tail"] == 0
    assert snap["dispatch_sample_tail_signatures"] == 1
    assert snap["dispatch_sample_tail_violations"] == 0
    # the control rejects speculation: the verify step cannot be defused
    with pytest.raises(ValueError):
        ServingEngine(
            cfg, params, n_slots=2, cache_len=CACHE_LEN,
            prefill_chunk=8, kv_layout="paged", page_size=8,
            fused_tail=False, draft_k=2,
        )
