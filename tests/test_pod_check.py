"""pod_check quick-lane coverage: single-process health + bandwidth micro.

The cross-process legs live in the slow tier (tests/multihost_worker.py);
these pin the value-checked psum and the bandwidth report shape on the
8-device virtual mesh.
"""
from zero_transformer_tpu.utils.pod_check import allreduce_bandwidth, pod_check


def test_pod_check_healthy(devices):
    # generous timeout: the suite shares the box with other jobs, and a
    # wall-clock guard must not convert CPU contention into a failure
    assert pod_check(timeout=600.0, verbose=False)


def test_allreduce_bandwidth_report(devices):
    r = allreduce_bandwidth(mib=1.0, reps=2, verbose=False, timeout=600.0)
    assert r["devices"] == 8
    assert r["buffer_mib_per_device"] == 1.0
    assert r["algo_bandwidth_GBps"] > 0
    # ring-transfer bytes are 2(n-1)/n of the buffer: 1.75x at n=8 (both
    # values are rounded to 2 decimals in the report, hence the tolerance)
    assert abs(r["ring_transfer_GBps"] / r["algo_bandwidth_GBps"] - 1.75) < 0.1
