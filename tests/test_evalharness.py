"""Eval-harness tests: loglikelihood scoring, LAMBADA acc/ppl, perplexity/BPB.

The reference had no in-repo eval at all (it exported to PyTorch and ran
lm-eval-harness on GPU, SURVEY §2); these tests pin the in-tree scoring math
against hand-computed log-softmax values on a tiny model.
"""
import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from zero_transformer_tpu.config import ModelConfig
from zero_transformer_tpu.evalharness import (
    choice_accuracy,
    lambada,
    loglikelihoods,
    perplexity,
    score_batch,
)
from zero_transformer_tpu.models import Transformer

CFG = ModelConfig(
    name="t", vocab_size=64, d_model=32, n_heads=4, n_layers=2, max_seq_len=32,
    dropout=0.0, compute_dtype="float32",
)


@pytest.fixture(scope="module")
def model_and_params():
    model = Transformer(CFG)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    return model, params


def _manual_logprob(model, params, tokens, positions):
    """Sum log P(tokens[t] | tokens[:t]) for t in positions, via full forward."""
    logits = model.apply({"params": params}, jnp.asarray([tokens], jnp.int32))
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)[0]
    total = 0.0
    all_greedy = True
    for t in positions:
        total += float(logp[t - 1, tokens[t]])
        all_greedy &= int(jnp.argmax(logp[t - 1])) == tokens[t]
    return total, all_greedy


def test_score_batch_matches_manual(model_and_params):
    model, params = model_and_params
    tokens = [5, 9, 11, 3, 7, 2]
    # continuation = positions 3..5
    batch = jnp.asarray([tokens], jnp.int32)
    mask = jnp.asarray([[0, 0, 0, 1, 1, 1]], jnp.int32)
    res = score_batch(model, params, batch, mask)
    want, greedy = _manual_logprob(model, params, tokens, [3, 4, 5])
    np.testing.assert_allclose(float(res["logprob"][0]), want, rtol=1e-5)
    assert int(res["tokens"][0]) == 3
    assert bool(res["greedy_match"][0]) == greedy


def test_loglikelihoods_padding_invariance(model_and_params):
    """Scores must not depend on batch padding or row position."""
    model, params = model_and_params
    ex = [([5, 9], [11, 3]), ([1, 2, 3], [4]), ([7], [8, 9, 10])]
    solo = [
        loglikelihoods(model, params, [e], seq_len=16, batch_size=4)[0] for e in ex
    ]
    together = loglikelihoods(model, params, ex, seq_len=16, batch_size=2)
    for s, t in zip(solo, together):
        assert s["tokens"] == t["tokens"]
        np.testing.assert_allclose(s["logprob"], t["logprob"], rtol=1e-4)
        assert s["greedy_match"] == t["greedy_match"]


def test_loglikelihoods_left_truncates_context(model_and_params):
    model, params = model_and_params
    long_ctx = list(range(1, 30))
    res = loglikelihoods(
        model, params, [(long_ctx, [5, 6])], seq_len=8, batch_size=1
    )[0]
    # must equal scoring with only the last 6 context tokens
    want = loglikelihoods(
        model, params, [(long_ctx[-6:], [5, 6])], seq_len=8, batch_size=1
    )[0]
    np.testing.assert_allclose(res["logprob"], want["logprob"], rtol=1e-5)


def test_lambada_metrics(model_and_params):
    model, params = model_and_params
    rng = np.random.default_rng(3)
    examples = [
        (list(rng.integers(1, 60, 6)), list(rng.integers(1, 60, 2))) for _ in range(5)
    ]
    out = lambada(model, params, examples, seq_len=16, batch_size=2)
    assert out["examples"] == 5
    assert out["ppl"] > 0 and 0.0 <= out["acc"] <= 1.0
    # ppl consistent with mean logprob
    res = loglikelihoods(model, params, examples, seq_len=16, batch_size=2)
    lp = sum(r["logprob"] for r in res) / sum(r["tokens"] for r in res)
    np.testing.assert_allclose(out["ppl"], math.exp(-lp), rtol=1e-6)


def test_choice_accuracy_matches_manual_argmax(model_and_params):
    """acc/acc_norm must equal a hand computation from raw loglikelihoods."""
    model, params = model_and_params
    rng = np.random.default_rng(7)
    examples = []
    for _ in range(6):
        ctx = list(rng.integers(1, 60, 5))
        choices = [list(rng.integers(1, 60, n)) for n in (2, 4, 3)]
        byte_lens = [9, 21, 15]  # surface-string UTF-8 lengths
        examples.append((ctx, choices, int(rng.integers(0, 3)), byte_lens))

    out = choice_accuracy(model, params, examples, seq_len=16, batch_size=4)
    assert out["norm"] == "bytes" and out["examples"] == 6

    # manual recomputation via the scoring primitive
    acc_hits, norm_hits = 0, 0
    for ctx, choices, gold, byte_lens in examples:
        lps = [
            loglikelihoods(model, params, [(ctx, c)], seq_len=16, batch_size=1)[0][
                "logprob"
            ]
            for c in choices
        ]
        acc_hits += int(np.argmax(lps)) == gold
        norm_hits += int(np.argmax([l / b for l, b in zip(lps, byte_lens)])) == gold
    np.testing.assert_allclose(out["acc"], acc_hits / 6)
    np.testing.assert_allclose(out["acc_norm"], norm_hits / 6)


def test_choice_accuracy_token_norm_fallback(model_and_params):
    model, params = model_and_params
    examples = [([5, 9, 2], [[1, 2], [3], [4, 5, 6]], 1)]  # no byte lengths
    out = choice_accuracy(model, params, examples, seq_len=16, batch_size=2)
    assert out["norm"] == "tokens"
    assert 0.0 <= out["acc"] <= 1.0 and 0.0 <= out["acc_norm"] <= 1.0


def test_choice_accuracy_rejects_mixed_normalization(model_and_params):
    model, params = model_and_params
    examples = [
        ([5, 9], [[1], [2]], 0, [4, 7]),
        ([5, 9], [[1], [2]], 1),  # missing byte lengths
    ]
    with pytest.raises(ValueError, match="all examples or none"):
        choice_accuracy(model, params, examples, seq_len=8, batch_size=2)


def test_choice_accuracy_micro_golden(model_and_params):
    """A rigged two-choice example where raw and normalized argmax MUST
    disagree: choice A = one copy of a high-probability token, choice B = two
    copies of it. B's summed logprob is lower (more tokens) but its per-byte
    score can win with a long byte length assigned to A. Pin both criteria."""
    model, params = model_and_params
    ctx = [5, 9]
    lp = loglikelihoods(
        model, params, [(ctx, [11]), (ctx, [11, 11])], seq_len=8, batch_size=2
    )
    lp_a, lp_b = lp[0]["logprob"], lp[1]["logprob"]
    assert lp_a > lp_b  # one factor vs two: strictly more probable
    # bytes: A long (normalizes to tiny), B short (normalizes to big)
    examples = [(ctx, [[11], [11, 11]], 0, [100, 1])]
    out = choice_accuracy(model, params, examples, seq_len=8, batch_size=2)
    assert out["acc"] == 1.0  # raw picks A (gold)
    assert out["acc_norm"] == (1.0 if lp_a / 100 > lp_b / 1 else 0.0)


def test_perplexity_and_bpb(model_and_params):
    model, params = model_and_params
    rng = np.random.default_rng(4)
    stream = list(rng.integers(1, 60, 70))
    out = perplexity(model, params, stream, seq_len=16, batch_size=2, num_bytes=300)
    # rolling windows (stride seq_len-1): every token but the stream's first
    # is predicted exactly once
    assert out["tokens"] == len(stream) - 1
    np.testing.assert_allclose(out["ppl"], math.exp(out["nll"] / out["tokens"]), rtol=1e-6)
    np.testing.assert_allclose(
        out["bits_per_byte"], out["nll"] / (math.log(2) * 300), rtol=1e-6
    )


def test_eval_cli_end_to_end(model_and_params, tmp_path, capsys, monkeypatch):
    """The `python -m zero_transformer_tpu.evalharness` driver: zoo model +
    msgpack params + token JSONL -> one JSON result line."""
    import json

    import flax.linen as nn
    from flax.serialization import msgpack_serialize

    from zero_transformer_tpu.evalharness import cli

    # params for the zoo's "test" model, exported the way export.py does
    from zero_transformer_tpu.config import model_config
    from zero_transformer_tpu.models import Transformer

    cfg = model_config("test", compute_dtype="float32", dropout=0.0)
    model = Transformer(cfg)
    params = nn.meta.unbox(
        model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    )
    params_path = tmp_path / "p.msgpack"
    params_path.write_bytes(msgpack_serialize(jax.tree.map(np.asarray, params)))

    rng = np.random.default_rng(0)
    data = tmp_path / "lambada.jsonl"
    with open(data, "w") as f:
        for _ in range(3):
            f.write(json.dumps({
                "context": [int(t) for t in rng.integers(1, 60, 6)],
                "target": [int(t) for t in rng.integers(1, 60, 2)],
            }) + "\n")

    cli.main([
        "--model", "test", "--params", str(params_path), "--task", "lambada",
        "--data", str(data), "--seq-len", "16", "--batch-size", "2",
        "--dtype", "float32",
    ])
    out = json.loads(capsys.readouterr().out.strip())
    assert out["task"] == "lambada" and out["examples"] == 3
    assert out["ppl"] > 0


def test_perplexity_batch_size_invariance(model_and_params):
    model, params = model_and_params
    rng = np.random.default_rng(5)
    stream = list(rng.integers(1, 60, 80))
    a = perplexity(model, params, stream, seq_len=16, batch_size=2)
    b = perplexity(model, params, stream, seq_len=16, batch_size=5)
    np.testing.assert_allclose(a["nll"], b["nll"], rtol=1e-5)


def test_eval_cli_quantize_close_to_full(tmp_path, capsys):
    """--quantize int8 scores the weight-only serving path: same CLI, same
    data, a ppl within a few percent of the full-precision run (per-channel
    int8 is a mild perturbation, not a different model)."""
    import json

    import flax.linen as nn
    from flax.serialization import msgpack_serialize

    from zero_transformer_tpu.config import model_config
    from zero_transformer_tpu.evalharness import cli
    from zero_transformer_tpu.models import Transformer

    cfg = model_config("test", compute_dtype="float32", dropout=0.0)
    params = nn.meta.unbox(
        Transformer(cfg).init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    )
    params_path = tmp_path / "p.msgpack"
    params_path.write_bytes(msgpack_serialize(jax.tree.map(np.asarray, params)))
    rng = np.random.default_rng(1)
    data = tmp_path / "stream.json"
    data.write_text(json.dumps(
        {"tokens": [int(t) for t in rng.integers(1, 60, 70)], "num_bytes": 300}
    ))

    results = {}
    for q in ("none", "int8"):
        cli.main([
            "--model", "test", "--params", str(params_path), "--task", "bpb",
            "--data", str(data), "--seq-len", "16", "--batch-size", "2",
            "--dtype", "float32", "--quantize", q,
        ])
        results[q] = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert results["int8"]["bits_per_byte"] == pytest.approx(
        results["none"]["bits_per_byte"], rel=0.05
    )
