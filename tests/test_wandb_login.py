"""wandb login helper (reference ``login.py:20-22`` equivalent)."""
import os

from zero_transformer_tpu.utils import wandb_login


def test_netrc_write_and_replace(tmp_path, monkeypatch):
    monkeypatch.setenv("HOME", str(tmp_path))
    path = wandb_login._netrc_login("k1")
    assert path == str(tmp_path / ".netrc")
    content = open(path).read()
    assert "api.wandb.ai" in content and "k1" in content
    assert oct(os.stat(path).st_mode & 0o777) == "0o600"
    # relogin replaces the existing entry, never duplicates it
    wandb_login._netrc_login("k2")
    content = open(path).read()
    assert "k2" in content and "k1" not in content
    assert content.count("api.wandb.ai") == 1


def test_key_resolution_order(monkeypatch, tmp_path):
    class A:
        key = None
        key_file = None

    monkeypatch.setenv("WANDB_API_KEY", "envkey")
    assert wandb_login._resolve_key(A()) == "envkey"
    monkeypatch.delenv("WANDB_API_KEY")
    f = tmp_path / "key"
    f.write_text("filekey\n")
    A.key_file = str(f)
    assert wandb_login._resolve_key(A()) == "filekey"
    A.key = "argkey"
    assert wandb_login._resolve_key(A()) == "argkey"


def test_broadcast_prints_gcloud_with_resolved_key(capsys, monkeypatch):
    monkeypatch.delenv("WANDB_API_KEY", raising=False)
    wandb_login.main(
        ["--broadcast", "mypod", "--zone", "us-central2-b", "--key", "sekrit"]
    )
    out = capsys.readouterr().out
    assert "gcloud compute tpus tpu-vm ssh mypod" in out
    assert "--worker=all" in out
    assert "--key sekrit" in out  # works from --key/--key-file, not only env


def test_netrc_preserves_following_default_entry(tmp_path, monkeypatch):
    # a `default` entry after the wandb machine block must survive relogin
    monkeypatch.setenv("HOME", str(tmp_path))
    (tmp_path / ".netrc").write_text(
        "machine api.wandb.ai\n  login user\n  password old\n"
        "default\n  login u\n  password p\n"
    )
    wandb_login._netrc_login("new")
    content = (tmp_path / ".netrc").read_text()
    assert "default" in content and "password p" in content
    assert "old" not in content and "new" in content
