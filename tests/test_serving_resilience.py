"""Serving resilience layer: lifecycle, tick supervision, drain, reload, shed.

The serving counterpart of tests/test_resilience.py. The load-bearing
invariants, each proven by injecting the fault and watching the blast
radius:

- a fault in one decode tick fails ONLY the slots it poisons (retryable
  error to those clients) — the scheduler thread, the queue, and every
  other request survive untouched (byte-identical to single-request
  ``generate()``);
- the breaker trips the engine into DEGRADED and rebuilds the jitted step
  after N consecutive faults; a clean tick closes it back to READY;
- drain stops admission (retryable 503s), finishes in-flight generations
  up to the deadline, then force-finishes — no handle ever hangs;
- hot reload swaps checkpoints between ticks without retiring a slot, and
  a corrupt/mismatched artifact is rejected with the engine READY on the
  old weights;
- infeasible deadlines shed at admission instead of timing out mid-queue.

Fast deterministic cases run in the quick lane; the full chaos scenario
(decode faults + NaN windows + mid-load SIGTERM over HTTP) carries the
``chaos`` marker: ``make serve-chaos``.
"""
import http.client
import json
import signal
import threading
import time

import jax
import jax.numpy as jnp
import pytest

from zero_transformer_tpu.checkpoint import export_params_msgpack
from zero_transformer_tpu.config import model_config
from zero_transformer_tpu.inference.generate import decode_model, generate
from zero_transformer_tpu.inference.sampling import SamplingConfig
from zero_transformer_tpu.models import Transformer
from zero_transformer_tpu.serving import (
    DEGRADED,
    DRAINING,
    READY,
    STARTING,
    STOPPED,
    ReloadError,
    ServeFault,
    ServingChaosMonkey,
    ServingEngine,
    ServingServer,
    run_server,
)
from zero_transformer_tpu.serving.resilience import (
    CircuitBreaker,
    ItlEwma,
    Lifecycle,
    infeasible_deadline,
)

CACHE_LEN = 32
SAMPLING = SamplingConfig(temperature=0.9, top_k=20)


@pytest.fixture(scope="module")
def cfg():
    return model_config("test", dropout=0.0, compute_dtype="float32")


@pytest.fixture(scope="module")
def params(cfg):
    model = Transformer(cfg)
    return model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))[
        "params"
    ]


@pytest.fixture(scope="module")
def params2(cfg):
    """A second, differently-initialized tree with the same structure —
    the hot-reload artifact."""
    model = Transformer(cfg)
    return model.init(jax.random.PRNGKey(1), jnp.zeros((1, 8), jnp.int32))[
        "params"
    ]


@pytest.fixture(scope="module")
def reference(cfg, params):
    model = decode_model(cfg, CACHE_LEN)

    def run(prompt, seed, max_new=8, p=params):
        toks = generate(
            model, p, jnp.asarray([prompt], jnp.int32), max_new,
            jax.random.PRNGKey(seed), SAMPLING,
        )
        return jax.device_get(toks)[0].tolist()

    return run


def make_engine(cfg, params, clock=None, **kw):
    kw.setdefault("n_slots", 2)
    kw.setdefault("cache_len", CACHE_LEN)
    kw.setdefault("sampling", SAMPLING)
    if clock is not None:
        kw["clock"] = clock
    return ServingEngine(cfg, params, **kw)


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


class ByteTokenizer:
    eos_token_id = None

    def encode(self, text):
        return list(text.encode("utf-8"))

    def decode(self, ids, **kw):
        return bytes(int(i) % 256 for i in ids).decode("utf-8", errors="replace")


# ----------------------------------------------------------------- lifecycle


def test_lifecycle_state_machine():
    clock = FakeClock()
    lc = Lifecycle(clock)
    assert lc.state == STARTING
    clock.t = 2.0
    assert lc.uptime_s == 2.0
    assert lc.to(READY)
    assert lc.to(DEGRADED) and lc.to(READY, reason="recovered")
    assert lc.to(DRAINING)
    assert not lc.to(READY)  # draining never returns to traffic
    assert not lc.to(DEGRADED)
    assert lc.to(STOPPED)
    assert not lc.to(READY)  # terminal
    assert [s for s, _, _ in lc.history] == [
        STARTING, READY, DEGRADED, READY, DRAINING, STOPPED,
    ]


def test_circuit_breaker_threshold_and_cooldown():
    br = CircuitBreaker(threshold=3, cooldown=2)
    assert not br.record_fault() and not br.record_fault()
    assert br.record_fault()  # 3rd consecutive opens it
    assert br.open and br.trips == 1
    assert not br.record_clean()  # cooldown=2: one clean tick isn't enough
    assert br.record_clean() and not br.open
    # a fault mid-cooldown resets the clean streak
    br2 = CircuitBreaker(threshold=1, cooldown=2)
    assert br2.record_fault() and br2.open
    assert not br2.record_clean()
    br2.record_fault()
    assert not br2.record_clean() and br2.open


def test_run_marks_ready_and_stop_marks_stopped(cfg, params):
    engine = make_engine(cfg, params)
    assert engine.lifecycle.state == STARTING
    stop = threading.Event()
    thread = threading.Thread(target=engine.run, args=(stop,), daemon=True)
    thread.start()
    give_up = time.monotonic() + 30
    while engine.lifecycle.state != READY and time.monotonic() < give_up:
        time.sleep(0.005)
    assert engine.lifecycle.state == READY
    stop.set()
    thread.join(timeout=30)
    assert engine.lifecycle.state == STOPPED


# --------------------------------------------------------- tick supervision


def test_tick_fault_fails_only_active_slots(cfg, params, reference):
    """One poisoned tick: the two decoding requests fail RETRYABLY, the
    queued request survives, admits afterwards, and its trajectory is
    byte-identical to single-request generate() — the scheduler never
    died."""
    chaos = ServingChaosMonkey([ServeFault("tick_fault", step=2, duration=1)])
    engine = make_engine(cfg, params, n_slots=2, chaos=chaos)
    a = engine.submit([1, 2], max_new_tokens=8, seed=0)
    b = engine.submit([3, 4], max_new_tokens=8, seed=1)
    queued = engine.submit([5, 6], max_new_tokens=8, seed=7)
    engine.run_until_idle()
    assert a.status == "failed" and a.retryable and "retryable" in a.error
    assert b.status == "failed" and b.retryable
    assert queued.status == "done"
    assert queued.tokens == reference([5, 6], 7)
    assert engine.stats["tick_faults"] == 1
    assert engine.stats["breaker_trips"] == 0  # one fault < threshold
    # blocked consumers unblocked (terminal events delivered)
    assert a.result(timeout=1) == a.tokens


def test_breaker_trips_rebuilds_and_recovers(cfg, params, reference):
    """Three consecutive faulted ticks open the breaker: DEGRADED, jitted
    step rebuilt, then the next clean tick closes it back to READY and the
    engine serves byte-identical output again."""
    chaos = ServingChaosMonkey([ServeFault("tick_fault", step=1, duration=3)])
    engine = make_engine(cfg, params, n_slots=1, chaos=chaos)
    victims = [engine.submit([i + 1], max_new_tokens=4, seed=i) for i in range(3)]
    engine.step()  # tick 0: clean (admits first victim)
    for _ in range(3):  # ticks 1-3: faulted
        engine.step()
    assert engine.lifecycle.state == DEGRADED
    assert engine.stats["breaker_trips"] == 1
    assert engine._breaker.open
    assert all(v.status == "failed" and v.retryable for v in victims)
    after = engine.submit([9, 9], max_new_tokens=8, seed=5)
    engine.run_until_idle()
    assert engine.lifecycle.state == READY  # clean tick closed the breaker
    assert not engine._breaker.open
    assert after.status == "done" and after.tokens == reference([9, 9], 5)


def test_degraded_idle_engine_self_probes_back_to_ready(cfg, params):
    """An idle DEGRADED engine must close its own breaker: a load balancer
    honoring the 503 sends no traffic, so the engine self-probes with an
    empty fused tick instead of staying DEGRADED forever."""
    chaos = ServingChaosMonkey([ServeFault("tick_fault", step=1, duration=3)])
    engine = make_engine(cfg, params, n_slots=1, chaos=chaos)
    for i in range(3):
        engine.submit([i + 1], max_new_tokens=4, seed=i)
    for _ in range(4):  # tick 0 clean, ticks 1-3 faulted -> breaker opens
        engine.step()
    assert engine.lifecycle.state == DEGRADED
    assert engine.queue_depth == 0 and engine.active_count == 0  # starved
    assert engine.step() is False  # the probe tick reports idle...
    assert engine.lifecycle.state == READY  # ...but proved the engine clean
    assert not engine._breaker.open


def test_breaker_escalates_after_max_rebuilds(cfg, params):
    """A fault that survives every rebuild is structural: the supervised
    tick must stop eating it and escalate out of run() so the replica dies
    loudly (bounded recovery, like the training supervisor's restart
    budget)."""
    chaos = ServingChaosMonkey([ServeFault("tick_fault", step=0, duration=10_000)])
    engine = make_engine(
        cfg, params, n_slots=1, chaos=chaos,
        breaker_threshold=2, max_rebuilds=1,
    )
    for i in range(8):
        engine.submit([i + 1], max_new_tokens=4, seed=i)
    with pytest.raises(RuntimeError, match="rebuilds"):
        engine.run(threading.Event())
    # the abort failed everything outstanding and the engine is dead
    assert engine.lifecycle.state == STOPPED
    late = engine.submit([1], max_new_tokens=2)
    assert late.status == "failed"


def test_nan_logits_retire_only_poisoned_slot(cfg, params, reference):
    """NaN logits in slot 0 retire ONLY slot 0 (retryable error); its
    neighbor's trajectory is byte-identical to an undisturbed run — the
    per-tick guard reuses the training anomaly predicate without a second
    host sync."""
    chaos = ServingChaosMonkey(
        [ServeFault("nan_logits", step=2, duration=1, slots=[0])]
    )
    engine = make_engine(cfg, params, n_slots=2, chaos=chaos)
    poisoned = engine.submit([5, 6], max_new_tokens=8, seed=0)
    neighbor = engine.submit([7, 8], max_new_tokens=8, seed=1)
    engine.run_until_idle()
    assert poisoned.status == "failed" and poisoned.retryable
    assert "non-finite" in poisoned.error
    assert 0 < len(poisoned.tokens) < 8  # partial output delivered
    assert neighbor.status == "done"
    assert neighbor.tokens == reference([7, 8], 1)
    assert engine.stats["poisoned_slots"] == 1
    assert engine.stats["tick_faults"] == 0  # guard path, not fault path
    assert engine.lifecycle.state != DEGRADED  # slot-level, not engine-level


# ---------------------------------------------------------------- draining


def test_drain_under_load(cfg, params, reference):
    """begin_drain: the queued request is rejected retryably AT ONCE, new
    submits bounce with Retry-After, the in-flight generation runs to
    completion (byte-identical), then the engine is STOPPED."""
    engine = make_engine(cfg, params, n_slots=1)
    hog = engine.submit([1, 2, 3], max_new_tokens=8, seed=0)
    queued = engine.submit([4, 5], max_new_tokens=4, seed=1)
    engine.step()  # hog admits
    assert engine.begin_drain(deadline_s=60.0)
    assert not engine.begin_drain(deadline_s=60.0)  # idempotent
    assert queued.status == "rejected" and queued.retryable
    assert "draining" in queued.error and queued.retry_after >= 1.0
    late = engine.submit([6], max_new_tokens=2, seed=2)
    assert late.status == "rejected" and late.retryable
    assert engine.stats["rejected_draining"] == 2
    while not engine.poll_drain():
        engine.step()
    assert hog.status == "done" and hog.tokens == reference([1, 2, 3], 0)
    assert engine.lifecycle.state == STOPPED
    assert engine.drain_latency_s is not None
    assert engine.stats["drain_forced"] == 0


def test_drain_deadline_force_finishes(cfg, params):
    """Past the drain deadline the remaining generation is force-finished
    retryably — the process gets to exit instead of hanging on one slow
    request; the handle still reaches a terminal event."""
    clock = FakeClock()
    engine = make_engine(cfg, params, n_slots=1, clock=clock)
    hog = engine.submit([1, 2], max_new_tokens=30, seed=0)
    engine.step()
    engine.begin_drain(deadline_s=5.0)
    engine.step()
    assert not engine.poll_drain()  # deadline not reached, hog still going
    clock.t = 10.0
    assert engine.poll_drain()
    assert hog.status == "failed" and hog.retryable
    assert "drain deadline" in hog.error
    assert engine.stats["drain_forced"] == 1
    assert engine.lifecycle.state == STOPPED
    assert hog.result(timeout=1) == hog.tokens  # no hang


def test_scheduler_thread_drains_and_exits(cfg, params):
    """The run() loop itself completes a drain: scheduler thread exits on
    its own (the serve_forever SIGTERM path rides on this)."""
    engine = make_engine(cfg, params, n_slots=1)
    stop = threading.Event()
    thread = threading.Thread(target=engine.run, args=(stop,), daemon=True)
    thread.start()
    handle = engine.submit([1, 2], max_new_tokens=6, seed=0)
    give_up = time.monotonic() + 30
    while handle.status == "queued" and time.monotonic() < give_up:
        time.sleep(0.005)
    engine.begin_drain(deadline_s=30.0)
    thread.join(timeout=60)
    assert not thread.is_alive()
    assert handle.status == "done" and len(handle.tokens) == 6
    assert engine.lifecycle.state == STOPPED


# --------------------------------------------------------------- hot reload


def test_hot_reload_swaps_without_retiring_slots(cfg, params, params2, reference):
    """Reload mid-generation: the active slot is never retired (its
    generation completes at full length), the swap lands between ticks,
    and post-reload requests decode with the NEW weights."""
    engine = make_engine(cfg, params, n_slots=1)
    mid = engine.submit([1, 2], max_new_tokens=10, seed=0)
    for _ in range(3):
        engine.step()
    assert mid.status == "running"
    engine.reload_params(params2)
    engine.run_until_idle()
    assert mid.status == "done" and len(mid.tokens) == 10  # slot survived
    assert engine.stats["reloads"] == 1
    assert engine.wait_reload(timeout=0.1)
    fresh = engine.submit([5, 6, 7], max_new_tokens=8, seed=3)
    engine.run_until_idle()
    assert fresh.status == "done"
    assert fresh.tokens == reference([5, 6, 7], 3, p=params2)
    assert fresh.tokens != reference([5, 6, 7], 3)  # weights really swapped


def test_reload_rejects_mismatched_and_corrupt(cfg, params, reference):
    """A wrong-model or corrupt artifact raises ReloadError; the engine
    stays READY on the old weights and keeps producing byte-identical
    output."""
    engine = make_engine(cfg, params, n_slots=1)
    stop = threading.Event()
    thread = threading.Thread(target=engine.run, args=(stop,), daemon=True)
    thread.start()
    try:
        with pytest.raises(ReloadError, match="mismatch"):
            engine.reload_params({"bogus": jnp.zeros((2, 2), jnp.float32)})
        wrong_shape = jax.tree.map(lambda x: jnp.zeros((1,) + x.shape, x.dtype), params)
        with pytest.raises(ReloadError, match="mismatch"):
            engine.reload_params(wrong_shape)

        def corrupt_loader():
            raise OSError("truncated msgpack")

        with pytest.raises(ReloadError, match="failed to load"):
            engine.reload_params(corrupt_loader)
        assert engine.stats["reloads_rejected"] == 3
        assert engine.stats["reloads"] == 0
        assert engine.lifecycle.state == READY  # never left
        handle = engine.submit([3, 7, 11], max_new_tokens=8, seed=0)
        assert handle.result(timeout=60) == reference([3, 7, 11], 0)
    finally:
        stop.set()
        thread.join(timeout=30)


def test_chaos_corrupt_reload_artifact_rejected(cfg, params, params2):
    """The chaos corrupt_reload fault mangles a VALID artifact between load
    and validation — the reject path the acceptance bar names."""
    chaos = ServingChaosMonkey([ServeFault("corrupt_reload", step=0)])
    engine = make_engine(cfg, params, n_slots=1, chaos=chaos)
    with pytest.raises(ReloadError, match="mismatch"):
        engine.reload_params(params2)
    assert engine.stats["reloads_rejected"] == 1
    # the fault is one-shot: the retry goes through clean
    engine.reload_params(params2)
    engine.step()
    assert engine.stats["reloads"] == 1


# ------------------------------------------------------------ load shedding


def test_infeasible_deadline_sheds_at_admission(cfg, params):
    """With a measured ITL, a deadline that provably cannot be met is shed
    as a fast retryable rejection instead of expiring mid-queue; feasible
    deadlines still admit."""
    clock = FakeClock()
    engine = make_engine(cfg, params, n_slots=1, clock=clock, shed_warmup=4)
    for _ in range(8):  # seed the EWMA: 0.1 s/token measured
        engine._itl_ewma.update(0.1)
    doomed = engine.submit([1, 2], max_new_tokens=20, seed=0, deadline=1.0)
    assert doomed.status == "rejected" and doomed.retryable
    assert "shed" in doomed.error
    assert engine.stats["shed_infeasible"] == 1
    feasible = engine.submit([1, 2], max_new_tokens=20, seed=0, deadline=100.0)
    assert feasible.status == "queued"
    engine.run_until_idle()
    assert feasible.status == "done"


def test_shed_is_inert_before_warmup(cfg, params):
    """A cold engine has no ITL evidence — nothing sheds, whatever the
    deadline (the guard must be provable, not a guess)."""
    clock = FakeClock()
    engine = make_engine(cfg, params, n_slots=1, clock=clock)
    tight = engine.submit([1], max_new_tokens=20, seed=0, deadline=0.001)
    assert tight.status == "queued"  # admitted; deadline enforcement owns it
    assert engine.stats["shed_infeasible"] == 0


def test_infeasible_deadline_math():
    itl = ItlEwma(decay=0.9, warmup=2)
    assert not infeasible_deadline(1.0, 0.0, 100, 0, 1, itl)  # cold: inert
    itl.update(0.05)
    itl.update(0.05)
    # 100 tokens * 50ms = 5s floor; deadline in 1s is provably infeasible
    assert infeasible_deadline(1.0, 0.0, 100, 0, 1, itl)
    assert not infeasible_deadline(10.0, 0.0, 100, 0, 1, itl)
    # queue depth pushes the bound out
    assert infeasible_deadline(6.0, 0.0, 100, 30, 1, itl)


# ----------------------------------------------------------------- HTTP API


def _get(conn, path):
    conn.request("GET", path)
    resp = conn.getresponse()
    return resp, json.loads(resp.read())


def test_healthz_lifecycle_codes_and_body(cfg, params):
    """503 (not 200) whenever the engine is not READY — starting, draining,
    stopped — with the lifecycle fields in the body."""
    engine = make_engine(cfg, params)
    server = ServingServer(engine, ByteTokenizer(), port=0)
    server.start(start_scheduler=False)
    try:
        conn = http.client.HTTPConnection("127.0.0.1", server.port, timeout=30)
        resp, body = _get(conn, "/healthz")
        assert resp.status == 503 and body["state"] == "starting"
        for key in ("state", "uptime_s", "reloads", "breaker_open"):
            assert key in body, key
        server.start_scheduler()
        give_up = time.monotonic() + 30
        while engine.lifecycle.state != READY and time.monotonic() < give_up:
            time.sleep(0.005)
        resp, body = _get(conn, "/healthz")
        assert resp.status == 200 and body["status"] == "ok"
        assert body["state"] == "ready" and body["breaker_open"] is False
        conn.close()
    finally:
        server.stop()
    # draining answers 503: on a server whose scheduler never runs, the
    # drain can't complete underneath the probe (an IDLE engine drains to
    # STOPPED instantly — also a 503, but a different state string)
    engine2 = make_engine(cfg, params)
    server2 = ServingServer(engine2, ByteTokenizer(), port=0)
    server2.start(start_scheduler=False)
    try:
        engine2.begin_drain(deadline_s=30.0)
        conn = http.client.HTTPConnection("127.0.0.1", server2.port, timeout=30)
        resp, body = _get(conn, "/healthz")
        assert resp.status == 503 and body["state"] == "draining"
        conn.close()
    finally:
        server2.stop()


def test_oversized_body_413(cfg, params):
    engine = make_engine(cfg, params)
    server = ServingServer(engine, ByteTokenizer(), port=0, max_body_bytes=512)
    server.start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", server.port, timeout=30)
        conn.request(
            "POST", "/generate", b'{"prompt": "' + b"x" * 4096 + b'"}',
            {"Content-Type": "application/json"},
        )
        resp = conn.getresponse()
        assert resp.status == 413
        assert "exceeds" in json.loads(resp.read())["error"]
        conn.close()
    finally:
        server.stop()


def test_draining_maps_to_503_with_retry_after(cfg, params):
    # scheduler deliberately NOT started: an idle engine's drain completes
    # instantly (STOPPED -> the dead-engine 503), and this test pins the
    # DRAINING rejection contract specifically
    engine = make_engine(cfg, params)
    server = ServingServer(engine, ByteTokenizer(), port=0)
    server.start(start_scheduler=False)
    try:
        engine.begin_drain(deadline_s=30.0)
        conn = http.client.HTTPConnection("127.0.0.1", server.port, timeout=30)
        conn.request("POST", "/generate", json.dumps({"prompt": "ab"}),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        assert resp.status == 503
        assert int(resp.getheader("Retry-After")) >= 1
        assert "draining" in json.loads(resp.read())["error"]
        conn.close()
    finally:
        server.stop()


def test_admin_reload_endpoint(cfg, params, params2, tmp_path):
    """POST /admin/reload: a good artifact swaps (200, reloads=1) without
    retiring anything; a corrupt artifact is 409 with the engine READY."""
    from zero_transformer_tpu.parallel.sharding import unbox

    good = export_params_msgpack(unbox(params2), tmp_path / "good.msgpack")
    corrupt = tmp_path / "corrupt.msgpack"
    corrupt.write_bytes(good.read_bytes()[: good.stat().st_size // 2])
    engine = make_engine(cfg, params)
    server = ServingServer(engine, ByteTokenizer(), port=0)
    server.start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", server.port, timeout=60)
        conn.request("POST", "/admin/reload",
                     json.dumps({"params": str(good)}),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        body = json.loads(resp.read())
        assert resp.status == 200, body
        assert body["reloaded"] is True and body["reloads"] == 1
        conn.request("POST", "/admin/reload",
                     json.dumps({"params": str(corrupt)}),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        body = json.loads(resp.read())
        assert resp.status == 409
        assert body["state"] == "ready" and body["reloads"] == 1
        resp, health = _get(conn, "/healthz")
        assert resp.status == 200  # still serving on the good weights
        conn.close()
    finally:
        server.stop()


def test_metrics_exports_resilience_counters(cfg, params):
    engine = make_engine(cfg, params)
    engine.submit([1, 2], max_new_tokens=4, seed=0)
    engine.run_until_idle()
    snap = engine.metrics_snapshot()
    for key in (
        "state", "uptime_s", "breaker_open", "itl_ewma_ms",
        "tick_faults", "poisoned_slots", "breaker_trips", "shed_infeasible",
        "rejected_draining", "drain_forced", "reloads", "reloads_rejected",
    ):
        assert key in snap, key


def test_resilience_events_land_in_metrics_timeline(cfg, params, tmp_path):
    """Breaker trips / poisoned slots / reload / drain emit
    MetricsLogger.event() entries — the same JSONL timeline PR 2
    established for training incidents."""
    from zero_transformer_tpu.utils.monitoring import MetricsLogger

    metrics = MetricsLogger(directory=tmp_path)
    chaos = ServingChaosMonkey(
        [ServeFault("nan_logits", step=2, duration=1, slots=[0])]
    )
    engine = make_engine(cfg, params, n_slots=1, chaos=chaos, metrics=metrics)
    engine.submit([1, 2], max_new_tokens=8, seed=0)
    engine.run_until_idle()
    engine.begin_drain(deadline_s=10.0)
    while not engine.poll_drain():
        engine.step()
    metrics.close()
    events = [
        json.loads(line)["event"]
        for line in (tmp_path / "metrics.jsonl").read_text().splitlines()
        if "event" in json.loads(line)
    ]
    assert "poisoned_slots" in events
    assert "drain_begin" in events and "drain_done" in events


# ------------------------------------------------------------- chaos proof


@pytest.mark.chaos
def test_serving_chaos_end_to_end(cfg, params, reference):
    """The acceptance-bar scenario over the real HTTP server: decode faults
    + NaN-logit windows + a mid-load SIGTERM. No in-flight request hangs
    (every handle reaches a terminal event), the server drains and the
    scheduler exits cleanly, and every request untouched by a fault is
    byte-identical to an undisturbed run with the same seed."""
    prompts = [[3 + i, 7, 11 + i] for i in range(10)]
    refs = {i: reference(p, i, max_new=12) for i, p in enumerate(prompts)}

    chaos = ServingChaosMonkey([
        ServeFault("tick_fault", step=8, duration=1),
        ServeFault("nan_logits", step=16, duration=1, slots=[0]),
        ServeFault("sigterm", step=24),
    ])
    engine = make_engine(cfg, params, n_slots=2, chaos=chaos, max_queue=64)
    server = ServingServer(engine, ByteTokenizer(), port=0)
    old_term = signal.getsignal(signal.SIGTERM)
    old_hup = signal.getsignal(signal.SIGHUP)
    server.install_signal_handlers(drain_deadline_s=30.0)
    server.start()
    results = {}
    lock = threading.Lock()

    def client(i):
        conn = http.client.HTTPConnection("127.0.0.1", server.port, timeout=120)
        try:
            conn.request(
                "POST", "/generate",
                json.dumps({"tokens": prompts[i], "max_new_tokens": 12,
                            "seed": i, "stream": False}),
                {"Content-Type": "application/json"},
            )
            resp = conn.getresponse()
            doc = json.loads(resp.read())
            with lock:
                results[i] = (resp.status, doc)
        except Exception as exc:  # connection torn down mid-drain: terminal too
            with lock:
                results[i] = (None, {"status": "connection_error", "error": repr(exc)})
        finally:
            conn.close()

    try:
        threads = [
            threading.Thread(target=client, args=(i,), daemon=True)
            for i in range(len(prompts))
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not any(t.is_alive() for t in threads), "a client hung"

        # SIGTERM fired mid-load -> the handler drained the engine and shut
        # the server down; the scheduler thread must have exited cleanly
        give_up = time.monotonic() + 60
        while engine.lifecycle.state != STOPPED and time.monotonic() < give_up:
            time.sleep(0.02)
        assert engine.lifecycle.state == STOPPED
        server._scheduler.join(timeout=30)
        assert not server._scheduler.is_alive()
        assert engine.active_count == 0 and engine.queue_depth == 0

        assert chaos.fired_log, "no fault fired"
        statuses = [doc.get("status") for _, doc in results.values()]
        completed = [
            i for i, (code, doc) in results.items()
            if code == 200 and doc.get("status") == "done"
        ]
        # every request reached a terminal outcome (done / failed /
        # rejected / connection closed by drain) — none hung, none vanished
        assert len(results) == len(prompts)
        # the byte-identical bar: untouched (completed) requests match the
        # undisturbed run exactly
        assert completed, f"nothing completed: {statuses}"
        for i in completed:
            assert results[i][1]["tokens"] == refs[i], f"request {i} garbled"
    finally:
        server.stop()
        signal.signal(signal.SIGTERM, old_term)
        signal.signal(signal.SIGHUP, old_hup)
