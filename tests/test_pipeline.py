"""GPipe pipeline parallelism on the 8-device mesh.

Capability beyond the reference (SURVEY §2 checklist: PP = none). Exactness
is the contract: the pipelined wavefront must reproduce the plain fused
step's training trajectory bit-for-bit-ish (f32 tolerances), because it is
the same math on a different schedule.
"""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from zero_transformer_tpu.config import MeshConfig, ModelConfig, OptimizerConfig
from zero_transformer_tpu.models import Transformer
from zero_transformer_tpu.parallel import (
    make_mesh,
    make_plan,
    init_train_state,
    make_train_step,
)
from zero_transformer_tpu.parallel.mesh import PIPE_AXIS
from zero_transformer_tpu.parallel.pipeline import bubble_fraction, interleaved_slot
from zero_transformer_tpu.training.optimizer import make_optimizer, make_schedule
from zero_transformer_tpu.utils.jax_compat import HAS_AMBIENT_MESH

# The pipe engines' shard_map programs don't trace/compile on this image's
# pre-ambient-mesh jax (the known old-jax failure set); NEW interleaved
# execution coverage is gated so the set doesn't grow — the schedule's
# dataflow itself is proven everywhere by the concrete-int simulation below.
requires_modern_shard_map = pytest.mark.skipif(
    not HAS_AMBIENT_MESH,
    reason="old-jax shard_map cannot trace the pipeline engine",
)

CFG = ModelConfig(
    name="t", vocab_size=256, d_model=64, n_heads=4, n_layers=4, max_seq_len=32,
    dropout=0.0, compute_dtype="float32",
)
OPT = OptimizerConfig(peak_learning_rate=1e-3, warmup_steps=4, total_steps=64)


def _setup(mesh_cfg, model_cfg=CFG, zero_stage=1, grad_accum_dtype="float32"):
    mesh = make_mesh(mesh_cfg)
    model = Transformer(model_cfg)
    tx = make_optimizer(OPT)
    plan = make_plan(model, tx, mesh, (2, 16), zero_stage)
    state = init_train_state(model, tx, jax.random.PRNGKey(0), mesh, (2, 16), plan)
    step = make_train_step(model, tx, mesh, plan, zero_stage, make_schedule(OPT),
                           pp_schedule=mesh_cfg.pp_schedule,
                           grad_accum_dtype=grad_accum_dtype)
    return mesh, state, step


def _batch(seed=0, accum=4, vocab=256):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.integers(0, vocab, (accum, 8, 16)), jnp.int32)


@pytest.mark.parametrize("mesh_cfg", [
    MeshConfig(pipe=2, data=4),
    MeshConfig(pipe=4, data=2),
])
def test_pp_matches_dp_trajectory(devices, mesh_cfg):
    mesh_pp, s_pp, step_pp = _setup(mesh_cfg)
    mesh_dp, s_dp, step_dp = _setup(MeshConfig())
    rng = jax.random.PRNGKey(7)
    for i in range(3):
        s_pp, mp = step_pp(s_pp, _batch(i), rng)
        s_dp, md = step_dp(s_dp, _batch(i), rng)
    np.testing.assert_allclose(float(mp["loss"]), float(md["loss"]), rtol=2e-4)
    for a, b in zip(jax.tree.leaves(s_pp.params), jax.tree.leaves(s_dp.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=3e-5)


def test_pp_blocks_sharded_over_pipe(devices):
    mesh, state, step = _setup(MeshConfig(pipe=2, data=4))
    wi = state.params["blocks"]["mlp"]["wi"]["kernel"]
    assert "pipe" in str(wi.sharding.spec), wi.sharding.spec
    # each stage holds half the layer stack
    assert wi.addressable_shards[0].data.shape[0] * 2 == wi.shape[0]


def test_pp_untied_head_and_rope(devices):
    cfg = dataclasses.replace(
        CFG, tie_embeddings=False, position="rope", norm="rmsnorm",
        activation="swiglu",
    )
    mesh_pp, s_pp, step_pp = _setup(MeshConfig(pipe=2, data=4), model_cfg=cfg)
    mesh_dp, s_dp, step_dp = _setup(MeshConfig(), model_cfg=cfg)
    rng = jax.random.PRNGKey(3)
    s_pp, mp = step_pp(s_pp, _batch(0), rng)
    s_dp, md = step_dp(s_dp, _batch(0), rng)
    np.testing.assert_allclose(float(mp["loss"]), float(md["loss"]), rtol=2e-4)


@pytest.mark.parametrize("policy", ["none", "qkv_mlp"])
def test_pp_with_remat_matches_dp(devices, policy):
    # the pipeline stage must honor cfg.remat (review finding: it was
    # silently ignored) and stay numerically identical — including under
    # the named-save policy, whose checkpoint_name sites sit inside the
    # scanned stage body under the pipe-manual shard_map (r5: the shared
    # resolve_remat_policy must not degrade to None here)
    cfg = dataclasses.replace(CFG, remat=True, remat_policy=policy)
    mesh_pp, s_pp, step_pp = _setup(MeshConfig(pipe=2, data=4), model_cfg=cfg)
    mesh_dp, s_dp, step_dp = _setup(MeshConfig(), model_cfg=cfg)
    rng = jax.random.PRNGKey(5)
    s_pp, mp = step_pp(s_pp, _batch(0), rng)
    s_dp, md = step_dp(s_dp, _batch(0), rng)
    np.testing.assert_allclose(float(mp["loss"]), float(md["loss"]), rtol=2e-4)


def test_pp_with_moe_trains(devices):
    cfg = dataclasses.replace(CFG, vocab_size=128, n_experts=4, moe_top_k=2)
    mesh, state, step = _setup(
        MeshConfig(pipe=2, data=2, expert=2), model_cfg=cfg
    )
    losses = []
    rng = jax.random.PRNGKey(1)
    batch = _batch(0, vocab=128)
    for _ in range(15):
        state, metrics = step(state, batch, rng)
        losses.append(float(metrics["loss"]))
        assert np.isfinite(losses[-1])
    assert losses[-1] < losses[0] - 0.5, losses


def test_pp_rejects_zero3_and_indivisible(devices):
    mesh = make_mesh(MeshConfig(pipe=2, data=4))
    model = Transformer(CFG)
    tx = make_optimizer(OPT)
    plan = make_plan(model, tx, mesh, (2, 16), 3)
    with pytest.raises(NotImplementedError, match="stage"):
        make_train_step(model, tx, mesh, plan, 3)
    bad = Transformer(dataclasses.replace(CFG, n_layers=3))
    plan3 = make_plan(bad, tx, mesh, (2, 16), 1)
    with pytest.raises(ValueError, match="divisible"):
        make_train_step(bad, tx, mesh, plan3, 1)
    # pipe x tensor: XLA SPMD partitioner crash — must refuse loudly
    mesh_tp = make_mesh(MeshConfig(pipe=2, data=2, tensor=2))
    plan_tp = make_plan(model, tx, mesh_tp, (2, 16), 1)
    with pytest.raises(NotImplementedError, match="tensor"):
        make_train_step(model, tx, mesh_tp, plan_tp, 1)


def test_pp_loss_chunk_matches_dp(devices):
    """Chunked CE through the pipeline engine: the last rank computes its
    loss tile-by-tile (no [b, T, vocab] logits) and the trajectory still
    matches the fused DP step running the same chunked loss."""
    cfg = dataclasses.replace(CFG, loss_chunk=5)
    mesh_pp, s_pp, step_pp = _setup(MeshConfig(pipe=2, data=4), model_cfg=cfg)
    mesh_dp, s_dp, step_dp = _setup(MeshConfig(), model_cfg=cfg)
    rng = jax.random.PRNGKey(7)
    for i in range(2):
        s_pp, mp = step_pp(s_pp, _batch(i), rng)
        s_dp, md = step_dp(s_dp, _batch(i), rng)
    np.testing.assert_allclose(float(mp["loss"]), float(md["loss"]), rtol=2e-4)
    for a, b in zip(jax.tree.leaves(s_pp.params), jax.tree.leaves(s_dp.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=3e-5)


def test_pp_adafactor_zero2_rejected(devices):
    """Adafactor (factored stats) is ZeRO-axis-aware but not pipe-aware:
    pipe x stage>=2 must reject with the reason, not die in an internal
    shard_map assertion (r5 review finding). Stage <= 1 pipe adafactor and
    non-pipe adafactor x ZeRO-2/3 both work."""
    mesh = make_mesh(MeshConfig(pipe=2, data=4))
    model = Transformer(CFG)
    opt_af = dataclasses.replace(OPT, optimizer="adafactor")
    tx = make_optimizer(opt_af)
    plan = make_plan(model, tx, mesh, (2, 16), 2)
    with pytest.raises(NotImplementedError, match="adafactor"):
        make_train_step(
            model, tx, mesh, plan, 2,
            tx_factory=lambda norm_fn, zc=None: make_optimizer(
                opt_af, None, norm_fn, zero_collectives=zc
            ),
        )
    # plain 1-arg factory (un-sharded adafactor) is rejected the same way
    with pytest.raises(NotImplementedError, match="adafactor"):
        make_train_step(model, tx, mesh, plan, 2)


def test_pp_packed_matches_dp_trajectory(devices):
    """Packed-sequence training through the pipeline wavefront: every rank
    derives the microbatch's document ids from the (pipe-replicated) batch,
    so masking and boundary-ignored loss match the fused step exactly."""
    cfg = dataclasses.replace(CFG, doc_sep_token=0)
    mesh_pp, s_pp, step_pp = _setup(MeshConfig(pipe=2, data=4), model_cfg=cfg)
    mesh_dp, s_dp, step_dp = _setup(MeshConfig(), model_cfg=cfg)
    rng = jax.random.PRNGKey(11)
    for i in range(2):
        batch = np.array(_batch(i))  # writable copy
        batch[:, :, 5] = 0  # separators straddling rows: 2+ docs per row
        batch[:, 1::2, 11] = 0
        batch = jnp.asarray(batch)
        s_pp, mp = step_pp(s_pp, batch, rng)
        s_dp, md = step_dp(s_dp, batch, rng)
    np.testing.assert_allclose(float(mp["loss"]), float(md["loss"]), rtol=2e-4)
    for a, b in zip(jax.tree.leaves(s_pp.params), jax.tree.leaves(s_dp.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=3e-5)


def test_pp_zero2_matches_dp_trajectory(devices):
    """Pipe x explicit ZeRO-2 (one shard_map manual over pipe+data: gradient
    psum_scatter, sharded optimizer, param all_gather) follows the same
    training trajectory as plain DP stage 0 — and its compiled HLO contains
    literal reduce-scatters with no gradient-sized all-reduce. Lifts the
    round-3 'pipe caps at ZeRO-1' composition block (VERDICT missing #4)."""
    mesh_pp = make_mesh(MeshConfig(pipe=2, data=4))
    model = Transformer(CFG)
    plan_pp = make_plan(model, make_optimizer(OPT), mesh_pp, (2, 16), 2)
    s_pp = init_train_state(
        model, make_optimizer(OPT), jax.random.PRNGKey(0), mesh_pp, (2, 16), plan_pp
    )
    # shard-aware clip norm, as the trainer wires it (trainer.py tx_factory)
    step_pp = make_train_step(
        model, make_optimizer(OPT), mesh_pp, plan_pp, 2, make_schedule(OPT),
        tx_factory=lambda norm_fn: make_optimizer(OPT, None, norm_fn),
    )
    mesh_dp, s_dp, step_dp = _setup(MeshConfig(), zero_stage=0)

    rng = jax.random.PRNGKey(7)
    for i in range(3):
        s_pp, mp = step_pp(s_pp, _batch(i), rng)
        s_dp, md = step_dp(s_dp, _batch(i), rng)
    np.testing.assert_allclose(float(mp["loss"]), float(md["loss"]), rtol=2e-4)
    # grad_norm must match too: adam + norm-clipping are scale-invariant, so
    # the param trajectory alone cannot catch a constant gradient-scale
    # error (found: differentiating the pipe-psum'd loss inside the manual
    # region scaled every grad by P via the psum transpose)
    np.testing.assert_allclose(
        float(mp["grad_norm"]), float(md["grad_norm"]), rtol=1e-3
    )
    for a, b in zip(jax.tree.leaves(s_pp.params), jax.tree.leaves(s_dp.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=3e-5)

    txt = step_pp.lower(s_pp, _batch(9), rng).compile().as_text()
    assert "reduce-scatter" in txt, "no literal reduce-scatter in pipe ZeRO-2 HLO"


def test_pp_1f1b_matches_dp_trajectory(devices):
    """The 1F1B schedule (hand-placed vjp per tick, O(P) input stash +
    recompute) is the same math as GPipe and the fused step — identical
    training trajectory within float tolerance. Gradient accumulation ORDER
    differs (per-microbatch as backwards complete vs one reverse sweep), so
    exact bitwise equality is not the contract."""
    mesh_pp, s_pp, step_pp = _setup(MeshConfig(pipe=2, data=4, pp_schedule="1f1b"))
    mesh_dp, s_dp, step_dp = _setup(MeshConfig())
    rng = jax.random.PRNGKey(7)
    for i in range(3):
        s_pp, mp = step_pp(s_pp, _batch(i), rng)
        s_dp, md = step_dp(s_dp, _batch(i), rng)
    np.testing.assert_allclose(float(mp["loss"]), float(md["loss"]), rtol=2e-4)
    # scale check, not just direction: clipping+adam hide constant factors
    np.testing.assert_allclose(
        float(mp["grad_norm"]), float(md["grad_norm"]), rtol=1e-3
    )
    for a, b in zip(jax.tree.leaves(s_pp.params), jax.tree.leaves(s_dp.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=3e-5)


def test_pp_1f1b_four_stages_and_remat(devices):
    cfg = dataclasses.replace(CFG, remat=True)
    mesh_pp, s_pp, step_pp = _setup(
        MeshConfig(pipe=4, data=2, pp_schedule="1f1b"), model_cfg=cfg
    )
    mesh_dp, s_dp, step_dp = _setup(MeshConfig(), model_cfg=cfg)
    rng = jax.random.PRNGKey(5)
    s_pp, mp = step_pp(s_pp, _batch(0), rng)
    s_dp, md = step_dp(s_dp, _batch(0), rng)
    np.testing.assert_allclose(float(mp["loss"]), float(md["loss"]), rtol=2e-4)


def test_pp_1f1b_zero2_matches_dp_trajectory(devices):
    """1F1B x explicit ZeRO-2 (round-4 VERDICT weak #3: the composition a
    large-model pipe run on small-HBM chips actually wants — O(P) stash AND
    sharded grads/optimizer). The 1F1B engine's (loss, grads) feed the same
    ZeroCollectives core as GPipe; trajectory, grad_norm (scale check —
    adam+clip hide constant factors), and literal reduce-scatters in the
    compiled HLO are the contract."""
    mesh_pp = make_mesh(MeshConfig(pipe=2, data=4, pp_schedule="1f1b"))
    model = Transformer(CFG)
    plan_pp = make_plan(model, make_optimizer(OPT), mesh_pp, (2, 16), 2)
    s_pp = init_train_state(
        model, make_optimizer(OPT), jax.random.PRNGKey(0), mesh_pp, (2, 16), plan_pp
    )
    step_pp = make_train_step(
        model, make_optimizer(OPT), mesh_pp, plan_pp, 2, make_schedule(OPT),
        tx_factory=lambda norm_fn: make_optimizer(OPT, None, norm_fn),
        pp_schedule="1f1b",
    )
    mesh_dp, s_dp, step_dp = _setup(MeshConfig(), zero_stage=0)

    rng = jax.random.PRNGKey(7)
    for i in range(3):
        s_pp, mp = step_pp(s_pp, _batch(i), rng)
        s_dp, md = step_dp(s_dp, _batch(i), rng)
    np.testing.assert_allclose(float(mp["loss"]), float(md["loss"]), rtol=2e-4)
    np.testing.assert_allclose(
        float(mp["grad_norm"]), float(md["grad_norm"]), rtol=1e-3
    )
    for a, b in zip(jax.tree.leaves(s_pp.params), jax.tree.leaves(s_dp.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=3e-5)

    txt = step_pp.lower(s_pp, _batch(9), rng).compile().as_text()
    assert "reduce-scatter" in txt, "no literal reduce-scatter in 1F1B ZeRO-2 HLO"


def test_pp_1f1b_bf16_accum_matches_f32(devices):
    """grad_accum_dtype=bfloat16 composes with 1F1B (the knob's target
    regime: O(P) stash AND a half-size accumulator carry — the 16 GB
    large-model recipe, see ``zero.py::_accum_add``): trajectory tracks the
    f32-accumulator 1F1B run closely. GPipe's rejection is covered in
    ``test_zero.py::test_grad_accum_dtype_rejections``."""
    pp = MeshConfig(pipe=2, data=4, pp_schedule="1f1b")
    _, s32, step32 = _setup(pp, grad_accum_dtype="float32")
    _, sbf, stepbf = _setup(pp, grad_accum_dtype="bfloat16")
    rng = jax.random.PRNGKey(7)
    for i in range(3):
        s32, m32 = step32(s32, _batch(i), rng)
        sbf, mbf = stepbf(sbf, _batch(i), rng)
    np.testing.assert_allclose(float(mbf["loss"]), float(m32["loss"]), rtol=5e-3)
    for a, b in zip(jax.tree.leaves(sbf.params), jax.tree.leaves(s32.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-3)


# ------------------------------------------------------ interleaved schedule


def test_interleaved_slot_dataflow():
    """Prove the interleaved schedule's index arithmetic by simulating the
    ring with symbolic values: every valid (rank, tick) consuming chunk
    v > 0 of microbatch m must find EXACTLY chunk v-1's output in its inbox
    (invalid ticks produce garbage, as the real engine's clipped compute
    does — stale-but-right values can't mask a schedule bug), and every
    microbatch must retire through the final stage. This is the same
    ``interleaved_slot`` the traced engine runs, on concrete ints."""
    for P in (2, 4):
        for V in (2, 4):
            for M in (P, 2 * P, 4 * P):
                outbox = [("init", r) for r in range(P)]
                done = []
                for t in range(V * M + P - 1):
                    inbox = [outbox[(r - 1) % P] for r in range(P)]
                    new_out = [None] * P
                    for r in range(P):
                        valid, mb, v, chunk, first, final = (
                            x if isinstance(x, bool) else int(x)
                            for x in interleaved_slot(t, r, P, V, M)
                        )
                        if not valid:
                            new_out[r] = ("garbage", t, r)
                            continue
                        if not first:
                            assert inbox[r] == ("h", mb, chunk - 1), (
                                P, V, M, t, r, inbox[r], (mb, chunk),
                            )
                        new_out[r] = ("h", mb, chunk)
                        if final:
                            assert chunk == P * V - 1
                            done.append(mb)
                    outbox = new_out
                # final stage retires microbatches in order, all of them
                assert done == list(range(M)), (P, V, M, done)


def test_bubble_fraction_formulas():
    """The ONE analytic bubble formula (trainer gauge, memory_analysis, and
    the step bench all read this function — they must never disagree)."""
    assert bubble_fraction("gpipe", 4, 16) == pytest.approx(3 / 19)
    assert bubble_fraction("1f1b", 4, 16) == pytest.approx(6 / 22)
    assert bubble_fraction("interleaved", 4, 16, 2) == pytest.approx(3 / 35)
    assert bubble_fraction("interleaved", 4, 16, 4) == pytest.approx(3 / 67)
    # no pipe axis -> no bubble
    assert bubble_fraction("gpipe", 1, 16) == 0.0
    # deeper interleave monotonically shrinks the bubble
    fr = [bubble_fraction("interleaved", 8, 16, v) for v in (1, 2, 4)]
    assert fr[0] > fr[1] > fr[2]
    with pytest.raises(ValueError, match="pp_schedule"):
        bubble_fraction("zigzag", 4, 16)


def test_interleaved_config_validation():
    with pytest.raises(ValueError, match="pp_interleave"):
        MeshConfig(pipe=2, data=4, pp_schedule="interleaved", pp_interleave=0)
    with pytest.raises(ValueError, match="only applies"):
        MeshConfig(pipe=2, data=4, pp_schedule="gpipe", pp_interleave=2)
    with pytest.raises(ValueError, match="exactly gpipe"):
        MeshConfig(pipe=2, data=4, pp_schedule="interleaved", pp_interleave=1)
    with pytest.raises(ValueError, match="pipe > 1"):
        MeshConfig(pp_schedule="interleaved", pp_interleave=2)
    MeshConfig(pipe=2, data=4, pp_schedule="interleaved", pp_interleave=2)


def test_interleaved_plan_blocks_replicated(devices):
    """Interleaved stores the block stack pipe-REPLICATED (a rank's virtual
    chunks are a round-robin set no contiguous shard holds); gpipe keeps
    the contiguous pipe shard. The engine refuses a plan/schedule mismatch
    at build time, before any tracing."""
    mesh = make_mesh(MeshConfig(pipe=2, data=4))
    model = Transformer(CFG)
    tx = make_optimizer(OPT)
    plan_il = make_plan(model, tx, mesh, (2, 16), 1, pp_schedule="interleaved")
    plan_gp = make_plan(model, tx, mesh, (2, 16), 1, pp_schedule="gpipe")
    il_specs = [
        str(ns.spec) for ns in jax.tree.leaves(plan_il.state.params["blocks"])
    ]
    gp_specs = [
        str(ns.spec) for ns in jax.tree.leaves(plan_gp.state.params["blocks"])
    ]
    assert not any("pipe" in s for s in il_specs), il_specs
    assert all("pipe" in s for s in gp_specs), gp_specs
    # non-blocks leaves keep their layout either way
    assert str(
        jax.tree.leaves(plan_il.state.params["wte"])[0].spec
    ) == str(jax.tree.leaves(plan_gp.state.params["wte"])[0].spec)

    with pytest.raises(ValueError, match="pipe-REPLICATED"):
        make_train_step(
            model, tx, mesh, plan_gp, 1, make_schedule(OPT),
            pp_schedule="interleaved", pp_interleave=2,
        )
    with pytest.raises(ValueError, match="pipe-replicated"):
        make_train_step(
            model, tx, mesh, plan_il, 1, make_schedule(OPT),
            pp_schedule="gpipe",
        )


def test_interleaved_build_validation(devices):
    mesh = make_mesh(MeshConfig(pipe=2, data=4))
    tx = make_optimizer(OPT)
    model = Transformer(CFG)
    plan = make_plan(model, tx, mesh, (2, 16), 1, pp_schedule="interleaved")
    with pytest.raises(ValueError, match="pp_interleave >= 2"):
        make_train_step(
            model, tx, mesh, plan, 1, make_schedule(OPT),
            pp_schedule="interleaved", pp_interleave=1,
        )
    with pytest.raises(ValueError, match="only applies"):
        make_train_step(
            model, tx, mesh, plan, 1, make_schedule(OPT),
            pp_schedule="gpipe", pp_interleave=2,
        )
    # n_layers=4 over pipe*V = 2*4 = 8 virtual stages: indivisible
    with pytest.raises(ValueError, match="divisible"):
        make_train_step(
            model, tx, mesh, plan, 1, make_schedule(OPT),
            pp_schedule="interleaved", pp_interleave=4,
        )


def _setup_interleaved(pp_interleave=2, zero_stage=1):
    mesh_cfg = MeshConfig(
        pipe=2, data=4, pp_schedule="interleaved", pp_interleave=pp_interleave,
        zero_stage=zero_stage,
    )
    mesh = make_mesh(mesh_cfg)
    model = Transformer(CFG)
    tx = make_optimizer(OPT)
    plan = make_plan(
        model, tx, mesh, (2, 16), zero_stage, pp_schedule="interleaved"
    )
    state = init_train_state(model, tx, jax.random.PRNGKey(0), mesh, (2, 16), plan)
    step = make_train_step(
        model, tx, mesh, plan, zero_stage, make_schedule(OPT),
        pp_schedule="interleaved", pp_interleave=pp_interleave,
    )
    return mesh, state, step


@requires_modern_shard_map
def test_pp_interleaved_matches_gpipe_and_dp(devices):
    """Interleaved runs the same per-layer math on a different wavefront:
    the trajectory must track GPipe and plain DP at the suite's pipeline
    tolerances (same fixed seed, same batches)."""
    _, s_il, step_il = _setup_interleaved()
    _, s_gp, step_gp = _setup(MeshConfig(pipe=2, data=4))
    _, s_dp, step_dp = _setup(MeshConfig())
    rng = jax.random.PRNGKey(7)
    for i in range(3):
        s_il, mi = step_il(s_il, _batch(i), rng)
        s_gp, mg = step_gp(s_gp, _batch(i), rng)
        s_dp, md = step_dp(s_dp, _batch(i), rng)
    np.testing.assert_allclose(float(mi["loss"]), float(mg["loss"]), rtol=2e-4)
    np.testing.assert_allclose(float(mi["loss"]), float(md["loss"]), rtol=2e-4)
    for a, b in zip(jax.tree.leaves(s_il.params), jax.tree.leaves(s_dp.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=3e-5)


@requires_modern_shard_map
def test_pp_interleaved_zero2_matches_dp(devices):
    _, s_il, step_il = _setup_interleaved(zero_stage=2)
    _, s_dp, step_dp = _setup(MeshConfig(), zero_stage=2)
    rng = jax.random.PRNGKey(7)
    for i in range(3):
        s_il, mi = step_il(s_il, _batch(i), rng)
        s_dp, md = step_dp(s_dp, _batch(i), rng)
    np.testing.assert_allclose(float(mi["loss"]), float(md["loss"]), rtol=2e-4)
    for a, b in zip(jax.tree.leaves(s_il.params), jax.tree.leaves(s_dp.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=3e-5)


@requires_modern_shard_map
def test_pp_interleaved_rejects_indivisible_microbatches(devices):
    """M % P != 0 breaks the just-in-time wrap-around hop — refused when
    the wavefront traces, not silently mis-scheduled."""
    _, state, step = _setup_interleaved()
    with pytest.raises(ValueError, match="divisible by pipe"):
        step(state, _batch(0, accum=3), jax.random.PRNGKey(7))
