"""Inference tests: sampling processors + KV-cached generation.

Counterpart of the reference's torch-side tests
(``torch_compatability/test_torch_models.py:42-160``: forward shapes, KV-cache
growth) plus the decode-equals-full-forward check its Flax side never had.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from zero_transformer_tpu.config import ModelConfig
from zero_transformer_tpu.inference import (
    SamplingConfig,
    apply_repetition_penalty,
    decode_model,
    generate,
    init_cache,
    prefill,
    sample_token,
    top_k_filter,
    top_p_filter,
)
from zero_transformer_tpu.models import Transformer

CFG = ModelConfig(
    name="t", vocab_size=64, d_model=32, n_heads=4, n_layers=2, max_seq_len=32,
    dropout=0.0, compute_dtype="float32",
)


# -- logit processors ---------------------------------------------------------


def test_top_k_keeps_k():
    logits = jnp.asarray([[1.0, 5.0, 3.0, 2.0, 4.0]])
    out = top_k_filter(logits, 2)
    assert (out > -1e9).sum() == 2
    assert float(out[0, 1]) == 5.0 and float(out[0, 4]) == 4.0


def test_top_k_disabled():
    logits = jnp.asarray([[1.0, 5.0, 3.0]])
    np.testing.assert_array_equal(top_k_filter(logits, 0), logits)
    np.testing.assert_array_equal(top_k_filter(logits, 3), logits)


def test_top_k_approx_is_softer_never_harder():
    """The approx arm (lax.approx_max_k partial-reduce) thresholds at the
    approximate k-th value, which is <= the exact one: every token the
    exact filter keeps must survive the approx filter, and the approx kept
    set may only be wider — never narrower.

    Honesty note: on CPU (where this suite runs) approx_max_k falls back
    to the exact sort, so here the assertions pin the PLUMBING (the impl
    switch routes, kept values pass through, superset trivially holds).
    The approximate-cutoff behavior itself only diverges on TPU, where the
    same superset property is a theorem (the min of k returned true values
    is <= the exact k-th value) rather than something this test can
    falsify."""
    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.normal(size=(4, 4096)).astype(np.float32))
    k = 40
    exact = top_k_filter(logits, k)
    approx = top_k_filter(logits, k, impl="approx")
    exact_kept = np.asarray(exact) > -1e9
    approx_kept = np.asarray(approx) > -1e9
    assert (approx_kept >= exact_kept).all(), "approx filter dropped a true top-k token"
    # kept values pass through unchanged (only the cutoff differs)
    np.testing.assert_array_equal(
        np.asarray(approx)[approx_kept], np.asarray(logits)[approx_kept]
    )
    # sanity: the widening is bounded in practice (recall target ~0.95)
    assert approx_kept.sum() <= 4 * 3 * k


def test_sampling_config_rejects_bad_top_k_impl():
    import pytest as _pytest

    from zero_transformer_tpu.inference.sampling import SamplingConfig

    with _pytest.raises(ValueError):
        SamplingConfig(top_k_impl="fast")


def test_top_p_keeps_nucleus():
    # probs ~ [0.64, 0.24, 0.09, 0.03]; p=0.7 keeps the first two (first token
    # always kept, second kept because cumulative mass before it is < p)
    logits = jnp.log(jnp.asarray([[0.64, 0.24, 0.09, 0.03]]))
    out = top_p_filter(logits, 0.7)
    kept = out > -1e9
    np.testing.assert_array_equal(kept, [[True, True, False, False]])


def test_top_p_always_keeps_top1():
    logits = jnp.log(jnp.asarray([[0.97, 0.01, 0.01, 0.01]]))
    out = top_p_filter(logits, 0.5)
    assert bool(out[0, 0] > -1e9)


def test_repetition_penalty_signs():
    logits = jnp.asarray([[2.0, -2.0, 1.0]])
    mask = jnp.asarray([[True, True, False]])
    out = apply_repetition_penalty(logits, mask, 2.0)
    np.testing.assert_allclose(out, [[1.0, -4.0, 1.0]])


def test_greedy_sampling_is_argmax():
    logits = jnp.asarray([[0.1, 3.0, 0.2], [5.0, 0.0, 0.1]])
    tok = sample_token(jax.random.PRNGKey(0), logits, SamplingConfig(greedy=True))
    np.testing.assert_array_equal(tok, [1, 0])


def test_categorical_respects_filter():
    logits = jnp.asarray([[0.0, 10.0, 0.1, 0.2]])
    cfg = SamplingConfig(top_k=1)
    toks = [
        int(sample_token(jax.random.PRNGKey(i), logits, cfg)[0]) for i in range(8)
    ]
    assert set(toks) == {1}


# -- KV-cache decode ----------------------------------------------------------


def _params(model, B=1, T=8):
    return model.init(jax.random.PRNGKey(0), jnp.zeros((B, T), jnp.int32))["params"]


@pytest.mark.parametrize("position", ["alibi", "rope", "learned"])
def test_cached_decode_matches_full_forward(position):
    """Prefill + per-token cached decode must reproduce the uncached forward
    logits at every position (the invariant behind the reference's KV cache,
    ``GPT2.py:175-245``)."""
    import dataclasses

    cfg = dataclasses.replace(CFG, position=position)
    full = Transformer(cfg)
    dec = decode_model(cfg, cache_len=16)
    B, T = 2, 10
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.integers(0, cfg.vocab_size, (B, T)), jnp.int32)
    params = _params(full, B, T)

    ref_logits = full.apply({"params": params}, x)  # [B, T, V]

    cache = init_cache(dec, B)
    last, cache = prefill(dec, params, x[:, :4], cache)
    np.testing.assert_allclose(last, ref_logits[:, 3], atol=1e-4, rtol=1e-4)
    for t in range(4, T):
        logits, vars_out = dec.apply(
            {"params": params, "cache": cache}, x[:, t : t + 1], mutable=["cache"]
        )
        cache = vars_out["cache"]
        np.testing.assert_allclose(
            logits[:, 0], ref_logits[:, t], atol=1e-4, rtol=1e-4,
            err_msg=f"position {t}",
        )


def test_cached_decode_matches_full_forward_moe():
    """KV-cache decode through MoE blocks: per-token routing (T=1, capacity
    1) must reproduce the full forward exactly when the full forward drops
    nothing — capacity_factor >= n_experts/top_k guarantees that (worst case
    a single expert receives every token once)."""
    import dataclasses

    cfg = dataclasses.replace(
        CFG, n_experts=4, moe_top_k=2, capacity_factor=2.0
    )
    full = Transformer(cfg)
    dec = decode_model(cfg, cache_len=16)
    B, T = 2, 10
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.integers(0, cfg.vocab_size, (B, T)), jnp.int32)
    params = _params(full, B, T)

    ref_logits = full.apply({"params": params}, x)

    cache = init_cache(dec, B)
    last, cache = prefill(dec, params, x[:, :4], cache)
    np.testing.assert_allclose(last, ref_logits[:, 3], atol=1e-4, rtol=1e-4)
    for t in range(4, T):
        logits, vars_out = dec.apply(
            {"params": params, "cache": cache}, x[:, t : t + 1], mutable=["cache"]
        )
        cache = vars_out["cache"]
        np.testing.assert_allclose(
            logits[:, 0], ref_logits[:, t], atol=1e-4, rtol=1e-4,
            err_msg=f"position {t}",
        )


def test_generate_greedy_matches_manual_loop():
    model = decode_model(CFG, cache_len=24)
    full = Transformer(CFG)
    params = _params(full)
    prompt = jnp.asarray([[5, 9, 11]], jnp.int32)
    out = generate(
        model, params, prompt, 6, jax.random.PRNGKey(0),
        SamplingConfig(greedy=True),
    )
    assert out.shape == (1, 6)

    # manual uncached argmax loop
    seq = prompt
    expect = []
    for _ in range(6):
        logits = full.apply({"params": params}, seq)
        nxt = int(jnp.argmax(logits[0, -1]))
        expect.append(nxt)
        seq = jnp.concatenate([seq, jnp.asarray([[nxt]], jnp.int32)], axis=1)
    np.testing.assert_array_equal(out[0], expect)


def test_generate_eos_stops_and_pads():
    model = decode_model(CFG, cache_len=40)
    full = Transformer(CFG)
    params = _params(full)
    prompt = jnp.asarray([[5, 9, 11]], jnp.int32)
    base = generate(
        model, params, prompt, 8, jax.random.PRNGKey(0), SamplingConfig(greedy=True)
    )
    eos = int(base[0, 2])  # pretend this generated token is EOS
    first = int(np.argmax(np.asarray(base[0]) == eos))  # first occurrence
    out = generate(
        model, params, prompt, 8, jax.random.PRNGKey(0),
        SamplingConfig(greedy=True), eos_token_id=eos, pad_token_id=63,
    )
    np.testing.assert_array_equal(out[0, : first + 1], base[0, : first + 1])
    np.testing.assert_array_equal(out[0, first + 1 :], [63] * (7 - first))


def test_generate_batched():
    model = decode_model(CFG, cache_len=24)
    full = Transformer(CFG)
    params = _params(full, B=2)
    prompt = jnp.asarray([[5, 9, 11], [3, 2, 1]], jnp.int32)
    out = generate(
        model, params, prompt, 5, jax.random.PRNGKey(1), SamplingConfig(greedy=True)
    )
    # each row equals its own single-row generation
    for b in range(2):
        row = generate(
            model, params, prompt[b : b + 1], 5, jax.random.PRNGKey(1),
            SamplingConfig(greedy=True),
        )
        np.testing.assert_array_equal(out[b], row[0])


def test_generate_overflow_rejected():
    model = decode_model(CFG, cache_len=8)
    full = Transformer(CFG)
    params = _params(full)
    with pytest.raises(ValueError):
        generate(
            model, params, jnp.zeros((1, 6), jnp.int32), 6, jax.random.PRNGKey(0)
        )


# -- int8 KV cache ------------------------------------------------------------


def test_int8_kv_cache_decode_close_to_full_forward():
    """kv_cache_dtype=int8: prefill + cached decode tracks the uncached
    forward logits within quantization tolerance, the cache variables really
    store int8 + f32 scales, and dequantized K/V stay within the int8 grid's
    error bound of the exact values."""
    import dataclasses

    cfg = dataclasses.replace(CFG, kv_cache_dtype="int8")
    full = Transformer(dataclasses.replace(CFG))
    dec = decode_model(cfg, cache_len=16)
    B, T = 2, 10
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.integers(0, cfg.vocab_size, (B, T)), jnp.int32)
    params = _params(full, B, T)

    ref_logits = full.apply({"params": params}, x)

    cache = init_cache(dec, B)
    jax.tree.map(lambda _: None, cache)  # structure sanity
    last, cache = prefill(dec, params, x[:, :4], cache)
    # one layer's cache leaves: int8 values + f32 scales
    leaves = jax.tree.leaves(cache)
    assert any(l.dtype == jnp.int8 for l in leaves)
    # scan_layers stacks a leading layer axis, so scale leaves are >=4-D
    assert any(l.dtype == jnp.float32 and l.ndim >= 4 and l.shape[-1] == 1 for l in leaves)

    np.testing.assert_allclose(last, ref_logits[:, 3], atol=0.08, rtol=0.05)
    for t in range(4, T):
        logits, vars_out = dec.apply(
            {"params": params, "cache": cache}, x[:, t : t + 1], mutable=["cache"]
        )
        cache = vars_out["cache"]
        np.testing.assert_allclose(
            logits[:, 0], ref_logits[:, t], atol=0.08, rtol=0.05,
            err_msg=f"position {t}",
        )
    # greedy tokens agree between int8 and full-precision decode
    out_q = generate(dec, params, x[:, :4], 6, jax.random.PRNGKey(1),
                     SamplingConfig(greedy=True))
    dec_fp = decode_model(CFG, cache_len=16)
    out_fp = generate(dec_fp, params, x[:, :4], 6, jax.random.PRNGKey(1),
                      SamplingConfig(greedy=True))
    assert int((out_q == out_fp).sum()) >= 4  # near-argmax ties may flip


def test_quantize_kv_roundtrip_bound():
    from zero_transformer_tpu.models.gpt import _quantize_kv

    x = jax.random.normal(jax.random.PRNGKey(0), (2, 5, 3, 16)) * 3.0
    q, scale = _quantize_kv(x)
    assert q.dtype == jnp.int8 and scale.dtype == jnp.float32
    deq = q.astype(jnp.float32) * scale
    # symmetric round-to-nearest: |err| <= scale/2 elementwise
    assert bool(jnp.all(jnp.abs(deq - x) <= scale / 2 + 1e-7))
    # zeros stay exactly zero
    qz, sz = _quantize_kv(jnp.zeros((1, 2, 1, 8)))
    assert bool(jnp.all(qz == 0)) and bool(jnp.all(qz.astype(jnp.float32) * sz == 0))


# -- tensor-parallel serving --------------------------------------------------


def test_tp2_decode_matches_single_device(devices):
    """TP=2 decode (serve_mesh + shard_for_inference) produces the same
    greedy tokens as plain single-device decode — serving can scale past one
    chip's HBM without changing outputs (round-3 VERDICT missing #5: the
    llama3_8b zoo entry could be plan-tested but never served). Greedy
    sampling so the check is on argmax identity; logits are also compared
    within float tolerance."""
    from zero_transformer_tpu.inference import serve_mesh, shard_for_inference

    model = decode_model(CFG, 32)
    prompt = jnp.asarray(
        np.random.default_rng(3).integers(0, CFG.vocab_size, (2, 8)), jnp.int32
    )
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((2, 8), jnp.int32))["params"]
    greedy = SamplingConfig(greedy=True)

    out_single = generate(model, params, prompt, 12, jax.random.PRNGKey(1), greedy)

    mesh = serve_mesh(2)
    sharded = shard_for_inference(model, params, mesh)
    # params really are distributed: each kv/mlp kernel leaf lives on 2 devices
    n_sharded = sum(
        1 for l in jax.tree.leaves(sharded) if len(l.sharding.device_set) == 2
    )
    assert n_sharded > 0, "no param was tensor-sharded"
    out_tp = generate(
        model, sharded, prompt, 12, jax.random.PRNGKey(1), greedy, mesh=mesh
    )
    np.testing.assert_array_equal(np.asarray(out_single), np.asarray(out_tp))


def test_tp_kv_cache_indivisible_warns(devices):
    """ADVICE r4: tp>1 with a KV-head count not divisible by tensor leaves
    the cache replicated while params are sharded — the HBM win quietly
    disappears unless init_cache makes the mismatch visible."""
    import dataclasses
    import warnings

    from zero_transformer_tpu.inference import serve_mesh

    # GQA with 3 KV heads on a tensor=2 mesh: 3 % 2 != 0
    cfg = dataclasses.replace(CFG, d_model=48, n_heads=6, n_kv_heads=3)
    model = decode_model(cfg, 32)
    mesh = serve_mesh(2)
    with pytest.warns(UserWarning, match="REPLICATED"):
        init_cache(model, 2, mesh=mesh)
    # divisible KV heads: no warning, and the K/V buffers really shard on
    # the KV-heads dim (dim -2 — under the scanned layer stack the leaves
    # are 5-D and indexing from the front used to shard the sequence dim)
    cfg_ok = dataclasses.replace(CFG, n_heads=4, n_kv_heads=2)
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        cache = init_cache(decode_model(cfg_ok, 32), 2, mesh=mesh)
    from zero_transformer_tpu.parallel.mesh import TENSOR_AXIS

    def kv_entries(tree):
        return [
            (p, l) for p, l in jax.tree_util.tree_leaves_with_path(tree)
            if str(p[-1].key).startswith("cached_")
        ]

    assert kv_entries(cache), "no KV buffers found in the cache tree"
    for path, leaf in kv_entries(cache):
        spec = leaf.sharding.spec
        assert spec[len(spec) - 2] == TENSOR_AXIS, (path, spec)
        assert len(leaf.sharding.device_set) == 2, path


def test_tp2_prefill_logits_close(devices):
    """TP=2 prefill logits match single-device within float tolerance (the
    reductions are reordered across chips, so bitwise equality is not the
    contract — argmax identity above is)."""
    from zero_transformer_tpu.inference import (
        init_cache,
        serve_mesh,
        shard_for_inference,
    )

    model = decode_model(CFG, 32)
    prompt = jnp.asarray(
        np.random.default_rng(5).integers(0, CFG.vocab_size, (2, 8)), jnp.int32
    )
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((2, 8), jnp.int32))["params"]
    logits_single, _ = prefill(model, params, prompt, init_cache(model, 2))

    mesh = serve_mesh(2)
    sharded = shard_for_inference(model, params, mesh)
    from zero_transformer_tpu.utils.jax_compat import set_mesh

    with set_mesh(mesh):
        logits_tp, _ = prefill(
            model, sharded, prompt, init_cache(model, 2, mesh=mesh)
        )
    np.testing.assert_allclose(
        np.asarray(logits_single), np.asarray(logits_tp), rtol=1e-5, atol=1e-5
    )
