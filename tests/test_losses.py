"""Loss unit tests (counterpart of reference ``tests/test_utils.py``)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from zero_transformer_tpu.ops.losses import (
    chunked_next_token_loss,
    cross_entropy_loss,
    next_token_loss,
    token_log_likelihood,
)


@pytest.mark.parametrize("chunk", [3, 7, 15, 64])
@pytest.mark.parametrize("ignore", [None, -1])
def test_chunked_loss_matches_full(chunk, ignore):
    """chunked_next_token_loss == next_token_loss(h @ w, ...) in value AND
    gradients (wrt hidden and the projection), across chunk sizes that do
    and don't divide T-1 (the pad path) and with/without ignored labels."""
    rng = np.random.default_rng(0)
    B, T, D, V = 2, 16, 8, 32
    h = jnp.asarray(rng.normal(size=(B, T, D)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(D, V)) * 0.2, jnp.float32)
    tokens = np.asarray(rng.integers(0, V, (B, T)), np.int32)
    if ignore is not None:
        tokens[:, 5] = ignore  # ignored labels scattered mid-sequence
        tokens[0, 9] = ignore
    tokens = jnp.asarray(tokens)

    def full(h, w):
        return next_token_loss(h @ w, tokens, ignore_index=ignore)

    def chunked(h, w):
        return chunked_next_token_loss(
            h, w, tokens, chunk, ignore_index=ignore
        )

    lf, (gh_f, gw_f) = jax.value_and_grad(full, argnums=(0, 1))(h, w)
    lc, (gh_c, gw_c) = jax.value_and_grad(chunked, argnums=(0, 1))(h, w)
    np.testing.assert_allclose(float(lc), float(lf), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(gh_c), np.asarray(gh_f), atol=1e-6)
    np.testing.assert_allclose(np.asarray(gw_c), np.asarray(gw_f), atol=1e-6)


def test_chunked_loss_z_loss_and_bf16():
    rng = np.random.default_rng(3)
    B, T, D, V = 2, 9, 8, 16
    h = jnp.asarray(rng.normal(size=(B, T, D)), jnp.bfloat16)
    w = jnp.asarray(rng.normal(size=(D, V)) * 0.2, jnp.bfloat16)
    tokens = jnp.asarray(rng.integers(0, V, (B, T)), jnp.int32)
    full = next_token_loss((h @ w), tokens, z_loss=1e-3)
    chunkd = chunked_next_token_loss(h, w, tokens, 4, z_loss=1e-3)
    assert chunkd.dtype == jnp.float32
    np.testing.assert_allclose(float(chunkd), float(full), rtol=1e-5)


def test_output_is_f32_even_for_bf16_logits():
    # the reference's core dtype guarantee (reference losses.py:22, logs/580.md:94-106)
    logits = jnp.zeros((4, 8, 16), jnp.bfloat16)
    labels = jnp.zeros((4, 8), jnp.int32)
    loss = cross_entropy_loss(logits, labels)
    assert loss.dtype == jnp.float32


def test_uniform_logits_golden_value():
    vocab = 64
    logits = jnp.zeros((2, 8, vocab))
    labels = jnp.ones((2, 8), jnp.int32)
    loss = cross_entropy_loss(logits, labels)
    np.testing.assert_allclose(loss, np.log(vocab), rtol=1e-6)


def test_matches_one_hot_formulation():
    # numerical parity with the reference's one-hot matmul formulation
    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.normal(size=(5, 7, 33)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, 33, size=(5, 7)), jnp.int32)
    ours = cross_entropy_loss(logits, labels)
    one_hot = jax.nn.one_hot(labels, 33)
    ref = -jnp.mean(jnp.sum(one_hot * jax.nn.log_softmax(logits.astype(jnp.float32)), -1))
    np.testing.assert_allclose(ours, ref, rtol=1e-6)


def test_ignore_index_masks_padding():
    logits = jnp.zeros((1, 4, 8))
    labels = jnp.asarray([[1, 2, -1, -1]], jnp.int32)
    # set a large logit at the ignored positions' labels — must not matter
    masked = cross_entropy_loss(logits, jnp.where(labels == -1, 0, labels), ignore_index=None)
    loss = cross_entropy_loss(logits, labels.clip(0), ignore_index=None)
    np.testing.assert_allclose(masked, loss)
    loss_ignored = cross_entropy_loss(logits, labels, ignore_index=-1)
    np.testing.assert_allclose(loss_ignored, np.log(8), rtol=1e-6)


def test_z_loss_adds_logz_penalty():
    logits = jnp.ones((2, 3, 10)) * 2.0
    labels = jnp.zeros((2, 3), jnp.int32)
    base = cross_entropy_loss(logits, labels)
    with_z = cross_entropy_loss(logits, labels, z_loss=1e-2)
    lse = 2.0 + np.log(10)
    np.testing.assert_allclose(with_z - base, 1e-2 * lse**2, rtol=1e-4)


def test_next_token_loss_shifts():
    vocab = 11
    tokens = jnp.asarray([[3, 5, 7, 9]], jnp.int32)
    # logits that put all mass on the correct next token -> loss ~ 0
    logits = jax.nn.one_hot(jnp.asarray([[5, 7, 9, 0]], jnp.int32), vocab) * 100.0
    loss = next_token_loss(logits, tokens)
    assert loss < 1e-3


def test_token_log_likelihood_greedy_flags():
    vocab = 6
    tokens = jnp.asarray([[1, 2, 3]], jnp.int32)
    logits = jax.nn.one_hot(jnp.asarray([[2, 0, 5]], jnp.int32), vocab) * 10.0
    ll, greedy = token_log_likelihood(logits, tokens)
    assert ll.shape == (1, 2) and greedy.shape == (1, 2)
    assert bool(greedy[0, 0]) is True  # predicted 2, target 2
    assert bool(greedy[0, 1]) is False  # predicted 0, target 3


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16, jnp.float16])
def test_all_dtypes_finite(dtype):
    rng = np.random.default_rng(1)
    logits = jnp.asarray(rng.normal(size=(2, 4, 32)) * 10, dtype)
    labels = jnp.asarray(rng.integers(0, 32, size=(2, 4)), jnp.int32)
    assert bool(jnp.isfinite(cross_entropy_loss(logits, labels)))
