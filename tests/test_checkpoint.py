"""Checkpoint tests: sharded round-trip, partial (warm-init) restore, msgpack.

The reference's restore is hand-coupled to its optax chain and untested
(``main_zero.py:105-139``); these tests pin the new structure-agnostic
restore on a real 8-device mesh.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from zero_transformer_tpu import checkpoint as ckpt_lib
from zero_transformer_tpu.config import MeshConfig, ModelConfig, OptimizerConfig
from zero_transformer_tpu.models.gpt import Transformer
from zero_transformer_tpu.parallel.mesh import make_mesh
from zero_transformer_tpu.parallel.zero import init_train_state, make_plan
from zero_transformer_tpu.training.optimizer import make_optimizer

CFG = ModelConfig(vocab_size=256, d_model=64, n_heads=4, n_layers=2,
                  max_seq_len=16, dropout=0.0)
SHAPE = (8, 16)


@pytest.fixture(scope="module")
def setup(devices):
    mesh = make_mesh(MeshConfig(zero_stage=1), devices=devices)
    model = Transformer(CFG)
    tx = make_optimizer(OptimizerConfig(warmup_steps=5, total_steps=50))
    plan = make_plan(model, tx, mesh, SHAPE, zero_stage=1)
    state = init_train_state(model, tx, jax.random.PRNGKey(0), mesh, SHAPE, plan)
    return mesh, model, tx, plan, state


def tree_allclose(a, b):
    flat_a, flat_b = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(flat_a) == len(flat_b)
    for x, y in zip(flat_a, flat_b):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), rtol=1e-6)


def test_roundtrip_preserves_values_and_shardings(setup, tmp_path):
    mesh, model, tx, plan, state = setup
    mgr = ckpt_lib.CheckpointManager(tmp_path / "ck", keep=2, async_save=False)
    assert mgr.save(0, state, meta={"loader": {"steps_consumed": 7}}, force=True)
    mgr.wait()

    target = ckpt_lib.abstract_state(model, tx, plan, SHAPE)
    restored, meta = mgr.restore(target)
    tree_allclose(state, restored)
    assert meta["loader"]["steps_consumed"] == 7
    # optimizer state came back in its ZeRO sharding, not replicated
    mu = restored.opt_state[1][0].mu
    leaf = jax.tree.leaves(mu)[0]
    assert not leaf.sharding.is_fully_replicated
    mgr.close()


def test_roundtrip_moe_on_expert_pipe_mesh(devices, tmp_path):
    """Sharded-native save/restore with MoE expert weights sharded over the
    expert axis AND the layer stack sharded over the pipe axis — the exotic
    layouts must round-trip like any other NamedSharding."""
    import dataclasses

    cfg = dataclasses.replace(CFG, vocab_size=128, n_experts=4, moe_top_k=2)
    mesh = make_mesh(MeshConfig(pipe=2, data=2, expert=2), devices=devices)
    model = Transformer(cfg)
    tx = make_optimizer(OptimizerConfig(warmup_steps=5, total_steps=50))
    plan = make_plan(model, tx, mesh, SHAPE, zero_stage=1)
    state = init_train_state(model, tx, jax.random.PRNGKey(1), mesh, SHAPE, plan)
    wi = state.params["blocks"]["moe"]["wi"]
    assert "expert" in str(wi.sharding.spec) and "pipe" in str(wi.sharding.spec)

    mgr = ckpt_lib.CheckpointManager(tmp_path / "ck", keep=1, async_save=False)
    assert mgr.save(0, state, force=True)
    mgr.wait()
    restored, _ = mgr.restore(ckpt_lib.abstract_state(model, tx, plan, SHAPE))
    tree_allclose(state, restored)
    wi_r = restored.params["blocks"]["moe"]["wi"]
    assert wi_r.sharding.is_equivalent_to(wi.sharding, wi.ndim)
    mgr.close()


def test_restore_params_only_warm_init(setup, tmp_path):
    mesh, model, tx, plan, state = setup
    mgr = ckpt_lib.CheckpointManager(tmp_path / "ck2", keep=1, async_save=False)
    mgr.save(3, state, force=True)
    mgr.wait()

    target = ckpt_lib.abstract_state(model, tx, plan, SHAPE)
    params = mgr.restore_params(target.params)
    tree_allclose(state.params, params)
    mgr.close()


def test_latest_step_and_keep(setup, tmp_path):
    mesh, model, tx, plan, state = setup
    mgr = ckpt_lib.CheckpointManager(tmp_path / "ck3", keep=2, save_frequency=1,
                                     async_save=False)
    for s in (1, 2, 3):
        import dataclasses
        mgr.save(s, dataclasses.replace(state, step=jnp.asarray(s, jnp.int32)))
    mgr.wait()
    assert mgr.latest_step() == 3
    assert mgr.all_steps() == [2, 3]  # keep=2 pruned step 1
    mgr.close()


def test_save_frequency_gate(setup, tmp_path):
    mesh, model, tx, plan, state = setup
    mgr = ckpt_lib.CheckpointManager(tmp_path / "ck4", keep=5, save_frequency=10,
                                     async_save=False)
    assert not mgr.save(5, state)   # off-interval: skipped
    assert mgr.save(10, state)      # on-interval
    mgr.wait()
    assert mgr.all_steps() == [10]
    mgr.close()


def test_msgpack_export_import_roundtrip(setup, tmp_path):
    _, _, _, _, state = setup
    path = ckpt_lib.export_params_msgpack(state.params, tmp_path / "params.msgpack")
    loaded = ckpt_lib.import_params_msgpack(path)
    tree_allclose(state.params, loaded)


def test_remote_gs_path_not_mangled():
    """gs:// directories must survive construction untouched (the reference's
    deployment mode, main_zero.py:58-93 writes checkpoints to GCS buckets).
    Round-3 bug: Path(directory).absolute() turned "gs://b/run" into
    "/cwd/gs:/b/run". Construction + step-path formatting are storage-free,
    so this runs with zero egress."""
    mgr = ckpt_lib.CheckpointManager("gs://bucket/run")
    assert str(mgr.directory) == "gs://bucket/run"
    assert str(mgr.step_path(100)) == "gs://bucket/run/100"
    assert str(mgr.step_path(0)) == "gs://bucket/run/0"
    assert mgr._mgr_inst is None  # no orbax manager (= no bucket I/O) yet
    mgr.close()  # close before first use must not touch storage either


def test_local_path_still_absolutized(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    mgr = ckpt_lib.CheckpointManager("rel/ckpts")
    assert mgr.directory.is_absolute()
    assert str(mgr.directory) == str(tmp_path / "rel" / "ckpts")
    mgr.close()


def test_metrics_logger_remote_directory_no_mkdir(capsys):
    from zero_transformer_tpu.utils.monitoring import MetricsLogger

    logger = MetricsLogger(directory="gs://bucket/run")
    assert logger._file is None  # JSONL sink gated off, not a mangled mkdir
    logger.log({"loss": 1.0}, step=1)  # console path still works
    logger.close()
    out = capsys.readouterr().out
    assert "JSONL sink disabled" in out and "loss=1" in out
