"""Checkpoint tests: sharded round-trip, partial (warm-init) restore, msgpack.

The reference's restore is hand-coupled to its optax chain and untested
(``main_zero.py:105-139``); these tests pin the new structure-agnostic
restore on a real 8-device mesh.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from zero_transformer_tpu import checkpoint as ckpt_lib
from zero_transformer_tpu.config import MeshConfig, ModelConfig, OptimizerConfig
from zero_transformer_tpu.models.gpt import Transformer
from zero_transformer_tpu.parallel.mesh import make_mesh
from zero_transformer_tpu.parallel.zero import init_train_state, make_plan
from zero_transformer_tpu.training.optimizer import make_optimizer

CFG = ModelConfig(vocab_size=256, d_model=64, n_heads=4, n_layers=2,
                  max_seq_len=16, dropout=0.0)
SHAPE = (8, 16)


@pytest.fixture(scope="module")
def setup(devices):
    mesh = make_mesh(MeshConfig(zero_stage=1), devices=devices)
    model = Transformer(CFG)
    tx = make_optimizer(OptimizerConfig(warmup_steps=5, total_steps=50))
    plan = make_plan(model, tx, mesh, SHAPE, zero_stage=1)
    state = init_train_state(model, tx, jax.random.PRNGKey(0), mesh, SHAPE, plan)
    return mesh, model, tx, plan, state


def tree_allclose(a, b):
    flat_a, flat_b = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(flat_a) == len(flat_b)
    for x, y in zip(flat_a, flat_b):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), rtol=1e-6)


def test_roundtrip_preserves_values_and_shardings(setup, tmp_path):
    mesh, model, tx, plan, state = setup
    mgr = ckpt_lib.CheckpointManager(tmp_path / "ck", keep=2, async_save=False)
    assert mgr.save(0, state, meta={"loader": {"steps_consumed": 7}}, force=True)
    mgr.wait()

    target = ckpt_lib.abstract_state(model, tx, plan, SHAPE)
    restored, meta = mgr.restore(target)
    tree_allclose(state, restored)
    assert meta["loader"]["steps_consumed"] == 7
    # optimizer state came back in its ZeRO sharding, not replicated
    mu = restored.opt_state[1][0].mu
    leaf = jax.tree.leaves(mu)[0]
    assert not leaf.sharding.is_fully_replicated
    mgr.close()


def test_roundtrip_moe_on_expert_pipe_mesh(devices, tmp_path):
    """Sharded-native save/restore with MoE expert weights sharded over the
    expert axis AND the layer stack sharded over the pipe axis — the exotic
    layouts must round-trip like any other NamedSharding."""
    import dataclasses

    cfg = dataclasses.replace(CFG, vocab_size=128, n_experts=4, moe_top_k=2)
    mesh = make_mesh(MeshConfig(pipe=2, data=2, expert=2), devices=devices)
    model = Transformer(cfg)
    tx = make_optimizer(OptimizerConfig(warmup_steps=5, total_steps=50))
    plan = make_plan(model, tx, mesh, SHAPE, zero_stage=1)
    state = init_train_state(model, tx, jax.random.PRNGKey(1), mesh, SHAPE, plan)
    wi = state.params["blocks"]["moe"]["wi"]
    assert "expert" in str(wi.sharding.spec) and "pipe" in str(wi.sharding.spec)

    mgr = ckpt_lib.CheckpointManager(tmp_path / "ck", keep=1, async_save=False)
    assert mgr.save(0, state, force=True)
    mgr.wait()
    restored, _ = mgr.restore(ckpt_lib.abstract_state(model, tx, plan, SHAPE))
    tree_allclose(state, restored)
    wi_r = restored.params["blocks"]["moe"]["wi"]
    assert wi_r.sharding.is_equivalent_to(wi.sharding, wi.ndim)
    mgr.close()


def test_restore_params_only_warm_init(setup, tmp_path):
    mesh, model, tx, plan, state = setup
    mgr = ckpt_lib.CheckpointManager(tmp_path / "ck2", keep=1, async_save=False)
    mgr.save(3, state, force=True)
    mgr.wait()

    target = ckpt_lib.abstract_state(model, tx, plan, SHAPE)
    params = mgr.restore_params(target.params)
    tree_allclose(state.params, params)
    mgr.close()


def test_latest_step_and_keep(setup, tmp_path):
    mesh, model, tx, plan, state = setup
    mgr = ckpt_lib.CheckpointManager(tmp_path / "ck3", keep=2, save_frequency=1,
                                     async_save=False)
    for s in (1, 2, 3):
        import dataclasses
        mgr.save(s, dataclasses.replace(state, step=jnp.asarray(s, jnp.int32)))
    mgr.wait()
    assert mgr.latest_step() == 3
    assert mgr.all_steps() == [2, 3]  # keep=2 pruned step 1
    mgr.close()


def test_save_frequency_gate(setup, tmp_path):
    mesh, model, tx, plan, state = setup
    mgr = ckpt_lib.CheckpointManager(tmp_path / "ck4", keep=5, save_frequency=10,
                                     async_save=False)
    assert not mgr.save(5, state)   # off-interval: skipped
    assert mgr.save(10, state)      # on-interval
    mgr.wait()
    assert mgr.all_steps() == [10]
    mgr.close()


def test_msgpack_export_import_roundtrip(setup, tmp_path):
    _, _, _, _, state = setup
    path = ckpt_lib.export_params_msgpack(state.params, tmp_path / "params.msgpack")
    loaded = ckpt_lib.import_params_msgpack(path)
    tree_allclose(state.params, loaded)


def test_latest_step_skips_partial_dir(setup, tmp_path):
    """Crash mid-async-save leaves a partial step dir; it must NEVER be the
    resume target (regression: orbax's own latest_step trusts the listing)."""
    mesh, model, tx, plan, state = setup
    mgr = ckpt_lib.CheckpointManager(tmp_path / "ck", keep=5, save_frequency=1,
                                     async_save=False)
    mgr.save(1, state, force=True)
    mgr.save(2, state, force=True)
    mgr.wait()
    # hand-made partials: an empty step dir, and one whose state item is
    # missing its metadata (the commit marker never landed)
    (tmp_path / "ck" / "4").mkdir()
    half = tmp_path / "ck" / "8"
    (half / "state").mkdir(parents=True)
    (half / "meta").mkdir()
    mgr2 = ckpt_lib.CheckpointManager(tmp_path / "ck", keep=5)
    assert mgr2.latest_step() == 2
    assert mgr2.all_steps() == [1, 2]
    target = ckpt_lib.abstract_state(model, tx, plan, SHAPE)
    restored, _, report = mgr2.restore_verified(target)
    assert report.step == 2 and report.quarantined == []
    tree_allclose(state, restored)
    mgr.close()
    mgr2.close()


def _corrupt(step_dir, mode):
    from zero_transformer_tpu.resilience.chaos import corrupt_step_dir

    corrupt_step_dir(step_dir, f"ckpt_{mode}")


@pytest.mark.parametrize("mode", ["truncate", "bitflip"])
def test_corrupt_step_quarantined_with_fallback(setup, tmp_path, mode):
    """A truncated or bit-flipped newest step is quarantined (renamed aside,
    counted, evented) and restore falls back to the newest VERIFIED step —
    never crash-looping on the same bad artifact."""
    import dataclasses

    import jax.numpy as jnp

    mesh, model, tx, plan, state = setup
    root = tmp_path / f"ck_{mode}"
    mgr = ckpt_lib.CheckpointManager(root, keep=5, save_frequency=1,
                                     async_save=False)
    good = dataclasses.replace(state, step=jnp.asarray(1, jnp.int32))
    mgr.save(1, good, force=True)
    mgr.save(2, dataclasses.replace(state, step=jnp.asarray(2, jnp.int32)),
             force=True)
    mgr.wait()
    _corrupt(root / "2", mode)

    events = []
    target = ckpt_lib.abstract_state(model, tx, plan, SHAPE)
    restored, _, report = mgr.restore_verified(
        target, on_event=lambda name, step, **f: events.append((name, step))
    )
    assert report.step == 1 and report.quarantined == [2]
    assert report.fallback_steps == 1
    tree_allclose(good, restored)
    assert ("ckpt_quarantined", 2) in events
    assert ("restore_fallback", 1) in events
    assert (root / "2.quarantined").exists()
    assert mgr.latest_step() == 1  # the quarantined dir left the listing
    mgr.close()


def test_quarantine_tombstones_in_place_when_rename_unsupported(
    setup, tmp_path, monkeypatch
):
    """Object stores can't rename directories: quarantine must fall back to
    an in-place _QUARANTINED tombstone that takes the step out of the
    candidate set, so a corrupt checkpoint on gs:// still falls back
    instead of crash-looping on the seen-step guard."""
    import dataclasses
    import pathlib

    import jax.numpy as jnp

    mesh, model, tx, plan, state = setup
    root = tmp_path / "ck_tomb"
    mgr = ckpt_lib.CheckpointManager(root, keep=5, save_frequency=1,
                                     async_save=False)
    good = dataclasses.replace(state, step=jnp.asarray(1, jnp.int32))
    mgr.save(1, good, force=True)
    mgr.save(2, dataclasses.replace(state, step=jnp.asarray(2, jnp.int32)),
             force=True)
    mgr.wait()
    _corrupt(root / "2", "truncate")

    from etils import epath

    # orbax's find_step_path returns an etils epath.Path whose rename does
    # not route through pathlib — deny the directory rename on BOTH types
    for cls in {pathlib.Path, type(epath.Path(str(root)))}:
        real_rename = cls.rename

        def deny(self, target, _real=real_rename):
            if str(self) == str(root / "2"):
                raise OSError("rename of directories is not supported")
            return _real(self, target)

        monkeypatch.setattr(cls, "rename", deny)
    target = ckpt_lib.abstract_state(model, tx, plan, SHAPE)
    restored, _, report = mgr.restore_verified(target)
    assert report.step == 1 and report.quarantined == [2]
    assert (root / "2" / "_QUARANTINED").exists()
    assert mgr.latest_step() == 1  # tombstoned step left the candidate set
    tree_allclose(good, restored)
    mgr.close()


def test_all_steps_corrupt_raises_actionable_error(setup, tmp_path):
    mesh, model, tx, plan, state = setup
    root = tmp_path / "ck_dead"
    mgr = ckpt_lib.CheckpointManager(root, keep=5, async_save=False)
    mgr.save(1, state, force=True)
    mgr.wait()
    _corrupt(root / "1", "truncate")
    with pytest.raises(FileNotFoundError, match="no verified checkpoint"):
        mgr.restore_verified(ckpt_lib.abstract_state(model, tx, plan, SHAPE))
    assert (root / "1.quarantined").exists()
    mgr.close()


def test_manifest_structural_mismatch_is_fatal_not_quarantine(setup, tmp_path):
    """A checkpoint from a DIFFERENT model must raise the precise config
    error — quarantining it would discard a good checkpoint."""
    import dataclasses as dc

    mesh, model, tx, plan, state = setup
    mgr = ckpt_lib.CheckpointManager(tmp_path / "ck", async_save=False)
    mgr.save(1, state, force=True)
    mgr.wait()
    other_cfg = dc.replace(CFG, d_model=128, n_heads=8)
    other = Transformer(other_cfg)
    other_plan = make_plan(other, tx, mesh, SHAPE, zero_stage=1)
    target = ckpt_lib.abstract_state(other, tx, other_plan, SHAPE)
    with pytest.raises(ValueError, match="different model/optimizer"):
        mgr.restore_verified(target)
    assert mgr.latest_step() == 1  # NOT quarantined
    mgr.close()


def test_tree_digests_exact_and_layout_invariant(setup):
    """The digest is an exact bit-sum: identical values -> identical digest
    regardless of sharding; one changed element -> different digest."""
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh, model, tx, plan, state = setup
    ref = np.arange(64, dtype=np.float32).reshape(8, 8)
    sharded = jax.device_put(ref, NamedSharding(mesh, P("data")))
    replicated = jax.device_put(ref, NamedSharding(mesh, P()))
    d1 = ckpt_lib.tree_digests({"x": sharded})
    d2 = ckpt_lib.tree_digests({"x": replicated})
    assert d1 == d2
    flipped = ref.copy()
    flipped[3, 3] = np.float32(np.nextafter(flipped[3, 3], np.inf))
    d3 = ckpt_lib.tree_digests({"x": jnp.asarray(flipped)})
    assert d3 != d1


def test_remote_gs_path_not_mangled():
    """gs:// directories must survive construction untouched (the reference's
    deployment mode, main_zero.py:58-93 writes checkpoints to GCS buckets).
    Round-3 bug: Path(directory).absolute() turned "gs://b/run" into
    "/cwd/gs:/b/run". Construction + step-path formatting are storage-free,
    so this runs with zero egress."""
    mgr = ckpt_lib.CheckpointManager("gs://bucket/run")
    assert str(mgr.directory) == "gs://bucket/run"
    assert str(mgr.step_path(100)) == "gs://bucket/run/100"
    assert str(mgr.step_path(0)) == "gs://bucket/run/0"
    assert mgr._mgr_inst is None  # no orbax manager (= no bucket I/O) yet
    mgr.close()  # close before first use must not touch storage either


def test_local_path_still_absolutized(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    mgr = ckpt_lib.CheckpointManager("rel/ckpts")
    assert mgr.directory.is_absolute()
    assert str(mgr.directory) == str(tmp_path / "rel" / "ckpts")
    mgr.close()


def test_metrics_logger_remote_directory_no_mkdir(capsys):
    from zero_transformer_tpu.utils.monitoring import MetricsLogger

    logger = MetricsLogger(directory="gs://bucket/run")
    assert logger._file is None  # JSONL sink gated off, not a mangled mkdir
    logger.log({"loss": 1.0}, step=1)  # console path still works
    logger.close()
    out = capsys.readouterr().out
    assert "JSONL sink disabled" in out and "loss=1" in out
