"""train.py CLI plumbing: dotted --set overrides (reference analogue:
``main_zero.py:41-55`` argparse + OmegaConf merge)."""
import pytest

from train import apply_overrides, parse_overrides
from zero_transformer_tpu.config import Config


def test_parse_literals_and_strings():
    out = parse_overrides(["a.b=3", "c.d=0.5", "e.f=True", "g.h=/tmp/x"])
    assert out == {"a.b": 3, "c.d": 0.5, "e.f": True, "g.h": "/tmp/x"}


def test_apply_dotted_override():
    cfg = apply_overrides(Config(), {"training.total_steps": 7, "mesh.pipe": 2})
    assert cfg.training.total_steps == 7 and cfg.mesh.pipe == 2


def test_unknown_field_raises():
    with pytest.raises(ValueError, match="unknown config field"):
        apply_overrides(Config(), {"training.nope": 1})


def test_model_size_zoo_lookup_keeps_other_model_overrides():
    # model.size replaces the model section from the zoo, but model.*
    # overrides must land ON TOP regardless of command-line order
    cfg = apply_overrides(
        Config(), {"model.remat": True, "model.size": "125m"}
    )
    assert cfg.model.name == "125m" and cfg.model.remat is True
