"""Distributed DP+ZeRO tests on the 8-device virtual CPU mesh.

This is the tier the reference has zero automated coverage for (SURVEY §4):
sharding spec derivation, ZeRO stage 0-3 training semantics, optimizer-state
placement, and cross-stage numerical equivalence.
"""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from zero_transformer_tpu.config import MeshConfig, ModelConfig, OptimizerConfig
from zero_transformer_tpu.models import Transformer
from zero_transformer_tpu.parallel import (
    DATA_AXIS,
    TENSOR_AXIS,
    make_mesh,
    make_plan,
    init_train_state,
    make_train_step,
    make_eval_step,
)
from zero_transformer_tpu.training.optimizer import make_optimizer, make_schedule
from zero_transformer_tpu.utils.jax_compat import HAS_AMBIENT_MESH

CFG = ModelConfig(
    name="t", vocab_size=256, d_model=64, n_heads=4, n_layers=2, max_seq_len=32,
    dropout=0.0, compute_dtype="float32",
)
OPT = OptimizerConfig(peak_learning_rate=1e-3, warmup_steps=4, total_steps=64)


def _setup(mesh_cfg=MeshConfig(), zero_stage=1, model_cfg=CFG):
    mesh = make_mesh(mesh_cfg)
    model = Transformer(model_cfg)
    tx = make_optimizer(OPT)
    plan = make_plan(model, tx, mesh, (2, 16), zero_stage)
    state = init_train_state(model, tx, jax.random.PRNGKey(0), mesh, (2, 16), plan)
    step = make_train_step(model, tx, mesh, plan, zero_stage, make_schedule(OPT))
    return mesh, model, plan, state, step


def _batch(accum=1, bs=8, T=16, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.integers(0, CFG.vocab_size, (accum, bs, T)), jnp.int32)


def test_mesh_axes(devices):
    mesh = make_mesh(MeshConfig())
    assert mesh.shape[DATA_AXIS] == 8
    mesh2 = make_mesh(MeshConfig(tensor=2))
    assert mesh2.shape[DATA_AXIS] == 4 and mesh2.shape[TENSOR_AXIS] == 2
    with pytest.raises(ValueError):
        make_mesh(MeshConfig(data=3))


def test_hybrid_mesh_validation(devices):
    """dcn_data (multi-slice DCN layout) must fail LOUDLY when the devices
    cannot honor it: single-process virtual CPU devices form one granule,
    so asking for 2 DCN groups must raise (never silently produce a mesh
    whose tensor axis would cross the slow network)."""
    with pytest.raises(ValueError, match="dcn_data"):
        MeshConfig(dcn_data=0)
    with pytest.raises(ValueError, match="not divisible by dcn_data"):
        make_mesh(MeshConfig(data=8, dcn_data=3))
    with pytest.raises(ValueError, match="hybrid mesh"):
        # 8 devices, all process 0 / no slice_index -> 1 granule != 2
        make_mesh(MeshConfig(data=8, dcn_data=2))


@pytest.mark.parametrize("zero_stage", [0, 1, 2, 3])
def test_loss_decreases_all_stages(zero_stage):
    mesh, model, plan, state, step = _setup(zero_stage=zero_stage)
    rng = jax.random.PRNGKey(42)
    losses = []
    for i in range(20):
        state, metrics = step(state, _batch(seed=0), rng)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0] - 0.5, f"stage {zero_stage}: no learning: {losses}"


def test_opt_state_sharded_8way_stage1():
    mesh, model, plan, state, step = _setup(zero_stage=1)
    # params replicated between steps (stage 1), optimizer mu sharded
    leaves = jax.tree.leaves(state.params)
    for leaf in leaves:
        assert leaf.sharding.is_fully_replicated, leaf.sharding
    # find a large opt leaf (mu of the mlp kernel) and check it is sharded
    opt_leaves = [l for l in jax.tree.leaves(state.opt_state) if l.ndim >= 2]
    sharded = [l for l in opt_leaves if not l.sharding.is_fully_replicated]
    assert sharded, "no optimizer leaf is sharded under ZeRO-1"
    big = max(sharded, key=lambda l: l.size)
    assert len(big.sharding.device_set) == 8
    # per-device bytes should be 1/8 of total
    shard_size = big.addressable_shards[0].data.size
    assert shard_size * 8 == big.size


def test_params_sharded_stage3():
    mesh, model, plan, state, step = _setup(zero_stage=3)
    big = max(jax.tree.leaves(state.params), key=lambda l: l.size)
    assert not big.sharding.is_fully_replicated
    assert big.addressable_shards[0].data.size * 8 == big.size


@pytest.mark.slow
def test_stages_numerically_equivalent():
    results = {}
    for stage in [0, 1, 2, 3]:
        mesh, model, plan, state, step = _setup(zero_stage=stage)
        rng = jax.random.PRNGKey(7)
        for i in range(3):
            state, metrics = step(state, _batch(seed=i), rng)
        results[stage] = float(metrics["loss"])
    base = results[0]
    for stage, loss in results.items():
        np.testing.assert_allclose(loss, base, rtol=2e-4, err_msg=f"stage {stage}")


@pytest.mark.slow
def test_grad_accumulation_matches_large_batch():
    mesh, model, plan, state, step = _setup(zero_stage=1)
    big = _batch(accum=1, bs=16, seed=3)
    split = big.reshape(2, 8, 16)  # [accum=2, 8, T]
    state_a = state
    state_b = jax.tree.map(jnp.copy, state)  # real copy: step() donates its input
    rng = jax.random.PRNGKey(0)
    state_a, ma = step(state_a, big, rng)
    state_b, mb = step(state_b, split, rng)
    np.testing.assert_allclose(float(ma["loss"]), float(mb["loss"]), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(state_a.params), jax.tree.leaves(state_b.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


@pytest.mark.slow
def test_tensor_parallel_matches_dp():
    mesh_tp, _, _, state_tp, step_tp = _setup(MeshConfig(tensor=2), zero_stage=1)
    mesh_dp, _, _, state_dp, step_dp = _setup(MeshConfig(), zero_stage=1)
    rng = jax.random.PRNGKey(1)
    for i in range(3):
        state_tp, mt = step_tp(state_tp, _batch(seed=i), rng)
        state_dp, md = step_dp(state_dp, _batch(seed=i), rng)
    np.testing.assert_allclose(float(mt["loss"]), float(md["loss"]), rtol=2e-4)
    # TP actually shards a param over the tensor axis
    any_tp = any(
        TENSOR_AXIS in str(l.sharding.spec) for l in jax.tree.leaves(state_tp.params)
    )
    assert any_tp, "no param sharded over tensor axis"


@pytest.mark.parametrize("zero_stage", [1, 2, 3])
def test_bf16_policy_trains_with_f32_master(zero_stage):
    """The shipped train configs run compute_dtype=bfloat16; this pins that
    regime (the one the reference shipped its quality bug in, reference
    ``logs/580.md:94-106``): loss decreases, master params and optimizer
    moments stay float32, and metrics stay finite."""
    cfg = dataclasses.replace(CFG, compute_dtype="bfloat16")
    mesh, model, plan, state, step = _setup(zero_stage=zero_stage, model_cfg=cfg)

    for leaf in jax.tree.leaves(state.params):
        assert leaf.dtype == jnp.float32, f"master param is {leaf.dtype}"

    rng = jax.random.PRNGKey(42)
    losses = []
    for i in range(20):
        state, metrics = step(state, _batch(seed=0), rng)
        losses.append(float(metrics["loss"]))
        assert np.isfinite(losses[-1])
        assert np.isfinite(float(metrics["grad_norm"]))
    assert losses[-1] < losses[0] - 0.5, f"stage {zero_stage}: no learning: {losses}"

    # master params and Adam moments still f32 after real bf16-compute steps
    for leaf in jax.tree.leaves(state.params):
        assert leaf.dtype == jnp.float32
    float_opt = [l for l in jax.tree.leaves(state.opt_state)
                 if jnp.issubdtype(l.dtype, jnp.floating)]
    assert float_opt
    for leaf in float_opt:
        assert leaf.dtype == jnp.float32, f"opt leaf is {leaf.dtype}"


def _collective_lines(step, state, batch, rng):
    """Compiled-HLO lines per collective op kind."""
    if tuple(int(x) for x in jax.__version__.split(".")[:2]) < (0, 5):
        # jaxlib 0.4.x ABORTS (uncatchable SIGABRT — it takes the whole
        # pytest process down) compiling the explicit shard_map core for
        # HLO inspection; the numerics tests above still cover these stages
        pytest.skip("jaxlib < 0.5 SIGABRTs on HLO compile of the shard_map core")
    txt = step.lower(state, batch, rng).compile().as_text()
    out = {}
    for name in ("reduce-scatter", "all-gather", "all-reduce"):
        out[name] = [
            l.strip() for l in txt.splitlines() if name in l and "=" in l
        ]
    return out


def _max_op_elems(lines):
    """Largest element count named in any shape literal on these HLO lines."""
    import re

    biggest = 0
    for l in lines:
        for dims in re.findall(r"[a-z0-9]+\[([0-9,]*)\]", l):
            n = 1
            for d in dims.split(","):
                if d:
                    n *= int(d)
            biggest = max(biggest, n)
    return biggest


@pytest.mark.parametrize(
    "mesh_cfg,zero_stage",
    [
        (MeshConfig(), 2),
        (MeshConfig(), 3),
        (MeshConfig(tensor=2), 2),  # partial-manual core: TP auto, ZeRO manual
        (MeshConfig(tensor=2), 3),
    ],
)
def test_hlo_collectives_explicit_zero(mesh_cfg, zero_stage):
    """ZeRO-2/3 compiles to literal reduce-scatter + all-gather, with NO
    gradient-sized all-reduce (that would mean the stage silently degraded to
    ZeRO-1 traffic). Guards the explicit shard_map core in
    ``parallel/zero.py`` on both pure-DP and tensor-parallel meshes — on the
    TP mesh the old constraint-hint path compiled to 0 reduce-scatters.
    Scalar psums (loss, grad norm) and TP's activation all-reduces are
    legitimate; anything at parameter scale is not."""
    mesh, model, plan, state, step = _setup(mesh_cfg, zero_stage=zero_stage)
    batch = _batch()
    ops = _collective_lines(step, state, batch, jax.random.PRNGKey(0))
    assert ops["reduce-scatter"], "no reduce-scatter in compiled ZeRO-2/3 step"
    assert ops["all-gather"], "no all-gather in compiled ZeRO-2/3 step"
    # activation-scale bound: TP legitimately all-reduces activations
    # (≤ microbatch_tokens × d_model elements) and scalars; any WEIGHT
    # gradient all-reduce (qkv: d×3d, mlp: d×4d — all > tokens×d here)
    # means the stage degraded to ZeRO-1 traffic
    activation_bound = batch.shape[1] * batch.shape[2] * CFG.d_model
    big = _max_op_elems(ops["all-reduce"])
    assert big <= activation_bound, (
        f"all-reduce of {big} elements in a stage-{zero_stage} step "
        f"(activation bound {activation_bound})"
    )


def test_tp_zero2_matches_dp():
    """TP=2 + ZeRO-2 (partial-manual explicit core) is numerically the same
    training trajectory as plain DP stage 0."""
    mesh_tp, _, _, state_tp, step_tp = _setup(MeshConfig(tensor=2), zero_stage=2)
    mesh_dp, _, _, state_dp, step_dp = _setup(MeshConfig(), zero_stage=0)
    rng = jax.random.PRNGKey(7)
    for i in range(3):
        state_tp, mt = step_tp(state_tp, _batch(seed=i), rng)
        state_dp, md = step_dp(state_dp, _batch(seed=i), rng)
    np.testing.assert_allclose(float(mt["loss"]), float(md["loss"]), rtol=2e-4)


def test_eval_step():
    mesh, model, plan, state, step = _setup()
    eval_step = make_eval_step(model, mesh, plan)
    loss = eval_step(state.params, _batch()[0])
    assert jnp.isfinite(loss) and float(loss) > 0


def test_train_step_donates_buffers():
    mesh, model, plan, state, step = _setup()
    old = state
    state, _ = step(state, _batch(), jax.random.PRNGKey(0))
    # donated input buffers are invalidated
    with pytest.raises(RuntimeError):
        _ = np.asarray(jax.tree.leaves(old.params)[0])


def test_llama3_8b_scale_plan_shapes(devices):
    """The sharding plan derives valid specs at flagship scale (llama3-8B
    geometry) on a data x fsdp x tensor mesh at ZeRO-3 — abstract shapes
    only, no weights materialize. Guards the shape-derived ZeRO spec pass
    and logical rules against the real 8B config, not just toy sizes."""
    from zero_transformer_tpu.config import MeshConfig, model_config
    from zero_transformer_tpu.models import Transformer
    from zero_transformer_tpu.training.optimizer import make_optimizer

    cfg = model_config("llama3_8b", remat=True)
    mesh = make_mesh(MeshConfig(data=2, fsdp=2, tensor=2, zero_stage=3))
    model = Transformer(cfg)
    tx = make_optimizer(OptimizerConfig(warmup_steps=10, total_steps=100))
    plan = make_plan(model, tx, mesh, (4, 8192), zero_stage=3)

    from zero_transformer_tpu.parallel.sharding import unbox

    shapes = unbox(jax.eval_shape(
        lambda r: model.init(r, jnp.zeros((1, 8), jnp.int32)),
        jax.random.PRNGKey(0),
    )["params"])
    flat_shapes = jax.tree.leaves(shapes)
    flat_specs = jax.tree.leaves(
        plan.state.params, is_leaf=lambda x: hasattr(x, "spec")
    )
    assert len(flat_shapes) == len(flat_specs)
    n_params = 0
    n_sharded = 0
    for shp, ns in zip(flat_shapes, flat_specs):
        n_params += int(np.prod(shp.shape))
        if len(shp.shape) >= 2 and int(np.prod(shp.shape)) > 1_000_000:
            # every big tensor must actually shard over at least one axis
            assert any(s is not None for s in ns.spec), (shp.shape, ns.spec)
            n_sharded += 1
    assert n_sharded >= 5
    assert n_params > 7_000_000_000, f"llama3_8b plan covers {n_params:,} params"


def test_tp_activation_sharding_hlo(devices):
    """TP activations are explicitly sharded, not left to GSPMD's choice
    (round-3 VERDICT weak #3: `activation_sharding` was dead code and TP
    activation layout was GSPMD-inferred). With tensor=2 the MLP hidden
    [B_local, T, ff] must appear HALVED on the feature dim in the compiled
    per-device HLO and the full-width hidden must never materialize.

    Shape-string hygiene: vocab_size is bumped so logits never read as
    hidden-sized, and T=24 so activations [B_local=2, 24, ff] can't collide
    with the stacked wi weight shard [n_layers=2, d_model/4=16, ff] that a
    T=16 batch would alias exactly.
    Covers BOTH step builders: the GSPMD constraint-hint path (stage 1) and
    the partial-manual explicit ZeRO core (stage 2, tensor stays auto)."""
    cfg = dataclasses.replace(CFG, vocab_size=1024)
    for stage in (1, 2):
        mesh, model, plan, state, step = _setup(
            MeshConfig(tensor=2), zero_stage=stage, model_cfg=cfg
        )
        txt = step.lower(state, _batch(T=24), jax.random.PRNGKey(0)).compile().as_text()
        # batch 8 over data=4 -> B_local 2; ff 256 over tensor=2 -> 128
        assert "f32[2,24,128]" in txt, f"stage {stage}: no tensor-sharded MLP hidden"
        assert "f32[2,24,256]" not in txt, (
            f"stage {stage}: full-width MLP hidden materialized despite tensor=2"
        )


@pytest.mark.parametrize("stage", [2, 3])
@pytest.mark.parametrize("dm", [64, 128])
def test_adafactor_zero2_matches_zero1(devices, stage, dm):
    """Adafactor x explicit ZeRO-2/3 (round-4 VERDICT weak #6: rejected
    outright before round 5). The shard-aware factored-rms/param-scale
    transforms must follow the SAME trajectory as plain optax.adafactor on
    the stage-1 GSPMD path — factored means psum/all-gather across the
    ZeRO axis instead of being computed on full tensors. d_model=128 so
    the >=128x128 factoring rule actually fires (wte [256,128] reduces
    across AND along the scatter dim; stacked norm scales [2,128] exercise
    the non-factored sharded fallback). Stage 3 adds FSDP param storage —
    the 1.3B-on-a-pod configuration the north star names. d_model=64: NO
    param factors, so opt_state_sharding ZeRO-scatters the whole
    param-shaped FactoredState.v tree — the elementwise update must run
    straight on the shards (r5 review finding: this layout crashed)."""
    cfg = dataclasses.replace(CFG, d_model=dm)
    opt_af = dataclasses.replace(OPT, optimizer="adafactor")

    def setup(stage):
        mesh = make_mesh(MeshConfig(zero_stage=max(stage, 1)))
        model = Transformer(cfg)
        tx = make_optimizer(opt_af)
        plan = make_plan(model, tx, mesh, (2, 16), stage)
        state = init_train_state(
            model, tx, jax.random.PRNGKey(0), mesh, (2, 16), plan
        )
        step = make_train_step(
            model, tx, mesh, plan, stage, make_schedule(opt_af),
            tx_factory=lambda norm_fn, zc=None: make_optimizer(
                opt_af, None, norm_fn, zero_collectives=zc
            ),
        )
        return state, step

    s1, step1 = setup(1)
    s2, step2 = setup(stage)
    rng = jax.random.PRNGKey(7)
    for i in range(3):
        s1, m1 = step1(s1, _batch(accum=2, seed=i), rng)
        s2, m2 = step2(s2, _batch(accum=2, seed=i), rng)
    np.testing.assert_allclose(float(m2["loss"]), float(m1["loss"]), rtol=2e-4)
    # scale check: factored-stat errors would warp grad_norm before loss
    np.testing.assert_allclose(
        float(m2["grad_norm"]), float(m1["grad_norm"]), rtol=1e-3
    )
    for a, b in zip(jax.tree.leaves(s1.params), jax.tree.leaves(s2.params)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-5, rtol=1e-4
        )
    # the stage-2 HLO still reduce-scatters (adafactor did not silently
    # downgrade the collective schedule)
    ops = _collective_lines(step2, s2, _batch(seed=9), jax.random.PRNGKey(0))
    assert ops["reduce-scatter"], "no reduce-scatter in adafactor ZeRO-2 HLO"


@pytest.mark.parametrize("cp", ["ring", "ulysses"])
@pytest.mark.parametrize("stage", [2, 3])
def test_zero2_sequence_parallel_explicit_collectives(devices, cp, stage):
    """ZeRO-2/3 x sequence parallel runs the EXPLICIT collective core with
    the CP engine's shard_map nested inside it (round 5; before, these
    meshes fell back to the GSPMD hint path, which compiled to ZERO
    reduce-scatters and weight-sized all-reduces — silent stage-1
    traffic). Contract: trajectory matches plain DP stage 0, and the
    compiled HLO contains literal reduce-scatters. The surviving
    all-reduces are the sequence-axis weight-grad reductions inherent to
    CP (tokens split over sequence) — bounded by the largest param, and
    the data-axis grad reduction must NOT ride them (reduce-scatter does)."""
    cfg = dataclasses.replace(CFG, cp_impl=cp)
    mesh = make_mesh(MeshConfig(data=4, sequence=2, zero_stage=stage))
    model = Transformer(cfg, mesh=mesh)
    tx = make_optimizer(OPT)
    plan = make_plan(model, tx, mesh, (4, 16), stage)
    s_sp = init_train_state(model, tx, jax.random.PRNGKey(0), mesh, (4, 16), plan)
    step_sp = make_train_step(
        model, tx, mesh, plan, stage, make_schedule(OPT),
        tx_factory=lambda norm_fn, zc=None: make_optimizer(OPT, None, norm_fn),
    )
    mesh_dp, _, _, s_dp, step_dp = _setup(MeshConfig(), zero_stage=0)

    rng = jax.random.PRNGKey(7)
    for i in range(3):
        batch = _batch(accum=2, seed=i)
        s_sp, m_sp = step_sp(s_sp, batch, rng)
        s_dp, m_dp = step_dp(s_dp, batch, rng)
    np.testing.assert_allclose(float(m_sp["loss"]), float(m_dp["loss"]), rtol=2e-4)
    np.testing.assert_allclose(
        float(m_sp["grad_norm"]), float(m_dp["grad_norm"]), rtol=1e-3
    )
    for a, b in zip(jax.tree.leaves(s_sp.params), jax.tree.leaves(s_dp.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=3e-5)

    ops = _collective_lines(step_sp, s_sp, _batch(accum=2, seed=9), jax.random.PRNGKey(0))
    assert ops["reduce-scatter"], f"{cp} stage {stage}: no reduce-scatter in HLO"


def test_loss_chunk_never_materializes_full_logits(devices):
    """cfg.loss_chunk's whole point, asserted in the compiled per-device
    HLO: the full [B_local, T, vocab] (or shifted T-1) f32 logits buffer
    must not exist anywhere in the step — only [B_local, chunk, vocab]
    tiles — in BOTH step builders (GSPMD stage 1 and the explicit stage-2
    core). vocab=1024 keeps the shape distinctive vs activations."""
    cfg = dataclasses.replace(CFG, vocab_size=1024, loss_chunk=8)
    for stage in (1, 2):
        mesh, model, plan, state, step = _setup(zero_stage=stage, model_cfg=cfg)
        txt = step.lower(state, _batch(T=24), jax.random.PRNGKey(0)).compile().as_text()
        # batch 8 over data=8 -> B_local 1
        assert "f32[1,8,1024]" in txt, f"stage {stage}: no chunked logits tile"
        for full in ("f32[1,24,1024]", "f32[1,23,1024]"):
            assert full not in txt, (
                f"stage {stage}: full logits {full} materialized despite loss_chunk"
            )


@pytest.mark.skipif(
    not HAS_AMBIENT_MESH,
    reason="old-jax SPMD partitioner involuntarily rematerializes the wte "
    "gather on this mesh whenever it actually RUNS (deterministic "
    "standalone failure on a clean tree); the test only ever passed here "
    "when in-process compile-cache state let jax skip the partitioner — "
    "exactly the masking the docstring warns about — making its outcome a "
    "function of which unrelated tests ran earlier in the process",
)
def test_no_involuntary_rematerialization(devices, capfd):
    """The data x tensor x sequence stage-3 mesh compiles with ZERO
    "[SPMD] Involuntary full rematerialization" warnings (round-4 VERDICT
    weak #2: the wte token gather's output inherited an embed-sharded
    layout GSPMD could only reshard by replicating the whole tensor each
    step; the lookup now runs on an explicitly replicated table view).
    The persistent compile cache is disabled for this compile — a cache
    hit skips the SPMD partitioner and would mask a regression. glog
    writes to the raw stderr fd, hence capfd (not capsys)."""
    old = jax.config.jax_enable_compilation_cache
    jax.config.update("jax_enable_compilation_cache", False)
    try:
        mesh, model, plan, state, step = _setup(
            MeshConfig(tensor=2, sequence=2), zero_stage=3
        )
        step.lower(state, _batch(), jax.random.PRNGKey(0)).compile()
    finally:
        jax.config.update("jax_enable_compilation_cache", old)
    err = capfd.readouterr().err
    assert "Involuntary full rematerialization" not in err, err[-2000:]


def test_bf16_grad_accum(devices):
    """grad_accum_dtype="bfloat16" — the knob that fits the 1.3B single-chip
    north star in 16 GB HBM (an f32 accumulator is one of three param-sized
    f32 trees the AOT compiler rejected, ``runs/bench_r5_live1.json``) —
    tracks the f32-accumulator trajectory closely in BOTH step builders,
    while "float32" stays bit-identical to the default path."""
    for stage in (1, 2):
        mesh = make_mesh(MeshConfig())
        model = Transformer(CFG)
        tx = make_optimizer(OPT)
        plan = make_plan(model, tx, mesh, (2, 16), stage)

        def run(**kw):
            state = init_train_state(
                model, tx, jax.random.PRNGKey(0), mesh, (2, 16), plan
            )
            step = make_train_step(
                model, tx, mesh, plan, stage, make_schedule(OPT), **kw
            )
            rng = jax.random.PRNGKey(5)
            for i in range(4):
                state, m = step(state, _batch(accum=4, seed=i), rng)
            return state, float(m["loss"])

        s_def, l_def = run()
        s_f32, l_f32 = run(grad_accum_dtype="float32")
        s_bf, l_bf = run(grad_accum_dtype="bfloat16")
        # explicit float32 is the default, bit for bit
        assert l_f32 == l_def, f"stage {stage}"
        for a, b in zip(jax.tree.leaves(s_f32.params), jax.tree.leaves(s_def.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        # bf16 accumulation rounds each micro-add to 8 mantissa bits; the
        # trajectory stays close but not identical
        np.testing.assert_allclose(l_bf, l_f32, rtol=5e-3, err_msg=f"stage {stage}")
        for a, b in zip(jax.tree.leaves(s_bf.params), jax.tree.leaves(s_f32.params)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=2e-3, err_msg=f"stage {stage}"
            )


def test_grad_accum_dtype_rejections():
    """Bad dtypes fail loudly; the GPipe schedule (accumulation lives inside
    scan-VJP, not a retargetable carry) rejects bfloat16 — 1F1B accepts it
    (``test_pipeline.py::test_pp_1f1b_bf16_accum_matches_f32``). Every
    rejection fires before any step executes, so no state init (an executed
    jit compile) is needed — build the plan pieces directly."""
    mesh = make_mesh(MeshConfig())
    model = Transformer(CFG)
    tx = make_optimizer(OPT)
    plan = make_plan(model, tx, mesh, (2, 16), 1)
    with pytest.raises(ValueError, match="grad_accum_dtype"):
        make_train_step(
            model, tx, mesh, plan, 1, grad_accum_dtype="float16"
        )
    from zero_transformer_tpu.config import TrainingConfig

    with pytest.raises(ValueError, match="grad_accum_dtype"):
        TrainingConfig(grad_accum_dtype="f32")
    mesh_pp = make_mesh(MeshConfig(data=4, pipe=2))
    with pytest.raises(NotImplementedError, match="1f1b"):
        make_train_step(
            model, tx, mesh_pp, plan, 1, grad_accum_dtype="bfloat16"
        )


def test_apply_tx_factory_signatures():
    """The tx_factory contract: 1-arg factories (the original form) get only
    the norm fn; 2-positional-arg factories also receive the
    ZeroCollectives; keyword-only/**kwargs params don't count (r5 review
    finding: counting them passed zc positionally into factories that can't
    bind it)."""
    from zero_transformer_tpu.parallel.zero import apply_tx_factory

    calls = []
    apply_tx_factory(lambda norm_fn: calls.append(("one", norm_fn)), "N", "ZC")
    apply_tx_factory(
        lambda norm_fn, zc=None: calls.append(("two", norm_fn, zc)), "N", "ZC"
    )
    apply_tx_factory(
        lambda norm_fn, **kw: calls.append(("kw", norm_fn, kw)), "N", "ZC"
    )

    def kwonly(norm_fn, *, log=False):
        calls.append(("kwonly", norm_fn, log))

    apply_tx_factory(kwonly, "N", "ZC")
    assert calls == [
        ("one", "N"), ("two", "N", "ZC"), ("kw", "N", {}), ("kwonly", "N", False),
    ]
