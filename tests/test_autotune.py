"""The autotuner (ISSUE 14): knob-space mechanics, the validity oracle,
analytic pruning, successive-halving determinism, the committed
TUNE_<target>.json artifact contract, and the --tuned gating.

Philosophy matches test_serve_bench.py / test_train_bench.py: the
committed artifact is driver-facing evidence, so its schema and
invariants are pinned here; the search MECHANICS (enumerate -> prune ->
halve -> artifact) are unit-tested deterministically without timing.
"""
import importlib.util
import json
from pathlib import Path
from types import SimpleNamespace

import pytest

from zero_transformer_tpu.analysis import autotune as at
from zero_transformer_tpu.config import Config, apply_dotted_overrides

REPO = Path(__file__).resolve().parent.parent


def _file_module(name, path):
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _bench_common():
    return _file_module("bench_common", REPO / "scripts" / "bench_common.py")


def _serve_base_cfg():
    # the tuner's serve base: prefix cache off (not a searched knob) so the
    # oracle's refusals name the searched knobs, not the cache coupling
    return apply_dotted_overrides(Config(), {"serving.prefix_cache_chunks": 0})


# ------------------------------------------------------------ space basics


def test_space_enumeration_is_deterministic_and_complete():
    s = at.train_space()
    points = s.points()
    assert len(points) == s.size
    assert points == at.train_space().points()  # rebuild -> same order
    # every point binds every knob to a domain value
    for p in points[:: max(1, len(points) // 17)]:
        for knob in s.knobs:
            assert p[knob.name] in knob.values
    # registering a knob is all it takes to join the search
    s2 = at.KnobSpace("train")
    s2.register(at.Knob("overlap_comm", (False, True), "mesh.overlap_comm",
                        "train", "BENCH_step"))
    assert s2.size == 2 and len(s2.points()) == 2
    with pytest.raises(ValueError, match="already registered"):
        s2.register(at.Knob("overlap_comm", (True,), "mesh.overlap_comm",
                            "train", "BENCH_step"))


def test_knob_rejects_empty_or_malformed_domains():
    with pytest.raises(ValueError, match="empty domain"):
        at.Knob("x", (), "mesh.pipe", "train", "BENCH_step")
    with pytest.raises(ValueError, match="dotted"):
        at.Knob("x", (1,), "pipe", "train", "BENCH_step")


# -------------------------------------------------- the validity oracle


@pytest.mark.parametrize("target", ["train", "serve"])
def test_validity_sweep_every_invalid_point_names_a_knob(target):
    """The acceptance-bar sweep: every invalid knob combination in the
    registered space must raise ValueError NAMING an offending knob —
    config validation is what keeps invalid points out of measured trials,
    so an anonymous refusal would make the prune trace unauditable."""
    space = at.train_space() if target == "train" else at.serve_space()
    base = Config() if target == "train" else _serve_base_cfg()
    knob_tokens = [k.field.rsplit(".", 1)[1] for k in space.knobs] + [
        k.name for k in space.knobs
    ]
    invalid = 0
    for point in space.points():
        try:
            apply_dotted_overrides(base, space.overrides(point))
        except ValueError as e:
            invalid += 1
            msg = str(e)
            assert any(tok in msg for tok in knob_tokens), (
                f"refusal for {point} names no searched knob: {msg}"
            )
    assert invalid > 0, "the space contains no invalid combinations?"


@pytest.mark.parametrize("target", ["train", "serve"])
def test_pruning_majority_reasons_and_valid_survivors(target):
    """Analytic pre-pruning must eliminate >= 50% of the enumerated space
    with every pruned point's (rule, reason) recorded, and every survivor
    must construct a valid Config — no measured trial ever runs an invalid
    point."""
    if target == "train":
        space, base = at.train_space(), Config()
        validators = [
            at.config_validator(space, base),
            at.train_redundancy_validator(),
        ]
    else:
        space, base = at.serve_space(), _serve_base_cfg()
        validators = [
            at.config_validator(space, base),
            at.serve_redundancy_validator(),
            at.serve_feasibility_validator(64),
        ]
    points = space.points()
    survivors, pruned = at.prune_points(points, validators)
    assert len(survivors) + len(pruned) == len(points)
    assert len(pruned) / len(points) >= 0.5, (
        f"only {len(pruned)}/{len(points)} pruned analytically"
    )
    for p in pruned:
        assert p.rule and p.reason, p
        assert points[p.index] == p.knobs
    for _, knobs in survivors:
        apply_dotted_overrides(base, space.overrides(knobs))  # must not raise


def test_serve_feasibility_rules():
    check = dict([at.serve_feasibility_validator(64)])
    fn = at.serve_feasibility_validator(64)[1]
    assert fn({"kv_layout": "slab", "page_size": 7}) is None
    assert "divide" in fn({"kv_layout": "paged", "page_size": 7,
                           "page_pool_tokens": 0})
    assert "worst-case" in fn({"kv_layout": "paged", "page_size": 4,
                               "page_pool_tokens": 32})
    assert fn({"kv_layout": "paged", "page_size": 4,
               "page_pool_tokens": 0}) is None
    assert check  # the validator is (rule, fn) shaped


# ------------------------------------------------- successive halving


def _fake_measure(scores):
    calls = []

    def measure(arm, budget, rung):
        calls.append((arm, budget, rung))
        if scores[arm] is None:
            return {"ok": False, "error": "boom"}
        # deterministic fake cost model: score independent of budget
        return {"ok": True, "score": scores[arm],
                "metrics": {"score": scores[arm], "budget": budget}}

    return measure, calls


def test_successive_halving_deterministic_and_failure_safe():
    scores = {0: 5.0, 1: 1.0, 2: 3.0, 3: None, 4: 2.0}
    runs = []
    for _ in range(2):
        measure, calls = _fake_measure(scores)
        winner, rungs = at.successive_halving(
            sorted(scores), measure, budgets=[2, 8], keep_frac=0.5
        )
        runs.append((winner, rungs, calls))
    assert runs[0][0] == runs[1][0] == 1  # lowest score wins, both passes
    assert runs[0][1] == runs[1][1]  # identical rung traces
    r0 = runs[0][1][0]
    # the failed arm is recorded with its error and never promoted
    failed = next(t for t in r0["trials"] if t["arm"] == 3)
    assert failed["ok"] is False and "boom" in failed["error"]
    assert 3 not in r0["promoted"]
    # rung 0 keeps ceil(4 ok arms * 0.5) = 2; the final rung keeps 1
    assert r0["promoted"] == [1, 4]
    assert runs[0][1][1]["promoted"] == [1]
    # cheap budget gates the expensive one: rung 1 only measured survivors
    rung1_arms = {a for a, b, r in runs[0][2] if r == 1}
    assert rung1_arms == {1, 4}


def test_successive_halving_all_failed_raises():
    measure, _ = _fake_measure({0: None, 1: None})
    with pytest.raises(RuntimeError, match="every arm failed"):
        at.successive_halving([0, 1], measure, budgets=[1])


def test_successive_halving_tie_break_is_by_arm_index():
    measure, _ = _fake_measure({7: 1.0, 3: 1.0})
    winner, rungs = at.successive_halving([3, 7], measure, budgets=[1])
    assert winner == 3  # equal scores: lowest arm id, deterministically


def test_successive_halving_tie_frac_absorbs_noise():
    """Arms within the declared noise floor are a statistical tie and
    resolve by arm index — a rerun whose noise flips their raw order must
    still reproduce the same winner (the determinism the artifact gate
    certifies)."""
    # run A: arm 7 measures 1% "faster"; run B: arm 3 does
    for scores in ({3: -100.0, 7: -101.0}, {3: -101.0, 7: -100.0}):
        measure, _ = _fake_measure(scores)
        winner, _ = at.successive_halving(
            [3, 7], measure, budgets=[1], tie_frac=0.05
        )
        assert winner == 3
    # a gap far beyond the floor is a real ranking, not a tie
    measure, _ = _fake_measure({3: -100.0, 7: -150.0})
    winner, _ = at.successive_halving(
        [3, 7], measure, budgets=[1], tie_frac=0.05
    )
    assert winner == 7


# ------------------------------------------ committed artifact contract


@pytest.fixture(scope="module", params=["TUNE_train.json", "TUNE_serve.json"])
def tune_artifact(request):
    path = REPO / request.param
    assert path.exists(), (
        f"commit {request.param} (JAX_PLATFORMS=cpu python "
        f"scripts/autotune.py --target "
        f"{request.param.split('_')[1].split('.')[0]} --reruns 2)"
    )
    return json.loads(path.read_text())


def test_tune_artifact_schema(tune_artifact):
    missing = at.TUNE_REQUIRED_KEYS - tune_artifact.keys()
    assert not missing, f"TUNE artifact missing keys: {sorted(missing)}"
    assert tune_artifact["schema_version"] == at.TUNE_SCHEMA_VERSION
    assert set(tune_artifact["platform"]) == {
        "backend", "device", "device_count",
    }
    assert tune_artifact["provenance"] == "measured"
    assert tune_artifact["target"] in ("train", "serve")


def test_tune_artifact_pruning_trace_is_auditable(tune_artifact):
    """The ISSUE 14 bar: >= 50% of the enumerated space pruned BEFORE any
    measured trial, every pruned point carrying its (rule, reason), and
    the partition exact."""
    pr = tune_artifact["pruning"]
    assert pr["enumerated"] == pr["pruned"] + pr["survivors"]
    assert pr["pruned_frac"] >= 0.5, pr["pruned_frac"]
    assert len(pr["points"]) == pr["pruned"]
    for p in pr["points"]:
        assert p["rule"] and p["reason"], p
    assert sum(pr["rules"].values()) == pr["pruned"]
    # measured arms are exactly the survivors
    assert len(tune_artifact["search"]["arms"]) == pr["survivors"]


def test_tune_artifact_winner_beats_hand_defaults(tune_artifact):
    """The committed artifact's claim: the autotuned config beats the hand
    defaults on its bench metric, measured as a within-run A/B on the
    platform named in the artifact (honest provenance — the tuned numbers
    only ever apply under a matching platform block, enforced by
    check_tuned)."""
    imp = tune_artifact["improvement"]
    assert imp["higher_is_better"] is True
    assert imp["winner"] > imp["baseline"], imp
    assert tune_artifact["value"] == imp["ratio"] > 1.0
    # winner knobs live inside the declared space, with a field mapping
    space = tune_artifact["space"]
    for name, value in tune_artifact["winner"]["knobs"].items():
        assert value in space[name]["values"], (name, value)
        assert "." in space[name]["field"]


def test_train_tune_pins_global_batch(tune_artifact):
    """The train accum knob microbatches a FIXED global batch: the winner's
    loadable overrides must pin batch_size x accum == the workload's global
    batch, so --tuned reproduces the measured geometry (same tokens per
    optimizer step — a perf knob, never a silent trajectory change)."""
    if tune_artifact["target"] != "train":
        pytest.skip("serve artifact")
    for block in ("winner", "baseline"):
        ov = tune_artifact[block]["overrides"]
        accum = ov["training.gradient_accumulation_steps"]
        assert (
            ov["training.batch_size"] * accum
            == tune_artifact["workload"]["spec"]["batch"]
        ), (block, ov)


def test_tune_artifact_determinism_block(tune_artifact):
    det = tune_artifact["determinism"]
    assert det["reruns"] >= 2
    assert det["winner_stable"] is True
    assert det["fingerprints_equal"] is True
    assert len(det["fingerprint"]) == 16


def test_tune_artifact_workload_hash_rederivable(tune_artifact):
    """The embedded workload spec must hash to the embedded hash — the
    byte-identical-replay claim is checkable from the artifact alone."""
    spec = tune_artifact["workload"]["spec"]
    assert at.workload_hash(spec) == tune_artifact["workload_hash"]


def test_tune_artifact_winner_overrides_apply_cleanly(tune_artifact):
    """The winner must load back through the SAME validated path --tuned
    uses (a committed artifact that train.py would refuse at apply time
    is worse than none)."""
    base = (
        Config() if tune_artifact["target"] == "train" else _serve_base_cfg()
    )
    overrides = at.winner_overrides(tune_artifact)
    assert overrides  # non-empty
    apply_dotted_overrides(base, overrides)  # must not raise


def test_winner_overrides_fall_back_to_space_mapping():
    art = {
        "winner": {"knobs": {"overlap_comm": True}},
        "space": {"overlap_comm": {"field": "mesh.overlap_comm"}},
    }
    assert at.winner_overrides(art) == {"mesh.overlap_comm": True}
    with pytest.raises(ValueError, match="no field mapping"):
        at.winner_overrides({"winner": {"knobs": {"x": 1}}, "space": {}})


# ------------------------------------------------------ --tuned gating


def _tuned_artifact(platform=None, model="test", target="train"):
    # the matching platform is THIS process' block (device_count included:
    # 8 virtual devices under the test env — a 1-device artifact must not
    # match it, and vice versa)
    return {
        "target": target, "model": model,
        "platform": platform or _bench_common().platform_block(),
        "workload_hash": "abc123",
        "value": 1.2,
        "winner": {
            "knobs": {"overlap_comm": True},
            "overrides": {"mesh.overlap_comm": True},
        },
    }


def test_check_tuned_matching_passes_and_mismatches_name_offender():
    bc = _bench_common()
    here = bc.platform_block()
    ok, reasons = bc.check_tuned(
        _tuned_artifact(), platform=here, model="test", target="train"
    )
    assert ok and not reasons
    ok, reasons = bc.check_tuned(
        _tuned_artifact({"backend": "tpu", "device": "v5e"}),
        platform=here, model="test", target="train",
    )
    assert not ok and any("platform" in r for r in reasons)
    ok, reasons = bc.check_tuned(
        _tuned_artifact(), platform=here, model="1_3b", target="train"
    )
    assert not ok and any("model" in r for r in reasons)
    ok, reasons = bc.check_tuned(
        _tuned_artifact(), platform=here, model="test", target="serve"
    )
    assert not ok and any("target" in r for r in reasons)
    ok, reasons = bc.check_tuned(
        _tuned_artifact(), platform=here, model="test",
        workload_hash="other", target="train",
    )
    assert not ok and any("workload" in r for r in reasons)
    # not a TUNE artifact at all
    ok, reasons = bc.check_tuned({"metric": "x"}, platform=here)
    assert not ok and any("winner" in r for r in reasons)


def test_train_apply_tuned_applies_refuses_and_respects_user(tmp_path):
    import train as train_mod

    art = _tuned_artifact()
    path = tmp_path / "TUNE_train.json"
    path.write_text(json.dumps(art))
    cfg = Config()
    # matching artifact (this box IS cpu/cpu under the test env): applied
    tuned_cfg = train_mod.apply_tuned(cfg, path, {})
    assert tuned_cfg.mesh.overlap_comm is True
    # an explicit --set of the same field wins over the tuned value
    kept = train_mod.apply_tuned(cfg, path, {"mesh.overlap_comm": False})
    assert kept.mesh.overlap_comm is False
    # coupled fields apply or drop TOGETHER: overriding accum must also
    # drop the tuned batch_size (half the pair would silently change the
    # global batch the pairing exists to freeze)
    art_pair = _tuned_artifact()
    art_pair["winner"]["overrides"] = {
        "training.gradient_accumulation_steps": 4,
        "training.batch_size": 2,
        "mesh.zero_stage": 2,
    }
    path.write_text(json.dumps(art_pair))
    half = train_mod.apply_tuned(
        cfg, path, {"training.gradient_accumulation_steps": 1}
    )
    assert half.training.batch_size == cfg.training.batch_size  # untouched
    assert half.mesh.zero_stage == 2  # uncoupled tuned fields still apply
    # restore the simple artifact for the remaining cases
    path.write_text(json.dumps(art))
    # foreign platform: REFUSED, hand defaults stand
    art["platform"] = {"backend": "tpu", "device": "v5e"}
    path.write_text(json.dumps(art))
    assert train_mod.apply_tuned(cfg, path, {}) == cfg
    # model mismatch: refused
    art["platform"] = {"backend": "cpu", "device": "cpu"}
    art["model"] = "1_3b"
    path.write_text(json.dumps(art))
    assert train_mod.apply_tuned(cfg, path, {}) == cfg
    # unreadable artifact: refused, not crashed
    assert train_mod.apply_tuned(cfg, tmp_path / "missing.json", {}) == cfg


def test_serve_resolve_tuned_args(tmp_path):
    from zero_transformer_tpu.serve import _TUNED_KNOBS, _resolve_tuned_args
    from zero_transformer_tpu.config import ServingConfig

    defaults = ServingConfig()

    def args(tuned=None, **explicit):
        ns = SimpleNamespace(
            model="test", tuned=tuned, no_fused_tail=None,
            repetition_penalty=1.0,
            **{k: None for k in _TUNED_KNOBS},
        )
        for k, v in explicit.items():
            setattr(ns, k, v)
        return ns

    # no artifact: ServingConfig hand defaults fill the sentinels
    a = _resolve_tuned_args(args())
    assert a.page_size == defaults.page_size
    assert a.draft_k == defaults.draft_k
    assert a.no_fused_tail is (not defaults.fused_tail)
    # matching artifact: winner knobs become the defaults...
    art = _tuned_artifact(target="serve")
    art["winner"] = {"knobs": {"draft_k": 4, "page_size": 8,
                               "fused_tail": True}}
    path = tmp_path / "TUNE_serve.json"
    path.write_text(json.dumps(art))
    a = _resolve_tuned_args(args(tuned=str(path)))
    assert a.draft_k == 4 and a.page_size == 8
    # ...but an explicit flag still wins
    a = _resolve_tuned_args(args(tuned=str(path), draft_k=0))
    assert a.draft_k == 0 and a.page_size == 8
    # a tuned draft_k that the engine would silently drop (repetition
    # penalty != 1.0) is refused AT RESOLUTION with the remedy — the
    # headline tuned knob must never vanish downstream of the banner
    a = _resolve_tuned_args(args(tuned=str(path), repetition_penalty=1.1))
    assert a.draft_k == defaults.draft_k  # tuned draft_k dropped loudly
    assert a.page_size == 8  # the compatible tuned knobs still apply
    # platform mismatch: refused loudly, hand defaults stand
    art["platform"] = {"backend": "tpu", "device": "v5e"}
    path.write_text(json.dumps(art))
    a = _resolve_tuned_args(args(tuned=str(path)))
    assert a.draft_k == defaults.draft_k
    assert a.page_size == defaults.page_size


# --------------------------------------------------- bench_common gates


def test_hardware_gate_semantics():
    bc = _bench_common()
    a = {"platform": {"backend": "cpu", "device": "x"}}
    b = {"platform": {"backend": "tpu", "device": "v4"}}
    ok, reason = bc.hardware_gate(a, dict(a))
    assert ok and reason is None
    ok, reason = bc.hardware_gate(a, b)
    assert not ok and "SKIP" in reason and "mismatch" in reason
    ok, reason = bc.hardware_gate({}, a)
    assert not ok and "SKIP" in reason and "lacks" in reason
    # an EMPTY platform block is as unknown as a missing one: two equal
    # empty blocks must skip, never grade perf on unidentified hardware
    ok, reason = bc.hardware_gate({"platform": {}}, {"platform": {}})
    assert not ok and "SKIP" in reason
    # the train guard's two-field form
    t = {"platform": "cpu", "device_kind": "cpu"}
    ok, _ = bc.hardware_gate(t, dict(t), fields=("platform", "device_kind"))
    assert ok
    ok, reason = bc.hardware_gate(
        t, {"platform": "tpu", "device_kind": "v5e"},
        fields=("platform", "device_kind"), what="timing not comparable",
    )
    assert not ok and "timing not comparable" in reason


def test_correctness_gate_requires_metric_and_platform():
    bc = _bench_common()
    base = {"metric": "m", "platform": {"backend": "cpu"}}
    assert bc.correctness_gate(base, dict(base))
    assert not bc.correctness_gate({"metric": "other",
                                    "platform": base["platform"]}, base)
    assert not bc.correctness_gate({"metric": "m"}, base)
    assert not bc.correctness_gate(
        base, {"metric": "m", "platform": {"backend": "tpu"}}
    )


def test_provenance_gate():
    bc = _bench_common()
    ok, reason = bc.provenance_gate({"provenance": "measured"},
                                    {"provenance": "measured"})
    assert ok and reason is None
    ok, reason = bc.provenance_gate({"provenance": "measured"},
                                    {"provenance": "projected_v5e"})
    assert not ok and "provenance" in reason


# --------------------------------------------- workload spec resolution


def test_workload_spec_resolution_and_hash(tmp_path):
    loadgen = _file_module("serve_loadgen", REPO / "scripts" / "serve_loadgen.py")
    spec_path = REPO / "configs" / "workloads" / "tune_serve.json"
    args1 = loadgen.parse_args(["--workload", str(spec_path)])
    name1, spec1, hash1 = loadgen.resolve_workload(args1)
    args2 = loadgen.parse_args(["--workload", str(spec_path),
                                "--requests", "99"])
    name2, spec2, hash2 = loadgen.resolve_workload(args2)
    # the spec file is the frozen source of truth: the CLI's --requests is
    # overwritten by the file, so the resolved workloads are identical
    assert name1 == name2 == "tune_serve_v1"
    assert spec1 == spec2 and hash1 == hash2
    assert args2.requests == spec1["requests"]
    # the resolved request mix replays byte-identically
    reqs1 = loadgen.make_requests(args1, 256, spec1["cache_len"])
    reqs2 = loadgen.make_requests(args2, 256, spec2["cache_len"])
    assert reqs1 == reqs2 and len(reqs1) == spec1["requests"]
    # a different workload hashes differently
    other = dict(spec1, max_new_tokens=spec1["max_new_tokens"] + 1)
    assert at.workload_hash(other) != hash1
    # shared-prefix traffic derives its prefix from the prefill chunk, so
    # there the chunk is part of the workload identity: different chunks
    # must never carry the same hash
    sp8 = loadgen.parse_args(["--shared-prefix", "--prefill-chunk", "8"])
    sp16 = loadgen.parse_args(["--shared-prefix", "--prefill-chunk", "16"])
    assert loadgen.resolve_workload(sp8)[2] != loadgen.resolve_workload(sp16)[2]
    # unknown keys are an error, not silently different traffic
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"name": "x", "reqests": 4}))
    args3 = loadgen.parse_args(["--workload", str(bad)])
    with pytest.raises(SystemExit, match="unknown keys"):
        loadgen.resolve_workload(args3)
    # the committed TUNE_serve.json was tuned under the committed spec
    tune_path = REPO / "TUNE_serve.json"
    if tune_path.exists():
        art = json.loads(tune_path.read_text())
        assert art["workload_hash"] == hash1


# ---------------------------------------------------- analytic memory


def test_analytic_memory_is_machine_readable_and_schedule_aware():
    from zero_transformer_tpu.analysis.memory import (
        analytic_memory,
        pp_stash_ticks,
    )

    cfg = Config()
    base = analytic_memory(cfg, n_devices=8)
    assert base["exact"] is False and base["provenance"] == "analytic"
    assert base["peak_bytes_est"] > base["per_device_state_bytes_est"] > 0
    # ZeRO-3 shards params 8x vs stage 0
    z0 = analytic_memory(
        apply_dotted_overrides(cfg, {"mesh.zero_stage": 0}), n_devices=8
    )
    z3 = analytic_memory(
        apply_dotted_overrides(cfg, {"mesh.zero_stage": 3}), n_devices=8
    )
    assert z3["per_device_params_bytes"] * 8 == z0["per_device_params_bytes"]
    assert z3["per_device_opt_state_bytes"] < z0["per_device_opt_state_bytes"]
    # the overlap gather buffer only appears with overlap_comm
    ov = analytic_memory(
        apply_dotted_overrides(cfg, {"mesh.overlap_comm": True}), n_devices=8
    )
    assert ov["overlap_gather_buffer_bytes_est"] > 0
    assert "overlap_gather_buffer_bytes_est" not in base
    # the stash formula table is the trainer's (one source of truth)
    assert pp_stash_ticks("gpipe", 8, 4, 1) == 11
    assert pp_stash_ticks("1f1b", 8, 4, 1) == 8
    assert pp_stash_ticks("interleaved", 8, 4, 2) == 19


def test_analytic_memory_cli_json(capsys):
    from zero_transformer_tpu.analysis.memory import main

    main(["--cfg", str(REPO / "configs" / "train_test.yaml"),
          "--set", "mesh.zero_stage=2", "--devices", "8", "--json"])
    out = json.loads(capsys.readouterr().out.strip())
    assert out["zero_stage"] == 2 and out["n_devices"] == 8
    assert out["peak_bytes_est"] > 0


# ------------------------------------------------- end-to-end smoke lane


@pytest.mark.slow
def test_tune_smoke_end_to_end(tmp_path):
    """make tune-smoke in-process: tiny space, 2 measured trials, schema +
    determinism (same winner and trace fingerprint across two passes).
    Slow lane: it runs real engine trials; tier-1 pins the mechanics and
    the committed-artifact schema above."""
    tuner = _file_module("autotune_script", REPO / "scripts" / "autotune.py")
    out = tmp_path / "TUNE_smoke.json"
    artifact = tuner.main([
        "--target", "serve", "--smoke", "--reruns", "2",
        "--out", str(out),
    ])
    on_disk = json.loads(out.read_text())
    assert on_disk == artifact
    missing = at.TUNE_REQUIRED_KEYS - artifact.keys()
    assert not missing, sorted(missing)
    assert artifact["determinism"]["winner_stable"] is True
    assert artifact["determinism"]["fingerprints_equal"] is True
    assert artifact["pruning"]["enumerated"] == 4
    assert artifact["pruning"]["pruned_frac"] >= 0.5
    # the winner's final-rung trial was byte-verified against generate()
    assert artifact["winner"]["metrics"]["mismatches"] == 0
