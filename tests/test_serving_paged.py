"""Paged KV cache + speculative serving: the ISSUE 6 parity and
refcount suite.

Two load-bearing claims:

- **Paged ≡ slab, bitwise.** Block-table paging only changes where K/V
  bytes live, so a paged engine's token trajectories must be byte-identical
  to the slab engine's AND to single-request ``generate()`` — across
  position schemes (ALiBi / RoPE / learned), the int8 KV cache, prefix-
  cache hits (which are page-refcount bumps, not span copies), and chunked
  prefill whose chunks cross page boundaries.
- **Greedy speculation ≡ plain decode, token-for-token.** The batched
  draft-and-verify step only ever keeps a draft the model itself would
  have emitted, so speculation changes throughput, never output; k=1
  degenerates to normal decode (plus one verified draft).

The refcount half pins what the allocator may never do: free a page a live
slot or a cached prefix still maps, or evict an LRU entry that a deeper
cached chunk depends on. Everything runs the ``test`` zoo model on CPU in
float32 (bitwise claims need a deterministic backend).
"""
import jax
import jax.numpy as jnp
import pytest

from zero_transformer_tpu.config import model_config
from zero_transformer_tpu.inference.generate import decode_model, generate
from zero_transformer_tpu.inference.sampling import SamplingConfig
from zero_transformer_tpu.models import Transformer
from zero_transformer_tpu.serving import PrefixCache, ServingEngine

CACHE_LEN = 48
SAMPLING = SamplingConfig(temperature=0.9, top_k=20)
GREEDY = SamplingConfig(greedy=True, temperature=0.9, top_k=20)


@pytest.fixture(scope="module")
def cfg():
    return model_config("test", dropout=0.0, compute_dtype="float32")


@pytest.fixture(scope="module")
def params(cfg):
    model = Transformer(cfg)
    return model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))[
        "params"
    ]


@pytest.fixture(scope="module")
def reference(cfg, params):
    model = decode_model(cfg, CACHE_LEN)

    def run(prompt, seed, max_new=8, sampling=SAMPLING, p=params):
        toks = generate(
            model, p, jnp.asarray([prompt], jnp.int32), max_new,
            jax.random.PRNGKey(seed), sampling,
        )
        return jax.device_get(toks)[0].tolist()

    return run


def make_engine(cfg, params, **kw):
    kw.setdefault("n_slots", 2)
    kw.setdefault("cache_len", CACHE_LEN)
    kw.setdefault("sampling", SAMPLING)
    kw.setdefault("prefill_chunk", 8)
    kw.setdefault("kv_layout", "paged")
    kw.setdefault("page_size", 4)
    return ServingEngine(cfg, params, **kw)


def _prompt(length, offset=0):
    return [(3 + offset + i) % 250 + 1 for i in range(length)]


# ------------------------------------------------------------------- parity


def test_paged_equals_slab_and_generate(cfg, params, reference):
    """5 mixed-length requests into 2 slots: the paged engine's every
    trajectory is byte-identical to the slab engine's and to
    single-request generate(). Lengths 9/17/31 make chunks cross page
    boundaries (chunk 8 = 2 pages of 4) and span multiple chunk ticks."""
    prompts = [_prompt(n, offset=i) for i, n in enumerate((2, 5, 9, 17, 31))]
    results = {}
    for layout in ("slab", "paged"):
        engine = make_engine(cfg, params, kv_layout=layout)
        handles = [
            engine.submit(p, max_new_tokens=8, seed=i)
            for i, p in enumerate(prompts)
        ]
        engine.run_until_idle()
        assert all(h.status == "done" for h in handles), layout
        results[layout] = [h.tokens for h in handles]
    assert results["paged"] == results["slab"]
    for i, p in enumerate(prompts):
        assert results["paged"][i] == reference(p, i)


@pytest.mark.parametrize("position", ["rope", "learned"])
def test_paged_parity_other_positions(position):
    """RoPE rotation and the learned-position decode_pos vector both ride
    the per-slot index through the paged write/gather path unchanged."""
    pcfg = model_config(
        "test", dropout=0.0, compute_dtype="float32", position=position
    )
    pparams = Transformer(pcfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    cache_len = pcfg.max_seq_len if position == "learned" else CACHE_LEN
    model = decode_model(pcfg, cache_len)
    prompt = _prompt(13)
    ref = jax.device_get(
        generate(model, pparams, jnp.asarray([prompt], jnp.int32), 6,
                 jax.random.PRNGKey(5), SAMPLING)
    )[0].tolist()
    engine = make_engine(pcfg, pparams, cache_len=cache_len, prefill_chunk=4)
    handle = engine.submit(prompt, max_new_tokens=6, seed=5)
    engine.run_until_idle()
    assert handle.status == "done" and handle.tokens == ref


def test_paged_int8_kv_parity(params):
    """int8 K/V + f32 scale leaves pool-shaped: quantize on write, dequant
    on the gathered view — still token-identical to generate()."""
    qcfg = model_config(
        "test", dropout=0.0, compute_dtype="float32", kv_cache_dtype="int8"
    )
    model = decode_model(qcfg, CACHE_LEN)
    prompt = _prompt(11)
    ref = jax.device_get(
        generate(model, params, jnp.asarray([prompt], jnp.int32), 8,
                 jax.random.PRNGKey(3), SAMPLING)
    )[0].tolist()
    engine = make_engine(qcfg, params, prefill_chunk=4, prefix_cache_chunks=8)
    handle = engine.submit(prompt, max_new_tokens=8, seed=3)
    engine.run_until_idle()
    assert handle.status == "done" and handle.tokens == ref
    # and a prefix hit over int8 PAGES stays exact too
    again = engine.submit(prompt, max_new_tokens=8, seed=3)
    engine.run_until_idle()
    assert again.prefix_hit_tokens > 0 and again.tokens == ref


def test_paged_prefix_hit_is_refcount_not_copy(cfg, params, reference):
    """A shared-prefix admission maps the CACHED pages into the new slot's
    block table (refcounts bump) instead of copying spans — and the
    trajectory stays byte-identical to generate()."""
    engine = make_engine(cfg, params, prefix_cache_chunks=16)
    prefix = _prompt(16, offset=40)
    a = engine.submit(prefix + _prompt(3, offset=7), max_new_tokens=6, seed=0)
    engine.run_until_idle()
    # the banked pages are held by BOTH the index and nothing else now
    banked = [
        p for pages in engine._prefix_cache._entries.values() for p in pages
    ]
    assert banked and all(engine.slots.pool.refs[p] >= 1 for p in banked)
    b = engine.submit(prefix + _prompt(4, offset=90), max_new_tokens=6, seed=1)
    engine.step()  # admit: the hit shares pages with the index
    shared = [
        p for p in banked if engine.slots.pool.refs[p] >= 2
    ]
    assert shared, "prefix hit did not bump any page refcount"
    engine.run_until_idle()
    assert b.prefix_hit_tokens == 16
    assert a.tokens == reference(prefix + _prompt(3, offset=7), 0, max_new=6)
    assert b.tokens == reference(prefix + _prompt(4, offset=90), 1, max_new=6)
    snap = engine.metrics_snapshot()
    assert snap["prefix_hits"] == 2 and snap["cow_copies"] == 0


# --------------------------------------------------------------- refcounts


def test_release_never_frees_cache_held_pages(cfg, params):
    """Retiring a slot decrefs its pages; pages the prefix index still
    holds survive (refcount 1) and serve a later hit — the satellite's
    'never free a page a longer-lived reference still maps'."""
    engine = make_engine(cfg, params, n_slots=1, prefix_cache_chunks=16)
    prompt = _prompt(16, offset=3) + [7, 8]
    h = engine.submit(prompt, max_new_tokens=4, seed=0)
    engine.run_until_idle()
    assert h.status == "done"
    banked = [
        p for pages in engine._prefix_cache._entries.values() for p in pages
    ]
    # the slot retired, so ONLY the index holds these pages now
    assert banked and all(engine.slots.pool.refs[p] == 1 for p in banked)
    in_use_before = engine.slots.pool.in_use
    assert in_use_before >= len(banked)
    # flush drops the index's references -> pages return to the free list
    engine._prefix_cache.flush()
    assert all(engine.slots.pool.refs[p] == 0 for p in banked)
    assert engine.slots.pool.in_use == in_use_before - len(banked)


def test_index_eviction_is_refcount_aware(cfg, params):
    """Reclaim under allocation pressure never frees (or even evicts) an
    entry whose pages a live slot still maps — evicting it would gain zero
    capacity and cost the hit. Once the slot retires, the pages become
    index-only and reclaim frees them."""
    engine = make_engine(
        cfg, params, n_slots=1, prefix_cache_chunks=2
    )
    prompt = _prompt(16, offset=11) + [9]
    hog = engine.submit(prompt, max_new_tokens=20, seed=0)
    # run prefill to completion (banks 2 chunks), then stay mid-decode
    for _ in range(4):
        engine.step()
    assert hog.status == "running"
    banked = [
        p for pages in engine._prefix_cache._entries.values() for p in pages
    ]
    assert banked and all(engine.slots.pool.refs[p] == 2 for p in banked)
    freed = engine._prefix_cache.reclaim(len(banked))
    # nothing freeable: every page is slot-mapped, so the HOT entries stay
    assert freed == 0 and len(engine._prefix_cache) == 2
    assert all(engine.slots.pool.refs[p] == 2 for p in banked)
    engine.run_until_idle()
    assert hog.status == "done"  # the slot kept valid K/V throughout
    # slot retired -> pages are index-only; now reclaim really frees
    assert all(engine.slots.pool.refs[p] == 1 for p in banked)
    freed = engine._prefix_cache.reclaim(len(banked))
    assert freed == len(banked)
    assert all(engine.slots.pool.refs[p] == 0 for p in banked)


def test_prefix_lru_evicts_leaves_before_parents():
    """The slab-era LRU bug: after a lookup touches chunks 1..k in order,
    the LRU front is the SHALLOWEST chunk — evicting it orphans every
    deeper entry. Eviction must take the least-recent LEAF instead."""
    pc = PrefixCache(chunk_tokens=4, capacity=3)
    p1 = list(range(1, 14))  # chunks at 4, 8, 12
    pc.store(p1, 1, "c1")
    pc.store(p1, 2, "c2")
    pc.store(p1, 3, "c3")
    fill, spans = pc.lookup(p1)  # LRU order now: c1, c2, c3 (front = c1)
    assert fill == 12
    other = [99] + p1[1:]
    pc.store(other, 1, "x1")  # forces one eviction
    assert pc.evictions == 1
    # the chain c1 -> c2 survives intact: the LEAF c3 was evicted, not c1
    fill, spans = pc.lookup(p1)
    assert fill == 8 and spans == ["c1", "c2"]


def test_paged_admission_waits_when_pool_exhausted(cfg, params):
    """Admission reserves a request's worst case up front: when the pool
    cannot cover it, the request WAITS (no preemption, no mid-decode
    fault) and admits once a retirement frees pages."""
    # pool of 32 tokens = 8 pages; each request needs ~6 pages
    engine = make_engine(
        cfg, params, n_slots=4, page_pool_tokens=32, prefill_chunk=4,
    )
    a = engine.submit(_prompt(8), max_new_tokens=12, seed=0)
    b = engine.submit(_prompt(8, offset=30), max_new_tokens=12, seed=1)
    for _ in range(3):
        engine.step()
    # only one fits: the other waits in the queue despite 4 free slots
    assert a.status == "running" and b.status == "queued"
    assert engine.queue_depth == 1
    engine.run_until_idle()
    assert a.status == "done" and b.status == "done"
    assert engine.stats["preemptions"] == 0


# ------------------------------------------------------------- speculation


@pytest.mark.parametrize("layout", ["slab", "paged"])
@pytest.mark.parametrize("draft_k", [1, 4])
def test_spec_greedy_matches_plain_decode(cfg, params, reference, layout, draft_k):
    """Greedy speculative serving is token-for-token identical to plain
    greedy decode (and therefore to generate()) on both KV layouts;
    draft_k=1 is the degenerate single-draft case."""
    prompts = [_prompt(n, offset=i) for i, n in enumerate((3, 7, 12))]
    engine = make_engine(
        cfg, params, kv_layout=layout, sampling=GREEDY, draft_k=draft_k
    )
    handles = [
        engine.submit(p, max_new_tokens=12, seed=i)
        for i, p in enumerate(prompts)
    ]
    engine.run_until_idle()
    for i, (p, h) in enumerate(zip(prompts, handles)):
        assert h.status == "done", (h.status, h.error)
        assert h.tokens == reference(p, i, max_new=12, sampling=GREEDY)
    snap = engine.metrics_snapshot()
    assert snap["spec_ticks"] > 0 and snap["draft_tokens"] > 0


def test_spec_stochastic_completes_and_respects_budget(cfg, params):
    """Stochastic speculation (rejection rule) completes every request at
    its exact budget; trajectories are distribution- not byte-preserving,
    so only structure is pinned here (the rule's math in
    test_speculative.py)."""
    engine = make_engine(cfg, params, draft_k=3)
    handles = [
        engine.submit(_prompt(4, offset=i), max_new_tokens=9, seed=i)
        for i in range(3)
    ]
    engine.run_until_idle()
    assert all(h.status == "done" and len(h.tokens) == 9 for h in handles)


def test_spec_eos_mid_block_truncates(cfg, params, reference):
    """An EOS accepted mid-block ends the stream AT the EOS token — the
    remaining accepted drafts are discarded, matching generate()'s
    contract."""
    plain = reference(_prompt(5), 0, max_new=12, sampling=GREEDY)
    eos = plain[3]
    # greedy output may repeat: the stream ends at the FIRST occurrence
    want = plain[: plain.index(eos) + 1]
    engine = make_engine(
        cfg, params, sampling=GREEDY, draft_k=4, eos_token_id=eos
    )
    h = engine.submit(_prompt(5), max_new_tokens=12, seed=0)
    engine.run_until_idle()
    assert h.status == "done" and h.tokens == want


def test_spec_headroom_validation(cfg, params):
    """The verify forward writes draft_k positions past the cursor before
    rewinding; a request whose worst case would clamp into its own tail
    rejects at submit."""
    engine = make_engine(cfg, params, sampling=GREEDY, draft_k=4)
    bad = engine.submit(_prompt(8), max_new_tokens=CACHE_LEN - 8)
    assert bad.status == "rejected" and "draft_k" in bad.error


def test_custom_draft_fn_is_clamped(cfg, params, reference):
    """A pluggable draft source that misbehaves (wrong length, out-of-vocab
    ids) degrades acceptance, never correctness."""
    engine = make_engine(
        cfg, params, sampling=GREEDY, draft_k=3,
        draft_fn=lambda hist, k: [10 ** 9, -5],  # garbage on purpose
    )
    h = engine.submit(_prompt(6), max_new_tokens=8, seed=0)
    engine.run_until_idle()
    assert h.status == "done"
    assert h.tokens == reference(_prompt(6), 0, max_new=8, sampling=GREEDY)


def test_spec_requires_no_repetition_penalty(cfg, params):
    with pytest.raises(ValueError, match="repetition_penalty"):
        make_engine(
            cfg, params, draft_k=2,
            sampling=SamplingConfig(repetition_penalty=1.2),
        )


# ---------------------------------------------------------------- allocator


def test_page_pool_unit():
    from zero_transformer_tpu.serving.slots import PagePool

    pool = PagePool(5)  # trash + 4 real
    assert pool.free_count == 4 and pool.in_use == 0
    a, b = pool.alloc(), pool.alloc()
    assert pool.in_use == 2
    pool.incref([a])
    assert pool.decref([a]) == 0  # still slot-held
    assert pool.decref([a]) == 1  # last reference frees
    with pytest.raises(ValueError):
        pool.decref([a])
    pool.reserved = 2
    assert pool.available == pool.free_count - 2
    assert pool.decref([b]) == 1
