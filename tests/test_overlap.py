"""Overlapped ZeRO communication (parallel/overlap.py): parity is the contract.

The overlapped step moves WHERE the collectives sit (per-layer gathers and
scatters inside the layer scan instead of one serial bracket) — it must not
move WHAT is computed. These tests pin:

- overlap-on ≡ overlap-off BITWISE at ZeRO-1 and ZeRO-2, including the
  optimizer trajectory over multiple steps (the A/B arms the step bench
  times share one core; a fast wrong arm must never win the A/B);
- the overlapped step ≡ the legacy serial explicit core BITWISE at ZeRO-2
  (same shard_map collective schedule, different placement only); ZeRO-1's
  legacy step is a GSPMD program with a different reduction order, so the
  cross-core pin there is allclose;
- bucket derivation comes from the ShardingPlan (layer count, byte sizes,
  the scan_layers requirement) — never a hand-list;
- the config/build seams refuse the combinations the design excludes
  (pipe meshes, ZeRO stage 0, unscanned layers) loudly.
"""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from zero_transformer_tpu.config import MeshConfig, ModelConfig, OptimizerConfig
from zero_transformer_tpu.models import Transformer
from zero_transformer_tpu.parallel import (
    make_mesh,
    make_plan,
    init_train_state,
    make_train_step,
)
from zero_transformer_tpu.parallel.overlap import (
    bucket_summary,
    derive_buckets,
    make_overlap_zero_step,
)
from zero_transformer_tpu.training.optimizer import make_optimizer, make_schedule

CFG = ModelConfig(
    name="t", vocab_size=256, d_model=64, n_heads=4, n_layers=2, max_seq_len=32,
    dropout=0.0, compute_dtype="float32",
)
OPT = OptimizerConfig(peak_learning_rate=1e-3, warmup_steps=4, total_steps=64)


def _setup(zero_stage, model_cfg=CFG):
    mesh = make_mesh(MeshConfig(zero_stage=zero_stage))
    model = Transformer(model_cfg)
    tx = make_optimizer(OPT)
    plan = make_plan(model, tx, mesh, (2, 16), zero_stage)
    return mesh, model, tx, plan


def _fresh(model, tx, mesh, plan):
    return init_train_state(model, tx, jax.random.PRNGKey(0), mesh, (2, 16), plan)


def _batch(accum=2, bs=8, T=16, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.integers(0, CFG.vocab_size, (accum, bs, T)), jnp.int32)


def _params_bitwise(a, b):
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


@pytest.mark.parametrize("zero_stage", [1, 2])
def test_overlap_on_off_bitwise(devices, zero_stage):
    """The A/B arms: identical compute, collective placement the only
    difference — params bitwise after a 2-step optimizer trajectory."""
    mesh, model, tx, plan = _setup(zero_stage)
    rng = jax.random.PRNGKey(7)
    states, losses = {}, {}
    for overlap in (False, True):
        step = make_overlap_zero_step(
            model, tx, mesh, plan, zero_stage, make_schedule(OPT),
            overlap=overlap,
        )
        state = _fresh(model, tx, mesh, plan)
        for i in range(2):
            state, metrics = step(state, _batch(seed=i), rng)
        states[overlap], losses[overlap] = state, float(metrics["loss"])
    assert losses[True] == losses[False]
    _params_bitwise(states[True].params, states[False].params)
    _params_bitwise(states[True].opt_state, states[False].opt_state)


def test_overlap_matches_legacy_serial_core_zero2(devices):
    """make_train_step(overlap_comm=True) vs the legacy ZeRO-2 explicit
    core: same shard_map collective schedule, so bitwise, trajectory
    included."""
    mesh, model, tx, plan = _setup(2)
    rng = jax.random.PRNGKey(7)
    results = {}
    for overlap in (False, True):
        step = make_train_step(
            model, tx, mesh, plan, 2, make_schedule(OPT), overlap_comm=overlap
        )
        state = _fresh(model, tx, mesh, plan)
        for i in range(3):
            state, metrics = step(state, _batch(seed=i), rng)
        results[overlap] = (state, float(metrics["loss"]))
    assert results[True][1] == results[False][1]
    _params_bitwise(results[True][0].params, results[False][0].params)


def test_overlap_close_to_legacy_gspmd_zero1(devices):
    """ZeRO-1's legacy step is a GSPMD program (pmean all-reduce) — a
    different reduction order from the overlap core's reduce-scatter +
    gather, so the pin is allclose, not bitwise."""
    mesh, model, tx, plan = _setup(1)
    rng = jax.random.PRNGKey(7)
    results = {}
    for overlap in (False, True):
        step = make_train_step(
            model, tx, mesh, plan, 1, make_schedule(OPT), overlap_comm=overlap
        )
        state = _fresh(model, tx, mesh, plan)
        for i in range(2):
            state, metrics = step(state, _batch(seed=i), rng)
        results[overlap] = (state, float(metrics["loss"]))
    np.testing.assert_allclose(results[True][1], results[False][1], rtol=2e-5)
    for a, b in zip(
        jax.tree.leaves(results[True][0].params),
        jax.tree.leaves(results[False][0].params),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=3e-6)


@pytest.mark.slow
def test_overlap_with_remat_bitwise(devices):
    """Under remat the gather sits inside the checkpointed region (backward
    re-gathers); placement still must not change the math."""
    cfg = dataclasses.replace(CFG, remat=True)
    mesh, model, tx, plan = _setup(2, model_cfg=cfg)
    rng = jax.random.PRNGKey(7)
    states = {}
    for overlap in (False, True):
        step = make_overlap_zero_step(
            model, tx, mesh, plan, 2, make_schedule(OPT), overlap=overlap
        )
        state = _fresh(model, tx, mesh, plan)
        state, _ = step(state, _batch(), rng)
        states[overlap] = state
    _params_bitwise(states[True].params, states[False].params)


def test_overlap_learns(devices):
    mesh, model, tx, plan = _setup(2)
    step = make_train_step(
        model, tx, mesh, plan, 2, make_schedule(OPT), overlap_comm=True
    )
    state = _fresh(model, tx, mesh, plan)
    rng = jax.random.PRNGKey(42)
    losses = []
    for _ in range(15):
        state, metrics = step(state, _batch(accum=1, seed=0), rng)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0] - 0.5, f"no learning: {losses}"


def test_bucket_derivation_from_plan(devices):
    """Buckets come from the plan's logical specs: one per layer + dense."""
    mesh, model, tx, plan = _setup(2)
    abstract = jax.eval_shape(
        lambda r: model.init(r, jnp.zeros((1, 8), jnp.int32)),
        jax.random.PRNGKey(0),
    )["params"]
    from zero_transformer_tpu.parallel.sharding import unbox

    abstract = unbox(abstract)
    b = derive_buckets(plan, mesh, abstract)
    assert b.n_layers == CFG.n_layers
    assert b.n_buckets == CFG.n_layers + 1
    blocks_bytes = sum(
        leaf.size * jnp.dtype(leaf.dtype).itemsize
        for leaf in jax.tree.leaves(abstract["blocks"])
    )
    assert b.layer_bucket_bytes == blocks_bytes // CFG.n_layers
    summary = bucket_summary(plan, mesh, abstract)
    assert summary["n_layer_buckets"] == CFG.n_layers
    # two gathered layers live during the telescoping prefetch
    assert summary["overlap_gather_buffer_bytes"] == 2 * b.layer_bucket_bytes


def test_overlap_requires_scan_layers(devices):
    cfg = dataclasses.replace(CFG, scan_layers=False)
    mesh, model, tx, plan = _setup(2, model_cfg=cfg)
    with pytest.raises(ValueError, match="scan_layers"):
        make_overlap_zero_step(model, tx, mesh, plan, 2)


def test_overlap_config_validation():
    with pytest.raises(ValueError, match="overlap_comm"):
        MeshConfig(overlap_comm=True, pipe=2, data=4)
    with pytest.raises(ValueError, match="zero_stage"):
        MeshConfig(overlap_comm=True, zero_stage=0)
    # valid combination constructs
    MeshConfig(overlap_comm=True, zero_stage=2)


def test_overlap_build_rejects_stage0_and_pipe(devices):
    mesh, model, tx, plan = _setup(0)
    with pytest.raises(ValueError, match="zero_stage"):
        make_train_step(
            model, tx, mesh, plan, 0, make_schedule(OPT), overlap_comm=True
        )
    mesh_pp = make_mesh(MeshConfig(pipe=2, data=4))
    model_pp = Transformer(dataclasses.replace(CFG, n_layers=4))
    tx_pp = make_optimizer(OPT)
    plan_pp = make_plan(model_pp, tx_pp, mesh_pp, (2, 16), 1)
    with pytest.raises(ValueError, match="pipe"):
        make_train_step(
            model_pp, tx_pp, mesh_pp, plan_pp, 1, make_schedule(OPT),
            overlap_comm=True,
        )


def test_overlap_psums_indivisible_leaves(devices):
    """Leaves with no dim divisible by the ZeRO world (d_model=68 on 8
    devices: ln scales, attention kernels) are stored replicated and get no
    gather — so autodiff gives their grads no collective. The overlap core
    must psum them explicitly (as the serial core's reduce_grads does) or
    replicas silently diverge; pinned bitwise against the legacy serial
    core, which handles them correctly."""
    cfg = dataclasses.replace(CFG, d_model=68, n_heads=4)
    mesh, model, tx, plan = _setup(2, model_cfg=cfg)
    from zero_transformer_tpu.parallel.mesh import zero_axes
    from zero_transformer_tpu.parallel.zero import _zero_scatter_dim

    sdims = jax.tree.map(
        lambda ns: _zero_scatter_dim(ns.spec, zero_axes(mesh)), plan.zero
    )
    assert any(d < 0 for d in jax.tree.leaves(sdims)), (
        "test premise broken: no ZeRO-replicated leaf in this model"
    )
    rng = jax.random.PRNGKey(7)
    results = {}
    for overlap in (False, True):
        step = make_train_step(
            model, tx, mesh, plan, 2, make_schedule(OPT), overlap_comm=overlap
        )
        state = _fresh(model, tx, mesh, plan)
        for i in range(2):
            state, metrics = step(state, _batch(seed=i), rng)
        results[overlap] = (state, float(metrics["loss"]))
    assert results[True][1] == results[False][1]
    _params_bitwise(results[True][0].params, results[False][0].params)
