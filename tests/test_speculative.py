"""Prompt-lookup speculative decoding: exact greedy equivalence in fewer
forwards. The acceptance rule only keeps a drafted token when it equals the
model's own argmax given the verified prefix, so the emitted sequence must be
bit-identical to plain greedy decode — on ANY model, trained or random."""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from zero_transformer_tpu.config import ModelConfig
from zero_transformer_tpu.inference import SamplingConfig, decode_model, generate
from zero_transformer_tpu.inference.speculative import generate_speculative

CFG = ModelConfig(
    name="t", vocab_size=64, d_model=32, n_heads=4, n_layers=2, max_seq_len=32,
    dropout=0.0, compute_dtype="float32",
)


def _model_and_params(cfg=CFG, cache_len=128, seed=0):
    model = decode_model(cfg, cache_len)
    params = model.init(
        jax.random.PRNGKey(seed), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return model, params


@pytest.mark.parametrize("position", ["alibi", "rope"])
@pytest.mark.parametrize("draft_len", [1, 4, 8])
def test_speculative_equals_plain_greedy(position, draft_len):
    cfg = dataclasses.replace(CFG, position=position)
    model, params = _model_and_params(cfg)
    prompt = jnp.asarray(
        np.random.default_rng(0).integers(1, 64, (1, 12)), jnp.int32
    )
    plain = generate(
        model, params, prompt, 40, jax.random.PRNGKey(0),
        SamplingConfig(greedy=True),
    )
    spec = generate_speculative(
        model, params, prompt, 40, draft_len=draft_len
    )
    np.testing.assert_array_equal(np.asarray(spec), np.asarray(plain))


@pytest.mark.parametrize("penalty", [1.1, 1.5])
def test_speculative_equals_greedy_with_repetition_penalty(penalty):
    """The penalty changes the argmax trajectory over time; the acceptance
    walk must reproduce it exactly (the evolving generated-token mask is
    threaded through the drafted block)."""
    model, params = _model_and_params()
    prompt = jnp.asarray(
        np.random.default_rng(2).integers(1, 64, (1, 12)), jnp.int32
    )
    plain = generate(
        model, params, prompt, 40, jax.random.PRNGKey(0),
        SamplingConfig(greedy=True, repetition_penalty=penalty),
    )
    spec = generate_speculative(
        model, params, prompt, 40, draft_len=4, repetition_penalty=penalty
    )
    np.testing.assert_array_equal(np.asarray(spec), np.asarray(plain))


def test_speculative_eos_and_padding():
    model, params = _model_and_params()
    prompt = jnp.asarray(
        np.random.default_rng(1).integers(1, 64, (1, 10)), jnp.int32
    )
    # use whatever greedy emits at step 3 as the "EOS" so it actually fires
    plain = generate(
        model, params, prompt, 24, jax.random.PRNGKey(0),
        SamplingConfig(greedy=True),
    )
    eos = int(plain[0, 3])
    ref = generate(
        model, params, prompt, 24, jax.random.PRNGKey(0),
        SamplingConfig(greedy=True), eos_token_id=eos, pad_token_id=0,
    )
    spec = generate_speculative(
        model, params, prompt, 24, draft_len=4, eos_token_id=eos,
        pad_token_id=0,
    )
    np.testing.assert_array_equal(np.asarray(spec), np.asarray(ref))


def test_speculative_accepts_on_repetitive_text():
    """On a strongly periodic prompt the drafts must actually be accepted:
    fewer model forwards than tokens emitted."""
    model, params = _model_and_params(cache_len=256)
    period = np.array([7, 11, 13, 17, 19, 23], np.int64)
    prompt = jnp.asarray(np.tile(period, 8)[None], jnp.int32)  # [1, 48]
    out, stats = generate_speculative(
        model, params, prompt, 64, draft_len=6, return_stats=True
    )
    assert out.shape == (1, 64)
    assert stats["forwards"] < 64, stats
    # and still exactly greedy
    plain = generate(
        model, params, prompt, 64, jax.random.PRNGKey(0),
        SamplingConfig(greedy=True),
    )
    np.testing.assert_array_equal(np.asarray(out), np.asarray(plain))


def test_speculative_guards():
    model, params = _model_and_params(cache_len=32)
    two_rows = jnp.zeros((2, 8), jnp.int32)
    with pytest.raises(ValueError, match="batch"):
        generate_speculative(model, params, two_rows, 4)
    prompt = jnp.zeros((1, 20), jnp.int32)
    with pytest.raises(ValueError, match="cache_len"):
        generate_speculative(model, params, prompt, 10, draft_len=8)
    # ADVICE r4: temperature<=0 must fail loudly (SamplingConfig parity),
    # not silently emit inf/NaN-logit garbage
    short = jnp.zeros((1, 4), jnp.int32)
    with pytest.raises(ValueError, match="temperature"):
        generate_speculative(model, params, short, 4, temperature=0.0)
    with pytest.raises(ValueError, match="temperature"):
        generate_speculative(model, params, short, 4, temperature=-1.0)
    with pytest.raises(ValueError, match="repetition_penalty"):
        generate_speculative(model, params, short, 4, repetition_penalty=0.0)


def test_speculative_temperature_shares_one_executable():
    """ADVICE r4: temperature is a traced operand of the decode loop — a
    serving knob must not trigger a full recompile per distinct value."""
    from zero_transformer_tpu.inference.speculative import _spec_loop

    model, params = _model_and_params()
    prompt = jnp.asarray(
        np.random.default_rng(6).integers(1, 64, (1, 12)), jnp.int32
    )
    generate_speculative(model, params, prompt, 8, draft_len=4, temperature=0.7)
    misses0 = _spec_loop._cache_size()
    for t in (0.8, 0.9, 1.1):
        generate_speculative(
            model, params, prompt, 8, draft_len=4, temperature=t
        )
    assert _spec_loop._cache_size() == misses0


def test_speculative_learned_positions_guard():
    cfg = dataclasses.replace(CFG, position="learned")
    model, params = _model_and_params(cfg, cache_len=128)
    prompt = jnp.zeros((1, 10), jnp.int32)
    with pytest.raises(ValueError, match="extrapolate"):
        generate_speculative(model, params, prompt, 30, draft_len=4)


def test_speculative_with_int8_kv_cache():
    """Speculation composes with the int8 KV cache: both paths run the same
    quantized model, so greedy equivalence must hold there too (the cache
    rewind must not corrupt the scale slots)."""
    cfg = dataclasses.replace(CFG, kv_cache_dtype="int8")
    model, params = _model_and_params(cfg)
    prompt = jnp.asarray(
        np.random.default_rng(5).integers(1, 64, (1, 12)), jnp.int32
    )
    plain = generate(
        model, params, prompt, 32, jax.random.PRNGKey(0),
        SamplingConfig(greedy=True),
    )
    spec = generate_speculative(model, params, prompt, 32, draft_len=4)
    np.testing.assert_array_equal(np.asarray(spec), np.asarray(plain))


@pytest.mark.parametrize("temperature", [0.7, 1.3])
def test_speculative_equals_greedy_with_temperature(temperature):
    """Greedy + temperature: FP division can collapse near-equal logits into
    a tie and flip the argmax, so the acceptance walk mirrors the SAME
    cast-then-divide transform the plain loop applies (ADVICE r3) — the
    outputs must be identical, not just argue-identical."""
    model, params = _model_and_params()
    prompt = jnp.asarray(
        np.random.default_rng(4).integers(1, 64, (1, 12)), jnp.int32
    )
    plain = generate(
        model, params, prompt, 40, jax.random.PRNGKey(0),
        SamplingConfig(greedy=True, temperature=temperature,
                       repetition_penalty=1.2),
    )
    spec = generate_speculative(
        model, params, prompt, 40, draft_len=4,
        repetition_penalty=1.2, temperature=temperature,
    )
    np.testing.assert_array_equal(np.asarray(spec), np.asarray(plain))


# ------------------------------------------------ serving-path primitives


def test_ngram_propose_prompt_lookup():
    """Host-side drafting for the SERVING tick: the continuation after the
    most recent earlier occurrence of the final bigram, offset by one
    (the tick's first token is sampled in-graph, so the draft skips the
    position it cannot know)."""
    from zero_transformer_tpu.inference.speculative import ngram_propose

    #        0  1  2  3  4  5  6  7
    hist = [5, 9, 2, 4, 7, 5, 9, 2]
    # final bigram (9, 2) matches at positions 1-2; continuation 4, 7, 5...
    # skip=1 drops the 4 (it predicts the in-graph sample) -> 7, 5
    assert ngram_propose(hist, 2) == [7, 5]
    # a repetition loop proposes the loop itself, full length
    loop = [3, 1] + [13] * 20
    assert ngram_propose(loop, 4) == [13, 13, 13, 13]
    # no earlier match / short history -> zero padding, never an error
    assert ngram_propose([1, 2, 3], 3) == [0, 0, 0]
    assert ngram_propose([4], 2) == [0, 0]
    assert ngram_propose([], 2) == [0, 0]
    assert ngram_propose(hist, 0) == []


def test_rejection_rule_reconstructs_target_distribution():
    """The serving verify step's acceptance math: a point-mass draft ``d``
    is accepted with probability p(d); on rejection the NEXT sample draws
    from the processed logits with ``d`` masked out (the engine's veto).
    accept*onehot(d) + (1-accept)*residual must equal the target p
    EXACTLY — the standard rejection-sampling identity, computed with the
    very transforms the engine uses (process_logits + NEG_INF masking),
    including their top-k/top-p interaction."""
    from zero_transformer_tpu.inference.sampling import (
        NEG_INF,
        SamplingConfig,
        process_logits,
    )

    rng = np.random.default_rng(0)
    for cfg in (
        SamplingConfig(temperature=0.9),
        SamplingConfig(temperature=1.3, top_k=8),
        SamplingConfig(top_p=0.9),
    ):
        logits = jnp.asarray(rng.normal(size=(1, 32)) * 3, jnp.float32)
        proc = process_logits(logits, cfg)
        p = np.asarray(jax.nn.softmax(proc, axis=-1))[0]
        d = int(np.argmax(rng.multinomial(1, p)))  # any in-support draft
        accept = p[d]
        vetoed = jnp.where(jnp.arange(32)[None, :] == d, NEG_INF, proc)
        residual = np.asarray(jax.nn.softmax(vetoed, axis=-1))[0]
        reconstructed = (1 - accept) * residual
        reconstructed[d] += accept
        np.testing.assert_allclose(reconstructed, p, atol=1e-6)
        assert residual[d] == 0.0  # a rejected draft can never re-emit
