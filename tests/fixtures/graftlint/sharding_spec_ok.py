"""Clean twin: every named axis is a declared mesh axis, used once."""
from jax.sharding import PartitionSpec as P

BATCH_SPEC = P("data", "tensor")
