"""True positive: a PartitionSpec naming an axis no mesh declares — it
would silently replicate (or fail deep inside pjit at first dispatch)."""
from jax.sharding import PartitionSpec as P

BATCH_SPEC = P("data", "bogus_axis")
