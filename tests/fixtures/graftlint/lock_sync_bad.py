"""True positive: a blocking device op under the engine lock — every
submit/scrape stalls for the sync's duration."""
import jax


def scrape(self):
    with self.lock:
        return jax.device_get(self.counters)
