"""True positive: ``time.time()`` where span/trace timestamps must ride
one monotonic clock (NTP steps would tear the timeline)."""
import time


def span_stamp():
    return time.time()
