"""Clean twin: the hot path dispatches and hands back futures — no
``.item()`` / ``device_get`` / ``block_until_ready`` on the tick."""


# graftlint: hot-path
def tick(engine):
    futures = engine.dispatch()
    return futures
