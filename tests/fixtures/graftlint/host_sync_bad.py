"""True positive: ``.item()`` forces a device->host sync inside a
hot-path-marked function (the per-tick/per-step no-sync budget)."""


# graftlint: hot-path
def tick(engine):
    loss = engine.last_loss.item()
    return loss
