"""Clean twin: the span clock is monotonic."""
import time


def span_stamp():
    return time.monotonic()
