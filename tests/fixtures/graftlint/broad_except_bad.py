"""True positive: a broad except inside a supervised seam that only logs —
it swallows the supervisor's retryable-vs-fatal classification."""


# graftlint: supervised-seam
def tick(engine, log):
    try:
        engine.dispatch()
    except Exception as exc:
        log.warning("tick failed: %r", exc)
