"""Clean twin: snapshot the reference under the lock, sync outside it."""
import jax


def scrape(self):
    with self.lock:
        snapshot = self.counters
    return jax.device_get(snapshot)
