"""Clean twin: the broad except hands the exception to the fault
classifier, preserving the retryable-vs-fatal decision."""


# graftlint: supervised-seam
def tick(engine):
    try:
        engine.dispatch()
    except Exception as exc:
        engine.classify_fault(exc)
