"""True positive: a checkpoint-restored tree reaches a donating jit
without an ``ensure_donatable`` seam (the jax 0.4.37 zero-copy class)."""
import jax

train_step = jax.jit(lambda state, batch: state, donate_argnums=(0,))


def resume_and_step(ckptr, abstract, batch):
    state = ckptr.restore(abstract)
    return train_step(state, batch)
