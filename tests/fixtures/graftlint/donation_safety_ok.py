"""Clean twin: the restored tree is sealed through ``ensure_donatable``
before the donating dispatch sees it."""
import jax

from zero_transformer_tpu.utils.jax_compat import ensure_donatable

train_step = jax.jit(lambda state, batch: state, donate_argnums=(0,))


def resume_and_step(ckptr, abstract, batch):
    state = ensure_donatable(ckptr.restore(abstract))
    return train_step(state, batch)
