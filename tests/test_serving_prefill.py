"""Chunked prefill / prefix cache / batched admission: the ISSUE 4 parity
and blast-radius suite.

The load-bearing claim is EQUIVALENCE: chunked prefill (and a prefix-cache
hit mid-prompt) must be bit-for-bit identical to the one-shot prefill path —
the logits at ``true_len - 1`` AND the full generated sequence — across
chunk sizes, prefill-bucket boundaries, position schemes (ALiBi, RoPE,
learned), and the int8 KV cache. The resilience interactions are pinned
too: a fault during a prefill chunk retires ONLY the mid-prefill slots
(decoding neighbors keep their exact trajectories), and a hot weight reload
flushes the prefix cache so stale K/V can never serve under new weights.

Everything runs the ``test`` zoo model on CPU in float32 (bitwise claims
need a deterministic backend).
"""
import numpy as np

import jax
import jax.numpy as jnp
import pytest

from zero_transformer_tpu.config import model_config
from zero_transformer_tpu.inference.generate import decode_model, generate
from zero_transformer_tpu.inference.sampling import SamplingConfig
from zero_transformer_tpu.models import Transformer
from zero_transformer_tpu.serving import (
    PrefixCache,
    ServeFault,
    ServingChaosMonkey,
    ServingEngine,
)

CACHE_LEN = 48
SAMPLING = SamplingConfig(temperature=0.9, top_k=20)


@pytest.fixture(scope="module", params=["alibi", "rope"])
def cfg(request):
    return model_config(
        "test", dropout=0.0, compute_dtype="float32", position=request.param
    )


@pytest.fixture(scope="module")
def params(cfg):
    # alibi and rope share a param structure (neither adds position params),
    # so one init per cfg keeps the module fast while covering both
    model = Transformer(cfg)
    return model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))[
        "params"
    ]


@pytest.fixture(scope="module")
def reference(cfg, params):
    model = decode_model(cfg, CACHE_LEN)

    def run(prompt, seed, max_new=8, p=params):
        toks = generate(
            model, p, jnp.asarray([prompt], jnp.int32), max_new,
            jax.random.PRNGKey(seed), SAMPLING,
        )
        return jax.device_get(toks)[0].tolist()

    return run


def make_engine(cfg, params, **kw):
    kw.setdefault("n_slots", 2)
    kw.setdefault("cache_len", CACHE_LEN)
    kw.setdefault("sampling", SAMPLING)
    return ServingEngine(cfg, params, **kw)


def _prompt(length, offset=0):
    return [(3 + offset + i) % 250 + 1 for i in range(length)]


def _drive_prefill_only(engine):
    """Admit + run chunk ticks WITHOUT any decode step, so the installed
    per-slot logits are exactly the prefill output."""
    engine._admit()
    ticks = 0
    while engine._prefilling:
        assert engine._prefill_tick()
        ticks += 1
        assert ticks < 1000, "chunked prefill failed to converge"


# ------------------------------------------------------------------- parity


@pytest.mark.parametrize("chunk", [8, 64, CACHE_LEN])
@pytest.mark.parametrize("length", [5, 9, 17, 31])
def test_chunk_prefill_logits_match_oneshot(cfg, params, chunk, length):
    """The logits at ``true_len - 1`` out of chunked prefill equal the
    one-shot padded prefill's, for prompts crossing power-of-two bucket
    boundaries and chunks from smaller-than-prompt up to (and past —
    64 > cache clamps) the cache capacity.

    Equality bar: BITWISE for chunk=8, where multi-chunk prefill splits the
    prompt across several narrow dispatches — proving the split itself
    (interleaved direct cache writes, per-row offsets, window padding) adds
    exactly nothing numerically. The cache-wide single-window chunks
    (48/64) necessarily run a DIFFERENT XLA program shape than the one-shot
    bucket ([S, 48] vs [1, 8..32]), and under this suite's forced 8-device
    CPU backend (conftest) XLA tiles the wider matmuls differently —
    1-ulp summation-order drift, identical math. Those compare at a
    few-ulp tolerance; the token-level decode outputs (the serving
    contract) are asserted bit-identical for EVERY chunk size in
    ``test_chunked_sequences_match_generate``."""
    legacy = make_engine(cfg, params)  # prefill_chunk=0: one-shot path
    oneshot_logits, _ = legacy._prefill(_prompt(length))
    oneshot = np.asarray(jax.device_get(oneshot_logits))[0]

    chunked = make_engine(cfg, params, prefill_chunk=chunk)
    handle = chunked.submit(_prompt(length), max_new_tokens=4, seed=0)
    _drive_prefill_only(chunked)
    assert handle.status == "running"
    slot = next(
        s for s, a in enumerate(chunked._active) if a is not None
    )
    got = np.asarray(jax.device_get(chunked._last_logits))[slot]
    if chunk == 8:
        assert np.array_equal(got, oneshot), (
            f"chunked (chunk={chunk}) prefill logits diverge from one-shot "
            f"for length {length}"
        )
    else:
        np.testing.assert_allclose(got, oneshot, rtol=2e-6, atol=1e-6)


@pytest.mark.parametrize("chunk", [8, 64, CACHE_LEN])
def test_chunked_sequences_match_generate(cfg, params, reference, chunk):
    """Full-sequence parity under real contention: 5 requests with lengths
    crossing bucket boundaries into 2 slots, chunked engine vs
    single-request generate()."""
    engine = make_engine(cfg, params, prefill_chunk=chunk)
    prompts = [_prompt(n, offset=i) for i, n in enumerate((2, 5, 9, 17, 31))]
    handles = [
        engine.submit(p, max_new_tokens=8, seed=i)
        for i, p in enumerate(prompts)
    ]
    engine.run_until_idle()
    for i, (p, h) in enumerate(zip(prompts, handles)):
        assert h.status == "done", (h.status, h.error)
        assert h.tokens == reference(p, i), f"request {i} (len {len(p)}) garbled"


def test_chunk_window_clamp_near_capacity(cfg, params, reference):
    """A prompt whose final chunk window would overrun the cache: the
    engine clamps the window to ``cache_len - chunk`` and re-sends the
    overlap, whose K/V recompute bit-identically — the trajectory must
    still match generate() exactly."""
    engine = make_engine(cfg, params, n_slots=1, prefill_chunk=16)
    prompt = _prompt(39)  # fills 0/16/32 -> final window clamps to [32..48)
    handle = engine.submit(prompt, max_new_tokens=2, seed=3)
    engine.run_until_idle()
    assert handle.status == "done"
    assert handle.tokens == reference(prompt, 3, max_new=2)


def test_prefix_cache_hit_mid_prompt_is_bit_identical(cfg, params, reference):
    """Second request shares the first's 2-chunk system prefix: admission
    reuses the cached spans (hits > 0, fill lands mid-prompt) and the
    generated sequence is STILL byte-identical to single-request
    generate() — reused K/V equals recomputed K/V."""
    engine = make_engine(
        cfg, params, prefill_chunk=8, prefix_cache_chunks=16
    )
    prefix = _prompt(16, offset=40)
    a = engine.submit(prefix + _prompt(3, offset=7), max_new_tokens=6, seed=0)
    engine.run_until_idle()
    b = engine.submit(prefix + _prompt(4, offset=90), max_new_tokens=6, seed=1)
    engine.run_until_idle()
    assert a.status == "done" and b.status == "done"
    assert b.prefix_hit_tokens == 16  # both prefix chunks reused
    assert engine._prefix_cache.hits == 2
    assert a.tokens == reference(prefix + _prompt(3, offset=7), 0, max_new=6)
    assert b.tokens == reference(prefix + _prompt(4, offset=90), 1, max_new=6)
    snap = engine.metrics_snapshot()
    assert snap["prefix_hits"] == 2 and snap["prefix_hit_rate"] > 0


def test_int8_kv_cache_chunked_parity(params):
    """Chunked prefill through the int8 KV cache (quantized spans + scale
    leaves ride the same slot rows) stays token-identical to generate()."""
    qcfg = model_config(
        "test", dropout=0.0, compute_dtype="float32", kv_cache_dtype="int8"
    )
    model = decode_model(qcfg, CACHE_LEN)
    prompt = _prompt(11)
    ref = jax.device_get(
        generate(model, params, jnp.asarray([prompt], jnp.int32), 8,
                 jax.random.PRNGKey(3), SAMPLING)
    )[0].tolist()
    engine = make_engine(qcfg, params, prefill_chunk=4, prefix_cache_chunks=8)
    handle = engine.submit(prompt, max_new_tokens=8, seed=3)
    engine.run_until_idle()
    assert handle.status == "done" and handle.tokens == ref
    # and a prefix hit over int8 spans stays exact too
    again = engine.submit(prompt, max_new_tokens=8, seed=3)
    engine.run_until_idle()
    assert again.prefix_hit_tokens > 0
    assert again.tokens == ref


def test_learned_positions_chunked_parity():
    """Learned absolute positions thread the per-slot decode_pos vector
    through chunked prefill too."""
    lcfg = model_config(
        "test", dropout=0.0, compute_dtype="float32", position="learned"
    )
    lparams = Transformer(lcfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    model = decode_model(lcfg, lcfg.max_seq_len)
    prompt = _prompt(13)
    ref = jax.device_get(
        generate(model, lparams, jnp.asarray([prompt], jnp.int32), 6,
                 jax.random.PRNGKey(5), SAMPLING)
    )[0].tolist()
    engine = ServingEngine(
        lcfg, lparams, n_slots=2, cache_len=lcfg.max_seq_len,
        sampling=SAMPLING, prefill_chunk=4,
    )
    handle = engine.submit(prompt, max_new_tokens=6, seed=5)
    engine.run_until_idle()
    assert handle.status == "done" and handle.tokens == ref


# -------------------------------------------------- batched admission


def test_batched_admission_single_install_dispatch(cfg, params, reference):
    """N free slots + N queued prompts admit as ONE batch: every prompt
    progresses through the same chunk dispatches and completion installs
    coalesce — and each trajectory still matches generate()."""
    engine = make_engine(cfg, params, n_slots=4, prefill_chunk=8)
    prompts = [_prompt(9, offset=i * 11) for i in range(4)]
    handles = [
        engine.submit(p, max_new_tokens=6, seed=i)
        for i, p in enumerate(prompts)
    ]
    _drive_prefill_only(engine)
    # all four admitted together and completed prefill in the SAME two
    # chunk dispatches (9 tokens / chunk 8 -> 2 chunks), not 4x2
    assert engine.stats["prefill_chunks"] == 8  # 4 slots x 2 ticks, batched
    assert all(h.status == "running" for h in handles)
    engine.run_until_idle()
    for i, (p, h) in enumerate(zip(prompts, handles)):
        assert h.tokens == reference(p, i, max_new=6)


def test_itl_attribution_excludes_prefill_ticks(cfg, params):
    """ITL samples from ticks that ran prefill work are excluded from the
    pure-decode split: with staggered budgets (so retires — and therefore
    admissions — desynchronize), some inter-token gap coincides with a
    neighbor's chunk prefill and itl_decode_ms sees fewer samples."""
    engine = make_engine(cfg, params, prefill_chunk=8)
    for i in range(8):
        engine.submit(_prompt(3, offset=i), max_new_tokens=6 + (i * 5) % 11, seed=i)
    engine.run_until_idle()
    assert len(engine._itl_decode) < len(engine._itl)
    snap = engine.metrics_snapshot()
    assert "itl_decode_ms_p99" in snap and "itl_ms_p99" in snap


# ------------------------------------------------------- resilience paths


@pytest.mark.chaos
def test_prefill_fault_retires_only_the_chunk_slots(cfg, params, reference):
    """A fault during a prefill chunk fails ONLY the mid-prefill slot
    (retryably): the decoding neighbor's trajectory is byte-identical to an
    undisturbed run, the breaker never opens, and the freed slot serves a
    retry cleanly."""
    chaos = ServingChaosMonkey([ServeFault("prefill_fault", step=4, duration=1)])
    engine = make_engine(cfg, params, prefill_chunk=4, chaos=chaos)
    neighbor = engine.submit(_prompt(3), max_new_tokens=12, seed=1)
    for _ in range(4):
        engine.step()
    victim = engine.submit(_prompt(13, offset=50), max_new_tokens=8, seed=3)
    engine.run_until_idle()
    assert victim.status == "failed" and victim.retryable
    assert "prefill chunk" in victim.error
    assert victim.tokens == []  # failed before its first token
    assert neighbor.status == "done"
    assert neighbor.tokens == reference(_prompt(3), 1, max_new=12)
    assert engine.stats["prefill_faults"] == 1
    assert engine.stats["tick_faults"] == 0
    assert not engine._breaker.open
    retry = engine.submit(_prompt(13, offset=50), max_new_tokens=8, seed=3)
    engine.run_until_idle()
    assert retry.status == "done"
    assert retry.tokens == reference(_prompt(13, offset=50), 3)


def test_decode_fault_mid_chunk_fails_prefilling_retryably(cfg, params, reference):
    """A DECODE tick fault while a prompt is mid-chunked-prefill: the
    device rebuild invalidates the half-filled rows too, so the prefilling
    handle fails retryably (never hangs), and the engine serves
    byte-identical output afterwards."""
    chaos = ServingChaosMonkey([ServeFault("tick_fault", step=4, duration=1)])
    engine = make_engine(cfg, params, prefill_chunk=4, chaos=chaos)
    decoding = engine.submit(_prompt(3), max_new_tokens=12, seed=1)
    for _ in range(4):
        engine.step()
    midway = engine.submit(_prompt(17, offset=60), max_new_tokens=8, seed=2)
    engine.step()  # tick 4: chunk 1 of `midway`, then the faulted decode
    assert decoding.status == "failed" and decoding.retryable
    assert midway.status == "failed" and midway.retryable
    engine.run_until_idle()
    after = engine.submit(_prompt(17, offset=60), max_new_tokens=8, seed=2)
    engine.run_until_idle()
    assert after.status == "done"
    assert after.tokens == reference(_prompt(17, offset=60), 2)


def test_reload_mid_prefill_restarts_under_new_weights(cfg, params, reference):
    """A hot reload landing while a prompt is MID-chunked-prefill: the job
    restarts from token zero under the new weights — its output is
    byte-identical to generate() with the new tree, and the spans it banks
    afterwards are pure new-weight K/V (a later shared-prefix request
    reusing them stays exact). Without the restart, positions [0, fill)
    keep old-weight K/V: the output mixes weights and the poisoned spans
    land in the just-flushed prefix cache."""
    params2 = Transformer(cfg).init(
        jax.random.PRNGKey(9), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    engine = make_engine(
        cfg, params, n_slots=1, prefill_chunk=4, prefix_cache_chunks=16
    )
    prompt = _prompt(17, offset=25)  # 5 chunks of 4
    mid = engine.submit(prompt, max_new_tokens=6, seed=2)
    engine._admit()
    engine._prefill_tick()  # chunks 1-2 computed under the OLD weights
    engine._prefill_tick()
    assert engine._prefilling and next(iter(engine._prefilling.values())).fill == 8
    engine.reload_params(params2)
    engine.run_until_idle()  # swap -> restart -> full prefill on params2
    assert mid.status == "done"
    new_ref = reference(prompt, 2, max_new=6, p=params2)
    assert mid.tokens == new_ref and mid.tokens != reference(prompt, 2, max_new=6)
    # the banked spans are new-weight: a shared-prefix follow-up that HITS
    # them must still be byte-identical to generate() on the new tree
    follow = engine.submit(prompt[:12] + _prompt(3, offset=70), max_new_tokens=6, seed=5)
    engine.run_until_idle()
    assert follow.prefix_hit_tokens > 0
    assert follow.tokens == reference(
        prompt[:12] + _prompt(3, offset=70), 5, max_new=6, p=params2
    )


def test_reload_flushes_prefix_cache(cfg, params, reference):
    """Hot weight reload invalidates the prefix cache at the swap tick:
    post-reload shared-prefix requests re-prefill under the NEW weights
    (bit-identical to generate() with them) instead of reusing stale K/V."""
    params2 = Transformer(cfg).init(
        jax.random.PRNGKey(9), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    engine = make_engine(cfg, params, prefill_chunk=8, prefix_cache_chunks=16)
    prefix = _prompt(16, offset=30)
    warm = engine.submit(prefix + _prompt(2), max_new_tokens=4, seed=0)
    engine.run_until_idle()
    assert warm.status == "done" and len(engine._prefix_cache) > 0
    engine.reload_params(params2)
    engine.step()  # the swap tick flushes
    assert len(engine._prefix_cache) == 0
    after = engine.submit(prefix + _prompt(3, offset=80), max_new_tokens=6, seed=4)
    engine.run_until_idle()
    assert after.status == "done"
    assert after.prefix_hit_tokens == 0  # cold again: nothing stale to hit
    new_ref = reference(prefix + _prompt(3, offset=80), 4, max_new=6, p=params2)
    assert after.tokens == new_ref
    assert after.tokens != reference(prefix + _prompt(3, offset=80), 4, max_new=6)


# ------------------------------------------------------------ bucket cap


def test_bucket_cap_bounds_compiled_prefill_programs(cfg, params, reference):
    """Legacy one-shot path: past ``max_prefill_buckets`` distinct buckets,
    new prompt lengths round UP to an existing bucket (exact — padded
    prefill is causality-safe) instead of compiling another program, the
    event is counted, and the gauge is exported."""
    engine = make_engine(
        cfg, params, n_slots=1, max_prefill_buckets=2
    )
    assert engine._bucket(3) == 8
    assert engine._bucket(12) == 16
    # budget spent: 24 would want bucket 32; it must round to an existing
    # one — none fits, so the capacity bucket (always admissible) is used
    assert engine._bucket(24) == CACHE_LEN
    assert engine._bucket(5) == 8  # still served by the compiled 8-bucket
    assert engine._bucket(13) == 16
    assert engine._bucket(9) == 16  # 16 exists; no new 8->16 gap compile
    assert engine.stats["prefill_bucket_capped"] >= 1
    assert engine.metrics_snapshot()["prefill_buckets"] == 3  # 8, 16, cap
    # and a request through the capped path still decodes exactly
    handle = engine.submit(_prompt(24), max_new_tokens=4, seed=7)
    engine.run_until_idle()
    assert handle.tokens == reference(_prompt(24), 7, max_new=4)


# ------------------------------------------------------------ prefix cache


def test_prefix_cache_lru_unit():
    """Host-side LRU semantics: chunk-aligned keys, last-chunk exclusion,
    eviction order, flush."""
    pc = PrefixCache(chunk_tokens=4, capacity=2)
    p1 = list(range(1, 11))  # 10 tokens: chunks at 4 and 8
    fill, spans = pc.lookup(p1)
    assert fill == 0 and spans == [] and pc.misses == 2
    pc.store(p1, 1, "span1")
    pc.store(p1, 2, "span2")
    fill, spans = pc.lookup(p1)
    assert fill == 8 and spans == ["span1", "span2"] and pc.hits == 2
    # a full-prompt-aligned lookup never consumes the final chunk: a
    # 8-token prompt sharing p1's first 8 tokens may only reuse chunk 1
    fill, spans = pc.lookup(p1[:8])
    assert fill == 4 and spans == ["span1"]
    # divergent prefix: chunk 1 differs -> no hit, and a deeper stored
    # chunk alone is unreachable without its predecessors
    other = [99] + p1[1:]
    fill, spans = pc.lookup(other)
    assert fill == 0 and spans == []
    # eviction: capacity 2, storing a third entry evicts the LRU one
    pc.store(other, 1, "span3")
    assert pc.evictions == 1 and len(pc) == 2
    assert pc.flush() == 2 and len(pc) == 0
