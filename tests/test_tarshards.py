"""Tar-shard source tests: brace expansion, index files, streaming, shard
striping, resume — the reference's actual data path (webdataset over tar.gz
shards, reference ``main_zero.py:389-421``, ``data/index/*.index``), which the
reference itself never tested (SURVEY §4).
"""
import io
import json
import tarfile

import numpy as np
import pytest

from zero_transformer_tpu.config import Config, DataConfig, ModelConfig, TrainingConfig
from zero_transformer_tpu.data import DataLoader, make_loader, make_source
from zero_transformer_tpu.data.tarshards import (
    TarShardSource,
    expand_braces,
    read_index,
)


def take(it, n):
    return [next(it) for _ in range(n)]


def write_shard(path, rows, fmt="npy", gz=False):
    """Write token rows as one-sample-per-member tar (optionally gzipped)."""
    mode = "w:gz" if gz else "w"
    with tarfile.open(path, mode) as tar:
        for i, row in enumerate(rows):
            row = np.asarray(row)
            if fmt == "npy":
                buf = io.BytesIO()
                np.save(buf, row)
                data, name = buf.getvalue(), f"{i:05d}.npy"
            elif fmt == "json":
                data, name = json.dumps(row.tolist()).encode(), f"{i:05d}.json"
            else:  # raw uint16
                data, name = row.astype(np.uint16).tobytes(), f"{i:05d}.bin"
            info = tarfile.TarInfo(name)
            info.size = len(data)
            tar.addfile(info, io.BytesIO(data))
    return str(path)


@pytest.fixture
def shard_dir(tmp_path):
    """4 shards x 4 rows of 8 tokens; row value encodes (shard, row)."""
    paths = []
    for s in range(4):
        rows = [np.full(8, s * 10 + r, np.int32) for r in range(4)]
        paths.append(write_shard(tmp_path / f"shard-{s:03d}.tar", rows))
    return tmp_path, paths


class TestExpansion:
    def test_braces(self):
        assert expand_braces("a-{000..002}.tar") == [
            "a-000.tar", "a-001.tar", "a-002.tar",
        ]

    def test_no_braces_passthrough(self):
        assert expand_braces("plain.tar") == ["plain.tar"]

    def test_index_file_with_comments(self, tmp_path):
        idx = tmp_path / "train.index"
        idx.write_text("# comment\n\ngs://b/x-{00..01}.tar.gz\nlocal.tar\n")
        # relative local entries resolve against the index's own directory
        # (relocatable datasets); URLs pass through verbatim
        assert read_index(idx) == [
            "gs://b/x-00.tar.gz", "gs://b/x-01.tar.gz", str(tmp_path / "local.tar")
        ]

    def test_empty_index_raises(self, tmp_path):
        idx = tmp_path / "empty.index"
        idx.write_text("# nothing\n")
        with pytest.raises(ValueError):
            read_index(idx)


class TestStreaming:
    @pytest.mark.parametrize("fmt,gz", [("npy", False), ("json", False), ("bin", True)])
    def test_payload_formats(self, tmp_path, fmt, gz):
        suffix = ".tar.gz" if gz else ".tar"
        rows = [np.arange(8, dtype=np.int32) + i for i in range(3)]
        p = write_shard(tmp_path / f"s{suffix}", rows, fmt=fmt, gz=gz)
        src = TarShardSource(p, max_context=8, shuffle_shards=False)
        got = take(iter(src), 3)
        for g, r in zip(got, rows):
            np.testing.assert_array_equal(g, r)
        assert got[0].dtype == np.int32

    def test_short_rows_skipped_long_truncated(self, tmp_path):
        rows = [np.arange(4), np.arange(12), np.arange(8)]
        p = write_shard(tmp_path / "s.tar", rows)
        src = TarShardSource(p, max_context=8, shuffle_shards=False)
        got = take(iter(src), 2)
        np.testing.assert_array_equal(got[0], np.arange(8))  # 12 truncated
        np.testing.assert_array_equal(got[1], np.arange(8))  # 4 skipped

    def test_epoch_reshuffles_and_covers_all(self, shard_dir):
        _, paths = shard_dir
        src = TarShardSource(paths, max_context=8, seed=7)
        it = iter(src)
        epochs = [[int(r[0]) for r in take(it, 16)] for _ in range(3)]
        full = sorted(s * 10 + r for s in range(4) for r in range(4))
        assert all(sorted(e) == full for e in epochs)
        # shard order reshuffles from (seed, epoch): not every epoch identical
        assert len({tuple(e) for e in epochs}) > 1

    def test_index_input(self, shard_dir):
        tmp_path, _ = shard_dir
        idx = tmp_path / "all.index"
        idx.write_text(str(tmp_path / "shard-{000..003}.tar") + "\n")
        src = TarShardSource(str(idx), max_context=8, shuffle_shards=False)
        assert len(src.shards) == 4
        assert int(next(iter(src))[0]) == 0


class TestStriping:
    def test_shard_striping_disjoint_and_complete(self, shard_dir):
        _, paths = shard_dir

        def rows_for(pidx):
            src = TarShardSource(paths, max_context=8, seed=7,
                                 process_index=pidx, process_count=2)
            assert src.pre_striped
            return [int(r[0]) for r in take(iter(src), 8)]  # one epoch each

        r0, r1 = rows_for(0), rows_for(1)
        assert not set(r0) & set(r1)
        assert sorted(r0 + r1) == sorted(s * 10 + r for s in range(4) for r in range(4))

    def test_few_shards_falls_back_to_row_striping(self, shard_dir):
        _, paths = shard_dir
        src = TarShardSource(paths[:1], max_context=8, process_index=0, process_count=2)
        assert not src.pre_striped  # 1 shard < 2*2: every process reads it

    def test_forced_striping_with_too_few_shards_raises(self, shard_dir):
        _, paths = shard_dir
        with pytest.raises(ValueError, match="own no"):
            TarShardSource(paths[:2], max_context=8, process_index=0,
                           process_count=4, stripe_shards=True)

    def test_loader_skips_row_striping_for_pre_striped(self, shard_dir):
        _, paths = shard_dir

        def loader_rows(pidx):
            src = TarShardSource(paths, max_context=8, seed=7,
                                 process_index=pidx, process_count=2)
            dl = DataLoader(src, batch_size=4, train_context=8,
                            process_index=pidx, process_count=2)
            return np.concatenate(take(iter(dl), 4)).reshape(-1, 8)

        r0, r1 = loader_rows(0), loader_rows(1)
        vals = sorted(int(v) for v in np.concatenate([r0, r1])[:, 0])
        assert vals == sorted(s * 10 + r for s in range(4) for r in range(4))

    def test_resume_mid_shard_matches_discard(self, shard_dir):
        _, paths = shard_dir
        src1 = TarShardSource(paths, max_context=8, seed=7,
                              process_index=0, process_count=2)
        it1 = iter(src1)
        take(it1, 3)  # stops mid-shard (2 rows into the 2nd owned shard)
        expected = next(it1)

        src2 = TarShardSource(paths, max_context=8, seed=7,
                              process_index=0, process_count=2)
        src2.restore(src1.state())  # 4 rows consumed
        take(iter(src1), 2)
        take(iter(src2), 2)
        np.testing.assert_array_equal(next(iter(src2)), next(iter(src1)))


def test_make_source_tar_from_config(shard_dir):
    tmp_path, _ = shard_dir
    idx = tmp_path / "all.index"
    idx.write_text(str(tmp_path / "shard-{000..003}.tar") + "\n")
    cfg = Config(
        model=ModelConfig(vocab_size=100),
        training=TrainingConfig(batch_size=4, train_context=8),
        data=DataConfig(source="tar", train_path=str(idx), max_context=8),
    )
    src = make_source(cfg, process_index=0, process_count=1)
    assert isinstance(src, TarShardSource)
    dl = make_loader(cfg, process_index=0, process_count=1)
    batch = next(iter(dl))
    assert batch.shape == (1, 4, 8)


class TestErrorTolerance:
    def _corrupt_setup(self, tmp_path):
        """shard0: good row, corrupt .npy member, good row; shard1: good."""
        good = np.full(8, 7, np.int32)
        p0 = str(tmp_path / "bad-000.tar")
        with tarfile.open(p0, "w") as tar:
            for name, data in [
                ("00000.npy", _npy_bytes(good)),
                ("00001.npy", b"\x00not-a-npy-file"),
                ("00002.npy", _npy_bytes(good + 1)),
            ]:
                info = tarfile.TarInfo(name)
                info.size = len(data)
                tar.addfile(info, io.BytesIO(data))
        p1 = write_shard(tmp_path / "bad-001.tar", [np.full(8, 9, np.int32)])
        return [p0, p1]

    def test_corrupt_member_skipped_by_default(self, tmp_path):
        shards = self._corrupt_setup(tmp_path)
        src = TarShardSource(shards, max_context=8, shuffle_shards=False)
        rows = take(iter(src), 3)
        np.testing.assert_array_equal(rows[0], np.full(8, 7))
        np.testing.assert_array_equal(rows[1], np.full(8, 8))  # after the bad one
        np.testing.assert_array_equal(rows[2], np.full(8, 9))

    def test_strict_raises_on_corrupt_member(self, tmp_path):
        shards = self._corrupt_setup(tmp_path)
        src = TarShardSource(
            shards, max_context=8, shuffle_shards=False, strict=True
        )
        it = iter(src)
        take(it, 1)
        with pytest.raises(Exception):
            take(it, 1)

    def test_all_shards_dead_raises_not_spins(self, tmp_path):
        # a fully unreadable shard list must raise after one epoch pass,
        # never busy-loop warnings forever
        bad = tmp_path / "nope-000.tar.gz"
        bad.write_bytes(b"not a tar at all")
        src = TarShardSource([str(bad)], max_context=8, shuffle_shards=False)
        with pytest.raises(RuntimeError, match="zero rows"):
            take(iter(src), 1)

    def test_truncated_gzip_shard_skipped(self, tmp_path):
        good = write_shard(tmp_path / "g-000.tar.gz",
                           [np.full(8, 1, np.int32)], gz=True)
        bad_path = tmp_path / "g-001.tar.gz"
        data = open(good, "rb").read()
        bad_path.write_bytes(data[: len(data) // 2])  # truncated stream
        tail = write_shard(tmp_path / "g-002.tar.gz",
                           [np.full(8, 3, np.int32)], gz=True)
        src = TarShardSource([good, str(bad_path), tail], max_context=8,
                             shuffle_shards=False)
        rows = take(iter(src), 2)
        np.testing.assert_array_equal(rows[0], np.full(8, 1))
        np.testing.assert_array_equal(rows[1], np.full(8, 3))


def _npy_bytes(row):
    buf = io.BytesIO()
    np.save(buf, np.asarray(row))
    return buf.getvalue()


def test_index_cwd_relative_fallback(tmp_path, monkeypatch):
    """Legacy index whose relative entries were written against the training
    job's cwd (pre-round-3 semantics). The fallback is OPT-IN (ADVICE r4):
    by default an entry that exists only cwd-relative raises loudly — a
    partially-copied dataset plus a same-layout dataset in the cwd must not
    silently train on the wrong shards — and the flag / env var restores
    the legacy resolution."""
    from zero_transformer_tpu.data.tarshards import read_index

    idx_dir = tmp_path / "indexes"
    idx_dir.mkdir()
    idx = idx_dir / "legacy.index"
    idx.write_text("shards/part-0.tar\n")
    cwd_shard = tmp_path / "shards" / "part-0.tar"
    cwd_shard.parent.mkdir()
    cwd_shard.write_bytes(b"")
    monkeypatch.chdir(tmp_path)
    monkeypatch.delenv("ZT_INDEX_CWD_FALLBACK", raising=False)
    with pytest.raises(ValueError, match="cwd-relative"):
        read_index(idx)  # ambiguous by default: fail loudly
    assert read_index(idx, legacy_cwd_fallback=True) == ["shards/part-0.tar"]
    monkeypatch.setenv("ZT_INDEX_CWD_FALLBACK", "1")
    assert read_index(idx) == ["shards/part-0.tar"]
    # index-relative wins once it exists (the modern layout) — no opt-in
    # needed and none consulted
    monkeypatch.delenv("ZT_INDEX_CWD_FALLBACK")
    new_shard = idx_dir / "shards" / "part-0.tar"
    new_shard.parent.mkdir()
    new_shard.write_bytes(b"")
    assert read_index(idx) == [str(idx_dir / "shards" / "part-0.tar")]
