"""Packed-sequence training: separator-derived document masking.

Beyond the reference (it trains on pre-packed fixed rows with cross-document
attention bleed — the standard shortcut). Exactness is the contract here:
because ALiBi and RoPE are both relative-position schemes, a document's
logits inside a packed row must EQUAL its logits as a standalone row once
cross-document attention is masked.
"""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from zero_transformer_tpu.config import ModelConfig
from zero_transformer_tpu.models import Transformer

SEP = 63
CFG = ModelConfig(
    name="t", vocab_size=64, d_model=32, n_heads=4, n_layers=2, max_seq_len=64,
    dropout=0.0, compute_dtype="float32", doc_sep_token=SEP,
)


def _params(model, T=16):
    return model.init(jax.random.PRNGKey(0), jnp.zeros((1, T), jnp.int32))["params"]


@pytest.mark.parametrize("position", ["alibi", "rope"])
def test_packed_doc_matches_standalone(position):
    cfg = dataclasses.replace(CFG, position=position)
    model = Transformer(cfg)
    rng = np.random.default_rng(0)
    doc1 = list(rng.integers(1, 60, 7)) + [SEP]
    doc2 = list(rng.integers(1, 60, 8))
    packed = jnp.asarray([doc1 + doc2], jnp.int32)  # [1, 16]
    params = _params(model, T=16)

    packed_logits = model.apply({"params": params}, packed)
    solo2 = model.apply({"params": params}, jnp.asarray([doc2], jnp.int32))
    # doc2's logits inside the packed row == standalone (relative positions)
    np.testing.assert_allclose(
        np.asarray(packed_logits[0, len(doc1):]), np.asarray(solo2[0]),
        atol=2e-5, rtol=2e-5,
    )
    # doc1 (incl. its separator) is also unaffected by doc2's presence
    solo1 = model.apply({"params": params}, jnp.asarray([doc1], jnp.int32))
    np.testing.assert_allclose(
        np.asarray(packed_logits[0, : len(doc1)]), np.asarray(solo1[0]),
        atol=2e-5, rtol=2e-5,
    )


def test_unpacked_model_differs_across_docs():
    """Sanity: WITHOUT doc masking, doc2's logits DO depend on doc1 — the
    bleed the feature removes."""
    cfg = dataclasses.replace(CFG, doc_sep_token=None)
    model = Transformer(cfg)
    rng = np.random.default_rng(0)
    doc1 = list(rng.integers(1, 60, 7)) + [SEP]
    doc2 = list(rng.integers(1, 60, 8))
    params = _params(model, T=16)
    packed_logits = model.apply(
        {"params": params}, jnp.asarray([doc1 + doc2], jnp.int32)
    )
    solo2 = model.apply({"params": params}, jnp.asarray([doc2], jnp.int32))
    assert not np.allclose(
        np.asarray(packed_logits[0, len(doc1):]), np.asarray(solo2[0]), atol=1e-4
    )


def test_loss_ignores_boundary_targets():
    """The first token of doc2 must not be a training target for doc1's
    context: loss over the packed row == weighted mean of per-doc losses."""
    model = Transformer(CFG)
    rng = np.random.default_rng(1)
    doc1 = list(rng.integers(1, 60, 7)) + [SEP]
    doc2 = list(rng.integers(1, 60, 8))
    packed = jnp.asarray([doc1 + doc2], jnp.int32)
    params = _params(model, T=16)
    _, packed_loss = model.apply({"params": params}, packed, labels=packed)

    def doc_loss(doc):
        x = jnp.asarray([doc], jnp.int32)
        return float(model.apply({"params": params}, x, labels=x)[1])

    n1, n2 = len(doc1) - 1, len(doc2) - 1  # targets per doc
    want = (doc_loss(doc1) * n1 + doc_loss(doc2) * n2) / (n1 + n2)
    np.testing.assert_allclose(float(packed_loss), want, rtol=1e-5)


def test_packing_guards():
    # learned positions break the packed==standalone contract: rejected
    with pytest.raises(ValueError, match="relative position"):
        dataclasses.replace(CFG, position="learned", max_seq_len=32)
    # out-of-vocab separator could never fire: rejected, not silently inert
    with pytest.raises(ValueError, match="outside vocab"):
        dataclasses.replace(CFG, doc_sep_token=50256)
    # decode-shaped (Tq != S) doc masking is invalid
    from zero_transformer_tpu.ops.pallas.flash import flash_attention

    q = jnp.zeros((1, 16, 4, 64))
    k = jnp.zeros((1, 32, 4, 64))
    with pytest.raises(ValueError, match="doc_ids"):
        flash_attention(q, k, k, doc_ids=jnp.zeros((1, 16), jnp.int32))


@pytest.mark.parametrize("alibi", [True, False])
def test_flash_kernel_doc_mask_matches_xla(alibi):
    """The Pallas kernel's doc masking (fwd AND grads) must match the XLA
    reference exactly — this is what keeps packing viable at 8k+ context
    where the XLA path OOMs."""
    from zero_transformer_tpu.ops.attention import xla_attention
    from zero_transformer_tpu.ops.pallas.flash import flash_attention

    B, T, H, D = 2, 512, 4, 64
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q, k, v = (jax.random.normal(kk, (B, T, H, D)) for kk in ks)
    # three documents per row, boundaries off block edges
    ids = jnp.asarray(
        np.concatenate([np.zeros(200), np.ones(190), np.full(122, 2)])[None]
        .repeat(B, 0),
        jnp.int32,
    )
    g = jax.random.normal(jax.random.PRNGKey(7), (B, T, H, D))

    # block=128 -> a 4x4 block grid with doc boundaries (200, 390) straddling
    # block edges: exercises the online-softmax (m, l, acc) carry across
    # fully- and partially-masked k-blocks, not just the single-block case
    def loss_flash(q, k, v):
        return jnp.sum(
            flash_attention(
                q, k, v, causal=True, alibi=alibi, doc_ids=ids, block=128,
                interpret=True,
            ) * g
        )

    def loss_ref(q, k, v):
        return jnp.sum(
            xla_attention(q, k, v, causal=True, alibi=alibi, doc_ids=ids) * g
        )

    out_f = flash_attention(
        q, k, v, causal=True, alibi=alibi, doc_ids=ids, block=128, interpret=True
    )
    out_x = xla_attention(q, k, v, causal=True, alibi=alibi, doc_ids=ids)
    np.testing.assert_allclose(out_f, out_x, atol=2e-5, rtol=2e-5)

    gf = jax.jit(jax.grad(loss_flash, argnums=(0, 1, 2)))(q, k, v)
    gx = jax.jit(jax.grad(loss_ref, argnums=(0, 1, 2)))(q, k, v)
    for name, a, b in zip("qkv", gf, gx):
        np.testing.assert_allclose(a, b, atol=1e-4, rtol=1e-3, err_msg=f"d{name}")


@pytest.mark.parametrize("impl,kwargs", [
    ("xla", {}),
    ("flash", {"interpret": True}),
])
def test_ring_doc_mask_matches_full_attention(devices, impl, kwargs):
    """Ring attention with packed documents: kv doc ids ride the ppermute
    ring, so cross-shard cross-document attention is masked identically to
    the single-device reference — forward and gradients."""
    from zero_transformer_tpu.config import MeshConfig
    from zero_transformer_tpu.ops.attention import xla_attention
    from zero_transformer_tpu.ops.ring_attention import ring_attention
    from zero_transformer_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(MeshConfig(data=2, sequence=4))
    B, T, H, D = 2, 512, 4, 64
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q, k, v = (jax.random.normal(kk, (B, T, H, D)) for kk in ks)
    # doc boundaries straddle shard edges (shard = 128 positions)
    ids = jnp.asarray(
        np.concatenate([np.zeros(200), np.ones(190), np.full(122, 2)])[None]
        .repeat(B, 0),
        jnp.int32,
    )
    g = jax.random.normal(jax.random.PRNGKey(7), (B, T, H, D))

    ref = xla_attention(q, k, v, causal=True, alibi=True, doc_ids=ids)
    out = jax.jit(
        lambda q, k, v: ring_attention(
            q, k, v, mesh, causal=True, alibi=True, doc_ids=ids, impl=impl, **kwargs
        )
    )(q, k, v)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

    def loss_ring(q, k, v):
        return jnp.sum(
            ring_attention(
                q, k, v, mesh, causal=True, alibi=True, doc_ids=ids, impl=impl,
                **kwargs
            ) * g
        )

    def loss_ref(q, k, v):
        return jnp.sum(xla_attention(q, k, v, causal=True, alibi=True, doc_ids=ids) * g)

    gr = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    gx = jax.jit(jax.grad(loss_ref, argnums=(0, 1, 2)))(q, k, v)
    for name, a, b in zip("qkv", gr, gx):
        np.testing.assert_allclose(a, b, atol=1e-4, rtol=1e-3, err_msg=f"d{name}")


@pytest.mark.parametrize("cp_impl", ["ring", "ulysses"])
def test_packed_model_with_sequence_parallel_matches_single(devices, cp_impl):
    """Full packed model under a sequence-parallel mesh == unsharded."""
    from zero_transformer_tpu.config import MeshConfig
    from zero_transformer_tpu.parallel.mesh import make_mesh

    cfg = dataclasses.replace(CFG, max_seq_len=32, cp_impl=cp_impl)
    mesh = make_mesh(MeshConfig(data=2, sequence=4))
    rng = np.random.default_rng(3)
    row = np.concatenate([rng.integers(1, 60, 13), [SEP], rng.integers(1, 60, 18)])
    x = jnp.asarray(np.tile(row, (2, 1)), jnp.int32)  # [2, 32]
    plain = Transformer(cfg)
    ringed = Transformer(cfg, mesh=mesh)
    params = plain.init(jax.random.PRNGKey(0), x)["params"]
    ref = plain.apply({"params": params}, x, labels=x)[1]
    out = jax.jit(lambda p, x: ringed.apply({"params": p}, x, labels=x)[1])(params, x)
    np.testing.assert_allclose(float(out), float(ref), rtol=1e-5)


def test_packed_training_decreases_loss(devices):
    """End-to-end: the packed model trains through the fused ZeRO step."""
    from zero_transformer_tpu.config import MeshConfig, OptimizerConfig
    from zero_transformer_tpu.parallel import (
        make_mesh, make_plan, init_train_state, make_train_step,
    )
    from zero_transformer_tpu.training.optimizer import make_optimizer, make_schedule

    opt = OptimizerConfig(peak_learning_rate=3e-3, warmup_steps=2, total_steps=40)
    mesh = make_mesh(MeshConfig())
    model = Transformer(CFG)
    tx = make_optimizer(opt)
    plan = make_plan(model, tx, mesh, (8, 16), 1)
    state = init_train_state(model, tx, jax.random.PRNGKey(0), mesh, (8, 16), plan)
    step = make_train_step(model, tx, mesh, plan, 1, make_schedule(opt))
    rng = np.random.default_rng(2)
    row = np.concatenate([rng.integers(1, 60, 7), [SEP], rng.integers(1, 60, 8)])
    batch = jnp.asarray(np.tile(row, (1, 8, 1)), jnp.int32)
    losses = []
    for _ in range(15):
        state, metrics = step(state, batch, jax.random.PRNGKey(3))
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0] - 0.5, losses
