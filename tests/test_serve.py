"""serve.py TextGenerator: the user-facing generation surface (the
reference's app.py was CUDA-gated and untestable off-GPU; this path runs
anywhere). A stub tokenizer keeps the test network-free."""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from zero_transformer_tpu.config import ModelConfig
from zero_transformer_tpu.models import Transformer
from zero_transformer_tpu.serve import TextGenerator

CFG = ModelConfig(
    name="t", vocab_size=64, d_model=32, n_heads=2, n_layers=2, max_seq_len=32,
    dropout=0.0, compute_dtype="float32",
)


class StubTokenizer:
    """Deterministic char-level tokenizer: token = ord(char) % 60 + 1."""

    eos_token_id = 0

    def encode(self, text):
        return [ord(c) % 60 + 1 for c in text]

    def decode(self, ids):
        return "".join(chr(96 + (t % 26)) for t in ids)


@pytest.fixture(scope="module")
def generator():
    model = Transformer(CFG)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return TextGenerator(CFG, params, StubTokenizer(), cache_len=32)


def test_one_shot_generation(generator):
    out = generator("hello", max_new_tokens=8, greedy=True)
    assert isinstance(out, str) and len(out) > 0


def test_greedy_is_deterministic(generator):
    a = generator("same prompt", max_new_tokens=8, greedy=True, seed=0)
    b = generator("same prompt", max_new_tokens=8, greedy=True, seed=123)
    assert a == b  # greedy ignores the sampling seed


def test_sampling_seed_changes_output(generator):
    outs = {
        generator("vary", max_new_tokens=12, temperature=1.5, seed=s)
        for s in range(4)
    }
    assert len(outs) > 1  # at temperature 1.5 seeds should diverge


def test_prompt_longer_than_budget_keeps_tail(generator):
    # budget = cache_len - max_new_tokens = 24; a 100-char prompt must be
    # tail-truncated (reference app.py:61-64 semantics), not error
    out = generator("x" * 100, max_new_tokens=8, greedy=True)
    assert isinstance(out, str)


def test_no_room_for_prompt_raises(generator):
    with pytest.raises(ValueError, match="no room"):
        generator("hi", max_new_tokens=32)


def test_stream_matches_one_shot_greedy(generator):
    full = generator("stream me", max_new_tokens=8, greedy=True)
    streamed = "".join(
        generator.stream("stream me", max_new_tokens=8, greedy=True)
    )
    assert streamed == full


def test_stream_holds_back_incomplete_multibyte_chars():
    """Byte-level BPE: a character spanning 2 tokens decodes to U+FFFD until
    complete — the stream must hold it back, never emit the replacement char
    mid-stream, and still concatenate to the full decode."""
    model = Transformer(CFG)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]

    class PairTok:
        eos_token_id = None  # never stop early

        def encode(self, text):
            return [ord(c) % 60 + 1 for c in text]

        def decode(self, ids):
            # every 2 tokens form one char; a dangling token is incomplete
            full = "".join(chr(97 + (a % 26)) for a in ids[::2][: len(ids) // 2])
            return full + ("�" if len(ids) % 2 else "")

    gen = TextGenerator(CFG, params, PairTok(), cache_len=32)
    pieces = list(gen.stream("seed", max_new_tokens=7, greedy=True))
    assert all("�" not in p for p in pieces[:-1])
    # concatenation equals the full decode of everything emitted (7 tokens:
    # 3 complete chars + one genuine trailing replacement char flushed at
    # stream end)
    full = "".join(pieces)
    assert full.count("�") == 1 and full.endswith("�")
    assert len(full) == 4  # 3 complete chars + held-back flush


def test_stream_tokens_matches_generate_greedy():
    """The streaming per-step path must sample the same greedy trajectory as
    the fused while_loop generate."""
    from zero_transformer_tpu.inference import (
        SamplingConfig, decode_model, generate, stream_tokens,
    )

    model = Transformer(CFG)
    dec = decode_model(CFG, cache_len=32)
    prompt = jnp.asarray([[5, 9, 11]], jnp.int32)
    params = model.init(jax.random.PRNGKey(0), prompt)["params"]
    cfg = SamplingConfig(greedy=True)
    rng = jax.random.PRNGKey(1)
    want = generate(dec, params, prompt, 8, rng, cfg)
    got = [int(t[0]) for t in stream_tokens(dec, params, prompt, 8, rng, cfg)]
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want[0]))


def test_byte_tokenizer_roundtrip():
    """--tokenizer bytes: offline fallback; UTF-8 round-trips exactly,
    including multi-byte characters, and streams through a byte-vocab model."""
    from zero_transformer_tpu.serve import ByteTokenizer, _load_tokenizer

    tok = _load_tokenizer("bytes")
    assert isinstance(tok, ByteTokenizer)
    text = "héllo ∀x"
    ids = tok.encode(text)
    assert all(0 <= t < 256 for t in ids)
    assert tok.decode(ids) == text
    # the serve streaming path holds back incomplete multi-byte sequences
    partial = tok.decode(ids[:2])  # b'h\xc3' — dangling UTF-8 lead byte
    assert partial.endswith("�")


def test_generator_with_byte_tokenizer():
    from zero_transformer_tpu.serve import ByteTokenizer

    cfg = dataclasses.replace(CFG, vocab_size=256)
    model = Transformer(cfg)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    gen = TextGenerator(cfg, params, ByteTokenizer(), cache_len=32)
    out = gen("hi", max_new_tokens=8, greedy=True)
    assert isinstance(out, str)
    # greedy + same seed: the streamed concatenation must equal the batch
    # decode exactly (the _decode cleanup pinning exists for this invariant)
    streamed = "".join(gen.stream("hi", max_new_tokens=8, greedy=True))
    assert streamed == out


def test_speculative_serve_matches_plain(generator):
    """--speculative K must not change output: every greedy configuration —
    including serve's DEFAULT repetition penalty of 1.1, which changes the
    argmax trajectory and is emulated inside the acceptance walk — routes
    through the speculative engine and must match the plain loop exactly.
    The sampled path ignores the flag."""
    spec_gen = TextGenerator(
        generator.cfg, generator.params, generator.tokenizer,
        cache_len=generator.cache_len, speculative=4,
    )
    kw = dict(max_new_tokens=12, greedy=True, repetition_penalty=1.0)
    assert spec_gen("hello there", **kw) == generator("hello there", **kw)
    # DEFAULT penalty 1.1: speculative engine vs plain loop, must agree
    a = generator("hello there", max_new_tokens=12, greedy=True)
    b = spec_gen("hello there", max_new_tokens=12, greedy=True)
    assert a == b
    # sampled path: same seed, speculative flag irrelevant
    a = generator("abc", max_new_tokens=6, greedy=False, seed=3)
    b = spec_gen("abc", max_new_tokens=6, greedy=False, seed=3)
    assert a == b
