"""serve.py TextGenerator: the user-facing generation surface (the
reference's app.py was CUDA-gated and untestable off-GPU; this path runs
anywhere). A stub tokenizer keeps the test network-free."""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from zero_transformer_tpu.config import ModelConfig
from zero_transformer_tpu.models import Transformer
from zero_transformer_tpu.serve import TextGenerator

CFG = ModelConfig(
    name="t", vocab_size=64, d_model=32, n_heads=2, n_layers=2, max_seq_len=32,
    dropout=0.0, compute_dtype="float32",
)


class StubTokenizer:
    """Deterministic char-level tokenizer: token = ord(char) % 60 + 1."""

    eos_token_id = 0

    def encode(self, text):
        return [ord(c) % 60 + 1 for c in text]

    def decode(self, ids):
        return "".join(chr(96 + (t % 26)) for t in ids)


@pytest.fixture(scope="module")
def generator():
    model = Transformer(CFG)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return TextGenerator(CFG, params, StubTokenizer(), cache_len=32)


def test_one_shot_generation(generator):
    out = generator("hello", max_new_tokens=8, greedy=True)
    assert isinstance(out, str) and len(out) > 0


def test_greedy_is_deterministic(generator):
    a = generator("same prompt", max_new_tokens=8, greedy=True, seed=0)
    b = generator("same prompt", max_new_tokens=8, greedy=True, seed=123)
    assert a == b  # greedy ignores the sampling seed


def test_sampling_seed_changes_output(generator):
    outs = {
        generator("vary", max_new_tokens=12, temperature=1.5, seed=s)
        for s in range(4)
    }
    assert len(outs) > 1  # at temperature 1.5 seeds should diverge


def test_prompt_longer_than_budget_keeps_tail(generator):
    # budget = cache_len - max_new_tokens = 24; a 100-char prompt must be
    # tail-truncated (reference app.py:61-64 semantics), not error
    out = generator("x" * 100, max_new_tokens=8, greedy=True)
    assert isinstance(out, str)


def test_no_room_for_prompt_raises(generator):
    with pytest.raises(ValueError, match="no room"):
        generator("hi", max_new_tokens=32)
