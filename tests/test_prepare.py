"""data.prepare CLI: raw text -> memmap / tar shards the loaders consume.

The reference's shard preparation lived outside its repo (its index files
point at finished GCS artifacts, reference ``main_zero.py:197-198``); here
the full path raw text -> training rows is in-tree and round-trip tested.
"""
import numpy as np
import pytest

from zero_transformer_tpu.data.prepare import main
from zero_transformer_tpu.data.sources import MemmapSource
from zero_transformer_tpu.data.tarshards import TarShardSource


@pytest.fixture()
def corpus(tmp_path):
    (tmp_path / "a.txt").write_text("hello world, this is document A!")
    (tmp_path / "b.txt").write_text("and B follows with more bytes than A has.")
    return tmp_path


def _expected_stream(sep):
    a = list(b"hello world, this is document A!")
    b = list(b"and B follows with more bytes than A has.")
    return a + ([sep] if sep is not None else []) + b


def test_memmap_roundtrip(corpus):
    out = corpus / "tokens.bin"
    main([
        "--input", str(corpus / "*.txt"), "--tokenizer", "bytes",
        "--max-context", "16", "--format", "memmap", "--out", str(out),
        "--doc-sep", "0",
    ])
    src = MemmapSource(str(out), max_context=16, shuffle=False)
    rows = [r for _, r in zip(range(src.n_rows), iter(src))]
    stream = _expected_stream(0)
    assert src.n_rows == len(stream) // 16  # trailing partial dropped
    np.testing.assert_array_equal(
        np.concatenate(rows), np.asarray(stream[: src.n_rows * 16])
    )


def test_tar_roundtrip_and_sharding(corpus):
    prefix = corpus / "shards" / "corpus"
    main([
        "--input", str(corpus / "*.txt"), "--tokenizer", "bytes",
        "--max-context", "8", "--format", "tar", "--out", str(prefix),
        "--rows-per-shard", "3", "--doc-sep", "0",
    ])
    index = f"{prefix}.index"
    src = TarShardSource(index, max_context=8, shuffle_shards=False, strict=True)
    stream = _expected_stream(0)
    n_rows = len(stream) // 8
    rows = [r for _, r in zip(range(n_rows), iter(src))]
    np.testing.assert_array_equal(
        np.concatenate(rows), np.asarray(stream[: n_rows * 8])
    )
    shards = open(index).read().splitlines()
    assert len(shards) == -(-n_rows // 3)  # ceil: rows-per-shard respected


def test_jsonl_input(tmp_path):
    p = tmp_path / "docs.jsonl"
    p.write_text('{"text": "abcdefgh"}\n{"text": "ijklmnop"}\n')
    out = tmp_path / "t.bin"
    main([
        "--input", str(p), "--tokenizer", "bytes", "--max-context", "4",
        "--format", "memmap", "--out", str(out), "--doc-sep", "0",
    ])
    src = MemmapSource(str(out), max_context=4, shuffle=False)
    stream = list(b"abcdefgh") + [0] + list(b"ijklmnop")
    assert src.n_rows == len(stream) // 4


def test_dtype_overflow_rejected(corpus, tmp_path):
    with pytest.raises(ValueError, match="uint16"):
        main([
            "--input", str(corpus / "*.txt"), "--tokenizer", "bytes",
            "--max-context", "8", "--format", "memmap",
            "--out", str(tmp_path / "x.bin"), "--doc-sep", "70000",
        ])


def test_negative_sep_rejected_not_wrapped(corpus, tmp_path):
    """A negative separator must error up front for BOTH formats — memmap
    would wrap it (int32 -1 -> uint16 65535) and tar would store it verbatim
    for nn.Embed to clamp silently at train time."""
    for fmt in ("memmap", "tar"):
        with pytest.raises(ValueError, match="doc-sep"):
            main([
                "--input", str(corpus / "*.txt"), "--tokenizer", "bytes",
                "--max-context", "8", "--format", fmt,
                "--out", str(tmp_path / f"y_{fmt}"), "--doc-sep", "-1",
            ])


def test_tar_index_relocatable_and_cwd_independent(corpus, tmp_path, monkeypatch):
    """Index entries are shard filenames resolved against the index's own
    directory: reading works from any cwd AND after moving the whole
    dataset directory."""
    import shutil

    prefix = tmp_path / "shards" / "c"
    main([
        "--input", str(corpus / "*.txt"), "--tokenizer", "bytes",
        "--max-context", "8", "--format", "tar", "--out", str(prefix),
        "--doc-sep", "0",
    ])
    monkeypatch.chdir("/")
    src = TarShardSource(f"{prefix}.index", max_context=8,
                         shuffle_shards=False, strict=True)
    assert next(iter(src)).shape == (8,)
    moved = tmp_path / "elsewhere"
    shutil.move(str(tmp_path / "shards"), str(moved))
    src2 = TarShardSource(str(moved / "c.index"), max_context=8,
                          shuffle_shards=False, strict=True)
    assert next(iter(src2)).shape == (8,)
