"""Reference-checkpoint importer: a trained fattorib/ZeRO-transformer
params tree must load into this framework and compute the SAME function.

The oracle below implements the reference's forward equations in plain
numpy (reference ``src/models/GPT.py:67-113``, ``src/models/layers.py:103-191``:
pre-LN, bias-free Dense, ALiBi as a key-position-only additive row — which
differs from our query-relative bias by a per-row constant that softmax
cancels — f32 softmax, tied head). If the converted params reproduce the
oracle's logits through OUR model, the rename/stack mapping and every
architectural convention (channel order, LN eps, gelu variant) are right.
"""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from zero_transformer_tpu.config import ModelConfig
from zero_transformer_tpu.export import convert_reference_params
from zero_transformer_tpu.models import Transformer
from zero_transformer_tpu.ops.positions import alibi_slopes_list

L, D, H, VOCAB, T = 2, 32, 4, 64, 12


def _ref_tree(seed=0):
    rng = np.random.default_rng(seed)

    def w(*shape):
        return (rng.normal(size=shape) * 0.05).astype(np.float32)

    def ln():
        return {"scale": (1.0 + rng.normal(size=(D,)) * 0.1).astype(np.float32)}

    tree = {"wte": {"embedding": w(VOCAB, D)}, "LayerNorm_0": ln()}
    for i in range(L):
        tree[f"TransformerBlock_{i}"] = {
            "LayerNorm_0": ln(),
            "LayerNorm_1": ln(),
            "CausalAttention_0": {
                name: {"kernel": w(D, D)}
                for name in ("query_proj", "key_proj", "value_proj", "residual_out")
            },
            "MLPBlock_0": {
                "fc_in": {"kernel": w(D, 4 * D)},
                "fc_residual": {"kernel": w(4 * D, D)},
            },
        }
    return tree


def _layernorm(x, scale, eps=1e-6):
    mu = x.mean(-1, keepdims=True)
    var = ((x - mu) ** 2).mean(-1, keepdims=True)
    return (x - mu) / np.sqrt(var + eps) * scale


def _gelu(x):  # tanh approximation (flax nn.gelu default, both codebases)
    return 0.5 * x * (1 + np.tanh(np.sqrt(2 / np.pi) * (x + 0.044715 * x**3)))


def _softmax(x):
    x = x - x.max(-1, keepdims=True)
    e = np.exp(x)
    return e / e.sum(-1, keepdims=True)


def _ref_forward(tree, x):
    """The reference's equations, numpy, batch [B, T] int -> logits."""
    emb = tree["wte"]["embedding"]
    h = emb[x]
    Dh = D // H
    slopes = np.asarray(alibi_slopes_list(H))
    # reference layers.py:33-44: the fixed mask keeps only row seq_len-1 of
    # the full distance matrix -> bias depends on the KEY position only
    bias = -(T - 1 - np.arange(T))[None, :] * slopes[:, None]  # [H, T]
    causal = np.tril(np.ones((T, T), bool))
    for i in range(L):
        blk = tree[f"TransformerBlock_{i}"]
        hn = _layernorm(h, blk["LayerNorm_0"]["scale"])
        att = blk["CausalAttention_0"]
        q, k, v = (
            (hn @ att[n]["kernel"]).reshape(-1, T, H, Dh).transpose(0, 2, 1, 3)
            for n in ("query_proj", "key_proj", "value_proj")
        )
        scores = q @ k.transpose(0, 1, 3, 2) / np.sqrt(Dh)  # [B, H, T, T]
        scores = scores + bias[None, :, None, :]
        scores = np.where(causal, scores, np.finfo(np.float32).min)
        out = _softmax(scores) @ v  # [B, H, T, Dh]
        out = out.transpose(0, 2, 1, 3).reshape(-1, T, D)
        h = h + out @ att["residual_out"]["kernel"]
        hn2 = _layernorm(h, blk["LayerNorm_1"]["scale"])
        mlp = _gelu(hn2 @ blk["MLPBlock_0"]["fc_in"]["kernel"])
        h = h + mlp @ blk["MLPBlock_0"]["fc_residual"]["kernel"]
    h = _layernorm(h, tree["LayerNorm_0"]["scale"])
    return h @ emb.T


def _our_cfg(scan):
    return ModelConfig(
        name="ref_t", vocab_size=VOCAB, d_model=D, n_heads=H, n_layers=L,
        max_seq_len=T, dropout=0.0, position="alibi", compute_dtype="float32",
        scan_layers=scan,
    )


@pytest.mark.parametrize("scan", [True, False])
def test_converted_params_reproduce_reference_logits(scan):
    tree = _ref_tree()
    params = convert_reference_params(tree, scan_layers=scan)
    x = np.random.default_rng(1).integers(0, VOCAB, (2, T))
    ref_logits = _ref_forward(tree, x)
    ours = Transformer(_our_cfg(scan)).apply(
        {"params": params}, jnp.asarray(x, jnp.int32)
    )
    np.testing.assert_allclose(np.asarray(ours), ref_logits, atol=2e-4, rtol=2e-4)


def test_convert_rejects_unknown_and_missing_leaves():
    tree = _ref_tree()
    tree["TransformerBlock_0"]["CausalAttention_0"]["query_proj"]["bias"] = (
        np.zeros(D, np.float32)
    )
    with pytest.raises(ValueError, match="unrecognized"):
        convert_reference_params(tree)
    tree = _ref_tree()
    del tree["TransformerBlock_1"]["MLPBlock_0"]["fc_in"]
    with pytest.raises(ValueError, match="missing"):
        convert_reference_params(tree)
    with pytest.raises(ValueError, match="reference params tree"):
        convert_reference_params({"wte": tree["wte"]})


@pytest.mark.parametrize("scan", [True, False])
def test_to_reference_roundtrip_identity(scan):
    """convert_to_reference_params is the exact inverse of
    convert_reference_params, in BOTH directions and both layer layouts:
    ref -> ours -> ref reproduces the reference tree leaf-for-leaf, and a
    fresh init of our model survives ours -> ref -> ours bit-identically
    (the outbound interchange the reference had via flax_to_pytorch.py,
    here torch-free — round-4 VERDICT missing #3)."""
    from zero_transformer_tpu.export import convert_to_reference_params

    tree = _ref_tree()
    ours = convert_reference_params(tree, scan_layers=scan)
    ref_again = convert_to_reference_params(ours)
    flat_a = jax.tree_util.tree_flatten_with_path(tree)[0]
    flat_b = jax.tree_util.tree_flatten_with_path(ref_again)[0]
    assert [p for p, _ in flat_a] == [p for p, _ in flat_b]
    for (pa, a), (_, b) in zip(flat_a, flat_b):
        np.testing.assert_array_equal(a, b, err_msg=str(pa))

    params = Transformer(_our_cfg(scan)).init(
        jax.random.PRNGKey(3), jnp.zeros((1, 4), jnp.int32)
    )["params"]
    from zero_transformer_tpu.parallel.sharding import unbox

    params = jax.tree.map(np.asarray, unbox(params))
    back = convert_reference_params(
        convert_to_reference_params(params), scan_layers=scan
    )
    for (pa, a), (pb, b) in zip(
        jax.tree_util.tree_flatten_with_path(params)[0],
        jax.tree_util.tree_flatten_with_path(back)[0],
    ):
        assert pa == pb
        np.testing.assert_array_equal(a, b, err_msg=str(pa))


def test_to_reference_rejects_out_of_family():
    """Leaves without a reference counterpart must raise, not silently drop
    — an exported checkpoint that loads but computes a different function
    is the worst failure mode an interchange path can have."""
    from zero_transformer_tpu.export import convert_to_reference_params

    # swiglu adds a gate kernel the reference MLP doesn't have
    cfg = dataclasses.replace(_our_cfg(True), activation="swiglu")
    params = Transformer(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
    )["params"]
    from zero_transformer_tpu.parallel.sharding import unbox

    with pytest.raises(ValueError, match="counterpart"):
        convert_to_reference_params(unbox(params))
    # untied head leaves an lm_head leftover
    cfg = dataclasses.replace(_our_cfg(True), tie_embeddings=False)
    params = Transformer(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
    )["params"]
    with pytest.raises(ValueError, match="counterpart"):
        convert_to_reference_params(unbox(params))
    # GQA: non-square kv projections cannot round-trip
    cfg = dataclasses.replace(_our_cfg(True), n_kv_heads=2)
    params = Transformer(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
    )["params"]
    with pytest.raises(ValueError, match="GQA"):
        convert_to_reference_params(unbox(params))
    # MISSING leaves raise too (incomplete per-block tree / index gap) —
    # an incomplete reference checkpoint would load and compute a
    # different function
    ours = convert_reference_params(_ref_tree(), scan_layers=False)
    del ours["block_1"]["mlp"]["wo"]
    with pytest.raises(ValueError, match="missing"):
        convert_to_reference_params(ours)
    ours = convert_reference_params(_ref_tree(), scan_layers=False)
    ours["block_3"] = ours.pop("block_1")  # non-contiguous indices
    with pytest.raises(ValueError, match="missing"):
        convert_to_reference_params(ours)


def test_to_reference_cli(tmp_path):
    """CLI: ours msgpack -> reference-layout msgpack (round-trip-verified
    in-command); --model family guard rejects llama-style zoo entries."""
    from flax.serialization import msgpack_restore, msgpack_serialize

    from zero_transformer_tpu.export import main
    from zero_transformer_tpu.parallel.sharding import unbox

    params = Transformer(_our_cfg(True)).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
    )["params"]
    params = jax.tree.map(np.asarray, unbox(params))
    ours_path = tmp_path / "ours.msgpack"
    ours_path.write_bytes(msgpack_serialize(params))
    out_path = tmp_path / "ref.msgpack"
    main(["to-reference", "--params", str(ours_path), "--out", str(out_path)])
    ref = msgpack_restore(out_path.read_bytes())
    assert set(ref) == {"wte", "LayerNorm_0"} | {
        f"TransformerBlock_{i}" for i in range(L)
    }
    # the emitted tree feeds straight back through the importer
    again = convert_reference_params(ref, scan_layers=True)
    for (pa, a), (pb, b) in zip(
        jax.tree_util.tree_flatten_with_path(params)[0],
        jax.tree_util.tree_flatten_with_path(again)[0],
    ):
        assert pa == pb
        np.testing.assert_array_equal(a, b)
    with pytest.raises(SystemExit, match="family"):
        main(["to-reference", "--params", str(ours_path),
              "--model", "llama3_test", "--out", str(out_path)])
    # an outer "params" wrapper (raw TrainState-style msgpack) is tolerated
    wrapped_path = tmp_path / "wrapped.msgpack"
    wrapped_path.write_bytes(msgpack_serialize({"params": params}))
    out2 = tmp_path / "ref2.msgpack"
    main(["to-reference", "--params", str(wrapped_path), "--out", str(out2)])
    assert out2.read_bytes() == out_path.read_bytes()


def test_import_reference_cli_roundtrip(tmp_path):
    """CLI: reference msgpack in, shape-validated msgpack out, loadable by
    the serve/eval path."""
    from flax.serialization import msgpack_restore, msgpack_serialize

    from zero_transformer_tpu.export import main

    ref_path = tmp_path / "ref.msgpack"
    ref_path.write_bytes(msgpack_serialize(_ref_tree()))
    out_path = tmp_path / "ours.msgpack"
    # the test zoo entry's geometry must match the synthetic tree; use an
    # explicit config via the zoo "test" name? test zoo differs -> expect
    # SystemExit on shape mismatch (negative), then succeed with a matching
    # custom config through the library API instead
    with pytest.raises(SystemExit, match="shape|params"):
        main(["import-reference", "--params", str(ref_path), "--model", "test",
              "--out", str(out_path)])
    # library path with matching geometry
    params = convert_reference_params(msgpack_restore(ref_path.read_bytes()))
    logits = Transformer(_our_cfg(True)).apply(
        {"params": params}, jnp.zeros((1, 4), jnp.int32)
    )
    assert np.isfinite(np.asarray(logits)).all()
