"""Fleet router: registry state machine, routing policy, failover, reload.

Three tiers of evidence, cheapest first:

- **pure logic** (no sockets): the replica registry's probe-outcome state
  machine (ejection after consecutive failures, exponential-backoff
  re-probe, recovery), and the routing policy (READY over DEGRADED, prefix
  affinity with longest-match, least-loaded tie-break) — the satellite's
  sockets-free unit tests;
- **stub replicas** (HTTP, no jax compute): paced fake replicas from
  ``scripts/serve_router.py`` prove the relay mechanics on the wire —
  X-Request-Id propagation, mid-stream failover that resumes the token
  sequence exactly, graceful degradation to a retryable terminal event,
  rolling reload with zero dropped streams, ejection flight dumps;
- **real engines** (in-process ``ServingServer`` fleet on the test zoo
  model): routed responses byte-identical to single-request ``generate()``,
  greedy mid-stream failover resuming the EXACT trajectory, fleet-wide
  rolling reload under live streams.

The SIGKILL chaos scenario (3 subprocess replicas, one killed mid-load,
then a rolling reload) is slow+chaos-marked: ``make router-chaos``.
"""
import http.client
import importlib.util
import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
from pathlib import Path

import pytest

from zero_transformer_tpu.serving.resilience import DEGRADED, DRAINING, READY
from zero_transformer_tpu.serving.router import (
    EJECTED,
    UNKNOWN,
    PrefixAffinity,
    Replica,
    ReplicaRegistry,
    RouterServer,
    chunk_prefix_key,
    pick_replica,
)

REPO = Path(__file__).resolve().parent.parent


def _load_serve_router():
    spec = importlib.util.spec_from_file_location(
        "serve_router", REPO / "scripts" / "serve_router.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


# ------------------------------------------------------------ registry (pure)


def _ids(replicas):
    return [r.id for r in replicas]


def test_registry_probe_failure_ejection_backoff_and_recovery():
    clk = FakeClock()
    reg = ReplicaRegistry(
        ["http://h:1", "http://h:2"], clock=clk, probe_interval=1.0,
        eject_threshold=3, backoff_base_s=2.0, backoff_max_s=8.0,
    )
    r1 = "h:1"
    # never probed: everyone is due immediately, nobody routable
    assert set(_ids(reg.due())) == {"h:1", "h:2"}
    assert reg.routable() == []

    assert reg.observe_probe(r1, True, 200, {"state": READY}) == []
    assert _ids(reg.routable()) == [r1]
    assert reg.get(r1).next_probe_at == 1.0  # probe_interval from now

    # two failures: suspicious but still in rotation (relay failover covers
    # the window); the third consecutive failure ejects
    assert reg.observe_probe(r1, False) == []
    assert reg.observe_probe(r1, False) == []
    assert _ids(reg.routable()) == [r1]
    assert reg.observe_probe(r1, False) == [("ejected", r1)]
    rep = reg.get(r1)
    assert rep.state == EJECTED and rep.backoff_s == 2.0
    assert reg.routable() == []

    # backoff honored: not due again until 2 s elapse, then each failed
    # re-probe doubles the wait up to the cap
    clk.t += 1.0
    assert r1 not in _ids(reg.due())
    clk.t += 1.1
    assert r1 in _ids(reg.due())
    assert reg.observe_probe(r1, False) == []  # still dead
    assert reg.get(r1).backoff_s == 4.0
    reg.observe_probe(r1, False)
    assert reg.get(r1).backoff_s == 8.0
    reg.observe_probe(r1, False)
    assert reg.get(r1).backoff_s == 8.0  # capped

    # one good probe recovers it completely
    events = reg.observe_probe(r1, True, 200, {"state": READY})
    assert ("recovered", r1) in events
    rep = reg.get(r1)
    assert rep.state == READY and rep.backoff_s == 0.0
    assert rep.consecutive_failures == 0
    assert _ids(reg.routable()) == [r1]


def test_registry_honors_replica_lifecycle_states():
    clk = FakeClock()
    reg = ReplicaRegistry(["http://h:1"], clock=clk)
    r1 = "h:1"
    # a 503 that ANSWERS with a draining/stopped body leaves rotation
    # without the ejection machinery (it may come back READY after restart)
    reg.observe_probe(r1, True, 503, {"state": DRAINING})
    assert reg.get(r1).state == DRAINING and reg.routable() == []
    reg.observe_probe(r1, True, 503, {"state": "stopped"})
    assert reg.get(r1).state == DRAINING
    # DEGRADED answers stay routable (deprioritized by the policy)
    reg.observe_probe(r1, True, 503, {"state": DEGRADED})
    assert reg.get(r1).state == DEGRADED and _ids(reg.routable()) == [r1]
    # STARTING is not routable yet
    reg.observe_probe(r1, True, 503, {"state": "starting"})
    assert reg.get(r1).state == UNKNOWN and reg.routable() == []
    # the probe scrapes the admission inputs from the body
    reg.observe_probe(r1, True, 200, {
        "state": READY, "itl_ewma_ms": 3.5, "queue_depth": 7,
        "active_slots": 2, "free_pages": 11,
    })
    rep = reg.get(r1)
    assert rep.itl_ewma_ms == 3.5 and rep.queue_depth == 7
    assert rep.active_slots == 2 and rep.free_pages == 11
    # cordon removes from rotation without touching probed state
    reg.cordon(r1)
    assert reg.routable() == [] and reg.get(r1).state == READY
    reg.uncordon(r1)
    assert _ids(reg.routable()) == [r1]


def test_registry_reregister_replace_does_not_resurrect_stale_cordon():
    """A SIGKILLed process that re-registers under the same id must get a
    FRESH row: inheriting the dead predecessor's cordon (or its tripped
    breaker) would keep the new, healthy process out of rotation forever.
    The training fleet's re-admission path rides exactly this seam."""
    clk = FakeClock()
    reg = ReplicaRegistry(["http://h:1"], clock=clk, eject_threshold=3)
    r1 = "h:1"
    reg.observe_probe(r1, True, 200, {"state": READY})
    # the old incarnation dies: failures trip the breaker, ops cordons it
    for _ in range(3):
        reg.observe_probe(r1, False)
    reg.cordon(r1)
    assert reg.get(r1).state == EJECTED and reg.routable() == []

    # default add() is the idempotent admin path: same id short-circuits,
    # stale state intentionally preserved (re-adding a draining live
    # replica must not silently uncordon it)
    assert reg.add("http://h:1") == r1
    assert reg.get(r1).cordoned and reg.get(r1).state == EJECTED

    # replace=True is the reincarnation path: clean slate
    assert reg.add("http://h:1", replace=True) == r1
    rep = reg.get(r1)
    assert not rep.cordoned
    assert rep.state == UNKNOWN  # fresh rows still earn routability
    assert rep.consecutive_failures == 0
    assert reg.routable() == []  # not routable on trust alone
    reg.observe_probe(r1, True, 200, {"state": READY})
    assert _ids(reg.routable()) == [r1]


def test_registry_probe_for_removed_replica_dropped_not_readded():
    """Late health data from a removed member (probe completing mid-retire,
    a worker heartbeat arriving after eviction) is DROPPED: re-admission is
    an explicit add(), never a side effect of stale telemetry."""
    clk = FakeClock()
    reg = ReplicaRegistry(["http://h:1"], clock=clk)
    r1 = "h:1"
    reg.observe_probe(r1, True, 200, {"state": READY})
    reg.remove(r1)
    assert reg.observe_probe(r1, True, 200, {"state": READY}) == []
    assert r1 not in reg.replicas and reg.routable() == []
    # failure-shaped stragglers equally inert
    assert reg.observe_probe(r1, False) == []
    assert r1 not in reg.replicas


def test_registry_relay_failure_feeds_breaker_and_reprobes_now():
    clk = FakeClock()
    reg = ReplicaRegistry(
        ["http://h:1"], clock=clk, probe_interval=5.0, eject_threshold=3,
    )
    r1 = "h:1"
    reg.observe_probe(r1, True, 200, {"state": READY})
    clk.t = 1.0
    assert reg.due() == []  # next probe is 5 s out
    assert reg.observe_relay_failure(r1, "connect refused") == []
    # the relay failure counts toward ejection AND forces an immediate probe
    assert reg.get(r1).consecutive_failures == 1
    assert _ids(reg.due()) == [r1]
    reg.observe_relay_failure(r1, "x")
    events = reg.observe_relay_failure(r1, "x")
    assert ("ejected", r1) in events


# ------------------------------------------------------------- policy (pure)


def _mk(rid, state=READY, q=0, itl=1.0, slots=0, relays=0):
    r = Replica(id=rid, url=f"http://h/{rid}", host="h", port=1)
    r.state = state
    r.queue_depth = q
    r.itl_ewma_ms = itl
    r.active_slots = slots
    r.active_relays = relays
    return r


def test_chunk_prefix_key_alignment():
    assert chunk_prefix_key(None, 4) is None
    assert chunk_prefix_key([1, 2, 3], 4) is None  # under one chunk
    assert chunk_prefix_key([1, 2, 3, 4], 4) == (1, 2, 3, 4)
    assert chunk_prefix_key([1, 2, 3, 4, 5, 6], 4) == (1, 2, 3, 4)
    assert chunk_prefix_key(list(range(8)), 4) == tuple(range(8))


def test_affinity_longest_match_and_forget():
    aff = PrefixAffinity(chunk_tokens=4, capacity=8)
    prompt_a = [1, 2, 3, 4, 5, 6, 7, 8, 9]  # levels [:8] and [:4]
    aff.record(prompt_a, "r1")
    # shares only the first chunk -> matched at the [:4] level
    assert aff.lookup([1, 2, 3, 4, 99, 98, 97, 96]) == "r1"
    # full deeper prefix -> matched at the [:8] level
    assert aff.lookup(prompt_a) == "r1"
    assert aff.lookup([9, 9, 9, 9]) is None
    # a later route claims every level of ITS prompt (the new replica now
    # holds the shared chunks too) — deepest-first lookup follows it
    aff.record([1, 2, 3, 4, 5, 6, 7, 8], "r2")
    assert aff.lookup(prompt_a) == "r2"
    assert aff.lookup([1, 2, 3, 4, 50]) == "r2"
    # forgetting a replica (ejection, reload) drops all its entries
    aff.forget_replica("r2")
    assert aff.lookup(prompt_a) is None
    # LRU bound holds
    for i in range(20):
        aff.record([i] * 4, "rX")
    assert len(aff) <= 8


def test_pick_replica_policy():
    # empty pool
    assert pick_replica([]) is None
    assert pick_replica([_mk("a", state=EJECTED)]) is None
    # READY beats DEGRADED even when the degraded one is idle
    ready_busy = _mk("busy", q=10, itl=5.0)
    degraded_idle = _mk("idle", state=DEGRADED)
    assert pick_replica([degraded_idle, ready_busy]).id == "busy"
    # DEGRADED serves when it is all there is
    assert pick_replica([degraded_idle]).id == "idle"
    # least-loaded: smaller backlog-x-ITL wins
    slow = _mk("slow", q=2, itl=10.0)
    fast = _mk("fast", q=2, itl=1.0)
    empty = _mk("empty", q=0, itl=10.0)
    assert pick_replica([slow, fast]).id == "fast"
    assert pick_replica([slow, fast, empty]).id == "empty"
    # the router's own in-flight relays count as load
    assert pick_replica([_mk("a", relays=3), _mk("b", relays=1)]).id == "b"
    # affinity wins within the healthy pool even against a lighter replica
    assert pick_replica([slow, fast], affinity_id="slow").id == "slow"
    # ...but never drags traffic to a DEGRADED replica while READY exists
    assert pick_replica(
        [degraded_idle, fast], affinity_id="idle"
    ).id == "fast"
    # deterministic id tie-break
    assert pick_replica([_mk("b"), _mk("a")]).id == "a"


# ----------------------------------------------------- stub fleet (HTTP, fast)


def _sse_post(port, body, headers=None, timeout=30.0):
    """Minimal SSE client: returns (status, events, json_doc). For 200
    streams, events is every parsed ``data:`` event through the done
    event; for JSON responses/rejections, json_doc is the parsed body."""
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request(
            "POST", "/generate", json.dumps(body),
            {"Content-Type": "application/json", **(headers or {})},
        )
        resp = conn.getresponse()
        ctype = resp.getheader("Content-Type", "")
        if "text/event-stream" not in ctype:
            return resp, [], json.loads(resp.read() or b"{}")
        events = []
        while True:
            line = resp.readline()
            if not line:
                break
            if not line.startswith(b"data: "):
                continue
            event = json.loads(line[6:])
            events.append(event)
            if event.get("done"):
                break
        return resp, events, None
    finally:
        conn.close()


def _get(port, path, headers=None):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    try:
        conn.request("GET", path, headers=headers or {})
        resp = conn.getresponse()
        return resp.status, resp.read(), dict(resp.getheaders())
    finally:
        conn.close()


def _wait(pred, timeout=10.0, interval=0.01, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {msg}")


@pytest.fixture(scope="module")
def serve_router_mod():
    return _load_serve_router()


def _stub_fleet(serve_router_mod, n=2, **kw):
    stubs = [serve_router_mod.StubReplica(**kw).start() for _ in range(n)]
    return stubs


def test_router_rejects_with_retry_after_when_no_replica_routable(
    serve_router_mod,
):
    # the fleet exists but is unreachable (stopped stub = connect refused):
    # requests must fail fast with 503 + Retry-After, not hang
    dead = serve_router_mod.StubReplica().start()
    dead.stop()
    router = RouterServer([dead.url], probe_interval=0.02, max_attempts=2)
    router.start()
    try:
        status, body, headers = _get(router.port, "/healthz")
        assert status == 503
        resp, events, doc = _sse_post(
            router.port, {"tokens": [1, 2, 3], "max_new_tokens": 4}
        )
        assert resp.status == 503
        assert doc["status"] == "rejected"
        assert int(resp.getheader("Retry-After")) >= 1
        assert resp.getheader("X-Request-Id")
        assert router.stats["rejected_no_replica"] == 1
        assert router.stats["dropped_streams"] == 0
    finally:
        router.stop()


def test_router_relays_stream_and_propagates_request_id(serve_router_mod):
    stubs = _stub_fleet(serve_router_mod, n=2, itl_s=0.001)
    router = RouterServer(
        [s.url for s in stubs], probe_interval=0.02, chunk_tokens=4,
    )
    router.start()
    try:
        assert router.wait_ready(5.0)
        tokens = [1, 2, 3, 4, 5]
        resp, events, _ = _sse_post(
            router.port,
            {"tokens": tokens, "max_new_tokens": 6},
            headers={"X-Request-Id": "client-id-042"},
        )
        assert resp.getheader("X-Request-Id") == "client-id-042"
        done = events[-1]
        assert done["done"] and done["status"] == "done"
        assert done["request_id"] == "client-id-042"
        assert done["failovers"] == 0
        ids = [e["token"] for e in events if "token" in e]
        # the stub's arithmetic sequence: base + prompt_len, +1, ...
        assert ids == list(range(1005, 1011))
        assert done["text"] == "".join(f"<{t}>" for t in ids)
        # the replica saw the SAME correlation id the client sent
        served = [s for s in stubs if s.requests]
        assert len(served) == 1
        assert served[0].seen_request_ids == ["client-id-042"]
        # and the span tree names the replica that served the hop
        relay_spans = [
            s for s in router.tracer.by_track("client-id-042")
            if s[2] == "relay"
        ]
        assert len(relay_spans) == 1
        srv_id = f"127.0.0.1:{served[0].port}"
        assert relay_spans[0][5]["replica"] == srv_id
        route_spans = [
            s for s in router.tracer.by_track("client-id-042")
            if s[2] == "route"
        ]
        assert route_spans and route_spans[0][5]["outcome"] == "done"
        assert router.stats["tokens_relayed"] == 6
        assert router.registry.get(srv_id).tokens_relayed == 6

        # JSON (non-stream) relay carries the id and the serving replica
        resp2, _, doc = _sse_post(
            router.port,
            {"tokens": tokens, "max_new_tokens": 3, "stream": False},
        )
        assert resp2.status == 200 and doc["status"] == "done"
        assert doc["tokens"] == list(range(1005, 1008))
        assert doc["replica"] in {f"127.0.0.1:{s.port}" for s in stubs}
    finally:
        router.stop()
        for s in stubs:
            s.stop()


def test_midstream_failover_resumes_token_sequence_on_survivor(
    serve_router_mod,
):
    # replica A dies (connection cut, no done event) after 3 tokens; the
    # router must re-dispatch prompt+generated to B and the CLIENT's stream
    # must be the uninterrupted arithmetic sequence
    a = serve_router_mod.StubReplica(itl_s=0.005, die_after_tokens=3).start()
    b = serve_router_mod.StubReplica(itl_s=0.005).start()
    router = RouterServer(
        [a.url, b.url], probe_interval=0.02, chunk_tokens=4, max_attempts=3,
    )
    # probes off, registry hand-fed: the stub that cuts ONE stream is still
    # alive on /healthz, so a live probe loop would legitimately clear the
    # relay failure's consecutive_failures before the assertions run
    router.start(probe=False)
    try:
        a_id, b_id = f"127.0.0.1:{a.port}", f"127.0.0.1:{b.port}"
        router.registry.observe_probe(a_id, True, 200, {"state": READY})
        router.registry.observe_probe(b_id, True, 200, {"state": READY})
        tokens = [7, 8, 9, 10]
        router.affinity.record(tokens, a_id)  # deterministic first hop
        resp, events, _ = _sse_post(
            router.port, {"tokens": tokens, "max_new_tokens": 6},
            headers={"X-Request-Id": "failover-1"},
        )
        done = events[-1]
        assert done["done"] and done["status"] == "done", done
        assert done["failovers"] == 1
        ids = [e["token"] for e in events if "token" in e]
        # A emitted 1004..1006 (prompt len 4), died; B resumed with prompt
        # len 7 -> 1007..1009. One continuous sequence, no gap, no repeat.
        assert ids == [1004, 1005, 1006, 1007, 1008, 1009]
        assert done["text"] == "".join(f"<{t}>" for t in ids)
        assert a.died and b.tokens_emitted == 3
        # B's resumed request carried prompt + generated-so-far and the
        # reduced budget
        resumed = b.seen_bodies[-1]
        assert resumed["tokens"] == tokens + [1004, 1005, 1006]
        assert resumed["max_new_tokens"] == 3
        assert b.seen_request_ids[-1] == "failover-1"
        assert router.stats["failovers"] == 1
        assert router.stats["resumed_streams"] == 1
        assert router.stats["dropped_streams"] == 0
        # the failed hop fed the victim's breaker and the affinity moved
        assert router.registry.get(a_id).consecutive_failures >= 1
        assert router.affinity.lookup(tokens) == b_id
        # span tree shows both hops, tagged with their replicas
        relays = [
            s for s in router.tracer.by_track("failover-1")
            if s[2] == "relay"
        ]
        assert [s[5]["replica"] for s in relays] == [a_id, b_id]
        assert relays[0][5]["resumed"] is False
        assert relays[1][5]["resumed"] is True
    finally:
        router.stop()
        for s in (a, b):
            s.stop()


def test_nonresumable_text_prompt_degrades_to_retryable_error(
    serve_router_mod,
):
    # a TEXT prompt cannot be re-dispatched once tokens were relayed (the
    # router never saw the replica's tokenization): the stream must end
    # with a retryable terminal error event — never a hang, never a drop
    a = serve_router_mod.StubReplica(itl_s=0.005, die_after_tokens=2).start()
    b = serve_router_mod.StubReplica(itl_s=0.005).start()
    router = RouterServer(
        [a.url, b.url], probe_interval=0.02, chunk_tokens=4,
    )
    router.start()
    try:
        _wait(lambda: len(router.registry.routable()) == 2, msg="fleet ready")
        # force the doomed replica: no tokens -> no affinity, so pin by load
        a_id = f"127.0.0.1:{a.port}"
        b_id = f"127.0.0.1:{b.port}"
        router.registry.get(b_id).queue_depth = 99  # scraped load, stale ok
        resp, events, _ = _sse_post(
            router.port, {"prompt": "hello world", "max_new_tokens": 6},
        )
        assert a.died
        done = events[-1]
        assert done["done"] and done["status"] == "failed"
        assert done["retryable"] is True
        assert "resumable" in done["error"]
        assert done["failovers"] == 1
        # the two tokens that made it through are in the accumulated text
        assert done["text"] == "".join(
            f"<{e['token']}>" for e in events if "token" in e
        )
        assert router.stats["aborted_streams"] == 1
        assert router.stats["dropped_streams"] == 0
    finally:
        router.stop()
        for s in (a, b):
            s.stop()


def test_connect_failure_fails_over_before_first_token(serve_router_mod):
    # replica believed-READY but gone (crash between probes): the router
    # must fail over silently — the client sees one clean stream
    dead = serve_router_mod.StubReplica().start()
    dead_id = f"127.0.0.1:{dead.port}"
    dead.stop()
    b = serve_router_mod.StubReplica(itl_s=0.002).start()
    b_id = f"127.0.0.1:{b.port}"
    router = RouterServer([dead.url, b.url], chunk_tokens=4, max_attempts=3)
    router.start(probe=False)  # registry state is hand-fed, probes off
    try:
        router.registry.observe_probe(dead_id, True, 200, {"state": READY})
        router.registry.observe_probe(b_id, True, 200, {"state": READY})
        tokens = [5, 5, 5, 5]
        router.affinity.record(tokens, dead_id)
        resp, events, _ = _sse_post(
            router.port, {"tokens": tokens, "max_new_tokens": 4}
        )
        done = events[-1]
        assert done["status"] == "done" and done["failovers"] == 1
        ids = [e["token"] for e in events if "token" in e]
        assert ids == [1004, 1005, 1006, 1007]  # all from B, from token 0
        assert router.stats["resumed_streams"] == 0  # nothing was relayed
        assert router.registry.get(dead_id).consecutive_failures >= 1
    finally:
        router.stop()
        b.stop()  # `dead` was already stopped by the scenario itself


def test_prestream_5xx_fails_over_with_suspicion(serve_router_mod):
    # a replica answering 500 BEFORE any stream bytes is alive-but-broken:
    # the router must silently try the next replica (module docstring's
    # pre-stream promise) and feed the victim's breaker — without
    # forgetting its affinity (its prefix cache is intact)
    sick = serve_router_mod.StubReplica(fail_5xx_requests=2).start()
    sick_id = f"127.0.0.1:{sick.port}"
    b = serve_router_mod.StubReplica(itl_s=0.002).start()
    b_id = f"127.0.0.1:{b.port}"
    router = RouterServer([sick.url, b.url], chunk_tokens=4, max_attempts=3)
    router.start(probe=False)
    try:
        router.registry.observe_probe(sick_id, True, 200, {"state": READY})
        router.registry.observe_probe(b_id, True, 200, {"state": READY})
        tokens = [6, 6, 6, 6]
        other = [9, 9, 9, 9]
        router.affinity.record(tokens, sick_id)
        router.affinity.record(other, sick_id)
        resp, events, _ = _sse_post(
            router.port, {"tokens": tokens, "max_new_tokens": 4}
        )
        done = events[-1]
        assert done["status"] == "done" and done["failovers"] == 1
        ids = [e["token"] for e in events if "token" in e]
        assert ids == [1004, 1005, 1006, 1007]  # served whole by B
        assert router.stats["failovers"] == 1
        assert router.stats["dropped_streams"] == 0
        assert router.registry.get(sick_id).consecutive_failures >= 1
        # the served prompt's affinity moved with the request; but unlike a
        # dead socket, a 5xx answer does NOT forget the replica's OTHER
        # affinities (the replica — and its prefix cache — is alive)
        assert router.affinity.lookup(tokens) == b_id
        assert router.affinity.lookup(other) == sick_id
        # JSON path: `other` is still affine to sick, whose second armed
        # 500 must hit the same retry-elsewhere semantics
        resp2, _, doc = _sse_post(
            router.port,
            {"tokens": other, "max_new_tokens": 3, "stream": False},
        )
        assert resp2.status == 200 and doc["status"] == "done"
        assert doc["replica"] == b_id
        assert router.stats["failovers"] == 2
    finally:
        router.stop()
        sick.stop()
        b.stop()


def test_malformed_numeric_fields_rejected_400_not_dropped(serve_router_mod):
    # a client typo in max_new_tokens must be a clean 400 — never an
    # uncaught ValueError tearing the socket and polluting dropped_streams
    stub = serve_router_mod.StubReplica().start()
    router = RouterServer([stub.url], probe_interval=0.02)
    router.start()
    try:
        assert router.wait_ready(5.0)
        resp, _, doc = _sse_post(
            router.port, {"tokens": [1, 2], "max_new_tokens": "ten"}
        )
        assert resp.status == 400
        assert "max_new_tokens" in doc["error"]
        resp2, _, doc2 = _sse_post(
            router.port,
            {"tokens": [1, 2], "max_new_tokens": 4, "timeout": "soon"},
        )
        assert resp2.status == 400
        assert router.stats["rejected_invalid"] == 2
        assert router.stats["dropped_streams"] == 0
    finally:
        router.stop()
        stub.stop()


def test_retry_after_header_propagates_from_replicas(serve_router_mod):
    # the replica advertises its backoff as an HTTP Retry-After HEADER (no
    # body field): a fleet that is all-draining must surface the largest
    # advertised wait on the router's 503, not a hardcoded 1s
    stubs = _stub_fleet(
        serve_router_mod, n=2, backpressure_retry_after=30.0
    )
    router = RouterServer(
        [s.url for s in stubs], probe_interval=0.02, max_attempts=3,
    )
    router.start()
    try:
        assert router.wait_ready(5.0)  # stubs probe READY, then 503 relays
        resp, _, doc = _sse_post(
            router.port, {"tokens": [1, 2, 3], "max_new_tokens": 4}
        )
        assert resp.status == 503 and doc["status"] == "rejected"
        assert int(resp.getheader("Retry-After")) >= 30
        # stream and JSON paths share the plumbing
        resp2, _, doc2 = _sse_post(
            router.port,
            {"tokens": [1, 2, 3], "max_new_tokens": 4, "stream": False},
        )
        assert resp2.status == 503
        assert int(resp2.getheader("Retry-After")) >= 30
    finally:
        router.stop()
        for s in stubs:
            s.stop()


def test_death_after_last_token_finishes_done_not_failed(serve_router_mod):
    # the replica emits every budgeted token then dies before its done
    # event, with NO retry budget left: the client holds the complete
    # generation, so the terminal event must say done — not push the client
    # into retrying (and regenerating) a finished response
    a = serve_router_mod.StubReplica(itl_s=0.002, die_after_tokens=4).start()
    router = RouterServer([a.url], probe_interval=0.02, max_attempts=1)
    router.start()
    try:
        assert router.wait_ready(5.0)
        resp, events, _ = _sse_post(
            router.port, {"tokens": [1, 2, 3], "max_new_tokens": 4}
        )
        done = events[-1]
        assert done["done"] and done["status"] == "done", done
        assert "error" not in done
        ids = [e["token"] for e in events if "token" in e]
        assert len(ids) == 4 and a.died
        assert router.stats["aborted_streams"] == 0
        assert router.stats["dropped_streams"] == 0
    finally:
        router.stop()
        a.stop()


def test_rolling_reload_under_load_drops_nothing(serve_router_mod):
    stubs = _stub_fleet(serve_router_mod, n=2, itl_s=0.005, slots=4)
    router = RouterServer(
        [s.url for s in stubs], probe_interval=0.02, chunk_tokens=4,
    )
    router.start()
    results = []
    try:
        _wait(lambda: len(router.registry.routable()) == 2, msg="fleet ready")

        def client(i):
            for j in range(3):
                resp, events, doc = _sse_post(
                    router.port,
                    {"tokens": [i, j, 1, 2], "max_new_tokens": 20},
                    timeout=60,
                )
                results.append(events[-1] if events else doc)

        threads = [
            threading.Thread(target=client, args=(i,), daemon=True)
            for i in range(4)
        ]
        for t in threads:
            t.start()
        time.sleep(0.05)  # streams in flight
        ok, steps = router.rolling_reload(drain_timeout_s=30.0,
                                          ready_timeout_s=30.0)
        for t in threads:
            t.join(timeout=60)
        assert not any(t.is_alive() for t in threads), "client hung"
        assert ok, steps
        assert [s["ok"] for s in steps] == [True, True]
        assert all(s.reloads == 1 for s in stubs)
        assert len(results) == 12
        assert all(r.get("status") == "done" for r in results), results
        assert router.stats["dropped_streams"] == 0
        assert router.stats["reload_steps"] == 2
    finally:
        router.stop()
        for s in stubs:
            s.stop()


def test_rolling_reload_refuses_concurrent_runs(serve_router_mod):
    stubs = _stub_fleet(serve_router_mod, n=2, reload_delay_s=0.3)
    router = RouterServer([s.url for s in stubs], probe_interval=0.02)
    router.start()
    try:
        _wait(lambda: len(router.registry.routable()) == 2, msg="fleet ready")
        first: dict = {}

        def run_first():
            first["result"] = router.rolling_reload()

        t = threading.Thread(target=run_first, daemon=True)
        t.start()
        time.sleep(0.1)  # first reload is mid-flight (0.3 s per replica)
        conn = http.client.HTTPConnection("127.0.0.1", router.port, timeout=10)
        conn.request("POST", "/admin/reload", b"{}",
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        body = json.loads(resp.read())
        conn.close()
        assert resp.status == 409 and "in progress" in body["error"]
        with pytest.raises(RuntimeError):
            router.rolling_reload()
        t.join(timeout=30)
        assert first["result"][0] is True
    finally:
        router.stop()
        for s in stubs:
            s.stop()


def test_ejection_dumps_flight_recorder_and_recovers(
    serve_router_mod, tmp_path
):
    stub = serve_router_mod.StubReplica().start()
    port = stub.port
    rid = f"127.0.0.1:{port}"
    router = RouterServer(
        [stub.url], probe_interval=0.02, eject_threshold=3,
        backoff_base_s=0.05, backoff_max_s=0.2, obs_dir=str(tmp_path),
    )
    router.start()
    try:
        assert router.wait_ready(5.0)
        stub.stop()
        _wait(lambda: router.registry.get(rid).state == EJECTED,
              timeout=10, msg="ejection")
        assert router.stats["ejections"] == 1
        dumps = list((tmp_path / "flightrec").glob("*replica_ejected*.json"))
        assert len(dumps) == 1
        doc = json.loads(dumps[0].read_text())
        assert doc["extra"]["replica"] == rid
        assert rid in doc["extra"]["registry"]
        status, body, _ = _get(router.port, "/healthz")
        assert status == 503
        health = json.loads(body)
        assert health["replicas"][rid]["state"] == EJECTED
        # a replacement process on the same address recovers the replica
        # on the next backed-off probe — no operator action needed
        stub2 = serve_router_mod.StubReplica(port=port).start()
        try:
            _wait(lambda: router.registry.get(rid).state == READY,
                  timeout=10, msg="recovery")
            assert router.stats["recoveries"] == 1
            status, _, _ = _get(router.port, "/healthz")
            assert status == 200
        finally:
            stub2.stop()
    finally:
        router.stop()


def test_router_metrics_json_and_prometheus(serve_router_mod):
    stub = serve_router_mod.StubReplica(itl_s=0.001).start()
    router = RouterServer([stub.url], probe_interval=0.02, chunk_tokens=4)
    router.start()
    try:
        assert router.wait_ready(5.0)
        _sse_post(router.port, {"tokens": [1, 2, 3, 4], "max_new_tokens": 2})
        status, body, _ = _get(router.port, "/metrics")
        snap = json.loads(body)
        assert status == 200
        assert snap["requests"] == 1 and snap["tokens_relayed"] == 2
        assert snap["routable_replicas"] == 1
        assert f"127.0.0.1:{stub.port}" in snap["replicas"]
        assert 0.0 <= snap["affinity_hit_rate"] <= 1.0
        status, text, headers = _get(
            router.port, "/metrics", headers={"Accept": "text/plain"}
        )
        assert status == 200
        assert "text/plain" in headers.get("Content-Type", "")
        exposition = text.decode()
        assert "router_requests_total 1" in exposition
        assert "router_tokens_relayed_total 2" in exposition
        assert "router_routable_replicas 1" in exposition
        assert 'router_replica_up{replica="127.0.0.1:' in exposition
    finally:
        router.stop()
        stub.stop()


# ------------------------------------------------- real-engine fleet (jax)


CACHE_LEN = 48


@pytest.fixture(scope="module")
def cfg():
    from zero_transformer_tpu.config import model_config

    return model_config("test", dropout=0.0, compute_dtype="float32")


@pytest.fixture(scope="module")
def params(cfg):
    import jax
    import jax.numpy as jnp

    from zero_transformer_tpu.models import Transformer

    return Transformer(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]


@pytest.fixture(scope="module")
def reference(cfg, params):
    import jax
    import jax.numpy as jnp

    from zero_transformer_tpu.inference.generate import decode_model, generate
    from zero_transformer_tpu.inference.sampling import SamplingConfig

    model = decode_model(cfg, CACHE_LEN)
    sampling = SamplingConfig(greedy=True)

    def run(prompt, max_new=8, seed=0):
        toks = generate(
            model, params, jnp.asarray([prompt], jnp.int32), max_new,
            jax.random.PRNGKey(seed), sampling,
        )
        return jax.device_get(toks)[0].tolist()

    return run


class ByteTokenizer:
    eos_token_id = None

    def encode(self, text):
        return list(text.encode("utf-8"))

    def decode(self, ids, **kw):
        return bytes(int(i) % 256 for i in ids).decode("utf-8", errors="replace")


def _make_replica(cfg, params, chaos=None, reload_source=None):
    from zero_transformer_tpu.inference.sampling import SamplingConfig
    from zero_transformer_tpu.serving import ServingEngine, ServingServer

    engine = ServingEngine(
        cfg, params, n_slots=2, cache_len=CACHE_LEN,
        sampling=SamplingConfig(greedy=True), chaos=chaos,
    )
    server = ServingServer(
        engine, ByteTokenizer(), port=0, reload_source=reload_source
    )
    server.start()
    return server


def test_replica_healthz_carries_router_admission_inputs(cfg, params):
    server = _make_replica(cfg, params)
    try:
        status, body, _ = _get(server.port, "/healthz")
        assert status == 200
        health = json.loads(body)
        # pre-existing fields intact
        for key in ("state", "uptime_s", "reloads", "breaker_open", "slots",
                    "active", "prefilling", "queued"):
            assert key in health, key
        # the router's admission inputs ride the same poll
        assert health["itl_ewma_ms"] == 0.0  # no samples yet
        assert health["queue_depth"] == 0
        assert health["active_slots"] == 0
        assert health["free_pages"] == 2  # slab layout: free slots
    finally:
        server.stop()


def test_fleet_parity_and_prefix_affinity(cfg, params, reference):
    servers = [_make_replica(cfg, params) for _ in range(2)]
    urls = [f"http://127.0.0.1:{s.port}" for s in servers]
    router = RouterServer(urls, probe_interval=0.05, chunk_tokens=4)
    router.start()
    try:
        _wait(lambda: len(router.registry.routable()) == 2,
              timeout=15, msg="fleet ready")
        groups = [
            [3, 5, 7, 9, 11, 13],
            [4, 6, 8, 10, 12, 14],
        ]
        tails = [[17, 19], [21, 23], [25, 27]]
        routed_to = {0: set(), 1: set()}
        for g, prefix in enumerate(groups):
            for tail in tails:
                prompt = prefix + tail
                resp, events, _ = _sse_post(
                    router.port,
                    {"tokens": prompt, "max_new_tokens": 8, "seed": 0},
                    timeout=120,
                )
                done = events[-1]
                assert done["status"] == "done", done
                ids = [e["token"] for e in events if "token" in e]
                # routed generation byte-identical to single-request
                # generate() — the fleet adds zero numerical surface
                assert ids == reference(prompt, 8), prompt
                aff = router.affinity.lookup(prompt)
                routed_to[g].add(aff)
        # each group stuck to ONE replica after its first request (the
        # distributed-prefix-cache property), 2 hits per group
        assert all(len(v) == 1 for v in routed_to.values()), routed_to
        assert router.stats["affinity_hits"] == 4
        assert router.stats["failovers"] == 0
        assert router.stats["dropped_streams"] == 0
    finally:
        router.stop()
        for s in servers:
            s.stop()


def test_fleet_midstream_failover_resumes_exact_greedy_trajectory(
    cfg, params, reference
):
    from zero_transformer_tpu.serving import ServeFault, ServingChaosMonkey

    # replica A's engine faults one decode tick mid-generation: its stream
    # ends with a retryable failed event after ~2 tokens; the router must
    # resume on B and the CLIENT-visible trajectory must equal the
    # uninterrupted greedy reference exactly
    chaos = ServingChaosMonkey([ServeFault("tick_fault", step=2, duration=1)])
    a = _make_replica(cfg, params, chaos=chaos)
    b = _make_replica(cfg, params)
    a_id, b_id = f"127.0.0.1:{a.port}", f"127.0.0.1:{b.port}"
    router = RouterServer(
        [f"http://{a_id}", f"http://{b_id}"],
        probe_interval=0.05, chunk_tokens=4, stream_timeout=120,
    )
    router.start()
    try:
        _wait(lambda: len(router.registry.routable()) == 2,
              timeout=15, msg="fleet ready")
        prompt = [9, 11, 13, 15, 17, 19]
        router.affinity.record(prompt, a_id)  # pin the first hop on A
        resp, events, _ = _sse_post(
            router.port,
            {"tokens": prompt, "max_new_tokens": 10, "seed": 0},
            headers={"X-Request-Id": "fleet-failover"},
            timeout=240,
        )
        done = events[-1]
        assert done["status"] == "done", done
        assert done["failovers"] == 1
        ids = [e["token"] for e in events if "token" in e]
        assert ids == reference(prompt, 10)
        assert router.stats["resumed_streams"] == 1
        relays = [
            s for s in router.tracer.by_track("fleet-failover")
            if s[2] == "relay"
        ]
        assert [s[5]["replica"] for s in relays] == [a_id, b_id]
    finally:
        router.stop()
        for s in (a, b):
            s.stop()


def test_fleet_rolling_reload_with_live_stream(cfg, params, reference):
    servers = [
        _make_replica(cfg, params, reload_source=lambda path=None: params)
        for _ in range(2)
    ]
    urls = [f"http://127.0.0.1:{s.port}" for s in servers]
    router = RouterServer(urls, probe_interval=0.05, chunk_tokens=4,
                          stream_timeout=120)
    router.start()
    out: dict = {}
    try:
        _wait(lambda: len(router.registry.routable()) == 2,
              timeout=15, msg="fleet ready")
        prompt = [2, 4, 6, 8]

        def client():
            out["resp"], out["events"], _ = _sse_post(
                router.port,
                {"tokens": prompt, "max_new_tokens": 32, "seed": 0},
                timeout=240,
            )

        t = threading.Thread(target=client, daemon=True)
        t.start()
        time.sleep(0.05)
        ok, steps = router.rolling_reload(drain_timeout_s=120.0,
                                          ready_timeout_s=120.0)
        t.join(timeout=240)
        assert not t.is_alive(), "stream hung across the rolling reload"
        assert ok, steps
        assert [s["ok"] for s in steps] == [True, True]
        done = out["events"][-1]
        assert done["status"] == "done"
        ids = [e["token"] for e in out["events"] if "token" in e]
        assert ids == reference(prompt, 32)
        assert router.stats["dropped_streams"] == 0
        for s in servers:
            _, body, _ = _get(s.port, "/healthz")
            assert json.loads(body)["reloads"] == 1
    finally:
        router.stop()
        for s in servers:
            s.stop()


# ------------------------------------------------------- chaos (subprocess)


def _spawn_worker(extra=()):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.Popen(
        [
            sys.executable, str(REPO / "scripts" / "serve_router.py"),
            "--replica-worker", "--port", "0", "--greedy",
            "--cache-len", "64", "--slots", "2", "--prefill-chunk", "0",
            *extra,
        ],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env,
        cwd=str(REPO),
    )
    return proc


def _worker_port(proc, timeout=240.0):
    deadline = time.monotonic() + timeout
    port: dict = {}

    def read():
        for line in proc.stdout:
            if line.startswith("REPLICA_PORT="):
                port["n"] = int(line.strip().split("=", 1)[1])
                break
        # keep draining so the worker never blocks on a full stdout pipe
        for _ in proc.stdout:
            pass

    t = threading.Thread(target=read, daemon=True)
    t.start()
    while time.monotonic() < deadline and "n" not in port:
        if proc.poll() is not None:
            raise AssertionError(f"worker died rc={proc.returncode}")
        time.sleep(0.1)
    assert "n" in port, "worker never reported its port"
    return port["n"]


@pytest.mark.slow
@pytest.mark.chaos
def test_chaos_sigkill_replica_midload_then_rolling_reload(
    cfg, params, reference, tmp_path
):
    """The acceptance chaos proof: a 3-replica fleet under live streaming
    load; one replica is SIGKILLed mid-stream — every in-flight stream
    either resumes on a survivor (token-exact, greedy) or ends with a
    retryable terminal event, zero hangs, zero drops; the dead replica is
    ejected with a flight-recorder dump. Before the kill, a rolling fleet
    reload completes under load with ``dropped_streams == 0``."""
    from zero_transformer_tpu.checkpoint import export_params_msgpack
    from zero_transformer_tpu.parallel.sharding import unbox

    procs = [_spawn_worker() for _ in range(3)]
    router = None
    try:
        ports = [_worker_port(p) for p in procs]
        rids = [f"127.0.0.1:{p}" for p in ports]
        router = RouterServer(
            [f"http://{r}" for r in rids], probe_interval=0.1,
            eject_threshold=3, backoff_base_s=0.2, chunk_tokens=4,
            stream_timeout=300, max_attempts=4, obs_dir=str(tmp_path),
        )
        router.start()
        _wait(lambda: len(router.registry.routable()) == 3,
              timeout=120, msg="3 replicas ready")

        # warm every replica's compile OUTSIDE the measured scenario: three
        # concurrent requests spread by least-loaded (active_relays)
        warm_threads = [
            threading.Thread(
                target=_sse_post,
                args=(router.port,
                      {"tokens": [40 + i] * 4, "max_new_tokens": 2}),
                kwargs={"timeout": 600}, daemon=True,
            )
            for i in range(3)
        ]
        for t in warm_threads:
            t.start()
        for t in warm_threads:
            t.join(timeout=600)
        assert not any(t.is_alive() for t in warm_threads), "warmup hung"

        # ---- phase 1: rolling reload under live load, zero drops
        ckpt = tmp_path / "reload.msgpack"
        export_params_msgpack(unbox(params), ckpt)
        results: list = []

        def client(prompt, max_new):
            resp, events, doc = _sse_post(
                router.port,
                {"tokens": prompt, "max_new_tokens": max_new, "seed": 0},
                timeout=600,
            )
            results.append((prompt, max_new, events[-1] if events else doc,
                            [e["token"] for e in events if "token" in e]))

        load1 = [
            threading.Thread(
                target=client, args=([2, 4, 6, 8, 10 + i], 16), daemon=True
            )
            for i in range(3)
        ]
        for t in load1:
            t.start()
        time.sleep(0.2)
        conn = http.client.HTTPConnection("127.0.0.1", router.port,
                                          timeout=600)
        conn.request(
            "POST", "/admin/reload",
            json.dumps({"params": str(ckpt), "drain_timeout": 300,
                        "ready_timeout": 300}),
            {"Content-Type": "application/json"},
        )
        resp = conn.getresponse()
        reload_doc = json.loads(resp.read())
        conn.close()
        assert resp.status == 200, reload_doc
        assert reload_doc["reloaded"] is True
        assert reload_doc["dropped_streams"] == 0
        assert [s["ok"] for s in reload_doc["replicas"]] == [True] * 3
        for t in load1:
            t.join(timeout=600)
        assert not any(t.is_alive() for t in load1), "stream hung in reload"
        assert all(r[2].get("status") == "done" for r in results), results
        assert router.stats["dropped_streams"] == 0

        # ---- phase 2: SIGKILL the replica that owns the shared prefix
        results.clear()
        shared = [9, 9, 9, 9]  # affinity concentrates these on one replica
        load2 = [
            threading.Thread(
                target=client, args=(shared + [30 + i], 24), daemon=True
            )
            for i in range(4)
        ]
        load2[0].start()
        _wait(lambda: router.affinity.lookup(shared) is not None,
              timeout=300, msg="first stream routed")
        victim_rid = router.affinity.lookup(shared)
        victim = procs[rids.index(victim_rid)]
        for t in load2[1:]:
            t.start()
        # let streams reach the victim mid-generation, then kill -9
        _wait(
            lambda: router.registry.get(victim_rid).active_relays >= 1
            and router.registry.get(victim_rid).tokens_relayed > 0,
            timeout=300, msg="victim streaming",
        )
        os.kill(victim.pid, signal.SIGKILL)
        for t in load2:
            t.join(timeout=600)
        assert not any(t.is_alive() for t in load2), "stream HUNG after kill"
        assert len(results) == 4
        for prompt, max_new, done, ids in results:
            # token prompts are always resumable: every stream must END,
            # and a completed one must be token-exact vs the uninterrupted
            # greedy reference (same params everywhere after the reload)
            assert done.get("done"), done
            if done["status"] == "done":
                assert ids == reference(prompt, max_new), (prompt, ids)
            else:
                assert done.get("retryable") is True, done
        assert any(r[2]["status"] == "done" for r in results), results
        assert router.stats["failovers"] >= 1
        assert router.stats["dropped_streams"] == 0
        _wait(lambda: router.registry.get(victim_rid).state == EJECTED,
              timeout=60, msg="victim ejected")
        dumps = list((tmp_path / "flightrec").glob("*replica_ejected*"))
        assert dumps, "ejection must dump the flight recorder"
        # the fleet keeps serving on the survivors
        resp, events, _ = _sse_post(
            router.port, {"tokens": [1, 3, 5, 7], "max_new_tokens": 8},
            timeout=600,
        )
        assert events[-1]["status"] == "done"
    finally:
        if router is not None:
            router.stop()
        for p in procs:
            if p.poll() is None:
                p.kill()
            p.wait(timeout=30)
