"""End-to-end trainer tests on an 8-device mesh: loss decreases, eval runs,
checkpoints land, and interrupted+resumed training exactly matches an
uninterrupted run — the distributed-testing tier the reference lacks
entirely (SURVEY §4: "Distributed testing: none automated")."""
import dataclasses
import json

import numpy as np
import pytest

import jax

from zero_transformer_tpu.config import (
    CheckpointConfig,
    Config,
    DataConfig,
    MeshConfig,
    ModelConfig,
    OptimizerConfig,
    TrainingConfig,
)
from zero_transformer_tpu.training.trainer import Trainer


def tiny_config(tmp_path, total_steps=20, zero_stage=1, data=None, **ckpt_kwargs) -> Config:
    return Config(
        model=ModelConfig(vocab_size=64, d_model=32, n_heads=2, n_layers=2,
                          max_seq_len=16, dropout=0.0),
        mesh=MeshConfig(zero_stage=zero_stage),
        optimizer=OptimizerConfig(peak_learning_rate=1e-2, warmup_steps=2,
                                  total_steps=total_steps),
        training=TrainingConfig(batch_size=8, train_context=16, total_steps=total_steps,
                                evaluation_frequency=10, maximum_evaluation_steps=2,
                                log_frequency=5, seed=0),
        data=data or DataConfig(source="synthetic", max_context=16),
        checkpoint=CheckpointConfig(directory=str(tmp_path / "run"),
                                    save_frequency=10, async_save=False,
                                    **ckpt_kwargs),
    )


def structured_data(tmp_path) -> DataConfig:
    """A learnable corpus: cyclic 0..63 token stream (next token is a pure
    function of the current one), so a working train loop must cut loss far
    below the uniform-random ln(64) floor."""
    from zero_transformer_tpu.data.sources import write_memmap

    tokens = np.tile(np.arange(64, dtype=np.uint16), 64)
    path = str(tmp_path / "train.bin")
    write_memmap(tokens, path)
    return DataConfig(source="memmap", train_path=path, validation_path=path,
                      max_context=16)


def params_equal(a, b, rtol=1e-6):
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), rtol=rtol, atol=1e-7)


def test_loss_decreases_and_artifacts(tmp_path, devices):
    cfg = tiny_config(tmp_path, total_steps=20, data=structured_data(tmp_path))
    trainer = Trainer(cfg)
    state = trainer.init_state()
    first_eval = trainer.evaluate(state)["loss"]
    state = trainer.train()
    assert int(state.step) == 20
    final_eval = trainer.evaluate(state)["loss"]
    assert final_eval < first_eval - 0.5, (first_eval, final_eval)

    # metrics jsonl written with expected keys
    lines = [json.loads(l) for l in
             (tmp_path / "run" / "metrics.jsonl").read_text().splitlines()]
    train_lines = [l for l in lines if "train/loss" in l]
    assert train_lines and "train/learning_rate" in train_lines[0]
    assert any("validation/loss" in l for l in lines)
    # checkpoints at save_frequency
    assert trainer.ckpt.all_steps() == [10, 20]
    trainer.close()


@pytest.mark.slow
def test_resume_matches_uninterrupted(tmp_path, devices):
    # uninterrupted 20 steps
    cfg_a = tiny_config(tmp_path / "a", total_steps=20)
    trainer_a = Trainer(cfg_a)
    state_a = trainer_a.train()
    trainer_a.close()

    # interrupted at 10, resumed to 20
    cfg_b = tiny_config(tmp_path / "b", total_steps=20)
    trainer_b = Trainer(cfg_b)
    trainer_b.train(max_steps=10)
    trainer_b.close()

    cfg_b2 = tiny_config(tmp_path / "b", total_steps=20, resume=True)
    trainer_b2 = Trainer(cfg_b2)
    state_b = trainer_b2.train()
    trainer_b2.close()

    assert int(state_b.step) == 20
    params_equal(state_a.params, state_b.params, rtol=1e-5)


@pytest.mark.parametrize("scan_layers", [True, False])
def test_warm_init_msgpack_upcycles_dense_to_moe(tmp_path, devices, scan_layers):
    """Dense donor msgpack into an MoE model config: sparse upcycling runs
    in the warm-init path, for both layer layouts (the stacked requirement
    is handled internally — review finding: scan_layers=False previously
    unstacked first and skipped the upcycle)."""
    from flax.serialization import msgpack_serialize

    donor_cfg = tiny_config(tmp_path)
    donor = Trainer(donor_cfg)
    donor_params = jax.tree.map(np.asarray, donor.init_state().params)
    src = tmp_path / "donor.msgpack"
    src.write_bytes(msgpack_serialize(donor_params))
    donor.close()

    moe_cfg = tiny_config(
        tmp_path / "moe", warm_init=True, warm_init_msgpack=str(src)
    )
    moe_cfg = dataclasses.replace(
        moe_cfg,
        model=dataclasses.replace(
            moe_cfg.model, n_experts=4, moe_top_k=2, scan_layers=scan_layers
        ),
    )
    trainer = Trainer(moe_cfg)
    state = trainer.init_state()
    got = jax.tree.map(np.asarray, state.params)
    blocks = got["blocks"] if scan_layers else got["block_0"]
    assert "moe" in blocks and "mlp" not in blocks
    # every expert is a copy of the donor MLP
    wi = blocks["moe"]["wi"]
    donor_wi = donor_params["blocks"]["mlp"]["wi"]["kernel"]
    if scan_layers:
        np.testing.assert_allclose(wi[:, 0], donor_wi, atol=1e-7)
        np.testing.assert_allclose(wi[:, 3], donor_wi, atol=1e-7)
    else:
        np.testing.assert_allclose(wi[0], donor_wi[0], atol=1e-7)
    trainer.close()


def test_halt_on_nan_saves_and_raises(tmp_path, devices):
    """A non-finite loss must checkpoint-and-stop, not burn further steps
    (checked at log sync points — no extra device syncs)."""
    import jax.numpy as jnp

    cfg = tiny_config(tmp_path, total_steps=20)
    trainer = Trainer(cfg)
    trainer.init_state()
    real_step = trainer.train_step

    def poisoned(state, batch, rng):
        state, metrics = real_step(state, batch, rng)
        metrics = dict(metrics)
        metrics["loss"] = jnp.float32(jnp.nan)
        return state, metrics

    trainer.train_step = poisoned
    with pytest.raises(RuntimeError, match="non-finite loss"):
        trainer.train()
    # the poisoned state must NOT bury the last good checkpoint: nothing is
    # saved at the NaN step (here: no checkpoint at all yet)
    assert trainer.ckpt.latest_step() is None
    trainer.close()


def test_evaluate_window_pinned(tmp_path, devices):
    # two consecutive evaluates on an unchanged model must score the SAME
    # data window (round-2 verdict: each eval consumed the next N batches of
    # a continuing stream, so validation curves weren't comparable)
    cfg = tiny_config(tmp_path, total_steps=20, data=structured_data(tmp_path))
    trainer = Trainer(cfg)
    state = trainer.init_state()
    first = trainer.evaluate(state)["loss"]
    second = trainer.evaluate(state)["loss"]
    assert first == second
    trainer.close()


@pytest.mark.parametrize("zero_stage", [2, 3])
def test_trains_at_higher_zero_stages(tmp_path, devices, zero_stage):
    cfg = tiny_config(tmp_path, total_steps=6, zero_stage=zero_stage)
    trainer = Trainer(cfg)
    state = trainer.train()
    assert int(state.step) == 6
    loss = trainer.evaluate(state)["loss"]
    assert np.isfinite(loss)
    trainer.close()


def test_warm_init_copies_params(tmp_path, devices):
    donor_cfg = tiny_config(tmp_path / "donor", total_steps=5)
    donor = Trainer(donor_cfg)
    donor_state = donor.train()
    donor.close()

    warm_cfg = tiny_config(tmp_path / "warm", total_steps=5,
                           warm_init=True,
                           warm_init_dir=str(tmp_path / "donor" / "run"))
    warm = Trainer(warm_cfg)
    state = warm.init_state()
    params_equal(donor_state.params, state.params)
    assert int(state.step) == 0  # fresh optimizer/step, donor params
    warm.close()


def test_warm_init_msgpack_with_depth_extension(tmp_path, devices):
    """Warm start from an exported msgpack of a SHALLOWER donor: depth is
    auto-extended (Gopher G.3.3, reference extend_params.py) and layouts
    converted — the reference's 580M->760M scale-up flow, in one config knob."""
    from flax.serialization import msgpack_serialize

    from zero_transformer_tpu.utils import surgery

    donor_cfg = tiny_config(tmp_path)
    donor = Trainer(donor_cfg)
    donor_state = donor.init_state()
    donor_params = jax.tree.map(np.asarray, donor_state.params)
    src = tmp_path / "donor.msgpack"
    src.write_bytes(msgpack_serialize(donor_params))
    donor.close()

    big = tiny_config(
        tmp_path / "big", warm_init=True, warm_init_msgpack=str(src)
    )
    big = dataclasses.replace(
        big, model=dataclasses.replace(big.model, n_layers=4, scan_layers=False)
    )
    trainer = Trainer(big)
    state = trainer.init_state()
    got = jax.tree.map(np.asarray, state.params)
    assert surgery.num_layers(got) == 4 and not surgery.is_stacked(got)
    # block 1 of the donor stack lands in blocks 2 and 3
    donor_blocks = surgery.unstack_blocks(donor_params)
    params_equal(got["block_2"], donor_blocks["block_1"])
    params_equal(got["block_3"], donor_blocks["block_1"])
    params_equal(got["wte"], donor_params["wte"])
    trainer.close()


def test_sigterm_preemption_checkpoints_and_stops(tmp_path, devices):
    """SIGTERM mid-run: the trainer finishes the current step, force-saves a
    checkpoint, and exits the loop early — the preemption handling the
    reference lacked (its only recovery was rerun --resume from the last
    periodic save). Resuming afterwards continues from the preempted step."""
    import os
    import signal
    import threading

    cfg = tiny_config(tmp_path, total_steps=5000, data=structured_data(tmp_path))
    trainer = Trainer(cfg)

    # fire SIGTERM only once the loop is demonstrably RUNNING (first metrics
    # line written) — a fixed timer races with compile time on a loaded
    # machine and can land before the handler is installed, killing pytest
    stop_poll = threading.Event()

    def fire_when_running():
        metrics = tmp_path / "run" / "metrics.jsonl"
        for _ in range(600):  # up to 60s for the first logged step
            if metrics.exists() and metrics.stat().st_size > 0:
                os.kill(os.getpid(), signal.SIGTERM)
                return
            if stop_poll.wait(0.1):
                return
        # even on a pathologically slow machine, fire rather than silently
        # letting the 5000-step run continue to a misleading failure (the
        # handler is installed before step 1, long before any logging)
        os.kill(os.getpid(), signal.SIGTERM)

    poller = threading.Thread(target=fire_when_running, daemon=True)
    poller.start()
    try:
        state = trainer.train()
    finally:
        stop_poll.set()
    stopped_at = int(state.step)
    assert 0 < stopped_at < 5000, "SIGTERM did not stop the loop early"
    assert stopped_at in trainer.ckpt.all_steps(), (
        stopped_at, trainer.ckpt.all_steps()
    )
    trainer.close()
    # the handler must have been restored (a second train() run would
    # otherwise inherit a stale flag); resume picks up at the saved step
    cfg2 = dataclasses.replace(cfg, checkpoint=dataclasses.replace(
        cfg.checkpoint, resume=True))
    t2 = Trainer(cfg2)
    s2 = t2.init_state()
    assert int(s2.step) == stopped_at
    t2.close()


def test_memory_analysis_reports_compiled_sizes(tmp_path, devices):
    """--memory-analysis surface: AOT-compiles the real train step from the
    config with NO state materialized and reports the compiled byte
    accounting (the pre-flight for sizing a config to a 16 GB chip)."""
    from zero_transformer_tpu.training.trainer import memory_analysis

    cfg = tiny_config(tmp_path)
    report = memory_analysis(cfg)
    assert report["state_bytes_global"] > 0
    assert report["tokens_per_step"] == 8 * 16
    if report["exact"]:
        # compiled numbers are PER DEVICE; with ZeRO-1 on the 8-device mesh
        # each device holds full params + 1/8 of the sharded opt state, so
        # the donated alias must cover at least the params and strictly
        # less than the whole global tree
        assert 0 < report["alias_bytes"] < report["state_bytes_global"]
        assert report["peak_estimate_bytes"] > 0
    else:  # backend without memory_analysis support — honest fallback
        assert "unavailable_reason" in report
