"""Elastic ZeRO resume: restore a checkpoint onto a DIFFERENT mesh.

The paper's runs live on preemptible pods — the topology that comes back
after a preemption is whatever the scheduler has, not necessarily what the
checkpoint was saved under. These tests pin the trustworthy-restore
contract across topology changes:

- an 8-device checkpoint resumes on a 4-device mesh (and 4 -> 8), with the
  ZeRO partition spec rebuilt for the new world and orbax resharding the
  arrays natively (GSPMD makes the partitioned program a pure function of
  mesh + program — arXiv:2105.04663 — so the TRAJECTORY is preserved up to
  reduction-order ulps);
- the loader position is stored in GLOBAL batches, so the global-token
  trajectory continues exactly; geometry changes remap by token count,
  rounding DOWN to a batch boundary (replay, never skip);
- genuinely incompatible topologies refuse with a precise error BEFORE
  compilation, not deep inside pjit.

The real multi-process version (save under 4 hosts / 8 devices, resume
under 2 hosts / 4 devices) lives in test_multihost.py (slow lane).
"""
import dataclasses

import numpy as np
import pytest

import jax

from zero_transformer_tpu.config import (
    CheckpointConfig,
    Config,
    DataConfig,
    MeshConfig,
    ModelConfig,
    OptimizerConfig,
    ResilienceConfig,
    TrainingConfig,
)
from zero_transformer_tpu.parallel import sharding as shd
from zero_transformer_tpu.parallel.mesh import make_mesh
from zero_transformer_tpu.training.trainer import Trainer, remap_loader_state
from zero_transformer_tpu.utils.jax_compat import HAS_AMBIENT_MESH


def tiny_config(directory, total_steps=8, zero_stage=1, batch_size=8):
    return Config(
        model=ModelConfig(vocab_size=64, d_model=32, n_heads=2, n_layers=2,
                          max_seq_len=16, dropout=0.0),
        mesh=MeshConfig(zero_stage=zero_stage),
        optimizer=OptimizerConfig(peak_learning_rate=1e-2, warmup_steps=2,
                                  total_steps=total_steps),
        training=TrainingConfig(batch_size=batch_size, train_context=16,
                                total_steps=total_steps,
                                evaluation_frequency=0,
                                log_frequency=2, seed=0),
        data=DataConfig(source="synthetic", max_context=16),
        checkpoint=CheckpointConfig(directory=str(directory),
                                    save_frequency=4, async_save=False),
        resilience=ResilienceConfig(),
    )


def params_close(a, b, atol):
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), rtol=0,
                                   atol=atol)


def _elastic_roundtrip(tmp_path, devices, n_save, n_resume, zero_stage,
                       truth_params, tag, atol=5e-4):
    """Save at step 4 under n_save devices, resume to step 8 under n_resume;
    compare against an uninterrupted ``truth_params`` run."""
    ckpt_dir = tmp_path / f"run_{tag}"
    mesh_save = make_mesh(MeshConfig(zero_stage=zero_stage),
                          devices=devices[:n_save])
    mesh_resume = make_mesh(MeshConfig(zero_stage=zero_stage),
                            devices=devices[:n_resume])

    # ONE schedule (total_steps=8) across every phase: the first trainer
    # just stops early, so the LR trajectory is comparable run-to-run
    cfg_save = tiny_config(ckpt_dir, total_steps=8, zero_stage=zero_stage)
    t = Trainer(cfg_save, mesh=mesh_save)
    t.train(max_steps=4)
    t.close()

    cfg8 = dataclasses.replace(
        cfg_save,
        checkpoint=dataclasses.replace(cfg_save.checkpoint, resume=True),
    )
    t_el = Trainer(cfg8, mesh=mesh_resume)
    elastic = t_el.train()
    report = t_el._restore_report
    t_el.close()
    assert int(elastic.step) == 8
    assert report is not None and report.quarantined == []

    # the restored VALUES are bitwise those of the save-topology run (see
    # test_elastic_restore_values_bitwise); steps run on a different device
    # count use a different collective schedule, so per-step reduction-order
    # ulps — amplified by adam's per-param normalization — compound to
    # ~1e-4 ABSOLUTE drift. Relative tolerance is meaningless on near-zero
    # weights; the trajectory-preservation contract is pinned absolutely.
    params_close(truth_params, elastic.params, atol=atol)
    return elastic


@pytest.mark.chaos  # runs in `make elastic-chaos` + the nightly full lane;
@pytest.mark.slow   # three full trainer runs — out of the tier-1 budget
def test_elastic_resume_8_to_4_and_back(tmp_path, devices):
    """The acceptance roundtrips, sharing one uninterrupted 8-device ground
    truth: save on 8 devices -> resume on 4; save on 4 -> resume on 8.
    (Tier-1 still pins the elastic restore itself —
    test_elastic_restore_values_bitwise — and the compat/remap contracts.)"""
    cfg_clean = tiny_config(tmp_path / "clean", total_steps=8)
    t_cl = Trainer(cfg_clean, mesh=make_mesh(MeshConfig(), devices=devices))
    clean = t_cl.train()
    t_cl.close()
    _elastic_roundtrip(tmp_path, devices, n_save=8, n_resume=4, zero_stage=1,
                       truth_params=clean.params, tag="8to4")
    # the 4->8 leg diverges from the 8-device truth on BOTH sides of the
    # save (steps 1-4 ran on 4 devices too), so its drift bound doubles
    _elastic_roundtrip(tmp_path, devices, n_save=4, n_resume=8, zero_stage=1,
                       truth_params=clean.params, tag="4to8", atol=3e-3)


@pytest.mark.slow
def test_elastic_resume_zero2_8_to_4(tmp_path, devices):
    """The explicit ZeRO-2 shard_map core rebuilds its collective schedule
    for the new world size; the optimizer state reshards 8-way -> 4-way.
    Slow lane: compiles the explicit core for two mesh sizes."""
    cfg_clean = tiny_config(tmp_path / "clean", total_steps=8, zero_stage=2)
    t_cl = Trainer(cfg_clean, mesh=make_mesh(MeshConfig(zero_stage=2),
                                             devices=devices))
    clean = t_cl.train()
    t_cl.close()
    _elastic_roundtrip(tmp_path, devices, n_save=8, n_resume=4, zero_stage=2,
                       truth_params=clean.params, tag="z2")


def test_elastic_restore_values_bitwise(tmp_path, devices):
    """The RESTORE itself is bitwise across topologies (only subsequent
    compute differs): an 8-device save restored onto 4 devices yields
    byte-identical leaves."""
    from zero_transformer_tpu import checkpoint as ckpt_lib

    cfg = tiny_config(tmp_path / "run", total_steps=4)
    mesh8 = make_mesh(MeshConfig(), devices=devices)
    t = Trainer(cfg, mesh=mesh8)
    final = t.train()
    t.close()

    mesh4 = make_mesh(MeshConfig(), devices=devices[:4])
    cfg_r = dataclasses.replace(
        cfg, checkpoint=dataclasses.replace(cfg.checkpoint, resume=True)
    )
    t4 = Trainer(cfg_r, mesh=mesh4)
    restored = t4.init_state()
    for a, b in zip(jax.tree.leaves(final.params),
                    jax.tree.leaves(restored.params)):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    # ... and the digests the manifest verified are topology-invariant
    d8 = ckpt_lib.tree_digests(final.params)
    d4 = ckpt_lib.tree_digests(restored.params)
    assert d8 == d4
    t4.close()


# -- topology compatibility validation ---------------------------------------


def test_incompatible_batch_refused_before_compile(tmp_path, devices):
    """batch_size not divisible by the new DP world must fail with the
    precise elastic error, not a sharding error deep in pjit."""
    mesh3 = make_mesh(MeshConfig(), devices=devices[:3])  # DP world of 3
    with pytest.raises(ValueError, match="not\\s+divisible by the new data-parallel"):
        shd.check_elastic_compat(
            shd.topology_summary(make_mesh(MeshConfig(), devices=devices), 1),
            mesh3, 1, global_batch=8,
        )


def test_compat_notes_describe_topology_change(devices):
    mesh8 = make_mesh(MeshConfig(), devices=devices)
    mesh4 = make_mesh(MeshConfig(), devices=devices[:4])
    saved = shd.topology_summary(mesh8, 1)
    notes = shd.check_elastic_compat(saved, mesh4, 2, global_batch=8)
    joined = "\n".join(notes)
    assert "8 -> 4" in joined and "zero_stage 1 -> 2" in joined
    # same topology: silent
    assert shd.check_elastic_compat(saved, mesh8, 1, global_batch=8) == []
    # legacy checkpoint without topology metadata: no notes, no crash
    assert shd.check_elastic_compat(None, mesh4, 1, global_batch=8) == []


# -- loader position remap (batch-boundary semantics) ------------------------


def test_loader_remap_same_geometry_is_identity():
    meta = {"loader": {"steps_consumed": 7},
            "schedule": {"batch_size": 8, "train_context": 16}}
    assert remap_loader_state(meta, 8, 16) == {"steps_consumed": 7}


def test_loader_remap_by_token_count():
    # 7 batches of 8x16 = 896 tokens -> 3 whole batches of 16x16 (768
    # tokens), 128 tokens REPLAYED (round down to the batch boundary)
    meta = {"loader": {"steps_consumed": 7},
            "schedule": {"batch_size": 8, "train_context": 16}}
    assert remap_loader_state(meta, 16, 16) == {"steps_consumed": 3}
    # exact multiple: nothing replayed
    meta["loader"]["steps_consumed"] = 8
    assert remap_loader_state(meta, 16, 16) == {"steps_consumed": 4}


def test_loader_remap_accounts_for_grad_accum():
    # the canonical elastic move: half the devices, double the accumulation
    # — sequences per optimizer step unchanged, so the position is too
    meta = {"loader": {"steps_consumed": 6},
            "schedule": {"batch_size": 8, "train_context": 16,
                         "accum_steps": 1}}
    assert remap_loader_state(meta, 4, 16, 2) == {"steps_consumed": 6}
    # doubling accum at the SAME batch size doubles tokens per step:
    # 6 steps x 128 tok -> 3 steps x 256 tok, nothing replayed
    assert remap_loader_state(meta, 8, 16, 2) == {"steps_consumed": 3}


def test_loader_remap_legacy_meta_passthrough():
    # checkpoints from before the schedule block: geometry assumed unchanged
    meta = {"loader": {"steps_consumed": 5}}
    assert remap_loader_state(meta, 8, 16) == {"steps_consumed": 5}
    assert remap_loader_state({}, 8, 16) is None


# -- pp_schedule changes (PR 8: interleaved stores blocks pipe-replicated) ----


def test_compat_notes_describe_pp_schedule_change(devices):
    """A schedule change is elastic but must be visible in the resume log —
    especially gpipe <-> interleaved, which RELAYOUTS the stored block
    stack (pipe-sharded <-> pipe-replicated)."""
    mesh = make_mesh(MeshConfig(), devices=devices)
    saved = shd.topology_summary(mesh, 1, pp_schedule="gpipe")
    assert saved["pp_schedule"] == "gpipe"
    notes = shd.check_elastic_compat(
        saved, mesh, 1, global_batch=8, pp_schedule="interleaved"
    )
    joined = "\n".join(notes)
    assert "pp_schedule gpipe -> interleaved" in joined
    assert "reshards natively" in joined
    # gpipe -> 1f1b: same stored layout, still logged
    notes2 = shd.check_elastic_compat(
        saved, mesh, 1, global_batch=8, pp_schedule="1f1b"
    )
    assert "same stored layout" in "\n".join(notes2)
    # pre-PR-8 checkpoints have no pp_schedule key: treated as gpipe
    legacy = {k: v for k, v in saved.items() if k != "pp_schedule"}
    assert shd.check_elastic_compat(
        legacy, mesh, 1, global_batch=8, pp_schedule="gpipe"
    ) == []


def test_pp_schedule_relayout_restore_bitwise(tmp_path, devices):
    """Save under the gpipe plan (blocks pipe-SHARDED), restore into the
    interleaved plan (blocks pipe-REPLICATED) and back: orbax reshards
    natively and every leaf is byte-identical — the state relayout half of
    an elastic pp_schedule change, without executing the pipe engine (this
    image's jax cannot trace it; the trajectory half runs on modern jax in
    test_pipeline.py)."""
    from zero_transformer_tpu import checkpoint as ckpt_lib
    from zero_transformer_tpu.config import ModelConfig
    from zero_transformer_tpu.models import Transformer
    from zero_transformer_tpu.parallel.zero import init_train_state, make_plan
    from zero_transformer_tpu.training.optimizer import make_optimizer

    cfg = ModelConfig(vocab_size=64, d_model=32, n_heads=2, n_layers=4,
                      max_seq_len=16, dropout=0.0)
    opt = OptimizerConfig(peak_learning_rate=1e-2, warmup_steps=2,
                          total_steps=8)
    mesh = make_mesh(MeshConfig(pipe=2, data=4), devices=devices)
    model = Transformer(cfg)
    tx = make_optimizer(opt)
    plan_gp = make_plan(model, tx, mesh, (2, 16), 1, pp_schedule="gpipe")
    plan_il = make_plan(model, tx, mesh, (2, 16), 1,
                        pp_schedule="interleaved")
    state = init_train_state(model, tx, jax.random.PRNGKey(0), mesh, (2, 16),
                             plan_gp)

    mgr = ckpt_lib.CheckpointManager(tmp_path / "ckpt", async_save=False)
    meta = {"topology": shd.topology_summary(mesh, 1, pp_schedule="gpipe")}
    assert mgr.save(4, state, meta=meta, force=True)

    abstract = ckpt_lib.abstract_state(model, tx, plan_il, (2, 16))
    restored, meta_r = mgr.restore(abstract)
    assert meta_r["topology"]["pp_schedule"] == "gpipe"
    notes = shd.check_elastic_compat(
        meta_r["topology"], mesh, 1, global_batch=8,
        pp_schedule="interleaved",
    )
    assert any("pp_schedule" in n for n in notes)

    # restored layout IS the interleaved plan's (blocks pipe-replicated)...
    blk = jax.tree.leaves(restored.params["blocks"])[0]
    assert "pipe" not in str(blk.sharding.spec)
    saved_blk = jax.tree.leaves(state.params["blocks"])[0]
    assert "pipe" in str(saved_blk.sharding.spec)
    # ...and every leaf is byte-identical through the relayout
    for a, b in zip(jax.tree.leaves(state.params),
                    jax.tree.leaves(restored.params)):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    mgr.close()


@pytest.mark.slow
@pytest.mark.skipif(
    not HAS_AMBIENT_MESH,
    reason="old-jax shard_map cannot trace the pipeline engine",
)
def test_elastic_resume_across_pp_schedule_change(tmp_path, devices):
    """Full trainer roundtrip: train 4 steps under gpipe, resume under
    interleaved — the loader position is in global batches so the token
    trajectory continues exactly, and the run completes to the target step.
    (Gated: the pipe engine doesn't trace on this image's jax; the state
    relayout half is pinned bitwise above, ungated.)"""
    ckpt_dir = tmp_path / "sched_change"
    mesh = make_mesh(MeshConfig(pipe=2, data=4), devices=devices)

    cfg = tiny_config(ckpt_dir, total_steps=8)
    cfg = dataclasses.replace(
        cfg,
        model=dataclasses.replace(cfg.model, n_layers=4),
        mesh=MeshConfig(pipe=2, data=4),
        training=dataclasses.replace(
            cfg.training, gradient_accumulation_steps=2
        ),
    )
    t = Trainer(cfg, mesh=mesh)
    t.train(max_steps=4)
    saved_loader = t.train_loader.state()
    t.close()

    cfg_r = dataclasses.replace(
        cfg,
        mesh=MeshConfig(pipe=2, data=4, pp_schedule="interleaved",
                        pp_interleave=2),
        checkpoint=dataclasses.replace(cfg.checkpoint, resume=True),
    )
    t_r = Trainer(cfg_r, mesh=mesh)
    final = t_r.train()
    resumed_from = t_r._restore_report
    t_r.close()
    assert int(final.step) == 8
    assert resumed_from is not None and resumed_from.quarantined == []
    # same geometry -> the loader position carried over verbatim (the token
    # trajectory continued exactly where the gpipe run stopped)
    assert saved_loader["steps_consumed"] > 0
