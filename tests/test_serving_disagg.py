"""Disaggregated prefill/decode fleet + live KV migration (ISSUE 12).

Three load-bearing claims:

- **Page spans are bit-exact transferable objects.** Exporting a slot's
  leading pages and importing them elsewhere reproduces every K/V byte
  (int8 scale leaves included) exactly, conserves page refcounts, and the
  imported pages are ordinary CoW-protected pool pages — a post-import
  write to a shared page copies first.
- **Migration replays ZERO tokens.** A stream moved between engines —
  mid-decode, mid-prefill, or as a prefill-role handoff — continues
  byte-identical to the uninterrupted ``generate()`` run, with the
  destination doing no prefill work for the consumed prefix
  (``prefill_chunks == 0`` on a decode import) and the router's
  ``resume_replayed_tokens`` counter pinned at 0 (the recompute fallback
  is what pays O(tokens)).
- **The fleet composes.** A router over one prefill-role + one decode-role
  replica splits requests by phase (DistServe-style) and the client stream
  is byte-identical to a single replica's; ``/admin/migrate`` moves a live
  routed stream with the client none the wiser; the autoscaler acts on the
  scraped load signals through the cordon/drain machinery and aborts a
  scale-down rather than drop a stream.

Chaos scenarios (``make disagg-chaos``): SIGKILL a prefill replica under a
long-prompt flood, and kill a migration mid-transfer — both degrade to the
recompute fallback with ``dropped_streams == 0``.
"""
import http.client
import json
import os
import signal
import subprocess
import sys
import threading
import time
from pathlib import Path

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from zero_transformer_tpu.config import model_config
from zero_transformer_tpu.inference.generate import decode_model, generate
from zero_transformer_tpu.inference.sampling import SamplingConfig
from zero_transformer_tpu.models import Transformer
from zero_transformer_tpu.serving import (
    PagedKVCache,
    Replica,
    RouterServer,
    ServingEngine,
    ServingServer,
    page_span_from_wire,
    page_span_to_wire,
    pick_decode_replica,
)
from zero_transformer_tpu.serving.resilience import READY

REPO = Path(__file__).resolve().parent.parent
CACHE_LEN = 48
SAMPLING = SamplingConfig(temperature=0.9, top_k=20)
GREEDY = SamplingConfig(greedy=True)


@pytest.fixture(scope="module")
def cfg():
    return model_config("test", dropout=0.0, compute_dtype="float32")


@pytest.fixture(scope="module")
def params(cfg):
    return Transformer(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]


@pytest.fixture(scope="module")
def reference(cfg, params):
    model = decode_model(cfg, CACHE_LEN)

    def run(prompt, seed, max_new=8, sampling=SAMPLING):
        toks = generate(
            model, params, jnp.asarray([prompt], jnp.int32), max_new,
            jax.random.PRNGKey(seed), sampling,
        )
        return jax.device_get(toks)[0].tolist()

    return run


def make_engine(cfg, params, **kw):
    kw.setdefault("n_slots", 2)
    kw.setdefault("cache_len", CACHE_LEN)
    kw.setdefault("sampling", SAMPLING)
    kw.setdefault("prefill_chunk", 8)
    kw.setdefault("kv_layout", "paged")
    kw.setdefault("page_size", 4)
    return ServingEngine(cfg, params, **kw)


def direct_shipper(dest_engine, captured):
    """An in-process page shipper: 'ship' by importing straight into the
    destination engine — the engine-level migration proofs need no HTTP."""

    def ship(payload, target, on_done):
        handle = dest_engine.import_stream(payload)
        captured.append(handle)
        if handle.status in ("queued", "running"):
            on_done(None)
        else:
            on_done(handle.error or handle.status)

    return ship


def _prompt(length, offset=0):
    return [(3 + offset + i) % 250 + 1 for i in range(length)]


# ------------------------------------------------- page spans: bitwise moves


def _synthetic_payload(kv, n_blocks, rng):
    """A random page-span payload matching ``kv``'s pool leaf geometry —
    roundtrip fidelity without paying a model forward."""
    leaves = {}
    for path, leaf in jax.tree_util.tree_leaves_with_path(kv.cache):
        name = str(path[-1].key if hasattr(path[-1], "key") else path[-1])
        if name not in ("cached_key", "cached_value", "key_scale",
                        "value_scale"):
            continue
        key = jax.tree_util.keystr(path)
        ax = leaf.ndim - 4
        per_page = tuple(d for i, d in enumerate(leaf.shape) if i != ax)
        dt = np.dtype(leaf.dtype)
        if dt.kind == "f":
            arr = rng.standard_normal((n_blocks,) + per_page).astype(dt)
        elif dt.kind == "V":
            # extension dtype (bf16/fp8): FINITE random values — real K/V
            # is finite by invariant (the non-finite guard retires poisoned
            # rows), and XLA canonicalizes NaN payload bits in data
            # movement, so random-bit NaNs would fail bitwise compares that
            # no real transfer ever faces
            arr = rng.standard_normal((n_blocks,) + per_page).astype(dt)
        else:
            info = np.iinfo(dt)
            arr = rng.integers(
                info.min, info.max, size=(n_blocks,) + per_page, dtype=dt
            )
        leaves[key] = arr
    return {"page_size": kv.page_size, "n_blocks": n_blocks,
            "n_tokens": n_blocks * kv.page_size, "leaves": leaves}


@pytest.mark.parametrize("page_size,int8", [(8, False), (8, True), (64, False)])
def test_page_span_roundtrip_bitwise(page_size, int8):
    """Import -> export reproduces every byte exactly, across page sizes
    {8, 64}, float and int8-scale pools, with refcounts conserved and the
    free list fully restored on release."""
    kw = {"dropout": 0.0, "compute_dtype": "float32"}
    if int8:
        kw["kv_cache_dtype"] = "int8"
    pcfg = model_config("test", **kw)
    cache_len = max(2 * page_size, 16)
    n_pages = (cache_len * 2) // page_size + 1
    model = decode_model(pcfg, cache_len, kv_pages=(n_pages, page_size))
    kv = PagedKVCache(model, n_slots=2)
    rng = np.random.default_rng(0)
    payload = _synthetic_payload(kv, n_blocks=2, rng=rng)
    if int8:
        assert any("scale" in k for k in payload["leaves"]), (
            "int8 pools must carry scale leaves"
        )

    free0 = kv.pool.free_count
    slot = kv.acquire()
    assert kv.import_page_span(slot, payload)
    out = kv.export_page_span(slot, payload["n_tokens"])
    assert out["n_blocks"] == payload["n_blocks"]
    for key, arr in payload["leaves"].items():
        got = out["leaves"][key]
        assert got.dtype == arr.dtype and got.shape == arr.shape
        assert np.array_equal(
            got.view(np.uint8), arr.view(np.uint8)
        ), f"leaf {key} not bit-exact"
    # wire codec: bytes -> payload -> bytes, extras preserved
    blob = page_span_to_wire({**out, "kind": "decode", "veto": -1})
    back = page_span_from_wire(blob)
    assert back["kind"] == "decode" and back["veto"] == -1
    for key, arr in out["leaves"].items():
        assert np.array_equal(
            back["leaves"][key].view(np.uint8), arr.view(np.uint8)
        )
    # refcount conservation: the import held exactly one ref per page
    assert kv.pool.free_count == free0 - payload["n_blocks"]
    kv.release([slot])
    assert kv.pool.free_count == free0
    assert all(r == 0 for r in kv.pool.refs[1:])


def test_page_span_ragged_tables_and_trash_padding(cfg):
    """Slots with different span lengths (ragged block tables) move
    independently; the power-of-two gather padding routes through the
    trash page and is sliced off — never exported."""
    model = decode_model(cfg, 32, kv_pages=(17, 4))
    kv = PagedKVCache(model, n_slots=3)
    rng = np.random.default_rng(1)
    payloads = {}
    for slot, blocks in ((0, 1), (1, 3), (2, 5)):
        payloads[slot] = _synthetic_payload(kv, n_blocks=blocks, rng=rng)
        assert kv.import_page_span(slot, payloads[slot])
    for slot, payload in payloads.items():
        out = kv.export_page_span(slot, payload["n_tokens"])
        assert out["n_blocks"] == payload["n_blocks"]
        for key, arr in payload["leaves"].items():
            assert np.array_equal(
                out["leaves"][key].view(np.uint8), arr.view(np.uint8)
            ), (slot, key)
    # exporting more than the slot maps is a loud error, not garbage
    with pytest.raises(ValueError, match="maps"):
        kv.export_page_span(0, 3 * kv.page_size)


def test_imported_pages_are_cow_protected(cfg):
    """The CoW guard fires on a post-import write to a SHARED imported
    page: the writer gets a private copy, the original bytes survive for
    the other holder, and ``cow_copies`` counts it."""
    model = decode_model(cfg, 32, kv_pages=(17, 4))
    kv = PagedKVCache(model, n_slots=2)
    rng = np.random.default_rng(2)
    payload = _synthetic_payload(kv, n_blocks=2, rng=rng)
    slot = kv.acquire()
    assert kv.import_page_span(slot, payload)
    # share the imported pages (what banking them in a prefix index does)
    pages = kv.bank(slot, 2)
    assert all(kv.pool.refs[p] == 2 for p in pages)
    assert kv.cow_copies == 0
    assert kv.cow(slot, 0)  # about to write block 0: must copy first
    assert kv.cow_copies == 1
    assert int(kv.table[slot, 0]) != pages[0], "writer must hold a copy"
    # the copy carries the same bytes, and the original is untouched
    out = kv.export_page_span(slot, payload["n_tokens"])
    for key, arr in payload["leaves"].items():
        assert np.array_equal(
            out["leaves"][key].view(np.uint8), arr.view(np.uint8)
        )
    assert kv.pool.refs[pages[0]] == 1  # only the bank's hold remains


def test_wire_codec_preserves_bfloat16_pools():
    """Extension dtypes (kind 'V') stringify to opaque void — the wire
    format must ship them by NAME or a bf16 serving fleet (the CLI
    default) rejects every import with a dtype mismatch. Found by the
    end-to-end CLI drive; pinned here."""
    import ml_dtypes

    pcfg = model_config("test", dropout=0.0)  # compute_dtype bf16 default
    model = decode_model(pcfg, 32, kv_pages=(17, 4))
    kv = PagedKVCache(model, n_slots=2)
    leaf_dtypes = {
        str(leaf.dtype)
        for _, leaf in jax.tree_util.tree_leaves_with_path(kv.cache)
    }
    assert "bfloat16" in leaf_dtypes, "the default pool must be bf16"
    arr = np.frombuffer(
        np.random.default_rng(3).integers(
            0, 2**16, size=32, dtype=np.uint16
        ).tobytes(),
        dtype=ml_dtypes.bfloat16,
    ).reshape(2, 16)
    blob = page_span_to_wire({"page_size": 4, "n_blocks": 2, "n_tokens": 8,
                              "leaves": {"x": arr}})
    back = page_span_from_wire(blob)
    assert back["leaves"]["x"].dtype == arr.dtype
    assert np.array_equal(
        back["leaves"]["x"].view(np.uint16), arr.view(np.uint16)
    )
    # and a real bf16 pool roundtrips through import/export
    rng = np.random.default_rng(4)
    payload = _synthetic_payload(kv, n_blocks=2, rng=rng)
    slot = kv.acquire()
    wired = page_span_from_wire(page_span_to_wire(payload))
    assert kv.import_page_span(slot, wired)
    out = kv.export_page_span(slot, payload["n_tokens"])
    for key, a in payload["leaves"].items():
        assert np.array_equal(
            out["leaves"][key].view(np.uint8), a.view(np.uint8)
        ), key


def test_wire_codec_rejects_torn_blobs():
    with pytest.raises(ValueError):
        page_span_from_wire(b"not a span")
    blob = page_span_to_wire({
        "page_size": 4, "n_blocks": 1, "n_tokens": 4,
        "leaves": {"x": np.arange(8, dtype=np.int8)},
    })
    with pytest.raises(ValueError):
        page_span_from_wire(blob[:-3])  # truncated mid-buffer


# ----------------------------------------------- migration parity (engines)


def test_decode_migration_is_byte_identical_and_replays_zero(
    cfg, params, reference
):
    """A stream migrated mid-decode continues the EXACT trajectory: the
    concatenated tokens equal the uninterrupted ``generate()`` run, and
    the destination did zero prefill work (the zero-recompute counter)."""
    captured = []
    dst = make_engine(cfg, params, role="decode")
    src = make_engine(
        cfg, params, page_shipper=direct_shipper(dst, captured)
    )
    prompt = _prompt(13)
    expect = reference(prompt, seed=5, max_new=10)
    handle = src.submit(prompt, max_new_tokens=10, seed=5)
    while len(handle.tokens) < 4:
        src.step()
    assert src.request_migration(handle.rid, "peer://dst")
    src.step()
    assert handle.status == "migrated", (handle.status, handle.error)
    assert handle.migrated_to == "peer://dst"
    cont = captured[0]
    dst.run_until_idle()
    assert cont.status == "done", (cont.status, cont.error)
    assert handle.tokens + cont.tokens == expect
    # zero-recompute, counter-asserted: no prefill work on the destination,
    # and the import-replay counter stays 0 (recompute fallback is what
    # would pay O(tokens))
    assert dst.stats["prefill_chunks"] == 0
    assert dst.stats["import_replayed_tokens"] == 0
    assert dst.stats["migrations_in"] == 1
    assert src.stats["migrations_out"] == 1
    assert src.migrations_in_flight == 0
    # continuation id is preserved for cross-tier correlation
    assert cont.rid == handle.rid


def test_midprefill_migration_is_byte_identical(cfg, params, reference):
    """Migrating DURING chunked prefill ships the finished chunks' pages;
    the destination completes the remaining chunks bit-identically (the
    deterministic forward recomputes nothing that moved)."""
    captured = []
    dst = make_engine(cfg, params, role="decode")
    src = make_engine(
        cfg, params, page_shipper=direct_shipper(dst, captured)
    )
    prompt = _prompt(30, offset=4)
    expect = reference(prompt, seed=9, max_new=6)
    handle = src.submit(prompt, max_new_tokens=6, seed=9)
    src.step()  # one 8-token chunk of the 30-token prompt
    assert handle.tokens == []
    assert src.request_migration(handle.rid, "peer://dst")
    src.step()
    assert handle.status == "migrated", (handle.status, handle.error)
    cont = captured[0]
    dst.run_until_idle()
    assert cont.status == "done", (cont.status, cont.error)
    assert cont.tokens == expect
    # the destination only prefilled the REMAINING chunks
    assert 0 < dst.stats["prefill_chunks"] < -(-len(prompt) // 8)


def test_spec_engine_migration_keeps_greedy_identity(cfg, params, reference):
    """Speculative engines migrate too: the veto/rng carry moves, and the
    migrated greedy stream still equals plain ``generate()``."""
    captured = []
    dst = make_engine(cfg, params, role="decode", draft_k=2, sampling=GREEDY)
    src = make_engine(
        cfg, params, draft_k=2, sampling=GREEDY,
        page_shipper=direct_shipper(dst, captured),
    )
    prompt = _prompt(11, offset=7)
    expect = reference(prompt, seed=1, max_new=10, sampling=GREEDY)
    handle = src.submit(prompt, max_new_tokens=10, seed=1)
    while len(handle.tokens) < 3:
        src.step()
    assert src.request_migration(handle.rid, "x")
    src.step()
    assert handle.status == "migrated", (handle.status, handle.error)
    cont = captured[0]
    dst.run_until_idle()
    assert cont.status == "done", (cont.status, cont.error)
    assert (handle.tokens + cont.tokens)[: len(expect)] == expect


def test_draft_k_mismatch_degrades_to_recompute(cfg, params):
    """A fleet-config mismatch (draft_k differs) must reject the import
    RETRYABLY — the source stream fails over to recompute, never corrupts."""
    captured = []
    dst = make_engine(cfg, params, role="decode", draft_k=0)
    src = make_engine(
        cfg, params, draft_k=2, sampling=GREEDY,
        page_shipper=direct_shipper(dst, captured),
    )
    handle = src.submit(_prompt(9), max_new_tokens=6, seed=0)
    while len(handle.tokens) < 2:
        src.step()
    assert src.request_migration(handle.rid, "x")
    src.step()
    assert handle.status == "failed" and handle.retryable, (
        handle.status, handle.error,
    )
    assert src.stats["migration_failures"] == 1
    assert captured[0].status == "rejected" and captured[0].retryable


def test_prefill_handoff_and_role_contracts(cfg, params, reference):
    """A prefill-role engine ships every finished prefill to the decode
    target the request names; the continuation equals ``generate()``. Role
    contracts: prefill-role requires ``prefill_to``; prefill-role rejects
    imports; non-mixed roles require the paged layout."""
    captured = []
    dst = make_engine(cfg, params, role="decode")
    pre = make_engine(
        cfg, params, role="prefill",
        page_shipper=direct_shipper(dst, captured),
    )
    prompt = _prompt(13)
    expect = reference(prompt, seed=3, max_new=8)
    handle = pre.submit(
        prompt, max_new_tokens=8, seed=3, prefill_to="http://dst"
    )
    pre.run_until_idle()
    assert handle.status == "migrated" and handle.migrated_to == "http://dst"
    cont = captured[0]
    dst.run_until_idle()
    assert cont.status == "done" and cont.tokens == expect
    assert pre.stats["prefill_handoffs"] == 1
    assert dst.stats["prefill_chunks"] == 0  # decode never re-prefilled

    bare = pre.submit(prompt, max_new_tokens=4)
    assert bare.status == "rejected" and "prefill_to" in bare.error
    carry = {
        "carry/last_logits": np.zeros((cfg.vocab_size,), np.float32),
        "carry/gen_mask": np.zeros((cfg.vocab_size,), np.bool_),
        "carry/rng": np.zeros((2,), np.uint32),
    }
    back = pre.import_stream({
        "prompt": prompt, "max_new_tokens": 4, "kind": "decode",
        "page_size": 4, "n_blocks": 0, "leaves": carry,
    })
    assert back.status == "rejected" and "prefill-role" in back.error
    # a structurally torn payload (version skew) rejects retryably instead
    # of KeyError-ing the tick thread
    torn = dst.import_stream({"kind": "decode", "leaves": {}})
    assert torn.status == "rejected" and torn.retryable
    assert "bad import payload" in torn.error
    with pytest.raises(ValueError, match="paged"):
        ServingEngine(cfg, params, role="decode", kv_layout="slab")


def test_migration_failure_dumps_flight_and_fails_retryably(
    cfg, params, tmp_path
):
    """A failed ship finishes the stream retryably (the router's recompute
    fallback key) and dumps the flight recorder for the post-mortem."""

    def broken_shipper(payload, target, on_done):
        on_done("target unreachable (chaos)")

    src = make_engine(
        cfg, params, page_shipper=broken_shipper, obs_dir=str(tmp_path)
    )
    handle = src.submit(_prompt(9), max_new_tokens=6, seed=0)
    while len(handle.tokens) < 2:
        src.step()
    assert src.request_migration(handle.rid, "dead://")
    src.step()
    assert handle.status == "failed" and handle.retryable
    assert "migration failed" in handle.error
    assert src.stats["migration_failures"] == 1
    dumps = list((tmp_path / "flightrec").glob("*migration_failed*"))
    assert dumps, "migration failure must dump the flight recorder"


# ----------------------------------------------------- HTTP fleet (sockets)


class _Tok:
    eos_token_id = None

    def encode(self, text):
        return [1 + (b % 250) for b in text.encode()]

    def decode(self, ids, **kw):
        return "".join(f"<{t}>" for t in ids)

    def convert_ids_to_tokens(self, ids):
        return [f"<{t}>" for t in ids]

    def convert_tokens_to_string(self, toks):
        return "".join(toks)


def _server(cfg, params, role, **kw):
    engine = make_engine(cfg, params, role=role, **kw)
    server = ServingServer(engine, _Tok(), port=0)
    server.start()
    return engine, server


def _sse(port, path, body, timeout=240.0):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("POST", path, json.dumps(body),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        if "text/event-stream" not in (resp.getheader("Content-Type") or ""):
            return resp.status, [], json.loads(resp.read() or b"{}")
        ids, done = [], None
        while True:
            line = resp.readline()
            if not line:
                break
            if not line.startswith(b"data: "):
                continue
            event = json.loads(line[6:])
            if event.get("done"):
                done = event
                break
            if "token" in event:
                ids.append(int(event["token"]))
        return resp.status, ids, done
    finally:
        conn.close()


def _wait(pred, timeout=120.0, interval=0.02, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {msg}")


def test_router_disaggregates_and_stream_is_byte_identical(
    cfg, params, reference
):
    """The fleet proof: router over {prefill-role, decode-role} splits the
    request by phase — prefill dispatch, page ship, attach — and the
    client's SSE is byte-identical to a single replica's, with ZERO
    replayed tokens. /healthz advertises the roles; the router's /metrics
    mirrors per-replica free_pages."""
    ed, sd = _server(cfg, params, "decode")
    ep, sp = _server(cfg, params, "prefill")
    router = RouterServer(
        [f"127.0.0.1:{sp.port}", f"127.0.0.1:{sd.port}"],
        probe_interval=0.05, chunk_tokens=8, stream_timeout=240.0,
    )
    try:
        router.start()
        assert router.wait_ready(30)
        _wait(
            lambda: any(
                r.role == "prefill" for r in router.registry.routable()
            ),
            msg="role scrape",
        )
        prompt = _prompt(13)
        expect = reference(prompt, seed=3, max_new=8)
        status, ids, done = _sse(
            router.port, "/generate",
            {"tokens": prompt, "max_new_tokens": 8, "seed": 3},
        )
        assert done and done.get("status") == "done", done
        assert ids == expect
        assert router.stats["disagg_dispatches"] == 1
        assert router.stats["resume_replayed_tokens"] == 0
        assert router.stats["dropped_streams"] == 0
        assert ep.stats["prefill_handoffs"] == 1
        assert ed.stats["migrations_in"] == 1 and ed.stats["prefill_chunks"] == 0
        # non-stream JSON rides the classic path to the decode replica
        status, _, doc = _sse(
            router.port, "/generate",
            {"tokens": prompt, "max_new_tokens": 8, "seed": 3,
             "stream": False},
        )
        assert doc.get("status") == "done" and doc.get("tokens") == expect
        # per-replica page-pool mirrors on the router's text exposition
        conn = http.client.HTTPConnection("127.0.0.1", router.port)
        conn.request("GET", "/metrics", headers={"Accept": "text/plain"})
        text = conn.getresponse().read().decode()
        conn.close()
        assert "router_replica_free_pages" in text
        assert "router_replica_migrations_in_flight" in text
    finally:
        router.stop()
        sd.stop()
        sp.stop()


def test_admin_migrate_moves_live_routed_stream_with_zero_replay(
    cfg, params, reference
):
    """Live migration through the fleet: /admin/migrate on the serving
    replica mid-stream; the router follows the ``migrated`` done event
    with an attach hop and the client's stream is byte-identical, zero
    tokens replayed, zero drops."""
    e1, s1 = _server(cfg, params, "mixed")
    e2, s2 = _server(cfg, params, "mixed")
    router = RouterServer(
        [f"127.0.0.1:{s1.port}", f"127.0.0.1:{s2.port}"],
        probe_interval=0.05, chunk_tokens=8, stream_timeout=240.0,
    )
    try:
        router.start()
        assert router.wait_ready(30)
        prompt = _prompt(13)
        expect = reference(prompt, seed=7, max_new=24)
        got = {}

        def client():
            got["r"] = _sse(
                router.port, "/generate",
                {"tokens": prompt, "max_new_tokens": 24, "seed": 7,
                 "request_id": "live-mig-1"},
            )

        t = threading.Thread(target=client, daemon=True)
        t.start()
        src = {}

        def find_src():
            for e, s, other in ((e1, s1, s2), (e2, s2, s1)):
                for act in e._active:
                    if (
                        act is not None
                        and act.handle.rid == "live-mig-1"
                        and len(act.handle.tokens) >= 3
                    ):
                        src["server"], src["target"] = s, other
                        return True
            return False

        _wait(find_src, msg="stream decoding on a replica")
        conn = http.client.HTTPConnection(
            "127.0.0.1", src["server"].port, timeout=30
        )
        conn.request(
            "POST", "/admin/migrate",
            json.dumps({"request_id": "live-mig-1",
                        "target": f"http://127.0.0.1:{src['target'].port}"}),
            {"Content-Type": "application/json"},
        )
        assert conn.getresponse().status == 202
        conn.close()
        t.join(timeout=240)
        assert not t.is_alive(), "migrated stream hung"
        _, ids, done = got["r"]
        assert done and done.get("status") == "done", done
        assert ids == expect
        assert router.stats["migration_resumes"] == 1
        assert router.stats["resume_replayed_tokens"] == 0
        assert router.stats["dropped_streams"] == 0
    finally:
        router.stop()
        s1.stop()
        s2.stop()


# -------------------------------------------------------------- autoscaler


class _StubScaler:
    def __init__(self, urls):
        self.urls = list(urls)
        self.spawned = []
        self.retired = []

    def spawn(self):
        url = self.urls.pop(0)
        self.spawned.append(url)
        return url

    def retire(self, url):
        self.retired.append(url)


def _fake_router(urls, scaler, **kw):
    kw.setdefault("autoscale_interval", 3600.0)  # tick driven by hand
    kw.setdefault("scale_patience", 2)
    router = RouterServer(urls, scaler=scaler, **kw)
    return router


def _prime(router, rid, state=READY, **fields):
    rep = router.registry.get(rid)
    rep.state = state
    for k, v in fields.items():
        setattr(rep, k, v)
    return rep


def test_pick_decode_replica_prefers_pages_then_itl():
    a = Replica(id="a", url="a", host="a", port=1, state=READY,
                free_pages=10, itl_ewma_ms=5.0)
    b = Replica(id="b", url="b", host="b", port=2, state=READY,
                free_pages=40, itl_ewma_ms=9.0)
    c = Replica(id="c", url="c", host="c", port=3, state=READY,
                free_pages=40, itl_ewma_ms=2.0)
    assert pick_decode_replica([a, b, c]).id == "c"
    assert pick_decode_replica([a, b]).id == "b"
    assert pick_decode_replica([]) is None


def test_autoscaler_scales_up_on_queue_and_down_when_idle():
    """Control-loop logic, socket-free: queue pressure past the patience
    window spawns; a sustained idle fleet retires the least-loaded replica
    (never below min_replicas), and every decision lands as an obs event."""
    scaler = _StubScaler(["127.0.0.1:7991"])
    router = _fake_router(
        ["127.0.0.1:7901", "127.0.0.1:7902"], scaler,
        scale_up_queue=4.0, scale_down_active=0, min_replicas=1,
        max_replicas=3,
    )
    for rid in list(router.registry.replicas):
        _prime(router, rid, queue_depth=8)
    router._autoscale_tick()
    assert not scaler.spawned  # patience: one breach is not a trend
    router._autoscale_tick()
    assert scaler.spawned == ["127.0.0.1:7991"]
    assert router.stats["autoscale_ups"] == 1
    assert "127.0.0.1:7991" in router.registry.replicas
    events = [name for _, name, _ in router.flight.events()]
    assert "autoscale_up" in events

    # now idle: everyone empty -> retire back down (the new replica never
    # probed READY, so the victim comes from the primed pool)
    for rid in list(router.registry.replicas):
        if rid != "127.0.0.1:7991":
            _prime(router, rid, queue_depth=0, active_slots=0)
    router._autoscale_tick()
    router._autoscale_tick()
    assert len(scaler.retired) == 1
    assert router.stats["autoscale_downs"] == 1
    assert len(router.registry) == 2
    events = [name for _, name, _ in router.flight.events()]
    assert "autoscale_down" in events


def test_autoscaler_aborts_scale_down_with_live_streams():
    """A victim with relays that will not drain keeps serving: the
    scale-down ABORTS (uncordons) instead of dropping streams."""
    scaler = _StubScaler([])
    router = _fake_router(
        ["127.0.0.1:7903", "127.0.0.1:7904"], scaler,
        scale_drain_timeout_s=0.1, min_replicas=1, migrate_drain=False,
    )
    _prime(router, "127.0.0.1:7903", queue_depth=0, active_slots=0)
    _prime(router, "127.0.0.1:7904", queue_depth=0, active_slots=0,
           active_relays=1)
    victim = router._pick_retire_victim()
    assert victim.id == "127.0.0.1:7903"  # least-loaded
    _prime(router, "127.0.0.1:7903", active_relays=2)
    router._scale_down(router._load_signals())
    assert router.stats["autoscale_aborts"] == 1
    assert not scaler.retired
    assert not router.registry.get("127.0.0.1:7903").cordoned
    events = [name for _, name, _ in router.flight.events()]
    assert "autoscale_down_aborted" in events


def test_autoscaler_never_retires_the_last_of_a_role():
    scaler = _StubScaler([])
    router = _fake_router(
        ["127.0.0.1:7905", "127.0.0.1:7906"], scaler, min_replicas=1,
    )
    _prime(router, "127.0.0.1:7905", role="prefill")
    _prime(router, "127.0.0.1:7906", role="decode")
    assert router._pick_retire_victim() is None


# ------------------------------------------------------------ chaos lane


def _spawn_worker(role, extra=()):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.Popen(
        [
            sys.executable, str(REPO / "scripts" / "serve_router.py"),
            "--replica-worker", "--port", "0", "--greedy",
            "--cache-len", "64", "--slots", "2", "--prefill-chunk", "8",
            "--page-size", "4", "--role", role,
            *extra,
        ],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env,
        cwd=str(REPO),
    )
    return proc


def _worker_port(proc, timeout=300.0):
    deadline = time.monotonic() + timeout
    port: dict = {}

    def read():
        for line in proc.stdout:
            if line.startswith("REPLICA_PORT="):
                port["n"] = int(line.strip().split("=", 1)[1])
                break
        for _ in proc.stdout:
            pass

    threading.Thread(target=read, daemon=True).start()
    while time.monotonic() < deadline and "n" not in port:
        if proc.poll() is not None:
            raise AssertionError(f"worker died rc={proc.returncode}")
        time.sleep(0.1)
    assert "n" in port, "worker never reported its port"
    return port["n"]


@pytest.mark.slow
@pytest.mark.chaos
def test_chaos_sigkill_prefill_replica_under_flood(tmp_path):
    """SIGKILL the prefill replica mid-long-prompt-flood: every stream
    finishes token-exact (greedy, resumable token prompts) or ends with a
    retryable terminal event; dropped_streams == 0; the fleet keeps
    serving through the surviving decode-capable replicas."""
    procs = [
        _spawn_worker("prefill"),
        _spawn_worker("mixed"),
        _spawn_worker("mixed", ("--init-seed", "0")),
    ]
    router = None
    try:
        ports = [_worker_port(p) for p in procs]
        router = RouterServer(
            [f"http://127.0.0.1:{p}" for p in ports],
            probe_interval=0.1, chunk_tokens=8, stream_timeout=300,
            max_attempts=4, obs_dir=str(tmp_path),
        )
        router.start()
        _wait(lambda: len(router.registry.routable()) == 3,
              timeout=300, msg="fleet ready")
        _wait(
            lambda: any(
                r.role == "prefill" for r in router.registry.routable()
            ),
            timeout=60, msg="role scrape",
        )
        # warm compiles with one short request per replica class
        _sse(router.port, "/generate",
             {"tokens": [5] * 9, "max_new_tokens": 2}, timeout=600)

        results = []
        lock = threading.Lock()

        def client(i):
            prompt = [(11 + i + j) % 250 + 1 for j in range(24)]  # long
            status, ids, done = _sse(
                router.port, "/generate",
                {"tokens": prompt, "max_new_tokens": 12, "seed": 0},
                timeout=600,
            )
            with lock:
                results.append((prompt, ids, done))

        flood = [
            threading.Thread(target=client, args=(i,), daemon=True)
            for i in range(4)
        ]
        for t in flood:
            t.start()
        # kill the prefill replica while the flood is in flight
        time.sleep(0.5)
        os.kill(procs[0].pid, signal.SIGKILL)
        for t in flood:
            t.join(timeout=600)
        assert not any(t.is_alive() for t in flood), "stream HUNG after kill"
        assert len(results) == 4
        done_count = 0
        for prompt, ids, done in results:
            assert done is not None and done.get("done"), (prompt, done)
            if done["status"] == "done":
                done_count += 1
                assert len(ids) == 12
            else:
                assert done.get("retryable") is True, done
        assert done_count >= 1, results
        assert router.stats["dropped_streams"] == 0
        # the fleet keeps serving without its prefill tier
        status, ids, done = _sse(
            router.port, "/generate",
            {"tokens": [1, 3, 5, 7, 9, 11, 13, 15, 17], "max_new_tokens": 4},
            timeout=600,
        )
        assert done and done["status"] == "done"
    finally:
        if router is not None:
            router.stop()
        for p in procs:
            if p.poll() is None:
                p.kill()
            p.wait(timeout=30)


@pytest.mark.slow
@pytest.mark.chaos
def test_chaos_kill_migration_mid_transfer(tmp_path):
    """Kill the migration TARGET so the ship dies mid-transfer: the source
    fails the stream retryably, the router's recompute fallback resumes it
    token-exact on a survivor, dropped_streams == 0."""
    procs = [_spawn_worker("mixed") for _ in range(3)]
    router = None
    try:
        ports = [_worker_port(p) for p in procs]
        router = RouterServer(
            [f"http://127.0.0.1:{p}" for p in ports],
            probe_interval=0.1, chunk_tokens=8, stream_timeout=300,
            max_attempts=4, obs_dir=str(tmp_path),
        )
        router.start()
        _wait(lambda: len(router.registry.routable()) == 3,
              timeout=300, msg="fleet ready")
        _sse(router.port, "/generate",
             {"tokens": [5] * 9, "max_new_tokens": 2}, timeout=600)

        got = {}

        def client():
            got["r"] = _sse(
                router.port, "/generate",
                {"tokens": [2, 4, 6, 8, 10, 12, 14, 16, 18],
                 "max_new_tokens": 24, "seed": 0,
                 "request_id": "mid-transfer-1"},
                timeout=600,
            )

        tokens_base = router.stats["tokens_relayed"]
        t = threading.Thread(target=client, daemon=True)
        t.start()
        src = {}

        def find_src():
            # per-replica tokens_relayed only lands at hop END; the live
            # signal is the router's global token counter + the replica
            # holding the active relay
            if router.stats["tokens_relayed"] < tokens_base + 3:
                return False
            for i, port in enumerate(ports):
                rep = router.registry.get(f"127.0.0.1:{port}")
                if rep.active_relays >= 1:
                    src["i"], src["port"] = i, port
                    return True
            return False

        _wait(find_src, timeout=300, msg="stream decoding")
        # the target dies FIRST, then the source is told to migrate there:
        # the ship hits a dead peer mid-transfer and must fall back
        target_i = (src["i"] + 1) % 3
        os.kill(procs[target_i].pid, signal.SIGKILL)
        conn = http.client.HTTPConnection(
            "127.0.0.1", src["port"], timeout=30
        )
        conn.request(
            "POST", "/admin/migrate",
            json.dumps({"request_id": "mid-transfer-1",
                        "target": f"http://127.0.0.1:{ports[target_i]}"}),
            {"Content-Type": "application/json"},
        )
        assert conn.getresponse().status == 202
        conn.close()
        t.join(timeout=600)
        assert not t.is_alive(), "stream hung after mid-transfer kill"
        _, ids, done = got["r"]
        assert done is not None and done.get("done"), done
        # the recompute fallback resumed it: token-exact end to end (greedy)
        assert done["status"] == "done", done
        assert len(ids) == 24
        assert router.stats["dropped_streams"] == 0
        assert router.stats["resume_replayed_tokens"] > 0, (
            "the fallback path replays; that is what the counter proves"
        )
    finally:
        if router is not None:
            router.stop()
        for p in procs:
            if p.poll() is None:
                p.kill()
            p.wait(timeout=30)
