"""Chaos-injection tests for the fault-tolerance subsystem.

Each test injects one of the failure modes a preemptible pod run actually
hits — a divergent (NaN) step, a loader IO error, SIGTERM preemption, a
failing checkpoint write, a hung step — and asserts the run recovers
WITHOUT a human: the supervised/guarded run reaches the same step count as
an undisturbed run, with finite loss. The deterministic fast cases are
unmarked (tier-1 exercises supervisor/anomaly/watchdog logic on CPU); the
heavier end-to-end scenarios carry the ``chaos`` marker (``make chaos``).
"""
import dataclasses

import numpy as np
import pytest

import jax

from zero_transformer_tpu.config import (
    CheckpointConfig,
    Config,
    DataConfig,
    MeshConfig,
    ModelConfig,
    OptimizerConfig,
    ResilienceConfig,
    TrainingConfig,
)
from zero_transformer_tpu.resilience import (
    AnomalyHalt,
    ChaosMonkey,
    Fault,
    HangError,
    RetryableError,
    Supervisor,
    Watchdog,
    backoff_delay,
    classify,
)
from zero_transformer_tpu.resilience.watchdog import dump_stacks
from zero_transformer_tpu.training.trainer import Trainer


def tiny_config(tmp_path, total_steps=12, resilience=None, log_frequency=2,
                save_frequency=4, **ckpt_kwargs) -> Config:
    return Config(
        model=ModelConfig(vocab_size=64, d_model=32, n_heads=2, n_layers=2,
                          max_seq_len=16, dropout=0.0),
        mesh=MeshConfig(),
        optimizer=OptimizerConfig(peak_learning_rate=1e-2, warmup_steps=2,
                                  total_steps=total_steps),
        training=TrainingConfig(batch_size=8, train_context=16,
                                total_steps=total_steps,
                                evaluation_frequency=0,
                                log_frequency=log_frequency, seed=0),
        data=DataConfig(source="synthetic", max_context=16),
        checkpoint=CheckpointConfig(directory=str(tmp_path / "run"),
                                    save_frequency=save_frequency,
                                    async_save=False, **ckpt_kwargs),
        resilience=resilience or ResilienceConfig(),
    )


def params_equal(a, b, rtol=1e-5, atol=1e-7):
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), rtol=rtol,
                                   atol=atol)


def all_finite(tree) -> bool:
    return all(np.isfinite(np.asarray(l)).all() for l in jax.tree.leaves(tree))


def run_undisturbed(tmp_path, total_steps=12):
    cfg = tiny_config(tmp_path / "clean", total_steps=total_steps)
    t = Trainer(cfg)
    state = t.train()
    t.close()
    return state


def supervise(tmp_path, chaos, total_steps=12, resilience=None, **cfg_kwargs):
    """Supervised run with one ChaosMonkey shared across restarts."""
    cfg = tiny_config(tmp_path / "chaotic", total_steps=total_steps,
                      resilience=resilience, **cfg_kwargs)
    sleeps = []
    sup = Supervisor(
        cfg,
        trainer_factory=lambda c: Trainer(c, chaos=chaos),
        sleep_fn=sleeps.append,
    )
    state = sup.run()
    return state, sup, sleeps


# -- exception classification (pure logic) ----------------------------------


def test_classify_taxonomy():
    assert classify(RetryableError("x")) == "retryable"
    assert classify(HangError("x")) == "retryable"
    assert classify(OSError("disk detached")) == "retryable"
    assert classify(ConnectionResetError("peer")) == "retryable"
    assert classify(TimeoutError()) == "retryable"
    # XLA/storage fingerprints in foreign exception text
    assert classify(RuntimeError("RESOURCE_EXHAUSTED: hbm oom")) == "retryable"
    assert classify(RuntimeError("UNAVAILABLE: socket closed")) == "retryable"
    # config/shape/user errors restart cannot fix
    assert classify(ValueError("d_model must divide")) == "fatal"
    assert classify(TypeError("bad arg")) == "fatal"
    assert classify(FileNotFoundError("no such config")) == "fatal"
    assert classify(AnomalyHalt("diverged")) == "fatal"
    assert classify(KeyboardInterrupt()) == "fatal"
    # unknown bugs default fatal: a blind restart loop is not recovery
    assert classify(RuntimeError("some novel crash")) == "fatal"


def test_classify_walks_cause_chain_explicit_raise_from():
    """A RetryableError re-raised under a fatal-looking wrapper type must
    classify by the innermost cause: the loader raising ``ValueError(...)
    from RetryableError`` is still a transient IO failure underneath."""
    try:
        try:
            raise RetryableError("shard read reset")
        except RetryableError as inner:
            raise ValueError("while decoding shard 7") from inner
    except ValueError as exc:
        wrapped = exc
    assert classify(wrapped) == "retryable"


def test_classify_walks_cause_chain_implicit_context():
    """Same honor for the implicit ``__context__`` chain — an exception
    raised INSIDE an ``except RetryableError:`` block carries the original
    as context, not cause."""
    try:
        try:
            raise RetryableError("watchdog abort")
        except RetryableError:
            raise KeyError("cleanup lookup failed")
    except KeyError as exc:
        wrapped = exc
    assert wrapped.__cause__ is None and wrapped.__context__ is not None
    assert classify(wrapped) == "retryable"


def test_classify_retryable_wrapping_fatal_stays_retryable():
    # reversed nesting order: the outermost exception IS a RetryableError,
    # whatever it wrapped
    try:
        try:
            raise ValueError("bad shape deep down")
        except ValueError as inner:
            raise RetryableError("transient wrapper") from inner
    except RetryableError as exc:
        wrapped = exc
    assert classify(wrapped) == "retryable"


def test_classify_user_interrupt_beats_cause_chain():
    """Ctrl-C wins even when a RetryableError sits underneath: the user
    asked the run to die, the supervisor must not resurrect it."""
    ki = KeyboardInterrupt()
    ki.__cause__ = RetryableError("mid-retry when interrupted")
    assert classify(ki) == "fatal"


def test_classify_cause_cycle_terminates():
    a = RuntimeError("a")
    b = RuntimeError("b")
    a.__cause__, b.__cause__ = b, a
    assert classify(a) == "fatal"  # and, crucially, it returns at all


# -- backoff jitter (satellite of the fleet supervisor) ----------------------


def test_backoff_delay_pinned_to_jitter_window():
    """The jittered delay is PINNED inside [base*2^(k-1)*(1-j), ...*(1+j)]:
    rng extremes map exactly onto the window edges, the midpoint is the
    undithered exponential value, and the cap applies before jitter."""
    for attempt, nominal in [(1, 1.0), (2, 2.0), (3, 4.0), (10, 60.0)]:
        lo = backoff_delay(1.0, 60.0, attempt, jitter=0.25, rng=lambda: 0.0)
        mid = backoff_delay(1.0, 60.0, attempt, jitter=0.25, rng=lambda: 0.5)
        hi = backoff_delay(1.0, 60.0, attempt, jitter=0.25, rng=lambda: 1.0)
        assert mid == pytest.approx(nominal)
        assert lo == pytest.approx(nominal * 0.75)
        assert hi == pytest.approx(nominal * 1.25)
    # jitter=0 degenerates to the old deterministic schedule
    assert backoff_delay(0.01, 1.0, 2, jitter=0.0) == pytest.approx(0.02)
    # sampled delays stay inside the window and actually spread
    import random as _random

    rng = _random.Random(7).random
    samples = [
        backoff_delay(1.0, 60.0, 1, jitter=0.1, rng=rng) for _ in range(64)
    ]
    assert all(0.9 <= s <= 1.1 for s in samples)
    assert len({round(s, 6) for s in samples}) > 10  # not secretly constant


def test_config_backoff_jitter_validation():
    with pytest.raises(ValueError, match="backoff_jitter"):
        ResilienceConfig(backoff_jitter=1.0)
    with pytest.raises(ValueError, match="backoff_jitter"):
        ResilienceConfig(backoff_jitter=-0.1)
    ResilienceConfig(backoff_jitter=0.0)  # edges that must remain legal
    ResilienceConfig(backoff_jitter=0.999)


def test_config_resilience_block_validation():
    with pytest.raises(ValueError, match="anomaly_response"):
        ResilienceConfig(anomaly_response="retry")
    with pytest.raises(ValueError, match="ema_decay"):
        ResilienceConfig(ema_decay=1.5)
    ResilienceConfig(anomaly_response="rollback", loss_spike_factor=3.0)


# -- anomaly guard ----------------------------------------------------------


def test_nan_step_skipped_run_matches_undisturbed_step_count(tmp_path, devices):
    """A NaN step under 'skip_batch' is dropped in-graph; the run completes
    to the SAME step count as an undisturbed run with finite loss/params —
    the end-state parity contract for fault injection."""
    clean = run_undisturbed(tmp_path, total_steps=12)
    chaos = ChaosMonkey([Fault(kind="nan_step", step=4, duration=2)])
    cfg = tiny_config(
        tmp_path / "chaotic", total_steps=12,
        resilience=ResilienceConfig(anomaly_response="skip_batch"),
    )
    t = Trainer(cfg, chaos=chaos)
    state = t.train()
    assert int(state.step) == int(clean.step) == 12
    assert t.resilience_report["anomalies"] == 2
    assert all_finite(state.params), "guard let a NaN update land"
    assert np.isfinite(t.evaluate(state)["loss"])
    t.close()


def test_nan_at_non_log_step_detected_without_poisoning(tmp_path, devices):
    """The halt_on_nan blind spot, closed: divergence at a NON-log step is
    caught at the next log point, and because the update was dropped
    in-graph, NO further updates were poisoned in the meantime (the
    historical path poisoned up to log_frequency - 1 of them)."""
    chaos = ChaosMonkey([Fault(kind="nan_step", step=2, duration=1)])
    cfg = tiny_config(tmp_path, total_steps=12, log_frequency=5,
                      save_frequency=100)
    t = Trainer(cfg, chaos=chaos)  # default response: halt
    # the NaN hits while computing step 3; the loss fetched at the step-5
    # log point is finite again, so ONLY the in-graph carry can report it —
    # and it does, at the first log point after the fault
    with pytest.raises(AnomalyHalt, match="1 flagged step\\(s\\) by step 5"):
        t.train()
    # nothing was checkpointed: the last good checkpoint (none yet) stands
    assert t.ckpt.latest_step() is None
    t.close()


def test_rollback_restores_snapshot_and_completes(tmp_path, devices):
    """A sustained anomaly streak escalates to rollback: params/opt restore
    from the host-RAM snapshot, the loader continues FORWARD past the bad
    window, and the run still completes to the target step."""
    chaos = ChaosMonkey([Fault(kind="nan_step", step=4, duration=4)])
    res = ResilienceConfig(
        anomaly_response="rollback", rollback_after=2, max_rollbacks=5,
        snapshot_frequency=2,
    )
    cfg = tiny_config(tmp_path, total_steps=14, resilience=res,
                      log_frequency=2)
    t = Trainer(cfg, chaos=chaos)
    state = t.train()
    assert int(state.step) == 14
    assert t.resilience_report["rollbacks"] >= 1
    assert t.resilience_report["anomalies"] >= 2
    assert all_finite(state.params)
    assert np.isfinite(t.evaluate(state)["loss"])
    t.close()
    # the rollback landed in the metrics timeline as a tagged event
    import json

    lines = [json.loads(l) for l in
             (tmp_path / "run" / "metrics.jsonl").read_text().splitlines()]
    events = [l for l in lines if l.get("event") == "anomaly_rollback"]
    assert events and events[0]["to_step"] <= events[0]["step"]


def test_rollback_budget_exhaustion_halts(tmp_path, devices):
    """A divergence that persists through every rollback must eventually
    halt (needs a human), not burn the pod in a rollback loop."""
    chaos = ChaosMonkey([Fault(kind="nan_step", step=2, duration=1000)])
    res = ResilienceConfig(
        anomaly_response="rollback", rollback_after=1, max_rollbacks=2,
        snapshot_frequency=1,
    )
    cfg = tiny_config(tmp_path, total_steps=50, resilience=res,
                      log_frequency=1, save_frequency=1000)
    t = Trainer(cfg, chaos=chaos)
    with pytest.raises(AnomalyHalt, match="rollback budget exhausted"):
        t.train()
    t.close()


def test_skip_batch_streak_limit_halts(tmp_path, devices):
    """skip_batch cannot spin forever on an all-anomalous stream."""
    chaos = ChaosMonkey([Fault(kind="nan_step", step=0, duration=1000)])
    res = ResilienceConfig(anomaly_response="skip_batch",
                           max_consecutive_anomalies=4)
    cfg = tiny_config(tmp_path, total_steps=50, resilience=res,
                      log_frequency=2, save_frequency=1000)
    t = Trainer(cfg, chaos=chaos)
    with pytest.raises(AnomalyHalt, match="consecutive"):
        t.train()
    t.close()


def test_guard_adds_no_per_step_host_sync(tmp_path, devices):
    """The acceptance bound: on the non-logging path the guarded step makes
    ZERO device→host transfers. Asserted directly — several guarded steps
    run under jax's transfer guard with device→host set to disallow; any
    implicit fetch (what a host-side NaN check would need) raises."""
    cfg = tiny_config(tmp_path, total_steps=8)
    t = Trainer(cfg)
    state = t.init_state()
    guard, step_fn = t._guarded_step()
    carry = guard.init_carry()
    batch_np = np.zeros((1, 8, 16), np.int32)
    with jax.transfer_guard_device_to_host("disallow"):
        for _ in range(3):
            batch = jax.device_put(batch_np, t.batch_sharding)
            state, metrics, carry = step_fn(state, batch, t.rng, carry)
    # ... and the carry DOES carry the information once the host asks
    stats = guard.read(carry)
    assert stats.count == 0
    t.close()


def test_guard_trajectory_matches_unguarded(tmp_path, devices):
    """With no anomalies the guard is a semantic no-op: the select picks
    every new state, so params after N steps match a detection-off run
    (up to compile-level reassociation — the guard inlines the step into a
    larger XLA program, which reorders fusions by a few ulps)."""
    cfg_on = tiny_config(tmp_path / "on", total_steps=6)
    cfg_off = dataclasses.replace(
        tiny_config(tmp_path / "off", total_steps=6),
        resilience=ResilienceConfig(anomaly_detection=False),
    )
    t_on, t_off = Trainer(cfg_on), Trainer(cfg_off)
    s_on, s_off = t_on.train(), t_off.train()
    params_equal(s_on.params, s_off.params, rtol=1e-3, atol=1e-5)
    t_on.close()
    t_off.close()


# -- supervisor + chaos end-to-end ------------------------------------------


@pytest.mark.chaos
def test_loader_error_supervised_recovers(tmp_path, devices):
    """A hard loader IO error is retryable: the supervisor restarts from the
    last checkpoint and the run completes to the undisturbed step count."""
    chaos = ChaosMonkey([Fault(kind="loader_error", step=6, exc=OSError)])
    state, sup, sleeps = supervise(tmp_path, chaos, total_steps=12,
                                   save_frequency=4)
    assert int(state.step) == 12
    assert len(sup.history) == 1 and "OSError" in sup.history[0].reason
    # one backoff sleep, inside the jitter window around the base delay
    assert len(sleeps) == 1
    b, j = sup.res.backoff_base_s, sup.res.backoff_jitter
    assert b * (1 - j) <= sleeps[0] <= b * (1 + j)
    assert "loader_error@6" in chaos.fired_log


@pytest.mark.chaos
def test_sigterm_preemption_supervised_parity(tmp_path, devices):
    """Simulated preemption: SIGTERM mid-train → force-save → supervised
    resume reproduces the SAME final params as an uninterrupted run (the
    loader position and per-step rng are both checkpoint-derived, so the
    trajectory is identical — not just the step count)."""
    clean = run_undisturbed(tmp_path, total_steps=12)
    chaos = ChaosMonkey([Fault(kind="sigterm", step=5)])
    state, sup, _ = supervise(tmp_path, chaos, total_steps=12)
    assert int(state.step) == int(clean.step) == 12
    assert len(sup.history) == 1 and "preempted" in sup.history[0].reason
    params_equal(clean.params, state.params)


@pytest.mark.chaos
def test_checkpoint_write_failure_supervised_recovers(tmp_path, devices):
    """A failed checkpoint write surfaces at the save tick (not hours later)
    and is retryable; the rerun completes."""
    chaos = ChaosMonkey([Fault(kind="ckpt_fail", step=4, exc=OSError)])
    state, sup, _ = supervise(tmp_path, chaos, total_steps=12,
                              save_frequency=4)
    assert int(state.step) == 12
    assert len(sup.history) == 1 and "OSError" in sup.history[0].reason


@pytest.mark.chaos
def test_slow_checkpoint_write_still_completes(tmp_path, devices):
    """A slow (but succeeding) save is not a failure: no restart, run done."""
    chaos = ChaosMonkey([Fault(kind="ckpt_slow", step=4, duration=1.0)])
    state, sup, sleeps = supervise(tmp_path, chaos, total_steps=8,
                                   save_frequency=4)
    assert int(state.step) == 8
    assert sup.history == [] and sleeps == []


@pytest.mark.chaos
def test_hung_step_watchdog_aborts_and_supervisor_recovers(tmp_path, devices):
    """A hung step trips the watchdog (stack dump + force-save + retryable
    abort); the supervisor restarts from the force-saved checkpoint and the
    run completes to the target step."""
    chaos = ChaosMonkey([Fault(kind="hang", step=3, duration=120.0)])
    res = ResilienceConfig(watchdog_timeout_s=3.0)
    state, sup, sleeps = supervise(tmp_path, chaos, total_steps=8,
                                   resilience=res, save_frequency=100)
    assert int(state.step) == 8
    assert len(sup.history) == 1 and "HangError" in sup.history[0].reason
    # the watchdog force-saved at the hang point, so the restart resumed
    # from step 3, not from scratch
    assert sup.history[0].step == 3


@pytest.mark.chaos
def test_supervisor_max_steps_is_a_run_budget_not_per_attempt(tmp_path, devices):
    """--supervise --max-steps N must stop at N total even across restarts:
    a retry gets only the REMAINING budget, not a fresh one."""
    chaos = ChaosMonkey([Fault(kind="sigterm", step=5)])
    cfg = tiny_config(tmp_path / "budget", total_steps=100)
    sup = Supervisor(
        cfg,
        trainer_factory=lambda c: Trainer(c, chaos=chaos),
        sleep_fn=lambda s: None,
    )
    state = sup.run(max_steps=12)
    assert int(state.step) == 12  # not 5 + 12


def test_supervisor_fatal_error_propagates(tmp_path, devices):
    """Config/shape errors must NOT be retried."""
    cfg = tiny_config(tmp_path, total_steps=4)
    calls = []

    def factory(c):
        calls.append(c)
        raise ValueError("shape mismatch: d_model")

    sup = Supervisor(cfg, trainer_factory=factory, sleep_fn=lambda s: None)
    with pytest.raises(ValueError, match="shape mismatch"):
        sup.run()
    assert len(calls) == 1  # no second attempt


def test_supervisor_budget_exhaustion(tmp_path, devices):
    cfg = tiny_config(tmp_path, total_steps=4)
    cfg = dataclasses.replace(
        cfg, resilience=ResilienceConfig(max_restarts=2, backoff_base_s=0.01)
    )

    class Always:
        def __init__(self, c):
            pass

        def train(self, max_steps=None):
            raise OSError("bucket gone")

        def close(self):
            pass

    sleeps = []
    sup = Supervisor(cfg, trainer_factory=Always, sleep_fn=sleeps.append)
    with pytest.raises(RetryableError, match="restart budget exhausted"):
        sup.run()
    # exponential backoff (base, 2*base), each dithered by the jitter window
    j = sup.res.backoff_jitter
    assert len(sleeps) == 2
    assert 0.01 * (1 - j) <= sleeps[0] <= 0.01 * (1 + j)
    assert 0.02 * (1 - j) <= sleeps[1] <= 0.02 * (1 + j)


def test_supervisor_backoff_deterministic_with_seeded_rng(tmp_path, devices):
    """An injected rng makes the jittered schedule reproducible — the seam
    the fleet tests (and anyone replaying an incident) rely on."""
    cfg = tiny_config(tmp_path, total_steps=4)
    cfg = dataclasses.replace(
        cfg,
        resilience=ResilienceConfig(
            max_restarts=2, backoff_base_s=0.01, backoff_jitter=0.5
        ),
    )

    class Always:
        def __init__(self, c):
            pass

        def train(self, max_steps=None):
            raise OSError("bucket gone")

        def close(self):
            pass

    sleeps = []
    sup = Supervisor(
        cfg, trainer_factory=Always, sleep_fn=sleeps.append, rng=lambda: 1.0
    )
    with pytest.raises(RetryableError):
        sup.run()
    assert sleeps == pytest.approx([0.015, 0.03])  # top edge of each window


# -- trustworthy restore: integrity + replica-audit chaos --------------------


def _events(tmp_path, name):
    import json

    path = tmp_path / "chaotic" / "run" / "metrics.jsonl"
    if not path.exists():
        return []
    return [
        json.loads(l)
        for l in path.read_text().splitlines()
        if json.loads(l).get("event") == name
    ]


@pytest.mark.chaos
@pytest.mark.slow  # two supervised restart runs; `make chaos`/`elastic-chaos`
@pytest.mark.parametrize("kind", ["ckpt_truncate", "ckpt_bitflip"])
def test_ckpt_corruption_supervised_falls_back_and_completes(
    tmp_path, devices, kind
):
    """The acceptance scenario: the newest checkpoint is corrupted on disk
    (torn write / bit rot) AFTER a successful save; a later retryable fault
    forces a supervised restart. The restore must QUARANTINE the corrupt
    step, fall back to the previous VERIFIED step, and still reach the
    undisturbed step count with finite loss — instead of crash-looping on
    (or silently training from) the bad artifact."""
    chaos = ChaosMonkey([
        Fault(kind=kind, step=8),         # corrupts the step-8 save
        Fault(kind="loader_error", step=9, exc=OSError),  # forces a restart
    ])
    state, sup, _ = supervise(tmp_path, chaos, total_steps=12,
                              save_frequency=4)
    assert int(state.step) == 12
    assert all_finite(state.params)
    assert f"{kind}@8" in chaos.fired_log
    # the corrupt step-8 dir was quarantined; the restart resumed from 4
    run_dir = tmp_path / "chaotic" / "run"
    assert list(run_dir.glob("8.quarantined*")), list(run_dir.iterdir())
    quarantines = _events(tmp_path, "ckpt_quarantined")
    fallbacks = _events(tmp_path, "restore_fallback")
    assert quarantines and quarantines[0]["step"] == 8
    assert fallbacks and fallbacks[0]["from_step"] == 8
    assert fallbacks[0]["fallback_steps"] == 4  # 8 -> 4


@pytest.mark.chaos
@pytest.mark.slow  # full chaotic run; `make chaos`/`elastic-chaos` + nightly
def test_replica_perturb_audit_trips_within_frequency(tmp_path, devices):
    """SDC desyncs one DP replica mid-run: the in-graph audit must trip
    within audit_frequency steps and escalate per the anomaly response
    (halt), naming the failure class — not wait for the loss curves to
    fork."""
    chaos = ChaosMonkey([Fault(kind="replica_perturb", step=5)])
    res = ResilienceConfig(audit_frequency=2, anomaly_response="halt")
    cfg = tiny_config(tmp_path / "chaotic", total_steps=20, resilience=res,
                      log_frequency=2)
    t = Trainer(cfg, chaos=chaos)
    with pytest.raises(AnomalyHalt, match="cross-replica divergence") as ei:
        t.train()
    t.close()
    # perturb lands after step 5; audits run on even steps — the step-6
    # audit is the FIRST chance, and the halt surfaces at that log point
    assert "step 6" in str(ei.value)
    events = _events(tmp_path, "replica_divergence")
    assert events and events[0]["step"] == 6


@pytest.mark.chaos
@pytest.mark.slow  # full heal-and-complete run; `make chaos`/`elastic-chaos`
def test_replica_perturb_rollback_heals_and_completes(tmp_path, devices):
    """With anomaly_response=rollback the divergence is HEALED: the host
    snapshot re-replicates identical copies on every device and the run
    completes to the undisturbed step count with finite loss."""
    chaos = ChaosMonkey([Fault(kind="replica_perturb", step=5)])
    res = ResilienceConfig(audit_frequency=2, anomaly_response="rollback",
                           snapshot_frequency=2, max_rollbacks=3)
    cfg = tiny_config(tmp_path / "chaotic", total_steps=12, resilience=res,
                      log_frequency=2)
    t = Trainer(cfg, chaos=chaos)
    state = t.train()
    assert int(state.step) == 12
    assert t.resilience_report["replica_audit_failures"] == 1
    assert t.resilience_report["rollbacks"] == 1
    assert all_finite(state.params)
    assert np.isfinite(t.evaluate(state)["loss"])
    t.close()
    assert _events(tmp_path, "replica_heal_rollback")


def test_replica_audit_detects_single_device_desync(tmp_path, devices):
    """Unit: the in-graph audit distinguishes a healthy replicated state
    from one where a single device's copy differs by one bit-level change
    (the desync is invisible to everything else — XLA assumes replicated
    copies identical)."""
    from zero_transformer_tpu.parallel.zero import make_replica_audit
    from zero_transformer_tpu.resilience.chaos import perturb_one_replica

    res = ResilienceConfig(audit_frequency=2)
    cfg = tiny_config(tmp_path, total_steps=4, resilience=res)
    t = Trainer(cfg)
    state = t.init_state()
    audit = make_replica_audit(t.mesh, t.plan)
    assert audit is not None
    assert not bool(jax.jit(audit)(state))
    desynced = perturb_one_replica(state)
    assert bool(jax.jit(audit)(desynced))
    # ... and ONLY the audit notices: the perturbed leaf still claims full
    # replication, so a plain device_get reads one copy and sees nothing
    t.close()


def test_audit_requires_anomaly_detection():
    with pytest.raises(ValueError, match="audit_frequency requires"):
        ResilienceConfig(audit_frequency=5, anomaly_detection=False)


# -- watchdog unit ----------------------------------------------------------


def test_dump_stacks_lists_threads():
    text = dump_stacks("unit test")
    assert "thread stacks" in text and "MainThread" in text
    assert "live device arrays" in text


def test_watchdog_fires_only_past_deadline():
    import time

    beats: list = []
    wd = Watchdog(timeout_s=0.4, on_hang=lambda: beats.append("hang"),
                  poll_s=0.05)
    wd.start()
    try:
        for _ in range(4):  # healthy heartbeat: never fires
            time.sleep(0.1)
            wd.beat()
        assert not wd.fired and beats == []
        with pytest.raises(KeyboardInterrupt):
            while True:  # stalled: fires once, interrupts the main thread
                time.sleep(0.05)
    finally:
        wd.stop()
    assert wd.fired and beats == ["hang"]


# -- checkpoint async-error surfacing ---------------------------------------


def test_async_save_errors_surface_at_next_save_tick(tmp_path, devices):
    """A dead async commit kills the run at the NEXT save() call, not at
    wait()/close() hours later."""
    from zero_transformer_tpu import checkpoint as ckpt_lib

    mgr = ckpt_lib.CheckpointManager(tmp_path / "ck", save_frequency=1,
                                     async_save=True)
    mgr.ensure_ready()

    def boom():
        raise RuntimeError("async commit died: bucket detached")

    assert hasattr(mgr._mgr, "check_for_errors"), "orbax too old for test"
    mgr._mgr_inst.check_for_errors = boom
    with pytest.raises(RuntimeError, match="async commit died"):
        mgr.save(1, {"x": np.zeros(2)})


# -- loader hardening --------------------------------------------------------


def test_tarshard_retry_backoff_and_fault_counters(tmp_path, devices):
    """An unreadable shard is retried with backoff then skipped, and the
    skip is COUNTED — surfaced via DataLoader.fault_counters() into the
    metrics stream rather than vanishing into a log."""
    import io
    import tarfile

    from zero_transformer_tpu.data.loader import DataLoader
    from zero_transformer_tpu.data.tarshards import TarShardSource

    def write_shard(path, rows):
        with tarfile.open(path, "w") as tar:
            for i, row in enumerate(rows):
                buf = io.BytesIO()
                np.save(buf, np.asarray(row))
                data = buf.getvalue()
                info = tarfile.TarInfo(f"{i:05d}.npy")
                info.size = len(data)
                tar.addfile(info, io.BytesIO(data))
        return str(path)

    good = write_shard(tmp_path / "a.tar", [np.arange(8)] * 4)
    bad = tmp_path / "b.tar"
    bad.write_bytes(b"this is not a tar archive")
    src = TarShardSource([good, str(bad)], max_context=8, shuffle_shards=False,
                         retry_backoff_s=0.0)
    loader = DataLoader(src, batch_size=2, train_context=8,
                        process_index=0, process_count=1)
    it = iter(loader)
    # 3 batches = 6 rows: exhausts the 4 good rows, runs into the corrupt
    # shard (retry x2, then skip), and wraps into epoch 2
    for _ in range(3):
        next(it)
    counters = loader.fault_counters()
    assert counters["skipped_shards"] == 1
    assert counters["shard_retries"] == 2  # two retries before the skip
    assert counters["skipped_members"] == 0


def test_trainer_reports_data_fault_counters(tmp_path, devices):
    """The metrics stream carries the loader's fault counters at log points."""
    cfg = tiny_config(tmp_path, total_steps=4)
    t = Trainer(cfg)
    t.train_loader.source.fault_counters = {"skipped_shards": 3}
    payload = t._data_fault_payload()
    assert payload == {"data_skipped_shards": 3.0}
    t.close()
