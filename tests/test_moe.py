"""Mixture-of-Experts + expert parallelism on the 8-device mesh.

Capability beyond the reference (SURVEY §2 checklist: EP/MoE = none).
Contracts pinned here: routing math (capacity, top-k weights), single-expert
equivalence to the dense MLP, EP sharding placement, and training (loss
decreases; ZeRO-2 explicit core composes with an active expert axis).
"""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from zero_transformer_tpu.config import MeshConfig, ModelConfig, OptimizerConfig
from zero_transformer_tpu.models import Transformer
from zero_transformer_tpu.models.moe import _routing
from zero_transformer_tpu.parallel import (
    make_mesh,
    make_plan,
    init_train_state,
    make_train_step,
)
from zero_transformer_tpu.parallel.mesh import EXPERT_AXIS
from zero_transformer_tpu.training.optimizer import make_optimizer, make_schedule

MOE_CFG = ModelConfig(
    name="moe_t", vocab_size=128, d_model=32, n_heads=4, n_layers=2,
    max_seq_len=16, dropout=0.0, compute_dtype="float32",
    n_experts=4, moe_top_k=2,
)


class TestRouting:
    def test_top1_dispatch_and_weights(self):
        # 1 batch, 4 tokens, 2 experts; logits force tokens 0,1,3->e1, 2->e0
        logits = jnp.asarray(
            [[[0.0, 2.0], [0.0, 2.0], [2.0, 0.0], [0.0, 2.0]]], jnp.float32
        )
        dispatch, combine, aux = _routing(logits, top_k=1, capacity=2)
        # expert 1 queue: token0 slot0, token1 slot1, token3 OVERFLOWS (C=2)
        assert dispatch[0, 0, 1, 0] == 1 and dispatch[0, 1, 1, 1] == 1
        assert jnp.sum(dispatch[0, 3]) == 0  # dropped
        assert dispatch[0, 2, 0, 0] == 1
        # top-1 combine weight = raw router prob (Switch convention)
        p = float(jax.nn.softmax(jnp.asarray([0.0, 2.0]))[1])
        np.testing.assert_allclose(float(combine[0, 0, 1, 0]), p, rtol=1e-6)

    def test_top2_weights_renormalized(self):
        logits = jnp.asarray([[[2.0, 1.0, -4.0]]], jnp.float32)  # 1 token, E=3
        dispatch, combine, aux = _routing(logits, top_k=2, capacity=1)
        probs = jax.nn.softmax(logits[0, 0])
        w0 = float(probs[0] / (probs[0] + probs[1]))
        w1 = float(probs[1] / (probs[0] + probs[1]))
        np.testing.assert_allclose(float(combine[0, 0, 0, 0]), w0, rtol=1e-5)
        np.testing.assert_allclose(float(combine[0, 0, 1, 0]), w1, rtol=1e-5)
        assert float(jnp.sum(dispatch)) == 2.0

    def test_balanced_routing_has_unit_aux(self):
        # perfectly uniform router → load-balance loss == 1 (its minimum)
        logits = jnp.zeros((2, 8, 4), jnp.float32)
        _, _, aux = _routing(logits, top_k=1, capacity=8)
        np.testing.assert_allclose(float(aux), 1.0, rtol=1e-6)


def test_single_expert_matches_dense_mlp():
    """E=1/k=1 MoE with the dense model's MLP weights transplanted must
    reproduce the dense model exactly (routing weight is softmax over one
    logit = 1.0; capacity ≥ T keeps every token)."""
    dense_cfg = dataclasses.replace(MOE_CFG, n_experts=0)
    moe_cfg = dataclasses.replace(
        MOE_CFG, n_experts=1, moe_top_k=1, capacity_factor=1.0
    )
    x = jnp.asarray(np.random.default_rng(0).integers(0, 128, (2, 16)), jnp.int32)
    import flax.linen as nn

    dense = Transformer(dense_cfg)
    moe = Transformer(moe_cfg)
    dparams = nn.meta.unbox(dense.init(jax.random.PRNGKey(0), x)["params"])
    mparams = nn.meta.unbox(moe.init(jax.random.PRNGKey(0), x)["params"])

    # transplant: dense blocks/mlp/{wi,wo} -> moe blocks/moe/{wi,wo} with a
    # leading expert dim of 1 (stacked layer dim stays leading)
    mlp = dparams["blocks"]["mlp"]
    moe_leaf = dict(mparams["blocks"]["moe"])
    for name in ("wi", "wo"):
        src = np.asarray(mlp[name]["kernel"])  # [L, d, f]
        moe_leaf[name] = jnp.asarray(src[:, None, :, :])  # [L, 1, d, f]
    new_blocks = dict(mparams["blocks"])
    new_blocks["moe"] = moe_leaf
    new_params = dict(mparams)
    new_params["blocks"] = new_blocks
    # everything except the MLP/MoE weights is shared via identical init
    for shared in ("attn", "ln_attn", "ln_mlp"):
        new_blocks[shared] = dparams["blocks"][shared]
    new_params["wte"] = dparams["wte"]
    new_params["ln_f"] = dparams["ln_f"]

    ref = dense.apply({"params": dparams}, x)
    out = moe.apply({"params": new_params}, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_upcycle_dense_to_moe_preserves_function():
    """Sparse upcycling: with every expert an exact copy of the donor MLP
    and top-2 renormalized weights (w1+w2=1), the upcycled model must
    compute the donor's function exactly (capacity high enough to drop
    nothing)."""
    import flax.linen as nn

    from zero_transformer_tpu.utils.surgery import upcycle_moe

    dense_cfg = dataclasses.replace(MOE_CFG, n_experts=0)
    moe_cfg = dataclasses.replace(MOE_CFG, n_experts=4, moe_top_k=2,
                                  capacity_factor=4.0)
    x = jnp.asarray(np.random.default_rng(1).integers(0, 128, (2, 16)), jnp.int32)
    dense = Transformer(dense_cfg)
    dparams = nn.meta.unbox(dense.init(jax.random.PRNGKey(0), x)["params"])
    mparams = upcycle_moe(dparams, n_experts=4)
    ref = dense.apply({"params": dparams}, x)
    out = Transformer(moe_cfg).apply({"params": mparams}, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_moe_params_shard_over_expert_axis(devices):
    mesh = make_mesh(MeshConfig(data=2, expert=2, tensor=2))
    assert mesh.shape[EXPERT_AXIS] == 2
    model = Transformer(MOE_CFG)
    tx = make_optimizer(OptimizerConfig(warmup_steps=2, total_steps=10))
    plan = make_plan(model, tx, mesh, (2, 16), zero_stage=1)
    state = init_train_state(
        model, tx, jax.random.PRNGKey(0), mesh, (2, 16), plan
    )
    wi = state.params["blocks"]["moe"]["wi"]
    assert "expert" in str(wi.sharding.spec), wi.sharding.spec
    # 4 experts over 2 expert-devices: each holds half the expert stack
    specs = [str(l.sharding.spec) for l in jax.tree.leaves(state.params)]
    assert any("tensor" in s for s in specs)  # TP still composes


@pytest.mark.parametrize("zero_stage", [1, 2])
def test_moe_trains_on_ep_mesh(devices, zero_stage):
    """Loss decreases with experts sharded over the expert axis; stage 2
    exercises the partial-manual ZeRO core with expert as an auto axis."""
    mesh = make_mesh(MeshConfig(data=4, expert=2))
    model = Transformer(MOE_CFG)
    opt = OptimizerConfig(peak_learning_rate=3e-3, warmup_steps=2, total_steps=40)
    tx = make_optimizer(opt)
    plan = make_plan(model, tx, mesh, (8, 16), zero_stage)
    state = init_train_state(model, tx, jax.random.PRNGKey(0), mesh, (8, 16), plan)
    step = make_train_step(model, tx, mesh, plan, zero_stage, make_schedule(opt))
    batch = jnp.asarray(
        np.random.default_rng(0).integers(0, 128, (1, 8, 16)), jnp.int32
    )
    losses = []
    rng = jax.random.PRNGKey(1)
    for _ in range(20):
        state, metrics = step(state, batch, rng)
        losses.append(float(metrics["loss"]))
        assert np.isfinite(losses[-1])
    assert losses[-1] < losses[0] - 0.5, f"stage {zero_stage}: {losses}"
