"""Model unit tests (counterpart of reference ``tests/test_model_components.py``
and ``tests/test_model_factory.py``, extended with GQA/RoPE/scan/decode)."""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from zero_transformer_tpu.config import ModelConfig, model_config
from zero_transformer_tpu.models import Transformer, model_getter
from zero_transformer_tpu.ops.losses import next_token_loss
from zero_transformer_tpu.ops.positions import alibi_slopes_list

TEST_CFG = ModelConfig(
    name="t", vocab_size=128, d_model=64, n_heads=4, n_layers=2, max_seq_len=32,
    dropout=0.0, compute_dtype="float32",
)


def _init_and_apply(cfg, B=2, T=16, train=False, seed=0):
    model = Transformer(cfg)
    x = jnp.asarray(np.random.default_rng(seed).integers(0, cfg.vocab_size, (B, T)))
    params = model.init(jax.random.PRNGKey(0), x)
    rngs = {"dropout": jax.random.PRNGKey(1)} if train else {}
    out = model.apply(params, x, train=train, rngs=rngs)
    return model, params, x, out


def test_forward_shapes():
    _, _, x, logits = _init_and_apply(TEST_CFG)
    assert logits.shape == (2, 16, TEST_CFG.vocab_size)


def test_internal_loss_matches_external():
    # reference pins this equality at tests/test_model_components.py:232-262
    cfg = TEST_CFG
    model = Transformer(cfg)
    x = jnp.asarray(np.random.default_rng(0).integers(0, cfg.vocab_size, (2, 16)))
    params = model.init(jax.random.PRNGKey(0), x)
    logits, loss = model.apply(params, x, labels=x)
    external = next_token_loss(logits, x)
    np.testing.assert_allclose(loss, external, rtol=1e-6)


@pytest.mark.parametrize("tie", [True, False])
@pytest.mark.parametrize("packed", [False, True])
def test_loss_chunk_matches_full_logits(tie, packed):
    """cfg.loss_chunk computes the identical loss AND parameter gradients
    without materializing the [B, T, vocab] logits — tied (embedding.T
    projection) and untied (LMHead kernel), with packed-document boundary
    masking threaded through. The param TREE is also identical, so the
    toggle never invalidates a checkpoint."""
    base = dataclasses.replace(
        TEST_CFG, tie_embeddings=tie,
        doc_sep_token=0 if packed else None,
    )
    chunked = dataclasses.replace(base, loss_chunk=5)  # 15 positions: pad path
    x = np.asarray(
        np.random.default_rng(0).integers(1, base.vocab_size, (2, 16)), np.int32
    )
    if packed:
        x[:, 7] = 0  # separators mid-row
        x[1, 11] = 0
    x = jnp.asarray(x)
    model_f = Transformer(base)
    params = model_f.init(jax.random.PRNGKey(0), x)
    model_c = Transformer(chunked)
    assert (
        jax.tree.structure(model_c.init(jax.random.PRNGKey(0), x))
        == jax.tree.structure(params)
    )

    def loss_of(model, p):
        out = model.apply(p, x, labels=x, train=True,
                          rngs={"dropout": jax.random.PRNGKey(1)})
        return out[1]

    lf, gf = jax.value_and_grad(lambda p: loss_of(model_f, p))(params)
    lc, gc = jax.value_and_grad(lambda p: loss_of(model_c, p))(params)
    np.testing.assert_allclose(float(lc), float(lf), rtol=1e-6)
    for (pa, a), (pb, b) in zip(
        jax.tree_util.tree_flatten_with_path(gf)[0],
        jax.tree_util.tree_flatten_with_path(gc)[0],
    ):
        assert pa == pb
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-6, err_msg=str(pa)
        )
    # the chunked loss-bearing call returns no logits...
    logits_c, _ = Transformer(chunked).apply(params, x, labels=x)
    assert logits_c is None
    # ...but the labels-free call still produces them (eval scoring)
    logits = Transformer(chunked).apply(params, x)
    assert logits.shape == (2, 16, base.vocab_size)


@pytest.mark.parametrize("position", ["alibi", "rope", "learned"])
def test_position_variants_forward(position):
    cfg = dataclasses.replace(TEST_CFG, position=position)
    _, _, _, logits = _init_and_apply(cfg)
    assert logits.shape[-1] == cfg.vocab_size
    assert bool(jnp.all(jnp.isfinite(logits)))


def test_alibi_extrapolates_beyond_train_length():
    # ALiBi's point: run at T > the config the params were built for
    cfg = TEST_CFG
    model = Transformer(cfg)
    x_short = jnp.zeros((1, 16), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), x_short)
    x_long = jnp.zeros((1, 64), jnp.int32)  # 2x max_seq_len
    logits = model.apply(params, x_long)
    assert logits.shape == (1, 64, cfg.vocab_size)
    assert bool(jnp.all(jnp.isfinite(logits)))


def test_scan_and_loop_layers_match():
    cfg_scan = dataclasses.replace(TEST_CFG, scan_layers=True)
    cfg_loop = dataclasses.replace(TEST_CFG, scan_layers=False)
    model_s = Transformer(cfg_scan)
    model_l = Transformer(cfg_loop)
    x = jnp.asarray(np.random.default_rng(0).integers(0, cfg_scan.vocab_size, (2, 8)))
    ps = model_s.init(jax.random.PRNGKey(0), x)
    # map scanned (stacked) params into per-layer params for the loop model
    pl_struct = model_l.init(jax.random.PRNGKey(0), x)

    def unstack(params_scan, template):
        import flax.traverse_util as tu

        fs = tu.flatten_dict(jax.tree.map(lambda x: x, params_scan["params"]))
        ft = tu.flatten_dict(template["params"])
        out = {}
        for key in ft:
            if key[0].startswith("block_"):
                i = int(key[0].split("_")[1])
                skey = ("blocks",) + key[1:]
                out[key] = fs[skey][i]
            else:
                out[key] = fs[key]
        return {"params": tu.unflatten_dict(out)}

    # unwrap Partitioned boxes for arithmetic
    import flax.linen as nn

    ps_un = nn.meta.unbox(ps)
    tmpl_un = nn.meta.unbox(pl_struct)
    pl = unstack(ps_un, tmpl_un)
    out_s = model_s.apply(ps_un, x)
    out_l = model_l.apply(pl, x)
    np.testing.assert_allclose(np.asarray(out_s), np.asarray(out_l), atol=2e-5)


@pytest.mark.parametrize("policy", ["none", "dots", "qkv_mlp"])
def test_remat_matches_no_remat(policy):
    cfg = dataclasses.replace(TEST_CFG, remat=True, remat_policy=policy)
    model_r = Transformer(cfg)
    model_n = Transformer(TEST_CFG)
    x = jnp.zeros((1, 8), jnp.int32)
    params = model_n.init(jax.random.PRNGKey(0), x)
    np.testing.assert_allclose(
        np.asarray(model_r.apply(params, x)), np.asarray(model_n.apply(params, x)), atol=1e-6
    )
    # gradients under the policy must match too (the policy changes what is
    # saved vs recomputed, never the math)
    def loss(m):
        def f(p):
            return jnp.sum(m.apply(p, x).astype(jnp.float32) ** 2)
        return f

    gr = jax.grad(loss(model_r))(params)
    gn = jax.grad(loss(model_n))(params)
    for a, b in zip(jax.tree.leaves(gr), jax.tree.leaves(gn)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_remat_policy_resolver_shared():
    """Both step builders (Transformer and the pipeline stage builder) take
    their checkpoint policy from the ONE resolver, so every policy name the
    config accepts must resolve — a name that fell back to None here would
    silently degrade to save-nothing remat (the round-5 review catch)."""
    from zero_transformer_tpu.models.gpt import resolve_remat_policy

    assert resolve_remat_policy(dataclasses.replace(TEST_CFG, remat_policy="none")) is None
    for name in ("dots", "qkv_mlp"):
        cfg = dataclasses.replace(TEST_CFG, remat=True, remat_policy=name)
        assert resolve_remat_policy(cfg) is not None, name


def test_remat_qkv_mlp_matches_on_moe():
    """The named-save policy must be numerically inert on MoE blocks too
    (MoEMLP carries its own mlp_wi/mlp_gate checkpoint_name sites)."""
    cfg = dataclasses.replace(
        TEST_CFG, n_experts=2, moe_top_k=1, activation="swiglu",
        remat=True, remat_policy="qkv_mlp",
    )
    base = dataclasses.replace(cfg, remat=False, remat_policy="none")
    x = jnp.zeros((1, 8), jnp.int32)
    params = Transformer(base).init(jax.random.PRNGKey(0), x)

    def f(model):
        def loss(p):
            out = model.apply(p, x)
            out = out[0] if isinstance(out, tuple) else out
            return jnp.sum(out.astype(jnp.float32) ** 2)
        return loss

    np.testing.assert_allclose(
        np.asarray(f(Transformer(cfg))(params)),
        np.asarray(f(Transformer(base))(params)), atol=1e-6,
    )
    gr = jax.grad(f(Transformer(cfg)))(params)
    gn = jax.grad(f(Transformer(base)))(params)
    for a, b in zip(jax.tree.leaves(gr), jax.tree.leaves(gn)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_gqa_llama_variant():
    cfg = model_config("llama3_test", compute_dtype="float32")
    _, _, _, logits = _init_and_apply(cfg, T=8)
    assert logits.shape == (2, 8, cfg.vocab_size)
    assert bool(jnp.all(jnp.isfinite(logits)))


def test_param_count_property_close_to_actual():
    cfg = TEST_CFG
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    actual = sum(x.size for x in jax.tree.leaves(params))
    assert abs(actual - cfg.num_params) / actual < 0.02


def test_dropout_active_only_in_train():
    cfg = dataclasses.replace(TEST_CFG, dropout=0.5)
    model = Transformer(cfg)
    x = jnp.zeros((1, 8), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), x)
    a = model.apply(params, x, train=True, rngs={"dropout": jax.random.PRNGKey(1)})
    b = model.apply(params, x, train=True, rngs={"dropout": jax.random.PRNGKey(2)})
    c = model.apply(params, x)
    d = model.apply(params, x)
    assert not np.allclose(np.asarray(a), np.asarray(b))
    np.testing.assert_allclose(np.asarray(c), np.asarray(d))


def test_alibi_slopes_power_of_two_and_not():
    s8 = alibi_slopes_list(8)
    np.testing.assert_allclose(s8, [2 ** (-i) for i in range(1, 9)], rtol=1e-6)
    s6 = alibi_slopes_list(6)
    assert len(s6) == 6 and all(s > 0 for s in s6)


def test_factory_validates_names_and_dtypes():
    with pytest.raises(ValueError):
        model_getter("nope")
    with pytest.raises(ValueError):
        model_getter("test", dtype=jnp.int32)
    model, cfg = model_getter("test", return_cfg=True, dtype=jnp.bfloat16)
    assert cfg.compute_dtype == "bfloat16"
    assert isinstance(model, Transformer)


def test_every_param_has_sharding_metadata():
    import flax.linen as nn

    model = Transformer(TEST_CFG)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    boxed = [
        (path, leaf)
        for path, leaf in jax.tree_util.tree_flatten_with_path(
            params, is_leaf=lambda x: isinstance(x, nn.Partitioned)
        )[0]
    ]
    assert boxed, "no params found"
    for path, leaf in boxed:
        assert isinstance(leaf, nn.Partitioned), f"{path} lacks partitioning metadata"
