"""Continuous-batching serving engine: scheduler state machine + parity.

The load-bearing invariant is REQUEST ISOLATION: a request admitted into a
slot must produce the same token trajectory as single-request ``generate()``
with the same seed, whatever its neighbors do — admissions, retirements,
cancellations, and deadline expiries in other slots must never perturb it.
Everything runs the ``test`` zoo model on CPU; the fake-clock tests drive
``step()`` by hand so deadline semantics are deterministic.
"""
import http.client
import json

import jax
import jax.numpy as jnp
import pytest

from zero_transformer_tpu.config import model_config
from zero_transformer_tpu.inference.generate import decode_model, generate
from zero_transformer_tpu.inference.sampling import SamplingConfig
from zero_transformer_tpu.models import Transformer
from zero_transformer_tpu.serving import ServingEngine, StreamDecoder, run_server

CACHE_LEN = 32
SAMPLING = SamplingConfig(temperature=0.9, top_k=20)


@pytest.fixture(scope="module")
def cfg():
    return model_config("test", dropout=0.0, compute_dtype="float32")


@pytest.fixture(scope="module")
def params(cfg):
    model = Transformer(cfg)
    return model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))[
        "params"
    ]


@pytest.fixture(scope="module")
def reference(cfg, params):
    """Single-request ``generate()`` tokens for (prompt, seed, max_new)."""
    model = decode_model(cfg, CACHE_LEN)

    def run(prompt, seed, max_new=8):
        toks = generate(
            model, params, jnp.asarray([prompt], jnp.int32), max_new,
            jax.random.PRNGKey(seed), SAMPLING,
        )
        return jax.device_get(toks)[0].tolist()

    return run


def make_engine(cfg, params, clock=None, **kw):
    kw.setdefault("n_slots", 2)
    kw.setdefault("cache_len", CACHE_LEN)
    kw.setdefault("sampling", SAMPLING)
    if clock is not None:
        kw["clock"] = clock
    return ServingEngine(cfg, params, **kw)


class FakeClock:
    """Manually-advanced monotonic clock for deadline tests."""

    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


# --------------------------------------------------------------- state machine


def test_slot_exhaustion_queues_then_completes(cfg, params, reference):
    """5 requests into 2 slots: the overflow queues, every request still
    finishes with its exact single-request trajectory, and occupancy peaks
    at (not above) the slot count."""
    prompts = [[3 + i, 7, 11 + i] for i in range(5)]
    engine = make_engine(cfg, params, n_slots=2)
    handles = [
        engine.submit(p, max_new_tokens=8, seed=i) for i, p in enumerate(prompts)
    ]
    assert engine.queue_depth == 5  # nothing admits until a tick runs
    engine.run_until_idle()
    for i, (p, h) in enumerate(zip(prompts, handles)):
        assert h.status == "done"
        assert h.tokens == reference(p, i)
    snap = engine.metrics_snapshot()
    assert snap["peak_occupancy"] == 2
    assert snap["completed"] == 5
    assert snap["peak_queue_depth"] == 5


def test_interleaved_admission_preserves_outputs(cfg, params, reference):
    """Mid-flight admissions (the continuous-batching case) must not
    perturb running requests: interleave submits with ticks and compare
    every trajectory to the single-request baseline."""
    engine = make_engine(cfg, params, n_slots=2)
    first = [engine.submit([10, 20, 30], max_new_tokens=8, seed=0),
             engine.submit([40, 50], max_new_tokens=8, seed=1)]
    for _ in range(3):  # partially decode the first wave
        engine.step()
    late = [engine.submit([60, 61, 62, 63], max_new_tokens=8, seed=2),
            engine.submit([70], max_new_tokens=8, seed=3)]
    engine.run_until_idle()
    expect = [([10, 20, 30], 0), ([40, 50], 1), ([60, 61, 62, 63], 2), ([70], 3)]
    for h, (p, s) in zip(first + late, expect):
        assert h.status == "done"
        assert h.tokens == reference(p, s)


def test_deadline_expiry_in_queue(cfg, params):
    clock = FakeClock()
    engine = make_engine(cfg, params, n_slots=1, clock=clock)
    hog = engine.submit([1, 2, 3], max_new_tokens=12, seed=0)
    doomed = engine.submit([4, 5, 6], max_new_tokens=4, seed=1, deadline=5.0)
    engine.step()  # hog admits; doomed waits
    clock.t = 10.0  # deadline passes while queued
    engine.run_until_idle()
    assert hog.status == "done" and len(hog.tokens) == 12
    assert doomed.status == "expired" and doomed.tokens == []
    assert "queue" in doomed.error
    assert engine.stats["expired_queued"] == 1


def test_queued_deadline_expires_while_all_slots_busy(cfg, params):
    """A queued request's deadline (and a queued cancel) must be honored on
    the NEXT TICK even when no slot frees — not deferred until admission
    finally pops it. Regression: the sweep used to live inside _admit's
    free-slot loop, so a busy engine held expired requests (and their
    blocked result() callers) hostage to the longest running generation."""
    clock = FakeClock()
    engine = make_engine(cfg, params, n_slots=1, clock=clock)
    hog = engine.submit([1, 2, 3], max_new_tokens=12, seed=0)
    doomed = engine.submit([4, 5, 6], max_new_tokens=4, seed=1, deadline=5.0)
    axed = engine.submit([7, 8], max_new_tokens=4, seed=2)
    engine.step()  # hog admits and holds the only slot
    clock.t = 10.0
    axed.cancel()
    engine.step()  # hog still decoding — the sweep alone must finish both
    assert hog.status == "running"
    assert doomed.status == "expired" and "queue" in doomed.error
    assert axed.status == "cancelled"
    assert engine.stats["expired_queued"] == 1
    assert engine.stats["cancelled"] == 1
    engine.run_until_idle()
    assert hog.status == "done" and len(hog.tokens) == 12


def test_deadline_expiry_mid_decode(cfg, params):
    clock = FakeClock()
    engine = make_engine(cfg, params, n_slots=2, clock=clock)
    doomed = engine.submit([1, 2, 3], max_new_tokens=20, seed=0, deadline=5.0)
    safe = engine.submit([4, 5, 6], max_new_tokens=20, seed=1)
    for _ in range(3):
        engine.step()
    assert doomed.status == "running" and len(doomed.tokens) == 3
    clock.t = 6.0  # expire mid-decode
    engine.run_until_idle()
    assert doomed.status == "expired" and len(doomed.tokens) == 3
    assert "mid-decode" in doomed.error
    assert safe.status == "done" and len(safe.tokens) == 20
    assert engine.stats["expired_decoding"] == 1


def test_cancellation_frees_slot_for_queued_request(cfg, params, reference):
    engine = make_engine(cfg, params, n_slots=1)
    hog = engine.submit([9, 9, 9], max_new_tokens=30, seed=0)
    waiting = engine.submit([5, 6], max_new_tokens=8, seed=7)
    for _ in range(2):
        engine.step()
    assert hog.status == "running" and waiting.status == "queued"
    hog.cancel()
    engine.run_until_idle()
    assert hog.status == "cancelled" and len(hog.tokens) == 2
    assert engine.stats["cancelled"] == 1
    # the freed slot served the queued request, unperturbed
    assert waiting.status == "done"
    assert waiting.tokens == reference([5, 6], 7)


def test_cancel_while_queued_never_admits(cfg, params):
    engine = make_engine(cfg, params, n_slots=1)
    hog = engine.submit([1], max_new_tokens=4, seed=0)
    queued = engine.submit([2], max_new_tokens=4, seed=1)
    queued.cancel()
    engine.run_until_idle()
    assert hog.status == "done"
    assert queued.status == "cancelled" and queued.tokens == []


def test_queue_full_rejects_with_backpressure(cfg, params):
    engine = make_engine(cfg, params, n_slots=1, max_queue=2)
    ok = [engine.submit([1], max_new_tokens=2, seed=i) for i in range(2)]
    rejected = engine.submit([2], max_new_tokens=2, seed=9)
    assert rejected.status == "rejected" and "queue full" in rejected.error
    assert engine.stats["rejected_queue_full"] == 1
    engine.run_until_idle()
    assert all(h.status == "done" for h in ok)


def test_invalid_requests_reject_at_submit(cfg, params):
    engine = make_engine(cfg, params)
    empty = engine.submit([], max_new_tokens=4)
    assert empty.status == "rejected" and "empty" in empty.error
    too_long = engine.submit([1] * 30, max_new_tokens=20)
    assert too_long.status == "rejected" and "cache_len" in too_long.error
    assert engine.stats["rejected_invalid"] == 2


def test_result_blocks_until_done_and_stream_yields_all(cfg, params, reference):
    """The thread-facing consumer API, driven from a scheduler thread."""
    import threading

    engine = make_engine(cfg, params)
    stop = threading.Event()
    thread = threading.Thread(target=engine.run, args=(stop,), daemon=True)
    thread.start()
    try:
        handle = engine.submit([11, 12, 13], max_new_tokens=8, seed=4)
        streamed = list(handle.stream(timeout=60))
        assert streamed == handle.result(timeout=1)
        assert streamed == reference([11, 12, 13], 4)
    finally:
        stop.set()
        thread.join(timeout=10)


def test_int8_kv_cache_parity(params):
    """The slot cache inherits int8-KV quantization from init_cache; the
    engine must stay token-identical to generate() under the same cfg."""
    qcfg = model_config(
        "test", dropout=0.0, compute_dtype="float32", kv_cache_dtype="int8"
    )
    model = decode_model(qcfg, CACHE_LEN)
    ref = jax.device_get(
        generate(model, params, jnp.asarray([[7, 8, 9]], jnp.int32), 8,
                 jax.random.PRNGKey(3), SAMPLING)
    )[0].tolist()
    engine = make_engine(qcfg, params, n_slots=2)
    handle = engine.submit([7, 8, 9], max_new_tokens=8, seed=3)
    engine.run_until_idle()
    assert handle.status == "done" and handle.tokens == ref


def test_scheduler_crash_fails_outstanding_requests_loudly(cfg, params):
    """A step() exception must not strand clients: every queued and active
    handle finishes as ``failed`` (unblocking result()/stream() waiters)
    and the exception re-raises out of run() instead of dying silently."""
    import threading

    engine = make_engine(cfg, params, n_slots=1)
    running = engine.submit([1, 2], max_new_tokens=8, seed=0)
    queued = engine.submit([3, 4], max_new_tokens=8, seed=1)
    engine.step()  # admit the first request
    assert running.status == "running"

    real_step = engine.step
    calls = {"n": 0}

    def dying_step():
        calls["n"] += 1
        if calls["n"] > 1:
            raise RuntimeError("boom")
        return real_step()

    engine.step = dying_step
    with pytest.raises(RuntimeError, match="boom"):
        engine.run(threading.Event())
    assert running.status == "failed" and "boom" in running.error
    assert queued.status == "failed"
    # blocked consumers unblock immediately (no TimeoutError)
    assert running.result(timeout=1) == running.tokens
    # and the dead engine fails NEW submits fast instead of queueing them
    # onto a queue no thread will ever drain
    late = engine.submit([5, 6], max_new_tokens=4, seed=2)
    assert late.status == "failed" and "boom" in late.error


def test_percentiles_nearest_rank():
    """p50 of an odd sample list is the true median — int(round()) banker's
    rounding regressed it to the 2nd-smallest of 5."""
    from zero_transformer_tpu.serving.engine import _percentiles

    assert _percentiles([1, 2, 3, 4, 5])["p50"] == 3
    assert _percentiles([5, 1])["p50"] == 1
    assert _percentiles([7.0])["p99"] == 7.0
    assert _percentiles([])["p90"] == 0.0


def test_graceful_stop_fails_outstanding_requests(cfg, params):
    """stop() mid-decode must not strand blocked consumers: run() aborts
    whatever is still queued or in a slot on the way out."""
    import threading

    engine = make_engine(cfg, params, n_slots=1)
    hog = engine.submit([1, 2], max_new_tokens=30, seed=0)
    queued = engine.submit([3], max_new_tokens=4, seed=1)
    stop = threading.Event()
    thread = threading.Thread(target=engine.run, args=(stop,), daemon=True)
    thread.start()
    import time as time_mod

    give_up = time_mod.monotonic() + 30
    while hog.status == "queued" and time_mod.monotonic() < give_up:
        time_mod.sleep(0.005)  # let the hog admit
    stop.set()
    thread.join(timeout=30)
    assert hog.status in ("failed", "done")  # done iff it finished pre-stop
    assert queued.status in ("failed", "done")
    # a dead (stopped) engine fails fresh submits fast
    late = engine.submit([5], max_new_tokens=2, seed=2)
    assert late.status == "failed" and "stopped" in late.error


def test_metrics_snapshot_schema(cfg, params):
    engine = make_engine(cfg, params)
    engine.submit([1, 2], max_new_tokens=4, seed=0)
    engine.run_until_idle()
    snap = engine.metrics_snapshot()
    for key in (
        "tokens_per_sec", "slot_occupancy", "queue_depth",
        "ttft_ms_p50", "ttft_ms_p90", "ttft_ms_p99",
        "itl_ms_p50", "itl_ms_p90", "itl_ms_p99",
        "submitted", "completed", "tokens_out", "peak_occupancy",
    ):
        assert key in snap, key
    assert snap["completed"] == 1 and snap["tokens_out"] == 4


# --------------------------------------------------------------------- detok


class ByteTokenizer:
    """Token id == byte value: multi-byte UTF-8 chars genuinely span
    tokens, exactly the hazard StreamDecoder exists for."""

    eos_token_id = 0

    def encode(self, text):
        return list(text.encode("utf-8"))

    def decode(self, ids, **kw):
        return bytes(ids).decode("utf-8", errors="replace")


def test_stream_decoder_holds_incomplete_multibyte():
    dec = StreamDecoder(ByteTokenizer())
    tokens = list("héllo".encode("utf-8"))  # é = 0xC3 0xA9
    pieces = [dec.push(t) for t in tokens]
    assert pieces[1] is None  # 0xC3 alone would decode to U+FFFD
    assert "".join(p for p in pieces if p) == "héllo"
    assert dec.flush() is None


def test_stream_decoder_flush_emits_tail():
    dec = StreamDecoder(ByteTokenizer())
    assert dec.push(0xC3) is None
    assert dec.flush() == "�"  # genuinely truncated stream: tail surfaces


# ------------------------------------------------------------------- server


def test_http_server_end_to_end(cfg, params):
    """Full admit→prefill→decode→stream→retire lifecycle over HTTP: SSE
    stream, non-streaming JSON, /healthz, /metrics, and 400 backpressure
    mapping — on an ephemeral port, fully on CPU."""
    engine = make_engine(cfg, params)
    server = run_server(engine, ByteTokenizer(), port=0, background=True)
    try:
        conn = http.client.HTTPConnection("127.0.0.1", server.port, timeout=60)

        def post(body):
            conn.request("POST", "/generate", json.dumps(body),
                         {"Content-Type": "application/json"})
            return conn.getresponse()

        # non-streaming JSON
        resp = post({"prompt": "ab", "max_new_tokens": 6, "seed": 1,
                     "stream": False})
        assert resp.status == 200
        doc = json.loads(resp.read())
        assert doc["status"] == "done" and len(doc["tokens"]) == 6

        # SSE stream: events concatenate to the final text
        resp = post({"tokens": [65, 66, 67], "max_new_tokens": 6, "seed": 2})
        assert resp.status == 200
        assert resp.getheader("Content-Type") == "text/event-stream"
        events = [
            json.loads(line[len(b"data: "):])
            for line in resp.read().split(b"\n\n")
            if line.startswith(b"data: ")
        ]
        assert events[-1]["done"] and events[-1]["status"] == "done"
        assert "".join(e["text"] for e in events[:-1]) == events[-1]["text"]

        # invalid request maps to 400, not a stream
        resp = post({"tokens": [], "max_new_tokens": 4})
        assert resp.status == 400 and "empty" in json.loads(resp.read())["error"]

        # ill-TYPED field values are also the client's fault: 400 with the
        # field named, never a dropped connection
        resp = post({"prompt": "ab", "timeout": "abc"})
        assert resp.status == 400
        assert "bad request field" in json.loads(resp.read())["error"]

        # valid JSON that is not an object: 400, not a handler traceback
        resp = post([1, 2, 3])
        assert resp.status == 400
        assert "JSON object" in json.loads(resp.read())["error"]

        conn.request("GET", "/healthz")
        health = json.loads(conn.getresponse().read())
        assert health["status"] == "ok" and health["slots"] == 2

        conn.request("GET", "/metrics")
        metrics = json.loads(conn.getresponse().read())
        assert metrics["completed"] == 2 and "ttft_ms_p50" in metrics
        conn.close()
    finally:
        server.stop()
