"""Ulysses (all-to-all) sequence parallelism vs the unsharded XLA path.

Second context-parallel engine next to ring attention (the reference has
neither — SURVEY §2 checklist: SP/CP = none). Exactness is the contract:
after the head/sequence all-to-all reshard, each device's local full-T flash
call must reproduce unsharded attention for every mesh layout — including
tensor-sharded heads (global ALiBi slope slices), GQA, and packed documents.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from zero_transformer_tpu.config import MeshConfig, ModelConfig
from zero_transformer_tpu.models import Transformer
from zero_transformer_tpu.ops.attention import xla_attention
from zero_transformer_tpu.ops.ulysses import ulysses_attention
from zero_transformer_tpu.parallel.mesh import make_mesh
from zero_transformer_tpu.utils.jax_compat import HAS_AMBIENT_MESH

# On pre-ambient-mesh jax (0.4.x) XLA SIGABRTs — killing the whole pytest
# process, not just the test — while compiling these specific ulysses
# programs (the engine backward, the interpreted flash forward, and the
# ZeRO-3 composition). Gate them to modern jax; the equivalent ring and
# non-flash ulysses coverage still runs everywhere.
requires_modern_shard_map = pytest.mark.skipif(
    not HAS_AMBIENT_MESH,
    reason="old-jax XLA aborts the process compiling this ulysses program",
)


def _qkv(B, T, H, KVH, D, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return (
        jax.random.normal(ks[0], (B, T, H, D)),
        jax.random.normal(ks[1], (B, T, KVH, D)),
        jax.random.normal(ks[2], (B, T, KVH, D)),
    )


@pytest.mark.parametrize(
    "mesh_cfg,H,KVH,alibi",
    [
        (MeshConfig(data=2, sequence=4), 4, 4, False),
        (MeshConfig(data=2, sequence=4), 4, 4, True),
        (MeshConfig(data=1, sequence=8), 8, 8, True),
        (MeshConfig(data=2, sequence=4), 8, 4, True),  # GQA
        (MeshConfig(data=2, tensor=2, sequence=2), 4, 4, True),  # TP-sharded heads
        (MeshConfig(data=2, tensor=2, sequence=2), 8, 4, False),  # TP + GQA
    ],
)
def test_ulysses_matches_full_attention(devices, mesh_cfg, H, KVH, alibi):
    mesh = make_mesh(mesh_cfg)
    B, T, D = 2, 32, 16
    q, k, v = _qkv(B, T, H, KVH, D)
    ref = xla_attention(q, k, v, causal=True, alibi=alibi)
    out = jax.jit(
        lambda q, k, v: ulysses_attention(q, k, v, mesh, causal=True, alibi=alibi)
    )(q, k, v)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize(
    "mesh_cfg,H,KVH",
    [
        (MeshConfig(data=2, sequence=4), 4, 4),
        (MeshConfig(data=2, tensor=2, sequence=2), 8, 4),  # TP + GQA slopes
    ],
)
@requires_modern_shard_map
def test_ulysses_gradients_match(devices, mesh_cfg, H, KVH):
    mesh = make_mesh(mesh_cfg)
    B, T, D = 1, 32, 16
    q, k, v = _qkv(B, T, H, KVH, D)
    g = jax.random.normal(jax.random.PRNGKey(7), (B, T, H, D))

    def loss_uly(q, k, v):
        return jnp.sum(ulysses_attention(q, k, v, mesh, causal=True, alibi=True) * g)

    def loss_ref(q, k, v):
        return jnp.sum(xla_attention(q, k, v, causal=True, alibi=True) * g)

    gu = jax.jit(jax.grad(loss_uly, argnums=(0, 1, 2)))(q, k, v)
    gx = jax.jit(jax.grad(loss_ref, argnums=(0, 1, 2)))(q, k, v)
    for name, a, b in zip("qkv", gu, gx):
        np.testing.assert_allclose(a, b, atol=5e-5, rtol=5e-4, err_msg=f"d{name}")


def test_ulysses_rejects_indivisible_heads(devices):
    mesh = make_mesh(MeshConfig(data=1, sequence=8))
    q, k, v = _qkv(1, 32, 4, 4, 16)  # 4 heads cannot split over 8 seq ranks
    with pytest.raises(ValueError, match="head"):
        ulysses_attention(q, k, v, mesh)


def test_ulysses_rejects_indivisible_seq(devices):
    mesh = make_mesh(MeshConfig(data=1, sequence=8))
    q, k, v = _qkv(1, 28, 8, 8, 16)
    with pytest.raises(ValueError, match="sequence"):
        ulysses_attention(q, k, v, mesh)


# -- flash inner engine (Pallas, interpret mode) ------------------------------


@pytest.mark.parametrize(
    "mesh_cfg,H,KVH,alibi",
    [
        (MeshConfig(data=2, sequence=4), 4, 4, True),
        (MeshConfig(data=2, sequence=4), 8, 4, False),  # GQA
        (MeshConfig(data=2, tensor=2, sequence=2), 4, 4, True),  # TP slopes
    ],
)
@requires_modern_shard_map
def test_flash_ulysses_matches_full_attention(devices, mesh_cfg, H, KVH, alibi):
    mesh = make_mesh(mesh_cfg)
    B, T, D = 1, 512, 64
    q, k, v = _qkv(B, T, H, KVH, D)
    ref = xla_attention(q, k, v, causal=True, alibi=alibi)
    out = jax.jit(
        lambda q, k, v: ulysses_attention(
            q, k, v, mesh, causal=True, alibi=alibi, impl="flash", interpret=True
        )
    )(q, k, v)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("H,KVH,alibi", [(4, 4, True), (8, 4, False)])
def test_flash_ulysses_gradients_match(devices, H, KVH, alibi):
    mesh = make_mesh(MeshConfig(data=2, sequence=4))
    B, T, D = 2, 512, 64
    q, k, v = _qkv(B, T, H, KVH, D)
    g = jax.random.normal(jax.random.PRNGKey(7), (B, T, H, D))

    def loss_uly(q, k, v):
        return jnp.sum(
            ulysses_attention(
                q, k, v, mesh, causal=True, alibi=alibi, impl="flash", interpret=True
            )
            * g
        )

    def loss_ref(q, k, v):
        return jnp.sum(xla_attention(q, k, v, causal=True, alibi=alibi) * g)

    gu = jax.jit(jax.grad(loss_uly, argnums=(0, 1, 2)))(q, k, v)
    gx = jax.jit(jax.grad(loss_ref, argnums=(0, 1, 2)))(q, k, v)
    for name, a, b in zip("qkv", gu, gx):
        np.testing.assert_allclose(a, b, atol=1e-4, rtol=1e-3, err_msg=f"d{name}")


@pytest.mark.parametrize("impl,kwargs", [
    ("xla", {}),
    ("flash", {"interpret": True}),
])
def test_ulysses_doc_mask_matches_full_attention(devices, impl, kwargs):
    """Packed documents under Ulysses: ids all-gather to the full sequence
    inside the body, so cross-document masking is exact even when boundaries
    straddle the original sequence shards."""
    mesh = make_mesh(MeshConfig(data=2, sequence=4))
    B, T, H, D = 2, 512, 4, 64
    q, k, v = _qkv(B, T, H, H, D)
    ids = jnp.asarray(
        np.concatenate([np.zeros(200), np.ones(190), np.full(122, 2)])[None]
        .repeat(B, 0),
        jnp.int32,
    )
    g = jax.random.normal(jax.random.PRNGKey(7), (B, T, H, D))

    ref = xla_attention(q, k, v, causal=True, alibi=True, doc_ids=ids)
    out = jax.jit(
        lambda q, k, v: ulysses_attention(
            q, k, v, mesh, causal=True, alibi=True, doc_ids=ids, impl=impl, **kwargs
        )
    )(q, k, v)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

    def loss_uly(q, k, v):
        return jnp.sum(
            ulysses_attention(
                q, k, v, mesh, causal=True, alibi=True, doc_ids=ids, impl=impl,
                **kwargs
            ) * g
        )

    def loss_ref(q, k, v):
        return jnp.sum(xla_attention(q, k, v, causal=True, alibi=True, doc_ids=ids) * g)

    gu = jax.jit(jax.grad(loss_uly, argnums=(0, 1, 2)))(q, k, v)
    gx = jax.jit(jax.grad(loss_ref, argnums=(0, 1, 2)))(q, k, v)
    for name, a, b in zip("qkv", gu, gx):
        np.testing.assert_allclose(a, b, atol=1e-4, rtol=1e-3, err_msg=f"d{name}")


# -- model / train-step integration ------------------------------------------


@pytest.mark.parametrize("position", ["alibi", "rope"])
def test_model_with_ulysses_matches_single(devices, position):
    """Full model forward with cp_impl=ulysses == unsharded model."""
    cfg = ModelConfig(
        name="t", vocab_size=64, d_model=32, n_heads=4, n_layers=2,
        max_seq_len=32, dropout=0.0, compute_dtype="float32", position=position,
        cp_impl="ulysses",
    )
    mesh = make_mesh(MeshConfig(data=2, sequence=4))
    plain = Transformer(cfg)
    sharded = Transformer(cfg, mesh=mesh)
    x = jnp.asarray(
        np.random.default_rng(0).integers(0, 64, (2, 32)), jnp.int32
    )
    params = plain.init(jax.random.PRNGKey(0), x)["params"]
    ref = plain.apply({"params": params}, x, labels=x)[1]
    out = jax.jit(lambda p, x: sharded.apply({"params": p}, x, labels=x)[1])(params, x)
    np.testing.assert_allclose(float(out), float(ref), rtol=1e-5)


def test_ulysses_train_step_decreases_loss(devices):
    """cp_impl=ulysses inside the fused ZeRO train step (remat on, bf16
    compute): the all-to-alls must compose with jax.checkpoint and the
    donated jit step exactly like ring attention does."""
    from zero_transformer_tpu.config import OptimizerConfig
    from zero_transformer_tpu.parallel import (
        init_train_state, make_plan, make_train_step,
    )
    from zero_transformer_tpu.training.optimizer import make_optimizer, make_schedule

    cfg = ModelConfig(
        name="uly_t", vocab_size=128, d_model=64, n_heads=4, n_layers=2,
        max_seq_len=32, dropout=0.0, position="alibi", remat=True,
        compute_dtype="bfloat16", cp_impl="ulysses",
    )
    opt = OptimizerConfig(peak_learning_rate=3e-3, warmup_steps=2, total_steps=40)
    mesh = make_mesh(MeshConfig(data=2, sequence=4))
    model = Transformer(cfg, mesh=mesh)
    tx = make_optimizer(opt)
    plan = make_plan(model, tx, mesh, (4, 32), zero_stage=1)
    state = init_train_state(model, tx, jax.random.PRNGKey(0), mesh, (4, 32), plan)
    step = make_train_step(model, tx, mesh, plan, 1, make_schedule(opt))

    batch = jnp.asarray(
        np.random.default_rng(0).integers(0, 128, (1, 4, 32)), jnp.int32
    )
    losses = []
    rng = jax.random.PRNGKey(1)
    for _ in range(15):
        state, metrics = step(state, batch, rng)
        losses.append(float(metrics["loss"]))
        assert np.isfinite(losses[-1]) and np.isfinite(float(metrics["grad_norm"]))
    assert losses[-1] < losses[0] - 0.5, f"no learning under ulysses: {losses}"


@requires_modern_shard_map
def test_ulysses_with_remat_zero3_trains_llama_shapes(devices):
    """Ulysses composed with ZeRO-3 (FSDP) and per-block remat at
    llama-family shapes (GQA + RoPE + RMSNorm + SwiGLU, scaled down) on a
    data=4 x sequence=2 mesh — the all-to-alls must survive jax.checkpoint's
    rematerialized backward and the GSPMD ZeRO-3 param gathers."""
    from zero_transformer_tpu.config import OptimizerConfig
    from zero_transformer_tpu.parallel import (
        init_train_state, make_plan, make_train_step,
    )
    from zero_transformer_tpu.training.optimizer import make_optimizer, make_schedule

    cfg = ModelConfig(
        name="llama_uly_t", vocab_size=128, d_model=64, n_heads=4, n_kv_heads=2,
        n_layers=2, max_seq_len=32, dropout=0.0, position="rope", norm="rmsnorm",
        activation="swiglu", tie_embeddings=False, remat=True,
        compute_dtype="bfloat16", cp_impl="ulysses",
    )
    opt = OptimizerConfig(peak_learning_rate=3e-3, warmup_steps=2, total_steps=40)
    mesh = make_mesh(MeshConfig(data=4, sequence=2, zero_stage=3))
    model = Transformer(cfg, mesh=mesh)
    tx = make_optimizer(opt)
    plan = make_plan(model, tx, mesh, (4, 32), zero_stage=3)
    state = init_train_state(model, tx, jax.random.PRNGKey(0), mesh, (4, 32), plan)
    step = make_train_step(model, tx, mesh, plan, 3, make_schedule(opt))

    batch = jnp.asarray(
        np.random.default_rng(0).integers(0, 128, (1, 4, 32)), jnp.int32
    )
    losses = []
    rng = jax.random.PRNGKey(1)
    for _ in range(15):
        state, metrics = step(state, batch, rng)
        losses.append(float(metrics["loss"]))
        assert np.isfinite(losses[-1]) and np.isfinite(float(metrics["grad_norm"]))
    assert losses[-1] < losses[0] - 0.5, f"no learning under ulysses+zero3: {losses}"


def test_ulysses_step_compiles_to_all_to_all(devices):
    """The compiled HLO of a cp_impl=ulysses train step must contain
    all-to-all collectives (the engine's defining reshard) — and the ring
    engine's compiled step must contain collective-permute instead. Guards
    against either engine silently degrading to all-gather materialization."""
    from zero_transformer_tpu.config import OptimizerConfig
    from zero_transformer_tpu.parallel import (
        init_train_state, make_plan, make_train_step,
    )
    from zero_transformer_tpu.training.optimizer import make_optimizer

    mesh = make_mesh(MeshConfig(data=2, sequence=4))
    opt = OptimizerConfig(peak_learning_rate=1e-3, warmup_steps=2, total_steps=40)
    tx = make_optimizer(opt)
    batch = jnp.zeros((1, 4, 32), jnp.int32)
    rng = jax.random.PRNGKey(0)

    def hlo_for(cp_impl):
        cfg = ModelConfig(
            name=f"hlo_{cp_impl}", vocab_size=64, d_model=32, n_heads=4,
            n_layers=2, max_seq_len=32, dropout=0.0, cp_impl=cp_impl,
        )
        model = Transformer(cfg, mesh=mesh)
        plan = make_plan(model, tx, mesh, (4, 32), zero_stage=1)
        state = init_train_state(model, tx, jax.random.PRNGKey(0), mesh, (4, 32), plan)
        step = make_train_step(model, tx, mesh, plan, 1)
        return step.lower(state, batch, rng).compile().as_text()

    uly = hlo_for("ulysses")
    assert "all-to-all" in uly, "no all-to-all in compiled ulysses step"
    ring = hlo_for("ring")
    assert "collective-permute" in ring, "no ppermute in compiled ring step"
    assert "all-to-all" not in ring
