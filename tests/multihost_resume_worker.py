"""Worker for the 4-process kill+resume test (test_multihost.py).

The crash-recovery story the reference left manual (reference
``src/utils/pod_test.py:1-6`` "run this before training to check the pod";
recovery after a mid-run host loss meant restarting the job by hand,
``main_zero.py:291-313`` restore branch), driven end-to-end across REAL
process boundaries:

- ``straight``  — 4 processes train steps 1-4; steps 3-4 losses are the
  ground truth.
- ``interrupted`` — 4 processes train steps 1-2, write a (periodic)
  checkpoint, then process 3 dies abruptly (``os._exit`` — a host crash,
  no goodbye to the coordinator). The survivors attempt step 3 anyway: the
  collective can never complete with a dead member, so a watchdog converts
  the stall into a documented exit code instead of a silent hang.
- ``resume``    — a FRESH 4-process job restores the checkpoint (sharded,
  every host reads only its pieces), restores the loader position, and
  trains steps 3-4. Its losses must equal ``straight``'s exactly — the
  interruption is invisible in the trajectory.

ELASTIC modes (test_multihost.py::test_elastic_resume_across_world_sizes)
run under a VARIABLE process count — the topology that comes back after a
preemption is whatever the scheduler has:

- ``elastic_save``   — train steps 1-2 on THIS job's world, save step 2
  (with topology metadata) through the verified-save path.
- ``elastic_resume`` — a job with a DIFFERENT world size restores through
  ``CheckpointManager.restore_verified`` (digest-verified, elastic-compat
  checked), rebuilds the ZeRO plan for its own mesh, and trains steps 3-4.
  Losses must match a same-topology uninterrupted run to reduction-order
  ulps (the global batch stream is identical; only collective schedules
  differ).

Prints ``LOSS step=N <loss>`` lines and ``WORKER_OK`` on success.
"""
import os
import sys
import threading

import jax

jax.config.update("jax_platforms", "cpu")
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=2"
)
# persistent compile cache, resolved by the SAME base+fingerprint rule as
# tests/conftest.py (shared helper) — suite-spawned and standalone runs both
# land in the host-correct directory. Three phases x four processes compile
# the SAME programs — without this the test's wall-clock is ~12 identical
# XLA compiles
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import _compile_cache  # noqa: E402

_compile_cache.configure(jax)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from zero_transformer_tpu.parallel.bootstrap import maybe_initialize  # noqa: E402

VICTIM = 3  # the process that "loses its host" in interrupted mode


def main():
    mode = os.environ["WORKER_MODE"]
    assert maybe_initialize(), "coordinator env vars must trigger initialization"
    if mode.startswith("elastic"):
        # elastic phases run under whatever world the harness launched
        assert jax.device_count() == 2 * jax.process_count(), jax.device_count()
    else:
        assert jax.process_count() == 4, jax.process_count()
        assert jax.device_count() == 8, jax.device_count()

    # Warmup collective FIRST: gloo creates its context lazily at the first
    # cross-process collective, with a fixed 30s key-value rendezvous
    # deadline. Reaching that first collective straight after init keeps
    # inter-process skew at milliseconds; without this, the first collective
    # is the train step, whose per-process XLA compile can skew processes
    # past 30s on a loaded box (observed flake). The clique is then cached
    # for every later collective.
    from zero_transformer_tpu.utils.pod_check import pod_check

    assert pod_check(timeout=300.0), "pod warmup psum failed"

    from jax.sharding import NamedSharding, PartitionSpec as P

    from zero_transformer_tpu import checkpoint as ckpt_lib
    from zero_transformer_tpu.config import MeshConfig, OptimizerConfig, model_config
    from zero_transformer_tpu.data import DataLoader, SyntheticSource, device_put_batch
    from zero_transformer_tpu.models.gpt import Transformer
    from zero_transformer_tpu.parallel.mesh import make_mesh
    from zero_transformer_tpu.parallel.zero import (
        init_train_state,
        make_plan,
        make_train_step,
    )
    from zero_transformer_tpu.training.optimizer import make_optimizer

    cfg = model_config("test", dropout=0.0)
    mesh = make_mesh(MeshConfig(zero_stage=2))
    model = Transformer(cfg)
    tx = make_optimizer(OptimizerConfig(warmup_steps=2, total_steps=10))

    batch_size, seq = 8, 32
    plan = make_plan(model, tx, mesh, (batch_size, seq), zero_stage=2)
    state = init_train_state(
        model, tx, jax.random.PRNGKey(0), mesh, (batch_size, seq), plan
    )
    step = make_train_step(model, tx, mesh, plan, zero_stage=2)

    def fresh_loader():
        return DataLoader(
            SyntheticSource(cfg.vocab_size, seq, seed=1),
            batch_size=batch_size,
            train_context=seq,
        )

    loader = fresh_loader()
    batch_sharding = NamedSharding(mesh, P(None, *plan.batch.spec))
    rng = jax.random.PRNGKey(2)
    mgr = ckpt_lib.CheckpointManager(
        os.environ["WORKER_CKPT_DIR"], keep=2, async_save=False
    )

    # AOT-compile + KV barrier before the FIRST execution of each phase:
    # per-rank XLA compile of the train step can skew ranks by minutes on a
    # loaded box, and a rank that starts executing while a peer still
    # compiles hits gloo's fixed ~30s read timeout mid-collective. The
    # barrier rides the coordination service (KV store, long timeout), not
    # gloo, so it absorbs the skew; execution then starts aligned.
    from jax._src import distributed as _dist

    _client = getattr(_dist.global_state, "client", None)

    def run_steps(it, state, n, tag, barrier=True):
        compiled = None
        for _ in range(n):
            batch = device_put_batch(next(it), batch_sharding)
            if compiled is None:
                compiled = step.lower(state, batch, rng).compile()
                if barrier and _client is not None:
                    _client.wait_at_barrier(f"compiled_{mode}_{tag}", 600_000)
            state, metrics = compiled(state, batch, rng)
            loss = float(metrics["loss"])
            assert loss == loss, "non-finite loss"
            print(f"LOSS step={int(state.step)} {loss:.10f}", flush=True)
        return state

    abstract = jax.tree.map(
        lambda l, s: jax.ShapeDtypeStruct(l.shape, l.dtype, sharding=s),
        jax.eval_shape(lambda s: s, state),
        plan.state,
    )
    # any restored state is donated by the train step below: force runtime-
    # owned buffers first (jax 0.4.37 CPU: donating an orbax zero-copy host
    # view corrupts the heap — glibc "corrupted double-linked list")
    from zero_transformer_tpu.utils.jax_compat import ensure_donatable

    if mode == "resume":
        state, meta = mgr.restore(abstract)
        state = ensure_donatable(state)
        assert int(state.step) == 2, int(state.step)
        loader.restore(meta["loader"])
        state = run_steps(iter(loader), state, 2, "resume")
    elif mode == "elastic_save":
        it = iter(loader)
        state = run_steps(it, state, 2, "warm")
        from zero_transformer_tpu.parallel.sharding import topology_summary

        mgr.save(
            2, state,
            meta={"loader": loader.state(),
                  "topology": topology_summary(mesh, 2),
                  "schedule": {"batch_size": batch_size, "train_context": seq}},
            force=True,
        )
        mgr.wait()
        print("SAVED step=2", flush=True)
    elif mode == "elastic_resume":
        # the trustworthy-restore path, across a topology change: digest
        # verification against the manifest, elastic-compat validation of
        # the saved topology vs THIS job's mesh, orbax native reshard into
        # the plan rebuilt for the new device count
        from zero_transformer_tpu.parallel.sharding import check_elastic_compat

        def check(meta):
            notes = check_elastic_compat(
                (meta or {}).get("topology"), mesh, 2, batch_size
            )
            for n in notes:
                print(f"ELASTIC {n}", flush=True)

        state, meta, report = mgr.restore_verified(abstract, check_meta=check)
        state = ensure_donatable(state)
        assert int(state.step) == 2, int(state.step)
        assert report.quarantined == [], report.quarantined
        loader.restore(meta["loader"])
        state = run_steps(iter(loader), state, 2, "elastic_resume")
    else:  # straight / interrupted
        it = iter(loader)
        state = run_steps(it, state, 2, "warm")
        mgr.save(2, state, meta={"loader": loader.state()}, force=True)
        mgr.wait()
        print("SAVED step=2", flush=True)
        if mode == "interrupted":
            if jax.process_index() == VICTIM:
                os._exit(9)  # host crash: no cleanup, no coordinator goodbye
            # survivors attempt the next step; with a dead member the
            # collective cannot complete — the watchdog documents the stall
            threading.Timer(90.0, lambda: os._exit(7)).start()
            try:
                # NO barrier here: it would wait on the dead victim and the
                # watchdog would fire before the collective is ever issued —
                # the property under test is the COLLECTIVE stalling with a
                # dead member (the clique already exists from steps 1-2)
                run_steps(it, state, 1, "survivor", barrier=False)
                print("SURVIVOR_STEP_COMPLETED_UNEXPECTEDLY", flush=True)
            except Exception as e:  # distributed runtime noticed the death
                print(f"SURVIVOR_ERROR {type(e).__name__}", flush=True)
            os._exit(7)
        else:
            state = run_steps(it, state, 2, "tail")

    mgr.close()
    print("WORKER_OK", flush=True)


if __name__ == "__main__":
    main()
