"""Overload isolation plane: QoS classes, fair admission, brownout.

Three tiers of evidence, cheapest first:

- **pure logic** (no jax, no sockets): token buckets, the DWRR class
  queue's fairness proportions and floor gating, reservation arithmetic,
  and the brownout controller's hysteresis ladder;
- **real engine** (test zoo model, CPU): per-tenant quota isolation,
  queue-full shedding that evicts a LOWER class, gold preemption of a
  running batch stream, slot-reservation floors, the brownout rungs'
  admission effects, per-class histogram exposition, and the stalled-SSE
  client's bounded emit buffer (chaos ``slow_client``) with neighbor
  byte-parity;
- **router** (real replica fleet): the dict SLO config carrying qos +
  brownout blocks, per-class objective binding to class-suffixed
  histogram families, the fleet brownout controller pushing rungs to
  replicas and fully reverting, fleet-level tenant quotas, and
  tenant-affinity routing.

The multi-tenant flood proof (one tenant floods a 2-replica fleet; the
gold tenant's latency and ``dropped_streams`` are pinned) is
slow+chaos-marked: ``make tenant-chaos``.
"""
import http.client
import json
import threading
import time
from pathlib import Path

import jax
import jax.numpy as jnp
import pytest

from zero_transformer_tpu.config import model_config
from zero_transformer_tpu.inference.generate import decode_model, generate
from zero_transformer_tpu.inference.sampling import SamplingConfig
from zero_transformer_tpu.models import Transformer
from zero_transformer_tpu.obs.fleet import TenantLedger
from zero_transformer_tpu.serving import (
    BROWNOUT_RUNGS,
    BrownoutController,
    ClassQueue,
    QosPolicy,
    RouterServer,
    ServeFault,
    ServingChaosMonkey,
    ServingEngine,
    ServingServer,
    TokenBucket,
    rung_at_least,
)
from zero_transformer_tpu.serving.qos import TenantBuckets, reserved_above

REPO = Path(__file__).resolve().parent.parent
CACHE_LEN = 32
SAMPLING = SamplingConfig(temperature=0.9, top_k=20)


@pytest.fixture(scope="module")
def cfg():
    return model_config("test", dropout=0.0, compute_dtype="float32")


@pytest.fixture(scope="module")
def params(cfg):
    model = Transformer(cfg)
    return model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))[
        "params"
    ]


@pytest.fixture(scope="module")
def reference(cfg, params):
    model = decode_model(cfg, CACHE_LEN)

    def run(prompt, seed, max_new=8):
        toks = generate(
            model, params, jnp.asarray([prompt], jnp.int32), max_new,
            jax.random.PRNGKey(seed), SAMPLING,
        )
        return jax.device_get(toks)[0].tolist()

    return run


def make_engine(cfg, params, **kw):
    kw.setdefault("n_slots", 2)
    kw.setdefault("cache_len", CACHE_LEN)
    kw.setdefault("sampling", SAMPLING)
    return ServingEngine(cfg, params, **kw)


class ByteTokenizer:
    eos_token_id = None

    def encode(self, text):
        return list(text.encode("utf-8"))

    def decode(self, ids, **kw):
        return bytes(int(i) % 256 for i in ids).decode("utf-8", errors="replace")


def _wait(pred, timeout=10.0, interval=0.01, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {msg}")


def _get(port, path, headers=None):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    try:
        conn.request("GET", path, headers=headers or {})
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read() or b"{}")
    finally:
        conn.close()


def _post(port, path, body, headers=None):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    try:
        conn.request("POST", path, json.dumps(body),
                     {"Content-Type": "application/json", **(headers or {})})
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read() or b"{}"), dict(
            resp.getheaders()
        )
    finally:
        conn.close()


# ---------------------------------------------------------------- pure logic


def test_token_bucket_charge_refill_and_wait():
    b = TokenBucket(rate=10.0, burst=20.0)
    assert b.take(20.0, now=0.0) == 0.0           # full burst admits
    wait = b.take(5.0, now=0.0)                    # empty: must wait
    assert wait == pytest.approx(0.5)              # 5 tokens at 10/s
    assert b.take(5.0, now=1.0) == 0.0             # refilled 10 in 1 s
    # scale multiplies capacity (the router's fleet-level bucket)
    fleet = TokenBucket(rate=10.0, burst=20.0)
    assert fleet.take(40.0, now=0.0, scale=2.0) == 0.0
    # inf burst (the inert default) never waits
    assert TokenBucket(rate=float("inf"), burst=float("inf")).take(
        1e12, now=0.0
    ) == 0.0


def test_qos_policy_defaults_are_inert_and_config_file_parses():
    # the policy-less default: no floors, unbounded buckets — an engine
    # without a qos config must behave exactly as before this plane existed
    p = QosPolicy.from_config(None)
    assert p.names() == ("gold", "standard", "batch")
    assert p.default_class == "standard"
    for cls in p.classes.values():
        assert cls.slot_floor == 0 and cls.page_floor_frac == 0.0
        assert cls.rate == float("inf")
    # unknown / missing class names degrade to default service, never a 400
    assert p.normalize("GOLD") == "gold"
    assert p.normalize("bogus") == "standard"
    assert p.normalize(None) == "standard"
    assert p.rank("gold") == 0 and p.rank("batch") == 2
    # the committed config carries real floors and quotas
    doc = json.loads((REPO / "configs" / "slo_default.json").read_text())
    q = QosPolicy.from_config(doc["qos"])
    assert q.classes["gold"].slot_floor == 1
    assert q.classes["gold"].page_floor_frac == 0.25
    assert q.classes["batch"].brownout_max_new_tokens == 16
    assert q.classes["gold"].retry_after_s < q.classes["batch"].retry_after_s


def test_qos_policy_rejects_bad_config():
    with pytest.raises(ValueError, match="unknown keys"):
        QosPolicy.from_config({"classes": {"gold": {"oops": 1}}})
    with pytest.raises(ValueError, match="weight"):
        QosPolicy.from_config({"classes": {"gold": {"weight": 0}}})
    with pytest.raises(ValueError, match="default_class"):
        QosPolicy.from_config({"default_class": "bogus"})


def test_class_queue_dwrr_fairness_and_floors():
    policy = QosPolicy.from_config(None)  # weights 8 : 4 : 1

    class Item:
        def __init__(self, qos, cost=10):
            self.qos, self.cost = qos, cost

    q = ClassQueue(policy, cost=lambda h: h.cost, class_of=lambda h: h.qos)
    for _ in range(40):
        q.append(Item("gold"))
        q.append(Item("standard"))
        q.append(Item("batch"))
    assert len(q) == 120
    assert q.counts() == {"gold": 40, "standard": 40, "batch": 40}
    served = [q.popleft().qos for _ in range(26)]
    # weighted-fair service: proportions track 8:4:1, and the heaviest
    # class cannot be starved out of its share by the others' backlog
    assert 14 <= served.count("gold") <= 18, served
    assert 6 <= served.count("standard") <= 10, served
    assert 1 <= served.count("batch") <= 4, served
    # floor gating: an ineligible class is skipped WITHOUT burning its
    # deficit — the next eligible pop still follows the weights
    nxt = q.popleft(eligible=lambda c: c != "gold")
    assert nxt.qos in ("standard", "batch")
    assert q.popleft(eligible=lambda c: False) is None
    # queue-full shed victim: lowest class, never at-or-above the bar
    victim = q.pop_lowest_class(above_rank=policy.rank("standard"))
    assert victim.qos == "batch"
    assert q.pop_lowest_class(above_rank=policy.rank("batch")) is None
    assert q.best_waiting_rank() == 0
    # appendleft is a refund: the item comes back out first for its class
    head = Item("gold", cost=1)
    q.appendleft(head)
    assert q.popleft(eligible=lambda c: c == "gold") is head


def test_reserved_above_arithmetic():
    policy = QosPolicy.from_config(
        {"classes": {"gold": {"slot_floor": 2}, "standard": {"slot_floor": 1}}}
    )
    floors = {n: c.slot_floor for n, c in policy.classes.items()}
    # batch sees both unmet floors; gold sees none (nothing outranks it)
    assert reserved_above(policy, "batch", floors, {}) == 3
    assert reserved_above(policy, "gold", floors, {}) == 0
    # a higher class already running inside its floor releases that much
    assert reserved_above(policy, "batch", floors, {"gold": 1}) == 2
    assert reserved_above(policy, "batch", floors, {"gold": 5}) == 1


def test_brownout_controller_hysteresis_and_force():
    bo = BrownoutController(calm_evals=3)
    assert bo.rung == "normal"
    assert bo.observe(True) == ("normal", "no_spec")
    assert bo.observe(True) == ("no_spec", "shrink_batch")
    assert bo.observe(True) == ("shrink_batch", "suspend_batch")
    assert bo.observe(True) is None  # already at the top
    # one calm blip mid-overload changes nothing; calm_evals consecutive
    # calm evaluations step down ONE rung (and reset the streak)
    assert bo.observe(False) is None
    assert bo.observe(True) is None  # hot again: streak resets
    for _ in range(2):
        assert bo.observe(False) is None
    assert bo.observe(False) == ("suspend_batch", "shrink_batch")
    for _ in range(8):
        bo.observe(False)
    assert bo.rung == "normal"  # sustained calm fully reverts
    assert bo.force("suspend_batch") == ("normal", "suspend_batch")
    assert bo.force("suspend_batch") is None  # idempotent
    with pytest.raises(ValueError):
        bo.force("bogus")
    snap = bo.snapshot()
    assert snap["rung"] == "suspend_batch" and snap["rungs"] == list(
        BROWNOUT_RUNGS
    )
    assert rung_at_least("shrink_batch", "no_spec")
    assert not rung_at_least("no_spec", "shrink_batch")
    assert rung_at_least("bogus", "normal")  # unknown compares as normal


def test_tenant_ledger_eviction_callback_and_lru_preference():
    evicted = []
    ledger = TenantLedger(capacity=2, on_evict=evicted.append)
    ledger.record("idle", {"tokens_out": 1})
    ledger.record("active", {"tokens_out": 1})
    ledger.record("active", {"tokens_out": 1})  # touch: active moves to MRU
    ledger.record("new", {"tokens_out": 1})     # capacity: IDLE is evicted
    assert evicted == ["idle"]
    assert ledger.evictions == 1
    assert set(ledger.snapshot()) == {"active", "new"}


# ------------------------------------------------------------- engine plane


def test_engine_tenant_quota_is_per_tenant(cfg, params):
    """A flooding tenant exhausts ITS OWN bucket: the rejection is
    retryable with a class-aware Retry-After, and another tenant's bucket
    is untouched."""
    engine = make_engine(
        cfg, params, qos={"classes": {"standard": {"rate": 1.0, "burst": 10.0}}}
    )
    ok = engine.submit([1, 2, 3], max_new_tokens=5, seed=0, tenant="flood")
    broke = engine.submit([1, 2, 3], max_new_tokens=5, seed=0, tenant="flood")
    other = engine.submit([1, 2, 3], max_new_tokens=5, seed=1, tenant="calm")
    assert ok.status == "queued" and other.status == "queued"
    assert broke.status == "rejected" and broke.retryable
    assert "quota" in broke.error
    assert broke.retry_after >= 1.0  # at least the class retry hint
    assert engine.stats["rejected_quota"] == 1
    engine.run_until_idle()
    assert ok.status == "done" and other.status == "done"


def test_engine_queue_full_sheds_lower_class(cfg, params):
    """At queue capacity a HIGHER-class arrival evicts the lowest-class
    waiter (retryably) instead of being turned away; an equal-class
    arrival still gets the classic queue-full rejection."""
    engine = make_engine(cfg, params, n_slots=1, max_queue=2,
                         qos={"classes": {}})
    waiters = [
        engine.submit([1, 2 + i], max_new_tokens=4, seed=i, qos="batch")
        for i in range(3)
    ]
    assert waiters[2].status == "rejected"  # queue full among equals
    assert engine.stats["rejected_queue_full"] == 1
    gold = engine.submit([1, 9], max_new_tokens=4, seed=9, qos="gold")
    assert gold.status == "queued"
    shed = [w for w in waiters[:2] if w.status == "rejected"]
    assert len(shed) == 1 and shed[0].retryable
    assert "shed" in shed[0].error
    assert engine.stats["shed_lower_class"] == 1
    engine.run_until_idle()
    assert gold.status == "done"


def test_engine_preempts_running_batch_for_waiting_gold(cfg, params):
    """With every slot busy on lower-class work, a waiting gold request
    preempts one victim (retryable terminal) instead of queueing behind
    it; gold never waits on batch."""
    engine = make_engine(cfg, params, n_slots=1, qos={"classes": {}})
    batch = engine.submit([2, 3], max_new_tokens=24, seed=0, qos="batch")
    for _ in range(3):
        engine.step()
    assert batch.status == "running"
    gold = engine.submit([2, 4], max_new_tokens=4, seed=1, qos="gold")
    engine.run_until_idle()
    assert gold.status == "done"
    assert batch.status == "failed" and batch.retryable
    assert "preempted" in batch.error
    assert engine.stats["preempted_for_class"] == 1
    # gold-for-gold never preempts: same-class contention just queues
    g1 = engine.submit([2, 5], max_new_tokens=24, seed=2, qos="gold")
    for _ in range(3):
        engine.step()
    g2 = engine.submit([2, 6], max_new_tokens=4, seed=3, qos="gold")
    engine.run_until_idle()
    assert g1.status == "done" and g2.status == "done"
    assert engine.stats["preempted_for_class"] == 1  # unchanged


def test_engine_slot_floor_reserves_capacity_for_gold(cfg, params):
    """A gold slot floor keeps batch from ever filling the last slot:
    batch runs one-at-a-time through 2 slots, and a gold arrival admits
    immediately into the reserved slot."""
    engine = make_engine(
        cfg, params, n_slots=2,
        qos={"classes": {"gold": {"slot_floor": 1}}},
    )
    waiters = [
        engine.submit([3, 5 + i], max_new_tokens=12, seed=i, qos="batch")
        for i in range(3)
    ]
    peak_batch = 0
    for _ in range(6):
        engine.step()
        active = [
            a.handle.request.qos
            for a in engine._active
            if a is not None
        ]
        peak_batch = max(peak_batch, active.count("batch"))
    assert peak_batch == 1  # the floor held a slot open throughout
    gold = engine.submit([3, 9], max_new_tokens=4, seed=9, qos="gold")
    engine.step()
    assert gold.status == "running"  # straight into the reserved slot
    engine.run_until_idle()
    assert gold.status == "done"
    assert all(w.status == "done" for w in waiters)


def test_engine_brownout_rungs_and_full_revert(cfg, params):
    """Every rung changes admission the way it advertises, transitions
    are counted + flight-recorded, and ``normal`` restores the exact
    pre-brownout behavior."""
    engine = make_engine(cfg, params, qos={"classes": {}})
    assert engine.brownout_rung == "normal" and engine._spec_enabled
    info = engine.set_brownout("no_spec")
    assert info == {"rung": "no_spec", "previous": "normal"}
    assert not engine._spec_enabled
    engine.set_brownout("shrink_batch")
    clamped = engine.submit([1, 2], max_new_tokens=24, seed=0, qos="batch")
    assert clamped.request.max_new_tokens == 16  # the class's brownout cap
    gold_uncapped = engine.submit([1, 3], max_new_tokens=24, seed=0,
                                  qos="gold")
    assert gold_uncapped.request.max_new_tokens == 24
    engine.set_brownout("suspend_batch")
    suspended = engine.submit([1, 4], max_new_tokens=4, seed=0, qos="batch")
    assert suspended.status == "rejected" and suspended.retryable
    assert "brownout" in suspended.error
    assert engine.stats["rejected_brownout"] == 1
    still_gold = engine.submit([1, 5], max_new_tokens=4, seed=0, qos="gold")
    assert still_gold.status == "queued"
    # full revert: batch admits again, spec re-enables, no clamp
    engine.set_brownout("normal")
    assert engine._spec_enabled
    back = engine.submit([1, 6], max_new_tokens=24, seed=0, qos="batch")
    assert back.status == "queued"
    assert back.request.max_new_tokens == 24
    assert engine.stats["brownout_transitions"] == 4
    assert engine.set_brownout("normal") == {"rung": "normal",
                                             "previous": "normal"}
    assert engine.stats["brownout_transitions"] == 4  # idempotent no-op
    with pytest.raises(ValueError):
        engine.set_brownout("bogus")
    engine.run_until_idle()
    snap = engine.metrics_snapshot()
    assert snap["brownout_rung"] == "normal"


def test_engine_per_class_histograms_and_new_exports(cfg, params):
    engine = make_engine(cfg, params, qos={"classes": {}})
    for i, q in enumerate(("gold", "batch", None)):
        engine.submit([3 + i, 7], max_new_tokens=4, seed=i, qos=q)
    engine.run_until_idle()
    text = engine.prometheus_text()
    for family in (
        "serve_ttft_seconds_gold", "serve_ttft_seconds_standard",
        "serve_ttft_seconds_batch", "serve_itl_seconds_gold",
        "serve_brownout_rung", "serve_rejected_quota",
        "serve_shed_lower_class", "serve_preempted_for_class",
        "serve_stalled_streams",
    ):
        assert family in text, family
    # the classless request landed in the default class's stream
    assert 'serve_ttft_seconds_standard_count 1' in text
    snap = engine.metrics_snapshot()
    for key in ("rejected_quota", "rejected_brownout", "shed_lower_class",
                "preempted_for_class", "brownout_transitions",
                "stalled_streams"):
        assert snap[key] == 0
    assert snap["queue_by_class"] == {"gold": 0, "standard": 0, "batch": 0}


def test_shed_ewma_stays_cold_across_breaker_rebuild(cfg, params):
    """Cold-start pin (satellite): the deadline shedder must be inert on
    an uninitialized ITL estimate — at engine start AND after a breaker
    rebuild, which must preserve (not reset) the warm estimate."""
    engine = make_engine(cfg, params, n_slots=1, shed_warmup=4)
    # fresh engine: no ITL evidence, nothing sheds however tight the ask
    tight = engine.submit([1], max_new_tokens=20, seed=0, deadline=0.001)
    assert tight.status == "queued"
    assert engine.stats["shed_infeasible"] == 0
    engine.run_until_idle()
    # warm the estimate, then force the breaker's device-state rebuild:
    # the EWMA is HOST state and must survive (a rebuild that zeroed it
    # would re-open the cold-start window after every trip)
    for _ in range(8):
        engine._itl_ewma.update(0.1)
    assert engine._itl_ewma.warm
    before = engine._itl_ewma.value
    engine._rebuild_device_state()
    assert engine._itl_ewma.warm and engine._itl_ewma.value == before
    doomed = engine.submit([1, 2], max_new_tokens=20, seed=0, deadline=0.5)
    assert doomed.status == "rejected" and "shed" in doomed.error


@pytest.mark.chaos
def test_slow_client_chaos_bounds_emit_buffer(cfg, params, reference):
    """Chaos ``slow_client``: an SSE consumer stalls mid-stream. The
    stalled stream's emit buffer hits its bound and the stream finishes
    RETRYABLY (slot released, done event delivered); a concurrent healthy
    stream is byte-identical to the undisturbed run."""
    chaos = ServingChaosMonkey([
        ServeFault("slow_client", step=2, duration=2.0),
    ])
    engine = make_engine(cfg, params, n_slots=2, chaos=chaos,
                         emit_buffer_max=3)
    server = ServingServer(engine, ByteTokenizer(), port=0)
    server.start()
    results = {}

    def client(i):
        conn = http.client.HTTPConnection("127.0.0.1", server.port,
                                          timeout=60)
        try:
            conn.request(
                "POST", "/generate",
                json.dumps({"tokens": [3 + i, 7, 11], "max_new_tokens": 24,
                            "seed": i, "stream": True}),
                {"Content-Type": "application/json"},
            )
            resp = conn.getresponse()
            toks, done = [], None
            while True:
                line = resp.readline()
                if not line:
                    break
                if not line.startswith(b"data: "):
                    continue
                event = json.loads(line[6:])
                if event.get("done"):
                    done = event
                    break
                if "token" in event:
                    toks.append(event["token"])
            results[i] = (toks, done)
        finally:
            conn.close()

    try:
        # client 0 arrives first — the chaos fault stalls ITS pump after
        # 2 delivered events; client 1 streams unperturbed alongside
        t0 = threading.Thread(target=client, args=(0,))
        t0.start()
        _wait(lambda: engine.stats["submitted"] >= 1, msg="first admit")
        t1 = threading.Thread(target=client, args=(1,))
        t1.start()
        t0.join(60)
        t1.join(60)
        stalled_toks, stalled_done = results[0]
        assert stalled_done is not None, "stalled stream must still terminate"
        assert stalled_done["status"] == "failed"
        assert stalled_done["retryable"] is True
        assert "stalled" in stalled_done["error"]
        assert engine.stats["stalled_streams"] == 1
        assert chaos.fired_log  # the fault actually fired
        # neighbor isolation: byte-identical to the undisturbed trajectory
        healthy_toks, healthy_done = results[1]
        assert healthy_done["status"] == "done"
        assert healthy_toks == reference([4, 7, 11], 1, max_new=24)
    finally:
        server.stop()


# ------------------------------------------------------------- router plane


def _make_replica(cfg, params, **engine_kw):
    engine_kw.setdefault("n_slots", 2)
    engine_kw.setdefault("cache_len", CACHE_LEN)
    engine_kw.setdefault("sampling", SamplingConfig(greedy=True))
    engine = ServingEngine(cfg, params, **engine_kw)
    server = ServingServer(engine, ByteTokenizer(), port=0)
    server.start()
    return server


def test_router_dict_slo_config_binds_per_class_objectives(cfg, params):
    """The config-file dict shape wires all three planes at once: the
    objective list (including per-class ones bound to class-suffixed
    histogram families), the QoS policy, and the brownout controller."""
    doc = json.loads((REPO / "configs" / "slo_default.json").read_text())
    t = [0.0]
    router = RouterServer(["127.0.0.1:9"], clock=lambda: t[0], slo=doc)
    router._httpd.server_close()  # never started; just release the socket
    assert router.qos.classes["gold"].slot_floor == 1
    assert router.brownout.calm_evals == 3
    assert router._brownout_protected == ("gold", "standard")
    assert set(router.slo._objectives) >= {"ttft_p99_gold", "itl_p99_gold"}
    # feed the aggregator a real engine's exposition carrying gold-only
    # traffic: the gold objective sees samples from the class-suffixed
    # family while the classless family feeds the fleet-wide objective
    engine = make_engine(cfg, params, qos={"classes": {}})
    engine.submit([3, 7], max_new_tokens=4, seed=0, qos="gold")
    engine.run_until_idle()
    router.aggregator.update("r1", "decode", engine.prometheus_text())
    t[0] += 1.0
    snap = router.evaluate_slo()
    gold = snap["objectives"]["ttft_p99_gold"]
    assert gold["qos_class"] == "gold"
    assert gold["total"] > 0  # the class-suffixed family reached the SLO
    # a plain objective list still works and leaves the inert policy
    plain = RouterServer(["127.0.0.1:9"], slo=doc["objectives"])
    plain._httpd.server_close()
    assert plain.qos.classes["gold"].slot_floor == 0


def test_router_brownout_propagates_and_reverts(cfg, params):
    """Hot per-class evaluations walk the fleet up the rung ladder and
    PUSH each rung to every replica; sustained calm walks it all the way
    back. Rungs are visible on /healthz at both tiers, every transition
    is a flight event, and the final rung rejects batch at the router."""
    replica = _make_replica(cfg, params)
    doc = json.loads((REPO / "configs" / "slo_default.json").read_text())
    router = RouterServer(
        [f"http://127.0.0.1:{replica.port}"], probe_interval=0.05, slo=doc,
        # obs loop off: the ladder is driven BY HAND below, and a live
        # loop's calm real evaluations would walk it back mid-assertion
        metrics_scrape_interval=0.0,
    )
    router.start()
    try:
        _wait(lambda: len(router.registry.routable()) == 1, timeout=15,
              msg="replica routable")
        hot = {"objectives": {"ttft_p99_gold": {
            "qos_class": "gold", "state": "fast_burn"}}}
        calm = {"objectives": {"ttft_p99_gold": {
            "qos_class": "gold", "state": "ok"}}}
        for _ in range(3):
            router.brownout_tick(hot)
        assert router.brownout.rung == "suspend_batch"
        _wait(
            lambda: replica.engine.brownout_rung == "suspend_batch",
            msg="rung pushed to replica",
        )
        code, health = _get(router.port, "/healthz")
        assert health["brownout_rung"] == "suspend_batch"
        # the final rung suspends batch AT THE FRONT DOOR, gold still flows
        code, body, headers = _post(
            router.port, "/generate",
            {"tokens": [3, 7], "max_new_tokens": 4, "seed": 0,
             "stream": False},
            headers={"X-QoS-Class": "batch"},
        )
        assert code == 503 and "brownout" in body["error"]
        assert int(headers.get("Retry-After", 0)) >= 1
        code, body, _ = _post(
            router.port, "/generate",
            {"tokens": [3, 7], "max_new_tokens": 4, "seed": 0,
             "stream": False},
            headers={"X-QoS-Class": "gold"},
        )
        assert code == 200 and body["status"] == "done"
        assert router.stats["rejected_brownout"] == 1
        # sustained calm fully reverts, and the revert propagates too
        for _ in range(12):
            router.brownout_tick(calm)
        assert router.brownout.rung == "normal"
        _wait(lambda: replica.engine.brownout_rung == "normal",
              msg="revert pushed to replica")
        code, body, _ = _post(
            router.port, "/generate",
            {"tokens": [3, 7], "max_new_tokens": 4, "seed": 0,
             "stream": False},
            headers={"X-QoS-Class": "batch"},
        )
        assert code == 200 and body["status"] == "done"
        assert router.stats["brownout_transitions"] == 6
        event_names = [e[1] for e in router.flight.events()]
        assert "fleet_brownout" in event_names
        # operator override via the router admin surface
        code, snap, _ = _post(router.port, "/admin/brownout",
                              {"rung": "no_spec"})
        assert code == 200 and snap["rung"] == "no_spec"
        _wait(lambda: replica.engine.brownout_rung == "no_spec",
              msg="forced rung pushed")
        code, _, _ = _post(router.port, "/admin/brownout", {"rung": "bogus"})
        assert code == 400
    finally:
        router.stop()
        replica.stop()


def test_router_fleet_tenant_quota_and_affinity(cfg, params):
    """The router's fleet-level bucket rejects a flooding tenant with 429
    + Retry-After before any replica sees the request, and a tenant's
    requests stick to one replica (tenant affinity)."""
    replica = _make_replica(cfg, params)
    doc = {
        "qos": {"classes": {"standard": {"rate": 1.0, "burst": 8.0}}},
        "objectives": json.loads(
            (REPO / "configs" / "slo_default.json").read_text()
        )["objectives"],
    }
    router = RouterServer(
        [f"http://127.0.0.1:{replica.port}"], probe_interval=0.05, slo=doc,
    )
    router.start()
    try:
        _wait(lambda: len(router.registry.routable()) == 1, timeout=15,
              msg="replica routable")
        body = {"tokens": [3, 7], "max_new_tokens": 4, "seed": 0,
                "stream": False}
        code, doc1, _ = _post(router.port, "/generate", body,
                              headers={"X-Tenant-Key": "flood"})
        assert code == 200, doc1
        code, doc2, headers = _post(router.port, "/generate", body,
                                    headers={"X-Tenant-Key": "flood"})
        assert code == 429 and "quota" in doc2["error"]
        assert int(headers.get("Retry-After", 0)) >= 1
        # another tenant's bucket is untouched
        code, doc3, _ = _post(router.port, "/generate", body,
                              headers={"X-Tenant-Key": "calm"})
        assert code == 200, doc3
        assert router.stats["rejected_quota"] == 1
        assert router.stats["tenant_affinity_hits"] >= 0
        assert router._tenant_affinity_lookup("calm") == replica_id(router)
        snap = router.metrics_snapshot()
        assert snap["brownout_rung"] == "normal"
        assert "gold" in snap["qos_classes"]
    finally:
        router.stop()
        replica.stop()


def replica_id(router):
    return next(iter(router.registry.replicas))


# ----------------------------------------------------- multi-tenant flood


@pytest.mark.slow
@pytest.mark.chaos
def test_tenant_flood_isolation_two_replica_fleet(cfg, params):
    """The acceptance-bar scenario: one tenant floods a 2-replica fleet
    with batch work while a gold tenant runs a steady trickle. The gold
    tenant's requests ALL complete, ``dropped_streams`` stays 0, every
    shed/suspended flood request ends retryably with a Retry-After, and
    the flood's damage is visible in the isolation counters."""
    qos = {
        "classes": {
            "gold": {"slot_floor": 1, "page_floor_frac": 0.25},
            "batch": {"rate": 20.0, "burst": 40.0},
        }
    }
    replicas = [_make_replica(cfg, params, qos=qos) for _ in range(2)]
    doc = json.loads((REPO / "configs" / "slo_default.json").read_text())
    doc["qos"]["classes"]["batch"].update(rate=20.0, burst=40.0)
    router = RouterServer(
        [f"http://127.0.0.1:{s.port}" for s in replicas],
        probe_interval=0.05, max_attempts=2, slo=doc,
    )
    router.start()
    try:
        _wait(lambda: len(router.registry.routable()) == 2, timeout=20,
              msg="fleet ready")
        stop = threading.Event()
        flood_codes = []
        flood_lock = threading.Lock()

        def flood():
            while not stop.is_set():
                try:
                    code, body, headers = _post(
                        router.port, "/generate",
                        {"tokens": [9, 9, 9], "max_new_tokens": 16,
                         "seed": 0, "stream": False},
                        headers={"X-Tenant-Key": "flooder",
                                 "X-QoS-Class": "batch"},
                    )
                    with flood_lock:
                        flood_codes.append((code, body, headers))
                except OSError:
                    pass

        threads = [threading.Thread(target=flood, daemon=True)
                   for _ in range(4)]
        for t in threads:
            t.start()
        gold_results = []
        for i in range(8):
            code, body, _ = _post(
                router.port, "/generate",
                {"tokens": [3, 5, 7 + i], "max_new_tokens": 8, "seed": i,
                 "stream": False},
                headers={"X-Tenant-Key": "vip", "X-QoS-Class": "gold"},
            )
            gold_results.append((code, body))
        stop.set()
        for t in threads:
            t.join(30)
        # EVERY gold request completed despite the flood
        assert all(
            code == 200 and body.get("status") == "done"
            for code, body in gold_results
        ), [c for c, _ in gold_results]
        # the flood was actually throttled — and every rejection honest:
        # retryable semantics with a Retry-After the client can obey
        rejected = [(c, b, h) for c, b, h in flood_codes if c != 200]
        assert rejected, "flood never hit a limit — not a flood"
        for code, body, headers in rejected:
            assert code in (429, 503), (code, body)
            assert int(headers.get("Retry-After", 0)) >= 1
        assert router.stats["dropped_streams"] == 0
        # isolation machinery engaged somewhere in the stack
        engine_stats = [s.engine.stats for s in replicas]
        engaged = (
            router.stats["rejected_quota"]
            + sum(st["rejected_quota"] for st in engine_stats)
            + sum(st["shed_lower_class"] for st in engine_stats)
            + sum(st["preempted_for_class"] for st in engine_stats)
            + sum(st["rejected_queue_full"] for st in engine_stats)
        )
        assert engaged > 0
        # the gold tenant's class-suffixed histograms carried its samples
        text = "".join(s.engine.prometheus_text() for s in replicas)
        assert "serve_ttft_seconds_gold_count" in text
    finally:
        router.stop()
        for s in replicas:
            s.stop()
